/* Merged-bottom-k MinHash pair statistics over a dense sketch matrix.
 *
 * Compiled-C twin of the reference's host pair loop (the reference runs
 * finch's merge walk in compiled Rust on a rayon pool; reference:
 * src/finch.rs:53-73). This is the honest CPU baseline for bench.py —
 * the strongest available stand-in given no Rust toolchain in the image
 * — and doubles as a production CPU fallback for the all-pairs pass.
 *
 * Semantics mirror the device extraction exactly: walk the two sorted
 * sketches in merge order over the smallest `sketch_size` distinct
 * union hashes (galah_tpu/ops/minhash_np.py::mash_jaccard), then apply
 * the SAME f64 rational keep-check as ops/pairwise.threshold_pairs'
 * host pass — common >= j_thr * total with j_thr precomputed by
 * ani_to_jaccard (no per-pair exp/log in the decision, so borderline
 * pairs cannot order differently from the device path) — and report
 * ANI = 1 + ln(2j/(1+j))/k for the survivors. total == 0 pairs (two
 * empty sketches) are never emitted, matching the device extraction.
 * Rows are sorted ascending with 0xFFFF..FF sentinel padding; per-row
 * valid lengths arrive precomputed.
 */

#include <math.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>

#if defined(__x86_64__) && defined(__GNUC__)
#include <immintrin.h>
#define GALAH_HAVE_AVX512_BUILD 1
#endif

typedef struct {
    const uint64_t *mat;
    const int64_t *lens;
    int64_t n, width;
    int sketch_size, kmer;
    double j_thr;        /* Jaccard-domain threshold (ani_to_jaccard) */
    int tid, n_threads;
    int64_t *out_i, *out_j;
    double *out_ani;
    int64_t cap;
    int64_t *next_slot;  /* shared atomic append cursor */
    int64_t found;       /* per-thread total (incl. past-cap) */
} ps_job;

static void pair_stats(const uint64_t *a, int64_t la, const uint64_t *b,
                       int64_t lb, int size, int64_t *common_out,
                       int64_t *total_out) {
    int64_t i = 0, j = 0, common = 0, total = 0;
    while (i < la && j < lb && total < size) {
        uint64_t x = a[i], y = b[j];
        if (x < y) {
            i++;
        } else if (y < x) {
            j++;
        } else {
            common++;
            i++;
            j++;
        }
        total++;
    }
    while (i < la && total < size) {
        i++;
        total++;
    }
    while (j < lb && total < size) {
        j++;
        total++;
    }
    *common_out = common;
    *total_out = total;
}

static void *worker(void *arg) {
    ps_job *w = (ps_job *)arg;
    /* interleaved rows: balances the shrinking upper triangle */
    for (int64_t r = w->tid; r < w->n; r += w->n_threads) {
        const uint64_t *ra = w->mat + r * w->width;
        int64_t la = w->lens[r];
        for (int64_t c = r + 1; c < w->n; c++) {
            int64_t common, total;
            pair_stats(ra, la, w->mat + c * w->width, w->lens[c],
                       w->sketch_size, &common, &total);
            if (total == 0 ||
                (double)common < w->j_thr * (double)total)
                continue;
            double jac = (double)common / (double)total;
            double ani =
                common > 0
                    ? 1.0 - (-log(2.0 * jac / (1.0 + jac)) /
                             (double)w->kmer)
                    : 0.0;
            w->found++;
            int64_t slot =
                __sync_fetch_and_add(w->next_slot, (int64_t)1);
            if (slot < w->cap) {
                w->out_i[slot] = r;
                w->out_j[slot] = c;
                w->out_ani[slot] = ani;
            }
        }
    }
    return NULL;
}

/* Exact merged-bottom-k stats for an EXPLICIT pair list (the sparse
 * screened path): for each (pi[x], pj[x]) run the same merge walk and
 * f64 rational keep-check as the all-pairs kernel; out_ani[x] = ANI for
 * keepers, -inf for non-keepers (a real ANI is always finite). Pairs
 * are split across threads. */

typedef struct {
    const uint64_t *mat;
    const int64_t *lens, *pi, *pj;
    int64_t n_pairs, width;
    int sketch_size, kmer;
    double j_thr;
    int tid, n_threads;
    double *out_ani;
} pl_job;

static void *pl_worker(void *arg) {
    pl_job *w = (pl_job *)arg;
    for (int64_t x = w->tid; x < w->n_pairs; x += w->n_threads) {
        int64_t i = w->pi[x], j = w->pj[x];
        int64_t common, total;
        pair_stats(w->mat + i * w->width, w->lens[i],
                   w->mat + j * w->width, w->lens[j], w->sketch_size,
                   &common, &total);
        if (total == 0 ||
            (double)common < w->j_thr * (double)total) {
            w->out_ani[x] = -HUGE_VAL; /* impossible ANI = rejected */
            continue;
        }
        double jac = (double)common / (double)total;
        w->out_ani[x] =
            common > 0
                ? 1.0 - (-log(2.0 * jac / (1.0 + jac)) /
                         (double)w->kmer)
                : 0.0;
    }
    return NULL;
}

void galah_pair_stats_for_pairs(
    const uint64_t *mat, int64_t n_pairs, int64_t width,
    const int64_t *lens, const int64_t *pi, const int64_t *pj,
    int sketch_size, int kmer, double j_thr, int n_threads,
    double *out_ani) {
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    pl_job jobs[64];
    pthread_t tids[64];
    for (int t = 0; t < n_threads; t++)
        jobs[t] = (pl_job){mat, lens, pi, pj, n_pairs, width,
                           sketch_size, kmer, j_thr, t, n_threads,
                           out_ani};
    if (n_threads == 1) {
        pl_worker(&jobs[0]);
        return;
    }
    for (int t = 0; t < n_threads; t++)
        pthread_create(&tids[t], NULL, pl_worker, &jobs[t]);
    for (int t = 0; t < n_threads; t++)
        pthread_join(tids[t], NULL);
}

/* Per-window fragment membership counts: for each row of `wins`
 * (SENTINEL-masked positional hash windows, ops/fragment_ani
 * GenomeProfile.windows layout), count valid hashes and how many are
 * present in the sorted distinct `ref` set (binary search) — the C twin
 * of ops/fragment_ani._window_match_counts_impl for CPU backends.
 * Rows are split across n_threads (each row is independent). */

typedef struct {
    const uint64_t *wins, *ref;
    int64_t W, L, H;
    int32_t *matched, *total;
    int tid, n_threads;
} wm_job;

static void *wm_worker(void *arg) {
    wm_job *w = (wm_job *)arg;
    const uint64_t SENT = 0xFFFFFFFFFFFFFFFFull;
    const uint64_t *ref = w->ref;
    const int64_t H = w->H;
    for (int64_t r = w->tid; r < w->W; r += w->n_threads) {
        const uint64_t *row = w->wins + r * w->L;
        int32_t m = 0, t = 0;
        for (int64_t i = 0; i < w->L; i++) {
            uint64_t h = row[i];
            if (h == SENT) continue;
            t++;
            /* branchless lower_bound: the compare compiles to cmov,
             * halving the branchy version's misprediction stalls */
            int64_t lo = 0, len = H;
            while (len > 1) {
                int64_t half = len >> 1;
                lo += (ref[lo + half - 1] < h) ? half : 0;
                len -= half;
            }
            if (H > 0 && ref[lo] == h) m++;
        }
        w->matched[r] = m;
        w->total[r] = t;
    }
    return NULL;
}

void galah_window_match_counts(const uint64_t *wins, int64_t W,
                               int64_t L, const uint64_t *ref,
                               int64_t H, int n_threads,
                               int32_t *matched, int32_t *total) {
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    if ((int64_t)n_threads > W) n_threads = W > 0 ? (int)W : 1;
    /* pthread spawn (~100 us each) swamps small membership tests —
     * typical greedy-phase calls are a few dozen windows */
    if (W * L < (int64_t)1 << 16) n_threads = 1;
    wm_job jobs[64];
    pthread_t tids[64];
    for (int t = 0; t < n_threads; t++)
        jobs[t] = (wm_job){wins, ref, W, L, H, matched, total,
                           t, n_threads};
    if (n_threads == 1) {
        wm_worker(&jobs[0]);
        return;
    }
    for (int t = 0; t < n_threads; t++)
        pthread_create(&tids[t], NULL, wm_worker, &jobs[t]);
    for (int t = 0; t < n_threads; t++)
        pthread_join(tids[t], NULL);
}

/* Returns the TOTAL number of passing pairs (callers detect overflow by
 * comparing against `cap`); the first min(total, cap) pairs are written
 * to the output arrays in nondeterministic thread order. */
int64_t galah_pair_stats_threshold(
    const uint64_t *mat, int64_t n, int64_t width, const int64_t *lens,
    int sketch_size, int kmer, double j_thr, int n_threads,
    int64_t *out_i, int64_t *out_j, double *out_ani, int64_t cap) {
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    int64_t next_slot = 0;
    ps_job jobs[64];
    pthread_t tids[64];
    for (int t = 0; t < n_threads; t++) {
        jobs[t] = (ps_job){mat, lens, n, width, sketch_size, kmer,
                           j_thr, t, n_threads, out_i, out_j,
                           out_ani, cap, &next_slot, 0};
    }
    if (n_threads == 1) {
        worker(&jobs[0]);
        return jobs[0].found;
    }
    for (int t = 0; t < n_threads; t++)
        pthread_create(&tids[t], NULL, worker, &jobs[t]);
    int64_t total = 0;
    for (int t = 0; t < n_threads; t++) {
        pthread_join(tids[t], NULL);
        total += jobs[t].found;
    }
    return total;
}

/* Compacted positional-hash window builder — the C twin of the
 * subsample_c > 1 branch of ops/fragment_ani GenomeProfile.windows():
 * surviving (non-sentinel) hashes move to the front of each fragment
 * row, k-mers crossing the fragment boundary (in-row position >=
 * L - (k - 1)) are dropped, row order of survivors is preserved. The
 * numpy formulation is a stable argsort over the full (W, L) array
 * (~150 ms per 3 Mbp genome); these two streaming passes replace it.
 *
 * Pass 1: per-row survivor counts (galah_window_survivor_counts) so
 * the caller can size `slots`. Pass 2: fill `wins` (W x slots,
 * prefilled with the sentinel by the caller). */

void galah_window_survivor_counts(const uint64_t *flat, int64_t n_flat,
                                  int64_t W, int64_t L, int k,
                                  int64_t *counts) {
    const uint64_t SENT = 0xFFFFFFFFFFFFFFFFull;
    const int64_t keep = L - (k - 1);
    for (int64_t r = 0; r < W; r++) counts[r] = 0;
    for (int64_t i = 0; i < n_flat; i++) {
        if (flat[i] == SENT) continue;
        int64_t r = i / L;
        if (i - r * L < keep) counts[r]++;
    }
}

void galah_fill_compact_windows(const uint64_t *flat, int64_t n_flat,
                                int64_t W, int64_t L, int k,
                                int64_t slots, uint64_t *wins) {
    const int64_t keep = L - (k - 1);
    const uint64_t SENT = 0xFFFFFFFFFFFFFFFFull;
    int64_t fill = 0, row = 0;
    for (int64_t i = 0; i < n_flat; i++) {
        int64_t r = i / L;
        if (r != row) {
            row = r;
            fill = 0;
        }
        if (flat[i] == SENT || i - r * L >= keep) continue;
        wins[r * slots + fill++] = flat[i];
    }
    (void)W;
    (void)SENT;
}

/* Sorted-merge membership counter — the per-pair fast path of the
 * fragment-ANI membership test. The matrix walker above pays
 * O(valid_slots * log H) binary searches per pair; with the query's
 * surviving hashes pre-sorted once per profile (cached host-side),
 * one linear merge against the sorted distinct ref set costs
 * O(nq + H) per pair. matched must be zeroed by the caller; totals
 * are pair-independent (per-window valid counts) and are computed by
 * the caller once per profile. Bit-identical matched counts to
 * galah_window_match_counts on the same windows. */
static void merge_count_scalar(const uint64_t *qh, const int32_t *qw,
                               int64_t nq, const uint64_t *ref,
                               int64_t H, int32_t *matched) {
    int64_t r = 0;
    for (int64_t i = 0; i < nq; i++) {
        uint64_t h = qh[i];
        while (r < H && ref[r] < h) r++;
        /* branchless increment — see the batch worker's note */
        matched[qw[i]] += (int32_t)(r < H && ref[r] == h);
    }
}

#ifdef GALAH_HAVE_AVX512_BUILD
/* AVX-512 block merge: compare 8-element query blocks against
 * 8-element ref blocks, all 64 lane combinations per block pair via 7
 * in-register rotations (valignq) + cmpeq, then advance the block
 * whose max is smaller. Ties advance the QUERY block only — the ref
 * block holding the equal element stays resident, so query duplicates
 * in later blocks still see it (ref values are distinct, query values
 * need not be). Match bits accumulate per query block and are flushed
 * as matched[qw[...]] increments at block retirement; the masked
 * flush preserves exact per-window counts. Scalar tails finish the
 * sub-block remainders from the block cursors — safe because every
 * retired ref block's max is strictly below some retired query max,
 * so no remaining query element can equal a retired ref element.
 * Bit-identical to merge_count_scalar by construction (and pinned by
 * tests/test_cpairstats.py across regimes and odd sizes). */
__attribute__((target("avx512f")))
static void merge_count_avx512(const uint64_t *qh, const int32_t *qw,
                               int64_t nq, const uint64_t *ref,
                               int64_t H, int32_t *matched) {
    int64_t qi = 0, ri = 0;
    const int64_t nqb = nq & ~(int64_t)7, nrb = H & ~(int64_t)7;
    if (nqb > 0 && nrb > 0) {
        __m512i qv = _mm512_loadu_si512((const void *)(qh + qi));
        __m512i rv = _mm512_loadu_si512((const void *)(ref + ri));
        unsigned m = 0;
        for (;;) {
            m |= (unsigned)_mm512_cmpeq_epu64_mask(qv, rv);
            m |= (unsigned)_mm512_cmpeq_epu64_mask(
                qv, _mm512_alignr_epi64(rv, rv, 1));
            m |= (unsigned)_mm512_cmpeq_epu64_mask(
                qv, _mm512_alignr_epi64(rv, rv, 2));
            m |= (unsigned)_mm512_cmpeq_epu64_mask(
                qv, _mm512_alignr_epi64(rv, rv, 3));
            m |= (unsigned)_mm512_cmpeq_epu64_mask(
                qv, _mm512_alignr_epi64(rv, rv, 4));
            m |= (unsigned)_mm512_cmpeq_epu64_mask(
                qv, _mm512_alignr_epi64(rv, rv, 5));
            m |= (unsigned)_mm512_cmpeq_epu64_mask(
                qv, _mm512_alignr_epi64(rv, rv, 6));
            m |= (unsigned)_mm512_cmpeq_epu64_mask(
                qv, _mm512_alignr_epi64(rv, rv, 7));
            if (ref[ri + 7] < qh[qi + 7]) {
                ri += 8;
                if (ri >= nrb) break;
                rv = _mm512_loadu_si512((const void *)(ref + ri));
            } else {
                while (m) {
                    int l = __builtin_ctz(m);
                    matched[qw[qi + l]]++;
                    m &= m - 1;
                }
                qi += 8;
                if (qi >= nqb) break;
                qv = _mm512_loadu_si512((const void *)(qh + qi));
            }
        }
        /* ref-exhausted exit leaves the current query block's bits
         * unflushed (query-exhausted exit left m == 0) */
        while (m) {
            int l = __builtin_ctz(m);
            matched[qw[qi + l]]++;
            m &= m - 1;
        }
    }
    /* scalar tails: double counting is impossible — a lane counted by
     * the mask matched a distinct ref value at index < ri, which the
     * offset scalar walk (equivalent to starting at r = ri) never
     * revisits */
    merge_count_scalar(qh + qi, qw + qi, nq - qi, ref + ri, H - ri,
                       matched);
}
#endif

typedef void (*merge_count_t)(const uint64_t *, const int32_t *,
                              int64_t, const uint64_t *, int64_t,
                              int32_t *);

/* Resolve the dispatch ONCE per public entry (not per pair — the
 * batched path exists because pair volume reaches N^2/2, and a getenv
 * environ scan per pair from concurrent threads is pure overhead).
 * Re-resolving per entry keeps GALAH_TPU_NO_AVX512 togglable within a
 * process (the A/B tests rely on that). */
static merge_count_t merge_count_resolve(void) {
#ifdef GALAH_HAVE_AVX512_BUILD
    if (__builtin_cpu_supports("avx512f") &&
        !getenv("GALAH_TPU_NO_AVX512"))
        return merge_count_avx512;
#endif
    return merge_count_scalar;
}

void galah_window_match_counts_merge(
    const uint64_t *qh, const int32_t *qw, int64_t nq,
    const uint64_t *ref, int64_t H, int32_t *matched) {
    merge_count_resolve()(qh, qw, nq, ref, H, matched);
}

/* Capability probe for the test harness: 1 iff the merge counter
 * would dispatch to the AVX-512 kernel right now (build support +
 * CPU support + GALAH_TPU_NO_AVX512 unset). Lets the A/B identity
 * test SKIP with an explicit reason instead of silently comparing
 * scalar against scalar on hosts without avx512f. */
int galah_merge_uses_avx512(void) {
#ifdef GALAH_HAVE_AVX512_BUILD
    return merge_count_resolve() != merge_count_scalar;
#else
    return 0;
#endif
}

/* Batched sorted-merge membership counter: the per-PAIR-LIST twin of
 * galah_window_match_counts_merge, for the exact-ANI stage when the
 * pair volume is large (the dense-similarity regime can carry N^2/2
 * screened pairs — a 5k-genome mega-family is 12.5M of them, and the
 * Python per-pair loop around the single-pair entry costs ~100x the
 * merge itself at typical small-genome sizes).
 *
 * Per-genome query data (qh/qw concatenated, offset by q_off) and
 * per-genome sorted distinct ref sets (ref concatenated, offset by
 * r_off) are laid out once by the caller; pair p counts query
 * pair_q[p] against ref pair_r[p] into the concatenated matched
 * output at m_off[p] (caller-computed prefix of each query's window
 * count; the output buffer must be zeroed). Pairs are independent —
 * split across threads; when H is much smaller than nq the merge
 * degenerates gracefully (it is O(nq + H) either way). */
typedef struct {
    const uint64_t *qh_cat;
    const int32_t *qw_cat;
    const int64_t *q_off;     /* per-genome [g, g+1) into qh/qw */
    const uint64_t *ref_cat;
    const int64_t *r_off;     /* per-genome [g, g+1) into ref_cat */
    const int32_t *pair_q, *pair_r;
    const int64_t *m_off;     /* per-pair output offset */
    int64_t n_pairs;
    int32_t *matched_cat;
    int tid, n_threads;
} wmb_job;

static void *wmb_worker(void *arg) {
    wmb_job *w = (wmb_job *)arg;
    merge_count_t mc = merge_count_resolve(); /* once per worker */
    for (int64_t p = w->tid; p < w->n_pairs; p += w->n_threads) {
        int64_t qg = w->pair_q[p], rg = w->pair_r[p];
        const uint64_t *qh = w->qh_cat + w->q_off[qg];
        const int32_t *qw = w->qw_cat + w->q_off[qg];
        int64_t nq = w->q_off[qg + 1] - w->q_off[qg];
        const uint64_t *ref = w->ref_cat + w->r_off[rg];
        int64_t H = w->r_off[rg + 1] - w->r_off[rg];
        int32_t *matched = w->matched_cat + w->m_off[p];
        /* AVX-512 block merge when the CPU has it, scalar walk
         * otherwise (branchless increment: in the dense-similarity
         * regime ~all query hashes match, in the sparse regime ~none —
         * either way compare-to-increment beats a data-dependent
         * branch) */
        mc(qh, qw, nq, ref, H, matched);
    }
    return NULL;
}

void galah_window_match_counts_merge_batch(
    const uint64_t *qh_cat, const int32_t *qw_cat,
    const int64_t *q_off, const uint64_t *ref_cat,
    const int64_t *r_off, const int32_t *pair_q,
    const int32_t *pair_r, const int64_t *m_off, int64_t n_pairs,
    int n_threads, int32_t *matched_cat) {
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    if ((int64_t)n_threads > n_pairs)
        n_threads = n_pairs > 0 ? (int)n_pairs : 1;
    wmb_job jobs[64];
    pthread_t tids[64];
    for (int t = 0; t < n_threads; t++)
        jobs[t] = (wmb_job){qh_cat, qw_cat, q_off, ref_cat, r_off,
                            pair_q, pair_r, m_off, n_pairs,
                            matched_cat, t, n_threads};
    if (n_threads == 1) {
        wmb_worker(&jobs[0]);
        return;
    }
    for (int t = 0; t < n_threads; t++)
        pthread_create(&tids[t], NULL, wmb_worker, &jobs[t]);
    for (int t = 0; t < n_threads; t++)
        pthread_join(tids[t], NULL);
}

/* Window assembly from the profile walk's kept (pos, hash) pairs —
 * O(n_valid) twins of galah_window_survivor_counts /
 * galah_fill_compact_windows, which each stream the full
 * 8-byte-per-bp flat array. Semantics identical: positions whose
 * in-window column is >= L - (k - 1) (a k-mer crossing the window
 * boundary) are dropped; survivors keep genome order within their
 * window. counts must be zeroed; wins must be SENTINEL-filled. */
void galah_window_counts_pairs(const int64_t *pos, int64_t nv,
                               int64_t W, int64_t L, int k,
                               int64_t *counts) {
    int64_t tail = L - (k - 1);
    for (int64_t i = 0; i < nv; i++) {
        int64_t col = pos[i] % L;
        if (col < tail) counts[pos[i] / L]++;
    }
    (void)W;
}

void galah_fill_windows_pairs(const int64_t *pos, const uint64_t *h,
                              int64_t nv, int64_t W, int64_t L, int k,
                              int64_t slots, int64_t *cursors,
                              uint64_t *wins) {
    int64_t tail = L - (k - 1);
    for (int64_t i = 0; i < nv; i++) {
        int64_t col = pos[i] % L;
        if (col >= tail) continue;
        int64_t w = pos[i] / L;
        int64_t c = cursors[w]++;
        if (c < slots) wins[w * slots + c] = h[i];
    }
    (void)W;
}
