/* Native collision counting over sketch/marker matrices.
 *
 * C twin of galah_tpu/ops/collision.py::collision_pair_counts — the
 * inverted-index screen that replaces every O(N^2) all-pairs pass
 * (reference analog: skani's marker screening, src/skani.rs:54-70).
 * The numpy formulation is O(NK log NK) but churns multi-GB
 * temporaries through argsort/fancy-indexing/np.unique compaction; at
 * N=100k (1e8 hashes) it measured 249 s on one core. This version:
 *
 *   1. extracts (hash, row) for every valid entry,
 *   2. LSB radix sort, 4 passes x 16 bits, payload carried alongside,
 *   3. walks runs of equal hashes:
 *        - small runs (2..big_run): emit every i<j pair, weight 1,
 *          into an open-addressing hashmap keyed i*n+j;
 *        - big runs (> big_run, near-duplicate mega-clusters): the
 *          run's sorted distinct rows form a group; identical groups
 *          across hashes are deduplicated by content and their
 *          occurrence counts added once per pair (keeps work
 *          O(K*m + output) instead of O(K*m^2)) — exactly the numpy
 *          path's group-signature semantics;
 *   4. returns the distinct (i, j, count) triples (unsorted; the
 *      Python wrapper orders them to match numpy's unique-sorted
 *      output bit-for-bit).
 *
 * Single-threaded by design: the pass is memory-bandwidth-bound and
 * the deployment box is one core; the radix buffers are the only
 * large allocations (~24 bytes per hash).
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* ---- open-addressing hashmap: u64 key -> i64 count ---- */

typedef struct {
    uint64_t *keys;
    int64_t *vals;
    uint8_t *used;
    uint64_t mask; /* capacity - 1 */
    int64_t n;     /* occupied slots */
} Map;

static uint64_t mix64(uint64_t x) {
    x ^= x >> 33;
    x *= 0xff51afd7ed558ccdULL;
    x ^= x >> 33;
    x *= 0xc4ceb9fe1a85ec53ULL;
    x ^= x >> 33;
    return x;
}

static int map_init(Map *m, uint64_t cap_pow2) {
    m->keys = (uint64_t *)malloc(cap_pow2 * sizeof(uint64_t));
    m->vals = (int64_t *)malloc(cap_pow2 * sizeof(int64_t));
    m->used = (uint8_t *)calloc(cap_pow2, 1);
    m->mask = cap_pow2 - 1;
    m->n = 0;
    if (!m->keys || !m->vals || !m->used) return -1;
    return 0;
}

static void map_free(Map *m) {
    free(m->keys);
    free(m->vals);
    free(m->used);
}

static int map_grow(Map *m);

/* Lookup without insert: returns the value or -1. */
static int64_t map_get(const Map *m, uint64_t key) {
    uint64_t h = mix64(key) & m->mask;
    while (m->used[h]) {
        if (m->keys[h] == key) return m->vals[h];
        h = (h + 1) & m->mask;
    }
    return -1;
}

/* Insert or overwrite. */
static int map_put(Map *m, uint64_t key, int64_t val) {
    if ((uint64_t)m->n * 2 >= m->mask + 1) {
        if (map_grow(m)) return -1;
    }
    uint64_t h = mix64(key) & m->mask;
    while (m->used[h]) {
        if (m->keys[h] == key) {
            m->vals[h] = val;
            return 0;
        }
        h = (h + 1) & m->mask;
    }
    m->used[h] = 1;
    m->keys[h] = key;
    m->vals[h] = val;
    m->n++;
    return 0;
}

static int map_add(Map *m, uint64_t key, int64_t w) {
    if ((uint64_t)m->n * 2 >= m->mask + 1) {
        if (map_grow(m)) return -1;
    }
    uint64_t h = mix64(key) & m->mask;
    while (m->used[h]) {
        if (m->keys[h] == key) {
            m->vals[h] += w;
            return 0;
        }
        h = (h + 1) & m->mask;
    }
    m->used[h] = 1;
    m->keys[h] = key;
    m->vals[h] = w;
    m->n++;
    return 0;
}

static int map_grow(Map *m) {
    Map bigger;
    if (map_init(&bigger, (m->mask + 1) * 2)) {
        map_free(&bigger); /* free any partial allocations */
        return -1;
    }
    for (uint64_t s = 0; s <= m->mask; s++) {
        if (!m->used[s]) continue;
        uint64_t h = mix64(m->keys[s]) & bigger.mask;
        while (bigger.used[h]) h = (h + 1) & bigger.mask;
        bigger.used[h] = 1;
        bigger.keys[h] = m->keys[s];
        bigger.vals[h] = m->vals[s];
        bigger.n++;
    }
    map_free(m);
    *m = bigger;
    return 0;
}

/* ---- big-run group table: content-addressed sorted row lists ---- */

typedef struct {
    int64_t *rows;   /* concatenated group row lists */
    int64_t *starts; /* group g occupies rows[starts[g]..starts[g+1]) */
    int64_t *occ;    /* occurrence count per group */
    int64_t *next;   /* same-signature chain link per group, -1 ends */
    Map sigmap;      /* content hash -> chain head group index */
    int64_t n_groups, rows_len, rows_cap, groups_cap;
} Groups;

static int groups_init(Groups *g) {
    memset(g, 0, sizeof(*g));
    g->rows_cap = 1 << 16;
    g->groups_cap = 1 << 10;
    g->rows = (int64_t *)malloc(g->rows_cap * sizeof(int64_t));
    g->starts = (int64_t *)malloc((g->groups_cap + 1) * sizeof(int64_t));
    g->occ = (int64_t *)malloc(g->groups_cap * sizeof(int64_t));
    g->next = (int64_t *)malloc(g->groups_cap * sizeof(int64_t));
    if (map_init(&g->sigmap, 1 << 10)) return -1;
    if (!g->rows || !g->starts || !g->occ || !g->next) return -1;
    g->starts[0] = 0;
    return 0;
}

static void groups_free(Groups *g) {
    free(g->rows);
    free(g->starts);
    free(g->occ);
    free(g->next);
    map_free(&g->sigmap);
}

static uint64_t group_hash(const int64_t *rows, int64_t m) {
    uint64_t h = 1469598103934665603ULL ^ (uint64_t)m;
    for (int64_t i = 0; i < m; i++)
        h = mix64(h ^ (uint64_t)rows[i]);
    return h;
}

/* Add one occurrence of the sorted, distinct row list `rows[0..m)`.
 * O(1) expected via the signature hashmap; exact regardless of 64-bit
 * signature collisions (chained content memcmp). */
static int groups_add(Groups *g, const int64_t *rows, int64_t m) {
    uint64_t sig = group_hash(rows, m);
    int64_t head = map_get(&g->sigmap, sig);
    for (int64_t k = head; k >= 0; k = g->next[k]) {
        int64_t len = g->starts[k + 1] - g->starts[k];
        if (len == m &&
            !memcmp(g->rows + g->starts[k], rows,
                    (size_t)m * sizeof(int64_t))) {
            g->occ[k]++;
            return 0;
        }
    }
    if (g->n_groups == g->groups_cap) {
        /* grow one array at a time, committing each success so a
         * mid-sequence failure leaves every pointer valid for free */
        int64_t new_cap = g->groups_cap * 2;
        int64_t *ns = (int64_t *)realloc(
            g->starts, (new_cap + 1) * sizeof(int64_t));
        if (!ns) return -1;
        g->starts = ns;
        int64_t *no = (int64_t *)realloc(
            g->occ, new_cap * sizeof(int64_t));
        if (!no) return -1;
        g->occ = no;
        int64_t *nn = (int64_t *)realloc(
            g->next, new_cap * sizeof(int64_t));
        if (!nn) return -1;
        g->next = nn;
        g->groups_cap = new_cap;
    }
    while (g->rows_len + m > g->rows_cap) {
        int64_t new_cap = g->rows_cap * 2;
        int64_t *nr = (int64_t *)realloc(
            g->rows, new_cap * sizeof(int64_t));
        if (!nr) return -1;
        g->rows = nr;
        g->rows_cap = new_cap;
    }
    memcpy(g->rows + g->rows_len, rows, (size_t)m * sizeof(int64_t));
    g->rows_len += m;
    g->occ[g->n_groups] = 1;
    g->next[g->n_groups] = head;
    if (map_put(&g->sigmap, sig, g->n_groups)) return -1;
    g->n_groups++;
    g->starts[g->n_groups] = g->rows_len;
    return 0;
}

/* ---- insertion sort for small run row-id lists ---- */

static void isort64(int64_t *a, int64_t m) {
    for (int64_t i = 1; i < m; i++) {
        int64_t v = a[i], j = i - 1;
        while (j >= 0 && a[j] > v) {
            a[j + 1] = a[j];
            j--;
        }
        a[j + 1] = v;
    }
}

/* Sort + dedupe in place; returns new length. */
static int64_t sort_unique(int64_t *a, int64_t m) {
    isort64(a, m);
    int64_t w = 0;
    for (int64_t i = 0; i < m; i++)
        if (i == 0 || a[i] != a[i - 1]) a[w++] = a[i];
    return w;
}

/* ---- main entry ----
 *
 * mat: (n, width) uint64, rows sorted ascending, SENTINEL-padded;
 * lens: per-row valid count. Emits distinct colliding pairs with
 * exact |A intersect B| counts. Returns the number of distinct pairs
 * (may exceed cap — only the first cap are written), or -1 on
 * allocation failure.
 */
int64_t galah_collision_pair_counts(
    const uint64_t *mat, int64_t n, int64_t width, const int64_t *lens,
    int64_t big_run,
    int64_t *out_i, int64_t *out_j, int64_t *out_c, int64_t cap) {
    int64_t total = 0;
    for (int64_t r = 0; r < n; r++) total += lens[r];
    if (total == 0) return 0;

    uint64_t *k0 = (uint64_t *)malloc(total * sizeof(uint64_t));
    uint64_t *k1 = (uint64_t *)malloc(total * sizeof(uint64_t));
    int64_t *p0 = (int64_t *)malloc(total * sizeof(int64_t));
    int64_t *p1 = (int64_t *)malloc(total * sizeof(int64_t));
    if (!k0 || !k1 || !p0 || !p1) {
        free(k0);
        free(k1);
        free(p0);
        free(p1);
        return -1;
    }
    int64_t m = 0;
    for (int64_t r = 0; r < n; r++) {
        const uint64_t *row = mat + r * width;
        for (int64_t c = 0; c < lens[r]; c++) {
            k0[m] = row[c];
            p0[m] = r;
            m++;
        }
    }

    /* LSB radix sort, 4 passes x 16 bits. The 512 KiB histogram is
     * heap-allocated: this pass can run on worker threads, whose
     * stacks may be far smaller than the main thread's (e.g. musl's
     * 128 KiB default). */
    static const int RADIX_BITS = 16;
    int64_t *hist = (int64_t *)malloc((1 << 16) * sizeof(int64_t));
    if (!hist) {
        free(k0);
        free(k1);
        free(p0);
        free(p1);
        return -1;
    }
    for (int pass = 0; pass < 4; pass++) {
        int shift = pass * RADIX_BITS;
        memset(hist, 0, (1 << 16) * sizeof(int64_t));
        for (int64_t i = 0; i < m; i++)
            hist[(k0[i] >> shift) & 0xFFFF]++;
        int64_t acc = 0;
        for (int64_t b = 0; b < (1 << 16); b++) {
            int64_t c = hist[b];
            hist[b] = acc;
            acc += c;
        }
        for (int64_t i = 0; i < m; i++) {
            int64_t d = hist[(k0[i] >> shift) & 0xFFFF]++;
            k1[d] = k0[i];
            p1[d] = p0[i];
        }
        uint64_t *tk = k0;
        k0 = k1;
        k1 = tk;
        int64_t *tp = p0;
        p0 = p1;
        p1 = tp;
    }
    free(hist);
    free(k1);
    free(p1);

    /* zero-init so the cleanup frees are safe even when an init
     * fails partway (free(NULL) is a no-op) */
    Map map;
    Groups groups;
    memset(&map, 0, sizeof(map));
    memset(&groups, 0, sizeof(groups));
    int err = map_init(&map, 1 << 16);
    if (!err) err = groups_init(&groups);
    int64_t *scratch = NULL;
    int64_t scratch_cap = 0;

    for (int64_t s = 0; s < m && !err;) {
        int64_t e = s + 1;
        while (e < m && k0[e] == k0[s]) e++;
        int64_t run = e - s;
        if (run >= 2) {
            if (run > scratch_cap) {
                scratch_cap = run * 2;
                int64_t *ns = (int64_t *)realloc(
                    scratch, scratch_cap * sizeof(int64_t));
                if (!ns) {
                    err = 1;
                    break;
                }
                scratch = ns;
            }
            memcpy(scratch, p0 + s, (size_t)run * sizeof(int64_t));
            if (run > big_run) {
                /* numpy big-run path dedupes rows (np.unique) */
                int64_t u = sort_unique(scratch, run);
                err = groups_add(&groups, scratch, u);
            } else {
                /* numpy small-run path sorts WITHOUT dedupe and only
                 * skips i==j — keep that exact semantics (duplicate
                 * row ids cannot occur for distinct-valued rows, but
                 * the defensive behavior must match bit-for-bit) */
                isort64(scratch, run);
                for (int64_t a = 0; a < run && !err; a++)
                    for (int64_t b = a + 1; b < run; b++) {
                        if (scratch[a] == scratch[b]) continue;
                        err = map_add(&map,
                                      (uint64_t)scratch[a] * (uint64_t)n +
                                          (uint64_t)scratch[b],
                                      1);
                    }
            }
        }
        s = e;
    }
    for (int64_t g = 0; g < groups.n_groups && !err; g++) {
        const int64_t *rows = groups.rows + groups.starts[g];
        int64_t len = groups.starts[g + 1] - groups.starts[g];
        int64_t occ = groups.occ[g];
        for (int64_t a = 0; a < len && !err; a++)
            for (int64_t b = a + 1; b < len; b++)
                err = map_add(&map,
                              (uint64_t)rows[a] * (uint64_t)n +
                                  (uint64_t)rows[b],
                              occ);
    }

    int64_t found = -1;
    if (!err) {
        found = map.n;
        int64_t w = 0;
        for (uint64_t slot = 0; slot <= map.mask && w < cap; slot++) {
            if (!map.used[slot]) continue;
            out_i[w] = (int64_t)(map.keys[slot] / (uint64_t)n);
            out_j[w] = (int64_t)(map.keys[slot] % (uint64_t)n);
            out_c[w] = map.vals[slot];
            w++;
        }
    }
    free(scratch);
    free(k0);
    free(p0);
    map_free(&map);
    groups_free(&groups);
    return found;
}
