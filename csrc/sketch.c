/* Native MinHash sketcher: codes -> bottom-k distinct canonical k-mer
 * hashes, single pass.
 *
 * Compiled-C twin of the JAX sketch pipeline (galah_tpu/ops/hashing.py
 * + ops/minhash.py) for CPU backends — the reference's finch sketching
 * is compiled Rust doing this exact job (reference: src/finch.rs:33-47,
 * sketch_files). Bit-identical contract:
 *   - canonical k-mer = lexicographic min of the forward ASCII k-mer
 *     and its reverse complement (A<C<G<T matches ASCII order, so the
 *     2-bit MSB-first packed integers compare identically);
 *   - "murmur3": MurmurHash3 x64_128 h1 (h1+h2 finalization) over the
 *     canonical ASCII bytes, seed as given;
 *   - "tpufast": the multiply-free shift-add mixer over the canonical
 *     2-bit packed key (mirrors hashing._tpufast_mix);
 *   - windows containing an ambiguous base (code 255) or crossing a
 *     contig boundary produce no hash;
 *   - result = the sketch_size smallest DISTINCT hash values, sorted.
 *
 * The rolling 2-bit packs make the per-position cost O(1); bottom-k is
 * a threshold + candidate buffer with periodic sort/dedup/merge.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* ---------------- murmur3 x64_128 (h1 + h2, return h1) ------------- */

static inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

static inline uint64_t fmix64(uint64_t x) {
    x ^= x >> 33;
    x *= 0xFF51AFD7ED558CCDull;
    x ^= x >> 33;
    x *= 0xC4CEB9FE1A85EC53ull;
    x ^= x >> 33;
    return x;
}

static uint64_t murmur3_x64_128_h1(const uint8_t *key, int len,
                                   uint64_t seed) {
    const uint64_t c1 = 0x87C37B91114253D5ull;
    const uint64_t c2 = 0x4CF5AD432745937Full;
    uint64_t h1 = seed, h2 = seed;
    int nblocks = len / 16;
    for (int b = 0; b < nblocks; b++) {
        uint64_t k1, k2;
        memcpy(&k1, key + b * 16, 8);      /* little-endian hosts */
        memcpy(&k2, key + b * 16 + 8, 8);
        k1 *= c1;
        k1 = rotl64(k1, 31);
        k1 *= c2;
        h1 ^= k1;
        h1 = rotl64(h1, 27);
        h1 += h2;
        h1 = h1 * 5 + 0x52DCE729ull;
        k2 *= c2;
        k2 = rotl64(k2, 33);
        k2 *= c1;
        h2 ^= k2;
        h2 = rotl64(h2, 31);
        h2 += h1;
        h2 = h2 * 5 + 0x38495AB5ull;
    }
    const uint8_t *tail = key + nblocks * 16;
    int rem = len & 15;
    uint64_t k1 = 0, k2 = 0;
    for (int b = rem - 1; b >= 8; b--) k2 = (k2 << 8) | tail[b];
    if (rem > 8) {
        k2 *= c2;
        k2 = rotl64(k2, 33);
        k2 *= c1;
        h2 ^= k2;
    }
    int top = rem < 8 ? rem : 8;
    for (int b = top - 1; b >= 0; b--) k1 = (k1 << 8) | tail[b];
    if (rem > 0) {
        k1 *= c1;
        k1 = rotl64(k1, 31);
        k1 *= c2;
        h1 ^= k1;
    }
    h1 ^= (uint64_t)len;
    h2 ^= (uint64_t)len;
    h1 += h2;
    h2 += h1;
    h1 = fmix64(h1);
    h2 = fmix64(h2);
    h1 += h2;
    return h1;
}

/* ---------------- tpufast mixer (mirrors hashing._tpufast_mix) ----- */

static uint64_t tpufast_mix(uint64_t x, uint64_t seed) {
    x ^= seed * 0x9E3779B97F4A7C15ull + 0x1B873593ull;
    static const int rounds[3][3] = {
        {21, 37, 29}, {13, 47, 31}, {17, 41, 33}};
    for (int r = 0; r < 3; r++) {
        x = x + (x << rounds[r][0]) + (x << rounds[r][1]);
        x = x ^ (x >> rounds[r][2]);
    }
    x = x + (x << 26);
    x = x ^ (x >> 32);
    return x;
}

/* ---------------- bottom-k distinct accumulator -------------------- */

typedef struct {
    uint64_t *sketch;   /* sorted distinct, <= size entries */
    int n_sketch;
    int size;
    uint64_t thr;       /* current admission threshold */
    uint64_t *cand;
    int n_cand, cap;
} bk_acc;

/* Inlined u64 quicksort (median-of-3, insertion cutoff): libc qsort's
 * function-pointer compares made bottom-k compaction the dominant cost
 * of sketching SMALL genomes (777 us for a 20 kb genome — 26 Mbp/s vs
 * the walker's ~150 Mbp/s on multi-Mbp inputs). */
static void sort_u64(uint64_t *a, int64_t n) {
    while (n > 16) {
        int64_t mid = n / 2;
        uint64_t p0 = a[0], p1 = a[mid], p2 = a[n - 1], t;
        if (p0 > p1) { t = p0; p0 = p1; p1 = t; }
        if (p1 > p2) { p1 = p2; }
        if (p0 > p1) { p1 = p0; }
        uint64_t piv = p1;
        int64_t i = 0, j = n - 1;
        for (;;) {
            while (a[i] < piv) i++;
            while (a[j] > piv) j--;
            if (i >= j) break;
            t = a[i]; a[i] = a[j]; a[j] = t;
            i++; j--;
        }
        /* recurse into the smaller side, loop on the larger */
        if (j + 1 < n - j - 1) {
            sort_u64(a, j + 1);
            a += j + 1;
            n -= j + 1;
        } else {
            sort_u64(a + j + 1, n - j - 1);
            n = j + 1;
        }
    }
    for (int64_t i = 1; i < n; i++) {
        uint64_t v = a[i];
        int64_t j = i - 1;
        while (j >= 0 && a[j] > v) { a[j + 1] = a[j]; j--; }
        a[j + 1] = v;
    }
}

static void bk_compact(bk_acc *acc) {
    /* merge sketch + candidates, dedup, keep the smallest `size` */
    int m = acc->n_sketch + acc->n_cand;
    uint64_t *buf = acc->cand; /* reuse: copy sketch in, sort whole */
    /* cand buffer has cap >= size + slack; ensure room */
    memcpy(buf + acc->n_cand, acc->sketch,
           (size_t)acc->n_sketch * sizeof(uint64_t));
    sort_u64(buf, m);
    int out = 0;
    for (int i = 0; i < m && out < acc->size; i++) {
        if (i > 0 && buf[i] == buf[i - 1]) continue;
        acc->sketch[out++] = buf[i];
    }
    acc->n_sketch = out;
    acc->n_cand = 0;
    if (out == acc->size) acc->thr = acc->sketch[out - 1];
}

static inline void bk_add(bk_acc *acc, uint64_t h) {
    /* branchless admission: always write, conditionally advance —
     * the data-dependent h >= thr branch mispredicts heavily on the
     * pre-threshold prefix of every genome */
    acc->cand[acc->n_cand] = h;
    acc->n_cand += (h < acc->thr);
    if (acc->n_cand >= acc->cap - acc->size) bk_compact(acc);
}

/* ---------------- shared canonical-window walker ------------------- */

/* One definition of the rolling canonical-k-mer iteration shared by the
 * bottom-k sketcher, the positional hasher, and the HLL fold: O(1)
 * rolling 2-bit packs, ambiguous-run and contig-crossing skipping,
 * canonical (min of forward/revcomp) key hashing with the selected
 * algo. Inside the statement hooks, WPOS is the window start index and
 * WHASH the canonical hash; VALID_STMT runs per valid window,
 * INVALID_STMT per invalid window position (both only for WPOS >= 0). */
#define GALAH_WALK(codes, n, offsets, n_offsets, k, seed, algo,        \
                   VALID_STMT, INVALID_STMT)                           \
    do {                                                               \
        const uint64_t mask_ =                                         \
            (k) < 32 ? (1ull << (2 * (k))) - 1 : ~0ull;                \
        const int shift_hi_ = 2 * ((k) - 1);                           \
        static const char ASCII_[4] = {'A', 'C', 'G', 'T'};            \
        const int64_t *interior_ = (offsets) + 1;                      \
        int64_t n_int_ = (n_offsets) >= 2 ? (n_offsets) - 2 : 0;       \
        int64_t bptr_ = 0;                                             \
        uint64_t fwd_ = 0, rev_ = 0;                                   \
        int valid_run_ = 0;                                            \
        uint8_t keybuf_[32];                                           \
        for (int64_t i_ = 0; i_ < (n); i_++) {                         \
            uint8_t c_ = (codes)[i_];                                  \
            int64_t WPOS = i_ - (k) + 1;                               \
            if (c_ > 3) {                                              \
                valid_run_ = 0;                                        \
            } else {                                                   \
                valid_run_++;                                          \
                fwd_ = ((fwd_ << 2) | c_) & mask_;                     \
                rev_ = (rev_ >> 2) |                                   \
                       ((uint64_t)(3 - c_) << shift_hi_);              \
            }                                                          \
            if (WPOS < 0) continue;                                    \
            int invalid_ = valid_run_ < (k);                           \
            if (!invalid_) {                                           \
                while (bptr_ < n_int_ && interior_[bptr_] <= WPOS)     \
                    bptr_++;                                           \
                invalid_ = bptr_ < n_int_ &&                           \
                           interior_[bptr_] < WPOS + (k);              \
            }                                                          \
            if (invalid_) {                                            \
                INVALID_STMT;                                          \
                continue;                                              \
            }                                                          \
            uint64_t canon_ = fwd_ <= rev_ ? fwd_ : rev_;              \
            uint64_t WHASH;                                            \
            if ((algo) == 1) {                                         \
                WHASH = tpufast_mix(canon_, (seed));                   \
            } else {                                                   \
                for (int b_ = 0; b_ < (k); b_++)                       \
                    keybuf_[b_] = (uint8_t)ASCII_[                     \
                        (canon_ >> (2 * ((k) - 1 - b_))) & 3];         \
                WHASH = murmur3_x64_128_h1(keybuf_, (k), (seed));      \
            }                                                          \
            VALID_STMT;                                                \
        }                                                              \
    } while (0)

/* ---------------- positional hashes -------------------------------- */

/* Every window's canonical hash in genome order; invalid windows
 * (ambiguous base / contig crossing) get the 0xFFFF..FF sentinel.
 * out: uint64[n - k + 1]. Twin of ops/fragment_ani.positional_hashes.
 * Returns n - k + 1, or 0 when n < k. */
int64_t galah_positional_hashes(const uint8_t *codes, int64_t n,
                                const int64_t *offsets,
                                int64_t n_offsets, int k, uint64_t seed,
                                int algo, uint64_t *out) {
    if (n < k || k < 1 || k > 32) return 0;
    const uint64_t SENT = 0xFFFFFFFFFFFFFFFFull;
    GALAH_WALK(codes, n, offsets, n_offsets, k, seed, algo,
               out[WPOS] = WHASH, out[WPOS] = SENT);
    return n - k + 1;
}

/* Positional hashes with the FracMinHash subsample mask and the valid
 * compaction folded into the same walk — the profile build's whole
 * host post-pass (np.where + boolean filter over an 8-byte-per-bp
 * array) collapses into it. cut == 0 means keep every valid hash;
 * cut > 0 keeps h < cut and masks the rest to the sentinel (the
 * FracMinHash criterion, reference analog: skani's c compression,
 * src/skani.rs:159-161). valid_out (capacity n - k + 1) receives the
 * kept hashes in genome order, duplicates included; *n_valid_out gets
 * the count. Returns n - k + 1, or 0 when n < k. */
int64_t galah_positional_hashes_profile(
    const uint8_t *codes, int64_t n, const int64_t *offsets,
    int64_t n_offsets, int k, uint64_t seed, int algo, uint64_t cut,
    uint64_t *out, uint64_t *valid_out, int64_t *pos_out,
    int64_t *n_valid_out);

int64_t galah_positional_hashes_masked(
    const uint8_t *codes, int64_t n, const int64_t *offsets,
    int64_t n_offsets, int k, uint64_t seed, int algo, uint64_t cut,
    uint64_t *out, uint64_t *valid_out, int64_t *n_valid_out) {
    /* one walk body to keep in sync: the profile variant with a NULL
     * position sink is this function */
    return galah_positional_hashes_profile(
        codes, n, offsets, n_offsets, k, seed, algo, cut, out,
        valid_out, NULL, n_valid_out);
}

/* ---------------- HLL registers ------------------------------------ */

/* 2^p uint8 HyperLogLog registers over the genome's canonical k-mer
 * hashes — C twin of ops/hll.hll_sketch_genome: register index = top p
 * bits, rho = leading zeros of the remaining bits + 1 (capped at
 * 64 - p + 1), registers take the max. regs must be zeroed by the
 * caller. Returns 0. */
int64_t galah_hll_registers(const uint8_t *codes, int64_t n,
                            const int64_t *offsets, int64_t n_offsets,
                            int k, int p, uint64_t seed, int algo,
                            uint8_t *regs) {
    if (n < k || k < 1 || k > 32 || p < 1 || p > 24) return 0;
    const uint8_t rho_cap = (uint8_t)(64 - p + 1);
    GALAH_WALK(
        codes, n, offsets, n_offsets, k, seed, algo,
        {
            uint64_t idx = WHASH >> (64 - p);
            uint64_t rest = WHASH << p;
            uint8_t rho = 1;
            if (rest == 0) {
                rho = rho_cap;
            } else {
                while (!(rest >> 63)) {
                    rest <<= 1;
                    rho++;
                }
                if (rho > rho_cap) rho = rho_cap;
            }
            if (rho > regs[idx]) regs[idx] = rho;
        },
        (void)0);
    return 0;
}

/* ---------------- main entry --------------------------------------- */

/* codes: uint8[n], values 0-3 or 255 (ambiguous).
 * offsets: int64[n_offsets] full contig offset array [0, ..., n].
 * algo: 0 = murmur3, 1 = tpufast.
 * out: uint64[sketch_size]; returns number of hashes written. */
int64_t galah_sketch_bottomk(const uint8_t *codes, int64_t n,
                             const int64_t *offsets, int64_t n_offsets,
                             int k, int sketch_size, uint64_t seed,
                             int algo, uint64_t *out) {
    if (n < k || k < 1 || k > 32 || sketch_size < 1) return 0;

    bk_acc acc;
    acc.size = sketch_size;
    acc.sketch = (uint64_t *)malloc((size_t)sketch_size * 8);
    acc.n_sketch = 0;
    acc.thr = 0xFFFFFFFFFFFFFFFFull;
    acc.cap = sketch_size + 4096 + sketch_size;
    acc.cand = (uint64_t *)malloc((size_t)acc.cap * 8);
    acc.n_cand = 0;
    if (!acc.sketch || !acc.cand) {
        free(acc.sketch);
        free(acc.cand);
        return -1;
    }

    GALAH_WALK(codes, n, offsets, n_offsets, k, seed, algo,
               bk_add(&acc, WHASH), (void)0);
    bk_compact(&acc);
    int64_t out_n = acc.n_sketch;
    memcpy(out, acc.sketch, (size_t)out_n * 8);
    free(acc.sketch);
    free(acc.cand);
    return out_n;
}

/* galah_positional_hashes_masked plus the kept hashes' POSITIONS: the
 * (pos, hash) pair list lets the window assembly run O(n_valid)
 * instead of re-walking the 8-byte-per-bp flat array twice
 * (csrc/pairstats.c::galah_window_counts_pairs / _fill_windows_pairs
 * consume it). pos_out may be NULL (positions discarded) — the masked
 * entry above is exactly that call, so there is ONE walk body. */
int64_t galah_positional_hashes_profile(
    const uint8_t *codes, int64_t n, const int64_t *offsets,
    int64_t n_offsets, int k, uint64_t seed, int algo, uint64_t cut,
    uint64_t *out, uint64_t *valid_out, int64_t *pos_out,
    int64_t *n_valid_out) {
    *n_valid_out = 0;
    if (n < k || k < 1 || k > 32) return 0;
    const uint64_t SENT = 0xFFFFFFFFFFFFFFFFull;
    int64_t nv = 0;
    GALAH_WALK(codes, n, offsets, n_offsets, k, seed, algo,
               {
                   if (!cut) {
                       /* keep-all: flat holds the raw hash; the valid
                        * list still excludes a natural sentinel-valued
                        * hash, matching the numpy != SENTINEL filter */
                       out[WPOS] = WHASH;
                       if (WHASH != SENT) {
                           valid_out[nv] = WHASH;
                           if (pos_out) pos_out[nv] = WPOS;
                           nv++;
                       }
                   } else if (WHASH < cut) {
                       out[WPOS] = WHASH;
                       valid_out[nv] = WHASH;
                       if (pos_out) pos_out[nv] = WPOS;
                       nv++;
                   } else {
                       out[WPOS] = SENT;
                   }
               },
               out[WPOS] = SENT);
    *n_valid_out = nv;
    return n - k + 1;
}

