/* Native FASTA ingestion kernel.
 *
 * The framework's needletail analog (reference: src/genome_stats.rs:1-51
 * consumes needletail's streaming parse; the reference is native here, so
 * this parser is C, not Python). One pass over a possibly gzip-compressed
 * FASTA produces:
 *
 *   - codes:   uint8 per base, A/C/G/T (case-insensitive) -> 0..3,
 *              anything else -> 255 (ambiguous)
 *   - offsets: int64 contig boundaries, length n_contigs + 1
 *   - num_ambiguous / n50: assembly stats computed in the same pass
 *              (semantics match reference: src/genome_stats.rs:11-51 and
 *              the goldens at :61-87)
 *
 * Line semantics deliberately mirror the Python fallback in
 * galah_tpu/io/fasta.py (the semantic reference): each line is stripped
 * of leading/trailing ASCII whitespace; blank lines are skipped; a
 * stripped line starting with '>' opens a new contig; sequence bytes
 * before the first header are dropped; interior whitespace inside a
 * sequence line maps through the LUT (i.e. counts as ambiguous).
 *
 * Exposed via ctypes (galah_tpu/io/_cingest.py); no CPython API used.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <zlib.h>

typedef struct {
    uint8_t *codes;
    int64_t total_len;
    int64_t *offsets; /* n_contigs + 1 entries */
    int64_t n_contigs;
    int64_t num_ambiguous;
    int64_t n50;
} GalahGenome;

enum {
    GALAH_OK = 0,
    GALAH_ERR_OPEN = -1,
    GALAH_ERR_NO_RECORDS = -2,
    GALAH_ERR_OOM = -3,
    GALAH_ERR_READ = -4,
};

static const uint8_t CODE_LUT[256] = {
    [0 ... 255] = 255,
    ['A'] = 0, ['C'] = 1, ['G'] = 2, ['T'] = 3,
    ['a'] = 0, ['c'] = 1, ['g'] = 2, ['t'] = 3,
};

/* "whitespace" = bytes Python's bytes.strip() removes */
static inline int is_ws(uint8_t b) {
    return b == ' ' || b == '\t' || b == '\r' || b == '\n' ||
           b == '\v' || b == '\f';
}

typedef struct {
    int64_t *data;
    int64_t len;
    int64_t cap;
} I64Buf;

static int i64_push(I64Buf *b, int64_t v) {
    if (b->len == b->cap) {
        int64_t cap = b->cap ? b->cap * 2 : 64;
        int64_t *p = realloc(b->data, (size_t)cap * sizeof(int64_t));
        if (!p) return -1;
        b->data = p;
        b->cap = cap;
    }
    b->data[b->len++] = v;
    return 0;
}

static int cmp_i64_desc(const void *a, const void *b) {
    int64_t x = *(const int64_t *)a, y = *(const int64_t *)b;
    return (x < y) - (x > y);
}

/* N50: accumulate contig lengths from longest; first length where the
 * cumulative sum reaches half the assembly (matches _compute_n50 /
 * reference golden 8289). Integer-exact: csum >= total/2 <=>
 * 2*csum >= total. */
static int64_t compute_n50(const int64_t *lengths, int64_t n) {
    if (n == 0) return 0;
    int64_t *s = malloc((size_t)n * sizeof(int64_t));
    if (!s) return 0;
    memcpy(s, lengths, (size_t)n * sizeof(int64_t));
    qsort(s, (size_t)n, sizeof(int64_t), cmp_i64_desc);
    int64_t total = 0;
    for (int64_t i = 0; i < n; i++) total += s[i];
    int64_t csum = 0, n50 = s[n - 1];
    for (int64_t i = 0; i < n; i++) {
        csum += s[i];
        if (2 * csum >= total) { n50 = s[i]; break; }
    }
    free(s);
    return n50;
}

void galah_free_genome(GalahGenome *g) {
    if (!g) return;
    free(g->codes);
    free(g->offsets);
    g->codes = NULL;
    g->offsets = NULL;
}

/* Slurp the whole (decompressed) file; gzread is transparent for
 * uncompressed input. Genomes are a few MB to a few hundred MB, so
 * whole-file buffering is the right trade for parse speed. */
static int read_all(const char *path, uint8_t **out, int64_t *out_len) {
    gzFile fh = gzopen(path, "rb");
    if (!fh) return GALAH_ERR_OPEN;
    gzbuffer(fh, 1 << 20);
    int64_t cap = 1 << 22, len = 0;
    uint8_t *buf = malloc((size_t)cap);
    if (!buf) { gzclose(fh); return GALAH_ERR_OOM; }
    for (;;) {
        if (len == cap) {
            cap <<= 1;
            uint8_t *p = realloc(buf, (size_t)cap);
            if (!p) { free(buf); gzclose(fh); return GALAH_ERR_OOM; }
            buf = p;
        }
        int64_t want = cap - len;
        if (want > (1 << 30)) want = 1 << 30; /* gzread len is 32-bit */
        int n = gzread(fh, buf + len, (unsigned)want);
        if (n < 0) { free(buf); gzclose(fh); return GALAH_ERR_READ; }
        if (n == 0) break;
        len += n;
    }
    gzclose(fh);
    *out = buf;
    *out_len = len;
    return GALAH_OK;
}

int galah_read_fasta(const char *path, GalahGenome *out) {
    memset(out, 0, sizeof(*out));
    uint8_t *data = NULL;
    int64_t size = 0;
    int rc = read_all(path, &data, &size);
    if (rc != GALAH_OK) return rc;

    /* codes can never exceed the raw byte count */
    uint8_t *codes = malloc(size ? (size_t)size : 1);
    if (!codes) { free(data); return GALAH_ERR_OOM; }
    int64_t clen = 0;
    I64Buf lens = {0};
    int64_t contig_start = 0;
    int64_t ambiguous = 0;
    int in_record = 0;

    const uint8_t *p = data, *end = data + size;
    while (p < end) {
        const uint8_t *nl = memchr(p, '\n', (size_t)(end - p));
        const uint8_t *eol = nl ? nl : end;
        const uint8_t *s = p, *e = eol;
        while (s < e && is_ws(*s)) s++;
        while (e > s && is_ws(e[-1])) e--;
        if (s < e) {
            if (*s == '>') {
                if (in_record) {
                    if (i64_push(&lens, clen - contig_start) != 0) {
                        rc = GALAH_ERR_OOM; goto done;
                    }
                }
                in_record = 1;
                contig_start = clen;
            } else if (in_record) {
                for (const uint8_t *q = s; q < e; q++) {
                    uint8_t c = CODE_LUT[*q];
                    codes[clen++] = c;
                    ambiguous += (c == 255);
                }
            }
        }
        p = eol + 1;
    }
    if (!in_record) { rc = GALAH_ERR_NO_RECORDS; goto done; }
    if (i64_push(&lens, clen - contig_start) != 0) {
        rc = GALAH_ERR_OOM; goto done;
    }

    out->offsets = malloc((size_t)(lens.len + 1) * sizeof(int64_t));
    if (!out->offsets) { rc = GALAH_ERR_OOM; goto done; }
    out->offsets[0] = 0;
    for (int64_t i = 0; i < lens.len; i++)
        out->offsets[i + 1] = out->offsets[i] + lens.data[i];
    out->n_contigs = lens.len;
    out->codes = codes;
    out->total_len = clen;
    codes = NULL; /* ownership moved to out */
    out->num_ambiguous = ambiguous;
    out->n50 = compute_n50(lens.data, lens.len);

done:
    free(data);
    free(codes);
    free(lens.data);
    if (rc != GALAH_OK) galah_free_genome(out);
    return rc;
}
