"""Exact-ANI cluster backends + skani-style preclusterer on the
fragment-containment kernel (ops/fragment_ani.py).

Three backends, mirroring the reference's surface:

  * FastANIEquivalentClusterer — the reference's fastANI wrapper semantics
    (reference: src/fastani.rs:26-73): bidirectional, fragment-fraction
    gate in either direction, None when gated out, max-ANI result,
    fragment length configurable (--fragment-length).
  * SkaniEquivalentClusterer — the reference's skani wrapper semantics
    (reference: src/skani.rs:108-129): always returns a value (a gated
    pair yields ANI 0.0 rather than None), min-aligned-fraction honored
    internally.
  * SkaniPreclusterer — all-pairs screening by marker-sketch containment
    on device, then exact fragment ANI on screened pairs only
    (reference: src/skani.rs:33-106).

All sketches/profiles are computed once per genome and cached in an LRU
ProfileStore (the reference re-sketches from disk on every pair,
reference: src/skani.rs:171-172 — deliberately not replicated).

K-mer size is 15 for both cluster backends: calibrated so the abisko4
golden clusterings (reference: src/clusterer.rs:481-663) reproduce with
margin; see tests/test_golden_clusters.py.
"""

from __future__ import annotations

import collections
import contextlib
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.config import Defaults
from galah_tpu.io import diskcache
from galah_tpu.io.fasta import read_genome
from galah_tpu.ops import fragment_ani
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.fragment_ani import GenomeProfile
from galah_tpu.ops.pairwise import screen_pairs
from galah_tpu.utils import timing

logger = logging.getLogger(__name__)

ANI_KMER = 15


class ProfileStore:
    """LRU cache: genome path -> GenomeProfile (profile once, reuse).

    With an on-disk cache (io/diskcache.py), the expensive profile
    arrays (positional hashes, distinct-set, markers) also persist
    across runs keyed by file identity + (k, fraglen).
    """

    def __init__(self, k: int = ANI_KMER,
                 fraglen: int = Defaults.FRAGMENT_LENGTH,
                 maxsize: int = 128,
                 cache: Optional[diskcache.CacheDir] = None,
                 subsample_c: int = Defaults.ANI_SUBSAMPLE,
                 threads: int = 1,
                 hash_algorithm: str = "murmur3") -> None:
        self.k = k
        self.fraglen = fraglen
        self.subsample_c = int(subsample_c)
        self.hash_algorithm = hash_algorithm
        self.threads = max(int(threads), 1)
        self.maxsize = maxsize
        self.disk = cache or diskcache.get_cache()
        self._cache: "collections.OrderedDict[str, GenomeProfile]" = (
            collections.OrderedDict())

    def _params(self) -> dict:
        p = {"k": self.k, "fraglen": self.fraglen}
        # only key the cache on non-default knobs, so default-path
        # entries from before each flag existed stay valid
        if self.subsample_c != 1:
            p["subsample_c"] = self.subsample_c
        if self.hash_algorithm != "murmur3":
            p["hash_algorithm"] = self.hash_algorithm
        return p

    @contextlib.contextmanager
    def reserve(self, n: int):
        """Temporarily grow the LRU to a batch's working set (a batch
        referencing more genomes than maxsize would otherwise rebuild
        profiles mid-batch), restoring the bound — and evicting the
        overflow — when the batch is done, so long-running processes
        don't keep every profile of a 50k-genome run resident."""
        old = self.maxsize
        self.maxsize = max(self.maxsize, n)
        try:
            yield
        finally:
            self.maxsize = old
            while len(self._cache) > self.maxsize:
                self._cache.popitem(last=False)

    def _insert(self, path: str, prof: GenomeProfile) -> None:
        self._cache[path] = prof
        if len(self._cache) > self.maxsize:
            self._cache.popitem(last=False)

    def _load_disk(self, path: str) -> Optional[GenomeProfile]:
        entry = self.disk.load(path, "profile", self._params())
        if entry is None:
            return None
        return GenomeProfile(
            path=path, k=self.k, fraglen=self.fraglen,
            flat_hashes=entry["flat_hashes"],
            ref_set=entry["ref_set"], markers=entry["markers"],
            subsample_c=self.subsample_c)

    def _store_disk(self, path: str, prof: GenomeProfile) -> None:
        from galah_tpu.obs import metrics as obs_metrics

        obs_metrics.counter(
            "sketch.profiles_computed",
            help="Fragment-ANI genome profiles computed (not served "
                 "from any cache)", unit="genomes").inc()
        self.disk.store(path, "profile", self._params(), {
            "flat_hashes": prof.flat_hashes,
            "ref_set": prof.ref_set,
            "markers": prof.markers,
        })

    def get(self, path: str) -> GenomeProfile:
        prof = self._cache.get(path)
        if prof is not None:
            self._cache.move_to_end(path)
            return prof
        prof = self._load_disk(path)
        if prof is None:
            prof = fragment_ani.build_profile(
                read_genome(path), k=self.k, fraglen=self.fraglen,
                subsample_c=self.subsample_c,
                hash_algorithm=self.hash_algorithm)
            self._store_disk(path, prof)
        self._insert(path, prof)
        return prof

    def get_many(self, paths: Sequence[str]) -> "list[GenomeProfile]":
        """Profiles for many paths; cache misses are ingested with the
        prefetch pool and hashed in grouped batch dispatches
        (ops/fragment_ani.build_profiles_batch) instead of one dispatch
        per genome."""
        from galah_tpu.io.prefetch import iter_prefetched, process_stream

        by_path: "dict[str, GenomeProfile]" = {}
        misses = []
        for p in dict.fromkeys(paths):
            prof = self._cache.get(p)
            if prof is not None:
                self._cache.move_to_end(p)
                by_path[p] = prof
                continue
            prof = self._load_disk(p)
            if prof is not None:
                self._insert(p, prof)
                by_path[p] = prof
            else:
                misses.append(p)
        from galah_tpu.ops.hashing import device_transfer_bound

        for p, prof in process_stream(
                iter_prefetched(misses, read_genome,
                                depth=max(2, self.threads)),
                lambda g: g.codes.shape[0],
                fragment_ani.PROFILE_BATCH_BUDGET,
                lambda buf: fragment_ani.build_profiles_batch(
                    [g for _, g in buf], k=self.k, fraglen=self.fraglen,
                    subsample_c=self.subsample_c,
                    hash_algorithm=self.hash_algorithm),
                lambda _path, g: fragment_ani.build_profile(
                    g, k=self.k, fraglen=self.fraglen,
                    subsample_c=self.subsample_c,
                    hash_algorithm=self.hash_algorithm),
                batched=device_transfer_bound(),
                workers=self.threads):
            self._store_disk(p, prof)
            self._insert(p, prof)
            by_path[p] = prof
        return [by_path[p] for p in paths]


class _FragmentANIMixin:
    """Shared bidirectional-ANI plumbing for the two cluster backends."""

    store: ProfileStore
    min_aligned_fraction: float

    def _pair_result(
        self, a: str, b: str
    ) -> Tuple[Optional[float], fragment_ani.DirectedANI,
               fragment_ani.DirectedANI]:
        pa = self.store.get(a)
        pb = self.store.get(b)
        return fragment_ani.bidirectional_ani(
            pa, pb, min_aligned_frac=self.min_aligned_fraction)

    def _batch_results(
        self, pairs: Sequence[tuple[str, str]]
    ) -> List[Optional[float]]:
        """ANI for every path pair via coalesced device dispatches."""
        with timing.stage("profile-genomes"):
            # each unique genome is profiled at most once per batch: the
            # LRU is grown to the batch's working set and paths are
            # fetched deduplicated before pair assembly
            unique = list(dict.fromkeys(p for pair in pairs for p in pair))
            with self.store.reserve(len(unique)):
                by_path = dict(zip(unique, self.store.get_many(unique)))
            profs = [(by_path[a], by_path[b]) for a, b in pairs]
        with timing.stage("fragment-ani"):
            return _guarded_ani_values(
                profs, self.min_aligned_fraction, self.store.threads)


def _guarded_ani_values(profs, min_aligned_frac: float,
                        threads: int) -> List[Optional[float]]:
    """Guarded batched bidirectional-ANI dispatch, shared by the
    cluster backends and the skani preclusterer. The per-pair fallback
    trades the coalesced batch for N tiny dispatches, so a persistently
    failing batched kernel degrades throughput, not the run (stage
    report: demoted[dispatch.fragment-ani]).

    Two fallback layers compose here: INSIDE the batch call,
    fragment_ani resolves the membership strategy
    (GALAH_TPU_FRAGMENT_STRATEGY: blocked Mosaic kernel / vmapped XLA
    / C merge, see docs/fragment_kernel.md) and an AUTO-chosen Pallas
    path already demotes to its XLA twin on Mosaic failure
    (fragment-pallas-demoted counter); this OUTER guard catches
    whole-batch failures of whatever strategy won and retries
    per-pair."""
    from galah_tpu.resilience import dispatch as rdispatch

    return rdispatch.run(
        "dispatch.fragment-ani",
        lambda: fragment_ani.bidirectional_ani_values(
            profs, min_aligned_frac=min_aligned_frac, threads=threads),
        fallback=lambda: [
            fragment_ani.bidirectional_ani_values(
                [pp], min_aligned_frac=min_aligned_frac,
                threads=threads)[0]
            for pp in profs],
        validate=rdispatch.expect_ani_values(len(profs)))


def _device_pair_block() -> int:
    """Backend batch-size hint (ClusterBackend.pair_block_multiple):
    on a TPU backend the device evaluates pairs in P-pair blocks
    (ops/pallas_pairlist.py), so the engine's speculative batches are
    sized to fill them; host backends report 1 (no blocking)."""
    from galah_tpu.ops.sparse_device import pair_block_quantum

    return pair_block_quantum()


class FastANIEquivalentClusterer(ClusterBackend, _FragmentANIMixin):
    def __init__(self, threshold: float, min_aligned_fraction: float,
                 fraglen: int = Defaults.FRAGMENT_LENGTH,
                 store: Optional[ProfileStore] = None) -> None:
        self._threshold = float(threshold)
        self.min_aligned_fraction = float(min_aligned_fraction)
        self.store = store or ProfileStore(k=ANI_KMER, fraglen=fraglen)
        if self.store.fraglen != fraglen:
            raise ValueError(
                f"fragment length mismatch: backend wants {fraglen}, "
                f"shared ProfileStore was built with {self.store.fraglen}")

    def method_name(self) -> str:
        return "fastani"

    @property
    def pair_block_multiple(self) -> int:
        return _device_pair_block()

    @property
    def ani_threshold(self) -> float:
        return self._threshold

    def calculate_ani_batch(
        self, pairs: Sequence[tuple[str, str]]
    ) -> List[Optional[float]]:
        return self._batch_results(pairs)


class SkaniEquivalentClusterer(ClusterBackend, _FragmentANIMixin):
    def __init__(self, threshold: float, min_aligned_fraction: float,
                 store: Optional[ProfileStore] = None) -> None:
        self._threshold = float(threshold)
        self.min_aligned_fraction = float(min_aligned_fraction)
        self.store = store or ProfileStore(k=ANI_KMER)

    def method_name(self) -> str:
        return "skani"

    @property
    def pair_block_multiple(self) -> int:
        return _device_pair_block()

    @property
    def ani_threshold(self) -> float:
        return self._threshold

    def calculate_ani_batch(
        self, pairs: Sequence[tuple[str, str]]
    ) -> List[Optional[float]]:
        # A gated-out pair is ANI 0.0, not None — the reference's skani
        # wrapper always returns Some (reference: src/skani.rs:126-129).
        return [ani if ani is not None else 0.0
                for ani in self._batch_results(pairs)]


class SkaniPreclusterer(PreclusterBackend):
    """Marker screening on device + exact fragment ANI on screened pairs."""

    SCREEN_IDENTITY = 0.80  # reference: src/skani.rs:59 screen_refs(0.80,..)

    def __init__(self, threshold: float, min_aligned_fraction: float,
                 store: Optional[ProfileStore] = None) -> None:
        self.threshold = float(threshold)
        self.min_aligned_fraction = float(min_aligned_fraction)
        self.store = store or ProfileStore(k=ANI_KMER)

    def method_name(self) -> str:
        return "skani"

    def _marker_matrix(self, profiles, n: int, width: int = 0):
        """Pad per-genome marker sketches to a common-width matrix
        (`width` forces the column count; 0 = fit to these profiles —
        the multihost path forces the allgather-agreed global width so
        both paths share this one padding loop)."""
        m = width or -(-max(max(
            (p.markers.shape[0] for p in profiles), default=1), 1)
            // 64) * 64
        mat = np.full((n, m), np.uint64(SENTINEL), dtype=np.uint64)
        counts = np.zeros(n, dtype=np.int64)
        for i, p in enumerate(profiles):
            cnt = min(p.markers.shape[0], m)
            mat[i, :cnt] = p.markers[:cnt]
            counts[i] = cnt
        return mat, counts

    def _marker_matrix_multihost(self, genome_paths: Sequence[str]):
        """Per-host profiling for the marker screen: each host profiles
        only its strided shard and exchanges the (small) marker rows —
        the global width is agreed with one scalar allgather first.
        Returns (mat, counts, warm) where `warm` maps this host's
        global genome index -> its built profile, handed to phase B so
        the shard's profiles survive regardless of LRU capacity or
        disk-cache availability."""
        from jax.experimental import multihost_utils

        from galah_tpu.parallel import distributed

        n = len(genome_paths)
        mine_idx = distributed.host_shard(list(range(n)))
        with timing.stage("profile-genomes"):
            with self.store.reserve(max(len(mine_idx), 1)):
                mine = self.store.get_many(
                    [genome_paths[i] for i in mine_idx])
        local_max = max(
            max((p.markers.shape[0] for p in mine), default=1), 1)
        maxes = np.asarray(multihost_utils.process_allgather(
            np.array([local_max], dtype=np.int64), tiled=False))
        m = -(-int(maxes.max()) // 64) * 64

        local_mat, local_counts = self._marker_matrix(
            mine, len(mine), width=m)
        local = np.concatenate(
            [local_mat, local_counts.astype(np.uint64)[:, None]], axis=1)
        full = distributed.allgather_host_rows(
            n, local, fill=np.uint64(SENTINEL))
        warm = dict(zip(mine_idx, mine))
        return (np.ascontiguousarray(full[:, :m]),
                full[:, m].astype(np.int64), warm)

    def _exact_ani_multihost(self, genome_paths, pairs, warm):
        """Exact ANI over the screened pairs, sharded by host: each
        host owns the pairs whose SECOND endpoint is in its phase-A
        genome shard (owner j % P composes with host_shard's stride),
        reuses `warm` profiles for those and profiles only cross-host
        first endpoints (the shared disk cache makes them warm too
        when enabled), then the per-pair ANIs are exchanged through
        the shared protocol — which also propagates a host failure to
        every peer instead of stranding them in the collective. Every
        host ends with the identical result vector."""
        from galah_tpu.parallel import distributed

        def compute_mine(idxs):
            my_pairs = [pairs[k] for k in idxs]
            endpoints = list(dict.fromkeys(
                g for pair in my_pairs for g in pair))
            missing = [g for g in endpoints if g not in warm]
            with timing.stage("profile-genomes"):
                with self.store.reserve(max(len(missing), 1)):
                    prof = dict(zip(missing, self.store.get_many(
                        [genome_paths[g] for g in missing])))
            prof.update(
                (g, warm[g]) for g in endpoints if g in warm)
            return _guarded_ani_values(
                [(prof[i], prof[j]) for i, j in my_pairs],
                self.min_aligned_fraction, self.store.threads)

        return distributed.sharded_optional_floats(
            len(pairs), compute_mine, owner=lambda k: pairs[k][1])

    def distances(self, genome_paths: Sequence[str]) -> PairDistanceCache:
        from galah_tpu.parallel import distributed

        n = len(genome_paths)
        n_proc = distributed.process_count()
        logger.info("Profiling %d genomes for skani-style preclustering ..",
                    n)
        warm = {}
        if n_proc > 1:
            mat, counts, warm = self._marker_matrix_multihost(
                genome_paths)
            profiles = None
        else:
            with timing.stage("profile-genomes"):
                with self.store.reserve(n):
                    profiles = self.store.get_many(genome_paths)
            mat, counts = self._marker_matrix(profiles, n)

        # Blocked screening: ONE device dispatch per row block (the same
        # extraction pattern as threshold_pairs — dispatch count scales
        # O(N / row_tile), not O((N / tile)^2); auto-shards the columns
        # over a multi-device mesh). Above the sparse crossover the
        # host collision screen runs instead (exact, any backend).
        logger.info("Screening all pairs by marker containment ..")
        c_floor = self.SCREEN_IDENTITY ** self.store.k
        with timing.stage("marker-screen"):
            pairs = screen_pairs(mat, counts, c_floor)
        logger.info("%d pairs passed screening; computing exact ANI ..",
                    len(pairs))

        cache = PairDistanceCache()
        if n_proc > 1:
            if pairs:
                anis = self._exact_ani_multihost(genome_paths, pairs,
                                                 warm)
                for (i, j), ani in zip(pairs, anis):
                    if ani is not None and ani >= self.threshold:
                        cache.insert((i, j), float(ani))
        else:
            anis = _guarded_ani_values(
                [(profiles[i], profiles[j]) for i, j in pairs],
                self.min_aligned_fraction, self.store.threads)
            for (i, j), ani in zip(pairs, anis):
                if ani is not None and ani >= self.threshold:
                    cache.insert((i, j), ani)
        logger.info("Found %d pairs passing precluster threshold %.4f",
                    len(cache), self.threshold)
        return cache

    def distances_subset(self, genome_paths: Sequence[str],
                         keep) -> PairDistanceCache:
        """Single-host distances() restricted to screened pairs with
        ``keep(i, j)`` true. The fleet merge computes only CROSS-shard
        pairs through this: same profile, screen and exact-ANI code
        path as the full run, only the pair list is filtered, so the
        kept values are bit-identical to a full distances() run's
        (the merge-determinism argument in docs/resilience.md)."""
        n = len(genome_paths)
        logger.info("Profiling %d genomes for cross-shard merge ..", n)
        with timing.stage("profile-genomes"):
            with self.store.reserve(n):
                profiles = self.store.get_many(genome_paths)
        mat, counts = self._marker_matrix(profiles, n)
        c_floor = self.SCREEN_IDENTITY ** self.store.k
        with timing.stage("marker-screen"):
            pairs = [p for p in screen_pairs(mat, counts, c_floor)
                     if keep(p[0], p[1])]
        logger.info("%d cross-shard pairs passed screening; "
                    "computing exact ANI ..", len(pairs))
        cache = PairDistanceCache()
        anis = _guarded_ani_values(
            [(profiles[i], profiles[j]) for i, j in pairs],
            self.min_aligned_fraction, self.store.threads)
        for (i, j), ani in zip(pairs, anis):
            if ani is not None and ani >= self.threshold:
                cache.insert((i, j), ani)
        return cache
