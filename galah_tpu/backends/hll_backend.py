"""HyperLogLog precluster backend — the dashing-equivalent.

The reference spawns the dashing C++ binary and parses its full N x N
distance matrix from stdout (reference: src/dashing.rs:11-100). Here the
HLL sketches are built and compared on device (ops/hll.py); only the
sparse thresholded pairs reach the host cache.
"""

from __future__ import annotations

import logging
from typing import Sequence

from galah_tpu.backends.base import PreclusterBackend
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.config import Defaults
from galah_tpu.io import diskcache
from galah_tpu.ops import hll
from galah_tpu.utils import timing

logger = logging.getLogger(__name__)


class HLLPreclusterer(PreclusterBackend):
    """All-pairs HLL Mash-ANI pass producing the sparse pair cache."""

    def __init__(self, min_ani: float, p: int = hll.DEFAULT_P,
                 k: int = Defaults.MINHASH_KMER,
                 seed: int = Defaults.MINHASH_SEED,
                 hash_algo: str = Defaults.HASH_ALGO,
                 cache: "diskcache.CacheDir | None" = None,
                 threads: int = 1) -> None:
        self.min_ani = float(min_ani)
        self.p = int(p)
        self.k = int(k)
        self.seed = int(seed)
        self.algo = hash_algo
        self.threads = max(int(threads), 1)
        self.cache = cache or diskcache.get_cache()

    def method_name(self) -> str:
        return "dashing"

    def _sketch_paths(self, paths: Sequence[str]) -> dict:
        """path -> (2^p,) register row for (deduped) paths: cache probe
        + prefetch + batched device sketching; the consumer loop is the
        single writer into the disk cache."""
        from galah_tpu.io.fasta import read_genome
        from galah_tpu.io.prefetch import (
            probe_and_prefetch,
            process_stream,
        )
        from galah_tpu.ops.hashing import (
            BATCH_BUDGET,
            device_transfer_bound,
        )

        params = {"p": self.p, "k": self.k, "seed": self.seed,
                  "algo": self.algo}

        def probe(path):
            entry = self.cache.load(path, "hll", params)
            return entry["regs"] if entry is not None else None

        from galah_tpu.resilience import dispatch as rdispatch

        def sketch_batch(buf):
            # Guarded device dispatch: retry transients, demote to the
            # per-genome path on persistent failure (stage report:
            # demoted[dispatch.sketch-hll]).
            return rdispatch.run(
                "dispatch.sketch-hll",
                lambda: hll.hll_sketch_genomes_batch(
                    [g for _, g in buf], p=self.p, k=self.k,
                    seed=self.seed, algo=self.algo),
                fallback=lambda: [hll.hll_sketch_genome(
                    g, p=self.p, k=self.k, seed=self.seed,
                    algo=self.algo) for _p, g in buf],
                validate=rdispatch.expect_len(len(buf)))

        by_path, miss_iter = probe_and_prefetch(
            paths, probe, read_genome, depth=max(2, self.threads))
        for path, row in process_stream(
                miss_iter, lambda g: g.codes.shape[0], BATCH_BUDGET,
                sketch_batch,
                lambda _path, g: hll.hll_sketch_genome(
                    g, p=self.p, k=self.k, seed=self.seed,
                    algo=self.algo),
                batched=device_transfer_bound(),
                workers=self.threads):
            by_path[path] = row
            from galah_tpu.obs import metrics as obs_metrics

            obs_metrics.counter(
                "sketch.hll_computed",
                help="HLL register rows computed (not served from any "
                     "cache)", unit="genomes").inc()
            self.cache.store(path, "hll", params, {"regs": row})
        return by_path

    def distances(self, genome_paths: Sequence[str]) -> PairDistanceCache:
        import numpy as np

        from galah_tpu.parallel import distributed

        n = len(genome_paths)
        logger.info("Sketching HLL registers of %d genomes on device ..", n)
        regs = np.zeros((n, 1 << self.p), dtype=np.uint8)
        index: "dict[str, list[int]]" = {}
        for i, path in enumerate(genome_paths):
            index.setdefault(path, []).append(i)
        with timing.stage("sketch-hll"):
            unique = list(index)
            if distributed.process_count() > 1:
                # Per-host ingestion, same shape as the MinHash
                # backend: sketch only this host's strided shard,
                # exchange the (tiny) register rows via the shared
                # protocol, reassemble identically on every host.
                mine = distributed.host_shard(unique)
                by_path = self._sketch_paths(mine)
                local = (np.stack([by_path[p] for p in mine])
                         if mine else
                         np.zeros((0, 1 << self.p), dtype=np.uint8))
                full = distributed.allgather_host_rows(
                    len(unique), local, fill=np.uint8(0))
                for row_i, path in enumerate(unique):
                    regs[index[path]] = full[row_i]
            else:
                by_path = self._sketch_paths(genome_paths)
                for path, row in by_path.items():
                    regs[index[path]] = row

        logger.info("Computing tiled all-pairs HLL ANI ..")
        with timing.stage("pairwise-hll"):
            pairs = hll.hll_threshold_pairs(regs, k=self.k,
                                            min_ani=self.min_ani)
        cache = PairDistanceCache()
        for (i, j), ani in pairs.items():
            cache.insert((i, j), ani)
        logger.info("Found %d pairs passing precluster threshold %.4f",
                    len(cache), self.min_ani)
        return cache
