"""Backend abstractions for the two-stage distance pipeline.

TPU-native re-design of the reference's two traits (reference:
src/lib.rs:23-37):

  * PreclusterDistanceFinder.distances(&[&str]) -> sparse pair cache
  * ClusterDistanceFinder.calculate_ani(f1, f2) -> Option<f32>

The key difference: the cluster-stage interface is **batched**. The
reference computes one genome pair per thread/subprocess call; here the
engine hands a whole candidate list to the backend at once so it can be
evaluated as a single device computation (and sketches are computed once
per genome and cached — fixing the reference's per-pair re-sketching,
reference: src/skani.rs:171-172).

ANI values everywhere are fractions in [0, 1] (the reference mixes
percent and fraction units across backends; this framework normalizes).
"""

from __future__ import annotations

import abc
from typing import TYPE_CHECKING, List, Optional, Sequence

if TYPE_CHECKING:
    from galah_tpu.cluster.cache import PairDistanceCache


class PreclusterBackend(abc.ABC):
    """Cheap sketch-based all-pairs pass producing the sparse pair cache."""

    @abc.abstractmethod
    def method_name(self) -> str: ...

    @abc.abstractmethod
    def distances(self, genome_paths: Sequence[str]) -> "PairDistanceCache":
        """ANI fraction for every i<j pair passing the precluster
        threshold."""


class ClusterBackend(abc.ABC):
    """Exact-ANI backend driving the greedy clustering decisions."""

    # Batch-size hint for callers assembling speculative pair batches
    # (cluster/engine.py): the backend's device evaluation processes
    # pairs in blocks of this size, so batches that are a multiple of
    # it run with no padded block slots. 1 = no blocking (host paths).
    pair_block_multiple: int = 1

    @abc.abstractmethod
    def method_name(self) -> str: ...

    @property
    @abc.abstractmethod
    def ani_threshold(self) -> float:
        """Final clustering ANI threshold, as a fraction."""

    @abc.abstractmethod
    def calculate_ani_batch(
        self, pairs: Sequence[tuple[str, str]]
    ) -> List[Optional[float]]:
        """ANI for each (path_a, path_b); None = failed aligned-fraction
        gate. The batch interface lets backends keep all inputs
        device-resident and (where shapes allow) coalesce dispatches;
        current fragment backends dispatch per direction with cached
        device arrays."""

    def calculate_ani(self, f1: str, f2: str) -> Optional[float]:
        return self.calculate_ani_batch([(f1, f2)])[0]

    def calculate_ani_batch_array(self, pairs: Sequence[tuple[str, str]]):
        """The batch result as a float64 array, NaN where the backend
        returned None (failed aligned-fraction gate).

        This is the device-consumer form of the batch API: the engine's
        round-based greedy selection (ops/greedy_select.py) feeds the
        values straight into jitted decision passes, where NaN already
        IS the no-edge encoding (an IEEE ``NaN >= thr`` compares False
        exactly like the host's ``ani is not None`` guard), so no
        None-boxing round trip is needed. Backends whose results are
        already device-resident may override to skip the Python list
        entirely; the default adapts :meth:`calculate_ani_batch`.
        """
        import numpy as np

        anis = self.calculate_ani_batch(pairs)
        return np.array(
            [np.nan if a is None else float(a) for a in anis],
            dtype=np.float64)
