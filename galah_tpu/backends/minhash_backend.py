"""MinHash precluster backend (finch-equivalent) on the device pipeline.

Semantics of the reference's FinchPreclusterer (reference:
src/finch.rs:4-73): sketch every genome (bottom-k 1000, k=21, seed 0),
all-pairs Mash ANI, keep pairs at or above the threshold. The all-pairs
loop runs as the tiled device kernel of ops/pairwise.py instead of a host
O(N^2) loop.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence

from galah_tpu.backends.base import PreclusterBackend
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.config import Defaults
from galah_tpu.io.fasta import read_genome
from galah_tpu.ops.minhash import sketch_genome_device, sketch_matrix
from galah_tpu.ops.minhash_np import MinHashSketch
from galah_tpu.ops.pairwise import threshold_pairs

logger = logging.getLogger(__name__)


class SketchStore:
    """Per-run cache: genome path -> MinHash sketch (sketch once, reuse)."""

    def __init__(self, sketch_size: int, k: int, seed: int = 0) -> None:
        self.sketch_size = sketch_size
        self.k = k
        self.seed = seed
        self._sketches: Dict[str, MinHashSketch] = {}

    def get(self, path: str) -> MinHashSketch:
        s = self._sketches.get(path)
        if s is None:
            s = sketch_genome_device(
                read_genome(path), sketch_size=self.sketch_size,
                k=self.k, seed=self.seed)
            self._sketches[path] = s
        return s


class MinHashPreclusterer(PreclusterBackend):
    def __init__(
        self,
        min_ani: float,
        sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
        k: int = Defaults.MINHASH_KMER,
        store: Optional[SketchStore] = None,
    ) -> None:
        self.min_ani = float(min_ani)
        self.sketch_size = sketch_size
        self.k = k
        self.store = store or SketchStore(sketch_size, k)

    def method_name(self) -> str:
        return "finch"

    def distances(self, genome_paths: Sequence[str]) -> PairDistanceCache:
        logger.info(
            "Sketching MinHash representations of %d genomes on device ..",
            len(genome_paths))
        sketches = [self.store.get(p) for p in genome_paths]
        mat = sketch_matrix(sketches, sketch_size=self.sketch_size)
        logger.info("Computing tiled all-pairs Mash ANI ..")
        pairs = threshold_pairs(
            mat, k=self.k, min_ani=self.min_ani,
            sketch_size=self.sketch_size)
        cache = PairDistanceCache()
        for (i, j), ani in pairs.items():
            cache.insert((i, j), ani)
        logger.info("Found %d pairs passing precluster threshold %.4f",
                    len(cache), self.min_ani)
        return cache
