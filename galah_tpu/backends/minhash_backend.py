"""MinHash precluster backend (finch-equivalent) on the device pipeline.

Semantics of the reference's FinchPreclusterer (reference:
src/finch.rs:4-73): sketch every genome (bottom-k 1000, k=21, seed 0),
all-pairs Mash ANI, keep pairs at or above the threshold. The all-pairs
loop runs as the tiled device kernel of ops/pairwise.py instead of a host
O(N^2) loop.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

from galah_tpu.backends.base import PreclusterBackend
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.config import Defaults
from galah_tpu.io import diskcache
from galah_tpu.io.diskcache import CacheDir
from galah_tpu.io.fasta import read_genome
from galah_tpu.ops.minhash import (
    sketch_genome_device,
    sketch_genomes_device_batch,
    sketch_matrix,
)
from galah_tpu.ops.minhash_np import MinHashSketch
from galah_tpu.ops.pairwise import threshold_pairs
from galah_tpu.utils import timing

logger = logging.getLogger(__name__)


class SketchStore:
    """Per-run cache: genome path -> MinHash sketch (sketch once, reuse).

    With a `cache` (io/diskcache.py), sketches also persist across runs,
    keyed by file identity + (sketch_size, k, seed).

    With a `pagestore` attached (io/pagestore.py, docs/memory.md),
    retained sketches live as rows of the mmap-backed page store
    instead of the `_sketches` dict: `get_cached` hands back
    zero-copy views and peak RSS is bounded by the pagestore's LRU
    budget instead of growing with the corpus. Both retention modes
    serve bit-identical sketches.
    """

    def __init__(self, sketch_size: int, k: int, seed: int = 0,
                 cache: Optional["CacheDir"] = None,
                 algo: str = Defaults.HASH_ALGO) -> None:
        self.sketch_size = sketch_size
        self.k = k
        self.seed = seed
        self.algo = algo
        self.cache = cache or diskcache.get_cache()
        self._sketches: Dict[str, MinHashSketch] = {}
        self.pagestore = None  # io/pagestore.SketchPageStore when paged

    def _params(self) -> dict:
        return {"sketch_size": self.sketch_size, "k": self.k,
                "seed": self.seed, "algo": self.algo}

    def attach_pagestore(self, pagestore) -> None:
        """Route sketch retention through a paged store: the dict's
        current residents spill in, later inserts append directly."""
        for path, s in self._sketches.items():
            pagestore.append(path, s.hashes)
        pagestore.flush()
        self._sketches.clear()
        self.pagestore = pagestore

    def _retain(self, path: str, s: MinHashSketch) -> MinHashSketch:
        if self.pagestore is not None:
            self.pagestore.append(path, s.hashes)
            return s
        self._sketches[path] = s
        return s

    def get_cached(self, path: str) -> Optional[MinHashSketch]:
        """Sketch from memory, the page store, or the disk cache only
        (no FASTA read)."""
        s = self._sketches.get(path)
        if s is not None:
            return s
        if self.pagestore is not None:
            hashes = self.pagestore.get(path)
            if hashes is not None:
                return MinHashSketch(hashes=hashes,
                                     sketch_size=self.sketch_size,
                                     kmer=self.k)
        entry = self.cache.load(path, "minhash", self._params())
        if entry is None:
            return None
        s = MinHashSketch(hashes=entry["hashes"],
                          sketch_size=self.sketch_size, kmer=self.k)
        return self._retain(path, s)

    def sketch_only(self, genome) -> MinHashSketch:
        """Pure compute: sketch an ingested genome, no state mutation —
        safe to run on process_stream worker threads; the consumer
        inserts via `insert` (mirroring HLLPreclusterer/ProfileStore,
        which mutate caches only on the consumer thread)."""
        return sketch_genome_device(
            genome, sketch_size=self.sketch_size, k=self.k,
            seed=self.seed, algo=self.algo)

    def insert(self, path: str, s: MinHashSketch) -> MinHashSketch:
        """Record a computed sketch in memory and the disk cache."""
        from galah_tpu.obs import metrics as obs_metrics

        obs_metrics.counter(
            "sketch.minhash_computed",
            help="MinHash sketches computed (not served from any "
                 "cache)", unit="genomes").inc()
        self.cache.store(path, "minhash", self._params(),
                         {"hashes": s.hashes})
        return self._retain(path, s)

    def insert_prefiltered(self, path: str,
                           s: MinHashSketch) -> MinHashSketch:
        """Record a sketch the ingest prefilter resolved without the
        full pipeline (ops/prefilter.py): cached and retained like
        `insert`, but NOT counted as computed — bench throughput and
        the report funnel stay honest about work actually done."""
        self.cache.store(path, "minhash", self._params(),
                         {"hashes": s.hashes})
        return self._retain(path, s)

    def put_from_genome(self, path: str, genome) -> MinHashSketch:
        """Sketch an already-ingested genome and cache it."""
        return self.insert(path, self.sketch_only(genome))

    def sketch_batch_only(self, items) -> "List[MinHashSketch]":
        """Pure compute twin of `sketch_only` for [(path, genome)]
        buffers — grouped device dispatches
        (ops/minhash.sketch_genomes_device_batch), bit-identical
        results, no state mutation."""
        return sketch_genomes_device_batch(
            [g for _, g in items], sketch_size=self.sketch_size,
            k=self.k, seed=self.seed, algo=self.algo)

    def put_from_genomes(self, items) -> "List[MinHashSketch]":
        """Batch-sketch [(path, genome)] and cache the results."""
        sketches = self.sketch_batch_only(items)
        for (p, _), s in zip(items, sketches):
            self.insert(p, s)
        return sketches

    def get(self, path: str) -> MinHashSketch:
        s = self.get_cached(path)
        if s is not None:
            return s
        return self.put_from_genome(path, read_genome(path))


class MinHashPreclusterer(PreclusterBackend):
    def __init__(
        self,
        min_ani: float,
        sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
        k: int = Defaults.MINHASH_KMER,
        store: Optional[SketchStore] = None,
        cache: Optional[CacheDir] = None,
        hash_algo: str = Defaults.HASH_ALGO,
        threads: int = 1,
    ) -> None:
        self.min_ani = float(min_ani)
        self.sketch_size = sketch_size
        self.k = k
        self.threads = max(int(threads), 1)
        self.store = store or SketchStore(sketch_size, k, cache=cache,
                                          algo=hash_algo)

    def method_name(self) -> str:
        return "finch"

    def _sketch_paths(self, paths: Sequence[str]) -> dict:
        """path -> sketch for (deduped) paths via the streaming
        ingest->sketch pipeline (ops/sketch_stream.py): bounded-depth
        prefetch ingest, double-buffered staging, and the resolved
        sketch strategy (fused Pallas / chunked XLA / C bottom-k), all
        overlapped. Worker threads only COMPUTE; the stream inserts
        into the store on this (consumer) thread."""
        from galah_tpu.ops.sketch_stream import iter_path_sketches

        return dict(iter_path_sketches(paths, self.store,
                                       threads=self.threads))

    def _sketch_matrix_multihost(self, genome_paths: Sequence[str]):
        """Per-host ingestion: each host reads + sketches only its
        strided shard of the unique genome list (FASTA IO and hashing
        scale linearly with hosts), then the sketch rows are exchanged
        (parallel/distributed.allgather_host_rows) and reassembled into
        the full matrix on every host — identical everywhere, so the
        downstream screen/engine decisions are too. The full matrix is
        K*8 bytes per genome (~8 KB at K=1000): 50k genomes is ~400 MB
        per host, far below the per-genome FASTA cost being split."""
        import numpy as np

        from galah_tpu.ops.constants import SENTINEL
        from galah_tpu.parallel import distributed

        unique = list(dict.fromkeys(genome_paths))
        mine = distributed.host_shard(unique)
        by_path = self._sketch_paths(mine)
        local = sketch_matrix([by_path[p] for p in mine],
                              sketch_size=self.sketch_size) \
            if mine else np.zeros((0, self.sketch_size), np.uint64)
        mat = distributed.allgather_host_rows(
            len(unique), local, fill=np.uint64(SENTINEL))
        index = {path: i for i, path in enumerate(unique)}
        return mat[[index[p] for p in genome_paths]]

    def distances_streamed(self, genome_paths: Sequence[str]):
        """Overlapped ingest->sketch->pair pass as a STREAM: a
        generator yielding `(r1, increment)` per arriving sketch block
        (ops/pairwise.iter_threshold_pairs_streamed) — the pair
        neighborhood of the prefix [0, r1) is complete at each yield,
        which is what lets the overlapped cluster engine start greedy
        rounds and speculative fragment-ANI while late genomes are
        still being ingested and sketched. Engaged only where it is
        bit-identical to the staged path AND the overlap can win:
        single process, unique paths, below the sparse-screen
        crossover (the sparse pair pass needs the full matrix up
        front), and a device sketch strategy (the single-device-CPU C
        path keeps its historical shape). Returns None when not
        engaged."""
        import jax

        from galah_tpu.ops.collision import sparse_screen_min_n
        from galah_tpu.ops.pairwise import iter_threshold_pairs_streamed
        from galah_tpu.ops.sketch_stream import (
            iter_sketch_row_blocks,
            resolve_sketch_strategy,
        )
        from galah_tpu.parallel import distributed

        from galah_tpu.ops.bucketing import bucketing_engaged

        n = len(genome_paths)
        strategy, _ = resolve_sketch_strategy()
        if (distributed.process_count() > 1
                or strategy == "c"
                or n >= sparse_screen_min_n()
                or len(dict.fromkeys(genome_paths)) != n
                # the bucketed pair pass needs every HLL cardinality
                # up front — streaming cannot band a prefix
                or bucketing_engaged(n)):
            return None
        mesh = None
        if jax.device_count() > 1:
            from galah_tpu.parallel.mesh import auto_mesh

            mesh = auto_mesh()
        logger.info(
            "Streaming %d genomes: ingest+sketch overlapped with the "
            "pair pass (strategy %s) ..", n, strategy)

        def gen():
            with timing.stage("sketch-pairwise-streamed"):
                # strategy=None: the stream re-resolves, preserving
                # the explicit-pin vs AUTO failure semantics
                blocks = iter_sketch_row_blocks(
                    genome_paths, self.store, threads=self.threads)
                for r1, inc in iter_threshold_pairs_streamed(
                        blocks, n, k=self.k, min_ani=self.min_ani,
                        sketch_size=self.sketch_size, mesh=mesh):
                    yield r1, inc

        return gen()

    def _streamed_pair_pass(self, genome_paths: Sequence[str]):
        """Drain `distances_streamed` into one pair dict (the
        stage-serial consumer). Returns None when the streamed path is
        not engaged."""
        stream = self.distances_streamed(genome_paths)
        if stream is None:
            return None
        out: dict = {}
        for _r1, inc in stream:
            out.update(inc)
        return out

    def _make_pagestore(self):
        """The run's paged sketch store (docs/memory.md): a fresh
        directory under the disk cache (or TMPDIR when caching is
        off), SENTINEL-filled so gathered rows are bit-identical to
        ops/minhash.sketch_matrix rows."""
        import atexit
        import shutil
        import tempfile

        from galah_tpu.io.pagestore import SketchPageStore
        from galah_tpu.ops.constants import SENTINEL

        base = self.store.cache.path if self.store.cache.enabled else None
        d = tempfile.mkdtemp(prefix="pagestore-", dir=base)
        atexit.register(shutil.rmtree, d, ignore_errors=True)
        return SketchPageStore(d, cols=self.sketch_size, fill=SENTINEL)

    def _paged_sketch_rows(self, genome_paths: Sequence[str]):
        """Stream-sketch into the mmap-backed page store and return
        the duck-typed row view the bucketed band walk gathers from —
        the full (N, K) sketch matrix is never materialized and peak
        RSS is bounded by the pagestore budget plus two bands' pages
        (docs/memory.md)."""
        import numpy as np

        from galah_tpu.io.pagestore import PagedRowView
        from galah_tpu.ops.sketch_stream import iter_path_sketches

        ps = self._make_pagestore()
        self.store.attach_pagestore(ps)
        logger.info(
            "Paged sketch retention engaged: %d genomes, %d MiB "
            "resident budget", len(genome_paths),
            ps.budget_bytes >> 20)
        for _p, _s in iter_path_sketches(genome_paths, self.store,
                                         threads=self.threads):
            pass
        ps.flush()
        rids = np.empty(len(genome_paths), dtype=np.int64)
        for i, p in enumerate(genome_paths):
            rid = ps.rid_for(p)
            if rid is None:
                raise RuntimeError(
                    f"paged sketch retention lost {p!r}")
            rids[i] = rid
        return PagedRowView(ps, rids)

    def _hll_cardinalities_chunked(self, genome_paths: Sequence[str],
                                   chunk: int = 512):
        """`_hll_cardinalities` with bounded residency for the paged
        path: register rows are loaded (mostly from the prefilter's
        pre-warmed cache entries), reduced to their f64 cardinality
        chunk by chunk, and dropped — cardinality is a per-row
        reduction, so the values are bit-identical to the stacked
        pass. Peak extra memory is one chunk of registers (~2 MB at
        p=12, chunk=512) instead of N rows."""
        import jax.numpy as jnp
        import numpy as np

        from galah_tpu.backends.hll_backend import HLLPreclusterer
        from galah_tpu.io.fasta import read_genome
        from galah_tpu.obs import metrics as obs_metrics
        from galah_tpu.ops import hll as hll_ops

        h = HLLPreclusterer(
            min_ani=self.min_ani, k=self.k, seed=self.store.seed,
            hash_algo=self.store.algo, cache=self.store.cache,
            threads=self.threads)
        params = {"p": h.p, "k": h.k, "seed": h.seed, "algo": h.algo}
        unique = list(dict.fromkeys(genome_paths))
        card_by_path: dict = {}
        for lo in range(0, len(unique), chunk):
            paths = unique[lo:lo + chunk]
            rows = []
            for path in paths:
                entry = h.cache.load(path, "hll", params)
                if entry is not None:
                    rows.append(entry["regs"])
                    continue
                row = hll_ops.hll_sketch_genome(
                    read_genome(path), p=h.p, k=h.k, seed=h.seed,
                    algo=h.algo)
                obs_metrics.counter(
                    "sketch.hll_computed",
                    help="HLL register rows computed (not served from "
                         "any cache)", unit="genomes").inc()
                h.cache.store(path, "hll", params, {"regs": row})
                rows.append(row)
            cards = np.asarray(
                hll_ops.hll_cardinality(jnp.asarray(np.stack(rows))),
                dtype=np.float64)
            for path, c in zip(paths, cards):
                card_by_path[path] = c
        return (np.array([card_by_path[p] for p in genome_paths],
                         dtype=np.float64), h.p)

    def _hll_cardinalities(self, genome_paths: Sequence[str]):
        """(n,) f64 HLL cardinality estimates for the bucketed pair
        pass, through the same disk-cache kind ('hll') the dashing
        backend uses — registers are ~4 KB per genome at p=12 and the
        linear sketch pass is amortized against the O(N^2) lattice it
        prunes."""
        import jax.numpy as jnp
        import numpy as np

        from galah_tpu.backends.hll_backend import HLLPreclusterer
        from galah_tpu.ops import hll as hll_ops

        h = HLLPreclusterer(
            min_ani=self.min_ani, k=self.k, seed=self.store.seed,
            hash_algo=self.store.algo, cache=self.store.cache,
            threads=self.threads)
        by_path = h._sketch_paths(list(dict.fromkeys(genome_paths)))
        regs = np.stack([by_path[p] for p in genome_paths])
        return np.asarray(
            hll_ops.hll_cardinality(jnp.asarray(regs)),
            dtype=np.float64), h.p

    def distances(self, genome_paths: Sequence[str]) -> PairDistanceCache:
        pairs = self._streamed_pair_pass(genome_paths)
        if pairs is not None:
            cache = PairDistanceCache()
            for (i, j), ani in pairs.items():
                cache.insert((i, j), ani)
            logger.info(
                "Found %d pairs passing precluster threshold %.4f",
                len(cache), self.min_ani)
            return cache
        logger.info(
            "Sketching MinHash representations of %d genomes on device ..",
            len(genome_paths))
        from galah_tpu.io.pagestore import pagestore_engaged
        from galah_tpu.ops.bucketing import (
            bucketed_threshold_pairs,
            bucketing_engaged,
        )
        from galah_tpu.parallel import distributed as _dist

        bucketed = (bucketing_engaged(len(genome_paths))
                    and _dist.process_count() == 1)
        # Out-of-core tier (docs/memory.md): the band walk of the
        # bucketed pass is also a paging schedule, so with both
        # engaged the sketch rows can live in the mmap-backed page
        # store and only bands b u (b+1) are ever resident.
        paged = (bucketed
                 and pagestore_engaged(len(genome_paths),
                                       self.sketch_size))
        with timing.stage("sketch-minhash"):
            from galah_tpu.parallel import distributed

            if distributed.process_count() > 1:
                mat = self._sketch_matrix_multihost(genome_paths)
            elif paged:
                mat = self._paged_sketch_rows(genome_paths)
            else:
                by_path = self._sketch_paths(genome_paths)
                sketches = [by_path[p] for p in genome_paths]
                mat = sketch_matrix(sketches,
                                    sketch_size=self.sketch_size)

        if bucketed:
            # Hierarchical precluster: HLL cardinality bands prune the
            # pair lattice before any MinHash screening; the kept pair
            # dict is bit-identical to the unbucketed pass
            # (ops/bucketing.py has the conservativeness argument).
            logger.info("Computing cardinality-bucketed all-pairs "
                        "Mash ANI ..")
            with timing.stage("precluster-hll-cards"):
                if paged:
                    cards, hll_p = self._hll_cardinalities_chunked(
                        genome_paths)
                else:
                    cards, hll_p = self._hll_cardinalities(genome_paths)
            with timing.stage("pairwise-minhash"):
                pairs = bucketed_threshold_pairs(
                    mat, cards, k=self.k, min_ani=self.min_ani,
                    sketch_size=self.sketch_size, p=hll_p)
            cache = PairDistanceCache()
            for (i, j), ani in pairs.items():
                cache.insert((i, j), ani)
            logger.info(
                "Found %d pairs passing precluster threshold %.4f",
                len(cache), self.min_ani)
            return cache
        logger.info("Computing tiled all-pairs Mash ANI ..")
        with timing.stage("pairwise-minhash"):
            # threshold_pairs auto-selects the column-sharded SPMD
            # implementation on a multi-device runtime
            pairs = threshold_pairs(
                mat, k=self.k, min_ani=self.min_ani,
                sketch_size=self.sketch_size)
        cache = PairDistanceCache()
        for (i, j), ani in pairs.items():
            cache.insert((i, j), ani)
        logger.info("Found %d pairs passing precluster threshold %.4f",
                    len(cache), self.min_ani)
        return cache
