from galah_tpu.backends.base import (  # noqa: F401
    ClusterBackend,
    PreclusterBackend,
)
from galah_tpu.backends.hll_backend import HLLPreclusterer  # noqa: F401
from galah_tpu.backends.minhash_backend import MinHashPreclusterer  # noqa: F401
from galah_tpu.backends.fragment_backend import (  # noqa: F401
    FastANIEquivalentClusterer,
    ProfileStore,
    SkaniEquivalentClusterer,
    SkaniPreclusterer,
)
