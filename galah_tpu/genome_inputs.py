"""Genome input specification: -f / -d / -x / --genome-fasta-list.

Mirrors the bird_tool_utils genome-input contract the reference uses
(reference: docs/galah-cluster.html GENOME INPUT section, consumed via
parse_list_of_genome_fasta_files at src/cluster_argument_parsing.rs:414):
explicit file lists, a list-file of paths, or a directory scanned for a
given extension (default "fna"). At least one source must be provided.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence


def parse_genome_inputs(
    genome_fasta_files: Optional[Sequence[str]] = None,
    genome_fasta_list: Optional[str] = None,
    genome_fasta_directory: Optional[str] = None,
    genome_fasta_extension: str = "fna",
    on_bad_genome: str = "error",
    manifest=None,
) -> List[str]:
    """Resolve the genome input spec into a path list.

    Under ``on_bad_genome="skip"`` a nonexistent path is recorded in
    `manifest` (a resilience.quarantine.QuarantineManifest) and dropped
    instead of raising — the stat() verdict is identical on every host
    of a shared-filesystem multi-host run, so the surviving list is
    too. Content-level validation (corrupt/empty FASTA) happens later
    in the preflight; this stage only has existence to go on.
    """
    out: List[str] = []
    if genome_fasta_files:
        out.extend(genome_fasta_files)
    if genome_fasta_list:
        with open(genome_fasta_list) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(line)
    if genome_fasta_directory:
        suffix = "." + genome_fasta_extension.lstrip(".")
        entries = sorted(os.listdir(genome_fasta_directory))
        out.extend(
            os.path.join(genome_fasta_directory, e)
            for e in entries if e.endswith(suffix))
    if not out:
        raise ValueError(
            "No genome input specified: use --genome-fasta-files, "
            "--genome-fasta-list or --genome-fasta-directory")
    missing = [p for p in out if not os.path.isfile(p)]
    if missing:
        if on_bad_genome == "skip":
            if manifest is not None:
                for p in missing:
                    manifest.add(p, "missing", "not a regular file")
            dropped = set(missing)
            out = [p for p in out if p not in dropped]
            if not out:
                raise FileNotFoundError(
                    "every input genome path is missing; nothing to "
                    "cluster")
            return out
        raise FileNotFoundError(
            f"Genome FASTA file(s) not found: {missing[:5]}")
    return out
