"""Multi-host scale-out: jax.distributed init + per-host data sharding.

The reference is strictly single-process shared-memory (SURVEY.md §2.3,
§5 — rayon threads, no network layer). The TPU-native distributed story
is SPMD over a global mesh:

  * every host runs this same program; `initialize()` wires them into
    one JAX runtime (coordinator rendezvous over DCN);
  * each host ingests and sketches only its shard of the genome list
    (`host_shard`) — FASTA IO and hashing scale linearly with hosts;
  * the per-host sketch rows are assembled into one globally-sharded
    device array (`global_sketch_matrix`) without any host ever holding
    the full matrix;
  * the pairwise pass is the same `shard_map` program as single-host
    (parallel/mesh.py) — XLA inserts all-gathers over ICI within a
    slice and DCN across slices from the shardings alone.

Single-process runs (including the CPU test mesh) take the same code
path: initialize() is a no-op, host_shard returns everything, and the
"global" mesh is the local one.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Sequence, TypeVar

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

T = TypeVar("T")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host JAX runtime (no-op when single-process).

    Arguments default from the standard env vars
    (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) so
    launchers can configure hosts uniformly; explicit arguments win.
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None

    if not coordinator_address or (num_processes or 1) <= 1:
        logger.debug("Single-process run; skipping jax.distributed")
        return
    logger.info("Joining distributed runtime as process %s/%s via %s",
                process_id, num_processes, coordinator_address)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def host_shard(items: Sequence[T]) -> List[T]:
    """This host's strided shard of a global work list.

    Strided (rather than contiguous) so quality-ordered genome lists
    spread evenly: genome sizes correlate with quality rank, and a
    contiguous split would put all the big genomes on host 0.
    """
    return list(items[process_index()::process_count()])


def global_mesh(axis_name: str = "i") -> Mesh:
    """1-D mesh over every device in the job (all hosts)."""
    return Mesh(np.array(jax.devices()), (axis_name,))


def exchange(tag: str, value: "np.ndarray",
             tiled: bool = False) -> "np.ndarray":
    """One guarded cross-host allgather: every collective in this module
    funnels through here so transient runtime errors (the wedged-tunnel
    signatures round 5 hit) get the retry policy, and so the fault
    injector can target collectives by site name ("collective.<tag>").

    Retry here is safe ONLY because a failed collective fails on every
    participant — all hosts observe the error and re-enter together.
    There is deliberately no fallback: a collective that stays broken
    after the retry budget must kill the run, not desync it.
    """
    from jax.experimental import multihost_utils

    from galah_tpu.resilience import dispatch as rdispatch

    return rdispatch.run(
        f"collective.{tag}",
        lambda: np.asarray(
            multihost_utils.process_allgather(value, tiled=tiled)))


def allgather_host_rows(n_unique: int, local_rows: "np.ndarray",
                        fill=0) -> "np.ndarray":
    """Exchange per-host strided-shard rows into the full row matrix.

    `local_rows` are this host's rows for `host_shard(range(n_unique))`
    in shard order; every host receives the identical (n_unique, ...)
    array. The one pad/process_allgather/strided-reassemble protocol
    shared by the sketching backends — the reassembly stride MUST
    mirror host_shard's `items[rank::count]`, so it lives next to it.
    """
    n_proc = process_count()
    if n_proc == 1:
        # Identity — and process_allgather's single-process return has
        # no leading process axis, so the reassembly below would
        # misindex it.
        return np.asarray(local_rows)[:n_unique]
    per = -(-n_unique // n_proc)
    padded = np.full((per, *local_rows.shape[1:]), fill,
                     dtype=local_rows.dtype)
    padded[: local_rows.shape[0]] = local_rows
    gathered = exchange("host-rows", padded)
    out = np.empty((n_unique, *local_rows.shape[1:]),
                   dtype=local_rows.dtype)
    for p in range(n_proc):
        idxs = np.arange(p, n_unique, n_proc)
        out[idxs] = gathered[p, : idxs.shape[0]]
    return out


def sharded_optional_floats(n_total: int, compute_mine,
                            owner=None) -> "List[Optional[float]]":
    """Distribute `n_total` Optional[float] computations across hosts.

    Every process calls this with the same n_total (a collective).
    `compute_mine(indices)` returns this host's values for its shard;
    `owner(k) -> int` assigns item k to a process (default: stride
    `k % P`) — callers pick owners so a shard shares expensive context
    (e.g. pair endpoints whose profiles the host already holds). The
    exchange carries explicit indices, so any deterministic ownership
    works. A host whose compute raises reports failure through the
    exchange and EVERY host re-raises — a lone crash never strands the
    peers inside the collective. None rides as NaN (producers never
    emit NaN values).
    """
    n_proc = process_count()
    if n_proc <= 1:
        return compute_mine(list(range(n_total)))
    rank = process_index()
    if owner is None:
        mine = list(range(rank, n_total, n_proc))
    else:
        mine = [k for k in range(n_total) if owner(k) % n_proc == rank]

    err: "Exception | None" = None
    vals: "List[Optional[float]]" = []
    try:
        vals = list(compute_mine(mine))
        if len(vals) != len(mine):
            raise RuntimeError(
                f"compute_mine returned {len(vals)} values for "
                f"{len(mine)} indices")
    except Exception as e:  # noqa: BLE001 - re-raised after exchange
        err = e
    raise_if_any_host_failed(err)

    sizes = exchange("shard-sizes",
                     np.array([len(mine)], dtype=np.int64))
    per = max(int(sizes.max()), 1)
    local = np.full((per, 2), np.nan, dtype=np.float64)
    local[:, 0] = -1.0  # "no item here"
    for r, (k, v) in enumerate(zip(mine, vals)):
        local[r, 0] = float(k)
        if v is not None:
            local[r, 1] = v
    gathered = exchange("shard-values", local)
    out: "List[Optional[float]]" = [None] * n_total
    for p in range(n_proc):
        for row in gathered[p]:
            k = int(row[0])
            if k >= 0:
                out[k] = None if np.isnan(row[1]) else float(row[1])
    return out


def raise_if_any_host_failed(err: "Exception | None") -> None:
    """Collective status exchange before a data collective: every
    process reports whether its local compute failed; if any did, ALL
    raise (the failing host its own error, peers a pointer to it) —
    a lone crash must never strand the other hosts inside the data
    exchange. Callers must reach this on every process."""
    if process_count() <= 1:
        if err is not None:
            raise err
        return
    status = np.array([1 if err is not None else 0], dtype=np.int64)
    statuses = exchange("host-status", status)
    if err is not None:
        raise err
    if int(statuses.sum()):
        raise RuntimeError(
            "a peer process failed its shard of a distributed pass; "
            "see that process's log for the original error")


def tokens_agree(token: bytes) -> bool:
    """True iff every process passed the identical token (fixed-length
    digest; callers hash variable-size state first). Used to make
    checkpoint resume all-or-nothing across hosts."""
    import hashlib

    digest = np.frombuffer(
        hashlib.sha256(token).digest(), dtype=np.uint8).copy()
    if process_count() == 1:
        return True
    gathered = exchange("resume-token", digest)
    return bool((gathered == gathered[0]).all())


def global_sketch_matrix(
    local_rows: np.ndarray,
    global_n: int,
    mesh: Mesh,
    axis_name: str = "i",
) -> jax.Array:
    """Assemble per-host sketch rows into one row-sharded global array.

    `local_rows` are this host's rows of the (global_n, K) matrix in
    host_shard order (strided); they are re-laid out into the
    contiguous row-sharded global array the pairwise kernels expect.
    No host ever materializes the full matrix: each host contributes
    exactly its rows via make_array_from_process_local_data, and the
    permutation from strided ingestion order to contiguous row order
    happens on device.

    global_n must be divisible by the mesh size (callers pad with
    SENTINEL rows, as the pairwise kernels already require).
    """
    n_proc = process_count()
    if global_n % mesh.devices.size:
        raise ValueError(
            f"global_n {global_n} not divisible by mesh size "
            f"{mesh.devices.size}; pad first")
    if n_proc == 1:
        sharding = NamedSharding(mesh, P(axis_name, None))
        return jax.device_put(local_rows, sharding)

    # Strided ingestion order -> contiguous global order: host p holds
    # global rows [p, p+P, p+2P, ...]. Build the global array in strided
    # order (host-contiguous blocks), then apply the inverse permutation
    # on device (an all-to-all XLA resolves from the sharding).
    sharding = NamedSharding(mesh, P(axis_name, None))
    strided = jax.make_array_from_process_local_data(
        sharding, local_rows, (global_n, local_rows.shape[1]))
    # row g of `strided` is global row (g % P) * ceil + ... : compute the
    # permutation explicitly instead: strided index s = p * per + q holds
    # global row q * P + p, where per = global_n // P.
    per = global_n // n_proc
    s_idx = np.arange(global_n)
    g_idx = (s_idx % per) * n_proc + (s_idx // per)
    inv = np.empty(global_n, dtype=np.int64)
    inv[g_idx] = s_idx

    @jax.jit
    def permute(x):
        out = jax.numpy.take(x, jax.numpy.asarray(inv), axis=0)
        return jax.lax.with_sharding_constraint(out, sharding)

    return permute(strided)
