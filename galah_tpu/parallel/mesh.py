"""Device-mesh helpers: the distributed execution layer.

`sharded_threshold_pairs` is the production sparse precluster pass: for
each row block, ONE SPMD dispatch computes the block's (common, total)
stripe with columns sharded over the mesh, thresholds conservatively and
compacts on device, and returns per-device candidate lists; the host
applies the exact f64 check. `sharded_pair_count` is the reduction-only
variant used by benchmarks and the multi-chip dry run.

The reference's only parallel runtime is a rayon thread pool over shared
memory (reference: src/cluster_argument_parsing.rs:409-412 and the
par_iter sites catalogued in SURVEY.md §2.3). The TPU-native equivalent is
a JAX device mesh: the sketch matrix is sharded by genome row, each device
computes its row block of the pair matrix against (replicated or
all-gathered) columns, and XLA collectives reduce the results over ICI.
Multi-host scale-out uses the same code path — `jax.distributed.initialize`
plus a bigger mesh — since shard_map is SPMD over whatever mesh it's given.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = "i") -> Mesh:
    """1-D mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def sharded_pair_count(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    mesh: Mesh,
    col_tile: int = 64,
    row_tile: Optional[int] = None,
) -> int:
    """Count i<j sketch pairs with ANI >= min_ani, fully on-mesh.

    One SPMD program: rows sharded over the mesh axis, per-device
    (row tile x col tile) loop over its row shard against all columns,
    upper-triangle mask via global row/col ids, and a `psum` over ICI
    producing the replicated global count. Tiling both axes bounds the
    (row_tile, col_tile, sketch) intermediates regardless of shard size,
    so a single dispatch covers any N. This is the collective-reduction
    pattern the bigger pipelines reuse (and what dryrun_multichip
    exercises on a virtual mesh).
    """
    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.pairwise import ani_to_jaccard, tile_stats

    n = sketch_mat.shape[0]
    n_dev = mesh.devices.size
    import math

    if row_tile is None:
        row_tile = min(64, col_tile)
    quantum = math.lcm(n_dev * row_tile, col_tile)
    pad_n = -(-n // quantum) * quantum
    mat = np.full((pad_n, sketch_mat.shape[1]), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:n] = sketch_mat
    j_thr = jnp.float32(ani_to_jaccard(min_ani, k))
    sketch_size = sketch_mat.shape[1]

    def spmd(rows_block, all_cols):
        block = rows_block.shape[0]
        row0 = jax.lax.axis_index("i") * block
        n_rt = block // row_tile
        n_ct = all_cols.shape[0] // col_tile

        def one_tile(t):
            tr = t // n_ct
            tc = t % n_ct
            rows = jax.lax.dynamic_slice_in_dim(
                rows_block, tr * row_tile, row_tile, axis=0)
            cols = jax.lax.dynamic_slice_in_dim(
                all_cols, tc * col_tile, col_tile, axis=0)
            common, total = tile_stats(rows, cols, sketch_size, k)
            passing = (common.astype(jnp.float32)
                       >= j_thr * total.astype(jnp.float32))
            passing = passing & (common > 0)
            gi = row0 + tr * row_tile + jnp.arange(row_tile)[:, None]
            gj = tc * col_tile + jnp.arange(col_tile)[None, :]
            mask = (gi < gj) & (gj < n) & (gi < n)
            return jnp.sum((passing & mask).astype(jnp.int32))

        local = jnp.sum(jax.lax.map(one_tile, jnp.arange(n_rt * n_ct)))
        return jax.lax.psum(local, "i")

    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P("i", None), P(None, None)),
        out_specs=P(),
    )
    return int(jax.jit(fn)(jnp.asarray(mat), jnp.asarray(mat)))


def sharded_threshold_pairs(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    mesh: Mesh,
    row_tile: int = 64,
    col_tile: int = 128,
    cap_per_row: int = 64,
) -> dict:
    """Sparse {(i, j): ani} for i<j pairs with ani >= min_ani, columns
    sharded over the mesh.

    The multi-device twin of ops/pairwise.threshold_pairs: each device
    owns a contiguous column range of the (replicated) sketch matrix,
    computes the row block's stats stripe against its range tile by
    tile (skipping below-diagonal tiles), thresholds conservatively and
    compacts on device; the host merges the per-device candidate lists
    and applies the exact f64 integer-Jaccard check. One dispatch per
    row block regardless of mesh size.
    """
    import math

    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.pairwise import (
        ani_to_jaccard,
        stats_to_ani_f64,
        tile_stats,
    )

    n = sketch_mat.shape[0]
    sketch_size = sketch_mat.shape[1]
    n_dev = mesh.devices.size
    quantum = math.lcm(n_dev * col_tile, row_tile)
    n_pad = -(-n // quantum) * quantum
    mat = np.full((n_pad, sketch_size), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:n] = sketch_mat
    jmat = jnp.asarray(mat)

    cols_per_dev = n_pad // n_dev
    tiles_per_dev = cols_per_dev // col_tile
    j_thr = ani_to_jaccard(min_ani, k)
    j_thr_lo = jnp.float64(j_thr * (1.0 - 1e-12) - 1e-300)

    def spmd(full, r0, thr_lo, cap):
        dev = jax.lax.axis_index("i")
        col0 = dev * cols_per_dev
        rows = jax.lax.dynamic_slice_in_dim(full, r0, row_tile, axis=0)
        t_first = r0 // col_tile

        def one_tile(t):
            gt = col0 // col_tile + t

            def compute(_):
                cols = jax.lax.dynamic_slice_in_dim(
                    full, gt * col_tile, col_tile, axis=0)
                c, tt = tile_stats(rows, cols, sketch_size, k)
                return c.astype(jnp.int32), tt.astype(jnp.int32)

            def skip(_):
                # pcast marks the constant zeros as device-varying so the
                # cond branches type-check under shard_map's vma typing.
                z = jax.lax.pcast(
                    jnp.zeros((row_tile, col_tile), jnp.int32),
                    "i", to="varying")
                return z, z

            return jax.lax.cond(gt >= t_first, compute, skip, None)

        common, total = jax.lax.map(one_tile, jnp.arange(tiles_per_dev))
        common = jnp.transpose(common, (1, 0, 2)).reshape(
            row_tile, cols_per_dev)
        total = jnp.transpose(total, (1, 0, 2)).reshape(
            row_tile, cols_per_dev)

        gi = r0 + jnp.arange(row_tile)[:, None]
        gj = col0 + jnp.arange(cols_per_dev)[None, :]
        mask = (common.astype(jnp.float64)
                >= thr_lo * total.astype(jnp.float64))
        mask &= (common > 0) & (gi < gj) & (gj < n)
        count = jnp.sum(mask.astype(jnp.int32))
        (flat_idx,) = jnp.nonzero(mask.ravel(), size=cap, fill_value=-1)
        safe = jnp.maximum(flat_idx, 0)
        return (flat_idx[None], jnp.take(common.ravel(), safe)[None],
                jnp.take(total.ravel(), safe)[None], count[None])

    @functools.partial(jax.jit, static_argnames=("cap",))
    def run_block(full, r0, thr_lo, cap):
        fn = shard_map(
            functools.partial(spmd, cap=cap),
            mesh=mesh,
            in_specs=(P(None, None), P(), P()),
            out_specs=(P("i"), P("i"), P("i"), P("i")),
        )
        return fn(full, r0, thr_lo)

    from galah_tpu.ops.compact import iter_blocks

    out: dict = {}
    for r0, (flat_idx, common, total, counts) in iter_blocks(
            n, row_tile, cap_per_row,
            lambda r0, cap: run_block(jmat, jnp.int32(r0), j_thr_lo, cap)):
        flat_idx = np.asarray(flat_idx)
        common = np.asarray(common).astype(np.int64)
        total = np.asarray(total).astype(np.int64)
        counts = np.asarray(counts)
        for dev in range(n_dev):
            cnt = int(counts[dev])
            fi = flat_idx[dev, :cnt]
            co = common[dev, :cnt]
            to = total[dev, :cnt]
            keep = co.astype(np.float64) >= j_thr * to
            fi, co, to = fi[keep], co[keep], to[keep]
            ani = stats_to_ani_f64(co, to, k)
            gi = r0 + fi // cols_per_dev
            gj = dev * cols_per_dev + fi % cols_per_dev
            for a, b, v in zip(gi.tolist(), gj.tolist(), ani.tolist()):
                out[(int(a), int(b))] = float(v)
    return out
