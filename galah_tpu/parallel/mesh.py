"""Device-mesh helpers: the distributed execution layer.

`sharded_threshold_pairs` is the production sparse precluster pass: for
each row block, ONE SPMD dispatch computes the block's (common, total)
stripe with columns sharded over the mesh, thresholds conservatively and
compacts on device, and returns per-device candidate lists; the host
applies the exact f64 check. `sharded_pair_count` is the reduction-only
variant used by benchmarks and the multi-chip dry run.

The reference's only parallel runtime is a rayon thread pool over shared
memory (reference: src/cluster_argument_parsing.rs:409-412 and the
par_iter sites catalogued in SURVEY.md §2.3). The TPU-native equivalent is
a JAX device mesh: the sketch matrix is sharded by genome row, each device
computes its row block of the pair matrix against (replicated or
all-gathered) columns, and XLA collectives reduce the results over ICI.
Multi-host scale-out uses the same code path — `jax.distributed.initialize`
plus a bigger mesh — since shard_map is SPMD over whatever mesh it's given.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from galah_tpu.obs.profile import profiled
from galah_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

logger = logging.getLogger(__name__)

#: The sharded pair passes must stay bit-identical to the host and
#: single-device paths whatever the mesh geometry: integer tile stats,
#: conservative f64 on-device prefilter, exact f64 host check.
DETERMINISM_CONTRACT = {
    "family": "mesh",
    "dtype": "float64",
    "functions": [
        "tile2d_stats",
        "sharded_threshold_pairs",
        "_sharded_threshold_pairs_impl",
        "sharded_stripe_stats_2d",
    ],
}


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = "i") -> Mesh:
    """1-D mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


# ---------------------------------------------------------------------------
# 2D tiled meshes (GALAH_TPU_MESH_SHAPE, docs/DISTRIBUTED.md)
# ---------------------------------------------------------------------------


def _squarest_factorization(n: int) -> Tuple[int, int]:
    r = int(math.isqrt(n))
    while n % r:
        r -= 1
    return r, n // r


def _demote_mesh_shape(raw: str, n: int, reason: str) -> None:
    from galah_tpu.obs import events, metrics as obs_metrics

    events.record("mesh-demoted", shape=raw, n_devices=n, reason=reason)
    obs_metrics.counter(
        "mesh.demoted_1d",
        help="2D mesh requests demoted to the 1-D fallback "
             "(non-factorable device count or a shape that does not "
             "cover it)").inc()
    logger.warning("mesh shape %r demoted to 1-D over %d devices: %s",
                   raw, n, reason)


def resolve_mesh_shape(
        n_devices: Optional[int] = None) -> Optional[Tuple[int, int]]:
    """(rows, cols) of the 2D tiled mesh, or None for the 1-D fallback.

    GALAH_TPU_MESH_SHAPE: 'auto' picks the squarest factorization of
    the device count (1-D when the count is 1 or prime — with a
    mesh-demoted event for the prime case), '1d' pins the single-axis
    mesh, 'RxC' pins that exact shape (a shape that does not cover the
    device count demotes to 1-D with an event rather than crashing a
    run over a config typo).
    """
    from galah_tpu.config import env_value

    n = len(jax.devices()) if n_devices is None else n_devices
    raw = (env_value("GALAH_TPU_MESH_SHAPE") or "auto").strip().lower()
    if raw in ("1d", "1"):
        return None
    if raw == "auto":
        if n < 2:
            return None
        r, c = _squarest_factorization(n)
        if r == 1:
            _demote_mesh_shape(
                raw, n, "device count has no non-trivial factorization")
            return None
        return r, c
    try:
        r_s, _, c_s = raw.partition("x")
        r, c = int(r_s), int(c_s)
    except ValueError:
        _demote_mesh_shape(
            raw, n, "unparseable shape (want 'auto', '1d' or 'RxC')")
        return None
    if r < 1 or c < 1 or r * c != n:
        _demote_mesh_shape(
            raw, n, f"{r}x{c} does not cover {n} devices")
        return None
    return r, c


def make_mesh_2d(shape: Tuple[int, int],
                 n_devices: Optional[int] = None) -> Mesh:
    """2D ("row", "col") mesh over the first r*c local devices."""
    r, c = shape
    devs = jax.devices()[:r * c]
    return Mesh(np.array(devs).reshape(r, c), ("row", "col"))


def auto_mesh(n_devices: Optional[int] = None) -> Mesh:
    """The mesh the distance passes should run on: the 2D tiled shape
    GALAH_TPU_MESH_SHAPE resolves to, else the 1-D fallback."""
    shape = resolve_mesh_shape(n_devices)
    if shape is not None:
        return make_mesh_2d(shape, n_devices)
    return make_mesh(n_devices)


def mesh_is_2d(mesh) -> bool:
    return mesh is not None and "row" in mesh.axis_names


def _dcn_crossings(mesh) -> int:
    """Interconnect hops each sketch row makes in one all-pairs pass.

    1-D: every row is replicated to every other device (n_dev - 1
    crossings). 2D tiled: a row is replicated only along its mesh row
    (as tile rows) and its mesh column (as tile columns) —
    (r - 1) + (c - 1) crossings, the communication-avoiding win.
    """
    if mesh_is_2d(mesh):
        r, c = mesh.devices.shape
        return (r - 1) + (c - 1)
    return mesh.devices.size - 1


def _emit_dcn_gauge(mesh, row_bytes: int) -> None:
    from galah_tpu.obs import metrics as obs_metrics

    obs_metrics.gauge(
        "mesh.dcn_bytes_per_row",
        help="Modeled interconnect bytes each sketch row crosses in "
             "one all-pairs pass: row bytes x mesh crossings "
             "(n_dev - 1 on the 1-D mesh, (r-1)+(c-1) on the 2D "
             "tiled mesh)",
        unit="bytes").set(float(_dcn_crossings(mesh) * row_bytes))


@profiled("mesh.tile2d_stats")
@functools.partial(jax.jit, static_argnames=("sketch_size", "k"))
def tile2d_stats(rows: jax.Array, cols: jax.Array,
                 sketch_size: int, k: int):
    """(common, total) int32 stats of one (row tile x col tile) lattice
    tile — the per-device unit of the 2D tiled passes. A thin jitted
    wrapper over ops/pairwise.tile_stats so the profiler and the shape
    lattice cover the 2D kernel as its own entry point; the integers
    are bit-identical to every other stats path."""
    from galah_tpu.ops.pairwise import tile_stats

    c, t = tile_stats(rows, cols, sketch_size, k)
    return c.astype(jnp.int32), t.astype(jnp.int32)


def sharded_pair_count(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    mesh: Mesh,
    col_tile: int = 64,
    row_tile: Optional[int] = None,
    use_pallas: Optional[bool] = None,
) -> int:
    """Count i<j sketch pairs with ANI >= min_ani, fully on-mesh.

    One SPMD program: rows sharded over the mesh axis, per-device
    (row tile x col tile) loop over its row shard against all columns,
    upper-triangle mask via global row/col ids, and a `psum` over ICI
    producing the replicated global count. Tiling both axes bounds the
    (row_tile, col_tile, sketch) intermediates regardless of shard size,
    so a single dispatch covers any N. This is the collective-reduction
    pattern the bigger pipelines reuse (and what dryrun_multichip
    exercises on a virtual mesh).
    """
    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.hll import use_pallas_default
    from galah_tpu.ops.pairwise import ani_to_jaccard, tile_stats

    if use_pallas is None:
        use_pallas = use_pallas_default()
    if use_pallas:
        col_tile = max(col_tile, 128)

    n = sketch_mat.shape[0]
    n_dev = mesh.devices.size
    import math

    if row_tile is None:
        row_tile = min(64, col_tile) if not use_pallas else 128
    quantum = math.lcm(n_dev * row_tile, col_tile)
    pad_n = -(-n // quantum) * quantum
    mat = np.full((pad_n, sketch_mat.shape[1]), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:n] = sketch_mat
    j_thr = jnp.float32(ani_to_jaccard(min_ani, k))
    sketch_size = sketch_mat.shape[1]

    if use_pallas:
        from galah_tpu.ops.pallas_pairwise import tile_stats_pallas

        def stats_fn(rows, cols):
            return tile_stats_pallas(rows, cols, sketch_size)
    else:
        def stats_fn(rows, cols):
            return tile_stats(rows, cols, sketch_size, k)

    def spmd(rows_block, all_cols):
        block = rows_block.shape[0]
        row0 = jax.lax.axis_index("i") * block
        n_rt = block // row_tile
        n_ct = all_cols.shape[0] // col_tile

        def one_tile(t):
            tr = t // n_ct
            tc = t % n_ct
            rows = jax.lax.dynamic_slice_in_dim(
                rows_block, tr * row_tile, row_tile, axis=0)
            cols = jax.lax.dynamic_slice_in_dim(
                all_cols, tc * col_tile, col_tile, axis=0)
            common, total = stats_fn(rows, cols)
            passing = (common.astype(jnp.float32)
                       >= j_thr * total.astype(jnp.float32))
            passing = passing & (common > 0)
            gi = row0 + tr * row_tile + jnp.arange(row_tile)[:, None]
            gj = tc * col_tile + jnp.arange(col_tile)[None, :]
            mask = (gi < gj) & (gj < n) & (gi < n)
            return jnp.sum((passing & mask).astype(jnp.int32))

        local = jnp.sum(jax.lax.map(one_tile, jnp.arange(n_rt * n_ct)))
        return jax.lax.psum(local, "i")

    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P("i", None), P(None, None)),
        out_specs=P(),
    )
    return int(jax.jit(fn)(jnp.asarray(mat), jnp.asarray(mat)))


def sharded_stripe_stats(
    rows_mat: np.ndarray,
    cols_mat: np.ndarray,
    sketch_size: int,
    k: int,
    mesh: Mesh,
    row_tile: int = 64,
    r_pad: Optional[int] = None,
):
    """(common, total) int32 of every done row against one incoming
    column block, rows sharded over the mesh — the SPMD twin of
    ops/pairwise._stripe_stats for the streamed pair pass. Each device
    lax.maps over the row tiles of its contiguous row shard against the
    (replicated) column block; the integers are bit-identical to the
    single-device stripe. `r_pad` must be a multiple of
    mesh_size * row_tile (the caller's pow2 padding guarantees it for
    pow2 meshes). A 2D ("row", "col") mesh dispatches to the tiled
    twin (rows sharded over mesh rows, the column block over mesh
    columns)."""
    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.pairwise import tile_stats

    if mesh_is_2d(mesh):
        return sharded_stripe_stats_2d(
            rows_mat, cols_mat, sketch_size, k, mesh,
            row_tile=row_tile, r_pad=r_pad)
    n_dev = mesh.devices.size
    if r_pad is None:
        q = n_dev * row_tile
        r_pad = -(-rows_mat.shape[0] // q) * q
    if r_pad % (n_dev * row_tile):
        raise ValueError(
            f"r_pad {r_pad} not a multiple of mesh size {n_dev} x "
            f"row_tile {row_tile}")
    mat = np.full((r_pad, rows_mat.shape[1]), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:rows_mat.shape[0]] = rows_mat

    def spmd(rows_shard, cols):
        n_rt = rows_shard.shape[0] // row_tile

        def one_tile(t):
            rows = jax.lax.dynamic_slice_in_dim(
                rows_shard, t * row_tile, row_tile, axis=0)
            c, tt = tile_stats(rows, cols, sketch_size, k)
            return c.astype(jnp.int32), tt.astype(jnp.int32)

        c, t = jax.lax.map(one_tile, jnp.arange(n_rt))
        b = cols.shape[0]
        return (c.reshape(n_rt * row_tile, b),
                t.reshape(n_rt * row_tile, b))

    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P("i", None), P(None, None)),
        out_specs=(P("i", None), P("i", None)),
    )
    _emit_dcn_gauge(mesh, cols_mat.shape[1] * cols_mat.dtype.itemsize)
    return jax.jit(fn)(jnp.asarray(mat), jnp.asarray(cols_mat))


def sharded_stripe_stats_2d(
    rows_mat: np.ndarray,
    cols_mat: np.ndarray,
    sketch_size: int,
    k: int,
    mesh: Mesh,
    row_tile: int = 64,
    r_pad: Optional[int] = None,
):
    """2D tiled twin of sharded_stripe_stats: done rows sharded over
    mesh rows, the incoming column block sharded over mesh columns, so
    each device computes its (row shard x column chunk) tile and a row
    is replicated along exactly one mesh axis instead of to every
    device. The assembled (r_pad, block) integer stripes are
    bit-identical to the 1-D and single-device paths (tile_stats is
    elementwise per pair)."""
    from galah_tpu.ops.constants import SENTINEL

    r, c = mesh.devices.shape
    block = cols_mat.shape[0]
    if block % c:
        raise ValueError(
            f"column block {block} not divisible by mesh cols {c}")
    q = r * row_tile
    if r_pad is None:
        r_pad = -(-rows_mat.shape[0] // q) * q
    if r_pad % q:
        raise ValueError(
            f"r_pad {r_pad} not a multiple of mesh rows {r} x "
            f"row_tile {row_tile}")
    mat = np.full((r_pad, rows_mat.shape[1]), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:rows_mat.shape[0]] = rows_mat

    def spmd(rows_shard, cols_shard):
        n_rt = rows_shard.shape[0] // row_tile

        def one_tile(t):
            rows = jax.lax.dynamic_slice_in_dim(
                rows_shard, t * row_tile, row_tile, axis=0)
            return tile2d_stats(rows, cols_shard, sketch_size, k)

        cm, tt = jax.lax.map(one_tile, jnp.arange(n_rt))
        b = cols_shard.shape[0]
        return (cm.reshape(n_rt * row_tile, b),
                tt.reshape(n_rt * row_tile, b))

    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P("row", None), P("col", None)),
        out_specs=(P("row", "col"), P("row", "col")),
    )
    _emit_dcn_gauge(mesh, cols_mat.shape[1] * cols_mat.dtype.itemsize)
    return jax.jit(fn)(jnp.asarray(mat), jnp.asarray(cols_mat))


def _sharded_blocked_extract(
    mesh: Mesh,
    arrays,              # tuple of replicated device arrays
    n: int,
    n_pad: int,
    row_tile: int,
    col_tile: int,
    cap_per_row: int,
    slice_rows,          # (arrays, r0) -> per-block row context
    compute_tile,        # (arrays, rows_ctx, gt) -> tuple of stripes
    stripe_dtypes,       # dtypes of compute_tile's outputs (for skips)
    stripe_mask,         # (stripes, ) -> bool pass mask (thresholding)
):
    """Core of the column-sharded sparse extractions.

    One SPMD dispatch per row block: every device computes the block's
    stripes against its contiguous column range tile by tile (lax.cond
    skips tiles entirely below the diagonal), applies `stripe_mask`
    plus the upper-triangle/bounds mask, and compacts passing entries
    to a fixed capacity on device. The compacted per-device outputs are
    all-gathered inside the SPMD program and returned REPLICATED, so a
    multi-host run (where per-device shards are not host-addressable)
    reads the same arrays as a single host — every host sees every
    device's candidates and produces the identical pair set. Yields
    (gi, gj, payloads) numpy arrays per (row block, device); overflow
    retry policy comes from ops/compact.iter_blocks.
    """
    from galah_tpu.ops.compact import iter_blocks

    n_dev = mesh.devices.size
    cols_per_dev = n_pad // n_dev
    tiles_per_dev = cols_per_dev // col_tile
    n_payload = len(stripe_dtypes)

    def spmd(*args):
        *arrs, r0, cap = args
        dev = jax.lax.axis_index("i")
        col0 = dev * cols_per_dev
        rows_ctx = slice_rows(arrs, r0)
        t_first = r0 // col_tile

        def one_tile(t):
            gt = col0 // col_tile + t

            def compute(_):
                return tuple(compute_tile(arrs, rows_ctx, gt))

            def skip(_):
                # pcast marks the constant zeros as device-varying so
                # the cond branches type-check under shard_map's vma
                # typing.
                from galah_tpu.utils.jax_compat import pcast_varying

                return tuple(
                    pcast_varying(
                        jnp.zeros((row_tile, col_tile), dt), "i")
                    for dt in stripe_dtypes)

            return jax.lax.cond(gt >= t_first, compute, skip, None)

        stripes = jax.lax.map(one_tile, jnp.arange(tiles_per_dev))
        stripes = tuple(
            jnp.transpose(s, (1, 0, 2)).reshape(row_tile, cols_per_dev)
            for s in stripes)

        gi = r0 + jnp.arange(row_tile)[:, None]
        gj = col0 + jnp.arange(cols_per_dev)[None, :]
        mask = stripe_mask(stripes) & (gi < gj) & (gj < n)
        count = jnp.sum(mask.astype(jnp.int32))
        (flat_idx,) = jnp.nonzero(mask.ravel(), size=cap, fill_value=-1)
        safe = jnp.maximum(flat_idx, 0)
        payloads = tuple(jnp.take(s.ravel(), safe) for s in stripes)
        # Replicate the (tiny) compacted results to every device so a
        # multi-host run can read them from any host: (n_dev, cap) per
        # payload, (n_dev,) counts.
        gather = functools.partial(jax.lax.all_gather, axis_name="i")
        return (gather(flat_idx), *map(gather, payloads), gather(count))

    @functools.partial(jax.jit, static_argnames=("cap",))
    def run_block(*args, cap):
        in_specs = tuple(P(*([None] * a.ndim)) for a in arrays) + (P(),)
        # check_vma off: the outputs ARE replicated (each is an
        # all_gather result, identical on every device), but the vma
        # type system cannot express post-gather invariance for P()
        # out_specs (pcast has no varying->invariant direction).
        fn = shard_map(
            functools.partial(lambda *a, cap: spmd(*a, cap), cap=cap),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=tuple(P() for _ in range(n_payload + 2)),
            check_vma=False,
        )
        return fn(*args)

    for r0, result in iter_blocks(
            n, row_tile, cap_per_row,
            lambda r0, cap: run_block(*arrays, jnp.int32(r0), cap=cap)):
        flat_idx = np.asarray(result[0])
        payloads = [np.asarray(p) for p in result[1:-1]]
        counts = np.asarray(result[-1])
        for dev in range(n_dev):
            cnt = int(counts[dev])
            fi = flat_idx[dev, :cnt]
            gi = r0 + fi // cols_per_dev
            gj = dev * cols_per_dev + fi % cols_per_dev
            yield gi, gj, tuple(p[dev, :cnt] for p in payloads)


def _sharded_blocked_extract_2d(
    mesh: Mesh,
    arrays,              # tuple of full (padded) device arrays
    n: int,
    n_pad: int,
    row_tile: int,
    col_tile: int,
    cap_per_row: int,
    slice_rows,          # (row_shards, local_r0) -> per-block row ctx
    compute_tile,        # (col_shards, rows_ctx, local_t) -> stripes
    stripe_dtypes,       # dtypes of compute_tile's outputs (for skips)
    stripe_mask,         # (stripes, ) -> bool pass mask (thresholding)
):
    """2D tiled twin of _sharded_blocked_extract.

    Each device owns one (row shard x column shard) tile of the pair
    lattice: every array is passed twice, once sharded over mesh rows
    (the row context) and once over mesh columns (the tile columns),
    so a sketch row is replicated along exactly one mesh row and one
    mesh column — (r-1)+(c-1) interconnect crossings instead of the
    1-D path's n_dev-1. One SPMD dispatch covers local row-block `lb`
    on EVERY device at once (mesh row i works global rows
    i*rows_per_dev + lb ..); the per-tile lax.cond skips tiles
    entirely below the diagonal, which prunes the redundant
    lower-triangle half of the lattice. The same closures as the 1-D
    core apply (slices address the LOCAL shard at LOCAL offsets), so
    the integers — and therefore the extracted pair set — are
    bit-identical. Yields (gi, gj, payloads) per (row block, device).
    """
    from galah_tpu.ops.compact import iter_blocks

    r, c = mesh.devices.shape
    rows_per_dev = n_pad // r
    cols_per_dev = n_pad // c
    tiles_per_dev = cols_per_dev // col_tile
    n_arr = len(arrays)
    n_payload = len(stripe_dtypes)

    def spmd(*args):
        *arrs, lb, cap = args
        row_arrs, col_arrs = arrs[:n_arr], arrs[n_arr:]
        mi = jax.lax.axis_index("row")
        mj = jax.lax.axis_index("col")
        r0 = mi * rows_per_dev + lb
        col0 = mj * cols_per_dev
        rows_ctx = slice_rows(row_arrs, lb)
        t_first = r0 // col_tile

        def one_tile(t):
            gt = col0 // col_tile + t

            def compute(_):
                return tuple(compute_tile(col_arrs, rows_ctx, t))

            def skip(_):
                from galah_tpu.utils.jax_compat import pcast_varying

                return tuple(
                    pcast_varying(pcast_varying(
                        jnp.zeros((row_tile, col_tile), dt),
                        "row"), "col")
                    for dt in stripe_dtypes)

            return jax.lax.cond(gt >= t_first, compute, skip, None)

        stripes = jax.lax.map(one_tile, jnp.arange(tiles_per_dev))
        stripes = tuple(
            jnp.transpose(s, (1, 0, 2)).reshape(row_tile, cols_per_dev)
            for s in stripes)

        gi = r0 + jnp.arange(row_tile)[:, None]
        gj = col0 + jnp.arange(cols_per_dev)[None, :]
        mask = stripe_mask(stripes) & (gi < gj) & (gj < n)
        count = jnp.sum(mask.astype(jnp.int32))
        (flat_idx,) = jnp.nonzero(mask.ravel(), size=cap, fill_value=-1)
        safe = jnp.maximum(flat_idx, 0)
        payloads = tuple(jnp.take(s.ravel(), safe) for s in stripes)

        # Replicate the (tiny) compacted results to every device —
        # (r, c, cap) per payload, (r, c) counts — same multi-host
        # rationale as the 1-D core.
        def gather(x):
            x = jax.lax.all_gather(x, axis_name="col")
            return jax.lax.all_gather(x, axis_name="row")

        return (gather(flat_idx), *map(gather, payloads), gather(count))

    @functools.partial(jax.jit, static_argnames=("cap",))
    def run_block(*args, cap):
        in_specs = (
            tuple(P(*(["row"] + [None] * (a.ndim - 1)))
                  for a in arrays)
            + tuple(P(*(["col"] + [None] * (a.ndim - 1)))
                    for a in arrays)
            + (P(),))
        # check_vma off for the same reason as the 1-D core: the
        # all_gather outputs ARE replicated but the vma type system
        # cannot express post-gather invariance for P() out_specs.
        fn = shard_map(
            functools.partial(lambda *a, cap: spmd(*a, cap), cap=cap),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=tuple(P() for _ in range(n_payload + 2)),
            check_vma=False,
        )
        return fn(*args)

    # Local row blocks with lb >= n are empty on every mesh row (mesh
    # row 0 starts at lb; higher rows start even later), so the block
    # loop is bounded by min(rows_per_dev, n).
    for lb, result in iter_blocks(
            min(rows_per_dev, n), row_tile, cap_per_row,
            lambda lb, cap: run_block(*arrays, *arrays, jnp.int32(lb),
                                      cap=cap)):
        flat_idx = np.asarray(result[0])
        payloads = [np.asarray(p) for p in result[1:-1]]
        counts = np.asarray(result[-1])
        for mi in range(r):
            for mj in range(c):
                cnt = int(counts[mi, mj])
                fi = flat_idx[mi, mj, :cnt]
                gi = mi * rows_per_dev + lb + fi // cols_per_dev
                gj = mj * cols_per_dev + fi % cols_per_dev
                yield gi, gj, tuple(p[mi, mj, :cnt] for p in payloads)


def sharded_threshold_pairs(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    mesh: Mesh,
    sketch_size: Optional[int] = None,
    row_tile: Optional[int] = None,
    col_tile: Optional[int] = None,
    cap_per_row: int = 64,
    use_pallas: Optional[bool] = None,
) -> dict:
    """Sparse {(i, j): ani} for i<j pairs with ani >= min_ani, columns
    sharded over the mesh.

    The multi-device twin of ops/pairwise.threshold_pairs: the blocked
    extraction core computes (common, total) stats stripes per device,
    prefilters with a conservative f64 threshold on device, and the
    host applies the exact f64 integer-Jaccard check over the sparse
    survivors. One dispatch per row block regardless of mesh size.
    With use_pallas (the default on a TPU backend) each device's stats
    tiles run the Mosaic kernel instead of the XLA searchsorted path —
    bit-identical integers either way.
    """
    from galah_tpu.ops.hll import use_pallas_default

    if use_pallas is None:
        use_pallas = use_pallas_default()
    if use_pallas:
        try:
            return _sharded_threshold_pairs_impl(
                sketch_mat, k, min_ani, mesh, sketch_size,
                row_tile if row_tile is not None else 128,
                col_tile if col_tile is not None else 128,
                cap_per_row, True)
        except Exception:
            # A Mosaic lowering failure must not take down the
            # multi-device production path either (the single-device
            # twin has the same fallback).
            import logging

            logging.getLogger(__name__).warning(
                "Pallas pair-stats kernel unavailable on the sharded "
                "path; falling back to XLA", exc_info=True)
    return _sharded_threshold_pairs_impl(
        sketch_mat, k, min_ani, mesh, sketch_size,
        row_tile if row_tile is not None else 64,
        col_tile if col_tile is not None else 128,
        cap_per_row, False)


def _sharded_threshold_pairs_impl(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    mesh: Mesh,
    sketch_size: Optional[int],
    row_tile: int,
    col_tile: int,
    cap_per_row: int,
    use_pallas: bool,
) -> dict:
    import math

    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.pairwise import (
        ani_to_jaccard,
        stats_to_ani_f64,
        tile_stats,
    )

    n = sketch_mat.shape[0]
    if sketch_size is None:
        sketch_size = sketch_mat.shape[1]
    two_d = mesh_is_2d(mesh)
    if two_d:
        r, c = mesh.devices.shape
        quantum = math.lcm(r * row_tile, c * col_tile)
    else:
        quantum = math.lcm(mesh.devices.size * col_tile, row_tile)
    n_pad = -(-n // quantum) * quantum
    mat = np.full((n_pad, sketch_mat.shape[1]), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:n] = sketch_mat
    jmat = jnp.asarray(mat)
    _emit_dcn_gauge(mesh, sketch_mat.shape[1]
                    * sketch_mat.dtype.itemsize)

    j_thr = ani_to_jaccard(min_ani, k)
    j_thr_lo = j_thr * (1.0 - 1e-12) - 1e-300

    def slice_rows(arrs, r0):
        return jax.lax.dynamic_slice_in_dim(arrs[0], r0, row_tile, axis=0)

    if use_pallas:
        from galah_tpu.ops.pallas_pairwise import tile_stats_pallas

        def stats_fn(rows, cols):
            return tile_stats_pallas(rows, cols, sketch_size)
    elif two_d:
        def stats_fn(rows, cols):
            return tile2d_stats(rows, cols, sketch_size, k)
    else:
        def stats_fn(rows, cols):
            return tile_stats(rows, cols, sketch_size, k)

    def compute_tile(arrs, rows, gt):
        cols = jax.lax.dynamic_slice_in_dim(
            arrs[0], gt * col_tile, col_tile, axis=0)
        c, t = stats_fn(rows, cols)
        return c.astype(jnp.int32), t.astype(jnp.int32)

    def stripe_mask(stripes):
        common, total = stripes
        mask = (common.astype(jnp.float64)
                >= jnp.float64(j_thr_lo) * total.astype(jnp.float64))
        return mask & (common > 0)

    extract = (_sharded_blocked_extract_2d if two_d
               else _sharded_blocked_extract)
    out: dict = {}
    for gi, gj, (common, total) in extract(
            mesh, (jmat,), n, n_pad, row_tile, col_tile, cap_per_row,
            slice_rows, compute_tile, (jnp.int32, jnp.int32),
            stripe_mask):
        common = common.astype(np.int64)
        total = total.astype(np.int64)
        keep = common.astype(np.float64) >= j_thr * total
        gi, gj = gi[keep], gj[keep]
        ani = stats_to_ani_f64(common[keep], total[keep], k)
        for a, b, v in zip(gi.tolist(), gj.tolist(), ani.tolist()):
            out[(int(a), int(b))] = float(v)
    return out


def sharded_screen_pairs(
    marker_mat: np.ndarray,
    counts: np.ndarray,
    c_floor: float,
    mesh: Mesh,
    row_tile: int = 64,
    col_tile: int = 256,
    cap_per_row: int = 256,
    use_pallas: Optional[bool] = None,
) -> list:
    """i<j pairs with marker containment >= c_floor, columns sharded over
    the mesh — the multi-device twin of ops/pairwise.screen_pairs (the
    same blocked extraction core, with the marker-intersection count as
    the tile computation and min-count containment as the threshold)."""
    import math

    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.hll import use_pallas_default
    from galah_tpu.ops.pairwise import tile_intersect_counts

    if use_pallas is None:
        use_pallas = use_pallas_default()

    n = marker_mat.shape[0]
    two_d = mesh_is_2d(mesh)
    if two_d:
        r, c = mesh.devices.shape
        quantum = math.lcm(r * row_tile, c * col_tile)
    else:
        quantum = math.lcm(mesh.devices.size * col_tile, row_tile)
    n_pad = -(-n // quantum) * quantum
    mat = np.full((n_pad, marker_mat.shape[1]), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:n] = marker_mat
    cnt = np.zeros(n_pad, dtype=np.int32)
    cnt[:n] = counts
    jmat = jnp.asarray(mat)
    jcnt = jnp.asarray(cnt)
    _emit_dcn_gauge(mesh, marker_mat.shape[1]
                    * marker_mat.dtype.itemsize)

    c_floor_lo = c_floor * (1.0 - 1e-12) - 1e-300

    def slice_rows(arrs, r0):
        return (jax.lax.dynamic_slice_in_dim(arrs[0], r0, row_tile,
                                             axis=0),
                jax.lax.dynamic_slice_in_dim(arrs[1], r0, row_tile,
                                             axis=0))

    def compute_tile(arrs, rows_ctx, gt):
        rows, rcnt = rows_ctx
        cols = jax.lax.dynamic_slice_in_dim(
            arrs[0], gt * col_tile, col_tile, axis=0)
        ccnt = jax.lax.dynamic_slice_in_dim(
            arrs[1], gt * col_tile, col_tile, axis=0)
        if use_pallas:
            from galah_tpu.ops.pallas_pairwise import tile_intersect_pallas

            inter = tile_intersect_pallas(rows, cols)
        else:
            inter = tile_intersect_counts(rows, cols).astype(jnp.int32)
        denom = jnp.minimum(rcnt[:, None], ccnt[None, :]).astype(jnp.int32)
        denom = jnp.broadcast_to(denom, inter.shape)
        return inter, denom

    def stripe_mask(stripes):
        inter, denom = stripes
        mask = (inter.astype(jnp.float64)
                >= jnp.float64(c_floor_lo) * denom.astype(jnp.float64))
        return mask & (inter > 0)

    extract = (_sharded_blocked_extract_2d if two_d
               else _sharded_blocked_extract)
    out: list = []
    for gi, gj, (inter, denom) in extract(
            mesh, (jmat, jcnt), n, n_pad, row_tile, col_tile,
            cap_per_row, slice_rows, compute_tile,
            (jnp.int32, jnp.int32), stripe_mask):
        inter = inter.astype(np.float64)
        denom = denom.astype(np.float64)
        # denom > 0 is belt and braces: the stripe mask already
        # requires inter > 0 and inter <= denom, so a denom == 0 pair
        # cannot reach here — the guard keeps this check self-contained
        keep = (denom > 0) & (inter >= c_floor * denom)
        out.extend(zip(gi[keep].tolist(), gj[keep].tolist()))
    out.sort()
    return out


def sharded_hll_threshold_pairs(
    regs_mat: np.ndarray,
    k: int,
    min_ani: float,
    mesh: Mesh,
    row_tile: int = 64,
    col_tile: int = 128,
    cap_per_row: int = 64,
) -> dict:
    """Sparse {(i, j): ani} over HLL register sketches, columns sharded
    over the mesh — the multi-device twin of ops/hll.hll_threshold_pairs
    (the same blocked extraction core, with the HLL union estimator as
    the tile computation)."""
    import math

    from galah_tpu.ops import hll as hll_ops

    n, m = regs_mat.shape
    two_d = mesh_is_2d(mesh)
    if two_d:
        r, c = mesh.devices.shape
        quantum = math.lcm(r * row_tile, c * col_tile)
    else:
        quantum = math.lcm(mesh.devices.size * col_tile, row_tile)
    n_pad = -(-n // quantum) * quantum
    mat = np.zeros((n_pad, m), dtype=np.uint8)
    mat[:n] = regs_mat
    jmat = jnp.asarray(mat)
    cards = hll_ops.hll_cardinality(jmat)
    pow2 = jnp.exp2(-jmat.astype(jnp.float32))
    _emit_dcn_gauge(mesh, m * regs_mat.dtype.itemsize)

    def slice_rows(arrs, r0):
        return (jax.lax.dynamic_slice_in_dim(arrs[0], r0, row_tile,
                                             axis=0),
                jax.lax.dynamic_slice_in_dim(arrs[1], r0, row_tile,
                                             axis=0))

    def compute_tile(arrs, rows_ctx, gt):
        rows, rcards = rows_ctx
        cols = jax.lax.dynamic_slice_in_dim(
            arrs[0], gt * col_tile, col_tile, axis=0)
        ccards = jax.lax.dynamic_slice_in_dim(
            arrs[1], gt * col_tile, col_tile, axis=0)
        powsum, zeros = hll_ops._xla_union_stats(rows, cols)
        return (hll_ops._ani_from_union_stats(
            powsum, zeros, rcards, ccards, k, m),)

    def stripe_mask(stripes):
        return stripes[0] >= jnp.float32(min_ani)

    extract = (_sharded_blocked_extract_2d if two_d
               else _sharded_blocked_extract)
    out: dict = {}
    for gi, gj, (vals,) in extract(
            mesh, (pow2, cards), n, n_pad, row_tile, col_tile,
            cap_per_row, slice_rows, compute_tile, (jnp.float32,),
            stripe_mask):
        for a, b, v in zip(gi.tolist(), gj.tolist(), vals.tolist()):
            out[(int(a), int(b))] = float(v)
    return out
