"""Device-mesh helpers: the distributed execution layer.

The reference's only parallel runtime is a rayon thread pool over shared
memory (reference: src/cluster_argument_parsing.rs:409-412 and the
par_iter sites catalogued in SURVEY.md §2.3). The TPU-native equivalent is
a JAX device mesh: the sketch matrix is sharded by genome row, each device
computes its row block of the pair matrix against (replicated or
all-gathered) columns, and XLA collectives reduce the results over ICI.
Multi-host scale-out uses the same code path — `jax.distributed.initialize`
plus a bigger mesh — since shard_map is SPMD over whatever mesh it's given.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = "i") -> Mesh:
    """1-D mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def sharded_pair_count(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    mesh: Mesh,
    col_tile: int = 64,
    row_tile: Optional[int] = None,
) -> int:
    """Count i<j sketch pairs with ANI >= min_ani, fully on-mesh.

    One SPMD program: rows sharded over the mesh axis, per-device
    (row tile x col tile) loop over its row shard against all columns,
    upper-triangle mask via global row/col ids, and a `psum` over ICI
    producing the replicated global count. Tiling both axes bounds the
    (row_tile, col_tile, sketch) intermediates regardless of shard size,
    so a single dispatch covers any N. This is the collective-reduction
    pattern the bigger pipelines reuse (and what dryrun_multichip
    exercises on a virtual mesh).
    """
    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.pairwise import ani_to_jaccard, tile_stats

    n = sketch_mat.shape[0]
    n_dev = mesh.devices.size
    import math

    if row_tile is None:
        row_tile = min(64, col_tile)
    quantum = math.lcm(n_dev * row_tile, col_tile)
    pad_n = -(-n // quantum) * quantum
    mat = np.full((pad_n, sketch_mat.shape[1]), np.uint64(SENTINEL),
                  dtype=np.uint64)
    mat[:n] = sketch_mat
    j_thr = jnp.float32(ani_to_jaccard(min_ani, k))
    sketch_size = sketch_mat.shape[1]

    def spmd(rows_block, all_cols):
        block = rows_block.shape[0]
        row0 = jax.lax.axis_index("i") * block
        n_rt = block // row_tile
        n_ct = all_cols.shape[0] // col_tile

        def one_tile(t):
            tr = t // n_ct
            tc = t % n_ct
            rows = jax.lax.dynamic_slice_in_dim(
                rows_block, tr * row_tile, row_tile, axis=0)
            cols = jax.lax.dynamic_slice_in_dim(
                all_cols, tc * col_tile, col_tile, axis=0)
            common, total = tile_stats(rows, cols, sketch_size, k)
            passing = (common.astype(jnp.float32)
                       >= j_thr * total.astype(jnp.float32))
            passing = passing & (common > 0)
            gi = row0 + tr * row_tile + jnp.arange(row_tile)[:, None]
            gj = tc * col_tile + jnp.arange(col_tile)[None, :]
            mask = (gi < gj) & (gj < n) & (gi < n)
            return jnp.sum((passing & mask).astype(jnp.int32))

        local = jnp.sum(jax.lax.map(one_tile, jnp.arange(n_rt * n_ct)))
        return jax.lax.psum(local, "i")

    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P("i", None), P(None, None)),
        out_specs=P(),
    )
    return int(jax.jit(fn)(jnp.asarray(mat), jnp.asarray(mat)))
