from galah_tpu.parallel import distributed  # noqa: F401
from galah_tpu.parallel.mesh import (  # noqa: F401
    auto_mesh,
    make_mesh,
    make_mesh_2d,
    resolve_mesh_shape,
    sharded_pair_count,
    sharded_threshold_pairs,
)
