from galah_tpu.parallel import distributed  # noqa: F401
from galah_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    sharded_pair_count,
    sharded_threshold_pairs,
)
