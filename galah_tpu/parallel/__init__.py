from galah_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    sharded_pair_count,
)
