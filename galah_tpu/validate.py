"""`cluster-validate`: re-check a cluster file with exact ANI.

Mirrors the reference's cluster_validation.rs:7-78: every member must be
within the ANI threshold of its representative, and every representative
pair must be BELOW the threshold (or gated out). Violations are logged as
errors; like the reference, validation does not exit nonzero on violation
— the count is returned for callers/tests.
"""

from __future__ import annotations

import itertools
import logging
from typing import List, Sequence

from galah_tpu.backends.base import ClusterBackend
from galah_tpu.outputs import read_cluster_file

logger = logging.getLogger(__name__)


def validate_clusters(
    cluster_file: str,
    clusterer: ClusterBackend,
) -> int:
    """Validate; returns the number of violations found."""
    clusters = read_cluster_file(cluster_file)
    thr = clusterer.ani_threshold
    violations = 0

    # members vs their rep
    member_pairs = [
        (cluster[0], member)
        for cluster in clusters
        for member in cluster[1:]
    ]
    anis = clusterer.calculate_ani_batch(member_pairs)
    for (rep, member), ani in zip(member_pairs, anis):
        if ani is None or ani < thr:
            violations += 1
            logger.error(
                "Member %s is not within %s ANI of its representative %s "
                "(found %s)", member, thr, rep, ani)

    # rep pairs must NOT match
    reps = [c[0] for c in clusters]
    rep_pairs = list(itertools.combinations(reps, 2))
    anis = clusterer.calculate_ani_batch(rep_pairs)
    for (r1, r2), ani in zip(rep_pairs, anis):
        if ani is not None and ani >= thr:
            violations += 1
            logger.error(
                "Representatives %s and %s are within %s ANI of each "
                "other (found %s)", r1, r2, thr, ani)

    if violations == 0:
        logger.info("Validated %d clusters: no violations", len(clusters))
    else:
        logger.error("Found %d validation violations", violations)
    return violations
