"""Retry policy: exponential backoff + jitter, per-attempt deadline,
overall budget.

Five rounds of hardware campaigns showed the failure mode this guards
against: a wedged TPU tunnel turns one stuck dispatch into an hours-long
hang that a watchdog can only kill from outside (VERDICT.md round 5).
Every device dispatch and collective in the pipeline is wrapped in
`call_with_retry` via resilience/dispatch.py, so a transient fault costs
one backoff sleep instead of the run, and a hang is abandoned at the
per-attempt deadline instead of holding the process hostage.

Jitter is deterministic per (site, attempt) when the policy carries a
seed — reproducibility of retry schedules is what makes the fault-
injection tests (tests/test_resilience.py) bit-stable.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from galah_tpu.utils import timing

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx):
# this module spawns attempt threads but owns no locked shared state —
# run_with_deadline's result box is per-call and handed off through a
# threading.Event.
GUARDED_BY = {}
LOCK_ORDER = []


class TransientDispatchError(RuntimeError):
    """A dispatch fault worth retrying (injected or classified)."""


class DeviceLostError(RuntimeError):
    """The accelerator went away mid-run (tunnel drop, preemption)."""


class GarbageResultError(RuntimeError):
    """A dispatch returned a result that fails shape/range validation."""


class DeadlineExceeded(TimeoutError):
    """An attempt outlived its per-attempt deadline and was abandoned."""


#: Exception types retried by default. ValueError/KeyError and friends
#: are deterministic — retrying them only delays the real traceback.
RETRYABLE_TYPES: Tuple[Type[BaseException], ...] = (
    OSError,
    ConnectionError,
    TimeoutError,          # includes DeadlineExceeded
    TransientDispatchError,
    DeviceLostError,
    GarbageResultError,
)

#: Exception type NAMES retried by default — jax runtime errors are
#: matched by name so this module never imports jaxlib.
RETRYABLE_NAMES = frozenset(
    {"XlaRuntimeError", "InternalError", "UnavailableError"})


def is_retryable(exc: BaseException) -> bool:
    if isinstance(exc, FileNotFoundError):
        return False  # a missing path will not appear on retry
    return (isinstance(exc, RETRYABLE_TYPES)
            or type(exc).__name__ in RETRYABLE_NAMES)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule + deadlines for one class of dispatch.

    delay(attempt) = min(max_delay, base_delay * 2^attempt), scaled by
    a deterministic jitter factor in [1 - jitter, 1 + jitter]. The
    per-attempt deadline bounds a single hang; total_budget bounds the
    whole retry loop (sleeps included) so N faulty attempts can never
    exceed the caller's time box.
    """

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.5
    attempt_deadline: Optional[float] = None
    total_budget: Optional[float] = None
    seed: Optional[int] = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(
                f"jitter must be in [0, 1], got {self.jitter}")

    @classmethod
    def from_env(cls, prefix: str = "GALAH_RETRY",
                 defaults: Optional[dict] = None,
                 **overrides) -> "RetryPolicy":
        """Policy with env-var overrides: <prefix>_MAX_ATTEMPTS,
        _BASE_DELAY, _MAX_DELAY, _JITTER, _ATTEMPT_DEADLINE,
        _TOTAL_BUDGET, _SEED. `defaults` seeds values the env may
        override (a caller's site-specific baseline, e.g. the IO
        policy's 0.1 s base delay); explicit keyword overrides win
        over both."""
        spec = {
            "max_attempts": int,
            "base_delay": float,
            "max_delay": float,
            "jitter": float,
            "attempt_deadline": float,
            "total_budget": float,
            "seed": int,
        }
        kwargs = dict(defaults or {})
        for name, conv in spec.items():
            raw = os.environ.get(f"{prefix}_{name.upper()}")
            if raw is not None and raw != "":
                kwargs[name] = conv(raw)
        kwargs.update(overrides)
        return cls(**kwargs)

    def delay(self, attempt: int, site: str = "") -> float:
        """Backoff sleep after failed attempt `attempt` (0-based)."""
        d = min(self.max_delay, self.base_delay * (2.0 ** attempt))
        if self.jitter:
            if self.seed is not None:
                # string seeding is hash-randomization-proof (seeded
                # via sha512 of the bytes), so schedules reproduce
                # across processes
                u = random.Random(
                    f"{self.seed}:{site}:{attempt}").random()
            else:
                # an unseeded policy asked for nondeterministic jitter:
                # this randomizes retry SCHEDULING, never numerics
                u = random.random()  # galah-lint: ignore[GL904]
            d *= 1.0 - self.jitter + 2.0 * self.jitter * u
        return d


def run_with_deadline(fn: Callable[[], T],
                      deadline: Optional[float]) -> T:
    """Run fn, abandoning it (DeadlineExceeded) after `deadline` seconds.

    The attempt runs on a daemon worker thread; on expiry the thread is
    ABANDONED, not cancelled — a dispatch wedged inside a native
    extension cannot be interrupted from Python, and abandoning it is
    exactly what the bench watchdog does from outside the process. The
    leaked thread holds only the attempt's closure; callers retry or
    fall back on a fresh one.
    """
    if deadline is None:
        return fn()
    box: dict = {}
    done = threading.Event()
    # adopt the spawning thread's stage context so any telemetry the
    # attempt emits (dispatch counts, retries) attributes to the stage
    # that issued the dispatch, not to a bare worker thread (GL804)
    token = timing.stage_token()

    def target() -> None:
        try:
            with timing.adopt(token):
                box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=target, daemon=True,
                         name="galah-attempt")
    t.start()
    if not done.wait(deadline):
        raise DeadlineExceeded(
            f"dispatch attempt exceeded {deadline:.1f}s deadline")
    if "error" in box:
        raise box["error"]
    return box["value"]


def call_with_retry(
    fn: Callable[[], T],
    policy: RetryPolicy,
    site: str = "",
    classify: Callable[[BaseException], bool] = is_retryable,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """fn() with the policy's retry schedule.

    Retries only exceptions `classify` accepts; re-raises the last
    error once attempts, the total budget, or the classifier say stop.
    `on_retry(attempt, exc)` fires before each backoff sleep (the
    dispatch supervisor counts retries into the stage report there).
    """
    t0 = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return run_with_deadline(fn, policy.attempt_deadline)
        except BaseException as e:  # noqa: BLE001 - filtered below
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            last = e
            if not classify(e) or attempt == policy.max_attempts - 1:
                raise
            d = policy.delay(attempt, site)
            if (policy.total_budget is not None
                    and time.monotonic() - t0 + d > policy.total_budget):
                logger.warning(
                    # the budget is config, not a measurement; retry
                    # counts land in the registry via on_retry
                    # galah-lint: ignore[GL702]
                    "%s: retry budget %.1fs exhausted after attempt "
                    "%d", site or "dispatch", policy.total_budget,
                    attempt + 1)
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            logger.warning(
                # the delay is the policy's schedule, not a measured
                # duration
                # galah-lint: ignore[GL702]
                "%s: attempt %d/%d failed (%s: %s); retrying in "
                "%.2fs", site or "dispatch", attempt + 1,
                policy.max_attempts, type(e).__name__, e, d)
            sleep(d)
    raise last if last is not None else RuntimeError("unreachable")
