"""Bad-input quarantine: isolate unreadable genomes instead of dying.

Real MAG collections carry truncated downloads, empty files, and
half-written FASTA — today one of them kills an hours-long run that
cluster/checkpoint.py then has to replay. Under ``--on-bad-genome skip``
the pipeline preflights every genome before the first sketch dispatch,
moves the unreadable ones into a quarantine manifest written next to
the outputs, and clusters the rest.

Determinism contract: the surviving genome list is IDENTICAL on every
host — each host validates only its strided shard (IO scales with
hosts), then the bad-genome masks are OR-combined through one
collective, so the post-quarantine list (and therefore the checkpoint
fingerprint, cluster/checkpoint.py run_fingerprint) agrees everywhere.
A run that quarantines a genome clusters the remaining genomes exactly
as a run that never saw it (pinned by tests/test_quarantine.py).

Transient IO errors are NOT quarantine-worthy: io/fasta.py retries
those with backoff first; only a genome that stays unreadable after
the retry budget lands here.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Callable, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

MANIFEST_NAME = "quarantine.json"

ON_BAD_GENOME_CHOICES = ("error", "skip")


@dataclasses.dataclass(frozen=True)
class QuarantineRecord:
    path: str
    reason: str      # "missing" | "empty" | "corrupt" | "io-error"
    detail: str = ""
    stage: str = "preflight"


class QuarantineManifest:
    """The run's quarantined genomes; serializes to quarantine.json."""

    def __init__(self) -> None:
        self._records: List[QuarantineRecord] = []

    def __len__(self) -> int:
        return len(self._records)

    def add(self, path: str, reason: str, detail: str = "",
            stage: str = "preflight") -> None:
        self._records.append(QuarantineRecord(
            path=path, reason=reason, detail=detail, stage=stage))
        logger.warning("Quarantined genome %s (%s%s)", path, reason,
                       f": {detail}" if detail else "")

    def records(self) -> List[QuarantineRecord]:
        return list(self._records)

    def paths(self) -> set:
        return {r.path for r in self._records}

    def write(self, directory: str) -> str:
        """Write the manifest into `directory`; returns the file path."""
        from galah_tpu.io import atomic

        out = os.path.join(directory or ".", MANIFEST_NAME)
        atomic.write_json(out, {
            "version": 1,
            "quarantined": [dataclasses.asdict(r)
                            for r in self._records],
        }, indent=2, site="io.atomic.write[quarantine]")
        logger.warning("Wrote quarantine manifest (%d genomes) to %s",
                       len(self._records), out)
        return out

    @classmethod
    def load(cls, path: str) -> "QuarantineManifest":
        with open(path) as f:
            data = json.load(f)
        m = cls()
        for rec in data.get("quarantined", []):
            m._records.append(QuarantineRecord(**rec))
        return m


def validate_genome(path: str) -> Optional[Tuple[str, str]]:
    """None when `path` parses as a FASTA genome, else (reason, detail).

    Runs the full ingestion path (stats only — no code array retained)
    so whatever would crash the sketch stage crashes here instead,
    with io/fasta.py's transient-IO retry already applied.
    """
    from galah_tpu.io.fasta import BadGenomeError, read_genome

    try:
        read_genome(path, with_codes=False)
        return None
    except FileNotFoundError as e:
        return "missing", str(e)
    except BadGenomeError as e:
        return e.reason, str(e)
    except OSError as e:  # persistent IO failure after retries
        return "io-error", f"{type(e).__name__}: {e}"


def preflight_quarantine(
    genome_paths: Sequence[str],
    manifest: Optional[QuarantineManifest] = None,
    validate: Callable[
        [str], Optional[Tuple[str, str]]] = validate_genome,
) -> Tuple[List[str], QuarantineManifest]:
    """Validate every genome; returns (kept paths, manifest).

    Multi-host: each host validates its strided shard, the bad masks
    are OR-exchanged, and every host removes the identical set —
    quality ordering, sketching, and the checkpoint fingerprint all see
    the same survivor list on every process.
    """
    import numpy as np

    from galah_tpu.parallel import distributed
    from galah_tpu.utils import timing

    manifest = manifest if manifest is not None else QuarantineManifest()
    unique = list(dict.fromkeys(genome_paths))
    n = len(unique)
    bad = np.zeros(n, dtype=np.uint8)
    reasons: dict = {}
    with timing.stage("preflight-genomes"):
        for i in distributed.host_shard(list(range(n))):
            verdict = validate(unique[i])
            if verdict is not None:
                bad[i] = 1
                reasons[i] = verdict
        if distributed.process_count() > 1:
            gathered = distributed.exchange("quarantine-mask", bad)
            bad = gathered.max(axis=0).astype(np.uint8)
    from galah_tpu.obs import events as obs_events

    for i in np.nonzero(bad)[0].tolist():
        reason, detail = reasons.get(
            i, ("corrupt", "flagged by a peer host"))
        manifest.add(unique[i], reason, detail)
        obs_events.record("quarantine", genome=unique[i],
                          reason=reason, detail=detail)
    timing.counter("quarantined-genomes", int(bad.sum()))
    dropped = {unique[i] for i in np.nonzero(bad)[0].tolist()}
    kept = [p for p in genome_paths if p not in dropped]
    return kept, manifest


def manifest_output_dir(
    cluster_definition: Optional[str] = None,
    representative_list: Optional[str] = None,
    checkpoint_dir: Optional[str] = None,
) -> str:
    """Where 'next to the outputs' is: the cluster-definition file's
    directory, else the representative list's, else the checkpoint
    dir, else the working directory."""
    for anchor in (cluster_definition, representative_list):
        if anchor:
            return os.path.dirname(os.path.abspath(anchor))
    if checkpoint_dir:
        return checkpoint_dir
    return "."
