"""Resilient dispatch layer: retry/backoff, fault injection, graceful
degradation, and bad-input quarantine.

See docs/resilience.md for the operator-facing knobs. Import surface:

  * policy — RetryPolicy, call_with_retry, the exception taxonomy
  * faults — deterministic FaultInjector (GALAH_FI env grammar)
  * dispatch — the DispatchSupervisor every hot-path dispatch routes
    through (retry + validate + demote-to-fallback)
  * quarantine — QuarantineManifest + the --on-bad-genome preflight
"""

from galah_tpu.resilience.policy import (  # noqa: F401
    DeadlineExceeded,
    DeviceLostError,
    GarbageResultError,
    RetryPolicy,
    TransientDispatchError,
    call_with_retry,
)
from galah_tpu.resilience.faults import (  # noqa: F401
    FaultInjector,
    FaultSpec,
)
from galah_tpu.resilience.dispatch import (  # noqa: F401
    DispatchSupervisor,
)
from galah_tpu.resilience.quarantine import (  # noqa: F401
    QuarantineManifest,
)
