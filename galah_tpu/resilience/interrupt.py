"""Cooperative preemption: stop at safe boundaries, resume later.

Preemptible TPU slices get SIGTERM with a short grace window; an
interactive operator sends SIGINT. Before this module the process just
died wherever it happened to be — mid-checkpoint-append, mid-report —
and the only crash-consistency story was the durable-write layer's
torn-tail recovery. This module adds the cooperative half: handlers
that REQUEST a stop, and checkpoints/engine loops that honor it at the
next safe boundary (a round edge or a completed checkpoint flush),
so the common preemption leaves a clean checkpoint instead of relying
on recovery at all.

Protocol:

  * ``install()`` registers SIGTERM/SIGINT handlers. The first signal
    only sets a flag and records which signal arrived; a second signal
    means "now", and the process hard-exits with ``EXIT_PREEMPTED``
    immediately (the durable-write layer makes that survivable too).
  * long-running loops call ``check("boundary-name")`` right AFTER
    their state reaches disk; it raises ``PreemptionRequested`` when a
    stop is pending. The CLI catches it, drains obs/ledger/checkpoint
    writers via the normal finalize path, emits a structured
    ``preempted`` event, and exits with ``EXIT_PREEMPTED`` (75,
    EX_TEMPFAIL: "transient, retry me") so wrappers can distinguish
    preemption from failure.
  * ``note_resume()`` records that this run continued an interrupted
    one; ``snapshot()`` feeds the run report's ``preemption`` section.

CPython delivers signals only on the main thread, and worker threads
only ever read the stop flag through a ``threading.Event`` — so the
module needs no locks of its own.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

#: EX_TEMPFAIL — the documented "preempted, safe to retry" exit code.
EXIT_PREEMPTED = 75

_HANDLED_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptionRequested(Exception):
    """Raised at a safe boundary after a stop was requested.

    Carries which boundary honored the request — the run report and
    the preempted event record it so a resume (and the chaos harness)
    can see exactly where the run stopped."""

    def __init__(self, boundary: str, signame: str) -> None:
        super().__init__(
            f"preemption requested ({signame}), stopping at safe "
            f"boundary {boundary!r}")
        self.boundary = boundary
        self.signame = signame


_STOP = threading.Event()
_SIGNALS: List[str] = []      # arrival order, main thread only
_BOUNDARY: Optional[str] = None
_RESUMED_FROM: Optional[str] = None
_PRIOR_INTERRUPTIONS = 0
_PREV_HANDLERS: Dict[int, Any] = {}
# Last-gasp flush hooks for the second-signal os._exit path (the
# normal first-signal path drains through obs.finalize instead). Each
# hook must be signal-safe-ish: bounded, lock-light, exception-proof
# here regardless. obs.install_crash_hooks() registers the heartbeat
# flush; the trace file needs none (flushed per event by design).
_FLUSH_HOOKS: List[Any] = []
# Process groups of live fleet workers (pgid == worker pid via
# start_new_session). The supervisor's second-signal hard exit must
# not orphan them: the handler forwards SIGTERM to every registered
# group before os._exit. Main-thread-only like _SIGNALS — the fleet
# poll loop runs on the main thread.
_WORKER_GROUPS: List[int] = []


def register_flush(fn) -> None:
    """Register a callable run right before the second-signal hard
    exit (idempotent per callable)."""
    if fn not in _FLUSH_HOOKS:
        _FLUSH_HOOKS.append(fn)


def register_worker_group(pgid: int) -> None:
    """Track a live worker process group for signal forwarding."""
    if pgid not in _WORKER_GROUPS:
        _WORKER_GROUPS.append(pgid)


def unregister_worker_group(pgid: int) -> None:
    try:
        _WORKER_GROUPS.remove(pgid)
    except ValueError:
        pass


def forward_to_worker_groups(signum: int = signal.SIGTERM) -> None:
    """Forward ``signum`` to every registered worker process group;
    already-dead groups are skipped silently (signal-path safe)."""
    for pgid in list(_WORKER_GROUPS):
        try:
            os.killpg(pgid, signum)
        except (ProcessLookupError, PermissionError):
            pass
        except Exception:
            logger.debug("forward to pgid %d failed", pgid,
                         exc_info=True)


def _handler(signum, frame) -> None:
    signame = signal.Signals(signum).name
    if _STOP.is_set():
        # Second signal: the operator/scheduler is done waiting. Die
        # now with the preemption code; durable artifacts are already
        # crash-consistent by construction — the flush hooks just add
        # one last heartbeat/telemetry record when they can.
        logger.error("second signal %s: exiting immediately (%d)",
                     signame, EXIT_PREEMPTED)
        # Workers first: a supervisor that dies here must not leave
        # its fleet running against checkpoints it no longer owns.
        forward_to_worker_groups(signal.SIGTERM)
        for fn in list(_FLUSH_HOOKS):
            try:
                fn()
            except Exception:
                logger.debug("flush hook failed", exc_info=True)
        os._exit(EXIT_PREEMPTED)
    _SIGNALS.append(signame)
    _STOP.set()
    logger.warning(
        "%s received: will stop at the next safe boundary "
        "(send again to exit immediately)", signame)


def install() -> None:
    """Register the cooperative handlers (idempotent; main thread)."""
    for sig in _HANDLED_SIGNALS:
        prev = signal.signal(sig, _handler)
        if sig not in _PREV_HANDLERS:
            _PREV_HANDLERS[sig] = prev


def uninstall() -> None:
    """Restore whatever handlers install() displaced."""
    for sig, prev in _PREV_HANDLERS.items():
        signal.signal(sig, prev)
    _PREV_HANDLERS.clear()


def reset() -> None:
    """Clear all interruption state (tests; between CLI invocations)."""
    global _BOUNDARY, _RESUMED_FROM, _PRIOR_INTERRUPTIONS
    _STOP.clear()
    _SIGNALS.clear()
    _WORKER_GROUPS.clear()
    _BOUNDARY = None
    _RESUMED_FROM = None
    _PRIOR_INTERRUPTIONS = 0


def request_stop(signame: str = "REQUESTED") -> None:
    """Programmatic stop request (tests; chaos harness)."""
    _SIGNALS.append(signame)
    _STOP.set()


def stop_requested() -> bool:
    return _STOP.is_set()


def check(boundary: str) -> None:
    """Honor a pending stop request: call right after the state that
    makes `boundary` safe has reached disk."""
    global _BOUNDARY
    if not _STOP.is_set():
        return
    if _BOUNDARY is None:
        _BOUNDARY = boundary
    signame = _SIGNALS[-1] if _SIGNALS else "REQUESTED"
    raise PreemptionRequested(boundary, signame)


def note_resume(resumed_from: str, prior_interruptions: int) -> None:
    """Record that this run continues an interrupted one."""
    global _RESUMED_FROM, _PRIOR_INTERRUPTIONS
    _RESUMED_FROM = resumed_from
    _PRIOR_INTERRUPTIONS = prior_interruptions
    logger.info("resuming from %s (%d prior interruption(s))",
                resumed_from, prior_interruptions)


def snapshot() -> Dict[str, Any]:
    """The run report's ``preemption`` section."""
    return {
        "stop_requested": _STOP.is_set(),
        "signals": list(_SIGNALS),
        "boundary": _BOUNDARY,
        "resumed_from": _RESUMED_FROM,
        "prior_interruptions": _PRIOR_INTERRUPTIONS,
    }
