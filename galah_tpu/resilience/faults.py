"""Deterministic fault injector for the dispatch layer.

Every retry/timeout/demotion path in this pipeline exists because real
TPU tunnels wedge, drop, and lie (VERDICT.md round 5) — but none of
those paths can wait for hardware to misbehave to be tested. The
injector plants faults at named dispatch sites so the full
retry -> deadline -> demote -> quarantine machinery is exercised on CPU,
seeded and bit-reproducible.

Fault classes (the failure signatures observed on hardware):

  * ``raise``       — TransientDispatchError before the dispatch runs
  * ``device-lost`` — DeviceLostError, the tunnel-drop signature
  * ``hang``        — sleep `hang_seconds` before dispatching (the
                      per-attempt deadline is what must catch this)
  * ``garbage``     — let the dispatch run, then truncate its result so
                      shape validation must reject it

Filesystem fault classes, consulted by io/atomic.py at ``io.atomic.*``
sites (the durable-write layer is where crash consistency must be
proven, so that is where the faults live):

  * ``enospc``      — OSError(ENOSPC) before the write starts
  * ``eio``         — OSError(EIO) before the write starts
  * ``torn-write``  — half the payload reaches disk, then the write
                      fails (the readers' checksum/recovery paths must
                      treat the debris as absent, never as data)
  * ``slow-io``     — sleep `hang_seconds` before the write

And the chaos primitive, firing at ANY registered site (dispatch or
filesystem):

  * ``kill``        — ``os._exit(KILL_EXIT_CODE)``: the process dies
                      mid-operation with no cleanup, unwinding, or
                      flushing — a preemption/SIGKILL stand-in the
                      chaos harness (scripts/chaos_run.py) uses to
                      prove resume-to-identical-clusters

Configuration is programmatic (`install`) or env-driven via GALAH_FI:

    GALAH_FI="site=dispatch.ani;kind=raise;prob=0.3;seed=7;max=2"

Multiple specs are separated by "|". `site` prefix-matches the dispatch
site name ("" matches everything); `max` caps how many faults a spec
fires (so "fail twice then recover" is expressible); `seed` makes the
per-call coin flips reproducible.
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
import random
import threading
import time
from typing import List, Optional, Sequence

from galah_tpu.resilience.policy import (
    DeviceLostError,
    TransientDispatchError,
)

logger = logging.getLogger(__name__)

#: Kinds that fire inside io/atomic.py (plus "kill", which fires
#: everywhere).
FS_FAULT_KINDS = ("enospc", "eio", "torn-write", "slow-io")

FAULT_KINDS = (("raise", "device-lost", "hang", "garbage", "kill")
               + FS_FAULT_KINDS)

#: Exit status used by the "kill" kind — the classic SIGKILL status, so
#: harnesses treat an injected kill exactly like a real preemption.
KILL_EXIT_CODE = 137

#: Kinds eligible at dispatch sites (before_dispatch).
_DISPATCH_KINDS = frozenset({"raise", "device-lost", "hang", "kill"})
#: Kinds eligible at filesystem sites (io/atomic.py).
_FS_KINDS = frozenset(FS_FAULT_KINDS) | {"kill"}

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx):
# fault draws arrive from prefetch worker threads; the fired counts
# and the install/env-discovery globals each stay under their lock.
GUARDED_BY = {
    "FaultInjector._fired": "FaultInjector._lock",
    "_INSTALLED": "_LOCK",
    "_ENV_CHECKED": "_LOCK",
}
LOCK_ORDER = ["_LOCK"]


@dataclasses.dataclass
class FaultSpec:
    """One fault source: where, how often, what, for how long."""

    site: str = ""               # prefix match against dispatch sites
    kind: str = "raise"
    prob: float = 1.0
    seed: int = 0
    max_faults: Optional[int] = None
    hang_seconds: float = 30.0

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; "
                f"choices: {FAULT_KINDS}")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(
                f"fault prob must be in [0, 1], got {self.prob}")


def parse_spec(text: str) -> List[FaultSpec]:
    """Parse the GALAH_FI grammar: ';'-separated key=value fields,
    '|'-separated specs."""
    specs: List[FaultSpec] = []
    for chunk in text.split("|"):
        chunk = chunk.strip()
        if not chunk:
            continue
        kwargs: dict = {}
        for field in chunk.split(";"):
            field = field.strip()
            if not field:
                continue
            if "=" not in field:
                raise ValueError(
                    f"bad GALAH_FI field {field!r} (want key=value)")
            key, value = field.split("=", 1)
            key = key.strip()
            value = value.strip()
            if key == "site":
                kwargs["site"] = value
            elif key == "kind":
                kwargs["kind"] = value
            elif key == "prob":
                kwargs["prob"] = float(value)
            elif key == "seed":
                kwargs["seed"] = int(value)
            elif key == "max":
                kwargs["max_faults"] = int(value)
            elif key == "hang":
                kwargs["hang_seconds"] = float(value)
            else:
                raise ValueError(f"unknown GALAH_FI key {key!r}")
        specs.append(FaultSpec(**kwargs))
    return specs


class FaultInjector:
    """Seeded fault source consulted by the dispatch supervisor.

    Thread-safe: dispatch sites fire from prefetch worker threads too.
    Each spec draws from its own seeded RNG, so whether call k faults
    depends only on (spec seed, how many matching calls preceded it) —
    not on wall clock or thread interleaving of OTHER sites.
    """

    def __init__(self, specs: Sequence[FaultSpec],
                 sleep=time.sleep) -> None:
        self._specs = list(specs)
        self._rngs = [random.Random(f"galah-fi:{s.seed}:{s.site}")
                      for s in self._specs]
        self._fired = [0] * len(self._specs)
        self._sleep = sleep
        self._lock = threading.Lock()

    def fired(self) -> int:
        """Total faults injected so far (all specs)."""
        with self._lock:
            return sum(self._fired)

    def _draw(self, site: str, kinds) -> Optional[FaultSpec]:
        """One seeded coin flip per matching spec of an eligible kind.

        Specs of other kinds are skipped WITHOUT advancing their RNG,
        so a spec's fault schedule depends only on the sequence of
        sites where it was eligible — the property the chaos harness's
        seed sweep relies on."""
        with self._lock:
            for n, spec in enumerate(self._specs):
                if spec.kind not in kinds:
                    continue
                if not site.startswith(spec.site):
                    continue
                if (spec.max_faults is not None
                        and self._fired[n] >= spec.max_faults):
                    continue
                if self._rngs[n].random() < spec.prob:
                    self._fired[n] += 1
                    return spec
        return None

    @staticmethod
    def _kill(site: str) -> None:
        logger.error("fault injector: KILL at %s (exit %d)", site,
                     KILL_EXIT_CODE)
        # os._exit on purpose: no atexit, no finally blocks, no stream
        # flushing — the whole point is to die the way a preemption
        # does, mid-operation.
        os._exit(KILL_EXIT_CODE)

    def before_dispatch(self, site: str) -> None:
        """Called before the real dispatch: may raise, stall, or die."""
        spec = self._draw(site, _DISPATCH_KINDS)
        if spec is None:
            return
        logger.warning("fault injector: %s at %s", spec.kind, site)
        if spec.kind == "kill":
            self._kill(site)
        if spec.kind == "raise":
            raise TransientDispatchError(
                f"injected transient fault at {site}")
        if spec.kind == "device-lost":
            raise DeviceLostError(f"injected device loss at {site}")
        if spec.kind == "hang":
            self._sleep(spec.hang_seconds)

    def filesystem(self, site: str) -> Optional[str]:
        """Called by io/atomic.py before a durable write: may raise
        OSError, stall, die, or ask the writer to tear its own write.

        Returns "torn-write" when the writer should half-write and
        fail (only the writer knows its record layout), else None.
        """
        spec = self._draw(site, _FS_KINDS)
        if spec is None:
            return None
        logger.warning("fault injector: %s at %s", spec.kind, site)
        if spec.kind == "kill":
            self._kill(site)
        if spec.kind == "enospc":
            raise OSError(errno.ENOSPC,
                          f"injected ENOSPC at {site}")
        if spec.kind == "eio":
            raise OSError(errno.EIO, f"injected EIO at {site}")
        if spec.kind == "slow-io":
            self._sleep(spec.hang_seconds)
            return None
        return "torn-write"

    def corrupt(self, site: str, result):
        """Called on the real dispatch's result: may mangle it.

        Only "garbage" specs fire here, from their own draw — a spec
        that raised in before_dispatch never also corrupts.
        """
        with self._lock:
            for n, spec in enumerate(self._specs):
                if spec.kind != "garbage":
                    continue
                if not site.startswith(spec.site):
                    continue
                if (spec.max_faults is not None
                        and self._fired[n] >= spec.max_faults):
                    continue
                if self._rngs[n].random() < spec.prob:
                    self._fired[n] += 1
                    logger.warning(
                        "fault injector: garbage at %s", site)
                    try:
                        return result[:-1]  # wrong length
                    except TypeError:
                        return None
        return result


_INSTALLED: Optional[FaultInjector] = None
_ENV_CHECKED = False
_LOCK = threading.Lock()


def install(injector: Optional[FaultInjector]) -> None:
    """Set (or with None, clear) the process-wide injector."""
    global _INSTALLED, _ENV_CHECKED
    with _LOCK:
        _INSTALLED = injector
        _ENV_CHECKED = True  # explicit install wins over env


def reset() -> None:
    """Drop any installed injector and re-arm env discovery."""
    global _INSTALLED, _ENV_CHECKED
    with _LOCK:
        _INSTALLED = None
        _ENV_CHECKED = False


def get_injector() -> Optional[FaultInjector]:
    """The installed injector, else one built from GALAH_FI, else None."""
    global _INSTALLED, _ENV_CHECKED
    with _LOCK:
        if not _ENV_CHECKED:
            _ENV_CHECKED = True
            text = os.environ.get("GALAH_FI")
            if text:
                _INSTALLED = FaultInjector(parse_spec(text))
                logger.warning(
                    "fault injection ACTIVE from GALAH_FI=%r", text)
        return _INSTALLED
