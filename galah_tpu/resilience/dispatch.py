"""Resilient dispatch: retry, validate, and degrade instead of dying.

The one wrapper every hot-path device dispatch goes through
(cluster/engine.py ANI batches, the backends' batched sketch dispatches,
parallel/distributed.py collectives). Semantics per call:

  1. consult the fault injector (resilience/faults.py) — testability;
  2. run the primary under the retry policy (backoff + per-attempt
     deadline + total budget, resilience/policy.py);
  3. validate the result (garbage-shape returns are a fault class the
     round-5 hardware campaigns actually produced) — a failed
     validation retries like any transient;
  4. on exhausted retries with a fallback available, DEMOTE the site:
     log it, count it into the stage report (``demoted[<site>]``), run
     the fallback, and route every later call at that site straight to
     the fallback — one wedged tunnel must cost seconds, not the run.

Fallbacks are the smaller-blast-radius twin of each dispatch (per-item
CPU sketching for the batched sketch dispatch, a per-pair loop for the
batched ANI call); they run OUTSIDE fault injection so a test that
wedges the primary proves the run completes on the fallback.

Retries are visible in the stage report as ``retries[<site>]``; the
demotion registry is queryable (`demotions()`) and is appended to the
quarantine/stage summary by the CLI.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Callable, Dict, List, Optional, TypeVar

from galah_tpu.obs import events as obs_events
from galah_tpu.obs import metrics as obs_metrics
from galah_tpu.resilience import faults
from galah_tpu.resilience.policy import (
    GarbageResultError,
    RetryPolicy,
    call_with_retry,
)
from galah_tpu.utils import timing

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx).
# _demote deliberately emits telemetry AFTER releasing the lock —
# obs/timing take their own locks and must not nest inside this one.
GUARDED_BY = {
    "DispatchSupervisor._demoted": "DispatchSupervisor._lock",
}
LOCK_ORDER = ["DispatchSupervisor._lock"]


@dataclasses.dataclass(frozen=True)
class Demotion:
    """One site's fall from device dispatch to its CPU fallback."""

    site: str
    reason: str


class DispatchSupervisor:
    """Per-process retry/demotion state for named dispatch sites."""

    def __init__(self, policy: Optional[RetryPolicy] = None) -> None:
        self.policy = policy or RetryPolicy.from_env()
        self._demoted: Dict[str, Demotion] = {}
        self._lock = threading.Lock()

    def demotions(self) -> List[Demotion]:
        with self._lock:
            return list(self._demoted.values())

    def is_demoted(self, site: str) -> bool:
        with self._lock:
            return site in self._demoted

    def _demote(self, site: str, exc: BaseException) -> None:
        with self._lock:
            if site in self._demoted:
                return
            self._demoted[site] = Demotion(
                site=site,
                reason=f"{type(exc).__name__}: {exc}")
        timing.counter(f"demoted[{site}]", 1)
        obs_metrics.counter(
            "dispatch.demotions",
            help="Dispatch sites demoted to their CPU fallback").inc()
        obs_events.record("demotion", site=site,
                          reason=f"{type(exc).__name__}: {exc}")
        logger.error(
            "%s: persistent dispatch failure (%s: %s); demoting to "
            "the fallback path for the rest of the run",
            site, type(exc).__name__, exc)

    def run(
        self,
        site: str,
        primary: Callable[[], T],
        fallback: Optional[Callable[[], T]] = None,
        validate: Optional[Callable[[T], None]] = None,
        policy: Optional[RetryPolicy] = None,
    ) -> T:
        """One guarded dispatch at `site`. See the module docstring."""
        if fallback is not None and self.is_demoted(site):
            return fallback()
        pol = policy or self.policy
        injector = faults.get_injector()

        def attempt() -> T:
            if injector is not None:
                injector.before_dispatch(site)
            out = primary()
            if injector is not None:
                out = injector.corrupt(site, out)
            if validate is not None:
                validate(out)
            return out

        def on_retry(attempt_n: int, exc: BaseException) -> None:
            timing.counter(f"retries[{site}]", 1)
            obs_metrics.counter(
                "dispatch.retries",
                help="Dispatch attempts retried after a transient "
                     "failure").inc()
            obs_events.record("retry", site=site, attempt=attempt_n,
                              error=f"{type(exc).__name__}: {exc}")

        try:
            return call_with_retry(attempt, pol, site=site,
                                   on_retry=on_retry)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 - demote-or-reraise
            if fallback is None:
                raise
            self._demote(site, e)
            return fallback()


def expect_len(n: int) -> Callable[[object], None]:
    """Validator: the dispatch must return exactly n results."""

    def check(out) -> None:
        try:
            got = len(out)  # type: ignore[arg-type]
        except TypeError:
            raise GarbageResultError(
                f"dispatch returned non-sequence {type(out).__name__}")
        if got != n:
            raise GarbageResultError(
                f"dispatch returned {got} results for {n} inputs")

    return check


def expect_ani_values(n: int) -> Callable[[object], None]:
    """Validator for ANI batches: n results, each None or a finite
    fraction in [0, 1] — out-of-range values are the garbage-return
    signature of a corrupted device result."""
    check_len = expect_len(n)

    def check(out) -> None:
        check_len(out)
        for v in out:  # type: ignore[union-attr]
            if v is None:
                continue
            f = float(v)
            if not 0.0 <= f <= 1.0:  # NaN fails both comparisons
                raise GarbageResultError(
                    f"dispatch returned out-of-range ANI {v!r}")

    return check


# Process-wide supervisor: call sites use these module-level helpers so
# demotion state and the retry policy are one per process, like the
# GLOBAL stage timer.
GLOBAL = DispatchSupervisor()


def run(site: str, primary: Callable[[], T],
        fallback: Optional[Callable[[], T]] = None,
        validate: Optional[Callable[[T], None]] = None,
        policy: Optional[RetryPolicy] = None) -> T:
    return GLOBAL.run(site, primary, fallback=fallback,
                      validate=validate, policy=policy)


def demotions() -> List[Demotion]:
    return GLOBAL.demotions()


def reset(policy: Optional[RetryPolicy] = None) -> None:
    """Fresh supervisor (tests; also re-reads the env policy)."""
    global GLOBAL
    GLOBAL = DispatchSupervisor(policy)
