"""Sparse pair-distance cache keyed by sorted genome-index pairs.

Equivalent of the reference's SortedPairGenomeDistanceCache
(reference: src/sorted_pair_genome_distance_cache.rs:5-58): a mapping
(i, j) -> Optional[ANI] where the key is always stored sorted ascending,
plus `transform_ids` to re-index a precluster subset into local 0..n ids.

Values are ANI fractions in [0, 1]; `None` records "computed but failed
the aligned-fraction gate" (distinct from absent = never computed).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

Key = Tuple[int, int]


def pair_key(i: int, j: int) -> Key:
    return (i, j) if i < j else (j, i)


class PairDistanceCache:
    def __init__(self) -> None:
        self._d: Dict[Key, Optional[float]] = {}

    def insert(self, key: Tuple[int, int], ani: Optional[float]) -> None:
        self._d[pair_key(*key)] = ani

    def get(self, key: Tuple[int, int]) -> Optional[float]:
        """Value for a computed pair; None if absent OR computed-but-None.

        Use `contains` to distinguish the two, as the reference does.
        """
        return self._d.get(pair_key(*key))

    def contains(self, key: Tuple[int, int]) -> bool:
        return pair_key(*key) in self._d

    def keys(self) -> Iterable[Key]:
        return self._d.keys()

    def items(self):
        return self._d.items()

    def __len__(self) -> int:
        return len(self._d)

    def __eq__(self, other) -> bool:
        return isinstance(other, PairDistanceCache) and self._d == other._d

    def __repr__(self) -> str:
        return f"PairDistanceCache({self._d!r})"

    def transform_ids(self, indices: Sequence[int]) -> "PairDistanceCache":
        """Re-key the subset `indices` into local ids 0..len(indices)-1.

        `indices` must be sorted ascending (precluster members are);
        mirrors reference src/sorted_pair_genome_distance_cache.rs:47-58.

        Cost: min(m^2/2 probes, one full-cache scan) — the greedy
        engine calls this once per precluster, and scanning the whole
        cache each time measured 22.7 s of a 40k-genome run (10k
        preclusters x 150k cached pairs); typical preclusters have a
        handful of members, so probing their own pairs wins by orders
        of magnitude, while near-duplicate mega-preclusters keep the
        scan path.
        """
        out = PairDistanceCache()
        m = len(indices)
        missing = object()
        if m * (m - 1) // 2 < len(self._d):
            for a in range(m):
                gi = indices[a]
                for b in range(a + 1, m):
                    v = self._d.get(pair_key(gi, indices[b]), missing)
                    if v is not missing:
                        out.insert((a, b), v)
            return out
        remap = {g: l for l, g in enumerate(indices)}
        for (i, j), v in self._d.items():
            if i in remap and j in remap:
                out.insert((remap[i], remap[j]), v)
        return out
