"""Two-stage greedy clustering engine.

Re-implements the reference's engine semantics exactly (reference:
src/clusterer.rs:14-125) with one structural change: every per-genome
candidate ANI set is evaluated as ONE batched backend call instead of the
reference's per-pair threads with `find_any` early exit. The greedy
decisions are identical — "is any candidate ANI >= threshold" does not
depend on which subset the early exit happened to compute — but here they
are deterministic, and the ANI cache is a superset of the reference's.

Semantics preserved:
  * genomes arrive pre-sorted by quality; rep selection scans them in
    order, so earlier (higher-quality) genomes become representatives
    (reference: src/clusterer.rs:164-223).
  * candidate reps for genome i = current reps with a precluster-cache
    hit against i (reference: src/clusterer.rs:167-177).
  * when precluster and cluster methods match, precluster ANIs are reused
    instead of recomputed (reference: src/clusterer.rs:29-33,180-186).
  * membership: each non-rep is assigned to the argmax-ANI rep over all
    cached/computed rep ANIs — NO threshold filter at this stage, ties
    to the lowest rep index (reference: src/clusterer.rs:371-403).
  * rep-phase ANIs carry into the membership phase via the shared cache
    (reference: src/clusterer.rs:160-162,211,321-334).
"""

from __future__ import annotations

import logging
import time
from typing import (TYPE_CHECKING, Dict, List, Optional, Sequence, Set,
                    Tuple)

if TYPE_CHECKING:
    from galah_tpu.cluster.checkpoint import ClusterCheckpoint

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster.cache import PairDistanceCache, pair_key
from galah_tpu.cluster.partition import partition_preclusters
from galah_tpu.resilience import interrupt
from galah_tpu.utils import timing

logger = logging.getLogger(__name__)

# GL10xx pipeline-discipline contract (analysis/pipeline_check.py):
# the overlapped dataflow consumes the precluster pair stream
# incrementally and must report how busy it kept each downstream stage
# (speculative fragment-ANI, eager greedy rounds).
PIPELINE_STAGE = {
    "streaming": ["_cluster_overlapped"],
    "occupancy_gauge": "workload.pipeline_occupancy",
}


DENSE_PRECLUSTER_CAP = 64

# Materialization sub-rounds per device-strategy window: each sub-round
# batches one new frontier rep per precluster segment against its later
# window neighbors, so this bounds the rep-chain depth a window resolves
# on device; deeper windows are conflict windows and finish on the
# host-order scan (greedy_select.FOLD_ITERS is kept at 2x this).
MAX_SUBROUNDS = 16

# Unique-genome cap per device-strategy backend dispatch: bounds the
# profile heap one chunk pins at once (see the batch() closure in
# _cluster_pending_rounds). Matches DENSE_PRECLUSTER_CAP, and stays
# under the ProfileStore's default LRU bound (128) so a chunk never
# thrashes its own working set.
ROUND_BATCH_GENOMES = 64

# Host-strategy speculative rep-scan batch width: genomes per window
# evaluated against all current reps in one backend call. Configurable
# via cluster(rep_scan_window=...) / --rep-scan-window; the waste it
# buys (ANIs computed but never consulted by a decision) is measured
# per run as the exact-ani-wasted counter in the stage report.
REP_SCAN_WINDOW = 128


def cluster(
    genomes: Sequence[str],
    preclusterer: PreclusterBackend,
    clusterer: ClusterBackend,
    checkpoint: Optional["ClusterCheckpoint"] = None,
    dense_precluster_cap: int = DENSE_PRECLUSTER_CAP,
    rep_scan_window: Optional[int] = None,
    rep_rounds: Optional[int] = None,
) -> List[List[int]]:
    """Cluster quality-ordered genome paths -> list of index clusters.

    Each cluster lists its representative first; clusters are ordered by
    precluster processing order (biggest precluster first) then by
    representative index — deterministic, unlike the reference's
    thread-completion order.

    With a `checkpoint` (cluster/checkpoint.py), the distance pass and
    each finished precluster persist to disk; an interrupted run resumes
    from the last completed precluster.

    Preclusters up to `dense_precluster_cap` members compute exact ANI
    for ALL their precluster-hit pairs in one batched dispatch before
    the greedy loop (every pair the loop could consult is a hit pair),
    so the sequential rep scan touches no device at all. The extra ANIs
    beyond what early exits would have needed are the same waste class
    as the reference's find_any computing an unpredictable candidate
    subset (reference: src/clusterer.rs:242-262) — traded here for one
    round trip per precluster instead of one per genome.

    Waste is measured, not assumed: the exact-ani-computed /
    exact-ani-wasted counters in the stage report count backend-computed
    pairs never read by any decision (exact-ani-wasted-rep /
    -membership / -warm split the total by the phase that paid for the
    speculation). On the 18-MAG abisko campaign (2026-07-30, fast mode,
    99% ANI) the windowed path computed 62 ANIs
    with 0 wasted — the membership argmax consults every (non-rep, rep)
    pair, consuming the speculation — while the dense-warm path computed
    153 with 91 unconsulted (59%), the price of one-dispatch-per-
    precluster. `rep_scan_window` (CLI --rep-scan-window) tunes the
    speculative width; tests/test_campaign_abisko18.py bounds the waste.

    Strategy: GALAH_TPU_GREEDY_STRATEGY pins the greedy scan to the
    round-based `device` path (K-genome rounds across ALL pending
    preclusters, one batched dispatch per round, jitted segmented
    selection — ops/greedy_select.py, `rep_rounds` / --rep-rounds sets
    K) or the per-precluster `host` scan; unset AUTO runs the device
    path and demotes to the host scan on failure
    (greedy-device-demoted). Decisions are bit-identical either way
    (docs/cluster_engine.md).
    """
    skip_clusterer = preclusterer.method_name() == clusterer.method_name()
    if skip_clusterer:
        logger.info(
            "Preclustering and clustering methods are the same, "
            "so reusing ANI values")

    # Workload fingerprint gauges: the perf ledger (obs/ledger.py) keys
    # cross-run comparison on them, so a run is only compared against
    # history with the same N and sketch K.
    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.obs import profile as obs_profile

    obs_metrics.gauge(
        "workload.n_genomes",
        help="Genomes in this clustering run").set(float(len(genomes)))
    sketch_k = getattr(preclusterer, "sketch_size", None)
    if sketch_k:
        obs_metrics.gauge(
            "workload.sketch_k",
            help="MinHash sketch size of the precluster backend").set(
            float(sketch_k))
    ingest_threads = getattr(preclusterer, "threads", None)
    if ingest_threads:
        from galah_tpu.ops.sketch_stream import ingest_depth

        obs_metrics.gauge(
            "workload.ingest_depth",
            help="Streaming ingest look-ahead depth "
                 "(GALAH_TPU_INGEST_DEPTH or max(2, threads))").set(
            float(ingest_depth(int(ingest_threads))))

    # Bucketed pair-pass entry: record whether the HLL cardinality
    # bands prune this run's lattice (the preclusterer routes itself;
    # the gauge keys the funnel and the perf-report narrative).
    from galah_tpu.ops.bucketing import (
        bucketing_engaged,
        resolve_hll_buckets,
    )

    hll_buckets = (bucketing_engaged(len(genomes))
                   and preclusterer.method_name() == "finch")
    obs_metrics.gauge(
        "workload.hll_buckets",
        help="1 when the HLL cardinality-bucketed precluster pass is "
             "engaged for this run (GALAH_TPU_HLL_BUCKETS)").set(
        float(hll_buckets))
    if hll_buckets:
        logger.info(
            "HLL cardinality bucketing engaged for the precluster "
            "pair pass (GALAH_TPU_HLL_BUCKETS=%s)",
            resolve_hll_buckets())

    pre_cache = checkpoint.load_distances() if checkpoint else None
    overlap_state = None
    if pre_cache is None:
        # overlapped end-to-end dataflow (docs/dataflow.md): the
        # streaming phase runs the pair pass, speculative fragment-ANI
        # and eager greedy rounds together, quiescing before any
        # durable write below
        overlap_state = _maybe_cluster_overlapped(
            genomes, preclusterer, clusterer, skip_clusterer,
            checkpoint, rep_rounds)
        if overlap_state is not None:
            pre_cache = overlap_state.pre_cache
            obs_profile.sample_memory("overlap-dataflow")
            if checkpoint:
                checkpoint.save_distances(pre_cache)
    if pre_cache is None:
        with timing.stage("precluster-distances"):
            pre_cache = preclusterer.distances(genomes)
        obs_profile.sample_memory("precluster-distances")
        if checkpoint:
            checkpoint.save_distances(pre_cache)
    # safe boundary: the distance pass (the single biggest recompute)
    # has just reached disk — a preemption here resumes past it
    interrupt.check("distances-saved")

    logger.info("Preclustering ..")
    with timing.stage("partition"):
        preclusters = partition_preclusters(len(genomes), pre_cache.keys())
    obs_profile.sample_memory("partition")
    logger.info("Found %d preclusters. The largest contained %d genomes",
                len(preclusters), len(preclusters[0]) if preclusters else 0)

    done = checkpoint.load_completed() if checkpoint else {}

    logger.info(
        "Finding representative genomes and assigning all genomes ..")
    all_clusters: List[List[int]] = []
    with timing.stage("greedy-cluster"):
        from galah_tpu.ops.greedy_select import resolve_greedy_strategy

        strategy, explicit = resolve_greedy_strategy()
        timing.counter(f"greedy-strategy-{strategy}", 1)
        from galah_tpu.ops.megakernel import resolve_megakernel

        mk_mode, _mk_explicit = resolve_megakernel()
        if mk_mode == "1" and strategy != "device":
            raise RuntimeError(
                "GALAH_TPU_MEGAKERNEL=1 requires the device greedy "
                f"strategy; GALAH_TPU_GREEDY_STRATEGY pins {strategy!r}"
                " — the fused slab fold only exists inside device "
                "rounds")
        pending = [(i, m) for i, m in enumerate(preclusters)
                   if i not in done]
        device_done: Optional[Dict[int, List[List[int]]]] = None
        if overlap_state is not None and pending:
            try:
                device_done = _finish_overlapped(
                    overlap_state, genomes, clusterer, pending,
                    skip_clusterer, checkpoint)
            except interrupt.PreemptionRequested:
                raise  # a stop request is never a demotion signal
            except Exception as e:  # noqa: BLE001 - AUTO demotes
                if _overlap_mode() == "1":
                    raise
                logger.warning(
                    "overlapped finish failed (%s: %s); falling back "
                    "to the host scan", type(e).__name__, e)
                timing.counter("overlap-demoted", 1)
                from galah_tpu.obs import events

                events.record("overlap-demoted",
                              error=f"{type(e).__name__}: {e}")
                device_done = None
        elif strategy == "device" and pending:
            try:
                device_done = _cluster_pending_rounds(
                    clusterer, genomes, pre_cache, pending,
                    skip_clusterer, checkpoint, rep_rounds)
            except interrupt.PreemptionRequested:
                raise  # a stop request is never a demotion signal
            except Exception as e:  # noqa: BLE001 - AUTO demotes
                if explicit:
                    raise
                logger.warning(
                    "device greedy selection failed (%s: %s); falling "
                    "back to the host scan", type(e).__name__, e)
                timing.counter("greedy-device-demoted", 1)
                from galah_tpu.obs import events

                events.record("greedy-demoted",
                              error=f"{type(e).__name__}: {e}")
                device_done = None
        if device_done is not None:
            for pc_index, global_clusters in sorted(
                    device_done.items()):
                if checkpoint:
                    checkpoint.save_precluster(
                        pc_index, global_clusters)
                done[pc_index] = global_clusters
            if checkpoint:
                checkpoint.clear_greedy_rounds()
        for pc_index, members in enumerate(preclusters):
            if pc_index in done:
                all_clusters.extend(done[pc_index])
                continue
            local_cache = pre_cache.transform_ids(members)
            local_genomes = [genomes[g] for g in members]
            warm_cache = None
            if (not skip_clusterer
                    and len(members) <= dense_precluster_cap):
                warm_cache = _warm_all_hit_pairs(
                    clusterer, local_cache, local_genomes)
            reps, ani_cache, computed, consulted = _find_representatives(
                clusterer, local_cache, local_genomes, skip_clusterer,
                warm_cache, rep_scan_window)
            n_rep_computed = len(computed)
            local_clusters = _find_memberships(
                clusterer, reps, local_cache, local_genomes, ani_cache,
                skip_clusterer, warm_cache, computed, consulted)
            # Speculative waste accounting: backend-computed pairs no
            # decision (rep scan or membership argmax) ever read —
            # covering both the windowed speculative batches and the
            # upfront dense-warm pass. The reference has the same waste
            # class via find_any computing an unpredictable candidate
            # subset (reference: src/clusterer.rs:242-262); here it is
            # measured and reported in the stage report, split by the
            # phase that paid for each pair.
            rep_keys = {pair_key(*p) for p in computed[:n_rep_computed]}
            mem_keys = {pair_key(*p)
                        for p in computed[n_rep_computed:]} - rep_keys
            warm_keys = (set(warm_cache.keys()) - rep_keys - mem_keys
                         if warm_cache is not None else set())
            computed_keys = rep_keys | mem_keys | warm_keys
            _emit_waste_counters(
                len(computed_keys),
                rep=len(rep_keys - consulted),
                membership=len(mem_keys - consulted),
                warm=len(warm_keys - consulted),
                label=f"precluster {pc_index}")
            global_clusters = [[members[i] for i in c]
                               for c in local_clusters]
            all_clusters.extend(global_clusters)
            if checkpoint:
                checkpoint.save_precluster(pc_index, global_clusters)
            # safe boundary: this precluster's clusters are durable —
            # a resume recomputes only the preclusters after it
            interrupt.check("precluster-saved")
    obs_profile.sample_memory("greedy-cluster")
    logger.info("Found %d clusters", len(all_clusters))
    return all_clusters


def _emit_waste_counters(n_computed: int, rep: int, membership: int,
                         warm: int, label: str) -> None:
    """Computed/wasted counters, the waste split by paying phase."""
    wasted = rep + membership + warm
    timing.counter("exact-ani-computed", n_computed)
    timing.counter("exact-ani-wasted", wasted)
    timing.counter("exact-ani-wasted-rep", rep)
    timing.counter("exact-ani-wasted-membership", membership)
    timing.counter("exact-ani-wasted-warm", warm)
    from galah_tpu.obs import metrics as obs_metrics

    obs_metrics.counter(
        "ani.exact_computed",
        help="Exact ANI pairs the backend computed",
        unit="pairs").inc(n_computed)
    obs_metrics.counter(
        "ani.exact_wasted",
        help="Backend-computed ANI pairs no greedy decision "
             "ever consulted (speculation waste)",
        unit="pairs").inc(wasted)
    if n_computed:
        logger.debug(
            "%s: %d exact ANIs computed, %d never consulted "
            "(%.1f%% waste; rep %d / membership %d / warm %d)",
            label, n_computed, wasted, 100.0 * wasted / n_computed,
            rep, membership, warm)


def _backend_ani_batch(
    clusterer: ClusterBackend,
    path_pairs: List[Tuple[str, str]],
) -> List[Optional[float]]:
    """One backend ANI batch, host-split on multi-host runs.

    Every process reaches this with the IDENTICAL pair list (the
    engine is deterministic and its caches are identical across
    hosts); the shared exchange (distributed.sharded_optional_floats)
    splits it with pairs OWNED BY their second endpoint's path hash —
    a genome's pairs against the (few, everywhere-profiled) reps land
    on one host, so per-host profiling stays near unique/P instead of
    every host touching every endpoint. A failing host propagates its
    error to every peer instead of stranding them in the collective.
    Single-process: a plain call.

    Both paths route the batched backend call through the dispatch
    supervisor (resilience/dispatch.py): transient device failures are
    retried with backoff, garbage-shaped or out-of-range results are
    rejected, and a persistently failing batch dispatch demotes this
    site to a per-pair fallback loop for the rest of the run — recorded
    in the stage report as ``demoted[dispatch.ani]``.
    """
    from galah_tpu.parallel import distributed

    n_proc = distributed.process_count()
    if n_proc <= 1 or len(path_pairs) < n_proc:
        return _guarded_ani_batch(clusterer, path_pairs)

    import zlib

    owners = [zlib.crc32(b.encode()) for _a, b in path_pairs]
    return distributed.sharded_optional_floats(
        len(path_pairs),
        lambda idxs: _guarded_ani_batch(
            clusterer, [path_pairs[k] for k in idxs]),
        owner=lambda k: owners[k])


def _guarded_ani_batch(
    clusterer: ClusterBackend,
    path_pairs: List[Tuple[str, str]],
) -> List[Optional[float]]:
    """The retry/validate/demote wrapper around one batched ANI call.

    The fallback computes each pair in its own single-pair batch — the
    smallest dispatch the backend exposes, so one poisoned batch (or a
    wedged batched kernel) degrades throughput instead of killing the
    run. Fallback results still flow through the batch validator.
    """
    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.resilience import dispatch as rdispatch

    def fallback() -> List[Optional[float]]:
        return [clusterer.calculate_ani_batch([p])[0]
                for p in path_pairs]

    obs_metrics.counter(
        "ani.batch_pairs",
        help="Genome pairs submitted to batched exact-ANI dispatches",
        unit="pairs").inc(len(path_pairs))
    with obs_metrics.histogram(
            "ani.batch_seconds",
            help="Wall-clock latency of one guarded batched exact-ANI "
                 "dispatch (retries and fallback included)",
            unit="s").time():
        return rdispatch.run(
            "dispatch.ani",
            lambda: clusterer.calculate_ani_batch(path_pairs),
            fallback=fallback,
            validate=rdispatch.expect_ani_values(len(path_pairs)))


def _batch_ani(
    clusterer: ClusterBackend,
    skip_clusterer: bool,
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
    pairs: Sequence[Tuple[int, int]],
    warm_cache: Optional[PairDistanceCache] = None,
    computed_log: Optional[List[Tuple[int, int]]] = None,
) -> List[Optional[float]]:
    """ANI for local index pairs: precluster reuse or batched backend call.

    With matching methods, a precluster-cache hit is authoritative (same
    algorithm, same parameters — reference: src/clusterer.rs:264-279);
    a `warm_cache` of upfront-computed exact ANIs is consulted next;
    only missing pairs go to the backend. Pairs that actually hit the
    backend (the only ones that cost compute) are appended to
    `computed_log` when given — the waste accounting's input.
    """
    out: List[Optional[float]] = [None] * len(pairs)
    to_compute: List[Tuple[int, Tuple[str, str]]] = []
    for n, (i, j) in enumerate(pairs):
        if skip_clusterer and pre_cache.contains((i, j)):
            out[n] = pre_cache.get((i, j))
        elif warm_cache is not None and warm_cache.contains((i, j)):
            out[n] = warm_cache.get((i, j))
        else:
            to_compute.append((n, (genomes[i], genomes[j])))
            if computed_log is not None:
                computed_log.append(pairs[n])
    if to_compute:
        anis = _backend_ani_batch(clusterer,
                                  [p for _, p in to_compute])
        for (n, _), ani in zip(to_compute, anis):
            out[n] = ani
    return out


def _warm_all_hit_pairs(
    clusterer: ClusterBackend,
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
) -> PairDistanceCache:
    """Exact ANI for every precluster-hit pair in ONE batched dispatch."""
    keys = sorted(pre_cache.keys())
    warm = PairDistanceCache()
    if keys:
        anis = _backend_ani_batch(
            clusterer,
            [(genomes[i], genomes[j]) for i, j in keys])
        for key, ani in zip(keys, anis):
            warm.insert(key, ani)
    return warm


def _greedy_digest(pending: List[Tuple[int, Sequence[int]]]) -> str:
    """Digest of the pending-precluster sequence a greedy-round
    checkpoint is valid for. A resume whose pending set differs (more
    preclusters finished, different partition, different genome list —
    the run fingerprint guards the rest) drops the round records
    instead of replaying pairs into a differently-shaped scan."""
    import hashlib
    import json

    ident = json.dumps([[pc, list(m)] for pc, m in pending])
    return hashlib.sha256(ident.encode()).hexdigest()


class _OverlapState:
    """What the overlapped streaming phase hands to the post-quiesce
    finish phase: the completed pair cache, the greedy decisions
    already made over the arrived prefix, and the shared batch/value
    closures so the membership pass reuses the same dedup + chunking
    + waste accounting (docs/dataflow.md)."""

    def __init__(self, n: int) -> None:
        self.pre_cache = PairDistanceCache()
        self.adj: Dict[int, List[int]] = {g: [] for g in range(n)}
        self.ani_cache = PairDistanceCache()
        self.computed: List[Tuple[int, int]] = []
        self.consulted: Set[Tuple[int, int]] = set()
        self.rep_order: List[int] = []
        self.rep_set: Set[int] = set()
        self.batch = None
        self.value = None
        self.eager_rounds = 0


class _MegaCtx:
    """Run-scoped megakernel strategy state (ops/megakernel.py).

    ``active`` drops to False when an AUTO run demotes — the rest of
    the run takes the per-window dense fold. ``dev_busy`` accumulates
    the device-dispatch bracket wall so the greedy stage's recorded
    service stays net of device time (the flow host-blame share keys
    off this split)."""

    def __init__(self, explicit: bool, cap: int, queue) -> None:
        self.explicit = explicit
        self.active = True
        self.cap = cap
        self.queue = queue
        self.dev_busy = 0.0


def _megakernel_ctx(stage_serial: bool = False) -> Optional[_MegaCtx]:
    """The megakernel context for one clustering run, or None when it
    should not engage. Callers are device-round engines; forced-mode
    ineligibility (host greedy strategy) is enforced at the strategy
    dispatch in cluster().

    AUTO engages only in the overlapped engine: that is the e2e path
    whose host round-trips the megakernel removes, and its eager-round
    cadence is already arrival-driven. The stage-serial engine keeps
    its round-per-window cadence under AUTO — one durable checkpoint
    record, one preemption boundary, and one backend-call pattern per
    round window is a contract resume tooling observes — and opts into
    slab-fused rounds only under an explicit GALAH_TPU_MEGAKERNEL=1
    (still durable and replayable, per slab)."""
    from galah_tpu.ops import device_queue
    from galah_tpu.ops.megakernel import resolve_megakernel

    mode, _explicit = resolve_megakernel()
    if mode == "0" or (stage_serial and mode != "1"):
        return None
    cap = device_queue.resolve_queue_cap()
    return _MegaCtx(mode == "1", cap, device_queue.PairQueue(cap))


def _grow_slab(seq, pos: int, width: int, adj: Dict[int, List[int]],
               cap: int, ready_limit: Optional[int] = None) -> List[int]:
    """Fuse up to megakernel.SLAB_WINDOWS consecutive round windows
    starting at ``pos`` into one slab, while the intra-slab hit-edge
    count stays within the queue capacity (the estimate counts every
    hit pair; the enqueued set — non-None values only — is a subset,
    so a fitting estimate can never overflow). Width invariance of the
    round machinery makes the slab's decisions bit-identical to its
    sequential windows. ``ready_limit`` (the overlapped engine's
    resolved-prefix frontier) stops growth at windows not yet final."""
    from galah_tpu.ops.megakernel import SLAB_WINDOWS

    n = len(seq)
    window = list(seq[pos:pos + width])
    # membership-test only (never iterated): hash order cannot leak
    slab_members = set(window)
    edges = sum(1 for g in window
                for t in adj[g] if t in slab_members) // 2
    k = 1
    while k < SLAB_WINDOWS:
        nstart = pos + len(window)
        if nstart >= n:
            break
        nend = min(nstart + width, n)
        if ready_limit is not None and ready_limit < nend:
            break
        nxt = list(seq[nstart:nend])
        nxt_members = set(nxt)
        grown = sum(1 for g in nxt for t in adj[g] if t in slab_members)
        grown += sum(1 for g in nxt for t in adj[g]
                     if t in nxt_members) // 2
        if edges + grown > cap:
            break
        edges += grown
        window += nxt
        slab_members |= nxt_members
        k += 1
    return window


def _megakernel_fold(mega: _MegaCtx, window: List[int],
                     win_pos: Dict[int, int],
                     adj: Dict[int, List[int]], ext, value, thr: float,
                     np):
    """Queue-fed slab fold: enqueue the slab's materialized hit edges
    into the on-device pair queue and run the fused fold program
    (ops/megakernel.slab_select) in place of one dense window fold per
    round window. Returns ``(rep_flags, converged)``, or
    ``(None, False)`` when the slab spilled (queue capacity) or an
    AUTO run demoted — the caller then takes the exact dense path."""
    from galah_tpu.obs import flow as obs_flow
    from galah_tpu.ops import megakernel as mk

    ei: List[int] = []
    ej: List[int] = []
    ev: List[float] = []
    for wi, g in enumerate(window):
        for t in adj[g]:
            ti = win_pos.get(t)
            if ti is None or ti <= wi:
                continue
            v = value(g, t)
            if v is None:
                continue
            ei.append(wi)
            ej.append(ti)
            ev.append(v)
    if len(ei) > mega.cap:
        timing.counter("megakernel-overflow-spills", 1)
        return None, False
    try:
        with obs_flow.blocked("greedy", "device-dispatch") as bdev:
            rep, converged = mk.slab_select(
                mega.queue, np.asarray(ei, dtype=np.int32),
                np.asarray(ej, dtype=np.int32),
                np.asarray(ev, dtype=np.float64),
                np.asarray(ext, dtype=bool), thr)
        mega.dev_busy += bdev.seconds
    except interrupt.PreemptionRequested:
        raise  # a stop request is never a demotion signal
    except Exception as e:  # noqa: BLE001 - AUTO demotes
        if mega.explicit:
            raise
        _demote_megakernel(mega, f"{type(e).__name__}: {e}")
        return None, False
    if rep is None:
        timing.counter("megakernel-overflow-spills", 1)
        return None, False
    timing.counter("megakernel-slab-folds", 1)
    return rep, converged


def _demote_megakernel(mega: _MegaCtx, error: str) -> None:
    """AUTO demotion: the rest of the run takes the per-window dense
    fold; the demotion is counted and event-logged like the overlap
    and greedy-strategy demotions."""
    logger.warning(
        "megakernel slab fold failed (%s); demoting to the per-window "
        "dense fold for this run", error)
    timing.counter("megakernel-demoted", 1)
    from galah_tpu.obs import events

    events.record("megakernel-demoted", error=error)
    mega.active = False


def _overlap_mode() -> str:
    from galah_tpu.config import env_value

    mode = (env_value("GALAH_TPU_OVERLAP") or "auto").strip().lower()
    if mode not in ("auto", "0", "1"):
        logger.warning("ignoring malformed GALAH_TPU_OVERLAP=%r "
                       "(want auto/0/1)", mode)
        return "auto"
    return mode


def _overlap_depth() -> int:
    from galah_tpu.config import env_value

    try:
        return max(1, int(env_value("GALAH_TPU_OVERLAP_DEPTH") or 512))
    except ValueError:
        logger.warning("ignoring malformed GALAH_TPU_OVERLAP_DEPTH")
        return 512


def _maybe_cluster_overlapped(
    genomes: Sequence[str],
    preclusterer: PreclusterBackend,
    clusterer: ClusterBackend,
    skip_clusterer: bool,
    checkpoint: Optional["ClusterCheckpoint"],
    rep_rounds: Optional[int],
) -> Optional[_OverlapState]:
    """Run the overlapped end-to-end dataflow when it is engaged,
    returning its state, or None for the stage-serial engine.

    Engagement (GALAH_TPU_OVERLAP=auto/1) requires a fresh run — no
    checkpointed distances or completed preclusters; a resume always
    takes the stage-serial path, where the saved distance pass and the
    greedy-round replay make the recompute free — plus a preclusterer
    exposing `distances_streamed` that accepts the workload, and the
    device greedy strategy (the eager rounds ARE device rounds).
    Forced mode (=1) propagates ineligibility of the preclusterer/
    strategy and any runtime failure; auto falls back to the
    stage-serial engine from scratch (sketches are disk-cached, so the
    retried prologue is cheap).
    """
    mode = _overlap_mode()
    if mode == "0":
        return None
    forced = mode == "1"
    if checkpoint and checkpoint.load_completed():
        # a resume is stage-serial by design (see docstring), even
        # when forced — this is ineligibility, not failure
        return None
    from galah_tpu.ops.greedy_select import resolve_greedy_strategy

    strategy, _explicit = resolve_greedy_strategy()
    if strategy != "device":
        if forced:
            raise RuntimeError(
                "GALAH_TPU_OVERLAP=1 requires the device greedy "
                f"strategy; GALAH_TPU_GREEDY_STRATEGY pins {strategy!r}")
        return None
    streamed = getattr(preclusterer, "distances_streamed", None)
    stream = streamed(genomes) if streamed is not None else None
    if stream is None:
        if forced:
            raise RuntimeError(
                "GALAH_TPU_OVERLAP=1 but the precluster backend "
                f"({preclusterer.method_name()}) did not engage its "
                "streamed pair pass for this workload")
        return None
    try:
        with timing.stage("overlap-dataflow"):
            st = _cluster_overlapped(genomes, clusterer, stream,
                                     skip_clusterer, rep_rounds)
        timing.counter("overlap-engaged", 1)
        return st
    except interrupt.PreemptionRequested:
        raise  # a stop request is never a demotion signal
    except Exception as e:  # noqa: BLE001 - AUTO demotes
        if forced:
            raise
        logger.warning(
            "overlapped dataflow failed (%s: %s); falling back to the "
            "stage-serial engine", type(e).__name__, e)
        timing.counter("overlap-demoted", 1)
        from galah_tpu.obs import events

        events.record("overlap-demoted",
                      error=f"{type(e).__name__}: {e}")
        return None


def _cluster_overlapped(
    genomes: Sequence[str],
    clusterer: ClusterBackend,
    stream,
    skip_clusterer: bool,
    rep_rounds: Optional[int],
) -> _OverlapState:
    """Consume the streamed pair pass as ONE overlapped dataflow:
    while the sketch stream's worker threads keep ingest+sketch
    running ahead, this (consumer) thread interleaves three downstream
    stages between block arrivals — the pair-screen stripes (inside
    the stream generator), speculative fragment-ANI batches over
    survivor pairs with a committed-rep endpoint, and eager greedy
    rounds over the resolved prefix.

    Frontier soundness (why eager decisions are bit-identical to the
    stage-serial engine): genome g's rep decision consults exactly the
    hit edges (i, g) with i < g and the rep status of those i. When
    the stream has screened rows [0, r1), every such edge for every
    g < r1 is known — the stripe covering block(j) evaluates rows
    [0, r1) x cols [r0, r1) — so rep decisions over the prefix are
    FINAL; no genome still being sketched can change them. Windows
    therefore run at fixed absolute boundaries [0,w), [w,2w), ... as
    soon as r1 reaches each window's end, grouped by the live
    union-find component of the hit graph (a hit pair's endpoints are
    already unioned when the edge arrives, so the current roots cover
    every candidate edge a decision can consult). Membership and the
    final cluster assembly wait for stream completion: a later rep
    can still win a non-rep's argmax.

    Speculation rule (zero extra waste): a survivor pair is offered to
    the fragment-ANI buffer iff one endpoint is already a committed
    rep — at edge arrival for the earlier endpoint, and via back-offer
    when a window commits new reps. Every backend pair the greedy/
    membership passes compute has a rep endpoint, so the offered set
    is exactly the stage-serial computed set: the speculation moves
    dispatches earlier, it never adds any. The buffer launches at
    GALAH_TPU_OVERLAP_DEPTH pending pairs (bounded in-flight window,
    memory O(depth)).

    No durable write happens while the stream is live; the caller
    quiesces (this function returns only once the stream is drained
    and every window resolved) before `save_distances` and the single
    greedy-round checkpoint record (_finish_overlapped).
    """
    import numpy as np

    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.ops import greedy_select

    thr = clusterer.ani_threshold
    width = (int(rep_rounds) if rep_rounds is not None
             else greedy_select.DEFAULT_ROUND_WIDTH)
    if width < 1:
        raise ValueError(f"rep_rounds must be >= 1, got {width}")
    from galah_tpu.obs import flow as obs_flow
    depth = _overlap_depth()
    n = len(genomes)

    st = _OverlapState(n)
    pre_cache, adj = st.pre_cache, st.adj
    ani_cache, computed = st.ani_cache, st.computed
    consulted, rep_set = st.consulted, st.rep_set

    # tiny union-find over arrived hit edges: current roots group the
    # window genomes with every rep a candidate edge can reach
    parent = list(range(n))

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    frag_busy = [0.0]   # batch-closure (fragment-ANI dispatch) wall
    greedy_busy = [0.0]  # window wall net of nested fragment time

    def batch(pairs: List[Tuple[int, int]]) -> None:
        """Same dedup + ROUND_BATCH_GENOMES chunking as the
        stage-serial batch closure (_cluster_pending_rounds), plus
        fragment-stage busy accounting for the occupancy gauge."""
        t0 = time.monotonic()
        fid = obs_flow.begin("fragment_batch")
        seen: Set[Tuple[int, int]] = set()
        uniq: List[Tuple[int, int]] = []
        for p in pairs:
            k = pair_key(*p)
            if k in seen or ani_cache.contains(p):
                continue
            seen.add(k)
            uniq.append(p)
        chunk: List[Tuple[int, int]] = []
        chunk_genomes: Set[int] = set()

        def flush() -> None:
            if not chunk:
                return
            anis = _batch_ani(clusterer, skip_clusterer, pre_cache,
                              genomes, chunk, None,
                              computed_log=computed)
            for p, ani in zip(chunk, anis):
                ani_cache.insert(p, ani)
            chunk.clear()
            chunk_genomes.clear()

        for p in uniq:
            if chunk and len(chunk_genomes | set(p)) > \
                    ROUND_BATCH_GENOMES:
                flush()
            chunk.append(p)
            chunk_genomes.update(p)
        flush()
        dt = time.monotonic() - t0
        frag_busy[0] += dt
        obs_flow.record_service("fragment", dt, items=len(uniq))
        obs_flow.complete(fid)

    def value(i: int, j: int) -> Optional[float]:
        if skip_clusterer and pre_cache.contains((i, j)):
            return pre_cache.get((i, j))
        return ani_cache.get((i, j))

    st.batch, st.value = batch, value

    hist = obs_metrics.histogram(
        "greedy.round_seconds",
        help="Wall-clock of one device-strategy selection round "
             "(speculative dispatch + frontier sub-rounds + jitted "
             "window fold)",
        unit="s")
    rounds_c = obs_metrics.counter(
        "greedy.rounds",
        help="Device-strategy selection rounds run", unit="rounds")
    conflicts_c = obs_metrics.counter(
        "greedy.conflict_windows",
        help="Round windows whose rep-chain depth exceeded the device "
             "resolution budget", unit="windows")
    fallback_c = obs_metrics.counter(
        "greedy.fallback_windows",
        help="Round windows finished by the exact host-order scan",
        unit="windows")
    eager_c = obs_metrics.counter(
        "overlap.eager_rounds",
        help="Greedy device rounds run while the sketch stream was "
             "still producing (the overlapped engine's eager windows)",
        unit="rounds")
    spec_c = obs_metrics.counter(
        "overlap.spec_pairs",
        help="Survivor pairs offered to the speculative fragment-ANI "
             "buffer", unit="pairs")

    # speculative fragment-ANI buffer: survivor pairs with a committed
    # rep endpoint, launched when `depth` accumulate
    spec: List[Tuple[int, int]] = []
    offered: Set[Tuple[int, int]] = set()
    stats = {"offered": 0, "batches": 0, "peak": 0}

    def flush_spec() -> None:
        if not spec:
            return
        stats["batches"] += 1
        batch(spec)
        spec.clear()

    def offer(pair: Tuple[int, int]) -> None:
        k = pair_key(*pair)
        if k in offered or ani_cache.contains(pair):
            return
        if skip_clusterer and pre_cache.contains(pair):
            return  # precluster reuse — never hits the backend
        offered.add(k)
        spec.append(pair)
        stats["offered"] += 1
        stats["peak"] = max(stats["peak"], len(spec))
        if len(spec) >= depth:
            flush_spec()

    frontier = [0]  # next undecided window start: prefix is FINAL
    mega = _megakernel_ctx()
    seq_all = range(n)

    def run_ready_windows(r1: int) -> None:
        while frontier[0] < n:
            end = min(frontier[0] + width, n)
            if r1 < end:
                return
            if mega is not None and mega.active:
                # fuse every already-ready consecutive window into one
                # queue-fed slab round (bit-identical by width
                # invariance) — eagerness is unchanged because growth
                # stops at the resolved prefix (r1), never waiting for
                # windows the stream has not finalized
                window = _grow_slab(seq_all, frontier[0], width, adj,
                                    mega.cap, ready_limit=r1)
                end = frontier[0] + len(window)
            else:
                window = list(range(frontier[0], end))
            n_windows = (len(window) + width - 1) // width
            t0 = time.monotonic()
            fid = obs_flow.begin("greedy_round")
            fb0 = frag_busy[0]
            db0 = mega.dev_busy if mega is not None else 0.0
            pc_of = {g: find(g) for g in window}
            reps_by_pc: Dict[int, List[int]] = {}
            for r in st.rep_order:
                reps_by_pc.setdefault(find(r), []).append(r)
            for g in window:
                reps_by_pc.setdefault(pc_of[g], [])
            with hist.time():
                _device_round(window, pc_of, adj, reps_by_pc, rep_set,
                              batch, value, consulted, thr,
                              greedy_select, np, conflicts_c,
                              fallback_c, mega=mega)
                timing.counter("greedy-rounds", 1)
                rounds_c.inc()
            timing.counter("overlap-eager-rounds", n_windows)
            eager_c.inc(n_windows)
            st.eager_rounds += n_windows
            # _device_round appends reps in window order; every window
            # genome was undecided before, so the in-rep_set window
            # genomes ARE this round's commits, in commit order
            new_reps = [g for g in window if g in rep_set]
            st.rep_order.extend(new_reps)
            # back-offer: every hit pair of a fresh rep is one a later
            # phase-1 candidate row or the membership argmax will read
            for r in new_reps:
                for t in adj[r]:
                    offer((r, t))
            frontier[0] = end
            dev_dt = ((mega.dev_busy - db0)
                      if mega is not None else 0.0)
            dt = ((time.monotonic() - t0) - (frag_busy[0] - fb0)
                  - dev_dt)
            greedy_busy[0] += dt
            obs_flow.record_service("greedy", dt)
            obs_flow.complete(fid)
            # live gauge refresh so the heartbeat samples a moving
            # occupancy time-series, not only the quiesce value
            wall_now = max(time.monotonic() - t_start, 1e-9)
            obs_metrics.pipeline_occupancy(
                min(1.0, greedy_busy[0] / wall_now), stage="greedy")
            if not skip_clusterer:
                obs_metrics.pipeline_occupancy(
                    min(1.0, frag_busy[0] / wall_now),
                    stage="fragment")

    t_start = time.monotonic()
    stream_it = iter(stream)
    while True:
        # blocked on the upstream pair-screen stream (obs/flow records
        # it as the greedy stage's upstream-empty wait — the signal
        # `galah-tpu flow analyze` forwards to the producer's blame)
        with obs_flow.blocked("greedy", "upstream-empty"):
            try:
                r1, inc = next(stream_it)
            except StopIteration:
                break
        obs_flow.absorb("pairs", "greedy")
        for (a, b), v in inc.items():
            pre_cache.insert((a, b), v)
            adj[a].append(b)
            adj[b].append(a)
            parent[find(a)] = find(b)
            if a in rep_set:
                offer((a, b))
        run_ready_windows(r1)
    if frontier[0] < n:
        raise RuntimeError(
            f"overlapped stream ended with the greedy frontier at "
            f"{frontier[0]} of {n} genomes")
    flush_spec()

    timing.counter("overlap-spec-pairs", stats["offered"])
    timing.counter("overlap-spec-batches", stats["batches"])
    spec_c.inc(stats["offered"])
    obs_metrics.gauge(
        "overlap.spec_pending_peak",
        help="High-water mark of the speculative fragment-ANI buffer "
             "(bounded by GALAH_TPU_OVERLAP_DEPTH)",
        unit="pairs").set(float(stats["peak"]))

    # per-stage occupancy over the streaming phase's wall, plus the
    # whole-pipeline value (mean of the per-stage gauges this run
    # emitted) as the unlabelled gauge
    wall = max(time.monotonic() - t_start, 1e-9)
    obs_metrics.pipeline_occupancy(greedy_busy[0] / wall,
                                   stage="greedy")
    if not skip_clusterer:
        obs_metrics.pipeline_occupancy(frag_busy[0] / wall,
                                       stage="fragment")
    prefix = obs_metrics.PIPELINE_OCCUPANCY_GAUGE + "["
    vals = [m["value"] for name, m in obs_metrics.snapshot().items()
            if name.startswith(prefix) and m.get("value") is not None]
    if vals:
        obs_metrics.pipeline_occupancy(sum(vals) / len(vals))
    return st


def _finish_overlapped(
    st: _OverlapState,
    genomes: Sequence[str],
    clusterer: ClusterBackend,
    pending: List[Tuple[int, Sequence[int]]],
    skip_clusterer: bool,
    checkpoint: Optional["ClusterCheckpoint"],
) -> Dict[int, List[List[int]]]:
    """Post-quiesce finish of the overlapped dataflow: persist every
    overlap-computed ANI as ONE digest-bound greedy-round record (a
    kill after this boundary resumes stage-serial and replays them
    with zero dispatches), then run the membership pass and per-
    precluster assembly exactly as the stage-serial device strategy
    does — decisions were already made during streaming."""
    import numpy as np

    from galah_tpu.ops import greedy_select

    pc_of: Dict[int, int] = {}
    for pc, members in pending:
        for g in members:
            pc_of[g] = pc
    reps_by_pc: Dict[int, List[int]] = {pc: [] for pc, _ in pending}
    for r in st.rep_order:
        if r in pc_of:
            reps_by_pc[pc_of[r]].append(r)

    digest = _greedy_digest(pending)
    if checkpoint and st.computed:
        checkpoint.save_greedy_round(
            digest,
            [(i, j, st.ani_cache.get((i, j))) for i, j in st.computed])
    # safe boundary: the streaming phase's ANI pairs are durable — a
    # stage-serial resume replays them and re-derives every greedy
    # decision for free
    interrupt.check("greedy-round-saved")

    # -- membership: one global batched dispatch + jitted argmax ------
    todo: List[Tuple[int, int]] = []
    for a, b in st.pre_cache.keys():
        if a not in pc_of:
            continue
        a_rep, b_rep = a in st.rep_set, b in st.rep_set
        if a_rep == b_rep:
            continue  # rep-rep / non-rep pairs never decide membership
        r, i = (a, b) if a_rep else (b, a)
        if not (skip_clusterer and st.pre_cache.contains((i, r))) \
                and not st.ani_cache.contains((i, r)):
            todo.append((r, i))
    todo.sort(key=lambda p: (p[1], p[0]))
    n_rep_computed = len(st.computed)
    st.batch(todo)

    results: Dict[int, List[List[int]]] = {}
    for pc, members in pending:
        rep_list = reps_by_pc[pc]
        rep_col = {r: c for c, r in enumerate(rep_list)}
        nonreps = [g for g in members if g not in st.rep_set]
        clusters: List[List[int]] = [[r] for r in rep_list]
        if nonreps:
            mat = np.full((len(nonreps), len(rep_list)), np.nan,
                          dtype=np.float64)
            for gi, g in enumerate(nonreps):
                for r in st.adj[g]:
                    c = rep_col.get(r)
                    if c is None:
                        continue
                    v = st.value(g, r)
                    if v is not None:
                        mat[gi, c] = v
            best, has = greedy_select.membership_argmax(mat)
            for gi, g in enumerate(nonreps):
                if not has[gi]:
                    raise RuntimeError(
                        f"genome {genomes[g]} passed the representative "
                        "test but has no ANI to any representative — "
                        "inconsistent backend")
                clusters[int(best[gi])].append(g)
        results[pc] = clusters

    # -- waste accounting, split by paying phase ----------------------
    computed_keys = {pair_key(*p) for p in st.computed}
    mem_consulted = {k for k in computed_keys
                     if (k[0] in st.rep_set) != (k[1] in st.rep_set)}
    live = st.consulted | mem_consulted
    rep_keys = {pair_key(*p) for p in st.computed[:n_rep_computed]}
    mem_keys = {pair_key(*p) for p in st.computed[n_rep_computed:]} \
        - rep_keys
    _emit_waste_counters(
        len(computed_keys),
        rep=len(rep_keys - live),
        membership=len(mem_keys - live),
        warm=0,
        label=f"overlapped rounds ({len(pending)} preclusters)")
    return results


def _cluster_pending_rounds(
    clusterer: ClusterBackend,
    genomes: Sequence[str],
    pre_cache: PairDistanceCache,
    pending: List[Tuple[int, Sequence[int]]],
    skip_clusterer: bool,
    checkpoint: Optional["ClusterCheckpoint"],
    rep_rounds: Optional[int],
) -> Dict[int, List[List[int]]]:
    """The round-based device greedy strategy over ALL pending
    preclusters at once: {precluster index -> its global clusters}.

    Each round takes the next K genomes of the concatenated pending
    sequence (partition order; within a precluster that IS quality
    order), evaluates their ANIs against every existing same-precluster
    rep in one batched dispatch, materializes the intra-window hit
    pairs that decisions need (one small frontier dispatch per
    sub-round, all segments batched together), and resolves the
    window's rep/member status with ONE jitted segmented fold
    (ops/greedy_select.window_select). Decisions are bit-identical to
    the per-precluster host scan; windows whose rep-chain depth
    exceeds the sub-round/fold budget are conflict windows and finish
    on the exact host-order scan (rare, measured:
    greedy-conflict-windows / greedy-host-fallback-windows).

    The win over the host path is dispatch count: the 1000-genome
    bench rung runs ~250 preclusters, which the host path walks one at
    a time (>=1 profile build + ANI dispatch each); here every round
    spans all of them, so dispatches drop to O(N / K) and the backend's
    batched profile build touches each genome group once.

    With a checkpoint, each round's backend-computed pairs append to
    greedy_rounds.jsonl (digest-bound to the pending sequence): a
    resume replays the values into the cache and re-derives every
    decision with zero dispatches up to the crash point.
    """
    import numpy as np

    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.ops import greedy_select

    thr = clusterer.ani_threshold
    width = (int(rep_rounds) if rep_rounds is not None
             else greedy_select.DEFAULT_ROUND_WIDTH)
    if width < 1:
        raise ValueError(f"rep_rounds must be >= 1, got {width}")

    seq: List[int] = []
    pc_of: Dict[int, int] = {}
    for pc, members in pending:
        for g in members:
            seq.append(g)
            pc_of[g] = pc
    # precluster-hit adjacency restricted to pending genomes: the hit
    # graph's components ARE the preclusters, so any key with one
    # pending endpoint has both in the same pending precluster
    adj: Dict[int, List[int]] = {g: [] for g in seq}
    for a, b in pre_cache.keys():
        if a in pc_of:
            adj[a].append(b)
            adj[b].append(a)
    for v in adj.values():
        v.sort()

    ani_cache = PairDistanceCache()
    computed: List[Tuple[int, int]] = []   # pairs that hit the backend
    consulted: Set[Tuple[int, int]] = set()  # pairs a rep decision read
    reps_by_pc: Dict[int, List[int]] = {pc: [] for pc, _ in pending}
    rep_set: Set[int] = set()

    digest = _greedy_digest(pending)
    if checkpoint:
        for i, j, ani in checkpoint.load_greedy_rounds(digest):
            ani_cache.insert((i, j), ani)
            computed.append((i, j))
    n_replayed = len(computed)

    def batch(pairs: List[Tuple[int, int]]) -> None:
        """Compute pairs missing from the cache, chunked so no single
        dispatch pins more than ROUND_BATCH_GENOMES genome profiles at
        once. One monolithic batch would keep the whole window's
        profile heap resident together (~1 MB/genome), and that
        allocator pressure measurably slows the per-pair host merges
        (~2x on the 1000-genome rung) — the pair lists arrive grouped
        by precluster segment, so capping the working set keeps each
        chunk's profiles cache-warm and lets the profile store's LRU
        evict between chunks. Chunking preserves pair order, so the
        computed log and every ANI value are bit-identical."""
        seen: Set[Tuple[int, int]] = set()
        uniq: List[Tuple[int, int]] = []
        for p in pairs:
            k = pair_key(*p)
            if k in seen or ani_cache.contains(p):
                continue
            seen.add(k)
            uniq.append(p)
        chunk: List[Tuple[int, int]] = []
        chunk_genomes: Set[int] = set()

        def flush() -> None:
            if not chunk:
                return
            anis = _batch_ani(clusterer, skip_clusterer, pre_cache,
                              genomes, chunk, None,
                              computed_log=computed)
            for p, ani in zip(chunk, anis):
                ani_cache.insert(p, ani)
            chunk.clear()
            chunk_genomes.clear()

        for p in uniq:
            if chunk and len(chunk_genomes | set(p)) > \
                    ROUND_BATCH_GENOMES:
                flush()
            chunk.append(p)
            chunk_genomes.update(p)
        flush()

    def value(i: int, j: int) -> Optional[float]:
        """The decision value for a hit pair, same precedence as
        _batch_ani: precluster reuse when methods match, else the
        computed exact ANI (None when absent or gated)."""
        if skip_clusterer and pre_cache.contains((i, j)):
            return pre_cache.get((i, j))
        return ani_cache.get((i, j))

    hist = obs_metrics.histogram(
        "greedy.round_seconds",
        help="Wall-clock of one device-strategy selection round "
             "(speculative dispatch + frontier sub-rounds + jitted "
             "window fold)",
        unit="s")
    rounds_c = obs_metrics.counter(
        "greedy.rounds",
        help="Device-strategy selection rounds run", unit="rounds")
    conflicts_c = obs_metrics.counter(
        "greedy.conflict_windows",
        help="Round windows whose rep-chain depth exceeded the device "
             "resolution budget", unit="windows")
    fallback_c = obs_metrics.counter(
        "greedy.fallback_windows",
        help="Round windows finished by the exact host-order scan",
        unit="windows")

    mega = _megakernel_ctx(stage_serial=True)
    n = len(seq)
    pos = 0
    while pos < n:
        if mega is not None and mega.active:
            # fuse consecutive ready windows into one queue-fed slab
            # round (bit-identical by width invariance; capacity- and
            # SLAB_WINDOWS-bounded). Checkpoint records stay per
            # round, so resume replay is granularity-agnostic.
            window = _grow_slab(seq, pos, width, adj, mega.cap)
        else:
            window = seq[pos:pos + width]
        pos += len(window)
        with hist.time():
            rstart = len(computed)
            _device_round(window, pc_of, adj, reps_by_pc, rep_set,
                          batch, value, consulted, thr, greedy_select,
                          np, conflicts_c, fallback_c, mega=mega)
            timing.counter("greedy-rounds", 1)
            rounds_c.inc()
            if checkpoint and len(computed) > rstart:
                checkpoint.save_greedy_round(
                    digest,
                    [(i, j, ani_cache.get((i, j)))
                     for i, j in computed[rstart:]])
        # safe boundary: this round's ANI pairs are durable — a
        # resume replays them and re-derives the decisions for free
        interrupt.check("greedy-round-saved")

    # -- membership: one global batched dispatch + jitted argmax ------
    todo: List[Tuple[int, int]] = []
    for a, b in pre_cache.keys():
        if a not in pc_of:
            continue
        a_rep, b_rep = a in rep_set, b in rep_set
        if a_rep == b_rep:
            continue  # rep-rep / non-rep pairs never decide membership
        # orient (rep, non-rep); the (genome, rep)-ascending sort below
        # keeps the host scan's deterministic batch order
        r, i = (a, b) if a_rep else (b, a)
        if not (skip_clusterer and pre_cache.contains((i, r))) \
                and not ani_cache.contains((i, r)):
            todo.append((r, i))
    todo.sort(key=lambda p: (p[1], p[0]))
    n_rep_computed = len(computed)
    batch(todo)

    results: Dict[int, List[List[int]]] = {}
    for pc, members in pending:
        rep_list = reps_by_pc[pc]
        rep_col = {r: c for c, r in enumerate(rep_list)}
        nonreps = [g for g in members if g not in rep_set]
        clusters: List[List[int]] = [[r] for r in rep_list]
        if nonreps:
            mat = np.full((len(nonreps), len(rep_list)), np.nan,
                          dtype=np.float64)
            for gi, g in enumerate(nonreps):
                for r in adj[g]:
                    c = rep_col.get(r)
                    if c is None:
                        continue
                    v = value(g, r)
                    if v is not None:
                        mat[gi, c] = v
            best, has = greedy_select.membership_argmax(mat)
            for gi, g in enumerate(nonreps):
                if not has[gi]:
                    raise RuntimeError(
                        f"genome {genomes[g]} passed the representative "
                        "test but has no ANI to any representative — "
                        "inconsistent backend")
                clusters[int(best[gi])].append(g)
        results[pc] = clusters

    # -- waste accounting, split by paying phase ----------------------
    # the membership argmax consults every cached (non-rep, rep) pair,
    # so any computed key joining a rep and a non-rep was consumed
    computed_keys = {pair_key(*p) for p in computed}
    mem_consulted = {k for k in computed_keys
                     if (k[0] in rep_set) != (k[1] in rep_set)}
    live = consulted | mem_consulted
    rep_keys = {pair_key(*p) for p in computed[:n_rep_computed]}
    mem_keys = {pair_key(*p) for p in computed[n_rep_computed:]} \
        - rep_keys
    _emit_waste_counters(
        len(computed_keys),
        rep=len(rep_keys - live),
        membership=len(mem_keys - live),
        warm=0,
        label=f"device rounds ({len(pending)} preclusters)")
    if n_replayed:
        timing.counter("greedy-replayed-pairs", n_replayed)
    return results


def _device_round(
    window: List[int],
    pc_of: Dict[int, int],
    adj: Dict[int, List[int]],
    reps_by_pc: Dict[int, List[int]],
    rep_set: Set[int],
    batch,
    value,
    consulted: Set[Tuple[int, int]],
    thr: float,
    greedy_select,
    np,
    conflicts_c,
    fallback_c,
    mega: Optional[_MegaCtx] = None,
) -> None:
    """Resolve one K-genome window; commits new reps into reps_by_pc.

    Three phases, mirroring the docstring of _cluster_pending_rounds:
    (1) one speculative batch of window x existing-rep hit pairs and
    the derived already-clustered flags; (2) bounded frontier
    sub-rounds that materialize exactly the intra-window pairs greedy
    decisions depend on (the first undecided genome of every segment
    is provably the next rep — all its earlier neighbors are decided
    and none claimed it); (3) the jitted segmented fold over the
    materialized matrix as the authoritative device decision pass,
    cross-checked against the sub-round bookkeeping. Windows the
    budget cannot finish fall back to the host-order scan for their
    undecided tail — decisions stay exact, only the dispatch pattern
    degrades.
    """
    w = len(window)
    win_pos = {g: wi for wi, g in enumerate(window)}
    hits = {g: set(adj[g]) for g in window}

    # (1) window x existing same-precluster reps, ONE dispatch, then
    # the already-clustered flags. The batched decision reads the whole
    # candidate row (no early exit to skip pairs the batch computed
    # anyway), so every candidate pair counts as consulted.
    batch([(r, g) for g in window for r in reps_by_pc[pc_of[g]]
           if r in hits[g]])
    ext = np.zeros(w, dtype=bool)
    for wi, g in enumerate(window):
        for r in reps_by_pc[pc_of[g]]:
            if r not in hits[g]:
                continue
            consulted.add(pair_key(r, g))
            v = value(r, g)
            if v is not None and v >= thr:
                ext[wi] = True

    # (2) frontier sub-rounds. The first undecided genome of a segment
    # is exactly the next greedy rep: every earlier same-precluster
    # genome is decided and none of the decided reps claimed it (prior
    # rounds via ext, in-window reps via earlier claim applications).
    # Each sub-round batches ALL segments' frontier-vs-later-hit pairs
    # into one dispatch and applies the claims.
    decided = ext.copy()
    tentative = np.zeros(w, dtype=bool)
    n_sub = 0
    for _ in range(MAX_SUBROUNDS):
        frontier: List[int] = []
        seen_seg: Set[int] = set()
        for wi in range(w):
            if decided[wi]:
                continue
            s = pc_of[window[wi]]
            if s in seen_seg:
                continue
            seen_seg.add(s)
            frontier.append(wi)
        if not frontier:
            break
        n_sub += 1
        pairs: List[Tuple[int, int]] = []
        claims: List[Tuple[int, int]] = []
        for fi in frontier:
            f = window[fi]
            for t in adj[f]:
                ti = win_pos.get(t)
                if ti is None or ti <= fi or decided[ti]:
                    continue
                pairs.append((f, t))
                claims.append((fi, ti))
        batch(pairs)
        for fi in frontier:
            decided[fi] = True
            tentative[fi] = True
        for fi, ti in claims:
            consulted.add(pair_key(window[fi], window[ti]))
            v = value(window[fi], window[ti])
            if v is not None and v >= thr:
                decided[ti] = True
    timing.counter("greedy-subrounds", n_sub)

    # (3) the jitted fold as the authoritative device decision pass.
    # Soundness gate: a fold is only authoritative when bookkeeping
    # is COMPLETE — over an incompletely materialized matrix, missing
    # edges read as no-edge and a converged fold can still be wrong.
    # With the megakernel engaged, a complete slab folds via the
    # queue-fed fused program (2 dispatches per slab instead of one
    # dense fold per window); spills/demotions fall through to the
    # dense path, so decisions stay exact either way.
    complete = bool(decided.all())
    rep_flags = None
    if complete and mega is not None and mega.active:
        rep_flags, converged = _megakernel_fold(
            mega, window, win_pos, adj, ext, value, thr, np)
        if rep_flags is not None and (
                not converged
                or not np.array_equal(rep_flags, tentative)):
            if mega.explicit:
                raise RuntimeError(
                    "megakernel slab fold disagreed with the exact "
                    "sub-round bookkeeping — refusing speculative "
                    "greedy decisions")
            _demote_megakernel(
                mega, "slab fold disagreed with sub-round bookkeeping")
            rep_flags = None
    if rep_flags is None:
        mat = np.full((w, w), np.nan, dtype=np.float64)
        for wi, g in enumerate(window):
            for t in adj[g]:
                ti = win_pos.get(t)
                if ti is None or ti <= wi:
                    continue
                v = value(g, t)
                if v is not None:
                    mat[wi, ti] = v
        rep_flags, converged = greedy_select.window_select(mat, ext,
                                                          thr)
    if complete:
        if not converged or not np.array_equal(rep_flags, tentative):
            raise RuntimeError(
                "device window fold disagreed with the exact sub-round "
                "bookkeeping — refusing speculative greedy decisions")
    else:
        # conflict window: rep-chain depth exceeded the sub-round
        # budget; finish the undecided tail with the exact host-order
        # scan (small per-genome batches), decisions unchanged.
        timing.counter("greedy-conflict-windows", 1)
        conflicts_c.inc()
        timing.counter("greedy-host-fallback-windows", 1)
        fallback_c.inc()
        for ti in range(w):
            if decided[ti]:
                continue
            t = window[ti]
            cands = [fi for fi in range(ti)
                     if tentative[fi] and window[fi] in hits[t]]
            batch([(window[fi], t) for fi in cands])
            is_rep = True
            for fi in cands:
                consulted.add(pair_key(window[fi], t))
                v = value(window[fi], t)
                if v is not None and v >= thr:
                    is_rep = False
                    break
            decided[ti] = True
            if is_rep:
                tentative[ti] = True

    for wi in range(w):
        if tentative[wi]:
            g = window[wi]
            reps_by_pc[pc_of[g]].append(g)
            rep_set.add(g)


def _find_representatives(
    clusterer: ClusterBackend,
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
    skip_clusterer: bool,
    warm_cache: Optional[PairDistanceCache] = None,
    rep_scan_window: Optional[int] = None,
) -> Tuple[Set[int], PairDistanceCache,
           List[Tuple[int, int]], Set[Tuple[int, int]]]:
    """Greedy quality-ordered representative selection.

    Reference: src/clusterer.rs:155-225 (find_dashing_fastani_
    representatives). Genome i becomes a representative iff no existing
    rep with a precluster hit has exact ANI >= threshold.

    Dispatch strategy: the scan is inherently sequential (genome i's
    candidate set is the reps chosen before it), but ANI VALUES are
    order-independent, so a window of upcoming genomes is evaluated
    against all current reps in ONE batched call; only pairs against
    reps that emerge inside the window need follow-up batches. Device
    round trips drop from O(N) to O(N/window + #new-reps) per
    precluster, with decisions identical to the per-genome scan (the
    extra ANIs computed for window genomes that join a cluster first
    are the same waste class as the reference's find_any computing an
    unpredictable candidate subset, reference: src/clusterer.rs:242-262).
    """
    reps: Set[int] = set()
    ani_cache = PairDistanceCache()
    thr = clusterer.ani_threshold
    n = len(genomes)
    window_size = (int(rep_scan_window) if rep_scan_window is not None
                   else REP_SCAN_WINDOW)
    if window_size < 1:
        raise ValueError(
            f"rep_scan_window must be >= 1, got {window_size}")
    computed: List[Tuple[int, int]] = []   # pairs that hit the backend
    consulted: Set[Tuple[int, int]] = set()  # pairs a decision read
    # Device-blocked backends (TPU pairlist kernel) evaluate pairs in
    # blocks of this size; the windowed speculative batches below top
    # up to a multiple of it with next-window pairs so the final block
    # of a dispatch runs full instead of padded. Pure cache fill —
    # decisions are identical, and the topped-up pairs are ones the
    # next window's batch would have computed anyway.
    quantum = max(1, int(getattr(clusterer, "pair_block_multiple", 1)))

    def ensure_anis(pairs: List[Tuple[int, int]],
                    lookahead=()) -> None:
        """Compute (rep, genome) ANIs not already in ani_cache."""
        missing = [(j, g) for j, g in pairs
                   if not ani_cache.contains((j, g))]
        if not missing:
            return
        if quantum > 1 and len(missing) % quantum:
            want = quantum - len(missing) % quantum
            have = set(missing)
            for p in lookahead:
                if want == 0:
                    break
                if p in have or ani_cache.contains(p):
                    continue
                missing.append(p)
                have.add(p)
                want -= 1
        anis = _batch_ani(clusterer, skip_clusterer, pre_cache, genomes,
                          missing, warm_cache, computed_log=computed)
        for (j, g), ani in zip(missing, anis):
            ani_cache.insert((j, g), ani)

    for w0 in range(0, n, window_size):
        window = range(w0, min(w0 + window_size, n))
        # speculative batch: every window genome vs every CURRENT rep
        # (order is irrelevant here — ensure_anis just fills the cache)
        rep_list = list(reps)
        nxt = range(window.stop, min(window.stop + window_size, n))
        ensure_anis([(j, g) for g in window for j in rep_list
                     if pre_cache.contains((g, j))],
                    lookahead=((j, g) for g in nxt for j in rep_list
                               if pre_cache.contains((g, j))))
        for i in window:
            cands = [(j, pre_cache.get((i, j))) for j in sorted(reps)
                     if pre_cache.contains((i, j))]
            # ascending by precluster ANI — preserved from the reference
            # (its comment says "highest first" but the sort is
            # ascending, reference: src/clusterer.rs:167-177)
            cands.sort(key=lambda t: t[1] if t[1] is not None else -1.0)
            # reps that emerged inside the window: their pairs weren't
            # in the speculative batch
            ensure_anis([(j, i) for j, _ in cands])
            is_rep = True
            for j, _ in cands:
                ani = ani_cache.get((j, i))
                consulted.add(pair_key(j, i))
                if ani is not None and ani >= thr:
                    is_rep = False
                    break
            if is_rep:
                logger.debug("Genome designated representative: %d %s",
                             i, genomes[i])
                reps.add(i)
                # speculate forward: the new rep is a candidate for the
                # REST of the window — batch those pairs now instead of
                # one small dispatch per subsequent genome
                ensure_anis([(i, gx) for gx in window if gx > i
                             and pre_cache.contains((gx, i))])
    return reps, ani_cache, computed, consulted


def _find_memberships(
    clusterer: ClusterBackend,
    reps: Set[int],
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
    ani_cache: PairDistanceCache,
    skip_clusterer: bool,
    warm_cache: Optional[PairDistanceCache] = None,
    computed: Optional[List[Tuple[int, int]]] = None,
    consulted: Optional[Set[Tuple[int, int]]] = None,
) -> List[List[int]]:
    """Assign every non-rep to its best (argmax exact ANI) representative.

    Reference: src/clusterer.rs:316-406 (find_dashing_fastani_
    memberships). Candidates needing computation are precluster hits not
    already in the ANI cache; the batched call covers ALL non-reps at
    once (one device dispatch), then argmax with ties to the lowest rep
    index.
    """
    rep_list = sorted(reps)
    rep_to_cluster = {r: n for n, r in enumerate(rep_list)}
    clusters: List[List[int]] = [[r] for r in rep_list]

    # Collect every (genome, rep) pair that still needs exact ANI.
    # Candidates are by definition precluster hits, so ONE pass over
    # the hit keys replaces the old O(non-reps x reps) double loop over
    # contains() probes (hit graphs are sparse: at the 1000-genome
    # bench rung this is ~2.7k keys vs ~560k probes); the (genome,
    # rep)-ascending sort reproduces the old loop's batch order
    # exactly, so dispatch contents are byte-identical.
    todo: List[Tuple[int, int]] = []
    for a, b in pre_cache.keys():
        a_rep, b_rep = a in reps, b in reps
        if a_rep == b_rep:
            continue  # rep-rep / non-rep pairs never decide membership
        r, i = (a, b) if a_rep else (b, a)
        if not ani_cache.contains((i, r)):
            todo.append((r, i))
    todo.sort(key=lambda p: (p[1], p[0]))
    anis = _batch_ani(clusterer, skip_clusterer, pre_cache, genomes, todo,
                      warm_cache, computed_log=computed)
    for (r, i), ani in zip(todo, anis):
        ani_cache.insert((r, i), ani)  # None recorded too, as the ref does

    for i in range(len(genomes)):
        if i in reps:
            continue
        best_rep = None
        best_ani = None
        for r in rep_list:
            ani = ani_cache.get((i, r))
            if consulted is not None:
                consulted.add(pair_key(i, r))
            if ani is not None and (best_ani is None or ani > best_ani):
                best_rep = r
                best_ani = ani
        if best_rep is None:
            raise RuntimeError(
                f"genome {genomes[i]} passed the representative test but "
                "has no ANI to any representative — inconsistent backend")
        clusters[rep_to_cluster[best_rep]].append(i)
    return clusters
