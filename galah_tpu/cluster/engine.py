"""Two-stage greedy clustering engine.

Re-implements the reference's engine semantics exactly (reference:
src/clusterer.rs:14-125) with one structural change: every per-genome
candidate ANI set is evaluated as ONE batched backend call instead of the
reference's per-pair threads with `find_any` early exit. The greedy
decisions are identical — "is any candidate ANI >= threshold" does not
depend on which subset the early exit happened to compute — but here they
are deterministic, and the ANI cache is a superset of the reference's.

Semantics preserved:
  * genomes arrive pre-sorted by quality; rep selection scans them in
    order, so earlier (higher-quality) genomes become representatives
    (reference: src/clusterer.rs:164-223).
  * candidate reps for genome i = current reps with a precluster-cache
    hit against i (reference: src/clusterer.rs:167-177).
  * when precluster and cluster methods match, precluster ANIs are reused
    instead of recomputed (reference: src/clusterer.rs:29-33,180-186).
  * membership: each non-rep is assigned to the argmax-ANI rep over all
    cached/computed rep ANIs — NO threshold filter at this stage, ties
    to the lowest rep index (reference: src/clusterer.rs:371-403).
  * rep-phase ANIs carry into the membership phase via the shared cache
    (reference: src/clusterer.rs:160-162,211,321-334).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, List, Optional, Sequence, Set, Tuple

if TYPE_CHECKING:
    from galah_tpu.cluster.checkpoint import ClusterCheckpoint

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster.cache import PairDistanceCache, pair_key
from galah_tpu.cluster.partition import partition_preclusters
from galah_tpu.utils import timing

logger = logging.getLogger(__name__)


DENSE_PRECLUSTER_CAP = 64


def cluster(
    genomes: Sequence[str],
    preclusterer: PreclusterBackend,
    clusterer: ClusterBackend,
    checkpoint: Optional["ClusterCheckpoint"] = None,
    dense_precluster_cap: int = DENSE_PRECLUSTER_CAP,
    rep_scan_window: Optional[int] = None,
) -> List[List[int]]:
    """Cluster quality-ordered genome paths -> list of index clusters.

    Each cluster lists its representative first; clusters are ordered by
    precluster processing order (biggest precluster first) then by
    representative index — deterministic, unlike the reference's
    thread-completion order.

    With a `checkpoint` (cluster/checkpoint.py), the distance pass and
    each finished precluster persist to disk; an interrupted run resumes
    from the last completed precluster.

    Preclusters up to `dense_precluster_cap` members compute exact ANI
    for ALL their precluster-hit pairs in one batched dispatch before
    the greedy loop (every pair the loop could consult is a hit pair),
    so the sequential rep scan touches no device at all. The extra ANIs
    beyond what early exits would have needed are the same waste class
    as the reference's find_any computing an unpredictable candidate
    subset (reference: src/clusterer.rs:242-262) — traded here for one
    round trip per precluster instead of one per genome.

    Waste is measured, not assumed: the exact-ani-computed /
    exact-ani-wasted counters in the stage report count backend-computed
    pairs never read by any decision. On the 18-MAG abisko campaign
    (2026-07-30, fast mode, 99% ANI) the windowed path computed 62 ANIs
    with 0 wasted — the membership argmax consults every (non-rep, rep)
    pair, consuming the speculation — while the dense-warm path computed
    153 with 91 unconsulted (59%), the price of one-dispatch-per-
    precluster. `rep_scan_window` (CLI --rep-scan-window) tunes the
    speculative width; tests/test_campaign_abisko18.py bounds the waste.
    """
    skip_clusterer = preclusterer.method_name() == clusterer.method_name()
    if skip_clusterer:
        logger.info(
            "Preclustering and clustering methods are the same, "
            "so reusing ANI values")

    pre_cache = checkpoint.load_distances() if checkpoint else None
    if pre_cache is None:
        with timing.stage("precluster-distances"):
            pre_cache = preclusterer.distances(genomes)
        if checkpoint:
            checkpoint.save_distances(pre_cache)

    logger.info("Preclustering ..")
    with timing.stage("partition"):
        preclusters = partition_preclusters(len(genomes), pre_cache.keys())
    logger.info("Found %d preclusters. The largest contained %d genomes",
                len(preclusters), len(preclusters[0]) if preclusters else 0)

    done = checkpoint.load_completed() if checkpoint else {}

    logger.info(
        "Finding representative genomes and assigning all genomes ..")
    all_clusters: List[List[int]] = []
    with timing.stage("greedy-cluster"):
        for pc_index, members in enumerate(preclusters):
            if pc_index in done:
                all_clusters.extend(done[pc_index])
                continue
            local_cache = pre_cache.transform_ids(members)
            local_genomes = [genomes[g] for g in members]
            warm_cache = None
            if (not skip_clusterer
                    and len(members) <= dense_precluster_cap):
                warm_cache = _warm_all_hit_pairs(
                    clusterer, local_cache, local_genomes)
            reps, ani_cache, computed, consulted = _find_representatives(
                clusterer, local_cache, local_genomes, skip_clusterer,
                warm_cache, rep_scan_window)
            local_clusters = _find_memberships(
                clusterer, reps, local_cache, local_genomes, ani_cache,
                skip_clusterer, warm_cache, computed, consulted)
            # Speculative waste accounting: backend-computed pairs no
            # decision (rep scan or membership argmax) ever read —
            # covering both the windowed speculative batches and the
            # upfront dense-warm pass. The reference has the same waste
            # class via find_any computing an unpredictable candidate
            # subset (reference: src/clusterer.rs:242-262); here it is
            # measured and reported in the stage report.
            computed_keys = {pair_key(*p) for p in computed}
            if warm_cache is not None:
                computed_keys |= set(warm_cache.keys())
            wasted = len(computed_keys - consulted)
            timing.counter("exact-ani-computed", len(computed_keys))
            timing.counter("exact-ani-wasted", wasted)
            from galah_tpu.obs import metrics as obs_metrics

            obs_metrics.counter(
                "ani.exact_computed",
                help="Exact ANI pairs the backend computed",
                unit="pairs").inc(len(computed_keys))
            obs_metrics.counter(
                "ani.exact_wasted",
                help="Backend-computed ANI pairs no greedy decision "
                     "ever consulted (speculation waste)",
                unit="pairs").inc(wasted)
            if computed_keys:
                logger.debug(
                    "precluster %d: %d exact ANIs computed, %d never "
                    "consulted (%.1f%% waste)", pc_index,
                    len(computed_keys), wasted,
                    100.0 * wasted / len(computed_keys))
            global_clusters = [[members[i] for i in c]
                               for c in local_clusters]
            all_clusters.extend(global_clusters)
            if checkpoint:
                checkpoint.save_precluster(pc_index, global_clusters)
    logger.info("Found %d clusters", len(all_clusters))
    return all_clusters


def _backend_ani_batch(
    clusterer: ClusterBackend,
    path_pairs: List[Tuple[str, str]],
) -> List[Optional[float]]:
    """One backend ANI batch, host-split on multi-host runs.

    Every process reaches this with the IDENTICAL pair list (the
    engine is deterministic and its caches are identical across
    hosts); the shared exchange (distributed.sharded_optional_floats)
    splits it with pairs OWNED BY their second endpoint's path hash —
    a genome's pairs against the (few, everywhere-profiled) reps land
    on one host, so per-host profiling stays near unique/P instead of
    every host touching every endpoint. A failing host propagates its
    error to every peer instead of stranding them in the collective.
    Single-process: a plain call.

    Both paths route the batched backend call through the dispatch
    supervisor (resilience/dispatch.py): transient device failures are
    retried with backoff, garbage-shaped or out-of-range results are
    rejected, and a persistently failing batch dispatch demotes this
    site to a per-pair fallback loop for the rest of the run — recorded
    in the stage report as ``demoted[dispatch.ani]``.
    """
    from galah_tpu.parallel import distributed

    n_proc = distributed.process_count()
    if n_proc <= 1 or len(path_pairs) < n_proc:
        return _guarded_ani_batch(clusterer, path_pairs)

    import zlib

    owners = [zlib.crc32(b.encode()) for _a, b in path_pairs]
    return distributed.sharded_optional_floats(
        len(path_pairs),
        lambda idxs: _guarded_ani_batch(
            clusterer, [path_pairs[k] for k in idxs]),
        owner=lambda k: owners[k])


def _guarded_ani_batch(
    clusterer: ClusterBackend,
    path_pairs: List[Tuple[str, str]],
) -> List[Optional[float]]:
    """The retry/validate/demote wrapper around one batched ANI call.

    The fallback computes each pair in its own single-pair batch — the
    smallest dispatch the backend exposes, so one poisoned batch (or a
    wedged batched kernel) degrades throughput instead of killing the
    run. Fallback results still flow through the batch validator.
    """
    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.resilience import dispatch as rdispatch

    def fallback() -> List[Optional[float]]:
        return [clusterer.calculate_ani_batch([p])[0]
                for p in path_pairs]

    obs_metrics.counter(
        "ani.batch_pairs",
        help="Genome pairs submitted to batched exact-ANI dispatches",
        unit="pairs").inc(len(path_pairs))
    with obs_metrics.histogram(
            "ani.batch_seconds",
            help="Wall-clock latency of one guarded batched exact-ANI "
                 "dispatch (retries and fallback included)",
            unit="s").time():
        return rdispatch.run(
            "dispatch.ani",
            lambda: clusterer.calculate_ani_batch(path_pairs),
            fallback=fallback,
            validate=rdispatch.expect_ani_values(len(path_pairs)))


def _batch_ani(
    clusterer: ClusterBackend,
    skip_clusterer: bool,
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
    pairs: Sequence[Tuple[int, int]],
    warm_cache: Optional[PairDistanceCache] = None,
    computed_log: Optional[List[Tuple[int, int]]] = None,
) -> List[Optional[float]]:
    """ANI for local index pairs: precluster reuse or batched backend call.

    With matching methods, a precluster-cache hit is authoritative (same
    algorithm, same parameters — reference: src/clusterer.rs:264-279);
    a `warm_cache` of upfront-computed exact ANIs is consulted next;
    only missing pairs go to the backend. Pairs that actually hit the
    backend (the only ones that cost compute) are appended to
    `computed_log` when given — the waste accounting's input.
    """
    out: List[Optional[float]] = [None] * len(pairs)
    to_compute: List[Tuple[int, Tuple[str, str]]] = []
    for n, (i, j) in enumerate(pairs):
        if skip_clusterer and pre_cache.contains((i, j)):
            out[n] = pre_cache.get((i, j))
        elif warm_cache is not None and warm_cache.contains((i, j)):
            out[n] = warm_cache.get((i, j))
        else:
            to_compute.append((n, (genomes[i], genomes[j])))
            if computed_log is not None:
                computed_log.append(pairs[n])
    if to_compute:
        anis = _backend_ani_batch(clusterer,
                                  [p for _, p in to_compute])
        for (n, _), ani in zip(to_compute, anis):
            out[n] = ani
    return out


def _warm_all_hit_pairs(
    clusterer: ClusterBackend,
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
) -> PairDistanceCache:
    """Exact ANI for every precluster-hit pair in ONE batched dispatch."""
    keys = sorted(pre_cache.keys())
    warm = PairDistanceCache()
    if keys:
        anis = _backend_ani_batch(
            clusterer,
            [(genomes[i], genomes[j]) for i, j in keys])
        for key, ani in zip(keys, anis):
            warm.insert(key, ani)
    return warm


# Speculative rep-scan batch width: genomes per window evaluated
# against all current reps in one backend call. Configurable via
# cluster(rep_scan_window=...) / --rep-scan-window; the waste it buys
# (ANIs computed but never consulted by a decision) is measured per
# run as the exact-ani-wasted counter in the stage report.
REP_SCAN_WINDOW = 128


def _find_representatives(
    clusterer: ClusterBackend,
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
    skip_clusterer: bool,
    warm_cache: Optional[PairDistanceCache] = None,
    rep_scan_window: Optional[int] = None,
) -> Tuple[Set[int], PairDistanceCache,
           List[Tuple[int, int]], Set[Tuple[int, int]]]:
    """Greedy quality-ordered representative selection.

    Reference: src/clusterer.rs:155-225 (find_dashing_fastani_
    representatives). Genome i becomes a representative iff no existing
    rep with a precluster hit has exact ANI >= threshold.

    Dispatch strategy: the scan is inherently sequential (genome i's
    candidate set is the reps chosen before it), but ANI VALUES are
    order-independent, so a window of upcoming genomes is evaluated
    against all current reps in ONE batched call; only pairs against
    reps that emerge inside the window need follow-up batches. Device
    round trips drop from O(N) to O(N/window + #new-reps) per
    precluster, with decisions identical to the per-genome scan (the
    extra ANIs computed for window genomes that join a cluster first
    are the same waste class as the reference's find_any computing an
    unpredictable candidate subset, reference: src/clusterer.rs:242-262).
    """
    reps: Set[int] = set()
    ani_cache = PairDistanceCache()
    thr = clusterer.ani_threshold
    n = len(genomes)
    window_size = (int(rep_scan_window) if rep_scan_window is not None
                   else REP_SCAN_WINDOW)
    if window_size < 1:
        raise ValueError(
            f"rep_scan_window must be >= 1, got {window_size}")
    computed: List[Tuple[int, int]] = []   # pairs that hit the backend
    consulted: Set[Tuple[int, int]] = set()  # pairs a decision read
    # Device-blocked backends (TPU pairlist kernel) evaluate pairs in
    # blocks of this size; the windowed speculative batches below top
    # up to a multiple of it with next-window pairs so the final block
    # of a dispatch runs full instead of padded. Pure cache fill —
    # decisions are identical, and the topped-up pairs are ones the
    # next window's batch would have computed anyway.
    quantum = max(1, int(getattr(clusterer, "pair_block_multiple", 1)))

    def ensure_anis(pairs: List[Tuple[int, int]],
                    lookahead=()) -> None:
        """Compute (rep, genome) ANIs not already in ani_cache."""
        missing = [(j, g) for j, g in pairs
                   if not ani_cache.contains((j, g))]
        if not missing:
            return
        if quantum > 1 and len(missing) % quantum:
            want = quantum - len(missing) % quantum
            have = set(missing)
            for p in lookahead:
                if want == 0:
                    break
                if p in have or ani_cache.contains(p):
                    continue
                missing.append(p)
                have.add(p)
                want -= 1
        anis = _batch_ani(clusterer, skip_clusterer, pre_cache, genomes,
                          missing, warm_cache, computed_log=computed)
        for (j, g), ani in zip(missing, anis):
            ani_cache.insert((j, g), ani)

    for w0 in range(0, n, window_size):
        window = range(w0, min(w0 + window_size, n))
        # speculative batch: every window genome vs every CURRENT rep
        # (order is irrelevant here — ensure_anis just fills the cache)
        rep_list = list(reps)
        nxt = range(window.stop, min(window.stop + window_size, n))
        ensure_anis([(j, g) for g in window for j in rep_list
                     if pre_cache.contains((g, j))],
                    lookahead=((j, g) for g in nxt for j in rep_list
                               if pre_cache.contains((g, j))))
        for i in window:
            cands = [(j, pre_cache.get((i, j))) for j in sorted(reps)
                     if pre_cache.contains((i, j))]
            # ascending by precluster ANI — preserved from the reference
            # (its comment says "highest first" but the sort is
            # ascending, reference: src/clusterer.rs:167-177)
            cands.sort(key=lambda t: t[1] if t[1] is not None else -1.0)
            # reps that emerged inside the window: their pairs weren't
            # in the speculative batch
            ensure_anis([(j, i) for j, _ in cands])
            is_rep = True
            for j, _ in cands:
                ani = ani_cache.get((j, i))
                consulted.add(pair_key(j, i))
                if ani is not None and ani >= thr:
                    is_rep = False
                    break
            if is_rep:
                logger.debug("Genome designated representative: %d %s",
                             i, genomes[i])
                reps.add(i)
                # speculate forward: the new rep is a candidate for the
                # REST of the window — batch those pairs now instead of
                # one small dispatch per subsequent genome
                ensure_anis([(i, gx) for gx in window if gx > i
                             and pre_cache.contains((gx, i))])
    return reps, ani_cache, computed, consulted


def _find_memberships(
    clusterer: ClusterBackend,
    reps: Set[int],
    pre_cache: PairDistanceCache,
    genomes: Sequence[str],
    ani_cache: PairDistanceCache,
    skip_clusterer: bool,
    warm_cache: Optional[PairDistanceCache] = None,
    computed: Optional[List[Tuple[int, int]]] = None,
    consulted: Optional[Set[Tuple[int, int]]] = None,
) -> List[List[int]]:
    """Assign every non-rep to its best (argmax exact ANI) representative.

    Reference: src/clusterer.rs:316-406 (find_dashing_fastani_
    memberships). Candidates needing computation are precluster hits not
    already in the ANI cache; the batched call covers ALL non-reps at
    once (one device dispatch), then argmax with ties to the lowest rep
    index.
    """
    rep_list = sorted(reps)
    rep_to_cluster = {r: n for n, r in enumerate(rep_list)}
    clusters: List[List[int]] = [[r] for r in rep_list]

    # Collect every (genome, rep) pair that still needs exact ANI.
    todo: List[Tuple[int, int]] = []
    for i in range(len(genomes)):
        if i in reps:
            continue
        for r in rep_list:
            if not ani_cache.contains((i, r)) and pre_cache.contains((i, r)):
                todo.append((r, i))
    anis = _batch_ani(clusterer, skip_clusterer, pre_cache, genomes, todo,
                      warm_cache, computed_log=computed)
    for (r, i), ani in zip(todo, anis):
        ani_cache.insert((r, i), ani)  # None recorded too, as the ref does

    for i in range(len(genomes)):
        if i in reps:
            continue
        best_rep = None
        best_ani = None
        for r in rep_list:
            ani = ani_cache.get((i, r))
            if consulted is not None:
                consulted.add(pair_key(i, r))
            if ani is not None and (best_ani is None or ani > best_ani):
                best_rep = r
                best_ani = ani
        if best_rep is None:
            raise RuntimeError(
                f"genome {genomes[i]} passed the representative test but "
                "has no ANI to any representative — inconsistent backend")
        clusters[rep_to_cluster[best_rep]].append(i)
    return clusters
