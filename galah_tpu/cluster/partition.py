"""Single-linkage precluster partitioning via union-find.

Equivalent of the reference's partition_sketches + DisjointSetVec
(reference: src/clusterer.rs:409-431): every cached pair joins its two
genomes; connected components become preclusters, each sorted ascending,
and the precluster list is ordered biggest-first so large components are
scheduled before small ones (reference: src/clusterer.rs:45-57).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple


class UnionFind:
    def __init__(self, n: int) -> None:
        self.parent = list(range(n))
        self.rank = [0] * n

    def find(self, x: int) -> int:
        root = x
        while self.parent[root] != root:
            root = self.parent[root]
        while self.parent[x] != root:  # path compression
            self.parent[x], x = root, self.parent[x]
        return root

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return
        if self.rank[ra] < self.rank[rb]:
            ra, rb = rb, ra
        self.parent[rb] = ra
        if self.rank[ra] == self.rank[rb]:
            self.rank[ra] += 1


def partition_preclusters(
    n_genomes: int, pair_keys: Iterable[Tuple[int, int]]
) -> List[List[int]]:
    """Connected components of the thresholded pair graph, biggest first.

    Ties in size keep the component of the lowest genome index first
    (stable, unlike the reference's unstable sort — deterministic output).
    """
    uf = UnionFind(n_genomes)
    for i, j in pair_keys:
        uf.union(i, j)
    comps: dict[int, List[int]] = {}
    for g in range(n_genomes):
        comps.setdefault(uf.find(g), []).append(g)
    out = [sorted(members) for members in comps.values()]
    out.sort(key=lambda c: (-len(c), c[0]))
    return out
