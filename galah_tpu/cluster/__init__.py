from galah_tpu.cluster.cache import PairDistanceCache  # noqa: F401
from galah_tpu.cluster.engine import cluster  # noqa: F401
