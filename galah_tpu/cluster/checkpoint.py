"""Checkpoint / resume for long clustering runs.

The reference keeps ALL intermediate state in memory — a crash at hour
N of a 50k-genome run loses everything (SURVEY.md §5: no
checkpoint/resume subsystem exists). Here the two expensive phases
persist incrementally:

  1. the precluster distance pass result (the sparse pair cache) is
     saved once, right after it completes;
  2. each precluster's finished clusters append to a log as the greedy
     phase walks the precluster list (big-first order is deterministic,
     so the resume point is well-defined).

A checkpoint is bound to a *fingerprint* — genome list (paths in quality
order), thresholds, methods — so resuming with different inputs starts
fresh instead of corrupting results. Everything is plain npz/json under
one directory; delete the directory to force a full re-run.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.io import atomic

logger = logging.getLogger(__name__)

_FINGERPRINT = "fingerprint.json"
_DISTANCES = "precluster_distances.npz"
_CLUSTERS = "clusters.jsonl"
_GREEDY = "greedy_rounds.jsonl"
_INTERRUPTIONS = "interruptions.jsonl"


def fingerprint_fields(genomes: Sequence[str], precluster_method: str,
                       cluster_method: str, ani: float,
                       precluster_ani: float,
                       min_aligned_fraction: float = 0.0,
                       fragment_length: int = 0,
                       backend_params: Optional[dict] = None
                       ) -> Dict[str, Any]:
    """The dict run_fingerprint hashes, also stored verbatim in
    fingerprint.json so a mismatch can name WHICH field changed.

    Genome paths are realpath-normalized first: `./a.fna`, `a.fna` and
    an absolute path to the same file must produce the same
    fingerprint, or a resume launched from a different cwd (or through
    a symlinked data dir) silently discards a valid checkpoint."""
    import galah_tpu

    return {
        "version": getattr(galah_tpu, "__version__", "0"),
        "genomes": [os.path.realpath(g) for g in genomes],
        "precluster_method": precluster_method,
        "cluster_method": cluster_method,
        "ani": ani,
        "precluster_ani": precluster_ani,
        "min_aligned_fraction": min_aligned_fraction,
        "fragment_length": fragment_length,
        "backend_params": backend_params or {},
    }


def fields_digest(fields: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(fields, sort_keys=True).encode()).hexdigest()


def run_fingerprint(genomes: Sequence[str], precluster_method: str,
                    cluster_method: str, ani: float,
                    precluster_ani: float,
                    min_aligned_fraction: float = 0.0,
                    fragment_length: int = 0,
                    backend_params: Optional[dict] = None) -> str:
    """Hash of everything that affects clustering results — any change
    invalidates the checkpoint rather than silently resuming stale
    state. `backend_params` carries sketch-level settings (MinHash
    sketch_size/k/seed, HLL p, marker-screen threshold, ...) so a resume
    under different sketching parameters starts fresh; the tool version
    is always included since kernel changes can shift distances."""
    return fields_digest(fingerprint_fields(
        genomes, precluster_method, cluster_method, ani,
        precluster_ani, min_aligned_fraction, fragment_length,
        backend_params))


class ClusterCheckpoint:
    """One run's resumable state under `path` (None disables)."""

    def __init__(self, path: Optional[str], fingerprint: str,
                 fields: Optional[Dict[str, Any]] = None,
                 require_match: bool = False) -> None:
        self.path = path
        self.fingerprint = fingerprint
        self.fields = fields
        self.matched_existing = False
        if not path:
            return
        os.makedirs(path, exist_ok=True)
        # a writer killed mid-write leaves *.tmp debris; the checkpoint
        # dir is single-owner, so sweep unconditionally at open
        atomic.sweep_tmp(path)
        fp_file = os.path.join(path, _FINGERPRINT)
        stored: Dict[str, Any] = {}
        if os.path.exists(fp_file):
            try:
                with open(fp_file) as f:
                    stored = json.load(f)
            except (OSError, ValueError):
                stored = {}
            existing = stored.get("fingerprint")
            if existing == fingerprint:
                self.matched_existing = True
            else:
                self._log_mismatch(stored.get("fields"))
                if require_match:
                    raise ValueError(
                        f"--resume: checkpoint at {path} belongs to a "
                        f"different run configuration (fingerprint "
                        f"{existing!r} != {fingerprint!r})")
                for name in (_FINGERPRINT, _DISTANCES, _CLUSTERS,
                             _GREEDY, _INTERRUPTIONS):
                    try:
                        os.unlink(os.path.join(path, name))
                    except FileNotFoundError:
                        pass
        elif require_match:
            raise ValueError(
                f"--resume: no checkpoint fingerprint at {path}")
        if (not self.matched_existing
                or (fields is not None
                    and stored.get("fields") != fields)):
            atomic.write_json(fp_file, {"fingerprint": fingerprint,
                                        "fields": fields})

    def _log_mismatch(self, stored_fields: Optional[Dict[str, Any]]
                      ) -> None:
        """Name the fields that differ — "fingerprint mismatch" alone
        sends operators diffing sha256 inputs by hand."""
        if stored_fields and self.fields:
            diffs = [k for k in sorted(set(stored_fields)
                                       | set(self.fields))
                     if stored_fields.get(k) != self.fields.get(k)]
            logger.warning(
                "Checkpoint at %s belongs to a different run "
                "configuration (mismatched fields: %s); starting fresh",
                self.path, ", ".join(diffs) or "<unknown>")
            for k in diffs:
                logger.warning("  %s: checkpoint=%r, run=%r", k,
                               stored_fields.get(k), self.fields.get(k))
        else:
            logger.warning(
                "Checkpoint at %s belongs to a different run "
                "configuration; starting fresh", self.path)

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def state_token(self) -> bytes:
        """Digest of the resumable state (distances file bytes + the
        completed-precluster ids): hosts compare these to make a
        multi-host resume all-or-nothing (cli.py), since resuming from
        UNEVEN checkpoints would desynchronize the collective-
        participating distance pass across processes."""
        h = hashlib.sha256()
        if not self.enabled:
            return h.digest()
        fn = os.path.join(self.path, _DISTANCES)
        if os.path.exists(fn):
            with open(fn, "rb") as f:
                h.update(f.read())
        done = sorted(self.load_completed())
        h.update(json.dumps(done).encode())
        # the greedy-round ANI log feeds the deterministic round replay
        # on every host; uneven logs would desynchronize the sharded
        # ANI exchange, so it is part of the all-or-nothing comparison
        gn = os.path.join(self.path, _GREEDY)
        if os.path.exists(gn):
            with open(gn, "rb") as f:
                h.update(f.read())
        return h.digest()

    def reset_state(self) -> None:
        """Drop the resumable state (keep the fingerprint): the next
        run recomputes from scratch on every host, symmetrically."""
        if not self.enabled:
            return
        for name in (_DISTANCES, _CLUSTERS, _GREEDY):
            try:
                os.unlink(os.path.join(self.path, name))
            except FileNotFoundError:
                pass

    # -- precluster distance pass ------------------------------------

    def load_distances(self) -> Optional[PairDistanceCache]:
        if not self.enabled:
            return None
        fn = os.path.join(self.path, _DISTANCES)
        if not os.path.exists(fn):
            return None
        with np.load(fn) as z:
            ii, jj = z["ii"], z["jj"]
            vals, has_val = z["vals"], z["has_val"]
        cache = PairDistanceCache()
        for i, j, v, hv in zip(ii.tolist(), jj.tolist(),
                               vals.tolist(), has_val.tolist()):
            cache.insert((i, j), float(v) if hv else None)
        logger.info("Resumed precluster distances from checkpoint "
                    "(%d pairs)", len(cache))
        return cache

    def save_distances(self, cache: PairDistanceCache) -> None:
        if not self.enabled:
            return
        keys = sorted(cache.keys())
        ii = np.array([k[0] for k in keys], dtype=np.int64)
        jj = np.array([k[1] for k in keys], dtype=np.int64)
        has_val = np.array([cache.get(k) is not None for k in keys],
                           dtype=bool)
        vals = np.array([cache.get(k) or 0.0 for k in keys],
                        dtype=np.float64)
        atomic.write_npz(os.path.join(self.path, _DISTANCES),
                         {"ii": ii, "jj": jj, "vals": vals,
                          "has_val": has_val},
                         site="io.atomic.write[ckpt.distances]")
        logger.info("Checkpointed precluster distances (%d pairs)",
                    len(cache))

    # -- greedy phase, per-precluster --------------------------------

    def load_completed(self) -> Dict[int, List[List[int]]]:
        """{precluster index -> its clusters (global genome ids)}."""
        out: Dict[int, List[List[int]]] = {}
        if not self.enabled:
            return out
        fn = os.path.join(self.path, _CLUSTERS)
        records, bad = atomic.read_jsonl(fn)
        if bad:
            # torn tail from a kill mid-write: drop it (that
            # precluster just recomputes) rather than failing resume
            logger.warning(
                "Dropped %d torn checkpoint record(s) (torn tail or "
                "corrupt frame) in %s", bad, fn)
        for rec in records:
            out[int(rec["precluster"])] = rec["clusters"]
        if out:
            logger.info("Resuming: %d preclusters already clustered",
                        len(out))
        return out

    def save_precluster(self, index: int,
                        clusters: List[List[int]]) -> None:
        if not self.enabled:
            return
        atomic.append_jsonl(os.path.join(self.path, _CLUSTERS),
                            {"precluster": index, "clusters": clusters},
                            site="io.atomic.append[ckpt.clusters]")

    # -- greedy phase, per-round (device strategy) --------------------
    #
    # The device strategy's rounds are deterministic given the ANI
    # values, so round-granular resume stores ONLY the backend-computed
    # (i, j, ani) triples each round produced — a persistent ANI cache,
    # no decision state. A resume replays the values and re-derives
    # every decision with zero dispatches up to the crash point. Each
    # record is digest-bound to the pending-precluster sequence it was
    # computed for (engine._greedy_digest); stale records are ignored.

    def load_greedy_rounds(
            self, digest: str) -> List[tuple]:
        """All (i, j, ani-or-None) triples recorded for `digest`."""
        out: List[tuple] = []
        if not self.enabled:
            return out
        fn = os.path.join(self.path, _GREEDY)
        records, bad = atomic.read_jsonl(fn)
        if bad:
            # torn tail from a kill mid-write: that round just
            # recomputes its pairs
            logger.warning(
                "Dropped %d torn/corrupt greedy-round record(s) in %s",
                bad, fn)
        for rec in records:
            if rec.get("digest") != digest:
                continue
            for i, j, ani in rec["pairs"]:
                out.append((int(i), int(j),
                            float(ani) if ani is not None else None))
        if out:
            logger.info("Resuming: replaying %d greedy-round ANI pairs",
                        len(out))
        return out

    def save_greedy_round(self, digest: str,
                          pairs: List[tuple]) -> None:
        if not self.enabled:
            return
        atomic.append_jsonl(
            os.path.join(self.path, _GREEDY),
            {"digest": digest,
             "pairs": [[i, j, ani] for i, j, ani in pairs]},
            site="io.atomic.append[ckpt.greedy]")

    def clear_greedy_rounds(self) -> None:
        """Drop the round log once its preclusters have all been saved
        to the clusters log (the durable form)."""
        if not self.enabled:
            return
        try:
            os.unlink(os.path.join(self.path, _GREEDY))
        except FileNotFoundError:
            pass

    # -- interruption / resume chain ----------------------------------
    #
    # One record per cooperative preemption, appended by the CLI as it
    # exits with EXIT_PREEMPTED. A resume reads the chain to report
    # `resumed_from` and how many interruptions preceded it
    # (run_report.json "preemption" section); the chaos harness asserts
    # the chain is present and consistent after every kill/resume.

    def record_interruption(self, info: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        atomic.append_jsonl(os.path.join(self.path, _INTERRUPTIONS),
                            info,
                            site="io.atomic.append[ckpt.interrupts]")

    def load_interruptions(self) -> List[Dict[str, Any]]:
        if not self.enabled:
            return []
        records, bad = atomic.read_jsonl(
            os.path.join(self.path, _INTERRUPTIONS))
        if bad:
            logger.warning(
                "Dropped %d torn interruption record(s) in %s", bad,
                self.path)
        return records
