"""Fleet supervisor: launch, watch, reassign preemptible workers.

One ``galah-tpu cluster`` worker subprocess per shard (own session, so
pgid == pid and signals reach the whole worker tree), at most
``workers`` live at once. Liveness is judged three ways and all three
are the SAME event — preemption: exit 75 (cooperative), death by
signal (SIGKILL'd spot capacity), and a stale heartbeat (wedged
worker, killed by the supervisor). A preempted shard goes back to
pending and is reassigned to a fresh worker that resumes from the
shard's checkpoint chain; worker-fault preemptions are budgeted by
resilience/policy RetryPolicy (``GALAH_TPU_FLEET_RETRY_*``), and a
shard that exhausts the budget is quarantined with a
``fleet-shard-failed`` event instead of wedging the fleet.

Everything the supervisor decides is event-sourced into
``fleet_events.jsonl`` (io/atomic framed appends) BEFORE it acts, so a
scheduler that is itself SIGKILL'd replays the log on restart, adopts
or kills the orphaned workers it finds, and continues — the chaos
harness (scripts/chaos_run.py --workload fleet) kills both workers
and the scheduler and asserts byte-identical convergence.

Import discipline: no accelerator imports — ``galah-tpu fleet status``
renders from this module on hosts with no device, and the sanitizer
imports it under GALAH_SAN=1.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from galah_tpu.fleet import plan as plan_mod
from galah_tpu.fleet.plan import ShardSpec
from galah_tpu.io import atomic
from galah_tpu.obs import events as obs_events
from galah_tpu.obs import metrics
from galah_tpu.obs.heartbeat import read_latest_beat
from galah_tpu.resilience import interrupt
from galah_tpu.resilience.policy import RetryPolicy

logger = logging.getLogger(__name__)

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx):
# the supervisor is a single-threaded poll loop on the main thread —
# worker parallelism lives in subprocesses, not threads, so there is
# no locked shared state to declare.
GUARDED_BY = {}
LOCK_ORDER = []

#: The shard artifact a worker must leave behind for the merge
#: (cluster/checkpoint.py's distance-cache file).
DISTANCES_FILENAME = "precluster_distances.npz"

#: Preemption reasons that are scheduler-side (interruption/adoption),
#: not worker faults — they trigger reassignment but never charge the
#: shard's retry budget, or an interrupted-and-resumed fleet would
#: quarantine healthy shards.
UNCHARGED_REASONS = frozenset({"fleet-interrupted", "orphaned"})


def _wall() -> float:
    return time.time()  # galah-lint: ignore[GL701] event timestamp


def append_stamp(fleet_dir: str, ev: str, **fields: Any) -> None:
    """Append one timestamped event to the fleet event log.

    Shared by the scheduler and by post-supervise phases (merge,
    finalize) in the CLI so the rollup aggregator (obs/fleet_view)
    can reconstruct the fleet wall from a single ordered log."""
    rec: Dict[str, Any] = {"ev": ev, "ts": _wall()}
    rec.update(fields)
    atomic.append_jsonl(plan_mod.events_path(fleet_dir), rec,
                        site="fleet-events")


def shard_root(fleet_dir: str, shard_id: int) -> str:
    return plan_mod.shard_dir(fleet_dir, shard_id)


def shard_ckpt_dir(fleet_dir: str, shard_id: int) -> str:
    return os.path.join(shard_root(fleet_dir, shard_id), "ckpt")


def shard_report_path(fleet_dir: str, shard_id: int) -> str:
    return os.path.join(shard_root(fleet_dir, shard_id),
                        "run_report.json")


def shard_heartbeat_path(fleet_dir: str, shard_id: int) -> str:
    # the worker's heartbeat thread writes beside its run report
    return os.path.join(shard_root(fleet_dir, shard_id),
                        "heartbeat.jsonl")


def shard_tsv_path(fleet_dir: str, shard_id: int) -> str:
    return os.path.join(shard_root(fleet_dir, shard_id),
                        "clusters.tsv")


def shard_distances_path(fleet_dir: str, shard_id: int) -> str:
    return os.path.join(shard_ckpt_dir(fleet_dir, shard_id),
                        DISTANCES_FILENAME)


@dataclass
class _ShardRuntime:
    spec: ShardSpec
    attempts: int = 0              # launches, lifetime (replayed)
    faults: int = 0                # budget-charged preemptions
    status: str = "pending"        # pending|running|done|failed
    proc: Optional[subprocess.Popen] = None
    pgid: Optional[int] = None
    launched_wall: float = 0.0
    next_eligible_mono: float = 0.0
    preemptions: List[str] = field(default_factory=list)


class FleetScheduler:
    """Supervise one fleet run over ``shards`` inside ``fleet_dir``.

    ``worker_argv(spec, resume)`` builds the worker command line (the
    CLI owns flag names; the scheduler owns lifecycle). ``run()``
    returns the snapshot dict mirrored into the run report's ``fleet``
    section; it raises PreemptionRequested through interrupt.check
    when the supervisor itself is being preempted.
    """

    def __init__(self, fleet_dir: str, shards: Sequence[ShardSpec],
                 worker_argv: Callable[[ShardSpec, bool], List[str]],
                 workers: int = 2, stale_s: float = 30.0,
                 poll_s: float = 0.2, heartbeat_s: float = 1.0,
                 policy: Optional[RetryPolicy] = None,
                 env: Optional[Dict[str, str]] = None) -> None:
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.fleet_dir = fleet_dir
        self.worker_argv = worker_argv
        self.workers = workers
        self.stale_s = stale_s
        self.poll_s = poll_s
        self.heartbeat_s = heartbeat_s
        self.policy = policy or RetryPolicy.from_env(
            "GALAH_TPU_FLEET_RETRY", defaults={"seed": 0})
        self.base_env = dict(os.environ if env is None else env)
        # worker faults are injected by the chaos harness at the
        # SUPERVISOR level (kills); never re-inject io faults inside
        # workers or the reference-vs-fleet comparison loses meaning
        self.base_env.pop("GALAH_FI", None)
        self.base_env["GALAH_OBS_HEARTBEAT_S"] = (
            str(self.heartbeat_s) if self.heartbeat_s > 0 else "0")
        # Orphan adoption must recognise workers launched by a PRIOR
        # scheduler over the same fleet dir, so the stamp is
        # deterministic per fleet dir, not per scheduler instance.
        # Only processes we Popen carry it in their environment —
        # matching /proc/<pid>/environ instead of cmdline means a
        # bystander whose argv merely names a shard path (e.g.
        # `galah-tpu top <fleet_dir>/shards/...`) is never killable.
        self._worker_stamp = ("GALAH_TPU_FLEET_WORKER="
                              + os.path.abspath(self.fleet_dir))
        self.base_env["GALAH_TPU_FLEET_WORKER"] = os.path.abspath(
            self.fleet_dir)
        self.shards = [_ShardRuntime(spec=s) for s in shards]
        self.preemptions = 0
        self.reassignments = 0
        self.retry_spend_s = 0.0
        self.resumed = False

    # ---------------------------------------------------------- events

    def _append_event(self, ev: str, **fields: Any) -> None:
        append_stamp(self.fleet_dir, ev, **fields)

    def _replay_events(self) -> List[Dict[str, Any]]:
        records, torn = atomic.read_jsonl(
            plan_mod.events_path(self.fleet_dir))
        if torn:
            logger.warning("fleet event log: %d torn record(s) skipped",
                           torn)
        launched_pids: Dict[int, int] = {}
        for rec in records:
            if not isinstance(rec, dict):
                continue
            ev = rec.get("ev")
            sid = rec.get("shard")
            rt = (self.shards[sid] if isinstance(sid, int)
                  and 0 <= sid < len(self.shards) else None)
            if rt is None:
                continue
            if ev == "shard-launched":
                rt.attempts += 1
                launched_pids[sid] = int(rec.get("pid") or 0)
            elif ev == "shard-started":
                launched_pids[sid] = int(rec.get("pid") or 0)
            elif ev == "shard-preempted":
                reason = str(rec.get("reason") or "unknown")
                rt.preemptions.append(reason)
                self.preemptions += 1
                self.reassignments += 1
                if reason not in UNCHARGED_REASONS:
                    rt.faults += 1
                launched_pids.pop(sid, None)
            elif ev == "shard-done":
                rt.status = "done"
                launched_pids.pop(sid, None)
            elif ev == "fleet-shard-failed":
                rt.status = "failed"
                launched_pids.pop(sid, None)
        if records:
            self.resumed = True
        # launched-but-unaccounted pids are orphans of a killed
        # scheduler: adopt by killing (their checkpoints make the
        # relaunch cheap) — but only after proving the pid is still
        # OUR worker, not a recycled pid
        for sid, pid in launched_pids.items():
            rt = self.shards[sid]
            if rt.status in ("done", "failed"):
                continue
            if pid > 0 and self._is_our_worker(pid):
                try:
                    os.killpg(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            self._preempt(rt, "orphaned", charge=False)
        if self.resumed:
            self._sweep_orphans()
        return records

    def _sweep_orphans(self) -> None:
        """Belt over the pid bookkeeping: a scheduler killed between
        the pre-act launch record and the pid record leaves a worker
        no event names. Sweep /proc for processes carrying OUR fleet
        dir's worker stamp and kill their groups before relaunching
        anything — two writers on one shard checkpoint would race."""
        try:
            pids = [int(p) for p in os.listdir("/proc")
                    if p.isdigit()]
        except OSError:
            return
        me = os.getpid()
        for pid in pids:
            if pid != me and self._is_our_worker(pid):
                try:
                    os.killpg(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

    def _is_our_worker(self, pid: int) -> bool:
        # environ (NUL-framed, same-uid readable) is spoof-proof where
        # cmdline is not: only our Popen'd workers inherit the stamp
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                env_blob = f.read()
        except OSError:
            return False
        return self._worker_stamp.encode() in env_blob.split(b"\0")

    # ------------------------------------------------------- lifecycle

    def _launch(self, rt: _ShardRuntime) -> None:
        sid = rt.spec.shard_id
        os.makedirs(shard_root(self.fleet_dir, sid), exist_ok=True)
        # a worker SIGKILL'd mid report-write leaves *.tmp in its
        # shard root; the root is single-owner between launches, so
        # sweep before handing it to the next attempt (the worker's
        # own checkpoint open sweeps the ckpt subdir)
        atomic.sweep_tmp(shard_root(self.fleet_dir, sid))
        # the previous attempt's heartbeat must not outlive it: left
        # in place, its last beat reads as instantly-stale before the
        # new worker's first beat lands (belt over the launch-wall
        # floor in _poll_one, and keeps `fleet status` beat ages sane)
        try:
            os.unlink(shard_heartbeat_path(self.fleet_dir, sid))
        except OSError:
            pass
        resume = os.path.exists(os.path.join(
            shard_ckpt_dir(self.fleet_dir, sid), "fingerprint.json"))
        argv = self.worker_argv(rt.spec, resume)
        rt.attempts += 1
        self._append_event("shard-launched", shard=sid,
                           attempt=rt.attempts, resume=resume, pid=-1)
        proc = subprocess.Popen(argv, env=self.base_env,
                                stdout=subprocess.DEVNULL,
                                start_new_session=True)
        rt.proc = proc
        rt.pgid = proc.pid
        rt.launched_wall = _wall()
        rt.status = "running"
        interrupt.register_worker_group(proc.pid)
        # second append with the real pid: the pre-act record above
        # guarantees the attempt is never invisible to a replay even
        # if the scheduler dies inside Popen
        self._append_event("shard-started", shard=sid,
                           attempt=rt.attempts, pid=proc.pid)
        logger.info("fleet: shard %d attempt %d -> pid %d%s", sid,
                    rt.attempts, proc.pid,
                    " (resume)" if resume else "")

    def _preempt(self, rt: _ShardRuntime, reason: str,
                 charge: bool = True) -> None:
        sid = rt.spec.shard_id
        if rt.pgid is not None:
            interrupt.unregister_worker_group(rt.pgid)
        rt.proc = None
        rt.pgid = None
        self._append_event("shard-preempted", shard=sid,
                           attempt=rt.attempts, reason=reason)
        obs_events.record("fleet-preempted", shard=sid, reason=reason)
        rt.preemptions.append(reason)
        self.preemptions += 1
        self.reassignments += 1
        if charge and reason not in UNCHARGED_REASONS:
            rt.faults += 1
        if rt.faults >= self.policy.max_attempts:
            rt.status = "failed"
            self._append_event("fleet-shard-failed", shard=sid,
                               attempts=rt.attempts, faults=rt.faults)
            obs_events.record("fleet-shard-failed", shard=sid,
                              attempts=rt.attempts)
            logger.error(
                "fleet: shard %d quarantined after %d fault(s) "
                "(retry budget %d)", sid, rt.faults,
                self.policy.max_attempts)
            return
        backoff = self.policy.delay(max(0, rt.faults - 1),
                                    site=f"fleet-shard-{sid}")
        if reason in UNCHARGED_REASONS:
            backoff = 0.0
        rt.next_eligible_mono = time.monotonic() + backoff
        self.retry_spend_s += backoff
        if backoff > 0:
            # rollup-ready stamp: fleet_view charges this window to
            # scheduler blame (backoff bucket) without re-deriving the
            # retry policy from env
            self._append_event("shard-backoff", shard=sid,
                               backoff_s=round(backoff, 6))
        rt.status = "pending"
        logger.warning("fleet: shard %d preempted (%s), reassigning",
                       sid, reason)

    def _kill_group(self, rt: _ShardRuntime) -> None:
        if rt.pgid is None:
            return
        try:
            os.killpg(rt.pgid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        if rt.proc is not None:
            try:
                rt.proc.wait(timeout=10)
            except Exception:
                logger.debug("worker wait after kill failed",
                             exc_info=True)

    def _poll_one(self, rt: _ShardRuntime) -> None:
        sid = rt.spec.shard_id
        proc = rt.proc
        if proc is None:
            return
        rc = proc.poll()
        if rc is None:
            if self.heartbeat_s > 0 and self.stale_s > 0:
                beat = read_latest_beat(
                    shard_heartbeat_path(self.fleet_dir, sid))
                # heartbeat.jsonl can survive a killed attempt (and a
                # killed scheduler); beats older than THIS attempt's
                # launch must not age it, or every resumed worker is
                # stale-killed on the first poll tick
                ref = rt.launched_wall
                if beat:
                    ref = max(ref, float(beat.get("ts") or 0.0))
                if _wall() - ref > self.stale_s:
                    self._kill_group(rt)
                    self._preempt(rt, "stale-heartbeat")
            return
        if rc == 0:
            if os.path.exists(
                    shard_distances_path(self.fleet_dir, sid)):
                if rt.pgid is not None:
                    interrupt.unregister_worker_group(rt.pgid)
                rt.proc = None
                rt.pgid = None
                rt.status = "done"
                self._append_event("shard-done", shard=sid,
                                   attempt=rt.attempts)
                logger.info("fleet: shard %d done (attempt %d)", sid,
                            rt.attempts)
            else:
                # exit 0 without the merge artifact: treat as a fault
                # so the budget bounds a worker that "succeeds" wrong
                self._preempt(rt, "no-distances")
        elif rc == interrupt.EXIT_PREEMPTED:
            self._preempt(rt, "exit-75")
        elif rc < 0:
            self._preempt(rt, f"signal-{-rc}")
        else:
            self._preempt(rt, f"exit-{rc}")

    def _launch_eligible(self) -> None:
        live = sum(1 for rt in self.shards if rt.status == "running")
        now = time.monotonic()
        for rt in self.shards:  # shard order: deterministic placement
            if live >= self.workers:
                return
            if (rt.status == "pending"
                    and rt.next_eligible_mono <= now):
                self._launch(rt)
                live += 1

    def _shutdown_workers(self) -> None:
        """Cooperative-stop path: SIGTERM every live worker group and
        give them one staleness window to reach a safe boundary, then
        SIGKILL the stragglers. Shards go back to pending uncharged —
        the resume relaunches them."""
        live = [rt for rt in self.shards if rt.status == "running"]
        for rt in live:
            if rt.pgid is None:
                continue
            try:
                os.killpg(rt.pgid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.monotonic() + max(self.stale_s, 5.0)
        for rt in live:
            if rt.proc is None:
                continue
            remaining = deadline - time.monotonic()
            try:
                rt.proc.wait(timeout=max(0.1, remaining))
            except subprocess.TimeoutExpired:
                self._kill_group(rt)
            self._preempt(rt, "fleet-interrupted", charge=False)

    def _update_gauges(self) -> None:
        live = sum(1 for rt in self.shards if rt.status == "running")
        done = sum(1 for rt in self.shards if rt.status == "done")
        metrics.gauge("fleet.workers_live",
                      help="live fleet worker subprocesses").set(live)
        metrics.gauge("fleet.shards_done",
                      help="shards completed").set(done)
        metrics.gauge("fleet.preemptions",
                      help="worker preemptions observed"
                      ).set(self.preemptions)
        metrics.gauge("fleet.reassignments",
                      help="shard reassignments to fresh workers"
                      ).set(self.reassignments)

    # ------------------------------------------------------------- run

    def run(self) -> Dict[str, Any]:
        os.makedirs(self.fleet_dir, exist_ok=True)
        # the fleet dir is single-owner (one supervisor): a scheduler
        # killed mid plan/event write leaves *.tmp only here
        atomic.sweep_tmp(self.fleet_dir)
        self._replay_events()
        try:
            while True:
                if interrupt.stop_requested():
                    self._shutdown_workers()
                    self._append_event("fleet-interrupted")
                    self._update_gauges()
                    interrupt.check("fleet-poll")
                for rt in self.shards:
                    self._poll_one(rt)
                self._launch_eligible()
                self._update_gauges()
                if all(rt.status in ("done", "failed")
                       for rt in self.shards):
                    break
                time.sleep(self.poll_s)
        finally:
            # never leak workers past the supervisor, whatever raised
            for rt in self.shards:
                if rt.status == "running":
                    self._kill_group(rt)
        self._update_gauges()
        # rollup-ready stamp: marks the supervise-phase end so the
        # aggregator can split fleet wall into supervise vs merge even
        # when the final run report never lands (scheduler killed later)
        self._append_event(
            "fleet-supervise-done",
            shards_done=sum(1 for rt in self.shards
                            if rt.status == "done"),
            retry_spend_s=round(self.retry_spend_s, 6))
        return self.snapshot()

    def snapshot(self) -> Dict[str, Any]:
        shards = [{
            "shard_id": rt.spec.shard_id,
            "lo": rt.spec.lo,
            "hi": rt.spec.hi,
            "n_genomes": len(rt.spec.genomes),
            "attempts": rt.attempts,
            "status": rt.status,
            "preemptions": list(rt.preemptions),
        } for rt in self.shards]
        return {
            "fleet_dir": os.path.abspath(self.fleet_dir),
            "n_shards": len(self.shards),
            "workers": self.workers,
            "shards_done": sum(1 for s in shards
                               if s["status"] == "done"),
            "shards_failed": sum(1 for s in shards
                                 if s["status"] == "failed"),
            "preemptions": self.preemptions,
            "reassignments": self.reassignments,
            "retry_spend_s": round(self.retry_spend_s, 6),
            "resumed": self.resumed,
            "shards": shards,
        }


def render_status(fleet_dir: str) -> str:
    """Human rendering of a fleet dir's plan + event log + heartbeat
    ages — the ``galah-tpu fleet status`` body (accelerator-free)."""
    doc = plan_mod.load_plan(fleet_dir)
    if doc is None:
        return (f"no fleet plan at {plan_mod.plan_path(fleet_dir)} "
                "(run `galah-tpu fleet run` first)\n")
    shards = [ShardSpec.from_dict(d) for d in doc.get("shards", [])]
    records, torn = atomic.read_jsonl(plan_mod.events_path(fleet_dir))
    state: Dict[int, str] = {s.shard_id: "pending" for s in shards}
    attempts: Dict[int, int] = {s.shard_id: 0 for s in shards}
    preempts: Dict[int, int] = {s.shard_id: 0 for s in shards}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        sid = rec.get("shard")
        if sid not in state:
            continue
        ev = rec.get("ev")
        if ev == "shard-launched":
            attempts[sid] += 1
            state[sid] = "running"
        elif ev == "shard-preempted":
            preempts[sid] += 1
            state[sid] = "pending"
        elif ev == "shard-done":
            state[sid] = "done"
        elif ev == "fleet-shard-failed":
            state[sid] = "failed"
    lines = [f"fleet {fleet_dir}",
             f"  shards {len(shards)}  events {len(records)}"
             + (f"  ({torn} torn)" if torn else "")]
    for s in shards:
        hb = read_latest_beat(
            shard_heartbeat_path(fleet_dir, s.shard_id))
        age = ""
        if hb is not None and state[s.shard_id] == "running":
            age = (f"  beat-age "
                   f"{max(0.0, _wall() - float(hb.get('ts') or 0.0)):.1f}s")
        lines.append(
            f"  shard {s.shard_id:3d} [{s.lo}:{s.hi})  "
            f"{state[s.shard_id]:<8} attempts={attempts[s.shard_id]} "
            f"preemptions={preempts[s.shard_id]}{age}")
    return "\n".join(lines) + "\n"
