"""Deterministic cross-shard merge: shard caches + cross pairs + replay.

Why this is bit-identical to a single-process run (the exact-replay
argument index/incremental.py already makes, extended across shards):

  * the skani pair pipeline is subset-invariant — a pair's exact ANI
    depends only on the two genomes' fragment profiles, and the marker
    screen is a per-pair predicate, so a shard-local distances() run
    produces the SAME values for its intra-shard pairs as the full run
    would (and the v1 skani/skani gate in the CLI pins the shard
    threshold to the final ANI, so shard caches hold exactly the
    full-run cache restricted to intra-shard pairs);
  * the remaining cross-shard pairs are computed here through the same
    profile → screen → exact-ANI path, filtered to cross pairs only
    (SkaniPreclusterer.distances_subset);
  * the union, remapped to global quality-order indices by each
    shard's ``lo`` offset, IS the full-run pair cache, and replaying
    the greedy engine over it (index/incremental.screen_new_genomes +
    clusters_from_state) reproduces cluster/engine.py's decisions
    byte-for-byte.

A rep-only hierarchical merge is NOT used: a shard-local rep that
globally joins an earlier rep can locally absorb a genome that
globally becomes its own rep, so only the full-pair replay is safe.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np

from galah_tpu.fleet import scheduler as fleet_scheduler
from galah_tpu.fleet.plan import ShardSpec

logger = logging.getLogger(__name__)


def shard_lookup(shards: Sequence[ShardSpec]) -> Callable[[int], int]:
    """Global genome index -> shard id (contiguous spans)."""
    bounds = [(s.lo, s.hi, s.shard_id) for s in shards]

    def lookup(g: int) -> int:
        for lo, hi, sid in bounds:
            if lo <= g < hi:
                return sid
        raise IndexError(f"genome index {g} outside every shard")

    return lookup


def load_shard_pairs(fleet_dir: str, shards: Sequence[ShardSpec]
                     ) -> Dict[Tuple[int, int], float]:
    """Union of the shard checkpoints' distance caches, remapped from
    shard-local to global indices by each shard's ``lo`` offset."""
    pairs: Dict[Tuple[int, int], float] = {}
    for s in shards:
        path = fleet_scheduler.shard_distances_path(fleet_dir,
                                                    s.shard_id)
        with np.load(path) as z:
            ii, jj = z["ii"], z["jj"]
            vals, has_val = z["vals"], z["has_val"]
        kept = 0
        for i, j, v, hv in zip(ii.tolist(), jj.tolist(),
                               vals.tolist(), has_val.tolist()):
            if not hv:
                continue
            pairs[(i + s.lo, j + s.lo)] = float(v)
            kept += 1
        logger.info("fleet merge: shard %d contributed %d pair(s)",
                    s.shard_id, kept)
    return pairs


def cross_shard_pairs(genomes: Sequence[str],
                      shards: Sequence[ShardSpec],
                      preclusterer) -> Dict[Tuple[int, int], float]:
    """Thresholded exact ANI for every screened pair whose endpoints
    live in different shards (same code path as the full run)."""
    lookup = shard_lookup(shards)
    cache = preclusterer.distances_subset(
        genomes, lambda i, j: lookup(i) != lookup(j))
    return {k: cache.get(k) for k in cache.keys()
            if cache.get(k) is not None}


def merge(fleet_dir: str, genomes: Sequence[str],
          shards: Sequence[ShardSpec], preclusterer,
          ani_threshold: float) -> List[List[int]]:
    """Merge shard checkpoints into the final cluster list (global
    quality-order indices, cluster/engine.py output order)."""
    from galah_tpu.index.incremental import (clusters_from_state,
                                             screen_new_genomes)
    from galah_tpu.index.store import IndexState

    pairs = load_shard_pairs(fleet_dir, shards)
    n_within = len(pairs)
    pairs.update(cross_shard_pairs(genomes, shards, preclusterer))
    logger.info("fleet merge: %d within-shard + %d cross-shard pairs",
                n_within, len(pairs) - n_within)
    state = IndexState(generation=0, genomes=list(genomes), keys=[],
                       sketches=[], pairs=pairs, reps=[],
                       membership={}, tombstones=set())
    screen_new_genomes(state, 0, ani_threshold)
    return clusters_from_state(state)
