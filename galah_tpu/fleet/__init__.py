"""Elastic preemptible-fleet execution: shard, supervise, merge.

``plan`` slices the quality-ordered genome set into self-describing
shard specs; ``scheduler`` supervises one ``galah-tpu cluster`` worker
subprocess per shard (preemption-aware, bounded retries); ``merge``
recombines shard checkpoints into clusters bit-identical to a
single-process run. See docs/resilience.md "Fleet execution".

This package module stays stdlib-only at import: the run-report
assembler reads the snapshot below on hosts with no accelerator, and
must never drag jax (or even numpy) in through it.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

#: Last fleet run's summary, mirrored into the run report's
#: ``fleet`` section by obs/report.assemble (reset with reset_run).
_SNAPSHOT: Optional[Dict[str, Any]] = None


def set_snapshot(snap: Dict[str, Any]) -> None:
    global _SNAPSHOT
    _SNAPSHOT = dict(snap)


def snapshot() -> Optional[Dict[str, Any]]:
    return dict(_SNAPSHOT) if _SNAPSHOT is not None else None


def reset() -> None:
    global _SNAPSHOT
    _SNAPSHOT = None
