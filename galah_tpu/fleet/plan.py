"""Deterministic shard planning for fleet execution.

The quality-ordered genome set is sliced into ``n_shards`` contiguous
spans (sizes differing by at most one) so each shard's local greedy
pass sees the same intra-shard quality order a single-process run
would, and the merge can replay the global order from the shard ``lo``
offsets. The plan is self-describing and durable: ``fleet_plan.json``
stores the run-configuration fields verbatim next to their sha256
fingerprint (the cluster/checkpoint.py discipline), so a resume under
different inputs is named field-by-field instead of silently reusing
stale shards.

Import discipline: ``load_plan``/``ShardSpec`` stay accelerator-free so
``galah-tpu fleet status`` can render on hosts with no device; the
fingerprint digest (which reaches through cluster/checkpoint.py into
numpy) is imported lazily inside the writers.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

PLAN_FILENAME = "fleet_plan.json"
EVENTS_FILENAME = "fleet_events.jsonl"


@dataclass(frozen=True)
class ShardSpec:
    """One contiguous quality-order slice: genomes[lo:hi] (global
    indices), carrying the ORIGINAL path strings — outputs must echo
    paths exactly as given (outputs.write_outputs), realpaths live
    only inside fingerprints."""

    shard_id: int
    lo: int
    hi: int
    genomes: Tuple[str, ...]

    def to_dict(self) -> Dict[str, Any]:
        return {"shard_id": self.shard_id, "lo": self.lo,
                "hi": self.hi, "genomes": list(self.genomes)}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ShardSpec":
        return ShardSpec(shard_id=int(d["shard_id"]), lo=int(d["lo"]),
                         hi=int(d["hi"]),
                         genomes=tuple(d["genomes"]))


def build_plan(genomes: Sequence[str], n_shards: int) -> List[ShardSpec]:
    """Contiguous quality-order slices, sizes differing by ≤ 1, empty
    shards dropped (n_shards > len(genomes) is legal)."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    n = len(genomes)
    shards: List[ShardSpec] = []
    base, extra = divmod(n, n_shards)
    lo = 0
    for k in range(n_shards):
        hi = lo + base + (1 if k < extra else 0)
        if hi > lo:
            shards.append(ShardSpec(shard_id=len(shards), lo=lo, hi=hi,
                                    genomes=tuple(genomes[lo:hi])))
        lo = hi
    return shards


def plan_path(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, PLAN_FILENAME)


def events_path(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, EVENTS_FILENAME)


def shard_dir(fleet_dir: str, shard_id: int) -> str:
    return os.path.join(fleet_dir, "shards", f"shard_{shard_id:03d}")


def save_plan(fleet_dir: str, fields: Dict[str, Any],
              shards: Sequence[ShardSpec]) -> None:
    from galah_tpu.cluster.checkpoint import fields_digest
    from galah_tpu.io import atomic

    os.makedirs(fleet_dir, exist_ok=True)
    atomic.write_json(plan_path(fleet_dir), {
        "fingerprint": fields_digest(fields),
        "fields": fields,
        "shards": [s.to_dict() for s in shards],
    }, site="fleet-plan")


def load_plan(fleet_dir: str) -> Optional[Dict[str, Any]]:
    """The stored plan document, or None if absent/unreadable (a torn
    plan means no plan — ensure_plan rebuilds it)."""
    try:
        with open(plan_path(fleet_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _mismatched_fields(stored: Dict[str, Any],
                       current: Dict[str, Any]) -> List[str]:
    return [k for k in sorted(set(stored) | set(current))
            if stored.get(k) != current.get(k)]


def ensure_plan(fleet_dir: str, genomes: Sequence[str],
                fields: Dict[str, Any], n_shards: int,
                require_match: bool = False) -> List[ShardSpec]:
    """Load-or-create the shard plan, bound to the run fingerprint.

    ``fields`` is the cluster fingerprint_fields dict; ``n_shards`` is
    folded in (a different shard layout invalidates shard checkpoints'
    genome subsets). On mismatch: ``require_match`` (--resume) raises
    ValueError naming the differing fields; otherwise the stale plan
    and event log are dropped and a fresh plan is written (shard
    checkpoints self-reset via their own fingerprints)."""
    from galah_tpu.cluster.checkpoint import fields_digest

    plan_fields = dict(fields)
    plan_fields["n_shards"] = n_shards
    fingerprint = fields_digest(plan_fields)
    stored = load_plan(fleet_dir)
    if stored is not None:
        if stored.get("fingerprint") == fingerprint:
            return [ShardSpec.from_dict(d)
                    for d in stored.get("shards", [])]
        diffs = _mismatched_fields(stored.get("fields") or {},
                                   plan_fields)
        if require_match:
            raise ValueError(
                f"--resume: fleet plan at {plan_path(fleet_dir)} "
                f"belongs to a different run configuration "
                f"(mismatched fields: {', '.join(diffs) or '<unknown>'})")
        logger.warning(
            "Fleet plan at %s belongs to a different run configuration "
            "(mismatched fields: %s); starting fresh",
            plan_path(fleet_dir), ", ".join(diffs) or "<unknown>")
        for path in (plan_path(fleet_dir), events_path(fleet_dir)):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
    elif require_match:
        raise ValueError(
            f"--resume: no fleet plan at {plan_path(fleet_dir)}")
    shards = build_plan(genomes, n_shards)
    save_plan(fleet_dir, plan_fields, shards)
    return shards
