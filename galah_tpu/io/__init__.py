from galah_tpu.io.fasta import Genome, GenomeStats, read_genome  # noqa: F401
