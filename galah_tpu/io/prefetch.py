"""Bounded IO prefetching: overlap host FASTA ingestion with device work.

The sketching loops alternate `read_genome` (host IO + C parser) with a
device dispatch; a bounded look-ahead pool keeps the next genomes'
ingestion running while the device sketches the current one (the
reference gets the same overlap from rayon's par_iter over files,
reference: src/finch.rs:47 via sketch_files). Depth stays small so a
50k-genome run holds at most `depth` parsed genomes in memory — plus,
when process_stream runs with workers > 1, up to 2*workers more in its
in-flight window, so the bound is O(depth + workers), never O(N).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Sequence, Tuple, TypeVar

T = TypeVar("T")
V = TypeVar("V")

# One shared executor, lazily created and grown to the largest worker
# count ever requested. The greedy engine streams thousands of tiny
# per-precluster loads through these helpers; a pool per call (the
# original shape) measured ~100 s of pure thread create/join/lock
# overhead at N=100k (24k threads). Look-ahead bounds stay per-call —
# each caller keeps its own in-flight window, the pool is just where
# the work runs.
_POOL: ThreadPoolExecutor | None = None
_POOL_SIZE = 0
_POOL_LOCK = threading.Lock()

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx):
# pool replacement must be atomic with the size check or two callers
# could each install a pool and strand the other's generators.
GUARDED_BY = {
    "_POOL": "_POOL_LOCK",
    "_POOL_SIZE": "_POOL_LOCK",
}
LOCK_ORDER = ["_POOL_LOCK"]


def _shared_pool(workers: int) -> ThreadPoolExecutor:
    global _POOL, _POOL_SIZE
    workers = max(1, int(workers))
    with _POOL_LOCK:
        if _POOL is None or _POOL_SIZE < workers:
            # Replace WITHOUT shutting the old pool down: live
            # generators captured it and must keep submitting
            # (shutdown would raise RuntimeError mid-stream). Its
            # worker threads exit via the executor's weakref wind-down
            # once the last holder releases it.
            _POOL = ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix="galah-prefetch")
            _POOL_SIZE = workers
        return _POOL


def _adopting(fn: Callable[..., T]) -> Callable[..., T]:
    """Wrap a pool-submitted callable so timing/trace/flow emission
    from the prefetch thread attributes to the stage that SUBMITTED
    the work — without this, a worker's dispatch() (or flow span)
    lands on the thread-local stack of a pool thread that never
    entered any stage."""
    from galah_tpu.obs import flow as obs_flow
    from galah_tpu.utils import timing

    token = timing.stage_token()
    ftoken = obs_flow.token()

    def wrapped(*a):
        with timing.adopt(token), obs_flow.adopt(ftoken):
            return fn(*a)

    return wrapped


def _settle(futures) -> None:
    """Cancel queued look-ahead futures and wait out already-running
    ones, so an abandoned generator (close/GeneratorExit/exception)
    leaves no load_fn racing with the caller's cleanup — e.g. a
    temp-dir removal after the exception that abandoned the stream."""
    running = [f for f in futures if not f.cancel()]
    for f in running:
        # exception() waits for completion and RETURNS the worker's
        # error instead of raising it (the consumer is gone; nothing to
        # surface it to) — while an ambient KeyboardInterrupt delivered
        # to THIS thread still propagates rather than being swallowed.
        f.exception()


def probe_and_prefetch(
    paths: Sequence[str],
    probe: Callable[[str], "V | None"],
    load_fn: Callable[[str], T],
    depth: int = 2,
):
    """Split unique paths into cache hits and a prefetched miss stream.

    Returns (hits, miss_iter): `hits` maps each unique path whose
    `probe` returned non-None to that value; `miss_iter` yields
    (path, load_fn(path)) for the rest with bounded look-ahead. The one
    dedup + cache-probe + prefetch idiom shared by the sketching
    backends.
    """
    hits = {}
    misses = []
    for p in dict.fromkeys(paths):  # de-dup, keep order
        v = probe(p)
        if v is None:
            misses.append(p)
        else:
            hits[p] = v
    return hits, iter_prefetched(misses, load_fn, depth=depth)


def iter_batches(
    items: Iterator[Tuple[str, T]],
    size_fn: Callable[[T], int],
    budget: int,
    max_items: int = 512,
) -> Iterator[list]:
    """Group a (path, item) stream into buffers of at most `budget` total
    size (per `size_fn`) or `max_items` entries — the one
    accumulate-then-flush policy shared by the batched sketching
    backends. The underlying prefetch threads keep loading ahead while
    the caller processes each yielded buffer."""
    from galah_tpu.obs import flow as obs_flow

    buf: list = []
    total = 0
    for path, item in items:
        buf.append((path, item))
        total += int(size_fn(item))
        if total >= budget or len(buf) >= max_items:
            fid = obs_flow.begin("genome_batch")
            obs_flow.emit("ingest", fid)
            yield buf
            buf, total = [], 0
    if buf:
        fid = obs_flow.begin("genome_batch")
        obs_flow.emit("ingest", fid)
        yield buf


def process_stream(
    items: Iterator[Tuple[str, T]],
    size_fn: Callable[[T], int],
    budget: int,
    batch_fn: Callable[[list], list],
    single_fn: Callable[[str, T], V],
    batched: bool,
    workers: int = 1,
) -> Iterator[Tuple[str, V]]:
    """Yield (path, result) for a (path, item) stream — through grouped
    `batch_fn(buffer) -> [result]` calls when `batched` (TPU backends,
    where dispatch round trips dominate), else per-item
    `single_fn(path, item)` (CPU backends, where per-genome chunks are
    cache-friendlier). The one gate/batch/store shape shared by the
    three sketching backends.

    With workers > 1 (and not batched), single_fn runs on a thread pool
    with a bounded in-flight window — the native C kernels release the
    GIL, so multicore hosts sketch that many genomes concurrently
    (results stream back in submission order)."""
    if batched:
        from galah_tpu.obs import flow as obs_flow

        for buf in iter_batches(items, size_fn, budget):
            obs_flow.absorb("ingest", "sketch")
            for (p, _), r in zip(buf, batch_fn(buf)):
                yield p, r
    elif workers > 1:
        from collections import deque

        pool = _shared_pool(workers)
        it = iter(items)
        pending: deque = deque()

        def submit_next() -> bool:
            try:
                p, item = next(it)
            except StopIteration:
                return False
            pending.append((p, pool.submit(_adopting(single_fn),
                                           p, item)))
            return True

        try:
            for _ in range(2 * workers):
                if not submit_next():
                    break
            while pending:
                p, fut = pending.popleft()
                result = fut.result()
                submit_next()
                yield p, result
        finally:
            _settle(fut for _, fut in pending)
    else:
        for p, it_ in items:
            yield p, single_fn(p, it_)


def iter_prefetched(
    paths: Sequence[str],
    load_fn: Callable[[str], T],
    depth: int = 2,
) -> Iterator[Tuple[str, T]]:
    """Yield (path, load_fn(path)) in order, loading up to `depth`
    ahead on worker threads. Exceptions surface at the failing item's
    turn, preserving the sequential error behavior."""
    depth = max(1, int(depth))
    if not paths:
        return
    pool = _shared_pool(depth)
    pending = []
    try:
        for idx in range(min(depth, len(paths))):
            pending.append(pool.submit(_adopting(load_fn), paths[idx]))
        for i, path in enumerate(paths):
            fut = pending.pop(0)
            nxt = i + depth
            if nxt < len(paths):
                pending.append(pool.submit(_adopting(load_fn),
                                           paths[nxt]))
            yield path, fut.result()
    finally:
        _settle(pending)
