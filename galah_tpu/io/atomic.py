"""The single durable-write primitive for every crash-surviving artifact.

Before this module, five call sites hand-rolled their own tmp+rename
idiom with inconsistent fsync discipline (diskcache entries, the
quarantine manifest, checkpoint fingerprint/distances, run reports, the
perf-ledger append) — and none of them fsynced the parent directory, so
a host crash could lose the rename itself. A run killed at an arbitrary
instant (preemptible TPU slices, `kill -9`, ENOSPC mid-write) must
leave every durable artifact either absent, fully old, or fully new —
never torn. This module is the one place that guarantee lives:

  * whole-file artifacts (``write_bytes`` / ``write_text`` /
    ``write_json`` / ``write_npz``): unique tmp in the same directory,
    single write, ``fsync(file)``, ``os.replace``, ``fsync(dir)`` —
    the rename is the commit point and it is itself made durable;
  * append-only JSONL logs (``append_jsonl``): one ``O_APPEND``
    ``write()`` per record with checksum framing
    (``<compact-json>\\t<crc32hex>\\n``) and fsync — ``read_jsonl``
    verifies the checksum, tolerates torn tails and legacy unframed
    lines, and ``append_jsonl`` self-heals a torn tail by terminating
    it before the next record (so one crash never poisons the line
    that follows it);
  * ``sweep_tmp``: removes the ``*.tmp`` debris a killed writer left
    behind (age-gated for shared directories like the sketch cache).

Filesystem fault injection (GALAH_FI kinds ``enospc`` / ``eio`` /
``torn-write`` / ``slow-io`` / ``kill``, docs/resilience.md) fires
INSIDE these primitives, at named ``io.atomic.*`` sites — the chaos
harness (scripts/chaos_run.py) uses it to prove the
all-or-nothing claim by killing real runs mid-write.

Import discipline: stdlib only at module import (numpy lazily inside
``write_npz``, the fault injector lazily per call) — the perf-ledger
and report paths run on hosts with no accelerator and must never drag
jax in.

Lint: the GL806 rule (analysis/fs_check.py) flags any write-mode
``open()`` in the durable-artifact modules OUTSIDE this file, so new
persistence code cannot quietly regress to a hand-rolled idiom.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import tempfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: Frame separator between a JSONL payload and its crc32. A raw tab
#: cannot appear in a compact json.dumps payload — control characters
#: are always escaped in strings and the separators contain none — so
#: rpartition on it is unambiguous. (Deliberately NOT \x1e/\x1c/\x1d:
#: those are str.splitlines boundaries, and tooling that reads these
#: logs line-wise would split one record into two "lines".)
FRAME_SEP = "\t"

#: Default age gate for sweep_tmp in SHARED directories (sketch cache):
#: a .tmp younger than this may belong to a live concurrent writer.
SHARED_TMP_MAX_AGE_S = 3600.0


# ---------------------------------------------------------------------------
# Fault injection hook
# ---------------------------------------------------------------------------


def _fs_fault(site: str) -> Optional[str]:
    """Consult the GALAH_FI injector for filesystem faults at `site`.

    enospc/eio raise the corresponding OSError here; kill never
    returns (os._exit); slow-io sleeps; torn-write returns the kind so
    the caller can tear its own write (only the writer knows what a
    half-written record looks like)."""
    from galah_tpu.resilience import faults

    inj = faults.get_injector()
    if inj is None:
        return None
    return inj.filesystem(site)


def _site(default_kind: str, path: str, site: Optional[str]) -> str:
    return site or f"io.atomic.{default_kind}[{os.path.basename(path)}]"


# ---------------------------------------------------------------------------
# Whole-file artifacts: tmp + fsync + rename + dir-fsync
# ---------------------------------------------------------------------------


def fsync_dir(path: str) -> None:
    """Make a completed rename in `path` durable. Best-effort: some
    filesystems refuse O_RDONLY directory fds — the rename is still
    atomic there, only its durability window widens."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_bytes(path: str, data: bytes,
                site: Optional[str] = None) -> None:
    """Atomically replace `path` with `data`, durably.

    Readers see the old content or the new content, never a mixture;
    after return the new content survives power loss. On any failure
    the injected-crash tmp debris (if torn) or nothing is left —
    `path` itself is untouched."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    action = _fs_fault(_site("write", path, site))
    fd, tmp = tempfile.mkstemp(dir=parent,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        try:
            if action == "torn-write":
                # simulate a crash mid-write: half the payload reaches
                # the tmp, no cleanup runs (sweep_tmp collects it), and
                # the caller sees the write fail
                os.write(fd, data[:len(data) // 2])
                raise OSError(
                    errno.EIO, f"injected torn write ({tmp})")
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
    except OSError as e:
        if action != "torn-write":
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise e
    fsync_dir(parent)


def write_text(path: str, text: str,
               site: Optional[str] = None) -> None:
    write_bytes(path, text.encode("utf-8"), site=site)


def write_json(path: str, obj: Any, indent: Optional[int] = None,
               site: Optional[str] = None) -> None:
    write_bytes(
        path,
        (json.dumps(obj, indent=indent, sort_keys=True) + "\n").encode(
            "utf-8"),
        site=site)


def write_npz(path: str, arrays: Dict[str, Any],
              site: Optional[str] = None) -> None:
    """Atomic .npz: serialized fully in memory, then one durable
    write — a killed writer can never leave a half-zipped entry under
    the final name."""
    import io as _io

    import numpy as np

    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    write_bytes(path, buf.getvalue(), site=site)


# ---------------------------------------------------------------------------
# Append-only JSONL with checksum framing
# ---------------------------------------------------------------------------


def frame_line(obj: Any) -> str:
    """One framed record: compact JSON + FRAME_SEP + crc32 + newline."""
    payload = json.dumps(obj, sort_keys=True, separators=(",", ":"))
    if "\n" in payload:  # defensive: a newline would tear the format
        raise ValueError("JSONL records must serialize to one line")
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{payload}{FRAME_SEP}{crc:08x}\n"


def append_jsonl(path: str, obj: Any,
                 site: Optional[str] = None) -> None:
    """Durably append one checksum-framed record as a single write().

    O_APPEND keeps concurrent appenders from interleaving inside a
    record; the crc frame lets read_jsonl reject the torn tail a
    mid-write kill leaves. If the existing tail is torn (no trailing
    newline — the previous writer died mid-append), the new record is
    prefixed with a newline so the torn bytes stay confined to their
    own (checksum-rejected) line instead of corrupting this one."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    action = _fs_fault(_site("append", path, site))
    data = frame_line(obj).encode("utf-8")
    # O_RDWR (not O_WRONLY): the torn-tail probe pread()s the last byte
    fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        size = os.fstat(fd).st_size
        if size and os.pread(fd, 1, size - 1) != b"\n":
            data = b"\n" + data
        if action == "torn-write":
            os.write(fd, data[:max(1, len(data) // 2)])
            raise OSError(errno.EIO,
                          f"injected torn append ({path})")
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)


def read_jsonl(path: str) -> Tuple[List[Any], int]:
    """All intact records of `path` in file order, plus the count of
    torn/corrupt lines skipped.

    Framed lines (FRAME_SEP present) are checksum-verified; legacy
    unframed lines (pre-framing checkpoints/ledgers) parse as plain
    JSON. A missing file is an empty log. Never raises on content —
    a crash mid-append must read as "one record short", not an error."""
    if not os.path.exists(path):
        return [], 0
    records: List[Any] = []
    bad = 0
    with open(path, encoding="utf-8", errors="replace") as fh:
        for line in fh:
            # rstrip newlines ONLY: a write torn right after the frame
            # separator must still look framed (and fail its crc), not
            # have the trailing tab stripped and sneak past as legacy
            line = line.rstrip("\r\n")
            if not line.strip():
                continue
            if FRAME_SEP in line:
                payload, _, crc_hex = line.rpartition(FRAME_SEP)
                try:
                    want = int(crc_hex, 16)
                except ValueError:
                    bad += 1
                    continue
                if (zlib.crc32(payload.encode("utf-8"))
                        & 0xFFFFFFFF) != want:
                    bad += 1
                    continue
                try:
                    records.append(json.loads(payload))
                except ValueError:
                    bad += 1
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                bad += 1
    return records, bad


# ---------------------------------------------------------------------------
# Crash-debris sweep
# ---------------------------------------------------------------------------


def sweep_tmp(directory: str, max_age_s: float = 0.0) -> int:
    """Remove ``*.tmp`` files a killed writer left in `directory`;
    returns how many were removed.

    ``max_age_s`` guards shared directories: a .tmp younger than it
    may belong to a live concurrent writer and is left alone (pass 0
    for single-owner directories like a run's checkpoint dir)."""
    import time

    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    # age gate, not a duration measurement
    now = time.time()  # galah-lint: ignore[GL701] wall-clock age gate
    for name in names:
        if not name.endswith(".tmp"):
            continue
        p = os.path.join(directory, name)
        try:
            if max_age_s and now - os.stat(p).st_mtime < max_age_s:
                continue
            os.unlink(p)
            removed += 1
        except OSError:
            continue
    if removed:
        logger.info("Swept %d stale .tmp file(s) from %s", removed,
                    directory)
    return removed
