"""Out-of-core paged sketch store — the NVMe tier of the sketch
memory hierarchy (docs/memory.md).

Sketch rows are packed into fixed-size *pages*: flat files holding a
crc-framed JSON header line (:func:`galah_tpu.io.atomic.frame_line`)
followed by a raw little-endian ``uint64`` payload of ``rows x cols``
hash slots.  Pages are committed with the ``io/atomic.py`` discipline
(tmp + fsync + rename + dir fsync), so a reader either sees a whole
page or no page — never a torn one — and the ``GALAH_FI`` fs-fault
sites (``io.atomic.write[pagestore.page]``,
``io.atomic.append[pagestore.dir]``) make the commit path chaos-
testable for free.

A ``pages.jsonl`` directory file (crc-framed, torn-tail healing via
:func:`read_jsonl`) names every committed page and the row keys it
holds.  The directory record for a page is appended only *after* the
page file itself is durable, so a committed record always references
an intact page; the payload crc in the page header is defense in
depth, not the primary integrity mechanism.

Resident set
------------
Pages are mmapped on first touch and the store hands out zero-copy
``numpy`` views into the maps.  An LRU list bounded by a hard byte
budget (``GALAH_TPU_SKETCH_RAM_MB``) decides which maps the store
keeps *referenced*; eviction drops the store's reference and hints
the kernel (``MADV_DONTNEED``) but never closes the map — live views
returned earlier keep their page alive via the buffer protocol, so
eviction can never invalidate data a caller still holds.

``pin()`` marks a set of pages unevictable for the duration of a band
walk: the bucketed scheduler pins at most the pages covering bands
b and b+1, which is the paging schedule's RSS bound.

Concurrency: one writer per process (pages carry a per-writer random
token so two processes sharing a directory never collide on names);
any number of readers.  ``refresh()`` re-reads ``pages.jsonl`` to
adopt pages other writers committed.
"""

from __future__ import annotations

import json
import logging
import mmap
import os
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as obs_metrics
from . import atomic

logger = logging.getLogger(__name__)

#: Concurrency contract — checked by the GL9xx lint family and GalahSan.
GUARDED_BY = {
    "SketchPageStore._pages": "SketchPageStore._lock",
    "SketchPageStore._order": "SketchPageStore._lock",
    "SketchPageStore._resident": "SketchPageStore._lock",
    "SketchPageStore._pins": "SketchPageStore._lock",
    "SketchPageStore._key_to_rid": "SketchPageStore._lock",
    "SketchPageStore._open_rows": "SketchPageStore._lock",
    "SketchPageStore._open_valid": "SketchPageStore._lock",
    "SketchPageStore._open_keys": "SketchPageStore._lock",
    "SketchPageStore._seq": "SketchPageStore._lock",
    "SketchPageStore._resident_bytes": "SketchPageStore._lock",
}
LOCK_ORDER = ["SketchPageStore._lock"]

PAGE_MAGIC = "galah-page"
PAGE_VERSION = 1
DIR_NAME = "pages.jsonl"

#: Rows packed per page.  256 rows x 1000 u64 cols is ~2 MiB per page
#: — large enough to amortize mmap/commit overhead, small enough that
#: the two-band pin floor stays well under any sane RAM budget.
DEFAULT_PAGE_ROWS = 256

_PAGE_SITE = "io.atomic.write[pagestore.page]"
_DIR_SITE = "io.atomic.append[pagestore.dir]"


class PageStoreError(RuntimeError):
    """A page failed its integrity checks (crc/shape mismatch)."""


def ram_budget_bytes() -> int:
    """The resident-set byte budget from ``GALAH_TPU_SKETCH_RAM_MB``.

    Malformed values are logged and the registry default applies.
    """
    from .. import config

    raw = config.env_value("GALAH_TPU_SKETCH_RAM_MB")
    try:
        mb = int(raw)  # type: ignore[arg-type]
        if mb <= 0:
            raise ValueError(raw)
    except (TypeError, ValueError):
        logger.warning("ignoring malformed GALAH_TPU_SKETCH_RAM_MB=%r", raw)
        mb = 512
    return mb * (1 << 20)


def pagestore_mode() -> str:
    """The ``GALAH_TPU_PAGESTORE`` tri-state: 'auto', '0' or '1'."""
    from .. import config

    val = config.env_value("GALAH_TPU_PAGESTORE") or "auto"
    return val if val in ("auto", "0", "1") else "auto"


def pagestore_engaged(n_rows: int, cols: int) -> bool:
    """Whether the paged sketch path should engage for an ``n_rows`` x
    ``cols`` u64 sketch matrix.

    '1' forces it, '0' disables it, and 'auto' engages when the
    all-resident matrix would exceed half the RAM budget (leaving the
    other half for pair state and the device runtime).
    """
    mode = pagestore_mode()
    if mode == "0":
        return False
    if mode == "1":
        return n_rows >= 2
    return n_rows * cols * 8 > ram_budget_bytes() // 2


class _Page:
    """One committed page: metadata plus the (lazy) mmap view."""

    __slots__ = ("name", "rows", "cols", "row0", "keys", "valid",
                 "nbytes", "_mm", "_mat")

    def __init__(self, name: str, rows: int, cols: int, row0: int,
                 keys: Sequence[str], valid: Sequence[int]):
        self.name = name
        self.rows = rows
        self.cols = cols
        self.row0 = row0                 # global row id of this page's row 0
        self.keys = list(keys)
        self.valid = list(valid)         # per-row count of real hashes
        self.nbytes = rows * cols * 8
        self._mm: Optional[mmap.mmap] = None
        self._mat: Optional[np.ndarray] = None


class SketchPageStore:
    """Paged, mmap-backed store of fixed-width ``uint64`` sketch rows.

    ``cols`` is the padded row width (``sketch_size``); rows shorter
    than ``cols`` are zero-padded and carry their true hash count in
    the directory (``valid``), so ``hashes(rid)`` can hand back the
    exact original array as a zero-copy slice.
    """

    def __init__(self, directory: str, cols: int,
                 page_rows: int = DEFAULT_PAGE_ROWS,
                 budget_bytes: Optional[int] = None,
                 fill: int = 0):
        if cols <= 0 or page_rows <= 0:
            raise ValueError("cols and page_rows must be positive")
        self.directory = os.path.abspath(directory)
        self.cols = int(cols)
        self.page_rows = int(page_rows)
        # Pad value for short rows: the MinHash pair kernels expect
        # SENTINEL padding (ops/constants.py) so padded slots can
        # never count as common hashes — gathers must be bit-identical
        # to ops/minhash.sketch_matrix rows.
        self.fill = np.uint64(fill)
        self.budget_bytes = (ram_budget_bytes() if budget_bytes is None
                             else int(budget_bytes))
        os.makedirs(self.directory, exist_ok=True)
        atomic.sweep_tmp(self.directory,
                         max_age_s=atomic.SHARED_TMP_MAX_AGE_S)
        self._lock = threading.RLock()
        self._token = os.urandom(4).hex()    # per-writer page-name salt
        self._seq = 0
        self._pages: List[_Page] = []
        self._key_to_rid: Dict[str, int] = {}
        # LRU order of resident page indices (most recent last) and the
        # pin counts that veto their eviction.
        self._order: List[int] = []
        self._resident: Dict[int, bool] = {}
        self._pins: Dict[int, int] = {}
        self._resident_bytes = 0
        # The open (not yet committed) page under construction.
        self._open_rows: List[np.ndarray] = []
        self._open_valid: List[int] = []
        self._open_keys: List[str] = []
        self._c_page_ins = obs_metrics.counter(
            "pagestore.page_ins", help="pages mapped into the resident set")
        self._c_page_outs = obs_metrics.counter(
            "pagestore.page_outs", help="pages evicted from the resident set")
        self._g_resident = obs_metrics.gauge(
            "pagestore.resident_bytes", unit="bytes",
            help="bytes of sketch pages currently resident (mmapped + LRU)")
        self.refresh()

    # -- directory ---------------------------------------------------------

    @property
    def dir_path(self) -> str:
        return os.path.join(self.directory, DIR_NAME)

    def refresh(self) -> int:
        """Re-read ``pages.jsonl`` and adopt pages committed by other
        writers.  Returns the number of newly adopted pages."""
        records, bad = atomic.read_jsonl(self.dir_path)
        if bad:
            logger.warning("pagestore %s: healed %d torn directory line(s)",
                        self.directory, bad)
        with self._lock:
            known = {p.name for p in self._pages}
            added = 0
            for rec in records:
                if not isinstance(rec, dict) or rec.get("page") in known:
                    continue
                self._adopt_locked(rec)
                added += 1
            return added

    def _adopt_locked(self, rec: dict) -> None:
        with self._lock:
            name = rec["page"]
            keys = rec.get("keys", [])
            valid = rec.get("valid", [])
            rows = int(rec.get("rows", len(keys)))
            cols = int(rec.get("cols", self.cols))
            if cols != self.cols or rows != len(keys) or rows != len(valid):
                raise PageStoreError(
                    f"pagestore {self.directory}: directory record for "
                    f"{name!r} is inconsistent (rows={rows} cols={cols})")
            row0 = sum(p.rows for p in self._pages)
            page = _Page(name, rows, cols, row0, keys, valid)
            self._pages.append(page)
            for i, key in enumerate(keys):
                if key:
                    self._key_to_rid.setdefault(key, row0 + i)

    # -- write path --------------------------------------------------------

    def append(self, key: str, hashes: np.ndarray) -> int:
        """Append one sketch row; returns its global row id.

        The row becomes durable (and visible to other processes) at
        the next page boundary or explicit :meth:`flush`.
        """
        arr = np.ascontiguousarray(hashes, dtype=np.uint64).ravel()
        if arr.size > self.cols:
            raise ValueError(
                f"row has {arr.size} hashes but page width is {self.cols}")
        with self._lock:
            row = np.full(self.cols, self.fill, dtype=np.uint64)
            row[:arr.size] = arr
            rid = (sum(p.rows for p in self._pages)
                   + len(self._open_rows))
            self._open_rows.append(row)
            self._open_valid.append(int(arr.size))
            self._open_keys.append(key or "")
            if key:
                self._key_to_rid.setdefault(key, rid)
            if len(self._open_rows) >= self.page_rows:
                self._commit_open_locked()
            return rid

    def flush(self) -> None:
        """Commit the open partial page, if any."""
        with self._lock:
            if self._open_rows:
                self._commit_open_locked()

    def _commit_open_locked(self) -> None:
        with self._lock:
            rows = len(self._open_rows)
            payload = np.vstack(self._open_rows).astype("<u8", copy=False)
            raw = payload.tobytes()
            name = f"page-{self._token}-{self._seq:06d}.gpg"
            self._seq += 1
            header = atomic.frame_line({
                "magic": PAGE_MAGIC, "version": PAGE_VERSION,
                "rows": rows, "cols": self.cols, "dtype": "<u8",
                "payload_crc": f"{zlib.crc32(raw) & 0xFFFFFFFF:08x}",
            }).encode("utf-8")
            path = os.path.join(self.directory, name)
            # Page body first, directory record second: a crash between the
            # two leaves an orphan page file (swept by age) but never a
            # directory record pointing at a missing/torn page.
            atomic.write_bytes(path, header + raw, site=_PAGE_SITE)
            rec = {"page": name, "rows": rows, "cols": self.cols,
                   "keys": list(self._open_keys),
                   "valid": list(self._open_valid)}
            atomic.append_jsonl(self.dir_path, rec, site=_DIR_SITE)
            self._adopt_locked(rec)
            self._open_rows = []
            self._open_valid = []
            self._open_keys = []

    # -- resident set ------------------------------------------------------

    def _map_locked(self, pi: int) -> np.ndarray:
        with self._lock:
            page = self._pages[pi]
            if page._mat is None:
                path = os.path.join(self.directory, page.name)
                with open(path, "rb") as fh:
                    head = fh.readline()
                    mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
                meta = self._check_header(page, head)
                offset = len(head)
                mat = np.frombuffer(mm, dtype="<u8",
                                    count=page.rows * page.cols,
                                    offset=offset).reshape(page.rows, page.cols)
                if meta.get("payload_crc"):
                    got = f"{zlib.crc32(mat.tobytes()) & 0xFFFFFFFF:08x}"
                    if got != meta["payload_crc"]:
                        raise PageStoreError(
                            f"pagestore page {page.name}: payload crc mismatch "
                            f"(want {meta['payload_crc']}, got {got})")
                page._mm = mm
                page._mat = mat
                self._resident[pi] = True
                self._resident_bytes += page.nbytes
                self._c_page_ins.inc()
                self._g_resident.set(self._resident_bytes)
            if pi in self._order:
                self._order.remove(pi)
            self._order.append(pi)
            self._evict_locked()
            return page._mat

    def _check_header(self, page: _Page, head: bytes) -> dict:
        try:
            text = head.decode("utf-8").rstrip("\n")
            body, crc = text.rsplit(atomic.FRAME_SEP, 1)
            if f"{zlib.crc32(body.encode('utf-8')) & 0xFFFFFFFF:08x}" != crc:
                raise ValueError("header crc mismatch")
            meta = json.loads(body)
        except Exception as exc:
            raise PageStoreError(
                f"pagestore page {page.name}: bad header ({exc})") from exc
        if (meta.get("magic") != PAGE_MAGIC
                or int(meta.get("rows", -1)) != page.rows
                or int(meta.get("cols", -1)) != page.cols):
            raise PageStoreError(
                f"pagestore page {page.name}: header/directory mismatch "
                f"({meta})")
        return meta

    def _evict_locked(self) -> None:
        with self._lock:
            while (self._resident_bytes > self.budget_bytes
                   and any(self._pins.get(pi, 0) == 0 for pi in self._order)):
                victim = next(pi for pi in self._order
                              if self._pins.get(pi, 0) == 0)
                self._order.remove(victim)
                page = self._pages[victim]
                mm = page._mm
                page._mat = None
                page._mm = None
                self._resident.pop(victim, None)
                self._resident_bytes -= page.nbytes
                self._c_page_outs.inc()
                self._g_resident.set(self._resident_bytes)
                # Never close the map: earlier zero-copy views keep it
                # alive via .base.  Just hint the kernel to drop the pages.
                dontneed = getattr(mmap, "MADV_DONTNEED", None)
                if mm is not None and dontneed is not None \
                        and hasattr(mm, "madvise"):
                    try:
                        mm.madvise(dontneed)
                    except (ValueError, OSError):
                        pass

    # -- read path ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return (sum(p.rows for p in self._pages)
                    + len(self._open_rows))

    @property
    def shape(self) -> Tuple[int, int]:
        return (len(self), self.cols)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def _locate_locked(self, rid: int) -> Tuple[int, int]:
        if rid < 0:
            raise IndexError(rid)
        for pi, page in enumerate(self._pages):
            if rid < page.row0 + page.rows:
                return pi, rid - page.row0
        raise IndexError(
            f"row {rid} is not committed (store has "
            f"{sum(p.rows for p in self._pages)} committed rows; call "
            "flush() first)")

    def _open_index_locked(self, rid: int) -> Optional[int]:
        """Offset into the open (uncommitted) page, or None."""
        committed = sum(p.rows for p in self._pages)
        if rid >= committed:
            off = rid - committed
            if off < len(self._open_rows):
                return off
            raise IndexError(rid)
        return None

    def row(self, rid: int) -> np.ndarray:
        """The full padded row — a zero-copy read-only view."""
        with self._lock:
            off = self._open_index_locked(rid)
            if off is not None:
                return self._open_rows[off]
            pi, off = self._locate_locked(rid)
            return self._map_locked(pi)[off]

    def n_valid(self, rid: int) -> int:
        with self._lock:
            off = self._open_index_locked(rid)
            if off is not None:
                return self._open_valid[off]
            pi, off = self._locate_locked(rid)
            return self._pages[pi].valid[off]

    def hashes(self, rid: int) -> np.ndarray:
        """The row's true (unpadded) hash array — zero-copy view."""
        with self._lock:
            off = self._open_index_locked(rid)
            if off is not None:
                return self._open_rows[off][:self._open_valid[off]]
            pi, off = self._locate_locked(rid)
            return self._map_locked(pi)[off][:self._pages[pi].valid[off]]

    def rid_for(self, key: str) -> Optional[int]:
        with self._lock:
            return self._key_to_rid.get(key)

    def get(self, key: str) -> Optional[np.ndarray]:
        """The true hash array for a content key, or None."""
        rid = self.rid_for(key)
        return None if rid is None else self.hashes(rid)

    def gather(self, indices: np.ndarray) -> np.ndarray:
        """A contiguous ``(len(indices), cols)`` submatrix copy.

        Pages covering the requested rows are pinned for the duration
        of the copy, then returned to normal LRU rotation.  This is
        the duck-typed hook :func:`ops.bucketing.bucketed_threshold_pairs`
        calls as ``band_gather`` — the only rows materialized are the
        two bands being walked.
        """
        idx = np.asarray(indices, dtype=np.int64).ravel()
        out = np.empty((idx.size, self.cols), dtype=np.uint64)
        with self._lock:
            if self._open_rows:
                self._commit_open_locked()
            touched = sorted({self._locate_locked(int(r))[0] for r in idx})
            for pi in touched:
                self._pins[pi] = self._pins.get(pi, 0) + 1
            try:
                for pi in touched:
                    self._map_locked(pi)
                for j, r in enumerate(idx):
                    pi, off = self._locate_locked(int(r))
                    out[j] = self._pages[pi]._mat[off]
            finally:
                for pi in touched:
                    left = self._pins.get(pi, 0) - 1
                    if left <= 0:
                        self._pins.pop(pi, None)
                    else:
                        self._pins[pi] = left
                self._evict_locked()
        return out

    #: Alias the bucketed scheduler duck-types on.
    band_gather = gather

    def valid_counts(self) -> np.ndarray:
        """Per-row true-hash counts for all committed rows."""
        with self._lock:
            counts: List[int] = []
            for page in self._pages:
                counts.extend(page.valid)
            return np.asarray(counts, dtype=np.int64)

    def close(self) -> None:
        with self._lock:
            if self._open_rows:
                self._commit_open_locked()
            for pi in list(self._order):
                self._pins.pop(pi, None)
            self.budget_bytes = 0
            self._evict_locked()


class PagedRowView:
    """Position-indexed facade over a :class:`SketchPageStore`: maps
    caller row positions (e.g. genome-path order, possibly with
    duplicate paths sharing a store row) to store row ids.  Duck-typed
    for :func:`ops.bucketing.bucketed_threshold_pairs` — exposes
    ``shape`` and ``band_gather`` only, so holding one is never
    holding a whole sketch matrix."""

    def __init__(self, store: SketchPageStore, rids) -> None:
        self.store = store
        self.rids = np.asarray(rids, dtype=np.int64)

    @property
    def shape(self) -> Tuple[int, int]:
        return (int(self.rids.shape[0]), self.store.cols)

    def band_gather(self, indices) -> np.ndarray:
        idx = np.asarray(indices, dtype=np.int64).ravel()
        return self.store.gather(self.rids[idx])
