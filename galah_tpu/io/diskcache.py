"""On-disk sketch / profile cache keyed by genome file identity + params.

The reference has no persistent caching at all — every run re-sketches
every genome from FASTA (SURVEY.md §5; the skani clusterer even
re-sketches per *pair*, reference: src/skani.rs:171-172). At the 50k-
genome scale this framework targets, ingestion + sketching is a large
fixed cost, so every sketch kind (MinHash vector, HLL registers,
fragment-ANI profile arrays) can be persisted once and memory-mapped
back on later runs.

Design:
  * a cache entry is one ``.npz`` file under the cache directory, named
    by a SHA-256 of (absolute path, file size, mtime_ns, kind, params) —
    touching or replacing a FASTA invalidates its entries automatically;
  * writes go through io/atomic.py (tmp + fsync + rename + dir-fsync)
    so concurrent runs sharing a cache directory never observe torn
    entries, and a host crash can't lose a completed store;
  * every entry embeds a content checksum (``__check__`` array); loads
    verify it, and ANY unreadable/truncated/checksum-mismatched entry —
    or ``.tmp`` debris from a killed writer — is miss-and-repair: drop
    the file, recompute, restore. A corrupt cache can cost time, never
    a wrong sketch;
  * the cache is strictly optional: ``CacheDir(None)`` is a no-op store,
    so call sites keep one code path.

Shared-directory hygiene: opening a ``CacheDir`` sweeps ``.tmp``
debris from killed writers, but only files older than
``io/atomic.py``'s ``SHARED_TMP_MAX_AGE_S`` (3600 s) — the cache
directory is shared between concurrent runs, and a *fresh* ``.tmp``
may belong to a live writer mid-commit; the age gate makes the sweep
safe without any cross-process locking.

Byte-level telemetry: ``cache.bytes_read`` / ``cache.bytes_written``
counters track entry traffic alongside the hit/miss counters, so the
run report can attribute cache IO against the pagestore's
(docs/memory.md) page traffic.

Enabled via ``--sketch-cache DIR`` on the CLI or the
``GALAH_TPU_CACHE`` environment variable.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import zlib
from typing import Dict, Optional

import numpy as np

from galah_tpu.io import atomic

logger = logging.getLogger(__name__)

#: Reserved entry member holding the content crc32 of all other arrays.
_CHECK_KEY = "__check__"


def _content_crc(arrays: Dict[str, np.ndarray]) -> int:
    """crc32 over names, dtypes, shapes, and bytes of every array — the
    whole meaning of the entry, so a flipped bit anywhere is a miss."""
    crc = 0
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        for part in (name, str(a.dtype), str(a.shape)):
            crc = zlib.crc32(part.encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def default_cache_dir() -> Optional[str]:
    """Cache directory from the GALAH_TPU_CACHE flag, or None
    (disabled). The flag's name and default live once, in the
    config.FLAGS registry — not here and not in cli.py."""
    from galah_tpu.config import env_value

    return env_value("GALAH_TPU_CACHE") or None


class CacheDir:
    """A directory of npz cache entries; ``CacheDir(None)`` disables."""

    def __init__(self, path: Optional[str]) -> None:
        self.path = path
        if path:
            os.makedirs(path, exist_ok=True)
            # debris from writers killed mid-store; age-gated because
            # the cache dir is SHARED — a fresh .tmp may belong to a
            # live concurrent run
            atomic.sweep_tmp(path,
                             max_age_s=atomic.SHARED_TMP_MAX_AGE_S)
        self.hits = 0
        self.misses = 0

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def _entry_path(self, genome_path: str, kind: str, params: dict) -> str:
        st = os.stat(genome_path)
        ident = json.dumps({
            "path": os.path.abspath(genome_path),
            "size": st.st_size,
            "mtime_ns": st.st_mtime_ns,
            "kind": kind,
            "params": {k: params[k] for k in sorted(params)},
        }, sort_keys=True)
        digest = hashlib.sha256(ident.encode()).hexdigest()[:32]
        return os.path.join(self.path, f"{kind}-{digest}.npz")

    def load(self, genome_path: str, kind: str,
             params: dict) -> Optional[Dict[str, np.ndarray]]:
        """Arrays for (genome, kind, params), or None on miss/disabled."""
        if not self.enabled:
            return None
        entry = self._entry_path(genome_path, kind, params)
        try:
            with np.load(entry) as z:
                out = {name: z[name] for name in z.files}
        except FileNotFoundError:
            self.misses += 1
            self._count("cache.misses",
                        "Sketch/profile cache lookups that recomputed")
            return None
        except Exception as exc:  # truncated/unreadable: miss-and-repair
            return self._repair(entry, f"unreadable ({exc})")
        check = out.pop(_CHECK_KEY, None)
        if check is not None and int(check[0]) != _content_crc(out):
            # a flipped bit would otherwise become a silently-wrong
            # sketch — the one failure mode a cache must never have
            return self._repair(entry, "content checksum mismatch")
        self.hits += 1
        self._count("cache.hits",
                    "Sketch/profile cache entries reused from disk")
        try:
            nbytes = os.stat(entry).st_size
        except OSError:
            nbytes = 0
        self._count("cache.bytes_read",
                    "Bytes of cache entries read back from disk",
                    unit="bytes", delta=nbytes)
        return out

    def _repair(self, entry: str,
                why: str) -> None:
        """Corrupt entry: drop the file and report a miss — the caller
        recomputes and store() restores a good entry."""
        logger.warning("Dropping corrupt cache entry %s (%s)", entry,
                       why)
        try:
            os.unlink(entry)
        except OSError:
            pass
        self.misses += 1
        self._count("cache.misses",
                    "Sketch/profile cache lookups that recomputed")
        self._count("cache.repaired",
                    "Corrupt cache entries dropped for recompute")
        return None

    @staticmethod
    def _count(name: str, help: str, unit: str = "",
               delta: int = 1) -> None:
        # Mirrored into the run report's precluster funnel (cache hit
        # rate); loads can come from prefetch worker threads, which the
        # registry lock makes safe.
        from galah_tpu.obs import metrics as obs_metrics

        obs_metrics.counter(name, help=help, unit=unit).inc(delta)

    def store(self, genome_path: str, kind: str, params: dict,
              arrays: Dict[str, np.ndarray]) -> None:
        if not self.enabled:
            return
        entry = self._entry_path(genome_path, kind, params)
        if _CHECK_KEY in arrays:
            raise ValueError(f"{_CHECK_KEY!r} is reserved for the "
                             "cache's content checksum")
        payload = dict(arrays)
        payload[_CHECK_KEY] = np.array([_content_crc(arrays)],
                                       dtype=np.uint64)
        atomic.write_npz(entry, payload,
                         site=f"io.atomic.write[cache.{kind}]")
        try:
            nbytes = os.stat(entry).st_size
        except OSError:
            nbytes = 0
        self._count("cache.bytes_written",
                    "Bytes of cache entries committed to disk",
                    unit="bytes", delta=nbytes)

    def stats(self) -> str:
        return f"{self.hits} hits / {self.misses} misses"


_NONE = CacheDir(None)


def get_cache(path: Optional[str] = None) -> CacheDir:
    """CacheDir for `path`, the env-var default, or the disabled cache."""
    if path is None:
        path = default_cache_dir()
    return CacheDir(path) if path else _NONE
