"""FASTA ingestion: file -> 2-bit codes + validity mask + contig offsets.

This is the framework's needletail analog (reference: src/genome_stats.rs:1-51
consumes needletail's streaming FASTA parse). The device-facing contract is a
flat uint8 code array (A=0 C=1 G=2 T=3, case-insensitive), a validity mask
(False where the base is ambiguous, e.g. N), and contig offsets — static-shape
friendly inputs for the JAX k-mer kernels.

A C++ fast path (galah_tpu.io._cingest, built from csrc/ingest.c) parses,
packs, and computes stats in one pass; the numpy implementation below is the
always-available fallback and the semantic reference.
"""

from __future__ import annotations

import dataclasses
import gzip
import logging
import zlib
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class BadGenomeError(ValueError):
    """A genome file that is deterministically unreadable — empty,
    truncated, or corrupt. Distinct from transient IO errors (which
    read_genome retries with backoff) and from FileNotFoundError (the
    caller's input-spec problem): under ``--on-bad-genome skip`` these
    land in the quarantine manifest instead of killing the run."""

    def __init__(self, path: str, reason: str, detail: str = "") -> None:
        self.path = path
        self.reason = reason  # "empty" | "corrupt"
        super().__init__(
            f"{reason} genome FASTA {path}"
            + (f": {detail}" if detail else ""))

# ASCII -> 2-bit code; 255 marks ambiguous/non-ACGT.
_CODE_LUT = np.full(256, 255, dtype=np.uint8)
for _i, _b in enumerate(b"ACGT"):
    _CODE_LUT[_b] = _i
    _CODE_LUT[_b + 32] = _i  # lowercase


@dataclasses.dataclass
class GenomeStats:
    """Assembly stats (reference: src/genome_stats.rs:11-51)."""

    num_contigs: int
    num_ambiguous_bases: int
    n50: int


@dataclasses.dataclass
class Genome:
    """A parsed genome ready for device sketching."""

    path: str
    codes: np.ndarray          # uint8 [total_len], 0-3 valid, 255 ambiguous
    contig_offsets: np.ndarray  # int64 [num_contigs + 1]
    stats: GenomeStats

    @property
    def length(self) -> int:
        return int(self.codes.shape[0])


def _open_maybe_gzip(path: str):
    with open(path, "rb") as fh:
        magic = fh.read(2)
    if magic == b"\x1f\x8b":
        return gzip.open(path, "rb")
    return open(path, "rb")


def _compute_n50(lengths: np.ndarray) -> int:
    """N50: length L such that contigs >= L cover half the assembly.

    Matches the reference's accumulate-from-longest definition
    (reference: src/genome_stats.rs:53-59 via the golden 8289 test).
    """
    if lengths.size == 0:
        return 0
    s = np.sort(lengths)[::-1]
    csum = np.cumsum(s)
    half = csum[-1] / 2.0
    idx = int(np.searchsorted(csum, half))
    return int(s[idx])


_CINGEST = None
_CINGEST_TRIED = False
_CINGEST_ERR: list = [None]


def _note_c_fallback(what: str, err: BaseException, path: str = "") -> None:
    """Make the ~10x slower numpy-parser fallback visible: one WARNING
    per process per failure site, a resilience event per occurrence,
    and an ``ingest.c_fallback`` counter so run_report.json shows how
    many genomes went down the slow path."""
    from galah_tpu.obs import events
    from galah_tpu.obs import metrics as obs_metrics

    events.warn_once(
        logger,
        "C FASTA ingest %s (%s: %s); falling back to the ~10x slower "
        "numpy parser", what, type(err).__name__, err,
        key=f"ingest.c_fallback:{what}")
    events.record("ingest-c-fallback", what=what, path=path,
                  error=f"{type(err).__name__}: {err}")
    obs_metrics.counter(
        "ingest.c_fallback",
        help="genome reads served by the numpy parser because the C "
             "ingest fast path failed (build/load or per-file parse)",
        unit="reads").inc()


def _get_cingest():
    """Import (and thereby build) the C fast path at most once per
    process; a failed build is cached so the compiler never reruns."""
    global _CINGEST, _CINGEST_TRIED
    if not _CINGEST_TRIED:
        _CINGEST_TRIED = True
        try:
            from galah_tpu.io import _cingest
            _CINGEST = _cingest
        except Exception as e:
            _CINGEST = None
            _CINGEST_ERR[0] = e
    return _CINGEST


_IO_POLICY = None


def _io_policy():
    """Lazy, cached GALAH_IO_RETRY policy (read_genome runs per genome;
    re-parsing the env every call would be pure overhead)."""
    global _IO_POLICY
    if _IO_POLICY is None:
        from galah_tpu.resilience.policy import RetryPolicy

        # defaults= (not keyword overrides) so the GALAH_IO_RETRY_*
        # env knobs actually win over the IO-specific baseline
        _IO_POLICY = RetryPolicy.from_env(
            "GALAH_IO_RETRY",
            defaults=dict(max_attempts=3, base_delay=0.1))
    return _IO_POLICY


def _io_retryable(exc: BaseException) -> bool:
    """Transient-IO classifier for the read retry: flaky network-FS
    OSErrors are worth a backoff; a missing path or corrupt payload
    (BadGzipFile/EOFError surface deterministically per byte content)
    is not."""
    if isinstance(exc, (FileNotFoundError, IsADirectoryError,
                        gzip.BadGzipFile, EOFError, zlib.error)):
        return False
    return isinstance(exc, (OSError, TimeoutError))


def read_genome(path: str, with_codes: bool = True) -> Genome:
    """Parse a (possibly gzipped) FASTA into codes + offsets + stats.

    Stats semantics match the reference goldens (reference:
    src/genome_stats.rs:61-87): num_contigs counts records, ambiguous counts
    every base that is not ACGT/acgt, N50 from descending cumulative sum.

    Transient IO errors (network FS flakes) are retried with backoff
    (GALAH_IO_RETRY_* env knobs, docs/resilience.md); deterministically
    unreadable content raises BadGenomeError, which the quarantine
    layer (resilience/quarantine.py) can isolate instead of dying.
    """
    from galah_tpu.resilience.policy import call_with_retry

    def attempt() -> Genome:
        cingest = _get_cingest()
        if cingest is not None:
            try:
                return _read_genome_c(cingest, path, with_codes)
            except Exception as e:
                # fall back to the numpy path on any C-side failure,
                # but never silently: the slow path must show up in obs
                _note_c_fallback("parse failed", e, path=path)
        elif _CINGEST_ERR[0] is not None:
            _note_c_fallback("build/load failed", _CINGEST_ERR[0],
                             path=path)
        return read_genome_numpy(path, with_codes)

    try:
        return call_with_retry(attempt, _io_policy(),
                               site=f"io.read[{path}]",
                               classify=_io_retryable)
    except BadGenomeError:
        raise
    except (gzip.BadGzipFile, EOFError, zlib.error) as e:
        raise BadGenomeError(path, "corrupt", str(e)) from e
    except ValueError as e:
        reason, detail = _classify_value_error(e)
        raise BadGenomeError(path, reason, detail) from e


def _classify_value_error(e: ValueError) -> Tuple[str, str]:
    msg = str(e)
    return ("empty" if "no FASTA records" in msg else "corrupt", msg)


def read_genome_numpy(path: str, with_codes: bool = True) -> Genome:
    """Pure-numpy parse — the semantic reference the C kernel must match
    (exercised directly by the parity tests in tests/test_cingest.py)."""
    contig_seqs: List[np.ndarray] = []
    cur_parts: List[bytes] = []
    n_contigs = 0
    with _open_maybe_gzip(path) as fh:
        for raw in fh:
            line = raw.strip()
            if not line:
                continue
            if line.startswith(b">"):
                if n_contigs > 0:
                    contig_seqs.append(
                        np.frombuffer(b"".join(cur_parts), dtype=np.uint8))
                cur_parts = []
                n_contigs += 1
            elif n_contigs > 0:
                # sequence lines before the first '>' header are not part
                # of any record; drop them like a streaming FASTA parser
                cur_parts.append(line)
        if n_contigs > 0:
            contig_seqs.append(
                np.frombuffer(b"".join(cur_parts), dtype=np.uint8))
    if n_contigs == 0:
        raise ValueError(f"no FASTA records found in {path}")

    lengths = np.array([c.shape[0] for c in contig_seqs], dtype=np.int64)
    offsets = np.zeros(n_contigs + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])

    ascii_all = (np.concatenate(contig_seqs) if contig_seqs
                 else np.zeros(0, dtype=np.uint8))
    codes = _CODE_LUT[ascii_all]
    num_ambiguous = int((codes == 255).sum())

    stats = GenomeStats(
        num_contigs=n_contigs,
        num_ambiguous_bases=num_ambiguous,
        n50=_compute_n50(lengths),
    )
    return Genome(
        path=path,
        codes=codes if with_codes else np.zeros(0, dtype=np.uint8),
        contig_offsets=offsets,
        stats=stats,
    )


def _read_genome_c(cingest, path: str, with_codes: bool) -> Genome:
    codes, offsets, num_ambiguous, n50 = cingest.read_fasta(path)
    n_contigs = int(offsets.shape[0]) - 1
    if n_contigs <= 0:
        raise ValueError(f"no FASTA records found in {path}")
    stats = GenomeStats(
        num_contigs=n_contigs,
        num_ambiguous_bases=int(num_ambiguous),
        n50=int(n50),
    )
    return Genome(
        path=path,
        codes=codes if with_codes else np.zeros(0, dtype=np.uint8),
        contig_offsets=offsets.astype(np.int64),
        stats=stats,
    )


def calculate_genome_stats(path: str) -> GenomeStats:
    """Stats-only entry point (reference: src/genome_stats.rs:11)."""
    return read_genome(path, with_codes=False).stats
