"""ctypes loader/builder for the native FASTA ingestion kernel.

Compiles csrc/ingest.c into a shared library on first import (gcc/cc +
zlib, both part of the baked-in toolchain) and exposes

    read_fasta(path) -> (codes uint8[L], offsets int64[C+1],
                         num_ambiguous, n50)

which is the contract galah_tpu.io.fasta expects from its C fast path.
Any build/load failure raises ImportError so fasta.py silently falls back
to the numpy parser; set GALAH_TPU_NO_CINGEST=1 to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import pathlib

import numpy as np

from galah_tpu.utils import cbuild

_PKG_DIR = pathlib.Path(__file__).resolve().parent


class _GalahGenome(ctypes.Structure):
    _fields_ = [
        ("codes", ctypes.POINTER(ctypes.c_uint8)),
        ("total_len", ctypes.c_int64),
        ("offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_contigs", ctypes.c_int64),
        ("num_ambiguous", ctypes.c_int64),
        ("n50", ctypes.c_int64),
    ]


_dll = cbuild.build_and_load(
    "ingest.c", "_libingest", out_dir=_PKG_DIR,
    extra_flags=("-lz",), disable_env="GALAH_TPU_NO_CINGEST")

_dll.galah_read_fasta.argtypes = [ctypes.c_char_p,
                                  ctypes.POINTER(_GalahGenome)]
_dll.galah_read_fasta.restype = ctypes.c_int
_dll.galah_free_genome.argtypes = [ctypes.POINTER(_GalahGenome)]
_dll.galah_free_genome.restype = None

_ERRORS = {
    -1: "could not open file",
    -2: "no FASTA records found",
    -3: "out of memory",
    -4: "read error (corrupt gzip?)",
}


def read_fasta(path: str):
    """Parse a (possibly gzipped) FASTA natively; see module docstring."""
    g = _GalahGenome()
    rc = _dll.galah_read_fasta(os.fsencode(path), ctypes.byref(g))
    if rc != 0:
        raise ValueError(
            f"{_ERRORS.get(rc, f'error {rc}')} in {path}")
    try:
        if g.total_len > 0:
            codes = np.ctypeslib.as_array(
                g.codes, shape=(g.total_len,)).copy()
        else:
            codes = np.zeros(0, dtype=np.uint8)
        offsets = np.ctypeslib.as_array(
            g.offsets, shape=(g.n_contigs + 1,)).copy()
        return codes, offsets, int(g.num_ambiguous), int(g.n50)
    finally:
        _dll.galah_free_genome(ctypes.byref(g))
