"""ctypes loader/builder for the native FASTA ingestion kernel.

Compiles csrc/ingest.c into a shared library on first import (gcc/cc +
zlib, both part of the baked-in toolchain) and exposes

    read_fasta(path) -> (codes uint8[L], offsets int64[C+1],
                         num_ambiguous, n50)

which is the contract galah_tpu.io.fasta expects from its C fast path.
Any build/load failure raises ImportError so fasta.py silently falls back
to the numpy parser; set GALAH_TPU_NO_CINGEST=1 to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import sysconfig

import numpy as np

if os.environ.get("GALAH_TPU_NO_CINGEST"):
    raise ImportError("native ingestion disabled via GALAH_TPU_NO_CINGEST")

_PKG_DIR = pathlib.Path(__file__).resolve().parent
_SRC = _PKG_DIR.parent.parent / "csrc" / "ingest.c"
_SOSUFFIX = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
_LIB = _PKG_DIR / f"_libingest{_SOSUFFIX}"


def _build() -> None:
    if not _SRC.is_file():
        raise ImportError(f"native ingestion source missing: {_SRC}")
    if _LIB.is_file() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
        return
    cc = os.environ.get("CC", "cc")
    # Compile to a temp path and os.replace for an atomic publish, so
    # concurrent importers never dlopen a half-written library.
    tmp = _LIB.with_name(f"{_LIB.stem}.{os.getpid()}{_LIB.suffix}")
    cmd = [cc, "-O3", "-shared", "-fPIC", "-o", str(tmp), str(_SRC), "-lz"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            raise ImportError(
                f"native ingestion build failed: "
                f"{' '.join(cmd)}\n{proc.stderr}")
        os.replace(tmp, _LIB)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise ImportError(f"native ingestion build failed to run: {e}")
    finally:
        tmp.unlink(missing_ok=True)


class _GalahGenome(ctypes.Structure):
    _fields_ = [
        ("codes", ctypes.POINTER(ctypes.c_uint8)),
        ("total_len", ctypes.c_int64),
        ("offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_contigs", ctypes.c_int64),
        ("num_ambiguous", ctypes.c_int64),
        ("n50", ctypes.c_int64),
    ]


_build()
try:
    _dll = ctypes.CDLL(str(_LIB))
except OSError as e:
    raise ImportError(f"native ingestion library failed to load: {e}")

_dll.galah_read_fasta.argtypes = [ctypes.c_char_p,
                                  ctypes.POINTER(_GalahGenome)]
_dll.galah_read_fasta.restype = ctypes.c_int
_dll.galah_free_genome.argtypes = [ctypes.POINTER(_GalahGenome)]
_dll.galah_free_genome.restype = None

_ERRORS = {
    -1: "could not open file",
    -2: "no FASTA records found",
    -3: "out of memory",
    -4: "read error (corrupt gzip?)",
}


def read_fasta(path: str):
    """Parse a (possibly gzipped) FASTA natively; see module docstring."""
    g = _GalahGenome()
    rc = _dll.galah_read_fasta(os.fsencode(path), ctypes.byref(g))
    if rc != 0:
        raise ValueError(
            f"{_ERRORS.get(rc, f'error {rc}')} in {path}")
    try:
        if g.total_len > 0:
            codes = np.ctypeslib.as_array(
                g.codes, shape=(g.total_len,)).copy()
        else:
            codes = np.zeros(0, dtype=np.uint8)
        offsets = np.ctypeslib.as_array(
            g.offsets, shape=(g.n_contigs + 1,)).copy()
        return codes, offsets, int(g.num_ambiguous), int(g.n50)
    finally:
        _dll.galah_free_genome(ctypes.byref(g))
