"""Inverted-index collision counting over sketch/marker matrices.

Pure numpy (no C toolchain required): sort the (hash, genome) multiset
of every valid entry; each run of equal hashes contributes one
collision to every genome pair in the run. Because rows hold DISTINCT
values by construction (bottom-k sketches, marker sets), the per-pair
collision count equals |A ∩ B| over the full rows — exactly.

This replaces O(N^2) all-pairs passes with
O(NK log NK + collision pairs) whenever similarity is sparse — the
same screening idea the reference's skani applies with marker sketches
(reference: src/skani.rs:54-70), generalized to any of this
framework's row sets. Consumers:

  * ops/_cpairstats.threshold_pairs_c — conservative MinHash screen
    (count upper-bounds the merge walk's `common`), survivors get the
    exact C walk;
  * ops/pairwise.screen_pairs — the marker-containment screen, where
    count IS the containment numerator, so the host check is exact
    with no second pass.

Near-duplicate mega-clusters (a hash shared by > _BIG_RUN genomes)
would emit the same group's pairs for ~every shared hash; such runs
are deduplicated by group signature and their occurrence counts added
per distinct group, keeping the work O(K*m + output pairs) instead of
O(K*m^2).
"""

from __future__ import annotations

import os

import numpy as np

from galah_tpu.ops.constants import SENTINEL

_BIG_RUN = 64

# Above this genome count the sparse collision screens replace the
# dense O(N^2) passes (below it, dense is cheaper than sorting the
# whole hash multiset). GALAH_TPU_DENSE_PAIRS=1 forces dense;
# GALAH_TPU_SPARSE_MIN_N overrides the crossover (read per call, like
# the DENSE_PAIRS gate, so late env changes take effect).
SPARSE_SCREEN_MIN_N = 1024


def sparse_screen_min_n() -> int:
    """The sparse-screen crossover: GALAH_TPU_SPARSE_MIN_N when set to
    a valid integer (malformed values are logged and ignored, never
    fatal), else the module default (monkeypatchable in tests)."""
    v = os.environ.get("GALAH_TPU_SPARSE_MIN_N")
    if v:
        try:
            return int(v)
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "ignoring malformed GALAH_TPU_SPARSE_MIN_N=%r", v)
    return SPARSE_SCREEN_MIN_N

# Emitted-key buffer compaction threshold: peak transient memory is
# O(this + distinct pairs), never O(total emissions) — mid-size
# families (2.._BIG_RUN members sharing most hashes) emit the same
# pair key once per shared hash, which would otherwise concatenate to
# multi-GB before the final unique.
_COMPACT_EVERY = 4 << 20


class _CountAccum:
    """Incrementally merge (key, weight) batches into exact per-key
    sums, compacting whenever the buffer exceeds _COMPACT_EVERY."""

    def __init__(self) -> None:
        self._keys = [np.zeros(0, np.int64)]
        self._weights = [np.zeros(0, np.int64)]
        self._buffered = 0

    def add(self, keys: np.ndarray, weights: np.ndarray) -> None:
        if keys.shape[0] == 0:
            return
        self._keys.append(keys)
        self._weights.append(weights)
        self._buffered += keys.shape[0]
        if self._buffered > _COMPACT_EVERY:
            self.compact()

    def compact(self) -> "tuple[np.ndarray, np.ndarray]":
        keys = np.concatenate(self._keys)
        weights = np.concatenate(self._weights)
        uniq, inv = np.unique(keys, return_inverse=True)
        sums = np.bincount(inv, weights=weights).astype(np.int64)
        self._keys = [uniq]
        self._weights = [sums]
        self._buffered = 0
        return uniq, sums


def collision_pair_counts(mat: np.ndarray, lens: np.ndarray):
    """Exact |A ∩ B| for every colliding row pair of a SENTINEL-padded
    sorted matrix with per-row valid lengths.

    Returns (pi, pj, counts) with pi < pj, int64. Pairs with zero
    collisions are not enumerated.

    The compiled counter (csrc/collision.c: radix sort + run walk +
    hashmap) carries the pass when it builds — the numpy formulation
    (_collision_pair_counts_np) is the always-available fallback and
    the semantic reference (parity pinned in tests/test_collision.py).
    This is host-side work on every backend, so unlike the device-twin
    C paths there is no backend gate — only availability.
    """
    try:
        from galah_tpu.ops._ccollision import collision_pair_counts_c

        return collision_pair_counts_c(mat, lens, _BIG_RUN)
    except ImportError:
        pass
    return _collision_pair_counts_np(mat, lens)


def _collision_pair_counts_np(mat: np.ndarray, lens: np.ndarray):
    """Numpy reference implementation (see collision_pair_counts)."""
    n = mat.shape[0]
    ids = np.repeat(np.arange(n, dtype=np.int64), lens)
    hv = mat[mat != np.uint64(SENTINEL)]
    order = np.argsort(hv, kind="stable")
    hs = hv[order]
    gs = ids[order]
    empty = (np.zeros(0, np.int64),) * 3
    if hs.shape[0] == 0:
        return empty
    starts = np.flatnonzero(np.concatenate([[True], hs[1:] != hs[:-1]]))
    run_len = np.diff(np.append(starts, hs.shape[0]))

    acc = _CountAccum()
    big_mask = run_len > _BIG_RUN
    groups: "dict[bytes, tuple[np.ndarray, int]]" = {}
    for s, m in zip(starts[big_mask], run_len[big_mask]):
        group = np.unique(gs[s:s + m])
        sig = group.tobytes()
        prev = groups.get(sig)
        groups[sig] = (group, (prev[1] if prev else 0) + 1)
    for group, occurrences in groups.values():
        gi = group[:, None]
        gj = group[None, :]
        keys = (gi * n + gj)[gi < gj]
        acc.add(keys,
                np.full(keys.shape[0], occurrences, dtype=np.int64))
    for m in np.unique(run_len[~big_mask]):
        if m < 2:
            continue
        s = starts[(run_len == m) & ~big_mask]
        block = gs[s[:, None] + np.arange(m)]
        block.sort(axis=1)
        for a in range(int(m)):
            for b in range(a + 1, int(m)):
                i, j = block[:, a], block[:, b]
                neq = i != j  # duplicate genome paths share rows
                acc.add(i[neq] * n + j[neq],
                        np.ones(int(neq.sum()), dtype=np.int64))
    uniq, counts = acc.compact()
    if uniq.shape[0] == 0:
        return empty
    return uniq // n, uniq % n, counts


def candidate_pairs_minhash(mat: np.ndarray, lens: np.ndarray,
                            j_thr: float, sketch_size: int):
    """Conservative MinHash candidate pairs by collision counting.

    The exact per-pair |A ∩ B| upper-bounds the merged-bottom-k walk's
    `common`, while that walk's `total` is at least
    t_min = min(sketch_size, max(|A|, |B|)) — so any pair with
    count < j_thr * t_min provably fails the exact keep-check
    (common >= j_thr * total) and is skipped. Survivors must still get
    the exact walk (C, XLA, or the batched device pass); results are
    then bit-identical to the dense path. Shared by the CPU C kernel
    (ops/_cpairstats.threshold_pairs_c) and the device sparse path
    (ops/sparse_device.threshold_pairs_sparse).
    """
    pi, pj, counts = collision_pair_counts(mat, lens)
    t_min = np.minimum(
        sketch_size, np.maximum(lens[pi], lens[pj])).astype(np.float64)
    keep = counts.astype(np.float64) >= j_thr * t_min - 1e-9
    return pi[keep], pj[keep]
