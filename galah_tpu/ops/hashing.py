"""JAX kernels: canonical k-mer extraction + MurmurHash3 x64_128 (h1).

Device-side twin of ops/murmur3_np.py / ops/minhash_np.py, verified
bit-exact against them in tests/test_minhash.py. All shapes are static; a
genome is processed as fixed-size chunks so XLA compiles once per chunk
size. uint64 arithmetic wraps (XLA emulates 64-bit integers with u32 pairs
on TPU; if profiling shows hashing hot, the planned optimization is a
Pallas u32-pair kernel).

Hash semantics mirror the reference's finch backend contract
(reference: src/finch.rs:33-47): canonical (lexicographic min of forward /
reverse-complement) k-mer ASCII bytes, murmur3 x64_128 seed 0, low u64.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Ensure 64-bit integer support; all dtypes in this package are explicit so
# enabling x64 does not change any float widths we use.
jax.config.update("jax_enable_x64", True)

from galah_tpu.ops.constants import SENTINEL

_C1 = jnp.uint64(0x87C37B91114253D5)
_C2 = jnp.uint64(0x4CF5AD432745937F)

HASH_SENTINEL = jnp.uint64(SENTINEL)  # "no k-mer here"

_ASCII = jnp.array([65, 67, 71, 84], dtype=jnp.uint8)  # ACGT


def _rotl64(x: jax.Array, r: int) -> jax.Array:
    return (x << jnp.uint64(r)) | (x >> jnp.uint64(64 - r))


def _fmix64(x: jax.Array) -> jax.Array:
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xFF51AFD7ED558CCD)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xC4CEB9FE1A85EC53)
    x = x ^ (x >> jnp.uint64(33))
    return x


def murmur3_x64_128_h1(keys: jax.Array, seed: int = 0) -> jax.Array:
    """h1 of murmur3 x64_128 over uint8 rows, shape (n, L) -> (n,) uint64.

    L is a static (trace-time) constant; the byte loops unroll at trace
    time into pure vector ops over the n axis.
    """
    n, length = keys.shape
    h1 = jnp.full((n,), jnp.uint64(seed))
    h2 = jnp.full((n,), jnp.uint64(seed))

    nblocks = length // 16
    for blk in range(nblocks):
        base = blk * 16
        k1 = jnp.zeros((n,), jnp.uint64)
        k2 = jnp.zeros((n,), jnp.uint64)
        for b in range(8):
            k1 = k1 | (keys[:, base + b].astype(jnp.uint64)
                       << jnp.uint64(8 * b))
            k2 = k2 | (keys[:, base + 8 + b].astype(jnp.uint64)
                       << jnp.uint64(8 * b))
        k1 = _rotl64(k1 * _C1, 31) * _C2
        h1 = h1 ^ k1
        h1 = _rotl64(h1, 27) + h2
        h1 = h1 * jnp.uint64(5) + jnp.uint64(0x52DCE729)
        k2 = _rotl64(k2 * _C2, 33) * _C1
        h2 = h2 ^ k2
        h2 = _rotl64(h2, 31) + h1
        h2 = h2 * jnp.uint64(5) + jnp.uint64(0x38495AB5)

    rem = length & 15
    base = nblocks * 16
    if rem > 8:
        k2 = jnp.zeros((n,), jnp.uint64)
        for b in range(8, rem):
            k2 = k2 | (keys[:, base + b].astype(jnp.uint64)
                       << jnp.uint64(8 * (b - 8)))
        k2 = _rotl64(k2 * _C2, 33) * _C1
        h2 = h2 ^ k2
    if rem > 0:
        k1 = jnp.zeros((n,), jnp.uint64)
        for b in range(min(rem, 8)):
            k1 = k1 | (keys[:, base + b].astype(jnp.uint64)
                       << jnp.uint64(8 * b))
        k1 = _rotl64(k1 * _C1, 31) * _C2
        h1 = h1 ^ k1

    h1 = h1 ^ jnp.uint64(length)
    h2 = h2 ^ jnp.uint64(length)
    h1 = h1 + h2
    h2 = h2 + h1
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = h1 + h2
    return h1


@functools.partial(jax.jit, static_argnames=("k", "seed"))
def canonical_kmer_hashes_chunk(
    codes: jax.Array,       # uint8 (C,), 0-3 valid, 255 ambiguous/pad
    boundary: jax.Array,    # int32 (C,), contig id per position
    k: int = 21,
    seed: int = 0,
) -> jax.Array:
    """Hash every canonical k-mer starting in this chunk -> (C-k+1,) uint64.

    Positions whose window contains an ambiguous base or crosses a contig
    boundary produce HASH_SENTINEL. The caller overlaps consecutive chunks
    by k-1 positions so every k-mer is seen exactly once.
    """
    n_win = codes.shape[0] - k + 1
    # (n_win, k) windows via k static slices — XLA fuses these gathers.
    win = jnp.stack([codes[i:i + n_win] for i in range(k)], axis=1)
    valid = jnp.all(win != jnp.uint8(255), axis=1)
    valid = valid & (boundary[:n_win] == boundary[k - 1:k - 1 + n_win])

    # Pack forward / reverse-complement for the lexicographic-min compare
    # (code order A<C<G<T matches ASCII order, so integer compare == string
    # compare at fixed length).
    shifts = jnp.uint64(2) * jnp.arange(k - 1, -1, -1, dtype=jnp.uint64)
    safe = jnp.where(valid[:, None], win, jnp.uint8(0))
    w64 = safe.astype(jnp.uint64)
    fwd = jnp.sum(w64 << shifts, axis=1, dtype=jnp.uint64)
    rc = (jnp.uint8(3) - safe)[:, ::-1]
    rev = jnp.sum(rc.astype(jnp.uint64) << shifts, axis=1, dtype=jnp.uint64)
    use_fwd = fwd <= rev

    canon = jnp.where(use_fwd[:, None], safe, rc)
    ascii_kmers = _ASCII[canon]
    hashes = murmur3_x64_128_h1(ascii_kmers, seed=seed)
    return jnp.where(valid, hashes, HASH_SENTINEL)


def iter_chunk_hashes(codes, contig_offsets, k: int, chunk: int, seed: int = 0):
    """Yield (hashes, n_new) device arrays over fixed-size overlapping chunks.

    Single implementation of the chunk/pad/overlap discipline shared by the
    MinHash sketcher and the fragment-ANI profiler: chunks overlap by k-1 so
    every k-mer window is hashed exactly once; `n_new` is how many leading
    entries of `hashes` are first-time positions (the rest are overlap).
    """
    import numpy as np

    if chunk <= k - 1:
        raise ValueError(f"chunk ({chunk}) must exceed k-1 ({k - 1})")
    n = codes.shape[0]
    boundary = np.zeros(n, dtype=np.int32)
    if contig_offsets.shape[0] > 2:
        boundary = np.searchsorted(
            contig_offsets, np.arange(n), side="right").astype(np.int32)

    step = chunk - (k - 1)
    pos = 0
    total = max(n - k + 1, 0)
    while pos < total or pos == 0:
        end = min(pos + chunk, n)
        c = np.full(chunk, 255, dtype=np.uint8)
        b = np.full(chunk, -1, dtype=np.int32)
        c[: end - pos] = codes[pos:end]
        b[: end - pos] = boundary[pos:end]
        hashes = canonical_kmer_hashes_chunk(
            jnp.asarray(c), jnp.asarray(b), k=k, seed=seed)
        n_new = min(total - pos, chunk - k + 1) if total else 0
        yield hashes, pos, n_new
        pos += step
        if end >= n:
            break


@functools.partial(jax.jit, static_argnames=("sketch_size",))
def bottom_k_update(
    running: jax.Array,  # uint64 (sketch_size,) sorted asc, SENTINEL-padded
    hashes: jax.Array,   # uint64 (m,) chunk hashes, SENTINEL where invalid
    sketch_size: int = 1000,
) -> jax.Array:
    """Fold a chunk of hashes into a running bottom-k distinct sketch."""
    allh = jnp.concatenate([running, hashes])
    allh = jnp.sort(allh)
    # Mark duplicates (keep first occurrence), then re-sort and truncate.
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool), allh[1:] == allh[:-1]])
    allh = jnp.where(dup, HASH_SENTINEL, allh)
    allh = jnp.sort(allh)
    return allh[:sketch_size]
