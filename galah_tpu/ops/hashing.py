"""JAX kernels: canonical k-mer extraction + MurmurHash3 x64_128 (h1).

Device-side twin of ops/murmur3_np.py / ops/minhash_np.py, verified
bit-exact against them in tests/test_minhash.py. All shapes are static; a
genome is processed as fixed-size chunks so XLA compiles once per chunk
size. uint64 arithmetic wraps (XLA emulates 64-bit integers with u32
pairs on TPU). The explicit u32-pair Mosaic implementation of the
murmur state machine exists in ops/pallas_sketch.py (16-bit-limb
constant multiplies, bit-identical): opt in with GALAH_TPU_PALLAS_HASH=1
(read at first trace; k=21 murmur3 only), benched against this XLA
path by scripts/bench_sketch_variants.py on hardware.

Hash semantics mirror the reference's finch backend contract
(reference: src/finch.rs:33-47): canonical (lexicographic min of forward /
reverse-complement) k-mer ASCII bytes, murmur3 x64_128 seed 0, low u64.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

# Ensure 64-bit integer support; all dtypes in this package are explicit so
# enabling x64 does not change any float widths we use.
jax.config.update("jax_enable_x64", True)

from galah_tpu.ops.constants import SENTINEL

_C1 = jnp.uint64(0x87C37B91114253D5)
_C2 = jnp.uint64(0x4CF5AD432745937F)

# Mosaic murmur3 state-machine default on TPU backends when
# GALAH_TPU_PALLAS_HASH is unset. DECIDED from hardware data,
# 2026-08-01 amortized on-chip campaign (scripts/bench_amortized.py,
# docs/artifacts/tpu_watch_20260801_0829/amortized.txt): Mosaic/XLA =
# 0.06x at n=2Mi hashes (650 M/s vs 10.9 G/s amortized) — the XLA
# emulation wins decisively on-chip, not just through the tunnel, so
# the default stays False. Re-run the campaign before revisiting.
_PALLAS_HASH_TPU_DEFAULT = False


def _use_pallas_hash() -> bool:
    """GALAH_TPU_PALLAS_HASH: '1' forces the Mosaic hash kernel, '0'
    forces the XLA emulation; unset defers to the data-driven TPU
    default above (never on for CPU backends — interpret mode is for
    tests that pin it explicitly)."""
    env = os.environ.get("GALAH_TPU_PALLAS_HASH")
    if env == "1":
        return True
    if env == "0":
        return False
    return _PALLAS_HASH_TPU_DEFAULT and jax.default_backend() == "tpu"

HASH_SENTINEL = jnp.uint64(SENTINEL)  # "no k-mer here"

# Chunking policy shared by every consumer of iter_chunk_hashes /
# iter_genome_groups (MinHash, HLL, fragment profiles): 8 Mi positions
# per single-genome chunk — one dispatch covers most MAGs, and through a
# remote-tunnel TPU the per-dispatch round trip dominates — and at most
# 32 Mi total positions per batched group dispatch (u64 hash rows + sort
# workspace stay well under HBM).
DEFAULT_CHUNK = 1 << 23
BATCH_BUDGET = 1 << 25

_ASCII = jnp.array([65, 67, 71, 84], dtype=jnp.uint8)  # ACGT


def device_transfer_bound() -> bool:
    """True when host->device transfer + dispatch round trips dominate
    small ops — i.e. on a real TPU backend (tunneled or PCIe). Gates the
    packed-upload and batched-grouping policies: on the CPU backend both
    are pure overhead (data is already in host memory, and the big
    batched arrays lose cache locality — measured 3x slower profile
    builds). Override with GALAH_PACKED_TRANSFER=0/1 for testing."""
    env = os.environ.get("GALAH_PACKED_TRANSFER")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend probing never raises
        return False


def _rotl64(x: jax.Array, r: int) -> jax.Array:
    return (x << jnp.uint64(r)) | (x >> jnp.uint64(64 - r))


def _fmix64(x: jax.Array) -> jax.Array:
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xFF51AFD7ED558CCD)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xC4CEB9FE1A85EC53)
    x = x ^ (x >> jnp.uint64(33))
    return x


def murmur3_x64_128_h1(keys: jax.Array, seed: int = 0) -> jax.Array:
    """h1 of murmur3 x64_128 over uint8 rows, shape (n, L) -> (n,) uint64.

    L is a static (trace-time) constant; the byte loops unroll at trace
    time into pure vector ops over the n axis.
    """
    n, length = keys.shape
    h1 = jnp.full((n,), jnp.uint64(seed))
    h2 = jnp.full((n,), jnp.uint64(seed))

    nblocks = length // 16
    for blk in range(nblocks):
        base = blk * 16
        k1 = jnp.zeros((n,), jnp.uint64)
        k2 = jnp.zeros((n,), jnp.uint64)
        for b in range(8):
            k1 = k1 | (keys[:, base + b].astype(jnp.uint64)
                       << jnp.uint64(8 * b))
            k2 = k2 | (keys[:, base + 8 + b].astype(jnp.uint64)
                       << jnp.uint64(8 * b))
        k1 = _rotl64(k1 * _C1, 31) * _C2
        h1 = h1 ^ k1
        h1 = _rotl64(h1, 27) + h2
        h1 = h1 * jnp.uint64(5) + jnp.uint64(0x52DCE729)
        k2 = _rotl64(k2 * _C2, 33) * _C1
        h2 = h2 ^ k2
        h2 = _rotl64(h2, 31) + h1
        h2 = h2 * jnp.uint64(5) + jnp.uint64(0x38495AB5)

    rem = length & 15
    base = nblocks * 16
    if rem > 8:
        k2 = jnp.zeros((n,), jnp.uint64)
        for b in range(8, rem):
            k2 = k2 | (keys[:, base + b].astype(jnp.uint64)
                       << jnp.uint64(8 * (b - 8)))
        k2 = _rotl64(k2 * _C2, 33) * _C1
        h2 = h2 ^ k2
    if rem > 0:
        k1 = jnp.zeros((n,), jnp.uint64)
        for b in range(min(rem, 8)):
            k1 = k1 | (keys[:, base + b].astype(jnp.uint64)
                       << jnp.uint64(8 * b))
        k1 = _rotl64(k1 * _C1, 31) * _C2
        h1 = h1 ^ k1

    h1 = h1 ^ jnp.uint64(length)
    h2 = h2 ^ jnp.uint64(length)
    h1 = h1 + h2
    h2 = h2 + h1
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = h1 + h2
    return h1


def _ascii64(c: jax.Array) -> jax.Array:
    """2-bit code (uint8 vector) -> ACGT ASCII byte as uint64.

    A select chain instead of a table gather: gathers are the scarce
    resource on the VPU, selects are plain vector ops.
    """
    b = jnp.where(
        c == jnp.uint8(0), jnp.uint8(65),
        jnp.where(c == jnp.uint8(1), jnp.uint8(67),
                  jnp.where(c == jnp.uint8(2), jnp.uint8(71),
                            jnp.uint8(84))))
    return b.astype(jnp.uint64)


def _murmur3_k21_1d(cb, seed: int) -> jax.Array:
    """murmur3 x64_128 h1 over 21-byte keys given as a list of 21 uint64
    byte vectors — the 1-D twin of murmur3_x64_128_h1's (n, 21) path
    (one 16-byte block + a 5-byte k1 tail), bit-identical."""
    length = len(cb)
    assert length == 21
    n = cb[0].shape[0]
    h1 = jnp.full((n,), jnp.uint64(seed))
    h2 = jnp.full((n,), jnp.uint64(seed))

    k1 = cb[0]
    for b in range(1, 8):
        k1 = k1 | (cb[b] << jnp.uint64(8 * b))
    k2 = cb[8]
    for b in range(1, 8):
        k2 = k2 | (cb[8 + b] << jnp.uint64(8 * b))
    k1 = _rotl64(k1 * _C1, 31) * _C2
    h1 = h1 ^ k1
    h1 = _rotl64(h1, 27) + h2
    h1 = h1 * jnp.uint64(5) + jnp.uint64(0x52DCE729)
    k2 = _rotl64(k2 * _C2, 33) * _C1
    h2 = h2 ^ k2
    h2 = _rotl64(h2, 31) + h1
    h2 = h2 * jnp.uint64(5) + jnp.uint64(0x38495AB5)

    k1 = cb[16]
    for b in range(1, 5):
        k1 = k1 | (cb[16 + b] << jnp.uint64(8 * b))
    k1 = _rotl64(k1 * _C1, 31) * _C2
    h1 = h1 ^ k1

    h1 = h1 ^ jnp.uint64(length)
    h2 = h2 ^ jnp.uint64(length)
    h1 = h1 + h2
    h2 = h2 + h1
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = h1 + h2
    return h1


def _tpufast_mix(x: jax.Array, seed: int) -> jax.Array:
    """Multiply-free 64-bit mixer for TPU (shift-add sparse-constant
    rounds).

    The TPU VPU has no fast integer multiplier (a u64 multiply costs
    ~50x a shift/xor under XLA's emulation), which makes MurmurHash3 —
    12 u64 multiplies per k-mer — the sketching bottleneck. MinHash only
    needs a UNIFORM ranking hash, not murmur parity, so this mixer
    replaces every dense multiply with a sparse-constant multiply
    (x * (1 + 2^a + 2^b) = x + (x<<a) + (x<<b): two shifts + two adds)
    interleaved with xorshifts. Avalanche quality is validated
    empirically in tests/test_tpufast_hash.py (bit balance, sketch-level
    Jaccard accuracy vs the murmur path).
    """
    x = x ^ jnp.uint64((seed * 0x9E3779B97F4A7C15 + 0x1B873593) % (1 << 64))
    for sh_a, sh_b, sh_x in ((21, 37, 29), (13, 47, 31), (17, 41, 33)):
        # x *= (1 + 2^a + 2^b); x ^= x >> c  — wrap-around adds mix the
        # low bits upward, the xorshift folds high entropy back down.
        x = x + (x << jnp.uint64(sh_a)) + (x << jnp.uint64(sh_b))
        x = x ^ (x >> jnp.uint64(sh_x))
    x = x + (x << jnp.uint64(26))
    x = x ^ (x >> jnp.uint64(32))
    return x


def _canonical_core(
    cs: jax.Array,          # uint8 (C,) sanitized codes, 0-3 everywhere
    valid1: jax.Array,      # bool (C,) False at ambiguous/pad positions
    offsets: jax.Array,     # int32 (B,) contig start offsets (padded with
                            # a value > any position; see iter_chunk_hashes)
    pos: jax.Array,         # int32 scalar: global position of cs[0]
    k: int,
):
    """Window packing + boundary masking + canonical orientation: the
    hash-independent front half of `_hash_core`, shared with the fused
    Pallas sketch preamble (`canonical_kmer_words`).

    Returns (fwd, rev, valid, use_fwd) over the C-k+1 window positions:
    the forward and reverse-complement 2-bit packed windows (uint64),
    the window validity (no ambiguous base, no contig crossing), and the
    canonical-orientation select.
    """
    n = cs.shape[0]
    n_win = n - k + 1

    # Sliding-window packs via log-doubling: pack(i, 2m) =
    # pack(i, m) << 2m | pack(i+m, m), so k-wide window packs (and the
    # window validity ANDs) cost O(log k) combines over 1-D arrays
    # instead of O(k) shift-or chains.
    w = {1: cs.astype(jnp.uint64)}                  # fwd pack, MSB-first
    r = {1: (jnp.uint8(3) - cs).astype(jnp.uint64)}  # revcomp pack
    v = {1: valid1}
    m = 1
    while 2 * m <= k:
        lm = n - 2 * m + 1
        w[2 * m] = (w[m][:lm] << jnp.uint64(2 * m)) | w[m][m:m + lm]
        r[2 * m] = r[m][:lm] | (r[m][m:m + lm] << jnp.uint64(2 * m))
        v[2 * m] = v[m][:lm] & v[m][m:m + lm]
        m *= 2

    # Combine the binary decomposition of k (most-significant first).
    parts = [p for p in sorted(w, reverse=True) if k & p]
    fwd = w[parts[0]][:n_win]
    rev = r[parts[0]][:n_win]
    valid = v[parts[0]][:n_win]
    off = parts[0]
    for p in parts[1:]:
        fwd = (fwd << jnp.uint64(2 * p)) | w[p][off:off + n_win]
        rev = rev | (r[p][off:off + n_win] << jnp.uint64(2 * off))
        valid = valid & v[p][off:off + n_win]
        off += p

    gpos = pos + jnp.arange(n, dtype=jnp.int32)
    boundary = jnp.searchsorted(offsets, gpos, side="right")
    valid = valid & (boundary[:n_win] == boundary[k - 1:k - 1 + n_win])

    # Lexicographic-min canonical compare: code order A<C<G<T matches
    # ASCII order, so integer compare == string compare at fixed length
    # (k <= 32 bases in 64 bits).
    use_fwd = fwd <= rev
    return fwd, rev, valid, use_fwd


def _canonical_bytes(cs, use_fwd, k: int, n_win: int):
    """Canonical ASCII byte vectors for the murmur contract: byte j is
    fwd ? ascii(cs[j]) : ascii(3-cs[k-1-j]). The select chains run ONCE
    over the full chunk; the per-byte views are slices of those two
    arrays."""
    af = _ascii64(cs)
    ar = _ascii64(jnp.uint8(3) - cs)
    return [
        jnp.where(use_fwd, af[j:j + n_win],
                  ar[k - 1 - j:k - 1 - j + n_win])
        for j in range(k)
    ]


def _hash_core(
    cs: jax.Array,          # uint8 (C,) sanitized codes, 0-3 everywhere
    valid1: jax.Array,      # bool (C,) False at ambiguous/pad positions
    offsets: jax.Array,     # int32 (B,) contig start offsets (padded with
                            # a value > any position; see iter_chunk_hashes)
    pos: jax.Array,         # int32 scalar: global position of cs[0]
    k: int,
    seed: int,
    algo: str,
) -> jax.Array:
    """Hash every canonical k-mer starting in this chunk -> (C-k+1,) uint64.

    Positions whose window contains an ambiguous base or crosses a contig
    boundary produce HASH_SENTINEL. The caller overlaps consecutive chunks
    by k-1 positions so every k-mer is seen exactly once. The contig id
    per position is derived ON DEVICE from the (tiny) offsets array —
    uploading a per-position boundary array would quadruple the
    host->device traffic of the 1-byte codes.

    Everything is formulated over 1-D shifted slices of `cs` (k static
    slices, fused elementwise chains) — the earlier (n_win, k) 2-D
    formulation materialized hundreds of MB of uint64 intermediates per
    chunk and was HBM-bound.

    `algo` selects the hash: "murmur3" reproduces the reference's finch
    contract bit-for-bit (canonical ASCII k-mer, murmur3 x64_128 h1,
    reference: src/finch.rs:33-47; the golden 0.9808188 depends on it);
    "tpufast" hashes the canonical 2-bit packed k-mer with a
    multiply-free mixer — statistically equivalent MinHash estimates at
    ~20x the device throughput (the VPU has no fast integer multiply).
    """
    n = cs.shape[0]
    n_win = n - k + 1
    fwd, rev, valid, use_fwd = _canonical_core(cs, valid1, offsets, pos, k)

    if algo == "tpufast":
        # the canonical 2-bit packed key is already in hand — no ASCII
        # expansion, no murmur: just the multiply-free mixer
        hashes = _tpufast_mix(jnp.where(use_fwd, fwd, rev), seed)
    elif algo == "murmur3":
        cb = _canonical_bytes(cs, use_fwd, k, n_win)
        if k == 21:
            # Opt-in Mosaic hash state machine (read at FIRST TRACE of
            # the enclosing jit — set before first use, or
            # jax.clear_caches()); interpret mode keeps the opt-in
            # exercisable on CPU backends.
            if _use_pallas_hash():
                from galah_tpu.ops.pallas_sketch import (
                    assemble_k21_words,
                    murmur3_k21_pallas,
                )

                kw1, kw2, kwt = assemble_k21_words(cb)
                hashes = murmur3_k21_pallas(
                    kw1, kw2, kwt, seed=seed,
                    interpret=jax.default_backend() != "tpu")
            else:
                hashes = _murmur3_k21_1d(cb, seed)
        else:
            ascii_kmers = jnp.stack(cb, axis=1).astype(jnp.uint8)
            hashes = murmur3_x64_128_h1(ascii_kmers, seed=seed)
    else:
        raise ValueError(f"unknown hash algorithm {algo!r}")
    return jnp.where(valid, hashes, HASH_SENTINEL)


def canonical_kmer_words(cs, valid1, offsets, pos, k: int, algo: str):
    """Canonical k-mer KEY WORDS + window validity — the front half of
    `_hash_core` (window packing, boundary masking, canonical selection)
    without the hash, for the fused Pallas sketch kernel
    (ops/pallas_sketch.fused_sketch_candidates) which hashes in-kernel.

    Returns (words, valid): `words` is a tuple of uint64 (C-k+1,)
    arrays — the assembled murmur3 key words (k1, k2, tail) for
    algo="murmur3" (k must be 21: the fused kernel bakes the 21-byte
    state machine), or the single canonical 2-bit packed k-mer for
    algo="tpufast". Bit-identical inputs to what `_hash_core` feeds its
    hash stage, so fused sketches match the XLA/C paths exactly.
    """
    n_win = cs.shape[0] - k + 1
    fwd, rev, valid, use_fwd = _canonical_core(cs, valid1, offsets, pos, k)
    if algo == "tpufast":
        return (jnp.where(use_fwd, fwd, rev),), valid
    if algo == "murmur3":
        if k != 21:
            raise ValueError(
                f"fused murmur3 sketching requires k=21, got k={k}")
        from galah_tpu.ops.pallas_sketch import assemble_k21_words

        cb = _canonical_bytes(cs, use_fwd, k, n_win)
        return assemble_k21_words(cb), valid
    raise ValueError(f"unknown hash algorithm {algo!r}")


def canonical_kmer_words_batch(packed, ambits, offsets, k, algo):
    """Batched-row twin of `canonical_kmer_words` over packed genome
    groups (same row layout as canonical_kmer_hashes_batch): (G, C/4)
    packed + (G, C/8) mask + (G, B) offsets -> (words, valid) with each
    word (G, C-k+1) uint64 and valid (G, C-k+1) bool.

    Unjitted building block: the fused sketch path embeds it in the
    same jit as the Pallas launch so XLA fuses the unpack/select chains
    into the kernel's operand production.
    """
    def row(p, a, o):
        cs, v1 = _unpack_codes(p, a)
        return canonical_kmer_words(cs, v1, o, jnp.int32(0), k, algo)

    return jax.vmap(row)(packed, ambits, offsets)


@functools.partial(jax.jit, static_argnames=("k", "seed", "algo"))
def canonical_kmer_hashes_chunk(
    codes: jax.Array,       # uint8 (C,), 0-3 valid, 255 ambiguous/pad
    offsets: jax.Array,
    pos: jax.Array,
    k: int = 21,
    seed: int = 0,
    algo: str = "murmur3",
) -> jax.Array:
    """Hash canonical k-mers from unpacked 1-byte-per-base codes.

    See _hash_core for semantics. Production chunk iteration uses the
    packed twin below (2.7x less host->device transfer); this entry point
    stays for callers holding codes already on device.
    """
    cs = jnp.where(codes == jnp.uint8(255), jnp.uint8(0), codes)
    return _hash_core(cs, codes != jnp.uint8(255), offsets, pos,
                      k, seed, algo)


def _unpack_codes(packed, ambits):
    """2-bit codes + ambiguity bitmask -> (codes uint8 (C,), valid bool)."""
    p = packed
    cs = jnp.stack(
        [(p >> jnp.uint8(6)) & jnp.uint8(3),
         (p >> jnp.uint8(4)) & jnp.uint8(3),
         (p >> jnp.uint8(2)) & jnp.uint8(3),
         p & jnp.uint8(3)], axis=-1).reshape(-1)
    a = ambits
    amb = jnp.stack(
        [(a >> jnp.uint8(s)) & jnp.uint8(1) for s in range(7, -1, -1)],
        axis=-1).reshape(-1)
    return cs, amb == jnp.uint8(0)


def _packed_core(packed, ambits, offsets, pos, k, seed, algo):
    """Unpack 2-bit codes + ambiguity bitmask on device, then hash."""
    cs, valid1 = _unpack_codes(packed, ambits)
    return _hash_core(cs, valid1, offsets, pos, k, seed, algo)


@functools.partial(jax.jit, static_argnames=("k", "seed", "algo"))
def canonical_kmer_hashes_chunk_packed(
    packed: jax.Array,      # uint8 (C/4,): 4 bases/byte, MSB-first
    ambits: jax.Array,      # uint8 (C/8,): ambiguity bitmask, MSB-first
    offsets: jax.Array,
    pos: jax.Array,
    k: int = 21,
    seed: int = 0,
    algo: str = "murmur3",
) -> jax.Array:
    """Packed-transfer twin of canonical_kmer_hashes_chunk, bit-identical.

    The host packs 4 bases/byte plus a 1-bit/base ambiguity mask (0.375
    bytes/base vs 1), and the device unpacks with shift/mask chains —
    host->device bytes are the scarce resource on a tunneled TPU
    (~30 MiB/s), and the unpack is a handful of fused vector ops.
    """
    return _packed_core(packed, ambits, offsets, pos, k, seed, algo)


def canonical_kmer_hashes_batch(packed, ambits, offsets, k, seed, algo):
    """Batched rows: (G, C/4) packed + (G, C/8) mask + (G, B) offsets ->
    (G, C-k+1) uint64 hashes. Each row is an independent genome starting
    at position 0 (offsets are that genome's interior contig starts).

    Unjitted building block (callers embed it in their own jit): one
    dispatch hashes a whole group of genomes — through a tunneled TPU the
    per-dispatch round trip (~50-150 ms) otherwise dominates small-genome
    sketching.
    """
    return jax.vmap(
        lambda p, a, o: _packed_core(p, a, o, jnp.int32(0), k, seed, algo)
    )(packed, ambits, offsets)


@functools.partial(jax.jit, static_argnames=("k", "seed", "algo"))
def canonical_kmer_hashes_batch_jit(packed, ambits, offsets, k=21,
                                    seed=0, algo="murmur3"):
    """Jitted standalone wrapper of canonical_kmer_hashes_batch for
    callers that want the raw positional hash rows (fragment profiles)."""
    return canonical_kmer_hashes_batch(packed, ambits, offsets, k, seed,
                                       algo)


def iter_genome_groups(genomes, budget, max_len, quantum=1 << 16):
    """Host-side grouping for batched sketching: bucket genomes by
    quantum-padded length (+ pow2 interior-offset width, bounding compile
    variants), pack each group, and yield
    (indices, packed (G, L/4), ambits (G, L/8), offsets (G, B)).

    Genomes longer than `max_len` are NOT yielded — callers handle them
    via their chunked single-genome path (their indices are returned in
    the `skipped` list, populated before the first yield).
    """
    import numpy as np

    groups: dict = {}
    skipped = []
    for i, g in enumerate(genomes):
        n = g.codes.shape[0]
        if n > max_len:
            skipped.append(i)
            continue
        lb = max(quantum, -(-n // quantum) * quantum)
        n_off = max(len(g.contig_offsets) - 2, 0)
        b = 1
        while b < max(n_off, 1):
            b <<= 1
        groups.setdefault((lb, b), []).append(i)

    def gen():
        for (lb, b), idxs in sorted(groups.items()):
            per = max(1, budget // lb)
            for start in range(0, len(idxs), per):
                chunk_idxs = idxs[start:start + per]
                G = len(chunk_idxs)
                packed = np.empty((G, lb // 4), dtype=np.uint8)
                ambits = np.empty((G, lb // 8), dtype=np.uint8)
                offs = np.full((G, b), np.int32(2**31 - 1),
                               dtype=np.int32)
                row_codes = np.full(lb, 255, dtype=np.uint8)
                for row, gi in enumerate(chunk_idxs):
                    g = genomes[gi]
                    row_codes[:] = 255
                    row_codes[: g.codes.shape[0]] = g.codes
                    packed[row], ambits[row] = pack_codes_host(row_codes)
                    interior = np.asarray(g.contig_offsets[1:-1],
                                          dtype=np.int64)
                    offs[row, : interior.shape[0]] = (
                        interior.astype(np.int32))
                yield chunk_idxs, packed, ambits, offs

    return skipped, gen()


def pack_codes_host(c: "np.ndarray"):
    """Host-side packing: uint8 codes (len % 8 == 0, 255 = ambiguous/pad)
    -> (packed 4 bases/byte, ambiguity bitmask), both uint8, MSB-first."""
    import numpy as np

    amb = c == 255
    sane = np.where(amb, np.uint8(0), c)
    s4 = sane.reshape(-1, 4)
    packed = ((s4[:, 0] << 6) | (s4[:, 1] << 4)
              | (s4[:, 2] << 2) | s4[:, 3]).astype(np.uint8)
    return packed, np.packbits(amb)


def iter_chunk_hashes(codes, contig_offsets, k: int, chunk: int,
                      seed: int = 0, algo: str = "murmur3"):
    """Yield (hashes, n_new) device arrays over fixed-size overlapping chunks.

    Single implementation of the chunk/pad/overlap discipline shared by the
    MinHash sketcher and the fragment-ANI profiler: chunks overlap by k-1 so
    every k-mer window is hashed exactly once; `n_new` is how many leading
    entries of `hashes` are first-time positions (the rest are overlap).
    """
    import numpy as np

    if chunk <= k - 1:
        raise ValueError(f"chunk ({chunk}) must exceed k-1 ({k - 1})")
    n = codes.shape[0]

    # Bucket the chunk size to the genome: padding a 2 Mbp genome into an
    # 8 Mi chunk would upload 4x the bytes for nothing. Buckets are 64 Ki
    # multiples so XLA compiles a handful of variants.
    quantum = 1 << 16
    chunk = max(quantum, min(chunk, -(-n // quantum) * quantum))
    # Host packing (4 bases/byte + bitmask) needs chunk % 8 == 0; only a
    # caller-supplied chunk between the quantum and the bucketed size can
    # be ragged — round it down (still > k-1 since chunk >= 64 Ki).
    chunk &= ~7

    # Contig offsets, padded to a power-of-two length (bounding compile
    # variants) with a sentinel beyond any real position so the padded
    # entries never split a window.
    offs = np.asarray(contig_offsets[1:-1], dtype=np.int64)
    b = 1
    while b < max(offs.shape[0], 1):
        b <<= 1
    offs_pad = np.full(b, np.int64(2**31 - 1), dtype=np.int64)
    offs_pad[: offs.shape[0]] = offs
    joffs = jnp.asarray(offs_pad.astype(np.int32))

    packed_transfer = device_transfer_bound()
    step = chunk - (k - 1)
    pos = 0
    total = max(n - k + 1, 0)
    while pos < total or pos == 0:
        end = min(pos + chunk, n)
        c = np.full(chunk, 255, dtype=np.uint8)
        c[: end - pos] = codes[pos:end]
        if packed_transfer:
            # Pack on host: 4 bases/byte + 1-bit ambiguity mask (chunk
            # is a 64 Ki multiple, so always divisible by 8). Cuts
            # host->device bytes 2.7x — the dominant cost through a
            # tunneled TPU. On CPU the unpack is pure overhead, so the
            # unpacked twin runs instead (bit-identical).
            packed, ambits = pack_codes_host(c)
            hashes = canonical_kmer_hashes_chunk_packed(
                jnp.asarray(packed), jnp.asarray(ambits), joffs,
                jnp.int32(pos), k=k, seed=seed, algo=algo)
        else:
            hashes = canonical_kmer_hashes_chunk(
                jnp.asarray(c), joffs, jnp.int32(pos), k=k, seed=seed,
                algo=algo)
        n_new = min(total - pos, chunk - k + 1) if total else 0
        yield hashes, pos, n_new
        pos += step
        if end >= n:
            break


@functools.partial(jax.jit, static_argnames=("sketch_size",))
def bottom_k_update(
    running: jax.Array,  # uint64 (sketch_size,) sorted asc, SENTINEL-padded
    hashes: jax.Array,   # uint64 (m,) chunk hashes, SENTINEL where invalid
    sketch_size: int = 1000,
) -> jax.Array:
    """Fold a chunk of hashes into a running bottom-k distinct sketch."""
    allh = jnp.concatenate([running, hashes])
    allh = jnp.sort(allh)
    # Mark duplicates (keep first occurrence), then re-sort and truncate.
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool), allh[1:] == allh[:-1]])
    allh = jnp.where(dup, HASH_SENTINEL, allh)
    allh = jnp.sort(allh)
    return allh[:sketch_size]
