"""HLL cardinality-bucketed hierarchical precluster (GALAH_TPU_HLL_BUCKETS).

The all-pairs precluster pass schedules the full O(N^2) lattice even
though most pairs cannot possibly reach the threshold: a pair's true
Jaccard is containment-limited,

    J(A, B) = |A n B| / |A u B| <= min(|A|, |B|) / max(|A|, |B|),

so two genomes whose k-mer cardinalities differ by more than the
threshold ratio can never pass. Bucketing genomes into overlapping
log-cardinality bands and scheduling only same- and adjacent-band tile
pairs prunes the rest of the lattice BEFORE any MinHash screening —
the 1M-genome regime never materializes the full lattice.

The band width is provably conservative for the pipeline's own
decisions (docs/DISTRIBUTED.md has the full derivation):

  * the pair decision is the SKETCH Jaccard (common/total >= j_thr
    with j_thr = ani_to_jaccard(min_ani, k)); the bottom-k estimate
    concentrates around the true J with std error sqrt(J(1-J)/K), so
    a pair that can pass satisfies J >= j_lo := j_thr - 6*sqrt(
    j_thr*(1-j_thr)/K);
  * HLL cardinality estimates carry relative std error sigma =
    1.04/sqrt(2^p) (~1.6% at p=12); padding by delta = 6*sigma bounds
    the estimate ratio: chat_A/chat_B >= j_lo * (1-delta)/(1+delta);
  * therefore every admissible pair satisfies
    |ln chat_A - ln chat_B| <= L := ln(1/j_lo) + ln((1+delta)/(1-delta)),
    and with band(g) = floor(ln chat_g / L) it lands within one band
    of itself: |band(A) - band(B)| <= 1.

Exact cover without duplicates: for each band b the submatrix S_b is
members(b) + members(b+1) in ascending global order; the pair pass
runs over S_b and only pairs with >= 1 endpoint in band b are kept
(pairs inside band b+1 are covered by S_{b+1}'s run). Every admissible
pair is evaluated exactly once with the SAME per-pair integer stats as
the full pass, so the pair set is bit-identical to bucketing off.

When the margins degenerate (j_lo <= 0 at tiny sketch sizes) the band
width is infinite — one band, zero pruning, still exact.
"""

from __future__ import annotations

import logging
import math
from typing import Callable, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: The bucketed pass must return the exact pair dict of the unbucketed
#: pass: band assignment is pure f64 host math and every scheduled
#: pair's ANI comes from the unchanged per-pair integer stats.
DETERMINISM_CONTRACT = {
    "family": "bucketing",
    "dtype": "float64",
    "functions": ["band_width", "assign_bands",
                  "bucketed_threshold_pairs"],
}

#: 6-sigma margins on both estimators keep the filter conservative far
#: beyond any plausible corpus size (per-pair miss odds ~1e-9).
_SIGMAS = 6.0


def resolve_hll_buckets() -> str:
    """The GALAH_TPU_HLL_BUCKETS flag value ('auto' | '0' | '1')."""
    from galah_tpu.config import env_value

    return (env_value("GALAH_TPU_HLL_BUCKETS") or "auto").strip()


def bucketing_engaged(n: int) -> bool:
    """Whether the cardinality-bucketed precluster pass should run for
    an n-genome workload: forced on ('1'), forced off ('0'), or AUTO —
    on above the sparse-screen crossover (the same large-N regime
    where materializing the full lattice starts to hurt)."""
    raw = resolve_hll_buckets()
    if raw == "0":
        return False
    if raw == "1":
        return n >= 2
    from galah_tpu.ops.collision import sparse_screen_min_n

    return n >= sparse_screen_min_n()


def band_width(min_ani: float, k: int, p: int,
               sketch_size: int) -> float:
    """Log-cardinality band width L (see module docstring); inf when
    the MinHash margin swallows the threshold (no safe pruning)."""
    from galah_tpu.ops.pairwise import ani_to_jaccard

    j_thr = float(ani_to_jaccard(min_ani, k))
    eps_mh = _SIGMAS * math.sqrt(
        j_thr * (1.0 - j_thr) / float(sketch_size))
    j_lo = j_thr - eps_mh
    if j_lo <= 0.0:
        return math.inf
    delta = _SIGMAS * 1.04 / math.sqrt(float(1 << p))
    if delta >= 1.0:
        return math.inf
    return (-math.log(j_lo)
            + math.log((1.0 + delta) / (1.0 - delta)))


def assign_bands(cards: np.ndarray, min_ani: float, k: int, p: int,
                 sketch_size: int) -> np.ndarray:
    """Band index per genome from its HLL cardinality estimate. An
    infinite band width (degenerate margins) puts everything in band
    0 — exact, just unpruned."""
    width = band_width(min_ani, k, p, sketch_size)
    c = np.maximum(np.asarray(cards, dtype=np.float64), 1.0)
    if not math.isfinite(width):
        return np.zeros(c.shape[0], dtype=np.int64)
    return np.floor(np.log(c) / width).astype(np.int64)


def _pair_counts(bands: np.ndarray) -> Tuple[int, int]:
    """(possible, scheduled) pair counts for the funnel gauges."""
    n = int(bands.shape[0])
    possible = n * (n - 1) // 2
    uniq, counts = np.unique(bands, return_counts=True)
    by_band = dict(zip(uniq.tolist(), counts.tolist()))
    scheduled = 0
    for b, m_b in by_band.items():
        m_next = by_band.get(b + 1, 0)
        s = m_b + m_next
        # pairs of S_b with >= 1 endpoint in band b (the kept set)
        scheduled += s * (s - 1) // 2 - m_next * (m_next - 1) // 2
    return possible, scheduled


def bucketed_threshold_pairs(
    sketch_mat: np.ndarray,
    cards: np.ndarray,
    k: int,
    min_ani: float,
    sketch_size: Optional[int] = None,
    p: int = 12,
    pair_pass: Optional[Callable[[np.ndarray], dict]] = None,
) -> Dict[Tuple[int, int], float]:
    """threshold_pairs with the cardinality-band prefilter: identical
    {(i, j): ani} pair dict, only same- and adjacent-band submatrices
    ever scheduled. `cards` is the per-genome HLL cardinality estimate
    aligned with `sketch_mat` rows; `pair_pass` (default
    ops/pairwise.threshold_pairs) maps a row-subset matrix to its
    local pair dict and is free to route to the C / sparse / 1-D / 2D
    mesh implementations — every one is per-pair exact.

    `sketch_mat` may be a real (N, K) matrix or any duck-typed object
    with `.shape` and a `band_gather(indices) -> contiguous submatrix`
    method (io/pagestore.py): the band walk only ever gathers bands
    b u (b+1), which is exactly the paging schedule — a paged store
    pins at most two bands' pages at once and the submatrices handed
    to `pair_pass` are bit-identical to all-resident slicing, so the
    pair dict is too (docs/memory.md)."""
    from galah_tpu.obs import events, metrics as obs_metrics

    n = sketch_mat.shape[0]
    eff_size = (sketch_size if sketch_size is not None
                else sketch_mat.shape[1])
    if pair_pass is None:
        from galah_tpu.ops.pairwise import threshold_pairs

        def pair_pass(sub):
            return threshold_pairs(sub, k=k, min_ani=min_ani,
                                   sketch_size=eff_size)

    bands = assign_bands(cards, min_ani, k, p, eff_size)
    possible, scheduled = _pair_counts(bands)
    pruned = possible - scheduled

    members: Dict[int, np.ndarray] = {
        int(b): np.nonzero(bands == b)[0]
        for b in np.unique(bands).tolist()}

    # Paged stores expose band_gather: rows of bands b u (b+1) land in
    # one contiguous copy while only their pages are pinned resident.
    band_gather = getattr(sketch_mat, "band_gather", None)

    out: Dict[Tuple[int, int], float] = {}
    for b in sorted(members):
        own = members[b]
        nxt = members.get(b + 1)
        idx = (own if nxt is None
               else np.sort(np.concatenate([own, nxt])))
        if idx.shape[0] < 2:
            continue
        in_b = set(own.tolist())
        if band_gather is not None:
            sub = pair_pass(band_gather(idx))
        else:
            sub = pair_pass(np.ascontiguousarray(sketch_mat[idx]))
        for (a, bb), ani in sub.items():
            ga, gb = int(idx[a]), int(idx[bb])
            # within-(b+1) pairs belong to S_{b+1}'s run
            if ga in in_b or gb in in_b:
                out[(ga, gb)] = ani

    n_bands = len(members)
    obs_metrics.gauge(
        "precluster.bucket_pruned_pairs",
        help="Candidate pairs the HLL cardinality-band prefilter "
             "removed from the all-pairs schedule (last precluster "
             "pass)", unit="pairs").set(float(pruned))
    obs_metrics.gauge(
        "precluster.bucket_pruned_fraction",
        help="Fraction of the full pair lattice the cardinality-band "
             "prefilter pruned (last precluster pass)",
        unit="fraction").set(
        float(pruned) / possible if possible else 0.0)
    obs_metrics.gauge(
        "precluster.bucket_count",
        help="Non-empty HLL cardinality bands in the last bucketed "
             "precluster pass", unit="bands").set(float(n_bands))
    events.record("hll-buckets", bands=n_bands, possible=possible,
                  scheduled=scheduled, pruned=pruned)
    logger.info(
        "HLL cardinality bucketing: %d bands, %d/%d candidate pairs "
        "pruned (%.1f%%)", n_bands, pruned, possible,
        100.0 * pruned / possible if possible else 0.0)
    return out
