"""Shared numeric constants for the sketch/pairwise kernels."""

# uint64 sentinel meaning "no hash here" (padding / invalid k-mer). Shared
# by the JAX kernels (ops/hashing.py re-exports it as a jnp scalar) and all
# host-side padding code.
SENTINEL = 0xFFFFFFFFFFFFFFFF
