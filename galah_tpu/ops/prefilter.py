"""Ingest-time probabilistic k-mer prefilter — tier 1 of the sketch
memory hierarchy (docs/memory.md).

Runs inside the streamed sketch stage (`ops/sketch_stream.py`),
screening genomes *before* they reach the batched device sketcher:

* **Duplicate screen** — a content digest (sha256 over the 2-bit code
  array and contig offsets) spots byte-identical genomes behind
  different paths.  The MinHash sketch is a pure function of the
  canonical k-mer multiset, which is itself a pure function of
  (codes, contig_offsets, k), so aliasing the first occurrence's
  sketch is *bit-identical* to recomputing it — the provably
  conservative case of deduplication.
* **Degenerate screen** — a genome with no valid k-mer window (every
  contig shorter than k, or no run of k unambiguous bases) has an
  empty k-mer set; its sketch is computed by the per-genome host
  sketcher (bit-identical to every batched strategy by the strategy
  contract) without occupying a device batch slot.
* **HLL pre-warm** — while the genome codes are hot in cache, the HLL
  registers the bucketed precluster needs later are computed on the C
  fast path (csrc/sketch.c::galah_hll_registers) and stored under the
  exact diskcache key `HLLPreclusterer` probes (kind="hll",
  params {p, k, seed, algo}), so the cardinality pass that drives the
  band-paging schedule never re-reads the FASTA files.

Conservativeness argument
-------------------------
A skip is only taken when the skipped genome's sketch is *provably
equal* to what the full pipeline would produce (duplicate: same input
bytes; degenerate: empty k-mer set).  Low k-mer cardinality alone is
measured (it feeds the band schedule) but never skips — "looks
low-complexity" cannot be conservative, because two low-complexity
genomes can still share a cluster.  Hence: prefilter on/off changes
no pair set and no clustering, bit for bit; the `prefilter.skipped`
counter is the only observable difference.

Gate: ``GALAH_TPU_PREFILTER`` (auto / 0 / 1).  auto engages with the
streamed single-process ingest.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def prefilter_mode() -> str:
    """The ``GALAH_TPU_PREFILTER`` tri-state: 'auto', '0' or '1'."""
    from galah_tpu import config

    val = config.env_value("GALAH_TPU_PREFILTER") or "auto"
    return val if val in ("auto", "0", "1") else "auto"


def prefilter_engaged() -> bool:
    """Whether the ingest prefilter should run for this process.

    '1' forces it, '0' disables it; 'auto' engages on single-process
    runs (the streamed ingest path — multi-host runs shard paths per
    host, where cross-host duplicates would dodge the digest table
    anyway)."""
    mode = prefilter_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    from galah_tpu.parallel import distributed

    return distributed.process_count() == 1


def _digest(genome) -> str:
    """Content digest of the parsed genome: identical digests imply
    identical canonical k-mer multisets, hence identical sketches."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(genome.codes).tobytes())
    h.update(b"|")
    h.update(np.ascontiguousarray(genome.contig_offsets).tobytes())
    return h.hexdigest()


def _has_valid_window(genome, k: int) -> bool:
    """True unless the genome provably has zero valid k-mer windows
    (no contig holds k consecutive unambiguous bases)."""
    codes = genome.codes
    offsets = genome.contig_offsets
    if codes.shape[0] < k:
        return False
    valid = codes != 255
    for c in range(offsets.shape[0] - 1):
        lo, hi = int(offsets[c]), int(offsets[c + 1])
        if hi - lo < k:
            continue
        run = valid[lo:hi]
        if run.all():
            return True
        # longest run of True: diff over padded cumulative resets
        idx = np.flatnonzero(~run)
        edges = np.concatenate(([-1], idx, [run.shape[0]]))
        if int(np.diff(edges).max()) - 1 >= k:
            return True
    return False


class IngestPrefilter:
    """Screens the streamed miss iterator; resolves screened paths to
    their provably-equal sketches at merge time.

    Single-threaded contract: ``screen`` is pulled by the compute
    pipeline's consumer chain and ``resolve`` by the merge loop — both
    on the consumer side of the stream, never concurrently."""

    def __init__(self, store, prewarm_hll: bool = True):
        from galah_tpu.obs import metrics as obs_metrics

        self.store = store
        # Pre-warming needs somewhere durable to put the registers; a
        # disabled cache (CacheDir(None)) would throw the work away.
        self.prewarm_hll = (prewarm_hll
                            and getattr(store.cache, "enabled", False))
        self._by_digest: Dict[str, str] = {}     # digest -> first path
        self._aliases: Dict[str, str] = {}       # dup path -> first path
        self._degenerate: Dict[str, object] = {}  # path -> MinHashSketch
        self._c_skipped = obs_metrics.counter(
            "prefilter.skipped", unit="genomes",
            help="genomes screened out of the full sketch pipeline by "
                 "the ingest prefilter (skips are provably "
                 "bit-identical: duplicates alias the first "
                 "occurrence's sketch, degenerate genomes have an "
                 "empty k-mer set)")
        self._c_dup = obs_metrics.counter(
            "prefilter.skipped_duplicate", unit="genomes",
            help="prefilter skips taken because the genome bytes "
                 "duplicate an earlier path")
        self._c_degen = obs_metrics.counter(
            "prefilter.skipped_degenerate", unit="genomes",
            help="prefilter skips taken because the genome has no "
                 "valid k-mer window")
        self._c_prewarm = obs_metrics.counter(
            "prefilter.hll_prewarmed", unit="genomes",
            help="HLL register rows computed during ingest and cached "
                 "for the bucketed precluster's cardinality pass")

    # -- producer side -----------------------------------------------------

    def screen(self, miss_iter: Iterable) -> Iterator:
        """Filter (path, genome) pairs: forward genomes that need the
        full sketch pipeline, record provable skips for ``resolve``."""
        for path, genome in miss_iter:
            if self.prewarm_hll:
                self._prewarm(path, genome)
            digest = _digest(genome)
            first = self._by_digest.get(digest)
            if first is not None:
                self._aliases[path] = first
                self._c_skipped.inc()
                self._c_dup.inc()
                continue
            self._by_digest[digest] = path
            if not _has_valid_window(genome, self.store.k):
                # empty k-mer set: the host per-genome sketcher is
                # bit-identical to every batched strategy and costs
                # nothing here (no windows to hash)
                self._degenerate[path] = self.store.sketch_only(genome)
                self._c_skipped.inc()
                self._c_degen.inc()
                continue
            yield path, genome

    def _prewarm(self, path: str, genome) -> None:
        from galah_tpu.ops import hll

        params = {"p": hll.DEFAULT_P, "k": self.store.k,
                  "seed": self.store.seed, "algo": self.store.algo}
        try:
            if self.store.cache.load(path, "hll", params) is not None:
                return
            row = hll.hll_sketch_genome(
                genome, p=hll.DEFAULT_P, k=self.store.k,
                seed=self.store.seed, algo=self.store.algo)
            self.store.cache.store(path, "hll", params, {"regs": row})
            self._c_prewarm.inc()
        except Exception as exc:  # pre-warm is an optimization only
            logger.warning("HLL pre-warm failed for %s: %s", path, exc)
            self.prewarm_hll = False

    # -- consumer side -----------------------------------------------------

    def resolve(self, path: str):
        """The screened path's sketch, or None if the path went
        through the full pipeline.  Must succeed for every path
        ``screen`` skipped — the merge loop has no other source."""
        s = self._degenerate.pop(path, None)
        if s is not None:
            return s
        first = self._aliases.get(path)
        if first is None:
            return None
        s = self.store.get_cached(first)
        if s is None:
            raise RuntimeError(
                f"prefilter invariant broken: duplicate {path!r} "
                f"aliases {first!r} but its sketch is not retained")
        return s


def maybe_prefilter(store) -> Optional[IngestPrefilter]:
    """An armed prefilter when the gate engages, else None."""
    return IngestPrefilter(store) if prefilter_engaged() else None
