"""Pallas TPU kernel: pairwise merged-bottom-k MinHash statistics.

The finch-equivalent precluster pass needs, for every sketch pair, the
pair (common, total) of the merged bottom-k distinct union
(ops/pairwise._pair_stats). The XLA path does a per-pair searchsorted;
Mosaic has no wide per-lane gather and no 64-bit integers, so the kernel
recomputes the same quantities from block compares on u32 hi/lo planes:

  * for each 128-element chunk of query sketch `a` (laid out along
    sublanes via a host-side transpose — no in-kernel relayout), compare
    against the whole reference sketch `b` broadcast along lanes: u64
    less-than/equal from lexicographic (hi, lo) compares. Row-sums give
    ltcnt_i = #{b < a_i} and a match flag per a_i.
  * union rank of a matched a_i is i + ltcnt_i - (#matches before i);
    the prefix term comes from log-step shift cumsums (no gathers).
  * common = matches with union rank < total, total = min(sketch_size,
    na + nb - n_matches) — bit-identical to the XLA path's integers.

One grid program computes one pair; a (Br, Bc) tile is a (Br, Bc) grid.
O(K^2) compares per pair instead of O(K log K) gathers — the VPU-
friendly trade on hardware where gathers are the scarce resource.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

CH = 128  # a-chunk: elements per sublane block

def _inclusive_cumsum_axis0(x: jax.Array) -> jax.Array:
    """Hillis-Steele prefix sum along sublanes via static shifts."""
    n = x.shape[0]
    sh = 1
    while sh < n:
        shifted = jnp.concatenate(
            [jnp.zeros((sh, x.shape[1]), x.dtype), x[:-sh, :]], axis=0)
        x = x + shifted
        sh *= 2
    return x


def _inclusive_cumsum_axis1(x: jax.Array) -> jax.Array:
    n = x.shape[1]
    sh = 1
    while sh < n:
        shifted = jnp.concatenate(
            [jnp.zeros((x.shape[0], sh), x.dtype), x[:, :-sh]], axis=1)
        x = x + shifted
        sh *= 2
    return x


def _make_kernel(k_width: int, sketch_size: int):
    nch = k_width // CH

    def kernel(a_hi_ref, a_lo_ref, b_hi_ref, b_lo_ref,
               common_ref, total_ref, lt_scr, match_scr):
        umax = jnp.uint32(0xFFFFFFFF)
        bh = b_hi_ref[:]          # (1, K)
        bl = b_lo_ref[:]

        na = jnp.int32(0)
        nb = jnp.sum((~((bh == umax) & (bl == umax))).astype(jnp.int32))

        for r in range(nch):
            ahc = a_hi_ref[r * CH:(r + 1) * CH, :]     # (CH, 1)
            alc = a_lo_ref[r * CH:(r + 1) * CH, :]
            # b_j < a_i on u64 via lexicographic u32 halves; sentinel
            # entries (UMAX, UMAX) are never < anything and only equal
            # other sentinels, which valid_a masks out.
            lt = (bh < ahc) | ((bh == ahc) & (bl < alc))     # (CH, K)
            eq = (bh == ahc) & (bl == alc)
            ltcnt = jnp.sum(lt.astype(jnp.int32), axis=1, keepdims=True)
            eqany = jnp.sum(eq.astype(jnp.int32), axis=1, keepdims=True)
            valid_a = ~((ahc == umax) & (alc == umax))
            match = ((eqany > 0) & valid_a).astype(jnp.int32)
            na = na + jnp.sum(valid_a.astype(jnp.int32))
            lt_scr[:, r:r + 1] = ltcnt
            match_scr[:, r:r + 1] = match

        match = match_scr[:]      # (CH, nch); a-index = col*CH + row
        ltv = lt_scr[:]
        n_common_all = jnp.sum(match)
        n_union = na + nb - n_common_all
        total = jnp.minimum(jnp.int32(sketch_size), n_union)

        colsum = jnp.sum(match, axis=0, keepdims=True)        # (1, nch)
        col_excl = _inclusive_cumsum_axis1(colsum) - colsum   # (1, nch)
        row_excl = _inclusive_cumsum_axis0(match) - match     # (CH, nch)
        cexcl = col_excl + row_excl

        s_idx = jax.lax.broadcasted_iota(jnp.int32, (CH, nch), 0)
        r_idx = jax.lax.broadcasted_iota(jnp.int32, (CH, nch), 1)
        i_idx = r_idx * CH + s_idx
        urank = i_idx + ltv - cexcl
        common = jnp.sum(match * (urank < total).astype(jnp.int32))

        common_ref[0, 0] = common
        total_ref[0, 0] = total

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("sketch_size", "interpret"))
def tile_stats_pallas(
    rows: jax.Array,   # uint64 (Br, K) sorted asc, SENTINEL-padded
    cols: jax.Array,   # uint64 (Bc, K)
    sketch_size: int,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 (Br, Bc) tiles — the Pallas twin of
    ops/pairwise.tile_stats (bit-identical integers)."""
    br, k_in = rows.shape
    bc = cols.shape[0]
    k_pad = -(-k_in // CH) * CH
    if k_pad != k_in:
        fill = jnp.full((1, k_pad - k_in), ~jnp.uint64(0), jnp.uint64)
        rows = jnp.concatenate([rows, jnp.tile(fill, (br, 1))], axis=1)
        cols = jnp.concatenate([cols, jnp.tile(fill, (bc, 1))], axis=1)

    a_hi = (rows >> jnp.uint64(32)).astype(jnp.uint32).T   # (K, Br)
    a_lo = rows.astype(jnp.uint32).T
    b_hi = (cols >> jnp.uint64(32)).astype(jnp.uint32)     # (Bc, K)
    b_lo = cols.astype(jnp.uint32)

    nch = k_pad // CH
    kernel = _make_kernel(k_pad, sketch_size)
    return pl.pallas_call(
        kernel,
        grid=(br, bc),
        in_specs=[
            pl.BlockSpec((k_pad, 1), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, 1), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (i, j),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i, j: (i, j),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((br, bc), jnp.int32),
            jax.ShapeDtypeStruct((br, bc), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((CH, nch), jnp.int32),
            pltpu.VMEM((CH, nch), jnp.int32),
        ],
        interpret=interpret,
    )(a_hi, a_lo, b_hi, b_lo)
