"""Pallas TPU kernel: pairwise merged-bottom-k MinHash statistics.

The finch-equivalent precluster pass needs, for every sketch pair, the
(common, total) stats of the merged bottom-k distinct union
(ops/pairwise._pair_stats). The XLA path does a per-pair u64
searchsorted — gather-heavy and 64-bit-emulated, both scarce on TPU.
This kernel recomputes the same integers from dense block compares on
u32 hi/lo planes, the VPU-friendly trade: O(K^2) vectorized compares
per pair instead of O(K log K) gathers.

Layouts (chosen so every BlockSpec is legal under Mosaic's (8, 128)
tiling rule — blocks either tile-align or span the full axis, and all
dynamic indexing happens on sublane (second-minor) dims, never lanes):

  * query sketches `a`: (Br*8, K/8) — query i's k-mer k = l*8 + s sits
    at row i*8+s, lane l: one query is a dynamically sliceable (8, K/8)
    sublane group, and a CHUNK of 8 consecutive sorted values is one
    static lane column (8, 1);
  * reference sketches `b`: (Bc*(K/128), 128) — reference j's chunk s
    (128 consecutive sorted values) is the dynamically sliceable row
    j*(K/128)+s;
  * outputs: (Br, Bc) int32 in (8, 128)-aligned VMEM blocks.

One grid program computes an (8, Bc) output stripe: fori loops walk the
8 query rows and all references; per pair, a static loop over a-chunks
and a fori loop over b-chunks accumulate, via broadcast (8, 1) x
(1, 128) compares, both #(b < a_i) and #(b == a_i) per query element;
union ranks come from log-step prefix sums exactly as in the XLA path.
Per-pair scalars land in the output lane vector via one-hot
accumulation (dynamic lane stores don't exist on TPU). Bit-identical
integers to ops/pairwise.tile_stats.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from galah_tpu.obs.profile import profiled

A_SUB = 8     # a-chunk height: consecutive sketch values per lane column
B_LANE = 128  # b-chunk width: consecutive sketch values per sublane row
ROWS_PER_PROGRAM = 8

# Static kernel contract checked by `galah-tpu lint` (GL1xx): bindings
# are representative *maximum* values of the call-site locals the
# BlockSpec shapes reference — k_pad=1024 (la = k_pad/A_SUB,
# sb = k_pad/B_LANE) and bc at its 4 MiB reference-side chunk limit.
PALLAS_CONTRACT = {
    "tile_stats_pallas": {
        "bindings": {"rp": 8, "la": 128, "sb": 8, "bc": 512},
        "in_dtypes": ["uint32", "uint32", "uint32", "uint32"],
        "kernel_fns": ["_make_kernel", "_pairmin", "_pairmax",
                       "_col_reduce", "_ssum_i32"],
    },
}


def _inclusive_cumsum_axis0(x: jax.Array) -> jax.Array:
    """Hillis-Steele prefix sum along sublanes via static shifts."""
    n = x.shape[0]
    sh = 1
    while sh < n:
        shifted = jnp.concatenate(
            [jnp.zeros((sh, x.shape[1]), x.dtype), x[:-sh, :]], axis=0)
        x = x + shifted
        sh *= 2
    return x


def _inclusive_cumsum_axis1(x: jax.Array) -> jax.Array:
    n = x.shape[1]
    sh = 1
    while sh < n:
        shifted = jnp.concatenate(
            [jnp.zeros((x.shape[0], sh), x.dtype), x[:, :-sh]], axis=1)
        x = x + shifted
        sh *= 2
    return x



def _ssum_i32(x) -> jax.Array:
    """Scalar int32 sum that survives Mosaic lowering under x64: the
    scalar-reduce proxy in the Mosaic lowering re-sums WITHOUT a dtype
    (promoting to int64, unsupported on TPU), so keep every reduction's
    output non-scalar — one axis at a time, keepdims, explicit dtype —
    and only then extract the scalar."""
    s = jnp.sum(x.astype(jnp.int32), axis=1, keepdims=True,
                dtype=jnp.int32)
    s = jnp.sum(s, axis=0, keepdims=True, dtype=jnp.int32)
    return s[0, 0]

def _pairmin(h1, l1, h2, l2):
    take2 = (h2 < h1) | ((h2 == h1) & (l2 < l1))
    return jnp.where(take2, h2, h1), jnp.where(take2, l2, l1)


def _pairmax(h1, l1, h2, l2):
    take2 = (h2 > h1) | ((h2 == h1) & (l2 > l1))
    return jnp.where(take2, h2, h1), jnp.where(take2, l2, l1)


def _col_reduce(h, low, op):
    """Per-column u64 min/max over sublanes of u32 (rows, la) planes
    via a slicing tournament -> (1, la) planes."""
    rows = h.shape[0]
    while rows > 1:
        half = rows // 2
        h, low = op(h[:half], low[:half], h[half:rows], low[half:rows])
        rows = half
    return h, low


def _make_kernel(la: int, sb: int, bc: int, sketch_size: int,
                 intersect: bool, range_skip: bool):
    """Kernel for K = 8*la = 128*sb padded sketch width.

    One program: rp=8 queries (a 64-sublane block) against all bc
    references. The compare loop batches ALL 8 queries into each
    (64, 128) vector op, so per-pair cost is one-eighth of a
    query-at-a-time formulation; the rank epilogue then runs per query
    on (8, la) slices. With `intersect` the kernel skips the less-than
    accumulation and rank math entirely and reports the raw
    |query ∩ reference| per pair (the marker-screening primitive,
    ops/pairwise.tile_intersect_counts).
    """
    rp = ROWS_PER_PROGRAM
    nrows = rp * A_SUB  # 64

    def kernel(a_hi_ref, a_lo_ref, b_hi_ref, b_lo_ref,
               common_ref, total_ref, lt_scr, eq_scr):
        umax = jnp.uint32(0xFFFFFFFF)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, bc), 1)
        subl = jax.lax.broadcasted_iota(jnp.int32, (rp, bc), 0)
        ah = a_hi_ref[:]          # (64, la); query q row group q*8..q*8+7
        al = a_lo_ref[:]
        valid_a = ~((ah == umax) & (al == umax))
        # per-query valid counts, computed once per program
        na_q = [
            _ssum_i32(valid_a[q * A_SUB:(q + 1) * A_SUB, :])
            for q in range(rp)
        ]
        if range_skip:
            # per-column u64 min/max over all 64 query values, once per
            # program: the skip tests below compare b-chunk endpoint
            # scalars against these
            amin_h, amin_l = _col_reduce(ah, al, _pairmin)   # (1, la)
            amax_h, amax_l = _col_reduce(ah, al, _pairmax)

        def j_body(j, carry):
            crows, trows = carry      # (rp, bc) int32 accumulators

            # reference j's valid count (shared by all queries); b rows
            # are sorted, so chunk endpoints are free scalar extracts
            nb = jnp.int32(0)
            b_first = []
            b_last = []
            for s in range(sb):
                bh = b_hi_ref[pl.ds(j * sb + s, 1), :]
                bl = b_lo_ref[pl.ds(j * sb + s, 1), :]
                nb = nb + _ssum_i32(~((bh == umax) & (bl == umax)))
                if range_skip:
                    b_first.append((bh[0, 0], bl[0, 0]))
                    b_last.append((bh[0, B_LANE - 1], bl[0, B_LANE - 1]))

            # compare loop: for each a-chunk column l, all 8 queries'
            # chunk-l elements (64, 1) against every b chunk (1, 128);
            # u64 compares from lexicographic (hi, lo) u32 halves.
            # Sentinel b entries (UMAX, UMAX) are never < a valid value
            # and only equal other sentinels, which valid_a masks out.
            for l in range(la):
                a_h = ah[:, l:l + 1]  # (64, 1) — static lane slice
                a_l = al[:, l:l + 1]
                if range_skip:
                    # chunks wholly below the column minimum form a
                    # PREFIX (b sorted): they contribute 128 to every
                    # lt count and nothing to eq; chunks wholly above
                    # the maximum form a suffix and contribute nothing.
                    # A wholly-below chunk can't hold sentinels (its
                    # max would be UMAX), so its valid count is exactly
                    # B_LANE. Only [s_lo, s_hi) compares elementwise.
                    mn_h = amin_h[0, l]
                    mn_l = amin_l[0, l]
                    mx_h = amax_h[0, l]
                    mx_l = amax_l[0, l]
                    s_lo = jnp.int32(0)
                    s_hi = jnp.int32(sb)
                    for s in range(sb):
                        fh, fl = b_first[s]
                        lh, ll = b_last[s]
                        below = (lh < mn_h) | ((lh == mn_h) & (ll < mn_l))
                        above = (fh > mx_h) | ((fh == mx_h) & (fl > mx_l))
                        s_lo = s_lo + below.astype(jnp.int32)
                        s_hi = s_hi - above.astype(jnp.int32)

                    def body(s, carry, a_h=a_h, a_l=a_l):
                        lt_c, eq_c = carry
                        bh = b_hi_ref[pl.ds(j * sb + s, 1), :]
                        bl = b_lo_ref[pl.ds(j * sb + s, 1), :]
                        eq = (bh == a_h) & (bl == a_l)
                        eq_c = eq_c + eq.astype(jnp.int32)
                        if not intersect:
                            lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
                            lt_c = lt_c + lt.astype(jnp.int32)
                        return lt_c, eq_c

                    zero = jnp.zeros((nrows, B_LANE), jnp.int32)
                    ltacc, eqacc = jax.lax.fori_loop(
                        s_lo, jnp.maximum(s_hi, s_lo), body, (zero, zero))
                    if not intersect:
                        lt_scr[:, l:l + 1] = (
                            jnp.sum(ltacc, axis=1, keepdims=True,
                                    dtype=jnp.int32)
                            + s_lo * jnp.int32(B_LANE))
                    eq_scr[:, l:l + 1] = jnp.sum(
                        eqacc, axis=1, keepdims=True, dtype=jnp.int32)
                    continue
                ltacc = jnp.zeros((nrows, B_LANE), jnp.int32)
                eqacc = jnp.zeros((nrows, B_LANE), jnp.int32)
                for s in range(sb):
                    bh = b_hi_ref[pl.ds(j * sb + s, 1), :]   # (1, 128)
                    bl = b_lo_ref[pl.ds(j * sb + s, 1), :]
                    eq = (bh == a_h) & (bl == a_l)           # (64, 128)
                    eqacc = eqacc + eq.astype(jnp.int32)
                    if not intersect:
                        lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
                        ltacc = ltacc + lt.astype(jnp.int32)
                if not intersect:
                    lt_scr[:, l:l + 1] = jnp.sum(
                        ltacc, axis=1, keepdims=True, dtype=jnp.int32)
                eq_scr[:, l:l + 1] = jnp.sum(
                    eqacc, axis=1, keepdims=True, dtype=jnp.int32)

            eqv_all = eq_scr[:]
            hot = (lane == j).astype(jnp.int32)              # (1, bc)
            if not intersect:
                ltv_all = lt_scr[:]

            # per-query epilogue on its (8, la) slice
            for q in range(rp):
                sl = slice(q * A_SUB, (q + 1) * A_SUB)
                eqv = eqv_all[sl, :]
                va = valid_a[sl, :]
                match = ((eqv > 0) & va).astype(jnp.int32)
                n_common_all = _ssum_i32(match)
                if intersect:
                    qmask = (subl == q).astype(jnp.int32)
                    crows = crows + qmask * (hot * n_common_all)
                    trows = trows + qmask * (hot * na_q[q])
                    continue
                ltv = ltv_all[sl, :]
                n_union = na_q[q] + nb - n_common_all
                total = jnp.minimum(jnp.int32(sketch_size), n_union)

                # union rank of matched a_i (i = l*8 + s): i + #(b<a_i)
                # - #(matches before i), via log-step shift cumsums
                colsum = jnp.sum(match, axis=0, keepdims=True,
                                 dtype=jnp.int32)             # (1, la)
                col_excl = _inclusive_cumsum_axis1(colsum) - colsum
                row_excl = _inclusive_cumsum_axis0(match) - match
                cexcl = col_excl + row_excl

                s_idx = jax.lax.broadcasted_iota(
                    jnp.int32, (A_SUB, la), 0)
                l_idx = jax.lax.broadcasted_iota(
                    jnp.int32, (A_SUB, la), 1)
                i_idx = l_idx * A_SUB + s_idx
                urank = i_idx + ltv - cexcl
                common = _ssum_i32(
                    match * (urank < total).astype(jnp.int32))

                qmask = (subl == q).astype(jnp.int32)         # (rp, bc)
                crows = crows + qmask * (hot * common)
                trows = trows + qmask * (hot * total)
            return crows, trows

        crows, trows = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(bc), j_body,
            (jnp.zeros((rp, bc), jnp.int32),
             jnp.zeros((rp, bc), jnp.int32)))
        common_ref[:] = crows
        total_ref[:] = trows

    return kernel


def _zi(i):
    """Index-map zero with the grid index's own dtype: a literal 0 in an
    index map canonicalizes to int64 under x64, which Mosaic rejects."""
    return i * 0


def _split_planes(mat: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return ((mat >> jnp.uint64(32)).astype(jnp.uint32),
            mat.astype(jnp.uint32))


@profiled("pairwise.tile_stats_pallas")
@functools.partial(jax.jit,
                   static_argnames=("sketch_size", "interpret",
                                    "intersect", "range_skip"))
def tile_stats_pallas(
    rows: jax.Array,   # uint64 (Br, K) sorted asc, SENTINEL-padded
    cols: jax.Array,   # uint64 (Bc, K)
    sketch_size: int,
    interpret: bool = False,
    intersect: bool = False,
    range_skip: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 (Br, Bc) tiles — the Pallas twin of
    ops/pairwise.tile_stats (bit-identical integers). With `intersect`,
    `common` is the raw |row ∩ col| count (the twin of
    ops/pairwise.tile_intersect_counts) and `total` the row's valid
    count.

    range_skip is QUARANTINED (hardware-retired): the 2026-08-01
    amortized on-chip campaign measured the skip variant 3.7x SLOWER
    on the dense tile (218.1k -> 59.4k pairs/s at 512x512;
    docs/artifacts/tpu_watch_20260801_0829/amortized.txt) — the
    data-dependent window bounds defeat Mosaic's static scheduling.
    No default path sets it; its parity tests run only in the
    slow/hardware tier. Kept as the reference windowed-compare
    formulation."""
    br_in, k_in = rows.shape
    bc_in = cols.shape[0]
    sent = ~jnp.uint64(0)

    # The reference side resides fully in VMEM (bc * k_pad * 8 bytes of
    # u32 planes); chunk the columns when it would overflow.
    k_pad_probe = -(-k_in // B_LANE) * B_LANE
    bc_limit = max(B_LANE, (4 << 20) // (k_pad_probe * 8))
    bc_limit = (bc_limit // B_LANE) * B_LANE
    if bc_in > bc_limit:
        parts = [
            tile_stats_pallas(rows, cols[c0:c0 + bc_limit], sketch_size,
                              interpret=interpret, intersect=intersect,
                              range_skip=range_skip)
            for c0 in range(0, bc_in, bc_limit)
        ]
        return (jnp.concatenate([p[0] for p in parts], axis=1),
                jnp.concatenate([p[1] for p in parts], axis=1))

    k_pad = -(-k_in // B_LANE) * B_LANE
    if k_pad != k_in:
        fill = jnp.full((1, k_pad - k_in), sent, jnp.uint64)
        rows = jnp.concatenate(
            [rows, jnp.tile(fill, (br_in, 1))], axis=1)
        cols = jnp.concatenate(
            [cols, jnp.tile(fill, (bc_in, 1))], axis=1)

    # Pad rows to the program height, cols to the output lane quantum.
    br = -(-br_in // ROWS_PER_PROGRAM) * ROWS_PER_PROGRAM
    bc = -(-bc_in // B_LANE) * B_LANE
    if br != br_in:
        rows = jnp.concatenate(
            [rows, jnp.full((br - br_in, k_pad), sent, jnp.uint64)],
            axis=0)
    if bc != bc_in:
        cols = jnp.concatenate(
            [cols, jnp.full((bc - bc_in, k_pad), sent, jnp.uint64)],
            axis=0)

    la = k_pad // A_SUB
    sb = k_pad // B_LANE

    # a: (Br, K) -> (Br*8, la); query i's value k = l*8 + s at
    # (row i*8 + s, lane l)
    a_hi, a_lo = _split_planes(rows)
    a_hi2 = a_hi.reshape(br, la, A_SUB).transpose(0, 2, 1).reshape(
        br * A_SUB, la)
    a_lo2 = a_lo.reshape(br, la, A_SUB).transpose(0, 2, 1).reshape(
        br * A_SUB, la)
    # b: (Bc, K) -> (Bc*sb, 128); ref j's chunk s (k = s*128 + l) at
    # row j*sb + s
    b_hi, b_lo = _split_planes(cols)
    b_hi2 = b_hi.reshape(bc * sb, B_LANE)
    b_lo2 = b_lo.reshape(bc * sb, B_LANE)

    kernel = _make_kernel(la, sb, bc, sketch_size, bool(intersect),
                          bool(range_skip))
    rp = ROWS_PER_PROGRAM
    common, total = pl.pallas_call(
        kernel,
        grid=(br // rp,),
        in_specs=[
            pl.BlockSpec((rp * A_SUB, la), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rp * A_SUB, la), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bc * sb, B_LANE),
                         lambda i: (_zi(i), _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bc * sb, B_LANE),
                         lambda i: (_zi(i), _zi(i)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rp, bc), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rp, bc), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((br, bc), jnp.int32),
            jax.ShapeDtypeStruct((br, bc), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rp * A_SUB, la), jnp.int32),
            pltpu.VMEM((rp * A_SUB, la), jnp.int32),
        ],
        interpret=interpret,
    )(a_hi2, a_lo2, b_hi2, b_lo2)
    return common[:br_in, :bc_in], total[:br_in, :bc_in]


def tile_intersect_pallas(
    rows: jax.Array,   # uint64 (Br, M) sorted asc, SENTINEL-padded
    cols: jax.Array,   # uint64 (Bc, M)
    interpret: bool = False,
) -> jax.Array:
    """|row ∩ col| int32 (Br, Bc) — the Mosaic twin of
    ops/pairwise.tile_intersect_counts for marker-containment
    screening (reference: src/skani.rs:54-70)."""
    common, _total = tile_stats_pallas(
        rows, cols, rows.shape[1], interpret=interpret, intersect=True)
    return common
