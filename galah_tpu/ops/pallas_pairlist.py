"""Pallas TPU kernel: merged-bottom-k stats for an explicit PAIR LIST.

The screened sparse pipeline (ops/sparse_device.py) evaluates only the
collision screen's survivors — gathered (a_p, b_p) sketch row pairs,
one result per pair, not a (rows x cols) tile. The XLA formulation is
a vmapped u64 searchsorted (gather-heavy and 64-bit-emulated — the
same costs that motivated ops/pallas_pairwise.py, which measured ~26x
over the XLA path on chip). This kernel recomputes the identical
integers from dense block compares on u32 hi/lo planes.

Design note (hardware-driven): the first cut of this kernel walked 64
pairs per grid program with `pl.ds(q, 1)` row loads; Mosaic rejects
that on real v5e hardware ("dynamic load with unaligned indices" —
dynamic sublane offsets must be 8-aligned). Both kernels here
therefore have NO dynamic indexing at all: the BlockSpec index maps
select each program's rows — block windowing is a DMA copy, which
takes arbitrary row offsets — and everything inside a program is a
STATIC slice.

Round-5 hardware data showed the one-pair-per-program grid paying its
full per-program fixed cost (grid bookkeeping + tiny DMA windows) per
pair: 62.8k pairs/s amortized, 7.8% of the derived VPU ceiling,
vs 27.3% for the dense tile whose programs pool 8 queries. The
BLOCKED kernel closes that gap by processing `block_pairs` (P,
default 8) pairs per program: the per-program fixed cost is amortized
P ways and the DMA windows are P× larger, so the pipeline's
double-buffered window loads (Pallas DMAs block g+1's a/b planes into
the alternate VMEM buffer while block g computes) run at useful
sizes. Layouts (P = 1 is exactly the round-5 one-pair kernel):

  * a side: (B*8, la) planes, la = K_pad/8 — pair p's value k = l*8+s
    at row p*8 + s, lane l (the dense kernel's query layout); block
    (P*8, la) at block-row g, pair p of the block at STATIC rows
    [p*8, p*8+8);
  * b side: (B*sb, 128) planes, sb = K_pad/128 — pair p's sorted row
    chunk s on row p*sb + s; block (P*sb, 128) at block-row g, chunk
    s of pair p at the block's STATIC row p*sb + s (K_pad is padded
    so P*sb satisfies the sublane-divisibility rule — a multiple of
    1024 = 8*128 only for P=1; P=8 needs just a multiple of 128);
  * out: (B*8, 128) int32, block (P*8, 128) at block-row g; pair p's
    (common, total) is broadcast across rows [p*8, p*8+8) and read
    back at (row p*8, lane 0). The pair axis is padded to a multiple
    of P with all-sentinel rows (their stats are (0, 0)) and trimmed
    on the host.

Per pair, static loops over a lanes x b chunks accumulate
#(b < a_i) and #(b == a_i) from (8, 1) x (1, 128) broadcast compares —
(8, 128) is one native vreg, so the VPU stays full. The union-rank
epilogue is the dense kernel's, on (8, la) planes. Bit-identical
integers to ops/pairwise._pair_stats (tests/test_pallas_pairlist.py;
hardware lowering of the P=1 kernel pinned by tests/test_tpu_hw.py;
the blocked lowering awaits the next healthy tunnel window via
scripts/bench_pairlist_variants.py).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from galah_tpu.obs.profile import profiled

from galah_tpu.ops.pallas_pairwise import (
    _inclusive_cumsum_axis0,
    _inclusive_cumsum_axis1,
    _split_planes,
    _ssum_i32,
    _zi,
)

A_SUB = 8
B_LANE = 128

# Pairs per grid program for the blocked kernel. 8 mirrors the dense
# tile's 8-query pooling (the 27.3%-of-ceiling configuration); the
# per-program fixed cost that dominated the one-pair grid is amortized
# across the block.
PAIRLIST_BLOCK_DEFAULT = 8

# Static kernel contract checked by `galah-tpu lint` (GL1xx):
# representative bindings at the default block (bp=8) and k_pad=1024,
# so la = k_pad/A_SUB and sb = k_pad/B_LANE.
PALLAS_CONTRACT = {
    "_pair_stats_pairs_jit": {
        "bindings": {"bp": 8, "la": 128, "sb": 8},
        "in_dtypes": ["uint32", "uint32", "uint32", "uint32"],
        "kernel_fns": ["_make_blocked_kernel", "_make_kernel",
                       "_pair_body"],
    },
}

# Numeric-determinism contract checked by `galah-tpu lint` (GL9xx):
# pair statistics are exact integer match counts — every pairlist
# strategy (blocked / gather / xla / cpu) must produce bit-identical
# (matches, lengths) for the same pairs, independent of strategy.
DETERMINISM_CONTRACT = {
    "family": "pairlist",
    "dtype": "int32",
    "functions": ["pair_stats_pairs_pallas", "_pair_stats_pairs_jit"],
}


def pairlist_block_pairs() -> int:
    """P for the blocked pairlist kernel (GALAH_TPU_PAIRLIST_BLOCK to
    tune; 1 selects the round-5 one-pair reference grid)."""
    return max(1, int(os.environ.get("GALAH_TPU_PAIRLIST_BLOCK",
                                     PAIRLIST_BLOCK_DEFAULT)))


def _pair_body(ah, al, bh_chunks, bl_chunks, la: int, sb: int,
               sketch_size: int, lo_only: bool = False):
    """One pair's merged-bottom-k stats from already-loaded planes.

    `ah`/`al` are the pair's (8, la) a-side hi/lo planes; `bh_chunks`/
    `bl_chunks` its sb (1, 128) b-side row chunks. Returns (common,
    total) int32 scalars — the integers of ops/pairwise._pair_stats.

    `lo_only` is a BENCH-ONLY knob (scripts/bench_pairlist_variants.py)
    that drops the hi-plane halves of the lt/eq compares to price the
    u64-emulation tax; its integers are WRONG for real sketches and no
    production path sets it."""
    umax = jnp.uint32(0xFFFFFFFF)
    valid_a = ~((ah == umax) & (al == umax))
    na = _ssum_i32(valid_a)

    nb = jnp.int32(0)
    for s in range(sb):
        nb = nb + _ssum_i32(
            ~((bh_chunks[s] == umax) & (bl_chunks[s] == umax)))

    lt_cols = []
    eq_cols = []
    for l in range(la):
        a_h = ah[:, l:l + 1]   # (8, 1)
        a_l = al[:, l:l + 1]
        ltacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
        eqacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
        for s in range(sb):
            bh = bh_chunks[s]
            bl = bl_chunks[s]
            if lo_only:
                eq = bl == a_l
                lt = bl < a_l
            else:
                eq = (bh == a_h) & (bl == a_l)
                lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
            eqacc = eqacc + eq.astype(jnp.int32)
            ltacc = ltacc + lt.astype(jnp.int32)
        lt_cols.append(jnp.sum(ltacc, axis=1, keepdims=True,
                               dtype=jnp.int32))
        eq_cols.append(jnp.sum(eqacc, axis=1, keepdims=True,
                               dtype=jnp.int32))
    ltv = jnp.concatenate(lt_cols, axis=1)   # (8, la)
    eqv = jnp.concatenate(eq_cols, axis=1)

    match = ((eqv > 0) & valid_a).astype(jnp.int32)
    n_common_all = _ssum_i32(match)
    n_union = na + nb - n_common_all
    total = jnp.minimum(jnp.int32(sketch_size), n_union)

    colsum = jnp.sum(match, axis=0, keepdims=True, dtype=jnp.int32)
    col_excl = _inclusive_cumsum_axis1(colsum) - colsum
    row_excl = _inclusive_cumsum_axis0(match) - match
    cexcl = col_excl + row_excl
    s_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 0)
    l_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 1)
    urank = l_idx * A_SUB + s_idx + ltv - cexcl
    common = _ssum_i32(match * (urank < total).astype(jnp.int32))
    return common, total


def _make_blocked_kernel(la: int, sb: int, sketch_size: int,
                         block_pairs: int, lo_only: bool = False):
    """Kernel for K_pad = 8*la = 128*sb; one program = `block_pairs`
    pairs, each at a STATIC row offset inside the (P*8, la) /
    (P*sb, 128) windows — no dynamic indexing, per the module's Mosaic
    design note. Pallas's pipeline double-buffers the windows across
    grid steps, so block g+1's hash-row DMAs overlap block g's
    compute."""

    def kernel(a_hi_ref, a_lo_ref, b_hi_ref, b_lo_ref,
               common_ref, total_ref):
        for p in range(block_pairs):
            r0 = p * A_SUB
            ah = a_hi_ref[r0:r0 + A_SUB, :]   # (8, la)
            al = a_lo_ref[r0:r0 + A_SUB, :]
            bh_chunks = [b_hi_ref[p * sb + s:p * sb + s + 1, :]
                         for s in range(sb)]
            bl_chunks = [b_lo_ref[p * sb + s:p * sb + s + 1, :]
                         for s in range(sb)]
            common, total = _pair_body(ah, al, bh_chunks, bl_chunks,
                                       la, sb, sketch_size,
                                       lo_only=lo_only)
            common_ref[r0:r0 + A_SUB, :] = jnp.broadcast_to(
                common, (A_SUB, B_LANE))
            total_ref[r0:r0 + A_SUB, :] = jnp.broadcast_to(
                total, (A_SUB, B_LANE))

    return kernel


def _make_kernel(la: int, sb: int, sketch_size: int,
                 range_skip: bool = False):
    """Kernel for K_pad = 8*la = 128*sb; one program = one pair.

    NON-PRODUCTION REFERENCE (hardware-retired). This is the round-5
    one-pair grid; production traffic now routes through
    `_make_blocked_kernel` (P=1 there reproduces this kernel's exact
    non-skip op sequence). It is kept solely as the home of the
    `range_skip` variant, which the 2026-08-01 amortized on-chip
    campaign measured 3.2x SLOWER than the plain compare loop
    (62.8k -> 19.5k pairs/s at B=8192;
    docs/artifacts/tpu_watch_20260801_0829/amortized.txt) — the
    data-dependent `pl.when` breaks Mosaic's pipelining on v5e. No
    default code path selects it; parity coverage lives behind the
    slow/hardware test gate.

    With `range_skip`, each lane column's 8 consecutive sorted a
    values carry tight scalar [min, max] bounds (ONE query per
    program, unlike the dense kernel's 8-query-pooled bounds), so b
    chunks wholly below contribute a scalar 128 to every lt count and
    chunks wholly above contribute nothing — only the 1-2 straddling
    chunks run vector compares, guarded by pl.when so the skipped
    work is actually skipped, not predicated."""

    def kernel(a_hi_ref, a_lo_ref, b_hi_ref, b_lo_ref,
               common_ref, total_ref, *scratch):
        umax = jnp.uint32(0xFFFFFFFF)

        ah = a_hi_ref[:, :]   # (8, la)
        al = a_lo_ref[:, :]
        valid_a = ~((ah == umax) & (al == umax))
        na = _ssum_i32(valid_a)

        # The pair's b row, materialized once as (1, 128) lane chunks:
        # chunk s is static row s of the (sb, 128) block.
        bh_chunks = [b_hi_ref[s:s + 1, :] for s in range(sb)]
        bl_chunks = [b_lo_ref[s:s + 1, :] for s in range(sb)]
        nb = jnp.int32(0)
        for s in range(sb):
            nb = nb + _ssum_i32(
                ~((bh_chunks[s] == umax) & (bl_chunks[s] == umax)))

        lt_cols = []
        eq_cols = []
        if range_skip:
            lt_scr, eq_scr = scratch
            b_first = [(bh_chunks[s][0, 0], bl_chunks[s][0, 0])
                       for s in range(sb)]
            b_last = [(bh_chunks[s][0, B_LANE - 1],
                       bl_chunks[s][0, B_LANE - 1]) for s in range(sb)]
        for l in range(la):
            a_h = ah[:, l:l + 1]   # (8, 1)
            a_l = al[:, l:l + 1]
            if range_skip:
                # Column l holds sorted values a[8l..8l+7]; a wholly-
                # below chunk can hold no sentinel (its max < a_min <=
                # UMAX) so it adds exactly B_LANE to every row's lt and
                # nothing to eq; a wholly-above chunk adds nothing to
                # either. (An all-padding column has a_min = UMAX, so
                # every valid chunk counts below — harmless: its rows
                # are masked out of `match` by valid_a.)
                amn_h, amn_l = ah[0, l], al[0, l]
                amx_h, amx_l = ah[A_SUB - 1, l], al[A_SUB - 1, l]
                lt_scr[:] = jnp.zeros((A_SUB, B_LANE), jnp.int32)
                eq_scr[:] = jnp.zeros((A_SUB, B_LANE), jnp.int32)
                n_below = jnp.int32(0)
                for s in range(sb):
                    fh, fl = b_first[s]
                    lh, ll = b_last[s]
                    below = (lh < amn_h) | ((lh == amn_h) & (ll < amn_l))
                    above = (fh > amx_h) | ((fh == amx_h) & (fl > amx_l))
                    n_below = n_below + below.astype(jnp.int32)

                    @pl.when(~(below | above))
                    def _(s=s, a_h=a_h, a_l=a_l):
                        bh = bh_chunks[s]
                        bl = bl_chunks[s]
                        eq = (bh == a_h) & (bl == a_l)
                        eq_scr[:] = eq_scr[:] + eq.astype(jnp.int32)
                        lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
                        lt_scr[:] = lt_scr[:] + lt.astype(jnp.int32)

                lt_cols.append(
                    jnp.sum(lt_scr[:], axis=1, keepdims=True,
                            dtype=jnp.int32)
                    + n_below * jnp.int32(B_LANE))
                eq_cols.append(jnp.sum(eq_scr[:], axis=1, keepdims=True,
                                       dtype=jnp.int32))
                continue
            ltacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
            eqacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
            for s in range(sb):
                bh = bh_chunks[s]
                bl = bl_chunks[s]
                eq = (bh == a_h) & (bl == a_l)
                eqacc = eqacc + eq.astype(jnp.int32)
                lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
                ltacc = ltacc + lt.astype(jnp.int32)
            lt_cols.append(jnp.sum(ltacc, axis=1, keepdims=True,
                                   dtype=jnp.int32))
            eq_cols.append(jnp.sum(eqacc, axis=1, keepdims=True,
                                   dtype=jnp.int32))
        ltv = jnp.concatenate(lt_cols, axis=1)   # (8, la)
        eqv = jnp.concatenate(eq_cols, axis=1)

        match = ((eqv > 0) & valid_a).astype(jnp.int32)
        n_common_all = _ssum_i32(match)
        n_union = na + nb - n_common_all
        total = jnp.minimum(jnp.int32(sketch_size), n_union)

        colsum = jnp.sum(match, axis=0, keepdims=True,
                         dtype=jnp.int32)
        col_excl = _inclusive_cumsum_axis1(colsum) - colsum
        row_excl = _inclusive_cumsum_axis0(match) - match
        cexcl = col_excl + row_excl
        s_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 0)
        l_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 1)
        urank = l_idx * A_SUB + s_idx + ltv - cexcl
        common = _ssum_i32(match * (urank < total).astype(jnp.int32))

        common_ref[:] = jnp.broadcast_to(common, (A_SUB, B_LANE))
        total_ref[:] = jnp.broadcast_to(total, (A_SUB, B_LANE))

    return kernel


def pair_stats_pairs_pallas(
    rows_a: jax.Array,   # uint64 (B, K) sorted asc, SENTINEL-padded
    rows_b: jax.Array,   # uint64 (B, K)
    sketch_size: int,
    interpret: bool = False,
    range_skip: bool = False,
    block_pairs: int = None,
    _lo_only: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 (B,) for each (rows_a[p], rows_b[p]) pair
    — the Mosaic twin of the vmapped ops/pairwise._pair_stats used by
    the screened sparse pipeline. Bit-identical integers for any
    block_pairs / range_skip setting (see _pair_body / _make_kernel).

    `block_pairs=None` takes GALAH_TPU_PAIRLIST_BLOCK (default 8):
    the blocked grid amortizes the per-program fixed cost that held
    the one-pair grid to 7.8% of the VPU ceiling. `block_pairs=1`
    without range_skip still goes through the blocked builder — same
    op sequence as the retired one-pair kernel.

    range_skip stays False by default — DECIDED from hardware:
    the 2026-08-01 amortized on-chip campaign measured the skip
    variant 3.2x SLOWER (62.8k -> 19.5k pairs/s at B=8192;
    docs/artifacts/tpu_watch_20260801_0829/amortized.txt) — the
    data-dependent `pl.when` breaks Mosaic's pipelining on v5e and
    costs more than the skipped compares save. It is a quarantined
    reference variant, only reachable by passing the flag, and forces
    the one-pair grid (the only kernel that implements it).

    `_lo_only` is bench-only (u64-emulation tax pricing) — WRONG
    integers for real sketches; see _pair_body."""
    if range_skip:
        block_pairs = 1
    elif block_pairs is None:
        block_pairs = pairlist_block_pairs()
    block_pairs = int(block_pairs)
    # Pad to the kernel's (pair, K) quanta OUT here, before the jit
    # boundary, so the cache keys on canonical padded shapes: every
    # ragged tail (b % P != 0) and sub-quantum width would otherwise
    # compile its own executable — one avoidable Mosaic compile per
    # ragged batch in production. The jit body's own padding is a
    # no-op on pre-padded inputs.
    b_in, k_in = rows_a.shape
    if b_in:
        sent = ~jnp.uint64(0)
        k_quantum = B_LANE * (A_SUB // math.gcd(block_pairs, A_SUB))
        k_pad = -(-k_in // k_quantum) * k_quantum
        if k_pad != k_in:
            fill = jnp.full((b_in, k_pad - k_in), sent, jnp.uint64)
            rows_a = jnp.concatenate([rows_a, fill], axis=1)
            rows_b = jnp.concatenate([rows_b, fill], axis=1)
        b_pad = -(-b_in // block_pairs) * block_pairs
        if b_pad != b_in:
            fill = jnp.full((b_pad - b_in, k_pad), sent, jnp.uint64)
            rows_a = jnp.concatenate([rows_a, fill], axis=0)
            rows_b = jnp.concatenate([rows_b, fill], axis=0)
    common, total = _pair_stats_pairs_jit(
        rows_a, rows_b, sketch_size=sketch_size,
        interpret=bool(interpret), range_skip=bool(range_skip),
        block_pairs=block_pairs, lo_only=bool(_lo_only))
    return common[:b_in], total[:b_in]


@profiled("pairlist.pair_stats_pairs")
@functools.partial(jax.jit,
                   static_argnames=("sketch_size", "interpret",
                                    "range_skip", "block_pairs",
                                    "lo_only"))
def _pair_stats_pairs_jit(
    rows_a: jax.Array,
    rows_b: jax.Array,
    sketch_size: int,
    interpret: bool,
    range_skip: bool,
    block_pairs: int,
    lo_only: bool,
) -> Tuple[jax.Array, jax.Array]:
    b_in, k_in = rows_a.shape
    if b_in == 0:
        z = jnp.zeros((0,), jnp.int32)
        return z, z
    sent = ~jnp.uint64(0)
    bp = block_pairs

    # K_pad must make the b-side (P*sb, 128) block satisfy Mosaic's
    # sublane-divisibility rule ((P*sb) % 8 == 0): a multiple of
    # 8*128/gcd(P, 8) — the full 1024 only for the P=1 grid, 128 at
    # the default P=8.
    k_quantum = B_LANE * (A_SUB // math.gcd(bp, A_SUB))
    k_pad = -(-k_in // k_quantum) * k_quantum
    if k_pad != k_in:
        fill = jnp.full((b_in, k_pad - k_in), sent, jnp.uint64)
        rows_a = jnp.concatenate([rows_a, fill], axis=1)
        rows_b = jnp.concatenate([rows_b, fill], axis=1)

    # Pair axis pads to a whole number of P-pair blocks; the sentinel
    # pairs cost one wasted program slot each (counted by the caller's
    # pairlist-blocked-pad counter) and compute to (0, 0).
    b_pad = -(-b_in // bp) * bp
    if b_pad != b_in:
        fill = jnp.full((b_pad - b_in, k_pad), sent, jnp.uint64)
        rows_a = jnp.concatenate([rows_a, fill], axis=0)
        rows_b = jnp.concatenate([rows_b, fill], axis=0)

    la = k_pad // A_SUB
    sb = k_pad // B_LANE

    a_hi, a_lo = _split_planes(rows_a)
    a_hi2 = a_hi.reshape(b_pad, la, A_SUB).transpose(0, 2, 1).reshape(
        b_pad * A_SUB, la)
    a_lo2 = a_lo.reshape(b_pad, la, A_SUB).transpose(0, 2, 1).reshape(
        b_pad * A_SUB, la)
    b_hi, b_lo = _split_planes(rows_b)
    b_hi2 = b_hi.reshape(b_pad * sb, B_LANE)
    b_lo2 = b_lo.reshape(b_pad * sb, B_LANE)

    if range_skip:
        kernel = _make_kernel(la, sb, sketch_size, range_skip=True)
    else:
        kernel = _make_blocked_kernel(la, sb, sketch_size, bp,
                                      lo_only=lo_only)
    common, total = pl.pallas_call(
        kernel,
        grid=(b_pad // bp,),
        in_specs=[
            pl.BlockSpec((bp * A_SUB, la), lambda g: (g, _zi(g)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp * A_SUB, la), lambda g: (g, _zi(g)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp * sb, B_LANE), lambda g: (g, _zi(g)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp * sb, B_LANE), lambda g: (g, _zi(g)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bp * A_SUB, B_LANE), lambda g: (g, _zi(g)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp * A_SUB, B_LANE), lambda g: (g, _zi(g)),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b_pad * A_SUB, B_LANE), jnp.int32),
            jax.ShapeDtypeStruct((b_pad * A_SUB, B_LANE), jnp.int32),
        ],
        scratch_shapes=(
            [pltpu.VMEM((A_SUB, B_LANE), jnp.int32),
             pltpu.VMEM((A_SUB, B_LANE), jnp.int32)]
            if range_skip else []),
        interpret=interpret,
    )(a_hi2, a_lo2, b_hi2, b_lo2)
    return (common.reshape(b_pad, A_SUB, B_LANE)[:b_in, 0, 0],
            total.reshape(b_pad, A_SUB, B_LANE)[:b_in, 0, 0])
