"""Pallas TPU kernel: merged-bottom-k stats for an explicit PAIR LIST.

The screened sparse pipeline (ops/sparse_device.py) evaluates only the
collision screen's survivors — gathered (a_p, b_p) sketch row pairs,
one result per pair, not a (rows x cols) tile. The XLA formulation is
a vmapped u64 searchsorted (gather-heavy and 64-bit-emulated — the
same costs that motivated ops/pallas_pairwise.py, which measured ~26x
over the XLA path on chip). This kernel recomputes the identical
integers from dense block compares on u32 hi/lo planes, per pair:

Layouts (legal under Mosaic's (8, 128) tiling; dynamic indexing on
sublanes only):

  * a side: (B*8, la) planes, la = K_pad/8 — pair p's value k = l*8+s
    at row p*8 + s, lane l (the dense kernel's query layout);
  * b side: (B, K_pad) planes — pair p's full sorted row on lanes
    (K_pad a multiple of 128);
  * out: (G*8, 128) int32 blocks, G = B_pad/PAIRS_PER_PROGRAM —
    program g writes pair q's (common, total) at row 8g, lane q via
    one-hot accumulation (no dynamic lane stores on TPU).

One grid program walks PAIRS_PER_PROGRAM pairs with a fori loop
(dynamic sublane slices select pair q's a group and b row); per pair,
static loops over a lanes x b chunks accumulate #(b < a_i) and
#(b == a_i) from (8, 1) x (1, 128) broadcast compares — (8, 128) is
one native vreg, so the VPU stays full. The union-rank epilogue is the
dense kernel's, on (8, la) planes. Bit-identical integers to
ops/pairwise._pair_stats (tests/test_pallas_pairlist.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from galah_tpu.ops.pallas_pairwise import (
    _inclusive_cumsum_axis0,
    _inclusive_cumsum_axis1,
    _split_planes,
    _ssum_i32,
    _zi,
)

A_SUB = 8
B_LANE = 128
PAIRS_PER_PROGRAM = 64


def _make_kernel(la: int, sb: int, sketch_size: int):
    """Kernel for K_pad = 8*la = 128*sb; one program = 64 pairs."""
    pp = PAIRS_PER_PROGRAM

    def kernel(a_hi_ref, a_lo_ref, b_hi_ref, b_lo_ref,
               common_ref, total_ref):
        umax = jnp.uint32(0xFFFFFFFF)
        lane = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, B_LANE), 1)
        subl = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, B_LANE), 0)

        def q_body(q, carry):
            crows, trows = carry    # (8, 128) accumulators, row 0 live
            ah = a_hi_ref[pl.ds(q * A_SUB, A_SUB), :]   # (8, la)
            al = a_lo_ref[pl.ds(q * A_SUB, A_SUB), :]
            valid_a = ~((ah == umax) & (al == umax))
            na = _ssum_i32(valid_a)

            nb = jnp.int32(0)
            lt_cols = []
            eq_cols = []
            for l in range(la):
                a_h = ah[:, l:l + 1]   # (8, 1)
                a_l = al[:, l:l + 1]
                ltacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
                eqacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
                for s in range(sb):
                    bh = b_hi_ref[pl.ds(q, 1),
                                  s * B_LANE:(s + 1) * B_LANE]  # (1,128)
                    bl = b_lo_ref[pl.ds(q, 1),
                                  s * B_LANE:(s + 1) * B_LANE]
                    if l == 0:
                        nb = nb + _ssum_i32(~((bh == umax) & (bl == umax)))
                    eq = (bh == a_h) & (bl == a_l)
                    eqacc = eqacc + eq.astype(jnp.int32)
                    lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
                    ltacc = ltacc + lt.astype(jnp.int32)
                lt_cols.append(jnp.sum(ltacc, axis=1, keepdims=True,
                                       dtype=jnp.int32))
                eq_cols.append(jnp.sum(eqacc, axis=1, keepdims=True,
                                       dtype=jnp.int32))
            ltv = jnp.concatenate(lt_cols, axis=1)   # (8, la)
            eqv = jnp.concatenate(eq_cols, axis=1)

            match = ((eqv > 0) & valid_a).astype(jnp.int32)
            n_common_all = _ssum_i32(match)
            n_union = na + nb - n_common_all
            total = jnp.minimum(jnp.int32(sketch_size), n_union)

            colsum = jnp.sum(match, axis=0, keepdims=True,
                             dtype=jnp.int32)
            col_excl = _inclusive_cumsum_axis1(colsum) - colsum
            row_excl = _inclusive_cumsum_axis0(match) - match
            cexcl = col_excl + row_excl
            s_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 0)
            l_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 1)
            urank = l_idx * A_SUB + s_idx + ltv - cexcl
            common = _ssum_i32(match * (urank < total).astype(jnp.int32))

            hot = ((lane == q) & (subl == 0)).astype(jnp.int32)
            return crows + hot * common, trows + hot * total

        crows, trows = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(pp), q_body,
            (jnp.zeros((A_SUB, B_LANE), jnp.int32),
             jnp.zeros((A_SUB, B_LANE), jnp.int32)))
        common_ref[:] = crows
        total_ref[:] = trows

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("sketch_size", "interpret"))
def pair_stats_pairs_pallas(
    rows_a: jax.Array,   # uint64 (B, K) sorted asc, SENTINEL-padded
    rows_b: jax.Array,   # uint64 (B, K)
    sketch_size: int,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 (B,) for each (rows_a[p], rows_b[p]) pair
    — the Mosaic twin of the vmapped ops/pairwise._pair_stats used by
    the screened sparse pipeline. Bit-identical integers."""
    b_in, k_in = rows_a.shape
    sent = ~jnp.uint64(0)

    k_pad = -(-k_in // B_LANE) * B_LANE
    if k_pad != k_in:
        fill = jnp.full((b_in, k_pad - k_in), sent, jnp.uint64)
        rows_a = jnp.concatenate([rows_a, fill], axis=1)
        rows_b = jnp.concatenate([rows_b, fill], axis=1)

    pp = PAIRS_PER_PROGRAM
    b_pad = max(pp, -(-b_in // pp) * pp)
    if b_pad != b_in:
        pad = jnp.full((b_pad - b_in, k_pad), sent, jnp.uint64)
        rows_a = jnp.concatenate([rows_a, pad], axis=0)
        rows_b = jnp.concatenate([rows_b, pad], axis=0)

    la = k_pad // A_SUB
    sb = k_pad // B_LANE

    a_hi, a_lo = _split_planes(rows_a)
    a_hi2 = a_hi.reshape(b_pad, la, A_SUB).transpose(0, 2, 1).reshape(
        b_pad * A_SUB, la)
    a_lo2 = a_lo.reshape(b_pad, la, A_SUB).transpose(0, 2, 1).reshape(
        b_pad * A_SUB, la)
    b_hi, b_lo = _split_planes(rows_b)

    grid = b_pad // pp
    common, total = pl.pallas_call(
        _make_kernel(la, sb, sketch_size),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((pp * A_SUB, la), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((pp * A_SUB, la), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((pp, k_pad), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((pp, k_pad), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((A_SUB, B_LANE), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((A_SUB, B_LANE), lambda i: (i, _zi(i)),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid * A_SUB, B_LANE), jnp.int32),
            jax.ShapeDtypeStruct((grid * A_SUB, B_LANE), jnp.int32),
        ],
        interpret=interpret,
    )(a_hi2, a_lo2, b_hi, b_lo)
    # program g's row 8g holds its 64 pairs on lanes 0..63
    common = common.reshape(grid, A_SUB, B_LANE)[:, 0, :pp].reshape(-1)
    total = total.reshape(grid, A_SUB, B_LANE)[:, 0, :pp].reshape(-1)
    return common[:b_in], total[:b_in]
