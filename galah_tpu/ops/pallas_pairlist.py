"""Pallas TPU kernel: merged-bottom-k stats for an explicit PAIR LIST.

The screened sparse pipeline (ops/sparse_device.py) evaluates only the
collision screen's survivors — gathered (a_p, b_p) sketch row pairs,
one result per pair, not a (rows x cols) tile. The XLA formulation is
a vmapped u64 searchsorted (gather-heavy and 64-bit-emulated — the
same costs that motivated ops/pallas_pairwise.py, which measured ~26x
over the XLA path on chip). This kernel recomputes the identical
integers from dense block compares on u32 hi/lo planes.

Design note (hardware-driven): the first cut of this kernel walked 64
pairs per grid program with `pl.ds(q, 1)` row loads; Mosaic rejects
that on real v5e hardware ("dynamic load with unaligned indices" —
dynamic sublane offsets must be 8-aligned). This version has NO
dynamic indexing at all: the grid is one program per pair and the
BlockSpec index maps select each pair's rows — block windowing is a
DMA copy, which takes arbitrary row offsets. Layouts:

  * a side: (B*8, la) planes, la = K_pad/8 — pair p's value k = l*8+s
    at row p*8 + s, lane l (the dense kernel's query layout); block
    (8, la) at block-row p;
  * b side: (B*sb, 128) planes, sb = K_pad/128 — pair p's sorted row
    chunk s on row p*sb + s; block (sb, 128) at block-row p, so chunk
    s is the block's STATIC row s (K_pad is padded to a multiple of
    1024 = 8*128 so sb satisfies the sublane-divisibility rule);
  * out: (B*8, 128) int32, block (8, 128) at block-row p; the pair's
    (common, total) is broadcast across the block and read back at
    (row 0, lane 0).

Per program, static loops over a lanes x b chunks accumulate
#(b < a_i) and #(b == a_i) from (8, 1) x (1, 128) broadcast compares —
(8, 128) is one native vreg, so the VPU stays full. The union-rank
epilogue is the dense kernel's, on (8, la) planes. Bit-identical
integers to ops/pairwise._pair_stats (tests/test_pallas_pairlist.py;
hardware lowering pinned by tests/test_tpu_hw.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from galah_tpu.ops.pallas_pairwise import (
    _inclusive_cumsum_axis0,
    _inclusive_cumsum_axis1,
    _split_planes,
    _ssum_i32,
    _zi,
)

A_SUB = 8
B_LANE = 128


def _make_kernel(la: int, sb: int, sketch_size: int,
                 range_skip: bool = False):
    """Kernel for K_pad = 8*la = 128*sb; one program = one pair.

    With `range_skip`, each lane column's 8 consecutive sorted a
    values carry tight scalar [min, max] bounds (ONE query per
    program, unlike the dense kernel's 8-query-pooled bounds), so b
    chunks wholly below contribute a scalar 128 to every lt count and
    chunks wholly above contribute nothing — only the 1-2 straddling
    chunks run vector compares, guarded by pl.when so the skipped
    work is actually skipped, not predicated."""

    def kernel(a_hi_ref, a_lo_ref, b_hi_ref, b_lo_ref,
               common_ref, total_ref, *scratch):
        umax = jnp.uint32(0xFFFFFFFF)

        ah = a_hi_ref[:, :]   # (8, la)
        al = a_lo_ref[:, :]
        valid_a = ~((ah == umax) & (al == umax))
        na = _ssum_i32(valid_a)

        # The pair's b row, materialized once as (1, 128) lane chunks:
        # chunk s is static row s of the (sb, 128) block.
        bh_chunks = [b_hi_ref[s:s + 1, :] for s in range(sb)]
        bl_chunks = [b_lo_ref[s:s + 1, :] for s in range(sb)]
        nb = jnp.int32(0)
        for s in range(sb):
            nb = nb + _ssum_i32(
                ~((bh_chunks[s] == umax) & (bl_chunks[s] == umax)))

        lt_cols = []
        eq_cols = []
        if range_skip:
            lt_scr, eq_scr = scratch
            b_first = [(bh_chunks[s][0, 0], bl_chunks[s][0, 0])
                       for s in range(sb)]
            b_last = [(bh_chunks[s][0, B_LANE - 1],
                       bl_chunks[s][0, B_LANE - 1]) for s in range(sb)]
        for l in range(la):
            a_h = ah[:, l:l + 1]   # (8, 1)
            a_l = al[:, l:l + 1]
            if range_skip:
                # Column l holds sorted values a[8l..8l+7]; a wholly-
                # below chunk can hold no sentinel (its max < a_min <=
                # UMAX) so it adds exactly B_LANE to every row's lt and
                # nothing to eq; a wholly-above chunk adds nothing to
                # either. (An all-padding column has a_min = UMAX, so
                # every valid chunk counts below — harmless: its rows
                # are masked out of `match` by valid_a.)
                amn_h, amn_l = ah[0, l], al[0, l]
                amx_h, amx_l = ah[A_SUB - 1, l], al[A_SUB - 1, l]
                lt_scr[:] = jnp.zeros((A_SUB, B_LANE), jnp.int32)
                eq_scr[:] = jnp.zeros((A_SUB, B_LANE), jnp.int32)
                n_below = jnp.int32(0)
                for s in range(sb):
                    fh, fl = b_first[s]
                    lh, ll = b_last[s]
                    below = (lh < amn_h) | ((lh == amn_h) & (ll < amn_l))
                    above = (fh > amx_h) | ((fh == amx_h) & (fl > amx_l))
                    n_below = n_below + below.astype(jnp.int32)

                    @pl.when(~(below | above))
                    def _(s=s, a_h=a_h, a_l=a_l):
                        bh = bh_chunks[s]
                        bl = bl_chunks[s]
                        eq = (bh == a_h) & (bl == a_l)
                        eq_scr[:] = eq_scr[:] + eq.astype(jnp.int32)
                        lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
                        lt_scr[:] = lt_scr[:] + lt.astype(jnp.int32)

                lt_cols.append(
                    jnp.sum(lt_scr[:], axis=1, keepdims=True,
                            dtype=jnp.int32)
                    + n_below * jnp.int32(B_LANE))
                eq_cols.append(jnp.sum(eq_scr[:], axis=1, keepdims=True,
                                       dtype=jnp.int32))
                continue
            ltacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
            eqacc = jnp.zeros((A_SUB, B_LANE), jnp.int32)
            for s in range(sb):
                bh = bh_chunks[s]
                bl = bl_chunks[s]
                eq = (bh == a_h) & (bl == a_l)
                eqacc = eqacc + eq.astype(jnp.int32)
                lt = (bh < a_h) | ((bh == a_h) & (bl < a_l))
                ltacc = ltacc + lt.astype(jnp.int32)
            lt_cols.append(jnp.sum(ltacc, axis=1, keepdims=True,
                                   dtype=jnp.int32))
            eq_cols.append(jnp.sum(eqacc, axis=1, keepdims=True,
                                   dtype=jnp.int32))
        ltv = jnp.concatenate(lt_cols, axis=1)   # (8, la)
        eqv = jnp.concatenate(eq_cols, axis=1)

        match = ((eqv > 0) & valid_a).astype(jnp.int32)
        n_common_all = _ssum_i32(match)
        n_union = na + nb - n_common_all
        total = jnp.minimum(jnp.int32(sketch_size), n_union)

        colsum = jnp.sum(match, axis=0, keepdims=True,
                         dtype=jnp.int32)
        col_excl = _inclusive_cumsum_axis1(colsum) - colsum
        row_excl = _inclusive_cumsum_axis0(match) - match
        cexcl = col_excl + row_excl
        s_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 0)
        l_idx = jax.lax.broadcasted_iota(jnp.int32, (A_SUB, la), 1)
        urank = l_idx * A_SUB + s_idx + ltv - cexcl
        common = _ssum_i32(match * (urank < total).astype(jnp.int32))

        common_ref[:] = jnp.broadcast_to(common, (A_SUB, B_LANE))
        total_ref[:] = jnp.broadcast_to(total, (A_SUB, B_LANE))

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("sketch_size", "interpret",
                                    "range_skip"))
def pair_stats_pairs_pallas(
    rows_a: jax.Array,   # uint64 (B, K) sorted asc, SENTINEL-padded
    rows_b: jax.Array,   # uint64 (B, K)
    sketch_size: int,
    interpret: bool = False,
    range_skip: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 (B,) for each (rows_a[p], rows_b[p]) pair
    — the Mosaic twin of the vmapped ops/pairwise._pair_stats used by
    the screened sparse pipeline. Bit-identical integers (either
    range_skip setting; see _make_kernel).

    range_skip stays False by default — DECIDED from hardware:
    the 2026-08-01 amortized on-chip campaign measured the skip
    variant 3.2x SLOWER (62.8k -> 19.5k pairs/s at B=8192;
    docs/artifacts/tpu_watch_20260801_0829/amortized.txt) — the
    data-dependent `pl.when` breaks Mosaic's pipelining on v5e and
    costs more than the skipped compares save."""
    b_in, k_in = rows_a.shape
    if b_in == 0:
        z = jnp.zeros((0,), jnp.int32)
        return z, z
    sent = ~jnp.uint64(0)

    # K_pad must be a multiple of 8*128 so the b-side (sb, 128) block
    # satisfies Mosaic's sublane-divisibility rule (sb % 8 == 0).
    k_pad = -(-k_in // (A_SUB * B_LANE)) * (A_SUB * B_LANE)
    if k_pad != k_in:
        fill = jnp.full((b_in, k_pad - k_in), sent, jnp.uint64)
        rows_a = jnp.concatenate([rows_a, fill], axis=1)
        rows_b = jnp.concatenate([rows_b, fill], axis=1)

    la = k_pad // A_SUB
    sb = k_pad // B_LANE

    a_hi, a_lo = _split_planes(rows_a)
    a_hi2 = a_hi.reshape(b_in, la, A_SUB).transpose(0, 2, 1).reshape(
        b_in * A_SUB, la)
    a_lo2 = a_lo.reshape(b_in, la, A_SUB).transpose(0, 2, 1).reshape(
        b_in * A_SUB, la)
    b_hi, b_lo = _split_planes(rows_b)
    b_hi2 = b_hi.reshape(b_in * sb, B_LANE)
    b_lo2 = b_lo.reshape(b_in * sb, B_LANE)

    common, total = pl.pallas_call(
        _make_kernel(la, sb, sketch_size, range_skip=bool(range_skip)),
        grid=(b_in,),
        in_specs=[
            pl.BlockSpec((A_SUB, la), lambda p: (p, _zi(p)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((A_SUB, la), lambda p: (p, _zi(p)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((sb, B_LANE), lambda p: (p, _zi(p)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((sb, B_LANE), lambda p: (p, _zi(p)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((A_SUB, B_LANE), lambda p: (p, _zi(p)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((A_SUB, B_LANE), lambda p: (p, _zi(p)),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b_in * A_SUB, B_LANE), jnp.int32),
            jax.ShapeDtypeStruct((b_in * A_SUB, B_LANE), jnp.int32),
        ],
        scratch_shapes=(
            [pltpu.VMEM((A_SUB, B_LANE), jnp.int32),
             pltpu.VMEM((A_SUB, B_LANE), jnp.int32)]
            if range_skip else []),
        interpret=interpret,
    )(a_hi2, a_lo2, b_hi2, b_lo2)
    return (common.reshape(b_in, A_SUB, B_LANE)[:, 0, 0],
            total.reshape(b_in, A_SUB, B_LANE)[:, 0, 0])
