"""Device HyperLogLog: sketching, cardinality, tiled pairwise union/ANI.

The framework's dashing analog. The reference shells out to the dashing
C++ binary, which HLL-sketches every genome and emits a full N x N
Mash-like distance matrix (reference: src/dashing.rs:33-100). Here the
whole pipeline is on-device JAX:

  * sketching: each canonical k-mer hash h (the same murmur3 pipeline the
    MinHash backend uses) updates register h >> (64-p) with
    rho = clz(h << p) + 1 via a scatter-max — chunked like the MinHash
    sketcher, so any genome length compiles to the same kernels;
  * cardinality: the classic HLL estimator (alpha_m * m^2 / sum 2^-reg)
    with the small-range linear-counting correction;
  * pairwise: |A u B| from the register-wise max of two sketches, Jaccard
    by inclusion-exclusion, then Mash distance d = -ln(2j/(1+j))/k and
    ANI = 1 - d, computed for (row_tile x col_tile) blocks per device
    dispatch.

Unlike dashing's matrix-on-stdout, tiles are thresholded on device and
only surviving sparse pairs reach the host. Exact dashing value parity is
not a goal (different hash; dashing itself is an estimator whose values
differ from finch/skani); cluster-level parity is covered by tests.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from galah_tpu.io.fasta import Genome
from galah_tpu.ops import hashing
from galah_tpu.utils import timing

DEFAULT_P = 12  # 4096 registers: ~1.6% cardinality std error, 4 KiB/genome


def _alpha(m: int) -> float:
    if m >= 128:
        return 0.7213 / (1.0 + 1.079 / m)
    if m == 64:
        return 0.709
    if m == 32:
        return 0.697
    return 0.673


@functools.partial(jax.jit, static_argnames=("p",))
def _hll_update(regs: jax.Array, hashes: jax.Array, p: int) -> jax.Array:
    """Fold a chunk of uint64 hashes into (2^p,) uint8 registers."""
    idx = (hashes >> jnp.uint64(64 - p)).astype(jnp.int32)
    rest = hashes << jnp.uint64(p)
    rho = jnp.minimum(jax.lax.clz(rest) + jnp.uint64(1),
                      jnp.uint64(64 - p + 1)).astype(jnp.uint8)
    # Invalid positions carry HASH_SENTINEL (all ones): rho == 1 there,
    # but their register index is m-1; mask them to rho 0 (a no-op for
    # max) instead.
    rho = jnp.where(hashes == hashing.HASH_SENTINEL, jnp.uint8(0), rho)
    return regs.at[idx].max(rho)


def hll_sketch_genome(
    genome: Genome,
    p: int = DEFAULT_P,
    k: int = 21,
    seed: int = 0,
    chunk: int = hashing.DEFAULT_CHUNK,
    algo: str = "murmur3",
) -> np.ndarray:
    """(2^p,) uint8 HLL registers over the genome's canonical k-mers.

    On a single-device CPU backend the compiled-C walker runs instead
    (csrc/sketch.c::galah_hll_registers, bit-identical); an explicit
    non-default chunk pins the JAX path. The device_count() == 1
    condition matches every other native-path gate (the op is
    per-genome so results would be identical either way; one rule for
    all gates keeps the policy auditable)."""
    if (jax.default_backend() == "cpu" and jax.device_count() == 1
            and k <= 32 and 1 <= p <= 24
            and chunk == hashing.DEFAULT_CHUNK):
        try:
            from galah_tpu.ops import _csketch

            return _csketch.hll_registers(
                genome.codes, genome.contig_offsets, k=k, p=p,
                seed=seed, algo=algo)
        except ImportError:
            pass  # no C toolchain: fall through to the JAX path
    regs = jnp.zeros((1 << p,), dtype=jnp.uint8)
    for hashes, _pos, _n_new in hashing.iter_chunk_hashes(
            genome.codes, genome.contig_offsets, k=k, chunk=chunk,
            seed=seed, algo=algo):
        regs = _hll_update(regs, hashes, p)
        timing.dispatch()
    timing.dispatch(sync=True)
    return np.asarray(regs)


@functools.partial(jax.jit, static_argnames=("p", "k", "seed", "algo"))
def _batch_hll_kernel(packed, ambits, offsets, p, k, seed, algo):
    """(G, C/4) packed genome rows -> (G, 2^p) uint8 HLL registers in one
    dispatch (vmapped hash + per-row register fold)."""
    h = hashing.canonical_kmer_hashes_batch(
        packed, ambits, offsets, k, seed, algo)
    return jax.vmap(
        lambda hrow: _hll_update(jnp.zeros((1 << p,), jnp.uint8),
                                 hrow, p))(h)


def hll_sketch_genomes_batch(
    genomes,
    p: int = DEFAULT_P,
    k: int = 21,
    seed: int = 0,
    algo: str = "murmur3",
    budget: int = hashing.BATCH_BUDGET,
) -> list:
    """Batch twin of hll_sketch_genome: grouped one-dispatch sketching
    of many genomes (see ops/minhash.sketch_genomes_device_batch for the
    rationale), bit-identical registers per genome."""
    out = [None] * len(genomes)
    skipped, group_iter = hashing.iter_genome_groups(
        genomes, budget=budget, max_len=hashing.DEFAULT_CHUNK)
    for i in skipped:
        out[i] = hll_sketch_genome(genomes[i], p=p, k=k, seed=seed,
                                   algo=algo)
    for chunk_idxs, packed, ambits, offs in group_iter:
        timing.dispatch()
        timing.dispatch(sync=True)
        regs = np.asarray(_batch_hll_kernel(
            jnp.asarray(packed), jnp.asarray(ambits), jnp.asarray(offs),
            p=p, k=k, seed=seed, algo=algo))
        for row, gi in enumerate(chunk_idxs):
            out[gi] = regs[row]
    return out


def _estimate(regs_f32_powsum: jax.Array, zeros: jax.Array,
              m: int) -> jax.Array:
    """HLL estimate from sum(2^-reg) and zero-register count (f32)."""
    raw = jnp.float32(_alpha(m) * m * m) / regs_f32_powsum
    small = raw <= jnp.float32(2.5 * m)
    lc = jnp.float32(m) * jnp.log(
        jnp.float32(m) / jnp.maximum(zeros, jnp.float32(1.0)))
    return jnp.where(small & (zeros > 0), lc, raw)


@jax.jit
def hll_cardinality(regs: jax.Array) -> jax.Array:
    """Cardinality estimate(s): (..., m) uint8 registers -> (...) f32."""
    m = regs.shape[-1]
    pow2 = jnp.exp2(-regs.astype(jnp.float32))
    powsum = jnp.sum(pow2, axis=-1)
    zeros = jnp.sum((regs == 0).astype(jnp.float32), axis=-1)
    return _estimate(powsum, zeros, m)


@functools.partial(jax.jit, static_argnames=("k", "m"))
def _ani_from_union_stats(
    powsum: jax.Array,     # f32 (Br, Bc) sum of 2^-union_reg
    zeros: jax.Array,      # f32 (Br, Bc) count of zero union registers
    row_cards: jax.Array,  # f32 (Br,) precomputed cardinalities
    col_cards: jax.Array,  # f32 (Bc,)
    k: int,
    m: int,
) -> jax.Array:
    u = _estimate(powsum, zeros, m)                  # (Br, Bc)
    inter = row_cards[:, None] + col_cards[None, :] - u
    j = jnp.clip(inter / jnp.maximum(u, jnp.float32(1.0)), 0.0, 1.0)
    ani = 1.0 + jnp.log(2.0 * j / (1.0 + j)) / jnp.float32(k)
    return jnp.where(j > 0, ani, jnp.float32(0.0))


@jax.jit
def _xla_union_stats(rows_pow2: jax.Array,
                     cols_pow2: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """XLA fallback for pallas_hll.hll_union_stats_tile (same contract)."""
    mn = jnp.minimum(rows_pow2[:, None, :], cols_pow2[None, :, :])
    return mn.sum(-1), (mn == 1.0).astype(jnp.float32).sum(-1)


def use_pallas_default() -> bool:
    """Pallas kernels are the default path on a real TPU backend."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend probing never raises
        return False


def tile_hll_ani(
    rows: jax.Array,       # uint8 (Br, m) registers
    cols: jax.Array,       # uint8 (Bc, m)
    row_cards: jax.Array,  # f32 (Br,) precomputed cardinalities
    col_cards: jax.Array,  # f32 (Bc,)
    k: int,
) -> jax.Array:
    """Mash-style ANI for every (row, col) pair -> (Br, Bc) f32.

    Union registers are the elementwise max (the HLL merge) — computed as
    the elementwise MIN of 2^-reg (monotonicity), so the union pass is
    pure min+add with exp2 hoisted out; Jaccard by inclusion-exclusion,
    clamped to [0, 1]; ANI = 1 + ln(2j/(1+j))/k, 0 where the estimated
    intersection is empty.
    """
    rows_pow2 = jnp.exp2(-rows.astype(jnp.float32))
    cols_pow2 = jnp.exp2(-cols.astype(jnp.float32))
    powsum, zeros = _xla_union_stats(rows_pow2, cols_pow2)
    return _ani_from_union_stats(powsum, zeros, row_cards, col_cards,
                                 k, rows.shape[-1])


@functools.partial(
    jax.jit,
    static_argnames=("k", "row_tile", "col_tile", "use_pallas", "cap"))
def _hll_rowblock(pow2, cards, r0, min_ani, n, *, k, row_tile, col_tile,
                  use_pallas, cap):
    """One dispatch: a row block's full ANI stripe, thresholded and
    compacted on device (same blocked-dispatch pattern as
    ops/pairwise.threshold_pairs). Module-level so the jit cache is
    shared across calls (keyed on shapes + the static tiling knobs, not
    on a per-call closure identity)."""
    m = pow2.shape[1]
    n_pad = pow2.shape[0]
    n_ct = n_pad // col_tile

    if use_pallas:
        from galah_tpu.ops.pallas_hll import hll_union_stats_tile

        def union_stats(rows, cols):
            return hll_union_stats_tile(rows, cols, chunk=min(1024, m))
    else:
        union_stats = _xla_union_stats

    rows = jax.lax.dynamic_slice_in_dim(pow2, r0, row_tile, axis=0)
    rcards = jax.lax.dynamic_slice_in_dim(cards, r0, row_tile, axis=0)
    t_first = r0 // col_tile

    def one_tile(t):
        def compute(_):
            cols = jax.lax.dynamic_slice_in_dim(
                pow2, t * col_tile, col_tile, axis=0)
            ccards = jax.lax.dynamic_slice_in_dim(
                cards, t * col_tile, col_tile, axis=0)
            powsum, zeros = union_stats(rows, cols)
            return _ani_from_union_stats(
                powsum, zeros, rcards, ccards, k, m)

        def skip(_):
            return jnp.zeros((row_tile, col_tile), jnp.float32)

        return jax.lax.cond(t >= t_first, compute, skip, None)

    ani = jax.lax.map(one_tile, jnp.arange(n_ct))
    ani = jnp.transpose(ani, (1, 0, 2)).reshape(row_tile, n_pad)
    gi = r0 + jnp.arange(row_tile)[:, None]
    gj = jnp.arange(n_pad)[None, :]
    mask = (ani >= min_ani) & (gi < gj) & (gj < n)
    count = jnp.sum(mask.astype(jnp.int32))
    (flat_idx,) = jnp.nonzero(mask.ravel(), size=cap, fill_value=-1)
    vals = jnp.take(ani.ravel(), jnp.maximum(flat_idx, 0))
    return flat_idx, vals, count


def hll_threshold_pairs(
    regs_mat: np.ndarray,
    k: int,
    min_ani: float,
    row_tile: int = 64,
    col_tile: int = 256,
    use_pallas: bool | None = None,
    cap_per_row: int = 64,
    mesh=None,
) -> dict[Tuple[int, int], float]:
    """Sparse {(i, j): ani} over i<j HLL pairs with ani >= min_ani.

    Host-orchestrated upper-triangle tiling; each tile is one device
    dispatch (union stats + estimate + threshold) and only surviving
    entries come back. The device-side analog of parsing dashing's full
    TSV matrix (reference: src/dashing.rs:76-100). The 2^-reg transform
    is applied ONCE to the whole matrix; each tile is then a pure
    min+add reduction — the Pallas kernel (ops/pallas_hll.py) on TPU,
    an XLA broadcast-min elsewhere.
    """
    # Auto-dispatch to the sharded SPMD implementation only when the
    # caller left BOTH knobs unset: an explicit use_pallas (or an
    # explicit mesh) pins the single-device implementation so kernel
    # parity tests and single-chip callers get what they asked for.
    if mesh is None and use_pallas is None and jax.device_count() > 1:
        from galah_tpu.parallel.mesh import auto_mesh

        mesh = auto_mesh()
    if mesh is not None and mesh.devices.size > 1:
        # Multi-device runtime: the column-sharded SPMD extraction
        # (parallel/mesh.py) covers the mesh with one dispatch per row
        # block.
        from galah_tpu.parallel.mesh import sharded_hll_threshold_pairs

        return sharded_hll_threshold_pairs(
            regs_mat, k=k, min_ani=min_ani, mesh=mesh,
            row_tile=row_tile, col_tile=col_tile,
            cap_per_row=cap_per_row)

    # Fall back to XLA on Mosaic failure ONLY when pallas was chosen by
    # default: an explicit use_pallas=True pins the kernel so parity
    # tests fail loudly instead of vacuously comparing XLA to XLA.
    explicit = use_pallas is not None
    if use_pallas is None:
        use_pallas = use_pallas_default()
    from galah_tpu.ops._fallback import run_with_pallas_fallback

    # The Mosaic kernel is compiled/validated at the 128x128 output
    # tile geometry (square tiles keep the out block at the native
    # (8,128)-register multiple); other shapes have hit remote-compile
    # hangs on v5e.
    result, _ = run_with_pallas_fallback(
        "HLL kernel", explicit, bool(use_pallas),
        lambda p: _hll_threshold_single(
            regs_mat, k, min_ani, 128 if p else row_tile,
            128 if p else col_tile, p, cap_per_row),
        fallback_label="the XLA union-stats path")
    return result


def _hll_threshold_single(
    regs_mat: np.ndarray,
    k: int,
    min_ani: float,
    row_tile: int,
    col_tile: int,
    use_pallas: bool,
    cap_per_row: int,
) -> dict[Tuple[int, int], float]:
    import math

    n, m = regs_mat.shape
    quantum = math.lcm(row_tile, col_tile)
    n_pad = -(-n // quantum) * quantum
    mat = np.zeros((n_pad, m), dtype=np.uint8)
    mat[:n] = regs_mat
    jmat = jnp.asarray(mat)
    cards = hll_cardinality(jmat)
    pow2 = jnp.exp2(-jmat.astype(jnp.float32))

    from galah_tpu.ops.compact import iter_blocks

    def run_block(r0, cap):
        timing.dispatch()
        return _hll_rowblock(
            pow2, cards, jnp.int32(r0), jnp.float32(min_ani),
            jnp.int32(n), k=k, row_tile=row_tile, col_tile=col_tile,
            use_pallas=use_pallas, cap=cap)

    out: dict[Tuple[int, int], float] = {}
    for r0, (flat_idx, vals, count) in iter_blocks(
            n, row_tile, cap_per_row, run_block):
        timing.dispatch(sync=True)
        count = int(count)
        flat_idx = np.asarray(flat_idx)[:count]
        vals = np.asarray(vals)[:count]
        gi = r0 + flat_idx // n_pad
        gj = flat_idx % n_pad
        for a, b, v in zip(gi.tolist(), gj.tolist(), vals.tolist()):
            out[(int(a), int(b))] = float(v)
    return out
