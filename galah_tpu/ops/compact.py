"""Shared host-side loop for blocked sparse extraction.

The three sparse pair-extraction paths (ops/pairwise.threshold_pairs,
ops/hll.hll_threshold_pairs, parallel/mesh.sharded_threshold_pairs) all
follow the same shape: one device dispatch per row block returns
capacity-bounded compacted candidates plus the true passing count; the
host retries a block whose candidates overflowed. This module owns that
retry loop so capacity policy lives in exactly one place.

Capacities are always rounded up to a power of two: `cap` is a jit
static argument, so arbitrary per-block capacities would recompile the
whole stripe program per block on dense workloads — power-of-two
rounding bounds distinct compilations to O(log n).
"""

from __future__ import annotations

from typing import Callable, Iterator, Tuple


def _pow2_at_least(x: int) -> int:
    p = 1
    while p < x:
        p <<= 1
    return p


def iter_blocks(
    n: int,
    row_tile: int,
    cap_per_row: int,
    run_block: Callable[[int, int], Tuple],
) -> Iterator[Tuple[int, Tuple]]:
    """Yield (r0, device_result) per row block, retrying on overflow.

    `run_block(r0, cap)` must return a tuple whose LAST element is the
    true passing count (scalar or per-device array); a max() over it
    exceeding `cap` triggers a retry with the next power-of-two
    capacity.
    """
    import numpy as np

    for r0 in range(0, n, row_tile):
        cap = _pow2_at_least(cap_per_row * row_tile)
        while True:
            result = run_block(r0, cap)
            count = int(np.max(np.asarray(result[-1])))
            if count <= cap:
                break
            cap = _pow2_at_least(max(2 * cap, count))
        yield r0, result
