"""Fragment-containment exact ANI — the TPU-native ANI refinement kernel.

This is the framework's re-design of the reference's two exact-ANI
backends, built on one primitive that maps well to the hardware instead of
their irregular algorithms:

  * fastANI (reference: src/fastani.rs:31-150) decomposes the query into
    3 kb fragments, maps each against the reference with Mashmap, and
    averages per-fragment identity over mapped fragments, gating on the
    mapped-fragment fraction.
  * skani (reference: src/skani.rs:125-177) chains FracMinHash seed
    matches into syntenic runs and reports identity over aligned regions
    plus an aligned fraction.

Both separate "how much of the genome aligns" (aligned fraction) from
"identity within aligned regions" (ANI). The TPU-native equivalent here:

  1. the query is cut into fixed-length windows (fragments); every
     canonical k-mer hash in a window is tested for membership in the
     reference's full distinct k-mer set (one big `searchsorted` — a
     regular, batchable gather instead of chaining/mapping);
  2. a window with matched-kmer fraction c_w above a floor counts as
     aligned; its identity estimate is c_w^(1/k) (the standard k-mer
     survival model: a fraction ANI^k of k-mers survives substitutions);
  3. ANI = mean identity over aligned windows; aligned fraction =
     aligned windows / total windows. Both directions are computed and
     combined by the caller's gate semantics.

Static shapes via bucketing: reference sets pad to the next power of two,
window counts to multiples of 64, so XLA compiles a handful of kernel
variants for any genome collection.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from galah_tpu.io.fasta import Genome
from galah_tpu.ops import hashing
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.utils import timing

MARKER_C = 1000  # FracMinHash compression for screening markers
                 # (reference: src/skani.rs:158 "let m = 1000")

# Numeric-determinism contract checked by `galah-tpu lint` (GL9xx):
# directed/bidirectional ANI must be bit-identical across the single,
# batch, and distributed paths. Weighted fragment coverage accumulates
# in float64 THROUGH COMPRESSED SEGMENTS (_seq_sum over c_w[mask],
# _segment_compressed_sums over concatenated survivors) — summing a
# zero-filled np.where instead drifts a ulp (the PR 5 regression).
DETERMINISM_CONTRACT = {
    "family": "fragment",
    "dtype": "float64",
    "functions": ["directed_ani", "directed_ani_batch",
                  "bidirectional_ani", "bidirectional_ani_batch",
                  "bidirectional_ani_values",
                  "_directed_from_counts",
                  "_directed_from_counts_arrays",
                  "_seq_sum", "_segment_compressed_sums"],
}


@dataclasses.dataclass
class GenomeProfile:
    """Device-facing k-mer views of one genome for exact ANI."""

    path: str
    k: int
    fraglen: int
    flat_hashes: np.ndarray   # uint64 (n-k+1,), positional, SENTINEL-masked
    ref_set: np.ndarray       # uint64 sorted distinct hashes
    markers: np.ndarray       # uint64 sorted, hashes < 2^64 / MARKER_C
    #: FracMinHash compression: only k-mers with hash < 2^64/c
    #: participate in window counting and the reference set (the
    #: reference's skani uses c=125 the same way, src/skani.rs:159-161);
    #: c=1 keeps every k-mer (dense, exact)
    subsample_c: int = 1

    # lazily cached device-resident padded views (upload once per genome)
    _dev_windows: Optional[jax.Array] = None
    _dev_ref_set: Optional[jax.Array] = None
    # ... and their padded host twins (computed once, reused by both the
    # single-device upload and the batch-sharding assembly path)
    _np_windows_padded: Optional[np.ndarray] = None
    _np_ref_padded: Optional[np.ndarray] = None
    # unpadded windows, cached for the C membership fast path
    _np_windows: Optional[np.ndarray] = None
    # (sorted hashes, their window ids, per-window totals) — cached for
    # the C merge membership fast path; totals are pair-independent
    _np_sorted_query: "Optional[tuple]" = None
    # kept (hash, position) pairs from the C profile walk: lets
    # windows() assemble compacted rows in O(n_valid) instead of two
    # streaming passes over the 8-byte-per-bp flat array
    _kept_hashes: Optional[np.ndarray] = None
    _kept_pos: Optional[np.ndarray] = None

    @property
    def n_windows(self) -> int:
        return -(-self.flat_hashes.shape[0] // self.fraglen)

    def sorted_query(self) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
        """(qh, qw, totals): the profile's surviving window hashes
        sorted ascending, their window row ids, and each window's
        valid-hash count. Built once from windows() and cached — the
        merge membership path (csrc/pairstats.c::
        galah_window_match_counts_merge) consumes it per pair."""
        if self._np_sorted_query is None:
            wins = self.windows()
            mask = wins != np.uint64(SENTINEL)
            totals = mask.sum(axis=1, dtype=np.int32)
            rows, _cols = np.nonzero(mask)
            qh = wins[mask]
            order = np.argsort(qh)
            self._np_sorted_query = (
                qh[order], rows[order].astype(np.int32), totals)
        return self._np_sorted_query

    def padded_windows(self) -> np.ndarray:
        if self._np_windows_padded is None:
            self._np_windows_padded = pad_windows(self.windows())
        return self._np_windows_padded

    def padded_ref_set(self) -> np.ndarray:
        if self._np_ref_padded is None:
            self._np_ref_padded = pad_ref_set(self.ref_set)
        return self._np_ref_padded

    def device_windows(self) -> jax.Array:
        if self._dev_windows is None:
            self._dev_windows = jnp.asarray(self.padded_windows())
        return self._dev_windows

    def device_ref_set(self) -> jax.Array:
        if self._dev_ref_set is None:
            self._dev_ref_set = jnp.asarray(self.padded_ref_set())
        return self._dev_ref_set

    def windows(self) -> np.ndarray:
        """(W, slots) positional hash windows; k-mers crossing a window
        boundary are masked so each fragment is self-contained, matching
        fastANI's disjoint 3 kb fragments.

        With subsample_c > 1 the surviving (non-SENTINEL) hashes are
        compacted to the front of each row and the row width shrinks to
        the longest window's count (padded to a multiple of 64) — the
        per-window (matched, total) integers are unchanged (counting is
        SENTINEL-aware and order-independent), but the membership-test
        work really does drop ~c-fold.

        Cached after the first call (the greedy loop re-queries the
        same profile across many batches).
        """
        if self._np_windows is not None:
            return self._np_windows
        L = self.fraglen
        flat = self.flat_hashes
        w = self.n_windows
        if self.subsample_c > 1:
            # Compacted layout from the profile walk's kept (pos, hash)
            # pairs when available — O(n_valid) assembly; else two
            # streaming C passes over flat — both bit-identical to the
            # numpy stable-argsort twin below (tests/test_cpairstats.py),
            # which costs ~150 ms per 3 Mbp genome and was the
            # realistic-rung exact-ANI wall. Host-side on any backend.
            try:
                from galah_tpu.ops import _cpairstats

                if self._kept_pos is not None:
                    self._np_windows = _cpairstats.windows_from_pairs(
                        self._kept_pos, self._kept_hashes, w, L,
                        self.k)
                    # consumed exactly once; the result is cached
                    self._kept_pos = None
                    self._kept_hashes = None
                else:
                    self._np_windows = _cpairstats.compact_windows(
                        flat, w, L, self.k)
                return self._np_windows
            except ImportError:
                pass
        pad = np.full(w * L, np.uint64(SENTINEL), dtype=np.uint64)
        pad[: flat.shape[0]] = flat
        wins = pad.reshape(w, L).copy()
        wins[:, L - (self.k - 1):] = np.uint64(SENTINEL)
        if self.subsample_c > 1:
            # stable argsort of the sentinel mask moves surviving
            # hashes to the front of each row, preserving their order
            order = np.argsort(wins == np.uint64(SENTINEL), axis=1,
                               kind="stable")
            wins = np.take_along_axis(wins, order, axis=1)
            counts = (wins != np.uint64(SENTINEL)).sum(axis=1)
            slots = max(int(counts.max()) if counts.size else 1, 1)
            slots = -(-slots // 64) * 64
            wins = wins[:, :slots].copy()
        self._np_windows = wins
        return wins


# Half the generic hashing.BATCH_BUDGET: profile batches download the
# FULL positional hash rows (8 bytes/position) to host, unlike the
# sketch paths that reduce on device first, so the per-dispatch host
# array is kept to ~128 MB.
PROFILE_BATCH_BUDGET = hashing.BATCH_BUDGET // 2


def positional_hashes(genome: Genome, k: int,
                      chunk: int = hashing.DEFAULT_CHUNK,
                      algo: str = "murmur3") -> np.ndarray:
    """All canonical k-mer hashes of a genome in genome order (device).

    On a single-process CPU backend the compiled-C walker
    (csrc/sketch.c::galah_positional_hashes) runs instead —
    bit-identical, and an order of magnitude faster than the XLA-CPU
    chunk pipeline on one core. An explicit non-default chunk pins the
    JAX path (parity tests drive it that way)."""
    n = genome.codes.shape[0]
    if n < k:
        return np.zeros(0, dtype=np.uint64)
    if (jax.default_backend() == "cpu" and k <= 32
            and chunk == hashing.DEFAULT_CHUNK):
        try:
            from galah_tpu.ops import _csketch

            return _csketch.positional_hashes(
                genome.codes, genome.contig_offsets, k=k, algo=algo)
        except ImportError:
            pass  # no C toolchain: fall through to the JAX path
    out = np.empty(n - k + 1, dtype=np.uint64)
    for h, pos, n_new in hashing.iter_chunk_hashes(
            genome.codes, genome.contig_offsets, k=k, chunk=chunk,
            algo=algo):
        timing.dispatch()
        timing.dispatch(sync=True)
        out[pos: pos + n_new] = np.asarray(h)[:n_new]
    return out


def positional_hashes_batch(genomes, k: int,
                            budget: int = PROFILE_BATCH_BUDGET,
                            algo: str = "murmur3") -> list:
    """Batch twin of positional_hashes: grouped one-dispatch hashing of
    many genomes (same grouping as ops/minhash batch sketching), each
    entry bit-identical to positional_hashes(genome, k)."""
    out = [None] * len(genomes)
    skipped, group_iter = hashing.iter_genome_groups(
        genomes, budget=budget, max_len=hashing.DEFAULT_CHUNK)
    for i in skipped:
        out[i] = positional_hashes(genomes[i], k, algo=algo)
    for chunk_idxs, packed, ambits, offs in group_iter:
        import jax.numpy as jnp

        from galah_tpu.obs import metrics as obs_metrics

        obs_metrics.counter(
            "hash.batched_genomes",
            help="Genomes hashed in grouped one-dispatch batches "
                 "(vs the per-genome chunk pipeline)",
            unit="genomes").inc(len(chunk_idxs))
        timing.dispatch()
        timing.dispatch(sync=True)
        h = np.asarray(hashing.canonical_kmer_hashes_batch_jit(
            jnp.asarray(packed), jnp.asarray(ambits), jnp.asarray(offs),
            k=k, algo=algo))
        for row, gi in enumerate(chunk_idxs):
            n = genomes[gi].codes.shape[0]
            if n < k:
                out[gi] = np.zeros(0, dtype=np.uint64)
            else:
                out[gi] = h[row, : n - k + 1].copy()
    return out


def _check_subsample(subsample_c: int) -> None:
    if not 1 <= subsample_c <= MARKER_C:
        raise ValueError(
            f"subsample_c must be in [1, {MARKER_C}], got {subsample_c}")


def _finish_profile(path: str, flat: np.ndarray, valid: np.ndarray,
                    k: int, fraglen: int, subsample_c: int,
                    pos: Optional[np.ndarray] = None) -> GenomeProfile:
    """Distinct set + marker slice + construction — the one tail
    shared by the C single-pass and generic profile builds. `pos`
    (the kept hashes' positions, when the C profile walk produced
    them) enables the O(n_valid) window assembly."""
    # np.unique stays: numpy's u64 sort is radix-backed and measured
    # 4x FASTER than the inlined C quicksort on 3M-hash inputs
    # (74 vs 287 ms, 2026-07-31) — a C dedup here is a pessimization.
    ref_set = np.unique(valid)
    markers = ref_set[ref_set < np.uint64((1 << 64) // MARKER_C)]
    return GenomeProfile(
        path=path, k=k, fraglen=fraglen,
        flat_hashes=flat, ref_set=ref_set, markers=markers,
        subsample_c=subsample_c,
        _kept_hashes=valid if pos is not None else None,
        _kept_pos=pos)


def _profile_from_flat(path: str, flat: np.ndarray, k: int, fraglen: int,
                       subsample_c: int) -> GenomeProfile:
    """Host post-pass shared by single and batched profile builds:
    FracMinHash subsample mask, distinct set, marker slice."""
    _check_subsample(subsample_c)
    if subsample_c > 1:
        cut = np.uint64((1 << 64) // subsample_c)
        flat = np.where(flat < cut, flat, np.uint64(SENTINEL))
    valid = flat[flat != np.uint64(SENTINEL)]
    return _finish_profile(path, flat, valid, k, fraglen, subsample_c)


def _c_profile_available(k: int) -> bool:
    """Gate for the C single-pass profile build — genome-independent
    by construction (backend, k width, toolchain), so callers may
    decide once per batch."""
    if jax.default_backend() != "cpu" or k > 32:
        return False
    try:
        from galah_tpu.ops import _csketch  # noqa: F401
    except ImportError:
        return False
    return True


def _profile_via_c(genome: Genome, k: int, fraglen: int,
                   subsample_c: int,
                   algo: str = "murmur3") -> GenomeProfile:
    """Single-pass C profile build: hash walk + FracMinHash mask +
    valid compaction in one sweep (csrc/sketch.c::
    galah_positional_hashes_masked), leaving only a small np.unique on
    the kept hashes. Bit-identical to the _profile_from_flat post-pass
    (parity: tests/test_csketch.py). Callers must check
    _c_profile_available first."""
    from galah_tpu.ops import _csketch

    if subsample_c == 1:
        # dense profile: windows() uses the flat layout directly, so
        # the kept-positions array would be 8 B/bp of dead weight
        flat, valid = _csketch.positional_hashes_masked(
            genome.codes, genome.contig_offsets, k=k, cut=0, algo=algo)
        return _finish_profile(genome.path, flat, valid, k, fraglen,
                               subsample_c)
    cut = (1 << 64) // subsample_c
    flat, valid, pos = _csketch.positional_hashes_profile(
        genome.codes, genome.contig_offsets, k=k, cut=cut, algo=algo)
    return _finish_profile(genome.path, flat, valid, k, fraglen,
                           subsample_c, pos=pos)


def build_profile(genome: Genome, k: int, fraglen: int,
                  subsample_c: int = 1,
                  hash_algorithm: str = "murmur3") -> GenomeProfile:
    """Profile a genome for fragment ANI.

    With subsample_c > 1 only k-mers whose hash falls below 2^64/c are
    kept (positionally SENTINEL-masked, so window structure survives):
    a FracMinHash subsample, exactly the compression the reference's
    skani applies with c=125 (reference: src/skani.rs:159-161). Both
    the query windows AND the reference set shrink by ~c, cutting the
    membership-test work ~c^2/c = c-fold per direction with an
    unbiased per-window matched-fraction estimate. Markers are computed
    from the full distinct set's sub-2^64/MARKER_C slice, which is a
    subset of any c <= MARKER_C selection, so screening semantics are
    unchanged.
    """
    _check_subsample(subsample_c)  # fail before any device hashing
    if _c_profile_available(k):
        return _profile_via_c(genome, k, fraglen, subsample_c,
                              algo=hash_algorithm)
    return _profile_from_flat(
        genome.path, positional_hashes(genome, k, algo=hash_algorithm),
        k, fraglen, subsample_c)


def build_profiles_batch(genomes, k: int, fraglen: int,
                         subsample_c: int = 1,
                         hash_algorithm: str = "murmur3") -> list:
    """Batch twin of build_profile: one hash dispatch per genome group
    instead of per genome (reference analog: skani's fastx_to_sketches
    over all files, src/skani.rs:46)."""
    _check_subsample(subsample_c)  # fail before any device hashing
    if genomes and _c_profile_available(k):
        # CPU backend with the C walker: per-genome single-pass builds
        # beat device batch grouping (no dispatch round trips to
        # amortize).
        return [_profile_via_c(g, k, fraglen, subsample_c,
                               algo=hash_algorithm)
                for g in genomes]
    flats = positional_hashes_batch(genomes, k, algo=hash_algorithm)
    return [
        _profile_from_flat(g.path, flat, k, fraglen, subsample_c)
        for g, flat in zip(genomes, flats)
    ]


def _bucket_pow2(n: int, floor: int = 1 << 12) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def pad_ref_set(ref_set: np.ndarray) -> np.ndarray:
    h = _bucket_pow2(max(ref_set.shape[0], 1))
    out = np.full(h, np.uint64(SENTINEL), dtype=np.uint64)
    out[: ref_set.shape[0]] = ref_set
    return out


def pad_windows(wins: np.ndarray, quantum: int = 64) -> np.ndarray:
    w = -(-wins.shape[0] // quantum) * quantum
    out = np.full((w, wins.shape[1]), np.uint64(SENTINEL), dtype=np.uint64)
    out[: wins.shape[0]] = wins
    return out


def _window_match_counts_impl(
    windows: jax.Array,   # uint64 (W, L), SENTINEL-masked
    ref_set: jax.Array,   # uint64 (H,) sorted, SENTINEL-padded
) -> Tuple[jax.Array, jax.Array]:
    """Per-window (matched k-mers, valid k-mers) against the ref set."""
    w, length = windows.shape
    q = windows.reshape(-1)
    valid = q != hashing.HASH_SENTINEL
    pos = jnp.searchsorted(ref_set, q)
    hit = jnp.take(ref_set, jnp.minimum(pos, ref_set.shape[0] - 1)) == q
    hit = hit & valid
    matched = jnp.sum(hit.reshape(w, length).astype(jnp.int32), axis=1)
    total = jnp.sum(valid.reshape(w, length).astype(jnp.int32), axis=1)
    return matched, total


_window_match_counts = jax.jit(_window_match_counts_impl)

# Batched twin: (B, W, L) windows x (B, H) ref sets -> (B, W) counts.
# One dispatch covers every directed query in a same-shape bucket.
_window_match_counts_batched = jax.jit(jax.vmap(_window_match_counts_impl))

# Memory cap for one batched dispatch: B * W * L uint64 elements.
_BATCH_ELEM_CAP = 32 << 20  # 256 MiB of window data per dispatch


@dataclasses.dataclass
class DirectedANI:
    ani: float               # mean identity over aligned windows (fraction)
    aligned_fraction: float  # aligned windows / valid windows
    frags_matching: int
    frags_total: int


# Fraction of a window's k-mer slots that must be valid for it to
# count as a fragment — shared by every ANI entry point so the
# per-pair and batched-array paths cannot drift apart.
DEFAULT_MIN_WINDOW_VALID_FRAC = 0.5


def directed_ani(
    query: GenomeProfile,
    ref: GenomeProfile,
    identity_floor: float = 0.80,
    min_window_valid_frac: float = DEFAULT_MIN_WINDOW_VALID_FRAC,
) -> DirectedANI:
    """One-way fragment ANI of `query` against `ref` (device dispatch).

    A window counts as a fragment iff at least `min_window_valid_frac` of
    its k-mer slots are valid (unambiguous, within one contig); it counts
    as ALIGNED iff its matched fraction implies identity >=
    `identity_floor` (c_w >= identity_floor^k).
    """
    matched, total = _window_match_counts(
        query.device_windows(), ref.device_ref_set())
    return _directed_from_counts(
        np.asarray(matched), np.asarray(total), query,
        identity_floor, min_window_valid_frac)


def _seq_sum(a: np.ndarray) -> float:
    """f64 sum in np.add.reduceat's order over a COMPRESSED array.

    reduceat's pairwise blocking is a function of the summed run's
    length, so the only way two code paths produce bit-identical sums
    is to hand reduceat the same element run: masked windows must be
    compressed OUT (a[mask]), never zero-filled in place — interleaved
    +0.0 terms shift the pairwise block boundaries and can move the
    total a ulp. The batched twin (_directed_from_counts_arrays)
    reduces each pair's compressed segment with reduceat at the
    compressed starts (_segment_compressed_sums), which is
    bit-identical to this call on the segment alone (reduceat's
    blocking does not depend on the segment's offset)."""
    if a.shape[0] == 0:
        return 0.0
    return float(np.add.reduceat(a, np.zeros(1, dtype=np.intp))[0])


def _segment_compressed_sums(
    values: np.ndarray,   # (W_total,) f64
    mask: np.ndarray,     # (W_total,) bool — which entries count
    starts: np.ndarray,   # (n_segs,) segment starts into values
) -> "Tuple[np.ndarray, np.ndarray]":
    """Per-segment (sum of values[mask], count of mask) — each sum
    bit-identical to _seq_sum over that segment's compressed slice.

    Compresses FIRST, then reduceat at the nonempty segments'
    compressed starts (empty segments occupy zero width in the
    compressed array, so the next nonempty start is exactly this
    segment's end — reduceat's [start_i, start_{i+1}) windows line up
    without materializing per-segment ends, and its empty-segment wart
    — a zero-width window yields a[start], not 0 — never arises)."""
    n = starts.shape[0]
    sums = np.zeros(n, dtype=np.float64)
    idx = np.flatnonzero(mask)
    counts = (np.searchsorted(idx, np.append(starts[1:],
                                             values.shape[0]))
              - np.searchsorted(idx, starts))
    if idx.size == 0:
        return sums, counts
    comp = values[idx]
    cstarts = np.searchsorted(idx, starts)
    nonempty = np.flatnonzero(counts > 0)
    sums[nonempty] = np.add.reduceat(
        comp, cstarts[nonempty].astype(np.intp))
    return sums, counts


def _directed_from_counts(
    matched: np.ndarray,
    total: np.ndarray,
    query: GenomeProfile,
    identity_floor: float,
    min_window_valid_frac: float,
) -> DirectedANI:
    """Host post-processing from per-window (matched, valid) counts."""
    k = query.k
    matched = matched.astype(np.float64)
    total = total.astype(np.float64)

    # expected k-mer slots per window shrink by the FracMinHash factor
    min_valid = (min_window_valid_frac * (query.fraglen - k + 1)
                 / query.subsample_c)
    frag_ok = total >= max(min_valid, 1.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        c_w = np.where(frag_ok, matched / np.maximum(total, 1.0), 0.0)
    c_floor = identity_floor ** k
    aligned = frag_ok & (c_w >= c_floor)

    frags_total = int(frag_ok.sum())
    frags_matching = int(aligned.sum())
    if frags_matching == 0:
        return DirectedANI(0.0, 0.0, 0, frags_total)

    # Background correction: unaligned windows measure the random k-mer
    # collision rate against this reference set (repeats, chance hits);
    # subtracting it from aligned windows' matched fraction removes the
    # upward bias before inverting the k-mer survival model.
    below = frag_ok & ~aligned
    r_est = (_seq_sum(c_w[below]) / int(below.sum())
             if below.any() else 0.0)
    c_adj = np.clip((c_w[aligned] - r_est) / max(1.0 - r_est, 1e-9),
                    1e-12, 1.0)
    identity = c_adj ** (1.0 / k)
    ani = _seq_sum(identity) / frags_matching
    af = frags_matching / max(frags_total, 1)
    return DirectedANI(ani, af, frags_matching, frags_total)


def _directed_from_counts_arrays(
    matched_cat: np.ndarray,   # (W_total,) int32, segments per pair
    total_cat: np.ndarray,     # (W_total,) int32, aligned to matched
    starts: np.ndarray,        # (n_pairs,) int64 segment starts
    k: int,
    fraglen: int,
    subsample_c: int,
    identity_floor: float,
    min_window_valid_frac: float,
):
    """Vectorized batch twin of _directed_from_counts over concatenated
    per-pair window segments — bit-identical floats: every f64
    reduction compresses masked windows out and reduceats the same
    element run the per-pair path's _seq_sum consumes (see
    _segment_compressed_sums; zero-filling masked slots instead would
    shift reduceat's pairwise block boundaries and drift a ulp).

    Returns (ani, af, frags_matching, frags_total) arrays, one entry
    per pair."""
    matched = matched_cat.astype(np.float64)
    total = total_cat.astype(np.float64)
    starts = np.ascontiguousarray(starts, dtype=np.intp)

    min_valid = (min_window_valid_frac * (fraglen - k + 1)
                 / subsample_c)
    frag_ok = total >= max(min_valid, 1.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        c_w = np.where(frag_ok, matched / np.maximum(total, 1.0), 0.0)
    c_floor = identity_floor ** k
    aligned = frag_ok & (c_w >= c_floor)

    frags_total = np.add.reduceat(
        frag_ok.astype(np.int64), starts)
    frags_matching = np.add.reduceat(
        aligned.astype(np.int64), starts)

    below = frag_ok & ~aligned
    sum_below, cnt_below = _segment_compressed_sums(c_w, below, starts)
    r_est = np.where(cnt_below > 0,
                     sum_below / np.maximum(cnt_below, 1), 0.0)

    seg_lens = np.diff(np.append(starts, matched.shape[0]))
    r_w = np.repeat(r_est, seg_lens)
    c_adj = np.clip((c_w - r_w) / np.maximum(1.0 - r_w, 1e-9),
                    1e-12, 1.0)
    # the power is elementwise (position-independent), so raising the
    # full array then compressing matches the per-pair compressed pow
    sum_id, _ = _segment_compressed_sums(c_adj ** (1.0 / k), aligned,
                                         starts)

    has = frags_matching > 0
    ani = np.where(has, sum_id / np.maximum(frags_matching, 1), 0.0)
    af = np.where(
        has,
        frags_matching / np.maximum(frags_total, 1).astype(np.float64),
        0.0)
    return ani, af, frags_matching, frags_total


# Window elements per batched-merge chunk: bounds the concatenated
# matched/total scratch (~26 B/window across the f64 temporaries) to
# ~200 MB while keeping chunks big enough to amortize the C call.
_MERGE_BATCH_WINDOW_CAP = 8 << 20

# Hard cap on the batched path's per-genome concatenation (qh/qw/ref
# elements across unique profiles, ~28 B/element): ~1.8 GB. Above it
# the per-pair loop runs instead — by then pairs-per-genome is low
# (the cap is only reachable with many LARGE genomes, where the
# screen keeps the pair list sparse and per-pair overhead is noise).
_MERGE_BATCH_CONCAT_CAP = 64 << 20


def _batch_path_worthwhile(
    queries: "list[Tuple[GenomeProfile, GenomeProfile]]",
) -> bool:
    """Whether the batched C path pays for its concatenation: enough
    pairs to amortize the setup (>= 64) and a bounded concat volume.
    The estimate mirrors _directed_ani_arrays_c's actual layout — a
    genome contributes its query-role arrays AND its ref-role set
    when it appears in both roles (the bidirectional case always has
    both). Expected survivor counts (flat length / subsample) stand
    in for len(sorted_query()) so no profile arrays are materialized
    early."""
    if len(queries) < 64:
        return False
    seen_q: "set[int]" = set()
    seen_r: "set[int]" = set()
    est = 0
    for q, r in queries:
        if id(q) not in seen_q:
            seen_q.add(id(q))
            est += q.flat_hashes.shape[0] // max(q.subsample_c, 1)
        if id(r) not in seen_r:
            seen_r.add(id(r))
            est += r.ref_set.shape[0]
    return est <= _MERGE_BATCH_CONCAT_CAP


def _directed_ani_batch_c(
    queries: "list[Tuple[GenomeProfile, GenomeProfile]]",
    identity_floor: float,
    min_window_valid_frac: float,
    threads: int,
) -> "list[DirectedANI]":
    """Boxed twin of _directed_ani_arrays_c (same arrays, DirectedANI
    objects out) — the directed_ani_batch fast path."""
    ani, af, fm, ft = _directed_ani_arrays_c(
        queries, identity_floor, min_window_valid_frac, threads)
    return [DirectedANI(float(ani[i]), float(af[i]),
                        int(fm[i]), int(ft[i]))
            for i in range(len(queries))]


def _directed_ani_arrays_c(
    queries: "list[Tuple[GenomeProfile, GenomeProfile]]",
    identity_floor: float,
    min_window_valid_frac: float,
    threads: int,
):
    """Batched CPU exact-ANI: per-pair merges in ONE threaded C call
    per chunk (csrc/pairstats.c::galah_window_match_counts_merge_batch)
    plus vectorized post-math, returning per-pair (ani, af,
    frags_matching, frags_total) ARRAYS — the per-pair Python loop
    costs ~100x the O(nq + H) merge itself at small-genome sizes,
    which is the entire wall of the dense-similarity mega-family
    regime (BASELINE.md rung-mega row; reference analog: one skani
    call per screened pair, src/skani.rs:85-104)."""
    from galah_tpu.ops._cpairstats import window_match_counts_merge_batch

    q0 = queries[0][0]
    k, fraglen, subsample_c = q0.k, q0.fraglen, q0.subsample_c

    # Unique profiles by object identity; sorted_query/ref_set are
    # cached per profile, so concatenation cost is one copy.
    q_idx: "dict[int, int]" = {}
    r_idx: "dict[int, int]" = {}
    q_profiles: "list[GenomeProfile]" = []
    r_profiles: "list[GenomeProfile]" = []
    pair_q = np.empty(len(queries), dtype=np.int32)
    pair_r = np.empty(len(queries), dtype=np.int32)
    for n, (q, r) in enumerate(queries):
        qi = q_idx.setdefault(id(q), len(q_profiles))
        if qi == len(q_profiles):
            q_profiles.append(q)
        ri = r_idx.setdefault(id(r), len(r_profiles))
        if ri == len(r_profiles):
            r_profiles.append(r)
        pair_q[n] = qi
        pair_r[n] = ri

    qh_parts, qw_parts, tot_parts = [], [], []
    for q in q_profiles:
        qh, qw, totals = q.sorted_query()
        qh_parts.append(qh)
        qw_parts.append(qw)
        tot_parts.append(totals)
    q_off = np.zeros(len(q_profiles) + 1, dtype=np.int64)
    np.cumsum([p.shape[0] for p in qh_parts], out=q_off[1:])
    tot_off = np.zeros(len(q_profiles) + 1, dtype=np.int64)
    np.cumsum([t.shape[0] for t in tot_parts], out=tot_off[1:])
    qh_cat = (np.concatenate(qh_parts) if qh_parts
              else np.zeros(0, dtype=np.uint64))
    qw_cat = (np.concatenate(qw_parts) if qw_parts
              else np.zeros(0, dtype=np.int32))
    tot_cat = (np.concatenate(tot_parts) if tot_parts
               else np.zeros(0, dtype=np.int32))
    n_win = np.asarray([t.shape[0] for t in tot_parts], dtype=np.int64)

    r_off = np.zeros(len(r_profiles) + 1, dtype=np.int64)
    np.cumsum([p.ref_set.shape[0] for p in r_profiles], out=r_off[1:])
    ref_cat = (np.concatenate([p.ref_set for p in r_profiles])
               if r_profiles else np.zeros(0, dtype=np.uint64))

    out_ani = np.zeros(len(queries), dtype=np.float64)
    out_af = np.zeros(len(queries), dtype=np.float64)
    out_fm = np.zeros(len(queries), dtype=np.int64)
    out_ft = np.zeros(len(queries), dtype=np.int64)
    pair_wins = n_win[pair_q]
    # zero-window queries never enter the C kernel (reduceat cannot
    # represent empty segments); their result is the all-zero row the
    # outputs are initialized to
    live = np.nonzero(pair_wins != 0)[0]

    pos = 0
    while pos < live.shape[0]:
        # chunk by total window volume
        end = pos
        vol = 0
        while end < live.shape[0] and (vol == 0
                                       or vol + pair_wins[live[end]]
                                       <= _MERGE_BATCH_WINDOW_CAP):
            vol += int(pair_wins[live[end]])
            end += 1
        chunk = live[pos:end]
        pos = end

        cw = pair_wins[chunk]
        m_off = np.zeros(chunk.shape[0], dtype=np.int64)
        np.cumsum(cw[:-1], out=m_off[1:])
        total_windows = int(cw.sum())
        matched_cat = window_match_counts_merge_batch(
            qh_cat, qw_cat, q_off, ref_cat, r_off,
            pair_q[chunk], pair_r[chunk], m_off, total_windows,
            threads=max(1, threads))
        # gather each pair's per-window valid counts
        within = np.arange(total_windows, dtype=np.int64) \
            - np.repeat(m_off, cw)
        tidx = np.repeat(tot_off[pair_q[chunk]], cw) + within
        total_cat = tot_cat[tidx]

        ani, af, fm, ft = _directed_from_counts_arrays(
            matched_cat, total_cat, m_off, k, fraglen, subsample_c,
            identity_floor, min_window_valid_frac)
        out_ani[chunk] = ani
        out_af[chunk] = af
        out_fm[chunk] = fm
        out_ft[chunk] = ft
    return out_ani, out_af, out_fm, out_ft


# Fragment-ANI membership strategies (GALAH_TPU_FRAGMENT_STRATEGY to
# pin; unset/"auto" resolves per backend):
#   pallas — ops/pallas_fragment.py's blocked multi-pair Mosaic kernel
#            (interpret-mode on non-TPU backends, so parity tests can
#            pin it on CPU)
#   xla    — the vmapped searchsorted dispatch path
#   c      — csrc/pairstats.c's merge membership counter (host)
FRAGMENT_STRATEGIES = ("pallas", "xla", "c")


def _c_merge_available() -> bool:
    try:
        from galah_tpu.ops import _cpairstats
    except Exception:  # pragma: no cover - import error == no C
        return False
    return hasattr(_cpairstats, "window_match_counts_merge")


def _resolve_fragment_strategy(
    backend: "Optional[str]" = None,
    n_devices: "Optional[int]" = None,
    c_ok: "Optional[bool]" = None,
) -> "Tuple[str, bool]":
    """(strategy, explicit) for the exact-ANI membership stage.

    An explicit GALAH_TPU_FRAGMENT_STRATEGY pin always wins (and its
    failures propagate — parity runs must never silently compare a
    fallback to itself). AUTO mirrors the historical defaults: the
    single-core C merge on a single-device CPU runtime (it beat the
    XLA-CPU searchsorted by avoiding padding entirely), the Mosaic
    kernel on a real TPU backend, the vmapped XLA path everywhere
    else (notably multi-device CPU meshes, whose sharded batch path
    the C merge cannot use). The injectable parameters exist for
    selection tests; production callers pass nothing.
    """
    env = (os.environ.get("GALAH_TPU_FRAGMENT_STRATEGY") or "").lower()
    if env in FRAGMENT_STRATEGIES:
        return env, True
    backend = jax.default_backend() if backend is None else backend
    n_devices = jax.device_count() if n_devices is None else n_devices
    if c_ok is None:
        c_ok = _c_merge_available()
    if backend == "cpu" and n_devices == 1 and c_ok:
        return "c", False
    from galah_tpu.ops.hll import use_pallas_default

    if backend == "tpu" and use_pallas_default():
        return "pallas", False
    return "xla", False


def directed_ani_batch(
    queries: "list[Tuple[GenomeProfile, GenomeProfile]]",
    identity_floor: float = 0.80,
    min_window_valid_frac: float = DEFAULT_MIN_WINDOW_VALID_FRAC,
    threads: int = 1,
) -> "list[DirectedANI]":
    """Directed fragment ANI for many (query, ref) pairs, coalescing
    device dispatches.

    The membership stage runs under the resolved fragment strategy
    (see _resolve_fragment_strategy): the C merge path consumes cached
    sorted queries with no padding; the XLA and Pallas paths group
    queries by their padded (W, L, H) shape bucket so a handful of
    kernel variants cover any genome collection. Results are
    bit-identical across all three (the per-window integers are exact
    and the f64 reduction is shared). This is the framework's answer
    to the reference's one-subprocess-per-pair fastANI calls
    (reference: src/fastani.rs:88-105) — and the reason the engine's
    backend interface is batched (see backends/base.py).
    """
    if not queries:
        return []
    strategy, explicit = _resolve_fragment_strategy()
    timing.counter(f"fragment-strategy-{strategy}", 1)
    if strategy == "c":
        return _directed_ani_batch_cmerge(
            queries, identity_floor, min_window_valid_frac, threads)

    from galah_tpu.ops._fallback import run_with_pallas_fallback

    def run(pallas: bool) -> "list[DirectedANI]":
        if pallas:
            return _directed_ani_batch_pallas(
                queries, identity_floor, min_window_valid_frac)
        return _directed_ani_batch_xla(
            queries, identity_floor, min_window_valid_frac)

    res, used = run_with_pallas_fallback(
        "fragment window-match kernel", explicit,
        strategy == "pallas", run)
    if strategy == "pallas" and not used:
        timing.counter("fragment-pallas-demoted", 1)
    return res


def _directed_ani_batch_cmerge(
    queries: "list[Tuple[GenomeProfile, GenomeProfile]]",
    identity_floor: float,
    min_window_valid_frac: float,
    threads: int,
) -> "list[DirectedANI]":
    """The compiled-C merge membership strategy (csrc/pairstats.c::
    galah_window_match_counts_merge — O(nq + H) per pair on the
    profile's cached sorted query, vs the matrix walker's
    O(slots * log H) binary searches); a host path, no device work.
    ImportError propagates: AUTO only resolves here when the extension
    probe passed, so reaching it without the toolchain means an
    explicit pin — which must fail loudly."""
    from galah_tpu.ops._cpairstats import window_match_counts_merge

    # Large pair lists (the dense-similarity regime can carry
    # N^2/2 screened pairs) take the fully batched path: ONE
    # threaded C call per chunk for the merges and vectorized
    # host post-math — bit-identical DirectedANI floats to the
    # per-pair loop below (see _directed_from_counts_arrays).
    if _batch_path_worthwhile(queries):
        uniform = len({(q.k, q.fraglen, q.subsample_c)
                       for q, _ in queries}) == 1
        if uniform:
            return _directed_ani_batch_c(
                queries, identity_floor, min_window_valid_frac,
                threads)

    def one(pair):
        q, r = pair
        qh, qw, totals = q.sorted_query()
        matched = window_match_counts_merge(
            qh, qw, q.n_windows, r.ref_set, validate=False)
        return _directed_from_counts(
            matched, totals, q, identity_floor,
            min_window_valid_frac)

    if threads > 1 and len(queries) > 1:
        # pairs are independent and the merge releases the GIL
        # (ctypes) — honor the threads knob across pairs. Warm
        # each unique query's sorted_query cache first so the
        # first wave of threads doesn't build it redundantly
        # (one candidate vs many refs is the common shape).
        from galah_tpu.io.prefetch import _shared_pool

        for q in {id(q): q for q, _ in queries}.values():
            q.sorted_query()
        # The shared pool is sized to the LARGEST worker count
        # ever requested in-process; keep at most `threads`
        # futures outstanding so a smaller knob here still
        # bounds concurrency to what the user asked for, and
        # refill on EACH completion (not in waves — pair costs
        # are heterogeneous, one big query vs many small refs
        # is the common shape).
        from concurrent.futures import FIRST_COMPLETED, wait

        pool = _shared_pool(threads)
        out: "list[Optional[DirectedANI]]" = [None] * len(queries)
        it = iter(enumerate(queries))
        pending = {}

        def submit_next() -> bool:
            try:
                i, pair = next(it)
            except StopIteration:
                return False
            pending[pool.submit(one, pair)] = i
            return True

        for _ in range(threads):
            if not submit_next():
                break
        while pending:
            done, _ = wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                out[pending.pop(f)] = f.result()
                submit_next()
        return out  # type: ignore[return-value]
    return [one(pair) for pair in queries]


def _directed_ani_batch_xla(
    queries: "list[Tuple[GenomeProfile, GenomeProfile]]",
    identity_floor: float,
    min_window_valid_frac: float,
) -> "list[DirectedANI]":
    """The vmapped-searchsorted strategy: queries grouped by padded
    shape bucket, each bucket dispatched in chunks of at most
    _BATCH_ELEM_CAP window elements (multi-device runtimes shard the
    batch dim over the host-local mesh). Bit-identical to per-pair
    `directed_ani` — the vmap computes the same per-row searchsorted;
    only the dispatch granularity changes."""
    out: "list[Optional[DirectedANI]]" = [None] * len(queries)
    groups: "dict[tuple, list[int]]" = {}
    for n, (q, r) in enumerate(queries):
        # padded host shapes only — no device upload during grouping
        key = (q.padded_windows().shape, r.padded_ref_set().shape[0])
        groups.setdefault(key, []).append(n)

    n_dev = len(jax.local_devices())  # host-local (see _shard_batch)
    for (wshape, _h), idxs in groups.items():
        per_query_elems = wshape[0] * wshape[1]
        b_max = max(1, _BATCH_ELEM_CAP // max(per_query_elems, 1))
        for start in range(0, len(idxs), b_max):
            chunk = idxs[start:start + b_max]
            timing.dispatch()
            timing.dispatch(sync=True)
            if len(chunk) == 1:
                n = chunk[0]
                q, r = queries[n]
                matched, total = _window_match_counts(
                    q.device_windows(), r.device_ref_set())
                mt = [(matched, total)]
            else:
                if n_dev > 1:
                    # Shard the batch over the mesh: the vmapped
                    # membership test is embarrassingly parallel per
                    # directed query, so a batch-dim sharding turns one
                    # dispatch into n_dev-way data parallelism. Staged
                    # through host numpy so padding never materializes
                    # a super-cap array on one device.
                    wins, refs = _shard_batch(
                        [queries[n] for n in chunk], n_dev)
                else:
                    wins = jnp.stack(
                        [queries[n][0].device_windows() for n in chunk])
                    refs = jnp.stack(
                        [queries[n][1].device_ref_set() for n in chunk])
                m_b, t_b = _window_match_counts_batched(wins, refs)
                mt = [(m_b[i], t_b[i]) for i in range(len(chunk))]
            for n, (m, t) in zip(chunk, mt):
                out[n] = _directed_from_counts(
                    np.asarray(m), np.asarray(t), queries[n][0],
                    identity_floor, min_window_valid_frac)
    return out  # type: ignore[return-value]


def _directed_ani_batch_pallas(
    queries: "list[Tuple[GenomeProfile, GenomeProfile]]",
    identity_floor: float,
    min_window_valid_frac: float,
) -> "list[DirectedANI]":
    """The blocked Mosaic strategy (ops/pallas_fragment.py): queries
    grouped by padded shape bucket like the XLA path — the kernel's
    launch packer then covers each bucket's pairs with as few grid
    launches as its volume caps allow. Per-ELEMENT membership flags
    come back host-side; one bincount per pair folds them into the
    same per-window matched counts the other strategies produce, and
    the shared _directed_from_counts_arrays reduction keeps the
    DirectedANI floats bit-identical."""
    from galah_tpu.ops import pallas_fragment

    # interpret-mode on non-TPU backends: parity tests pin the
    # strategy on CPU; a real TPU lowers through Mosaic
    interpret = jax.default_backend() != "tpu"
    out: "list[Optional[DirectedANI]]" = [None] * len(queries)
    groups: "dict[tuple, list[int]]" = {}
    for n, (q, r) in enumerate(queries):
        key = (q.padded_windows().shape, r.padded_ref_set().shape[0],
               q.k, q.fraglen, q.subsample_c)
        groups.setdefault(key, []).append(n)

    for (_w, _h, k, fraglen, subsample_c), idxs in groups.items():
        items = []
        for n in idxs:
            q, r = queries[n]
            items.append(
                (q.sorted_query()[0], r.ref_set, r.padded_ref_set()))
        hits = pallas_fragment.window_element_hits(
            items, interpret=interpret)

        matched_parts, total_parts, starts, live = [], [], [], []
        seg = 0
        for j, n in enumerate(idxs):
            q, _r = queries[n]
            _qh, qw, totals = q.sorted_query()
            w = totals.shape[0]
            if w == 0:
                # reduceat cannot represent empty segments; the
                # zero-window result is all-zero by definition
                out[n] = DirectedANI(0.0, 0.0, 0, 0)
                continue
            matched = np.bincount(
                qw[hits[j] != 0], minlength=w).astype(np.int32)
            matched_parts.append(matched)
            total_parts.append(totals)
            starts.append(seg)
            seg += w
            live.append(n)
        if not live:
            continue
        ani, af, fm, ft = _directed_from_counts_arrays(
            np.concatenate(matched_parts),
            np.concatenate(total_parts),
            np.asarray(starts, dtype=np.int64), k, fraglen,
            subsample_c, identity_floor, min_window_valid_frac)
        for i, n in enumerate(live):
            out[n] = DirectedANI(float(ani[i]), float(af[i]),
                                 int(fm[i]), int(ft[i]))
    return out  # type: ignore[return-value]


def _shard_batch(pairs: "list[Tuple[GenomeProfile, GenomeProfile]]",
                 n_dev: int):
    """Batch-dim-sharded (wins, refs) device arrays for (query, ref)
    pairs, padded to a mesh multiple (padding repeats the first pair;
    callers index only the real rows).

    The padded batch is assembled in host numpy and device_put straight
    into its sharded layout, so each device only ever holds its own
    shard — never the whole super-capacity batch.

    The mesh is HOST-LOCAL (jax.local_devices()): on a multi-host
    runtime each process batches its own (possibly host-divergent)
    pair work — a global sharding would demand identical values on
    every process, which the host-sharded exact-ANI split
    (backends/fragment_backend._exact_ani_multihost) deliberately
    violates. Single-process behavior is identical.
    """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    b = len(pairs)
    b_pad = -(-b // n_dev) * n_dev
    padded = pairs + [pairs[0]] * (b_pad - b)
    wins_np = np.stack([q.padded_windows() for q, _ in padded])
    refs_np = np.stack([r.padded_ref_set() for _, r in padded])
    mesh = Mesh(np.array(jax.local_devices()), ("i",))
    wins = jax.device_put(wins_np, NamedSharding(mesh, P("i", None, None)))
    refs = jax.device_put(refs_np, NamedSharding(mesh, P("i", None)))
    return wins, refs


def _check_same_subsample(a: GenomeProfile, b: GenomeProfile) -> None:
    """Profiles built at different FracMinHash cuts are incomparable —
    a query filtered at one cut can never match a reference filtered at
    another, silently collapsing ANI to nothing."""
    if a.subsample_c != b.subsample_c:
        raise ValueError(
            f"GenomeProfiles built with different subsample_c "
            f"({a.subsample_c} vs {b.subsample_c}) cannot be compared")


def bidirectional_ani_batch(
    pairs: "list[Tuple[GenomeProfile, GenomeProfile]]",
    min_aligned_frac: float,
    identity_floor: float = 0.80,
    threads: int = 1,
) -> "list[Tuple[Optional[float], DirectedANI, DirectedANI]]":
    """Batched twin of `bidirectional_ani`: both directions of every pair
    go through one `directed_ani_batch` call; the gate/max semantics per
    pair are identical to the scalar path."""
    for a, b in pairs:
        _check_same_subsample(a, b)
    directed = directed_ani_batch(
        [(a, b) for a, b in pairs] + [(b, a) for a, b in pairs],
        identity_floor=identity_floor, threads=threads)
    n = len(pairs)
    out = []
    for i in range(n):
        ab, ba = directed[i], directed[n + i]
        out.append((_combine_bidirectional(ab, ba, min_aligned_frac),
                    ab, ba))
    return out


def bidirectional_ani_values(
    pairs: "list[Tuple[GenomeProfile, GenomeProfile]]",
    min_aligned_frac: float,
    identity_floor: float = 0.80,
    threads: int = 1,
) -> "list[Optional[float]]":
    """ANI values only — `[ani for ani, _, _ in
    bidirectional_ani_batch(...)]` with the DirectedANI boxing removed
    on the batched-C path (at mega-pair volumes the 2x-per-pair object
    construction and per-pair gate loop dominate the exact math;
    identical Nones/floats either way — the gate arithmetic is the
    same f64 ops _combine_bidirectional runs on ints)."""
    n = len(pairs)
    # Gate exactly as the fallback's inner directed_ani_batch would on
    # the doubled directed list — but WITHOUT materializing that list
    # (2n tuples is hundreds of MB at mega-pair volumes) unless the
    # arrays path is actually taken: in the bidirectional list every
    # genome appears in both roles, so the concat estimate is each
    # unique genome's query-role plus ref-role contribution.
    seen: "set[int]" = set()
    est = 0
    for a, b in pairs:
        for p in (a, b):
            if id(p) not in seen:
                seen.add(id(p))
                est += (p.flat_hashes.shape[0]
                        // max(p.subsample_c, 1))
                est += p.ref_set.shape[0]
    # the boxing-free shortcut only exists for the C merge strategy;
    # pallas/xla resolve to the fallback below, whose inner
    # directed_ani_batch re-resolves and routes accordingly (AUTO only
    # returns "c" when the extension probe passed; an explicit c pin
    # without the toolchain fails loudly inside the arrays path)
    strategy, _explicit = _resolve_fragment_strategy()
    use_arrays = (
        strategy == "c"
        and 2 * n >= 64 and est <= _MERGE_BATCH_CONCAT_CAP
        and len({(p.k, p.fraglen, p.subsample_c)
                 for pair in pairs for p in pair}) == 1)
    if not use_arrays:
        return [ani for ani, _, _ in bidirectional_ani_batch(
            pairs, min_aligned_frac, identity_floor=identity_floor,
            threads=threads)]

    directed = [(a, b) for a, b in pairs] + [(b, a) for a, b in pairs]
    ani, _af, fm, ft = _directed_ani_arrays_c(
        directed, identity_floor, DEFAULT_MIN_WINDOW_VALID_FRAC,
        threads)
    ab, ba = slice(0, n), slice(n, 2 * n)
    gate = (
        ((ft[ab] > 0)
         & (fm[ab] / np.maximum(ft[ab], 1) >= min_aligned_frac))
        | ((ft[ba] > 0)
           & (fm[ba] / np.maximum(ft[ba], 1) >= min_aligned_frac)))
    has = (fm[ab] > 0) | (fm[ba] > 0)
    keep = gate & has
    val = np.maximum(ani[ab], ani[ba])
    af_ab = fm[ab] / np.maximum(ft[ab], 1)
    af_ba = fm[ba] / np.maximum(ft[ba], 1)
    hazard = keep & _repeat_hazard_mask(af_ab, af_ba, min_aligned_frac)
    if hazard.any():
        i = int(np.flatnonzero(hazard)[0])
        _warn_repeat_merge_hazard(
            int(hazard.sum()), float(max(af_ab[i], af_ba[i])),
            float(min(af_ab[i], af_ba[i])), min_aligned_frac)
    return [float(v) if k_ else None
            for v, k_ in zip(val.tolist(), keep.tolist())]


# Repeat-merge hazard signature (tests/test_repeat_regime.py): the
# gate passes on an aligned fraction that is both MARGINAL (below
# margin x threshold) and ASYMMETRIC (the other direction far lower).
# Genome-wide relatedness aligns a similar fraction in both directions;
# shared repeats/mobile elements align a sliver of each genome and the
# slivers differ with genome size — exactly this shape.
_HAZARD_AF_MARGIN = 2.0
_HAZARD_ASYMMETRY = 3.0


def _repeat_hazard_mask(af_ab, af_ba, min_aligned_frac: float):
    """Vectorized hazard test on aligned-fraction pairs that already
    passed the gate: marginal pass + strong directional asymmetry."""
    hi = np.maximum(af_ab, af_ba)
    lo = np.minimum(af_ab, af_ba)
    return ((hi < _HAZARD_AF_MARGIN * min_aligned_frac)
            & (hi >= _HAZARD_ASYMMETRY * lo))


def _warn_repeat_merge_hazard(count: int, af_hi: float, af_lo: float,
                              min_aligned_frac: float) -> None:
    warnings.warn(
        f"{count} pair(s) passed the aligned-fraction gate marginally "
        f"and asymmetrically (e.g. {af_hi:.3f} vs {af_lo:.3f} against "
        f"threshold {min_aligned_frac:.3f}) — the signature of shared "
        "repeats/mobile elements rather than genome-wide identity; "
        "the reported ANI is the max over directions and may merge "
        "unrelated genomes. Consider raising --min-aligned-fraction "
        "(see the manpage's 'Repeat-driven merges' note).",
        RuntimeWarning, stacklevel=3)


def _combine_bidirectional(
    ab: DirectedANI, ba: DirectedANI, min_aligned_frac: float
) -> Optional[float]:
    """The reference's fastANI-wrapper gate (reference:
    src/fastani.rs:56-65): pass iff EITHER direction's matched-fragment
    fraction >= min_aligned_frac; result is the max ANI."""
    af_ab = ab.frags_matching / max(ab.frags_total, 1)
    af_ba = ba.frags_matching / max(ba.frags_total, 1)
    gate = ((ab.frags_total > 0 and af_ab >= min_aligned_frac)
            or (ba.frags_total > 0 and af_ba >= min_aligned_frac))
    if not gate or (ab.frags_matching == 0 and ba.frags_matching == 0):
        return None
    if bool(_repeat_hazard_mask(af_ab, af_ba, min_aligned_frac)):
        _warn_repeat_merge_hazard(1, max(af_ab, af_ba),
                                  min(af_ab, af_ba), min_aligned_frac)
    return max(ab.ani, ba.ani)


def bidirectional_ani(
    a: GenomeProfile,
    b: GenomeProfile,
    min_aligned_frac: float,
    identity_floor: float = 0.80,
) -> Tuple[Optional[float], DirectedANI, DirectedANI]:
    """Bidirectional max-ANI with the reference's fragment-fraction gate.

    Mirrors the reference's fastANI wrapper (reference:
    src/fastani.rs:31-73): both directions are computed; the pair passes
    iff EITHER direction's matched-fragment fraction >= min_aligned_frac;
    the returned ANI is the max of the two directions. Returns None (gate
    failed / nothing aligned) plus both directed results for callers that
    need them.
    """
    _check_same_subsample(a, b)
    ab = directed_ani(a, b, identity_floor=identity_floor)
    ba = directed_ani(b, a, identity_floor=identity_floor)
    return _combine_bidirectional(ab, ba, min_aligned_frac), ab, ba
