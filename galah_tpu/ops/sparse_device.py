"""Sparse screened pairwise evaluation on the device path.

The CPU backend eliminates the O(N^2) pair wall with the host
inverted-index collision screen (ops/collision.py). This module ports
that two-phase shape to the device backends (TPU and meshes, per the
docs/DISTRIBUTED.md roadmap): the host produces the sparse candidate
list by exact collision counting, and the device evaluates ONLY the
survivors — batched (common, total) pair stats over gathered (i, j)
sketch rows instead of dense (row x col) tiles. This is the screening
idea of the reference's skani preclusterer (reference:
src/skani.rs:54-70) applied to the MinHash pass on device.

Exactness: the collision screen is conservative for merged-bottom-k
Mash (ops/collision.candidate_pairs_minhash proves the bound), and the
gathered-pair device pass computes the identical integer
(common, total) as the dense tiles, so results are bit-identical to
the dense path — pinned by tests/test_sparse_device.py.

Cost model: collision counting is O(NK log NK + colliding pairs) on
host; the device pass is O(S * K log K) for S surviving candidates.
Above ops/collision.SPARSE_SCREEN_MIN_N genomes this replaces the
O(N^2 * K log K / tile-throughput) dense wall whenever similarity is
sparse (real dereplication inputs are: most genome pairs share no
sketch hashes at all).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from galah_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from galah_tpu.obs.profile import profiled
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pairwise import (
    _pair_stats,
    ani_to_jaccard,
    stats_to_ani_f64,
)
from galah_tpu.utils import timing

# Candidate pairs evaluated per device dispatch. Large enough to
# amortize dispatch latency (the gathered rows are B x K u64 reads
# from HBM), small enough that the gather scratch stays tens of MB.
# On TPU the default is 4x larger (HBM is plentiful and each dispatch
# through a remote attach pays real RTT); GALAH_TPU_PAIR_BATCH
# overrides either way.
PAIR_BATCH = 8192

# ---- survivor-evaluation strategy selection (AUTO) -------------------
#
# Three ways to evaluate the screen's survivors, picked per call from
# survivor count and duplication factor (how many pairs each distinct
# sketch row participates in), with the decision and per-strategy waste
# recorded as timing counters in the stage report:
#
#   blocked — the P-pairs-per-program Mosaic pairlist kernel
#             (ops/pallas_pairlist.py), the default device strategy;
#   gather  — permute survivor rows into (GATHER_ROWS x GATHER_COLS)
#             dense tiles and evaluate through the 27.3%-of-ceiling
#             dense kernel (ops/pallas_pairwise.py), ignoring the
#             unused cells; wins only when the survivors are so
#             duplication-heavy (near-clique families) that tile fill
#             beats the blocked kernel's rate;
#   cpu     — a single host-side XLA-CPU evaluation for survivor
#             counts too small to be worth even one device dispatch
#             (each dispatch through a remote attach pays ~66 ms of
#             RTT per BASELINE.md round-5 data).
#
# The rate constants are the round-5 hardware numbers (BASELINE.md
# roofline table): the dense tile measured 218,077 pairs/s; the
# blocked kernel is unmeasured until the next healthy tunnel window
# (scripts/bench_pairlist_variants.py), so its estimate is the design
# target — recalibrate both from hardware, or pin a strategy with
# GALAH_TPU_PAIRLIST_STRATEGY=blocked|gather|xla|cpu.
DENSE_RATE_EST = 218_077.0
BLOCKED_RATE_EST = 200_000.0
GATHER_MIN_DUP = 4.0     # don't even plan tiles below this duplication
PAIRLIST_CPU_MAX = 256   # survivor count where one host eval wins
GATHER_ROWS = 64         # unique a-rows per gather-dense tile
GATHER_COLS = 128        # unique b-rows per gather-dense tile

# Numeric-determinism contract checked by `galah-tpu lint` (GL9xx):
# all survivor-evaluation strategies must agree bit-for-bit on the
# integer (matches, lengths) stats, so the AUTO strategy pick can
# never change clustering output.
DETERMINISM_CONTRACT = {
    "family": "pairlist",
    "dtype": "int32",
    "functions": ["pair_stats_for_pairs", "threshold_pairs_sparse",
                  "_batch_pair_stats", "_gather_dense_pair_stats",
                  "_cpu_pair_stats"],
}


def _default_pair_batch() -> int:
    env = os.environ.get("GALAH_TPU_PAIR_BATCH")
    if env:
        return max(1, int(env))
    return 4 * PAIR_BATCH if jax.default_backend() == "tpu" \
        else PAIR_BATCH


def pair_block_quantum() -> int:
    """Pairs per device evaluation block — callers sizing speculative
    batches (cluster/engine.py) round up to a multiple of this so the
    blocked pairlist kernel's programs run full."""
    from galah_tpu.ops.hll import use_pallas_default

    if not use_pallas_default():
        return 1
    from galah_tpu.ops.pallas_pairlist import pairlist_block_pairs

    return pairlist_block_pairs()


@profiled("sparse.batch_pair_stats")
@functools.partial(
    jax.jit,
    static_argnames=("sketch_size", "use_pallas", "interpret"))
def _batch_pair_stats(jmat: jax.Array, pi: jax.Array, pj: jax.Array,
                      sketch_size: int,
                      use_pallas: bool = False,
                      interpret: bool = False,
                      ) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 for each gathered (pi[b], pj[b]) row pair.

    With use_pallas (the default on a TPU backend) the gathered pairs
    run the Mosaic pairlist kernel (ops/pallas_pairlist.py) instead of
    the vmapped u64 searchsorted — bit-identical integers either way.
    """
    rows = jnp.take(jmat, pi, axis=0)
    cols = jnp.take(jmat, pj, axis=0)
    if use_pallas:
        from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas

        return pair_stats_pairs_pallas(rows, cols, sketch_size,
                                       interpret=interpret)
    return jax.vmap(
        lambda a, b: _pair_stats(a, b, sketch_size))(rows, cols)


@functools.lru_cache(maxsize=8)
def _make_sharded_batch_stats(mesh: Mesh, sketch_size: int,
                              use_pallas: bool = False,
                              interpret: bool = False):
    """SPMD twin: the candidate batch is sharded over the mesh axis,
    the sketch matrix is replicated; each device evaluates its slice
    of the pair list. The per-pair outputs are all-gathered back to a
    replicated (B,) layout so a multi-host run (where P("i") shards
    are not host-addressable) reads the identical arrays on every
    host."""

    def spmd(jmat, pi, pj):
        c, t = _batch_pair_stats(jmat, pi, pj, sketch_size,
                                 use_pallas=use_pallas,
                                 interpret=interpret)
        return (jax.lax.all_gather(c, "i", tiled=True),
                jax.lax.all_gather(t, "i", tiled=True))

    # check_vma off: the outputs ARE replicated post-gather, but the
    # vma type system cannot express that for P() out_specs.
    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P(None, None), P("i"), P("i")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def _plan_gather_segments(spi: np.ndarray, spj: np.ndarray,
                          rows_cap: int = GATHER_ROWS,
                          cols_cap: int = GATHER_COLS):
    """Host plan for the gather-dense strategy: split a (pi, pj)-sorted
    pair list into dense-tile jobs of at most `rows_cap` unique a-rows
    x `cols_cap` unique b-rows. Every job is padded to the fixed caps
    (repeating row 0 — its cells are computed and never read) so all
    segments share ONE compiled tile shape.

    Returns (segments, cells): segments is a list of
    (ua, ub, ra, rb, idx) — gather indices (rows_cap,)/(cols_cap,),
    per-pair tile coordinates, and the pair positions in the sorted
    list; cells is the total padded tile area (the strategy's waste
    denominator). O(S log S) numpy throughout — no per-pair Python."""
    n = spi.shape[0]
    # dense rank of each pair's a-row (pairs are a-sorted, so ranks are
    # a prefix-sum over boundaries) -> blocks of rows_cap distinct a's
    a_rank = np.zeros(n, dtype=np.int64)
    if n > 1:
        a_rank[1:] = np.cumsum(spi[1:] != spi[:-1])
    block = a_rank // rows_cap
    starts = np.flatnonzero(np.r_[True, block[1:] != block[:-1]])
    bounds = np.r_[starts, n]
    segments = []
    for bi in range(len(starts)):
        s, e = int(bounds[bi]), int(bounds[bi + 1])
        ua_vals = np.unique(spi[s:e])
        ub_all = np.unique(spj[s:e])
        ra_all = (a_rank[s:e] - a_rank[s]).astype(np.int32)
        pos_b = np.searchsorted(ub_all, spj[s:e]).astype(np.int64)
        piece = pos_b // cols_cap
        for t in range(int(piece.max()) + 1 if e > s else 0):
            mask = piece == t
            idx = np.flatnonzero(mask) + s
            ua = np.zeros(rows_cap, dtype=np.int32)
            ua[:ua_vals.size] = ua_vals
            ub_piece = ub_all[t * cols_cap:(t + 1) * cols_cap]
            ub = np.zeros(cols_cap, dtype=np.int32)
            ub[:ub_piece.size] = ub_piece
            segments.append((ua, ub, ra_all[mask],
                             (pos_b[mask] - t * cols_cap).astype(np.int32),
                             idx))
    cells = len(segments) * rows_cap * cols_cap
    return segments, cells


@profiled("sparse.gather_tile_stats")
@functools.partial(jax.jit,
                   static_argnames=("sketch_size", "interpret"))
def _gather_tile_stats(jmat: jax.Array, ua: jax.Array, ub: jax.Array,
                       sketch_size: int, interpret: bool = False):
    """One gather-dense tile: permute the survivor rows and run the
    dense Mosaic kernel over the full cross product."""
    from galah_tpu.ops.pallas_pairwise import tile_stats_pallas

    rows = jnp.take(jmat, ua, axis=0)
    cols = jnp.take(jmat, ub, axis=0)
    return tile_stats_pallas(rows, cols, sketch_size,
                             interpret=interpret)


def _gather_dense_pair_stats(
    jmat: jax.Array,
    pi32: np.ndarray,
    pj32: np.ndarray,
    sketch_size: int,
    interpret: bool,
    explicit: bool,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Evaluate the pair list through dense tiles (the gather-dense
    strategy). Returns None when the dense kernel's Mosaic lowering
    fails and the caller should re-run everything on the batched
    fallback path (run_with_pallas_fallback policy: an explicit pin
    propagates the failure instead)."""
    from galah_tpu.ops._fallback import run_with_pallas_fallback

    n_pairs = pi32.shape[0]
    order = np.lexsort((pj32, pi32))
    spi, spj = pi32[order], pj32[order]
    segments, cells = _plan_gather_segments(spi, spj)
    timing.counter("pairlist-gather-cells", int(cells))
    timing.counter("pairlist-gather-used", int(n_pairs))

    common = np.empty(n_pairs, dtype=np.int32)
    total = np.empty(n_pairs, dtype=np.int32)

    def eval_seg(seg, pallas: bool):
        ua, ub, ra, rb, idx = seg
        if not pallas:
            raise RuntimeError(
                "gather-dense has no non-Mosaic form")  # pragma: no cover
        timing.dispatch()
        return _gather_tile_stats(jmat, jnp.asarray(ua),
                                  jnp.asarray(ub), sketch_size,
                                  interpret=interpret)

    def store_seg(seg, c, t):
        ua, ub, ra, rb, idx = seg
        timing.dispatch(sync=True)
        common[order[idx]] = np.asarray(c)[ra, rb]
        total[order[idx]] = np.asarray(t)[ra, rb]

    # First tile eagerly through the fallback gate: a lowering failure
    # here downgrades the whole strategy (return None -> caller redoes
    # on the batched path) instead of half-filling the output.
    try:
        (c0, t0), pallas_used = run_with_pallas_fallback(
            "gather-dense tile kernel", explicit, True,
            lambda p: tuple(np.asarray(x)
                            for x in eval_seg(segments[0], p)))
    except RuntimeError:
        if explicit:
            raise
        return None
    if not pallas_used:  # pragma: no cover - fallback gate returned XLA
        return None
    store_seg(segments[0], c0, t0)

    # Remaining tiles ride JAX's async dispatch queue; materialization
    # failures downgrade the whole call (rare, mirrors downgrade_and_
    # redo's recompute-everything-after-the-fault semantics).
    futs = [(seg, eval_seg(seg, True)) for seg in segments[1:]]
    try:
        for seg, (c, t) in futs:
            store_seg(seg, c, t)
    except Exception:
        if explicit:
            raise
        return None
    return common, total


def _cpu_pair_stats(sketch_mat: np.ndarray, pi32: np.ndarray,
                    pj32: np.ndarray, sketch_size: int,
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Tiny survivor lists: one XLA-CPU evaluation on host — no device
    dispatch, no batching, no padding."""
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        c, t = _batch_pair_stats(
            jax.device_put(
                np.ascontiguousarray(sketch_mat, dtype=np.uint64), cpu),
            jax.device_put(pi32, cpu), jax.device_put(pj32, cpu),
            sketch_size, use_pallas=False)
        return np.asarray(c), np.asarray(t)


def _resolve_pairlist_strategy(
    pi32: np.ndarray,
    pj32: np.ndarray,
    use_pallas: bool,
    explicit: bool,
    mesh: Optional[Mesh],
    batch: Optional[int],
) -> str:
    """AUTO strategy pick from survivor count and duplication factor.

    GALAH_TPU_PAIRLIST_STRATEGY pins it. AUTO only deviates from the
    historical batched path when nothing else is pinned: an explicit
    use_pallas, a mesh, or a caller batch size all mean the caller
    chose a shape — keep it (and parity/fault tests rely on that)."""
    env = os.environ.get("GALAH_TPU_PAIRLIST_STRATEGY", "").lower()
    if env in ("blocked", "gather", "xla", "cpu"):
        return env
    if not use_pallas:
        return "xla"
    if explicit or batch is not None or (
            mesh is not None and mesh.devices.size > 1):
        return "blocked"
    n_pairs = int(pi32.shape[0])
    if n_pairs <= PAIRLIST_CPU_MAX:
        return "cpu"
    uniq = np.union1d(pi32, pj32).size
    dup = n_pairs / max(uniq, 1)
    if dup < GATHER_MIN_DUP:
        return "blocked"
    order = np.lexsort((pj32, pi32))
    _, cells = _plan_gather_segments(pi32[order], pj32[order])
    fill = n_pairs / max(cells, 1)
    if fill * DENSE_RATE_EST > BLOCKED_RATE_EST:
        return "gather"
    return "blocked"


def pair_stats_for_pairs(
    sketch_mat: np.ndarray,
    pi: np.ndarray,
    pj: np.ndarray,
    sketch_size: int,
    mesh: Optional[Mesh] = None,
    batch: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact merged-bottom-k (common, total) for an explicit pair list.

    One device dispatch per `batch` candidates (fixed shape, so the
    trace compiles once); the final partial batch is padded with pair
    (0, 0) and trimmed on host. With a multi-device `mesh` the batch is
    sharded over the mesh axis. use_pallas selects the Mosaic pairlist
    kernel (default: on for TPU backends, with XLA fallback on a
    lowering failure — explicit True pins it, failures propagate).

    On the default path an AUTO strategy pick (see the module's
    strategy block) may reroute the evaluation through the gather-dense
    tiles or a single host XLA-CPU shot; the decision and each
    strategy's waste land in the timing counters
    (pairlist-strategy-*, pairlist-gather-cells/used,
    pairlist-pad-slots, pairlist-blocked-pad-pairs). All strategies
    produce bit-identical integers (tests/test_pallas_pairlist.py).
    """
    n_pairs = int(pi.shape[0])
    common = np.empty(n_pairs, dtype=np.int32)
    total = np.empty(n_pairs, dtype=np.int32)
    if n_pairs == 0:
        return common, total

    explicit = use_pallas is not None
    if use_pallas is None:
        from galah_tpu.ops.hll import use_pallas_default

        use_pallas = use_pallas_default()

    pi32 = np.ascontiguousarray(pi, dtype=np.int32)
    pj32 = np.ascontiguousarray(pj, dtype=np.int32)
    strategy = _resolve_pairlist_strategy(pi32, pj32, bool(use_pallas),
                                          explicit, mesh, batch)
    timing.counter(f"pairlist-strategy-{strategy}", 1)
    if strategy == "cpu":
        return _cpu_pair_stats(sketch_mat, pi32, pj32, sketch_size)
    if strategy == "xla":
        use_pallas = False

    jmat = jnp.asarray(np.ascontiguousarray(sketch_mat, dtype=np.uint64))
    if strategy == "gather":
        got = _gather_dense_pair_stats(jmat, pi32, pj32, sketch_size,
                                       interpret, explicit)
        if got is not None:
            return got
        # dense-kernel downgrade: the batched XLA path below redoes
        # everything (mirror of downgrade_and_redo)
        use_pallas = False
        timing.counter("pairlist-gather-downgraded", 1)

    n_dev = mesh.devices.size if mesh is not None else 1
    if batch is None:
        batch = _default_pair_batch()
    b = -(-batch // n_dev) * n_dev

    def make_fn(pallas: bool):
        if mesh is not None and n_dev > 1:
            return _make_sharded_batch_stats(mesh, sketch_size, pallas,
                                             interpret=interpret)
        return functools.partial(_batch_pair_stats,
                                 sketch_size=sketch_size,
                                 use_pallas=pallas,
                                 interpret=interpret)

    from galah_tpu.ops._fallback import run_with_pallas_fallback

    starts = list(range(0, n_pairs, b))
    # Waste on the record: zero-padded slots in the final partial batch
    # plus, on the blocked kernel path, the sentinel pairs each
    # dispatch adds to fill its last P-pair program.
    timing.counter("pairlist-pad-slots", len(starts) * b - n_pairs)
    if use_pallas:
        from galah_tpu.ops.pallas_pairlist import pairlist_block_pairs

        timing.counter("pairlist-blocked-pad-pairs",
                       len(starts) * (-b % pairlist_block_pairs()))

    def dispatch(fn, s):
        e = min(s + b, n_pairs)
        bi = np.zeros(b, dtype=np.int32)
        bj = np.zeros(b, dtype=np.int32)
        bi[: e - s] = pi32[s:e]
        bj[: e - s] = pj32[s:e]
        timing.dispatch()
        return fn(jmat, jnp.asarray(bi), jnp.asarray(bj))

    def store(s, c, t):
        e = min(s + b, n_pairs)
        timing.dispatch(sync=True)
        common[s:e] = np.asarray(c)[: e - s]
        total[s:e] = np.asarray(t)[: e - s]

    # First batch materializes eagerly: Mosaic lowering/runtime
    # failures surface here, where the fallback can still downgrade
    # every remaining batch cheaply.
    (c0, t0), use_pallas = run_with_pallas_fallback(
        "pairlist kernel", explicit, bool(use_pallas),
        lambda p: tuple(np.asarray(x)
                        for x in dispatch(make_fn(p), starts[0])))
    store(starts[0], c0, t0)

    # Remaining batches PIPELINE with a bounded in-flight window:
    # dispatches run ahead of the ordered host syncs so each sync's
    # round trip (50-150 ms through a remote attach) overlaps the next
    # batches' compute, while the window caps live device buffers —
    # a mega-run can carry 100k+ batches, so unbounded queueing would
    # hold O(n_batches * batch) device memory.
    from collections import deque

    fn = make_fn(bool(use_pallas))
    window = 16
    inflight: deque = deque()
    todo = iter(starts[1:])

    def downgrade_and_redo(failed_starts, was_pallas):
        # A rare runtime (post-lowering) Mosaic failure — at enqueue or
        # at host materialization: redo the failed batch and every
        # remaining one on the XLA path, mirroring the first batch's
        # run_with_pallas_fallback policy. `was_pallas` is the path the
        # FAILING batch was dispatched on — an earlier drain may have
        # downgraded the globals already, and that must not turn a
        # recoverable Mosaic failure into a hard raise.
        nonlocal use_pallas, fn
        if explicit or not was_pallas:
            raise  # noqa: PLE0704 - re-raise the active exception
        if use_pallas:
            use_pallas = False
            fn = make_fn(False)
        inflight.clear()
        for s2 in failed_starts:
            c2, t2 = dispatch(fn, s2)
            store(s2, c2, t2)

    def drain_one():
        s, fut, was_pallas = inflight.popleft()
        try:
            c, t = fut
            store(s, c, t)
        except Exception:
            downgrade_and_redo(
                [s] + [s2 for s2, _, _ in inflight], was_pallas)

    for s in todo:
        try:
            inflight.append((s, dispatch(fn, s), bool(use_pallas)))
        except Exception:
            # enqueue-time failure: settle what's already in flight,
            # then redo this batch and the rest (on the XLA path when
            # the failing dispatch was a Mosaic one)
            was_pallas = bool(use_pallas)
            while inflight:
                drain_one()
            downgrade_and_redo([s] + list(todo), was_pallas)
            break
        if len(inflight) >= window:
            drain_one()
    while inflight:
        drain_one()
    return common, total


def threshold_pairs_sparse(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    sketch_size: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    batch: Optional[int] = None,
) -> dict:
    """Sparse {(i, j): ani} for i<j pairs with ani >= min_ani — the
    screened device pipeline: host collision screen, batched gathered
    pair stats on device, exact f64 integer-Jaccard check on host.

    Bit-identical to ops/pairwise.threshold_pairs' dense tiled path
    (same integers, same f64 keep-check and ANI), selected by it above
    ops/collision.SPARSE_SCREEN_MIN_N genomes on device backends.
    """
    from galah_tpu.ops.collision import candidate_pairs_minhash

    mat = np.ascontiguousarray(sketch_mat, dtype=np.uint64)
    n = mat.shape[0]
    if sketch_size is None:
        sketch_size = mat.shape[1]
    lens = (mat != np.uint64(SENTINEL)).sum(axis=1).astype(np.int64)
    j_thr = ani_to_jaccard(min_ani, k)
    pi, pj = candidate_pairs_minhash(mat, lens, j_thr, sketch_size)
    # Survivor economics on the record (BASELINE.md dense-kernel
    # decision): candidates = pairs the exact device pass must
    # evaluate, out of n*(n-1)/2 possible.
    timing.counter("screen-candidates", int(pi.shape[0]))
    timing.counter("screen-possible-pairs", n * (n - 1) // 2)
    del n  # candidates are already in-bounds i < j < n
    if pi.shape[0] == 0:
        return {}
    common, total = pair_stats_for_pairs(
        mat, pi, pj, sketch_size, mesh=mesh, batch=batch)
    common = common.astype(np.int64)
    total = total.astype(np.int64)
    keep = common.astype(np.float64) >= j_thr * total
    timing.counter("screen-kept-pairs", int(keep.sum()))
    from galah_tpu.obs import metrics as obs_metrics

    obs_metrics.gauge(
        "screen.survival_rate",
        help="Fraction of screened candidate pairs the threshold "
             "kept (last screening pass)", unit="fraction").set(
        float(keep.sum()) / pi.shape[0] if pi.shape[0] else 0.0)
    ani = stats_to_ani_f64(common[keep], total[keep], k)
    return {(int(a), int(b)): float(v)
            for a, b, v in zip(pi[keep], pj[keep], ani)}
