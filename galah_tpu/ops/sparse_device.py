"""Sparse screened pairwise evaluation on the device path.

The CPU backend eliminates the O(N^2) pair wall with the host
inverted-index collision screen (ops/collision.py). This module ports
that two-phase shape to the device backends (TPU and meshes, per the
docs/DISTRIBUTED.md roadmap): the host produces the sparse candidate
list by exact collision counting, and the device evaluates ONLY the
survivors — batched (common, total) pair stats over gathered (i, j)
sketch rows instead of dense (row x col) tiles. This is the screening
idea of the reference's skani preclusterer (reference:
src/skani.rs:54-70) applied to the MinHash pass on device.

Exactness: the collision screen is conservative for merged-bottom-k
Mash (ops/collision.candidate_pairs_minhash proves the bound), and the
gathered-pair device pass computes the identical integer
(common, total) as the dense tiles, so results are bit-identical to
the dense path — pinned by tests/test_sparse_device.py.

Cost model: collision counting is O(NK log NK + colliding pairs) on
host; the device pass is O(S * K log K) for S surviving candidates.
Above ops/collision.SPARSE_SCREEN_MIN_N genomes this replaces the
O(N^2 * K log K / tile-throughput) dense wall whenever similarity is
sparse (real dereplication inputs are: most genome pairs share no
sketch hashes at all).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from galah_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pairwise import (
    _pair_stats,
    ani_to_jaccard,
    stats_to_ani_f64,
)
from galah_tpu.utils import timing

# Candidate pairs evaluated per device dispatch. Large enough to
# amortize dispatch latency (the gathered rows are B x K u64 reads
# from HBM), small enough that the gather scratch stays tens of MB.
# On TPU the default is 4x larger (HBM is plentiful and each dispatch
# through a remote attach pays real RTT); GALAH_TPU_PAIR_BATCH
# overrides either way.
PAIR_BATCH = 8192


def _default_pair_batch() -> int:
    import os

    env = os.environ.get("GALAH_TPU_PAIR_BATCH")
    if env:
        return max(1, int(env))
    return 4 * PAIR_BATCH if jax.default_backend() == "tpu" \
        else PAIR_BATCH


@functools.partial(
    jax.jit,
    static_argnames=("sketch_size", "use_pallas", "interpret"))
def _batch_pair_stats(jmat: jax.Array, pi: jax.Array, pj: jax.Array,
                      sketch_size: int,
                      use_pallas: bool = False,
                      interpret: bool = False,
                      ) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 for each gathered (pi[b], pj[b]) row pair.

    With use_pallas (the default on a TPU backend) the gathered pairs
    run the Mosaic pairlist kernel (ops/pallas_pairlist.py) instead of
    the vmapped u64 searchsorted — bit-identical integers either way.
    """
    rows = jnp.take(jmat, pi, axis=0)
    cols = jnp.take(jmat, pj, axis=0)
    if use_pallas:
        from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas

        return pair_stats_pairs_pallas(rows, cols, sketch_size,
                                       interpret=interpret)
    return jax.vmap(
        lambda a, b: _pair_stats(a, b, sketch_size))(rows, cols)


@functools.lru_cache(maxsize=8)
def _make_sharded_batch_stats(mesh: Mesh, sketch_size: int,
                              use_pallas: bool = False,
                              interpret: bool = False):
    """SPMD twin: the candidate batch is sharded over the mesh axis,
    the sketch matrix is replicated; each device evaluates its slice
    of the pair list. The per-pair outputs are all-gathered back to a
    replicated (B,) layout so a multi-host run (where P("i") shards
    are not host-addressable) reads the identical arrays on every
    host."""

    def spmd(jmat, pi, pj):
        c, t = _batch_pair_stats(jmat, pi, pj, sketch_size,
                                 use_pallas=use_pallas,
                                 interpret=interpret)
        return (jax.lax.all_gather(c, "i", tiled=True),
                jax.lax.all_gather(t, "i", tiled=True))

    # check_vma off: the outputs ARE replicated post-gather, but the
    # vma type system cannot express that for P() out_specs.
    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(P(None, None), P("i"), P("i")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def pair_stats_for_pairs(
    sketch_mat: np.ndarray,
    pi: np.ndarray,
    pj: np.ndarray,
    sketch_size: int,
    mesh: Optional[Mesh] = None,
    batch: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact merged-bottom-k (common, total) for an explicit pair list.

    One device dispatch per `batch` candidates (fixed shape, so the
    trace compiles once); the final partial batch is padded with pair
    (0, 0) and trimmed on host. With a multi-device `mesh` the batch is
    sharded over the mesh axis. use_pallas selects the Mosaic pairlist
    kernel (default: on for TPU backends, with XLA fallback on a
    lowering failure — explicit True pins it, failures propagate).
    """
    n_pairs = int(pi.shape[0])
    common = np.empty(n_pairs, dtype=np.int32)
    total = np.empty(n_pairs, dtype=np.int32)
    if n_pairs == 0:
        return common, total

    explicit = use_pallas is not None
    if use_pallas is None:
        from galah_tpu.ops.hll import use_pallas_default

        use_pallas = use_pallas_default()

    jmat = jnp.asarray(np.ascontiguousarray(sketch_mat, dtype=np.uint64))
    n_dev = mesh.devices.size if mesh is not None else 1
    if batch is None:
        batch = _default_pair_batch()
    b = -(-batch // n_dev) * n_dev

    def make_fn(pallas: bool):
        if mesh is not None and n_dev > 1:
            return _make_sharded_batch_stats(mesh, sketch_size, pallas,
                                             interpret=interpret)
        return functools.partial(_batch_pair_stats,
                                 sketch_size=sketch_size,
                                 use_pallas=pallas,
                                 interpret=interpret)

    from galah_tpu.ops._fallback import run_with_pallas_fallback

    pi32 = np.ascontiguousarray(pi, dtype=np.int32)
    pj32 = np.ascontiguousarray(pj, dtype=np.int32)
    starts = list(range(0, n_pairs, b))

    def dispatch(fn, s):
        e = min(s + b, n_pairs)
        bi = np.zeros(b, dtype=np.int32)
        bj = np.zeros(b, dtype=np.int32)
        bi[: e - s] = pi32[s:e]
        bj[: e - s] = pj32[s:e]
        timing.dispatch()
        return fn(jmat, jnp.asarray(bi), jnp.asarray(bj))

    def store(s, c, t):
        e = min(s + b, n_pairs)
        timing.dispatch(sync=True)
        common[s:e] = np.asarray(c)[: e - s]
        total[s:e] = np.asarray(t)[: e - s]

    # First batch materializes eagerly: Mosaic lowering/runtime
    # failures surface here, where the fallback can still downgrade
    # every remaining batch cheaply.
    (c0, t0), use_pallas = run_with_pallas_fallback(
        "pairlist kernel", explicit, bool(use_pallas),
        lambda p: tuple(np.asarray(x)
                        for x in dispatch(make_fn(p), starts[0])))
    store(starts[0], c0, t0)

    # Remaining batches PIPELINE with a bounded in-flight window:
    # dispatches run ahead of the ordered host syncs so each sync's
    # round trip (50-150 ms through a remote attach) overlaps the next
    # batches' compute, while the window caps live device buffers —
    # a mega-run can carry 100k+ batches, so unbounded queueing would
    # hold O(n_batches * batch) device memory.
    from collections import deque

    fn = make_fn(bool(use_pallas))
    window = 16
    inflight: deque = deque()
    todo = iter(starts[1:])

    def downgrade_and_redo(failed_starts, was_pallas):
        # A rare runtime (post-lowering) Mosaic failure — at enqueue or
        # at host materialization: redo the failed batch and every
        # remaining one on the XLA path, mirroring the first batch's
        # run_with_pallas_fallback policy. `was_pallas` is the path the
        # FAILING batch was dispatched on — an earlier drain may have
        # downgraded the globals already, and that must not turn a
        # recoverable Mosaic failure into a hard raise.
        nonlocal use_pallas, fn
        if explicit or not was_pallas:
            raise  # noqa: PLE0704 - re-raise the active exception
        if use_pallas:
            use_pallas = False
            fn = make_fn(False)
        inflight.clear()
        for s2 in failed_starts:
            c2, t2 = dispatch(fn, s2)
            store(s2, c2, t2)

    def drain_one():
        s, fut, was_pallas = inflight.popleft()
        try:
            c, t = fut
            store(s, c, t)
        except Exception:
            downgrade_and_redo(
                [s] + [s2 for s2, _, _ in inflight], was_pallas)

    for s in todo:
        try:
            inflight.append((s, dispatch(fn, s), bool(use_pallas)))
        except Exception:
            # enqueue-time failure: settle what's already in flight,
            # then redo this batch and the rest (on the XLA path when
            # the failing dispatch was a Mosaic one)
            was_pallas = bool(use_pallas)
            while inflight:
                drain_one()
            downgrade_and_redo([s] + list(todo), was_pallas)
            break
        if len(inflight) >= window:
            drain_one()
    while inflight:
        drain_one()
    return common, total


def threshold_pairs_sparse(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    sketch_size: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    batch: Optional[int] = None,
) -> dict:
    """Sparse {(i, j): ani} for i<j pairs with ani >= min_ani — the
    screened device pipeline: host collision screen, batched gathered
    pair stats on device, exact f64 integer-Jaccard check on host.

    Bit-identical to ops/pairwise.threshold_pairs' dense tiled path
    (same integers, same f64 keep-check and ANI), selected by it above
    ops/collision.SPARSE_SCREEN_MIN_N genomes on device backends.
    """
    from galah_tpu.ops.collision import candidate_pairs_minhash

    mat = np.ascontiguousarray(sketch_mat, dtype=np.uint64)
    n = mat.shape[0]
    if sketch_size is None:
        sketch_size = mat.shape[1]
    lens = (mat != np.uint64(SENTINEL)).sum(axis=1).astype(np.int64)
    j_thr = ani_to_jaccard(min_ani, k)
    pi, pj = candidate_pairs_minhash(mat, lens, j_thr, sketch_size)
    # Survivor economics on the record (BASELINE.md dense-kernel
    # decision): candidates = pairs the exact device pass must
    # evaluate, out of n*(n-1)/2 possible.
    timing.counter("screen-candidates", int(pi.shape[0]))
    timing.counter("screen-possible-pairs", n * (n - 1) // 2)
    del n  # candidates are already in-bounds i < j < n
    if pi.shape[0] == 0:
        return {}
    common, total = pair_stats_for_pairs(
        mat, pi, pj, sketch_size, mesh=mesh, batch=batch)
    common = common.astype(np.int64)
    total = total.astype(np.int64)
    keep = common.astype(np.float64) >= j_thr * total
    timing.counter("screen-kept-pairs", int(keep.sum()))
    ani = stats_to_ani_f64(common[keep], total[keep], k)
    return {(int(a), int(b)): float(v)
            for a, b, v in zip(pi[keep], pj[keep], ani)}
