"""ctypes binding for the native MinHash sketcher (csrc/sketch.c).

Exposes

    sketch_bottomk(codes, contig_offsets, k, sketch_size, seed, algo)
        -> uint64[<=sketch_size] sorted distinct bottom-k hashes
    positional_hashes(codes, contig_offsets, k, seed, algo)
        -> uint64[n-k+1] genome-order hashes, SENTINEL where invalid

bit-identical to the JAX pipelines (ops/minhash.py,
ops/fragment_ani.py) for both hash algorithms and full 64-bit seeds —
the CPU-backend fast path for sketching (reference analog: finch's
compiled sketching, src/finch.rs:33-47). Build/load failures raise
ImportError (cached by utils/cbuild); set GALAH_TPU_NO_CSKETCH=1 to
force the JAX path.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from galah_tpu.utils import cbuild

_lib = cbuild.build_and_load(
    "sketch.c", "_libsketch",
    out_dir=os.path.dirname(os.path.abspath(__file__)),
    disable_env="GALAH_TPU_NO_CSKETCH")

_ALGOS = {"murmur3": 0, "tpufast": 1}

_fn = _lib.galah_sketch_bottomk
_fn.restype = ctypes.c_int64
_fn.argtypes = [
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
    ctypes.POINTER(ctypes.c_uint64),
]
_fn_pos = _lib.galah_positional_hashes
_fn_pos.restype = ctypes.c_int64
_fn_pos.argtypes = [
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
    ctypes.POINTER(ctypes.c_uint64),
]


_fn_pos_masked = _lib.galah_positional_hashes_masked
_fn_pos_masked.restype = ctypes.c_int64
_fn_pos_masked.argtypes = [
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64,
    ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ctypes.POINTER(ctypes.c_int64),
]

_fn_pos_profile = _lib.galah_positional_hashes_profile
_fn_pos_profile.restype = ctypes.c_int64
_fn_pos_profile.argtypes = [
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64,
    ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
]


_fn_hll = _lib.galah_hll_registers
_fn_hll.restype = ctypes.c_int64
_fn_hll.argtypes = [
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
    ctypes.POINTER(ctypes.c_uint8),
]


def hll_registers(codes: np.ndarray, contig_offsets, k: int, p: int,
                  seed: int, algo: str) -> np.ndarray:
    """(2^p,) uint8 HLL registers over the genome's canonical k-mers —
    C twin of ops/hll.hll_sketch_genome."""
    _check(algo, k)
    if not 1 <= p <= 24:
        raise ValueError(f"p must be in [1, 24], got {p}")
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    offs = np.ascontiguousarray(contig_offsets, dtype=np.int64)
    regs = np.zeros(1 << p, dtype=np.uint8)
    _fn_hll(codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            codes.shape[0],
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            offs.shape[0], int(k), int(p),
            int(seed) & 0xFFFFFFFFFFFFFFFF, _ALGOS[algo],
            regs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return regs


def _check(algo: str, k: int) -> None:
    if algo not in _ALGOS:
        raise ValueError(f"unknown hash algorithm {algo!r}")
    if not 1 <= k <= 32:
        raise ValueError(f"k must be in [1, 32], got {k}")


def sketch_bottomk(codes: np.ndarray, contig_offsets, k: int,
                   sketch_size: int, seed: int, algo: str) -> np.ndarray:
    """Sorted distinct bottom-k canonical k-mer hashes of a genome."""
    _check(algo, k)
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    offs = np.ascontiguousarray(contig_offsets, dtype=np.int64)
    out = np.empty(sketch_size, dtype=np.uint64)
    n = _fn(codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            codes.shape[0],
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            offs.shape[0], int(k), int(sketch_size),
            int(seed) & 0xFFFFFFFFFFFFFFFF, _ALGOS[algo],
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    if n < 0:
        raise MemoryError("native sketcher allocation failed")
    return out[:n].copy()


def positional_hashes(codes: np.ndarray, contig_offsets, k: int,
                      seed: int = 0,
                      algo: str = "murmur3") -> np.ndarray:
    """Every window's canonical hash in genome order (SENTINEL where
    invalid) — C twin of ops/fragment_ani.positional_hashes."""
    _check(algo, k)
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    offs = np.ascontiguousarray(contig_offsets, dtype=np.int64)
    n = codes.shape[0]
    if n < k:
        return np.zeros(0, dtype=np.uint64)
    out = np.empty(n - k + 1, dtype=np.uint64)
    got = _fn_pos(
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offs.shape[0], int(k), int(seed) & 0xFFFFFFFFFFFFFFFF,
        _ALGOS[algo],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return out[:max(got, 0)]


def positional_hashes_masked(
        codes: np.ndarray, contig_offsets, k: int, cut: int,
        seed: int = 0,
        algo: str = "murmur3") -> "tuple[np.ndarray, np.ndarray]":
    """(flat, valid): every window's canonical hash with the
    FracMinHash mask (hashes >= cut -> SENTINEL; cut=0 keeps all) and
    the kept hashes compacted in genome order — the profile build's
    hash walk and host post-pass in one C pass. Bit-identical to
    positional_hashes + np.where + the != SENTINEL filter."""
    _check(algo, k)
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    offs = np.ascontiguousarray(contig_offsets, dtype=np.int64)
    n = codes.shape[0]
    if n < k:
        return (np.zeros(0, dtype=np.uint64),
                np.zeros(0, dtype=np.uint64))
    out = np.empty(n - k + 1, dtype=np.uint64)
    valid = np.empty(n - k + 1, dtype=np.uint64)
    n_valid = ctypes.c_int64(0)
    got = _fn_pos_masked(
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offs.shape[0], int(k), int(seed) & 0xFFFFFFFFFFFFFFFF,
        _ALGOS[algo], int(cut) & 0xFFFFFFFFFFFFFFFF,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.byref(n_valid))
    return out[:max(got, 0)], valid[:n_valid.value].copy()


def positional_hashes_profile(
        codes: np.ndarray, contig_offsets, k: int, cut: int,
        seed: int = 0, algo: str = "murmur3",
) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """(flat, valid, pos): positional_hashes_masked plus the kept
    hashes' positions — the (pos, hash) pairs drive the O(n_valid)
    window assembly (ops/_cpairstats.windows_from_pairs), replacing
    two full streaming passes over the flat array."""
    _check(algo, k)
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    offs = np.ascontiguousarray(contig_offsets, dtype=np.int64)
    n = codes.shape[0]
    if n < k:
        return (np.zeros(0, dtype=np.uint64),
                np.zeros(0, dtype=np.uint64),
                np.zeros(0, dtype=np.int64))
    out = np.empty(n - k + 1, dtype=np.uint64)
    valid = np.empty(n - k + 1, dtype=np.uint64)
    pos = np.empty(n - k + 1, dtype=np.int64)
    n_valid = ctypes.c_int64(0)
    got = _fn_pos_profile(
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offs.shape[0], int(k), int(seed) & 0xFFFFFFFFFFFFFFFF,
        _ALGOS[algo], int(cut) & 0xFFFFFFFFFFFFFFFF,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(n_valid))
    nv = n_valid.value
    return (out[:max(got, 0)], valid[:nv].copy(), pos[:nv].copy())

