"""Streaming ingest->sketch pipeline: storage-bound, not dispatch-bound.

BASELINE's 100k rung left sketching as the dominant wall term (~7-8
Mbp/s, far below disk bandwidth): the serial shape read-everything ->
sketch-everything leaves the disk idle while the device hashes and the
device idle while the host parses. This module makes the sketch stage a
three-stage stream instead:

  stage 1  ingest    — FASTA parse on the shared prefetch pool
                       (io/prefetch.py; the C parser in csrc/ingest.c
                       already streams gzip), bounded look-ahead;
  stage 2  staging   — host-side packing of genome groups into the
                       device layout (2-bit codes + ambiguity masks +
                       offsets), double-buffered on the same pool so
                       the NEXT batch packs while the previous batch's
                       launch runs;
  stage 3  sketch    — one device dispatch per packed group under the
                       resolved strategy (below).

Memory stays O(depth + workers) genomes: stage 1 holds at most `depth`
parsed genomes ahead, stage 2 at most 2 staged batches, and nothing
else accumulates (sketches are ~8 KB each).

Strategy (GALAH_TPU_SKETCH_STRATEGY pin; unset resolves per backend):

  fused — ops/pallas_sketch.fused_sketch_candidates: ONE Pallas launch
          hashes a whole packed group and reduces it in-kernel to
          per-class distinct-minima candidates, so per-chunk hashes
          never round-trip through an XLA top-k. The XLA post-pass
          checks the completeness certificate; the rare "suspect" job
          re-runs on the exact chunked path — fused sketches are
          therefore BIT-IDENTICAL to the other strategies, always.
  xla   — ops/minhash's chunked/batched XLA kernels (hash -> sort ->
          distinct bottom-k), the historical device path.
  c     — csrc/sketch.c's host bottom-k sketcher, the historical
          single-device-CPU path.

An explicit pin propagates failures (parity runs must never silently
compare a fallback to itself); AUTO demotes fused -> xla once per
process on a Mosaic failure, with a `sketch-fused-demoted` event.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from galah_tpu.config import Defaults
from galah_tpu.obs.profile import profiled
from galah_tpu.ops import hashing
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.minhash import (
    DEFAULT_CHUNK,
    sketch_genome_device,
    sketch_genomes_device_batch,
)
from galah_tpu.ops.minhash_np import MinHashSketch
from galah_tpu.ops.pallas_sketch import (
    BLOCK_SUB,
    CAND_SUB,
    LANES,
    R_REG,
    fused_sketch_candidates,
)
from galah_tpu.obs import flow as obs_flow
from galah_tpu.utils import timing

#: Max total positions per fused launch. Each position ships
#: 2 * n_words + 1 uint32 planes to the kernel (28 B/position for
#: murmur3), so this bounds the staged-buffer and device operand
#: footprint at ~120 MB while still amortizing the launch over many
#: genomes.
FUSED_BUDGET = 1 << 22

#: Positions per (BLOCK_SUB, LANES) kernel block.
_BLOCK = BLOCK_SUB * LANES

#: Candidates per job the fused kernel emits.
_CAND = R_REG * CAND_SUB * LANES

#: Job-count floor for pow2 padding (compile-variant bounding, the
#: pallas_fragment recipe).
_JOB_FLOOR = 8

SKETCH_STRATEGIES = ("fused", "xla", "c")

# Determinism contract, machine-checked by `galah-tpu lint` (GL9xx):
# all three strategies produce bit-identical uint64 sketches — fused
# via the completeness certificate + exact re-sketch of suspect jobs,
# never via float accumulation order.
DETERMINISM_CONTRACT = {
    "family": "sketch",
    "dtype": "uint64",
    "functions": ["resolve_sketch_strategy", "sketch_genomes_fused",
                  "iter_path_sketches"],
}

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx):
# stage-2 packing and stage-1 ingest run on io/prefetch's shared pool
# (its own GUARDED_BY covers the pool); this module's only shared
# mutable state is the once-per-process fused demotion latch.
GUARDED_BY = {
    "_DEMOTED": "_DEMOTE_LOCK",
}
LOCK_ORDER = ["_DEMOTE_LOCK"]

# Pipeline contract, machine-checked by `galah-tpu lint` (GL10xx):
# these stages are generators that must stay streamed (GL1001/GL1002),
# and this module feeds the occupancy gauge that proves the overlap
# (GL1004; the ROADMAP's "no stage starves" target).
PIPELINE_STAGE = {
    "streaming": ["iter_path_sketches", "iter_sketch_row_blocks"],
    "occupancy_gauge": "workload.pipeline_occupancy",
}

_DEMOTE_LOCK = threading.Lock()
_DEMOTED = False


def _c_sketcher_available() -> bool:
    try:
        from galah_tpu.ops import _csketch  # noqa: F401
    except Exception:  # pragma: no cover - import error == no C
        return False
    return True


def resolve_sketch_strategy(
    backend: Optional[str] = None,
    n_devices: Optional[int] = None,
    c_ok: Optional[bool] = None,
) -> Tuple[str, bool]:
    """(strategy, explicit) for the sketch stage.

    An explicit GALAH_TPU_SKETCH_STRATEGY pin always wins (and its
    failures propagate). AUTO keeps the historical winners: the C
    bottom-k sketcher on a single-device CPU runtime, the fused Pallas
    kernel on a real TPU backend, the chunked/batched XLA path
    everywhere else. The injectable parameters exist for selection
    tests; production callers pass nothing.
    """
    env = (os.environ.get("GALAH_TPU_SKETCH_STRATEGY") or "").lower()
    if env in SKETCH_STRATEGIES:
        return env, True
    backend = jax.default_backend() if backend is None else backend
    n_devices = jax.device_count() if n_devices is None else n_devices
    if c_ok is None:
        c_ok = _c_sketcher_available()
    if backend == "cpu" and n_devices == 1 and c_ok:
        return "c", False
    from galah_tpu.ops.hll import use_pallas_default

    if backend == "tpu" and use_pallas_default():
        return "fused", False
    return "xla", False


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _fused_group_sketch_jit(packed, ambits, offsets, k: int, seed: int,
                            algo: str, sketch_size: int, span: int,
                            interpret: bool):
    """One fused dispatch over a packed genome group: XLA preamble
    (unpack + canonical key words), the Pallas hash+reduce launch, and
    the tiny candidate post-pass (sort + dedup + certificate) — all in
    one jit. Returns (sketches (G, sketch_size) uint64 ascending with
    sentinel padding, suspect (G,) bool).

    The certificate: T = the sketch_size-th smallest distinct
    candidate; a job is suspect iff any class's final largest register
    is < T (that class filled up below T and may have dropped a
    distinct value the true bottom-k needs). Non-suspect jobs are
    PROVABLY exact; suspect jobs re-run on the chunked path.
    """
    words, valid = hashing.canonical_kmer_words_batch(
        packed, ambits, offsets, k, algo)
    g, n_win = valid.shape
    pad = span * _BLOCK - n_win
    words = tuple(jnp.pad(w, ((0, 0), (0, pad))) for w in words)
    valid = jnp.pad(valid, ((0, 0), (0, pad)))
    cand = fused_sketch_candidates(words, valid, algo=algo, seed=seed,
                                   interpret=interpret)
    flat = jnp.sort(cand.reshape(g, _CAND), axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros((g, 1), bool), flat[:, 1:] == flat[:, :-1]], axis=1)
    distinct = jnp.sort(
        jnp.where(dup, jnp.uint64(SENTINEL), flat), axis=-1)
    sketch = distinct[:, :sketch_size]
    t = distinct[:, sketch_size - 1]
    suspect = jnp.any(cand[:, R_REG - 1, :] < t[:, None], axis=-1)
    return sketch, suspect


_fused_group_sketch = profiled("sketch.fused")(jax.jit(
    _fused_group_sketch_jit,
    static_argnames=("k", "seed", "algo", "sketch_size", "span",
                     "interpret")))


def _pack_fused(genomes):
    """Stage-2 host transform: bucket + 2-bit pack the genomes into
    padded fused launch groups (the pallas_fragment recipe: pow2 job
    count >= _JOB_FLOOR, pow2 block span; padding jobs are
    all-ambiguous rows whose positions hash to the sentinel and never
    enter the candidate file). Pure — safe on pool threads."""
    skipped, group_iter = hashing.iter_genome_groups(
        genomes, budget=FUSED_BUDGET, max_len=DEFAULT_CHUNK)
    groups = []
    for chunk_idxs, packed, ambits, offs in group_iter:
        g = len(chunk_idxs)
        lb = packed.shape[1] * 4
        span = _pow2(lb // _BLOCK)
        g_pad = _pow2(max(g, _JOB_FLOOR))
        if g_pad > g:
            packed = np.vstack(
                [packed, np.zeros((g_pad - g, packed.shape[1]),
                                  np.uint8)])
            ambits = np.vstack(
                [ambits, np.full((g_pad - g, ambits.shape[1]), 0xFF,
                                 np.uint8)])
            offs = np.vstack(
                [offs, np.full((g_pad - g, offs.shape[1]),
                               np.int32(2**31 - 1), np.int32)])
        groups.append((chunk_idxs, packed, ambits, offs, span))
    return skipped, groups


def _sketch_packed_fused(genomes, skipped, groups, sketch_size, k,
                         seed, algo, interpret) -> List[MinHashSketch]:
    """Stage-3 launches over prepacked groups + the exact-path sweep
    for skipped (over-length) and suspect jobs."""
    out: List[MinHashSketch] = [None] * len(genomes)  # type: ignore
    for i in skipped:
        out[i] = sketch_genome_device(
            genomes[i], sketch_size=sketch_size, k=k, seed=seed,
            algo=algo)
    launches = jobs = slots = blocks = blocks_needed = suspects = 0
    for chunk_idxs, packed, ambits, offs, span in groups:
        g = len(chunk_idxs)
        g_pad = packed.shape[0]
        timing.dispatch()
        sketch, suspect = _fused_group_sketch(
            jnp.asarray(packed), jnp.asarray(ambits), jnp.asarray(offs),
            k=k, seed=seed, algo=algo, sketch_size=sketch_size,
            span=span, interpret=interpret)
        timing.dispatch(sync=True)
        mat = np.asarray(sketch)
        susp = np.asarray(suspect)
        launches += 1
        jobs += g
        slots += g_pad
        blocks += g_pad * span
        blocks_needed += sum(
            -(-(max(genomes[gi].codes.shape[0] - k + 1, 1)) // _BLOCK)
            for gi in chunk_idxs)
        for row, gi in enumerate(chunk_idxs):
            if susp[row]:
                # the certificate flagged a possible candidate drop:
                # re-sketch exactly (deterministic detection, so the
                # strategy stays bit-identical end to end)
                suspects += 1
                out[gi] = sketch_genome_device(
                    genomes[gi], sketch_size=sketch_size, k=k,
                    seed=seed, algo=algo)
            else:
                hs = mat[row]
                hs = hs[hs != np.uint64(SENTINEL)]
                out[gi] = MinHashSketch(
                    hashes=hs, sketch_size=sketch_size, kmer=k)
    if launches:
        from galah_tpu.obs import metrics as obs_metrics

        timing.counter("sketch-fused-launches", launches)
        timing.counter("sketch-fused-jobs", jobs)
        timing.counter("sketch-fused-job-slots", slots)
        timing.counter("sketch-fused-blocks", blocks)
        timing.counter("sketch-fused-blocks-needed", blocks_needed)
        if suspects:
            timing.counter("sketch-fused-suspect", suspects)
        obs_metrics.gauge(
            "sketch.fused_job_occupancy",
            help="real jobs / padded job slots of the fused sketch "
                 "launches (pow2 job padding waste)",
            unit="fraction").set(jobs / slots)
        obs_metrics.gauge(
            "sketch.fused_span_occupancy",
            help="needed kernel blocks / launched blocks of the fused "
                 "sketch launches (length-bucket + pow2 span waste)",
            unit="fraction").set(blocks_needed / blocks)
    return out


def sketch_genomes_fused(
    genomes: Sequence,
    sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
    k: int = Defaults.MINHASH_KMER,
    seed: int = Defaults.MINHASH_SEED,
    algo: str = Defaults.HASH_ALGO,
    interpret: Optional[bool] = None,
) -> List[MinHashSketch]:
    """Fused-kernel twin of ops/minhash.sketch_genomes_device_batch,
    bit-identical per genome (hard gate; the suspect certificate makes
    it unconditional). Genomes longer than DEFAULT_CHUNK, and
    sketch_size beyond the candidate capacity, take the exact chunked
    path.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if sketch_size > _CAND // 4:
        # candidate capacity cannot certify completeness cheaply —
        # not a production shape (default sketch_size=1000 vs 16384
        # candidates); take the exact path outright.
        return sketch_genomes_device_batch(
            genomes, sketch_size=sketch_size, k=k, seed=seed, algo=algo)
    skipped, groups = _pack_fused(genomes)
    return _sketch_packed_fused(genomes, skipped, groups, sketch_size,
                                k, seed, algo, interpret)


def _demote_fused(err: Exception) -> None:
    """Record the once-per-process fused->xla demotion."""
    global _DEMOTED
    with _DEMOTE_LOCK:
        if _DEMOTED:
            return
        _DEMOTED = True
    from galah_tpu.obs import events

    timing.counter("sketch-fused-demoted", 1)
    events.record("sketch-fused-demoted",
                  error=f"{type(err).__name__}: {err}")


def _fused_demoted() -> bool:
    with _DEMOTE_LOCK:
        return _DEMOTED


def ingest_depth(threads: int) -> int:
    """Stage-1 look-ahead depth: GALAH_TPU_INGEST_DEPTH pin, else
    max(2, threads) — deep enough to keep `threads` parser workers
    busy, shallow enough to bound resident parsed genomes."""
    env = os.environ.get("GALAH_TPU_INGEST_DEPTH")
    if env:
        return max(1, int(env))
    return max(2, threads)


def _ingest_read(path: str):
    """Stage-1 loader: the FASTA read, with the fault injector
    consulted at an `io.ingest` site first so slow-disk/backpressure
    behavior is testable (GALAH_FI kind=slow-io)."""
    from galah_tpu.io.fasta import read_genome
    from galah_tpu.resilience import faults

    injector = faults.get_injector()
    if injector is not None:
        injector.filesystem(f"io.ingest[{path}]")
    return read_genome(path)


def _iter_staged(items: Iterator, stage_fn, depth: int = 2):
    """Ordered double-buffered staging on the shared prefetch pool:
    submit stage_fn(item) keeping at most `depth` staged results in
    flight, yield (item, result) in submission order. With depth=2 the
    next batch packs while the caller consumes (launches) the previous
    one."""
    from galah_tpu.io import prefetch

    pool = prefetch._shared_pool(depth)
    pending: deque = deque()
    it = iter(items)
    token = timing.stage_token()
    ftoken = obs_flow.token()

    def staged(item):
        # stage-token adoption: telemetry from the pool thread lands
        # on the submitting thread's stage (and flow context), not an
        # empty stack
        with timing.adopt(token), obs_flow.adopt(ftoken):
            return stage_fn(item)

    def submit_next() -> bool:
        try:
            item = next(it)
        except StopIteration:
            return False
        pending.append((item, pool.submit(staged, item)))
        return True

    try:
        for _ in range(depth):
            if not submit_next():
                break
        while pending:
            item, fut = pending.popleft()
            result = fut.result()
            submit_next()
            yield item, result
    finally:
        prefetch._settle(fut for _, fut in pending)


def _iter_fused_sketches(miss_iter, sketch_size, k, seed, algo,
                         explicit):
    """(path, sketch) stream under the fused strategy: stage-2 packing
    double-buffered against stage-3 launches. The pack step is a pure
    host transform (iter_genome_groups' bucketing + 2-bit packing);
    the launch step runs the fused group dispatches on the consumer
    thread."""
    from galah_tpu.io import prefetch
    from galah_tpu.ops._fallback import run_with_pallas_fallback

    interpret = jax.default_backend() != "tpu"

    def pack(buf):
        return _pack_fused([g for _, g in buf])

    batches = prefetch.iter_batches(
        miss_iter, lambda g: g.codes.shape[0], FUSED_BUDGET)
    for buf, (skipped, groups) in _iter_staged(batches, pack, depth=2):
        gs = [g for _, g in buf]

        def run(pallas: bool) -> List[MinHashSketch]:
            if pallas:
                return _sketch_packed_fused(
                    gs, skipped, groups, sketch_size, k, seed, algo,
                    interpret)
            return sketch_genomes_device_batch(
                gs, sketch_size=sketch_size, k=k, seed=seed, algo=algo)

        use_fused = not _fused_demoted()
        sketches, used = run_with_pallas_fallback(
            "fused sketch kernel", explicit, use_fused, run)
        if use_fused and not used:
            _demote_fused(RuntimeError("Mosaic lowering failed"))
        for (p, _g), s in zip(buf, sketches):
            yield p, s


def _emit_sketch_occupancy(wall: float, wait_s: float,
                           ingest_s: list) -> float:
    """Refresh the sketch/ingest occupancy gauges mid-stream (the
    heartbeat thread samples them into its time-series)."""
    from galah_tpu.obs import metrics as obs_metrics

    wall = max(wall, 1e-9)
    occ = 1.0 - wait_s / wall
    obs_metrics.pipeline_occupancy(occ, stage="sketch")
    obs_metrics.pipeline_occupancy(sum(ingest_s) / wall, stage="ingest")
    return occ


def iter_path_sketches(
    paths: Sequence[str],
    store,
    threads: int = 1,
    strategy: Optional[str] = None,
) -> Iterator[Tuple[str, MinHashSketch]]:
    """The streaming sketch stage: yield (path, sketch) for the UNIQUE
    paths, in path order, overlapping ingest, staging, and sketch
    compute. Cache hits (store.get_cached) yield without any IO;
    misses stream through the resolved strategy and are inserted into
    the store on this (consumer) thread — the single-writer rule the
    sketching backends share.
    """
    from galah_tpu.io.prefetch import probe_and_prefetch, process_stream
    from galah_tpu.resilience import dispatch as rdispatch

    if strategy is None:
        strategy, explicit = resolve_sketch_strategy()
    else:
        explicit = True
    if strategy == "fused" and store.sketch_size > _CAND // 4:
        # candidate capacity cannot certify completeness at this
        # sketch_size — route to the exact batched path
        strategy = "xla"
        explicit = False
    timing.counter(f"sketch-strategy-{strategy}", 1)

    t0 = time.monotonic()
    bp_total = 0

    # per-read ingest wall, appended from the prefetch workers (list
    # append is atomic); its sum over the stage wall is the ingest
    # stage's occupancy gauge
    ingest_s: list = []

    def _timed_ingest(path):
        ti = time.monotonic()
        g = _ingest_read(path)
        ingest_s.append(time.monotonic() - ti)
        return g

    hits, miss_iter = probe_and_prefetch(
        paths, store.get_cached, _timed_ingest,
        depth=ingest_depth(threads))

    def counting(it):
        nonlocal bp_total
        for p, g in it:
            bp_total += int(g.codes.shape[0])
            yield p, g

    miss_iter = counting(miss_iter)

    # Ingest-time prefilter (ops/prefilter.py): provably conservative
    # duplicate/degenerate screening ahead of the batched sketcher,
    # plus the HLL pre-warm the bucketed pass reuses. Screened paths
    # never reach `computed`; the merge loop resolves them instead.
    from galah_tpu.ops import prefilter as _prefilter

    pre = _prefilter.maybe_prefilter(store)
    if pre is not None:
        miss_iter = pre.screen(miss_iter)

    if strategy == "fused":
        computed = _iter_fused_sketches(
            miss_iter, store.sketch_size, store.k, store.seed,
            store.algo, explicit)
    elif strategy == "xla":
        def sketch_batch(buf):
            # Guarded device dispatch: retries transient failures and,
            # after repeated ones, demotes this site to the per-genome
            # CPU sketch path for the rest of the run.
            return rdispatch.run(
                "dispatch.sketch-minhash",
                lambda: store.sketch_batch_only(buf),
                fallback=lambda: [store.sketch_only(g)
                                  for _p, g in buf],
                validate=rdispatch.expect_len(len(buf)))

        computed = process_stream(
            miss_iter, lambda g: g.codes.shape[0],
            hashing.BATCH_BUDGET, sketch_batch,
            lambda _path, g: store.sketch_only(g),
            batched=True, workers=threads)
    elif strategy == "c":
        computed = process_stream(
            miss_iter, lambda g: g.codes.shape[0],
            hashing.BATCH_BUDGET, None,
            lambda _path, g: store.sketch_only(g),
            batched=False, workers=threads)
    else:
        raise ValueError(f"unknown sketch strategy {strategy!r}")

    # Misses stream back in submission order == path order restricted
    # to misses, so a single merge walk yields every unique path in
    # original order — the property the overlapped pair pass needs.
    wait_s = 0.0
    yielded = 0
    # One-slot pushback: when the compute pipeline runs ahead of the
    # merge walk (its look-ahead pulled paths the prefilter screened
    # out), the next computed sketch parks here until its path comes
    # up in the walk.
    parked: Optional[tuple] = None
    for p in dict.fromkeys(paths):
        s = hits.get(p)
        if s is None and pre is not None:
            ps = pre.resolve(p)
            if ps is not None:
                s = store.insert_prefiltered(p, ps)
        if s is None and parked is not None and parked[0] == p:
            s = store.insert(p, parked[1])
            parked = None
        if s is None:
            # time blocked on the producer = consumer starvation; the
            # complement is the occupancy the overlap is meant to buy
            # (obs/flow records it as the sketch stage's
            # upstream-empty wait for `galah-tpu flow analyze`)
            with obs_flow.blocked("sketch", "upstream-empty") as bw:
                try:
                    cp, cs = next(computed)
                except StopIteration:
                    cp, cs = None, None
            wait_s += bw.seconds
            if cp == p:
                s = store.insert(p, cs)
            else:
                # p was screened while the pipeline looked ahead to
                # cp (or to exhaustion): the skip record exists now.
                assert parked is None, \
                    f"sketch stream out of order: {cp} != {p}"
                if cp is not None:
                    parked = (cp, cs)
                ps = pre.resolve(p) if pre is not None else None
                assert ps is not None, \
                    f"sketch stream out of order: {cp} != {p}"
                s = store.insert_prefiltered(p, ps)
        yield p, s
        yielded += 1
        # live gauge refresh so the heartbeat samples a moving
        # occupancy time-series, not only the quiesce value
        if bp_total and yielded % 64 == 0:
            _emit_sketch_occupancy(time.monotonic() - t0, wait_s,
                                   ingest_s)

    wall = max(time.monotonic() - t0, 1e-9)
    if bp_total:
        from galah_tpu.obs import metrics as obs_metrics

        obs_metrics.gauge(
            "workload.ingest_mbp",
            help="megabases ingested by the streaming sketch stage",
            unit="Mbp").set(bp_total / 1e6)
        obs_metrics.gauge(
            "workload.ingest_mbp_s",
            help="end-to-end ingest+sketch throughput of the streaming "
                 "sketch stage", unit="Mbp/s").set(bp_total / 1e6 / wall)
        occ = _emit_sketch_occupancy(wall, wait_s, ingest_s)
        # the unlabelled gauge keeps its historical meaning (this
        # stage's occupancy) until the overlapped engine overwrites it
        # with the whole-pipeline mean at quiesce (cluster/engine.py)
        obs_metrics.pipeline_occupancy(occ)
        obs_flow.record_service("sketch", max(wall - wait_s, 0.0),
                                items=yielded)
        obs_flow.record_service("ingest", sum(ingest_s),
                                items=len(ingest_s))


def iter_sketch_row_blocks(
    paths: Sequence[str],
    store,
    threads: int = 1,
    strategy: Optional[str] = None,
    block: int = 256,
):
    """Row-block consumer of the sketch stream for the overlapped pair
    pass: yield (r0, rows) with rows an (b, sketch_size) uint64
    sentinel-padded matrix over the unique paths in order, while the
    stream keeps ingesting ahead on the pool threads."""
    from galah_tpu.ops.minhash import sketch_matrix

    buf: list = []
    r0 = 0
    for _p, s in iter_path_sketches(paths, store, threads=threads,
                                    strategy=strategy):
        buf.append(s)
        if len(buf) == block:
            fid = obs_flow.begin("sketch_block")
            rows = sketch_matrix(buf, sketch_size=store.sketch_size)
            obs_flow.emit("sketch", fid)
            yield r0, rows
            r0 += len(buf)
            buf = []
    if buf:
        fid = obs_flow.begin("sketch_block")
        rows = sketch_matrix(buf, sketch_size=store.sketch_size)
        obs_flow.emit("sketch", fid)
        yield r0, rows
