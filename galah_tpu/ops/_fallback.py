"""Shared explicit-pin / default-fallback policy for Mosaic kernels.

Every device extraction offers a Mosaic kernel with an XLA twin. The
policy, identical at every call site: when the caller pinned the path
(explicit use_pallas=True/False) failures propagate loudly — parity
tests must never vacuously compare XLA to XLA; when pallas was chosen
by default (use_pallas=None resolved via use_pallas_default), a Mosaic
lowering failure (driver/toolchain drift) must never take down the
production path — warn once with the traceback and rerun via XLA.
"""

from __future__ import annotations

import logging
from typing import Callable, Tuple, TypeVar

T = TypeVar("T")

log = logging.getLogger(__name__)


def run_with_pallas_fallback(
    kernel_label: str,
    explicit: bool,
    use_pallas: bool,
    run: Callable[[bool], T],
    fallback_label: str = "the XLA searchsorted path",
) -> Tuple[T, bool]:
    """Run `run(pallas)` under the shared fallback policy.

    Returns (result, pallas_used) so loops that dispatch many batches
    can downgrade once and skip the retry for the rest of the run.
    """
    if use_pallas:
        try:
            return run(True), True
        except Exception:
            if explicit:
                raise
            log.warning(
                "Pallas %s unavailable; falling back to %s",
                kernel_label, fallback_label, exc_info=True)
    return run(False), False
