"""ctypes binding for the native pair-stats kernel (csrc/pairstats.c).

Exposes

    threshold_pairs_c(mat, sketch_size, kmer, min_ani, threads)
        -> {(i, j): ani}

the compiled-C twin of ops/pairwise.threshold_pairs for host CPUs —
same f64 rational keep-check, same Mash ANI values (reference analog:
the compiled pair loop of src/finch.rs:53-73). Build/load failures
raise ImportError (cached by utils/cbuild); set
GALAH_TPU_NO_CPAIRSTATS=1 to force callers' fallbacks.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from galah_tpu.ops.constants import SENTINEL
from galah_tpu.utils import cbuild

_lib = cbuild.build_and_load(
    "pairstats.c", "_libpairstats",
    out_dir=os.path.dirname(os.path.abspath(__file__)),
    extra_flags=("-lpthread", "-lm"),
    disable_env="GALAH_TPU_NO_CPAIRSTATS")
_fn = _lib.galah_pair_stats_threshold
_fn.restype = ctypes.c_int64
_fn.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
    ctypes.c_double, ctypes.c_int,
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
]


_fn_wm = _lib.galah_window_match_counts
_fn_wm.restype = None
_fn_wm.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64, ctypes.c_int,
    ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
]


def window_match_counts(
        wins: np.ndarray, ref_set: np.ndarray,
        threads: int = 1) -> "tuple[np.ndarray, np.ndarray]":
    """Per-window (matched, valid) counts of SENTINEL-masked hash
    windows against a sorted distinct reference set — C twin of
    ops/fragment_ani._window_match_counts_impl, row-parallel over
    `threads`."""
    wins = np.ascontiguousarray(wins, dtype=np.uint64)
    ref_set = np.ascontiguousarray(ref_set, dtype=np.uint64)
    if wins.ndim != 2:
        raise ValueError(
            f"wins must be a (W, L) window matrix, got shape "
            f"{wins.shape}")
    w = wins.shape[0]
    matched = np.empty(w, dtype=np.int32)
    total = np.empty(w, dtype=np.int32)
    _fn_wm(wins.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
           w, wins.shape[1],
           ref_set.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
           ref_set.shape[0], max(int(threads), 1),
           matched.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
           total.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return matched, total


_fn_pl = _lib.galah_pair_stats_for_pairs
_fn_pl.restype = None
_fn_pl.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
    ctypes.c_double, ctypes.c_int, ctypes.POINTER(ctypes.c_double),
]

# Crossover/env policy and the conservative screen live with the
# collision counter; re-exported for existing importers.
from galah_tpu.ops.collision import (  # noqa: E402
    SPARSE_SCREEN_MIN_N,
    candidate_pairs_minhash as _candidate_pairs_sparse,
    sparse_screen_min_n,
)


def threshold_pairs_c(mat: np.ndarray, sketch_size: int, kmer: int,
                      min_ani: float, threads: int = 0,
                      initial_cap: int = 0) -> dict:
    """All-pairs merged-bottom-k Mash ANI at or above min_ani.

    `mat` is the (N, width) uint64 SENTINEL-padded sorted sketch matrix
    (ops/minhash.sketch_matrix layout). The keep decision is the same
    f64 rational check as the device path (common >= j_thr * total with
    j_thr from pairwise.ani_to_jaccard), so both backends agree on
    borderline pairs. Retries with a grown buffer on overflow, so the
    result is always complete (`initial_cap` exists for tests).
    """
    from galah_tpu.ops.pairwise import ani_to_jaccard

    mat = np.ascontiguousarray(mat, dtype=np.uint64)
    n, width = mat.shape
    lens = (mat != np.uint64(SENTINEL)).sum(axis=1).astype(np.int64)
    if threads <= 0:
        threads = os.cpu_count() or 1
    j_thr = ani_to_jaccard(min_ani, kmer)

    if (n >= sparse_screen_min_n()
            and not os.environ.get("GALAH_TPU_DENSE_PAIRS")):
        pi, pj = _candidate_pairs_sparse(mat, lens, j_thr, sketch_size)
        from galah_tpu.utils import timing

        timing.counter("screen-candidates", int(pi.shape[0]))
        timing.counter("screen-possible-pairs", n * (n - 1) // 2)
        out_ani = np.full(pi.shape[0], float("-inf"), dtype=np.float64)
        if pi.shape[0]:
            _fn_pl(
                mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                pi.shape[0], width,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                np.ascontiguousarray(pi).ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)),
                np.ascontiguousarray(pj).ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)),
                sketch_size, kmer, float(j_thr), int(threads),
                out_ani.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        kept_n = int((out_ani != float("-inf")).sum())
        timing.counter("screen-kept-pairs", kept_n)
        from galah_tpu.obs import metrics as obs_metrics

        obs_metrics.gauge(
            "screen.survival_rate",
            help="Fraction of screened candidate pairs the threshold "
                 "kept (last screening pass)", unit="fraction").set(
            float(kept_n) / pi.shape[0] if pi.shape[0] else 0.0)
        return {(int(a), int(b)): float(v)
                for a, b, v in zip(pi, pj, out_ani)
                if v != float("-inf")}

    cap = initial_cap if initial_cap > 0 else max(4 * n + 1024, 1 << 16)
    while True:
        out_i = np.empty(cap, dtype=np.int64)
        out_j = np.empty(cap, dtype=np.int64)
        out_ani = np.empty(cap, dtype=np.float64)
        total = _fn(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            n, width,
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sketch_size, kmer, float(j_thr), int(threads),
            out_i.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out_j.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out_ani.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            cap)
        if total <= cap:
            break
        cap = int(total) + 1024
    m = int(min(total, cap))
    return {(int(out_i[x]), int(out_j[x])): float(out_ani[x])
            for x in range(m)}


_fn_wsc = _lib.galah_window_survivor_counts
_fn_wsc.restype = None
_fn_wsc.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64, ctypes.c_int64,
    ctypes.c_int64, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
]

_fn_fcw = _lib.galah_fill_compact_windows
_fn_fcw.restype = None
_fn_fcw.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64, ctypes.c_int64,
    ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint64),
]


def compact_windows(flat: np.ndarray, n_windows: int, fraglen: int,
                    k: int) -> np.ndarray:
    """Compacted (W, slots) positional-hash windows from a flat
    SENTINEL-masked array — C twin of the subsample_c > 1 branch of
    fragment_ani.GenomeProfile.windows() (two streaming passes instead
    of a full stable argsort). Bit-identical layout: survivors to the
    front in order, boundary-crossing k-mers dropped, slots = the
    longest row's count rounded up to a multiple of 64 (min 64)."""
    flat = np.ascontiguousarray(flat, dtype=np.uint64)
    if flat.shape[0] > n_windows * fraglen:
        # the numpy twin fails loudly on inconsistent sizing; the C
        # walk would write past counts/wins instead
        raise ValueError(
            f"flat length {flat.shape[0]} exceeds n_windows*fraglen "
            f"{n_windows}*{fraglen}")
    counts = np.empty(max(n_windows, 1), dtype=np.int64)
    _fn_wsc(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        flat.shape[0], n_windows, fraglen, int(k),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    slots = max(int(counts[:n_windows].max()) if n_windows else 1, 1)
    # the numpy twin slices its (W, L) array to `slots` columns, so
    # the effective width can never exceed L
    slots = min(-(-slots // 64) * 64, fraglen)
    wins = np.full((n_windows, slots), np.uint64(SENTINEL),
                   dtype=np.uint64)
    _fn_fcw(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        flat.shape[0], n_windows, fraglen, int(k), slots,
        wins.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return wins


_fn_wcp = _lib.galah_window_counts_pairs
_fn_wcp.restype = None
_fn_wcp.argtypes = [
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
    ctypes.c_int64, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
]

_fn_fwp = _lib.galah_fill_windows_pairs
_fn_fwp.restype = None
_fn_fwp.argtypes = [
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
    ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_uint64),
]


def windows_from_pairs(pos: np.ndarray, hashes: np.ndarray,
                       n_windows: int, fraglen: int,
                       k: int) -> np.ndarray:
    """Compacted (W, slots) windows from the profile walk's kept
    (pos, hash) pairs — bit-identical layout to compact_windows, in
    O(n_valid) instead of two streaming passes over the 8-byte-per-bp
    flat array."""
    pos = np.ascontiguousarray(pos, dtype=np.int64)
    hashes = np.ascontiguousarray(hashes, dtype=np.uint64)
    if pos.shape != hashes.shape:
        raise ValueError("pos/hashes shape mismatch")
    if pos.shape[0] and (pos.min() < 0
                         or pos.max() >= n_windows * fraglen):
        raise ValueError("position out of range")
    counts = np.zeros(max(n_windows, 1), dtype=np.int64)
    _fn_wcp(
        pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        pos.shape[0], n_windows, fraglen, int(k),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    slots = max(int(counts[:n_windows].max()) if n_windows else 1, 1)
    slots = min(-(-slots // 64) * 64, fraglen)
    wins = np.full((max(n_windows, 1), slots), np.uint64(SENTINEL),
                   dtype=np.uint64)
    cursors = np.zeros(max(n_windows, 1), dtype=np.int64)
    _fn_fwp(
        pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        pos.shape[0], n_windows, fraglen, int(k), slots,
        cursors.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        wins.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return wins[:n_windows]


_fn_wmm = _lib.galah_window_match_counts_merge
_fn_wmm.restype = None
_fn_wmm.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int32),
    ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int32),
]


_fn_avx = _lib.galah_merge_uses_avx512
_fn_avx.restype = ctypes.c_int
_fn_avx.argtypes = []


def merge_uses_avx512() -> bool:
    """True iff the merge counter would dispatch to the AVX-512 kernel
    right now (build + CPU support, GALAH_TPU_NO_AVX512 unset).
    Re-resolved per call, so env toggles within a process are seen."""
    return bool(_fn_avx())


def window_match_counts_merge(
        qh: np.ndarray, qw: np.ndarray, n_windows: int,
        ref_set: np.ndarray, validate: bool = True) -> np.ndarray:
    """Per-window matched counts via one linear merge of the profile's
    pre-sorted surviving hashes against the sorted distinct ref set —
    bit-identical to window_match_counts' matched output. qh must be
    sorted ascending with qw its window ids. Pass validate=False only
    when the arrays come from a source that already guarantees the
    bounds (GenomeProfile.sorted_query) — the check is two O(nq) scans,
    which would otherwise repeat per pair on the hot path."""
    qh = np.ascontiguousarray(qh, dtype=np.uint64)
    qw = np.ascontiguousarray(qw, dtype=np.int32)
    ref_set = np.ascontiguousarray(ref_set, dtype=np.uint64)
    if qh.shape != qw.shape:
        raise ValueError("qh/qw shape mismatch")
    if validate and qw.shape[0] and (qw.min() < 0
                                     or qw.max() >= n_windows):
        raise ValueError("window id out of range")
    matched = np.zeros(n_windows, dtype=np.int32)
    _fn_wmm(
        qh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        qw.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        qh.shape[0],
        ref_set.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ref_set.shape[0],
        matched.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return matched

_fn_wmb = _lib.galah_window_match_counts_merge_batch
_fn_wmb.restype = None
_fn_wmb.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint64),
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
]


def window_match_counts_merge_batch(
        qh_cat: np.ndarray, qw_cat: np.ndarray, q_off: np.ndarray,
        ref_cat: np.ndarray, r_off: np.ndarray, pair_q: np.ndarray,
        pair_r: np.ndarray, m_off: np.ndarray, total_windows: int,
        threads: int = 1) -> np.ndarray:
    """Concatenated per-window matched counts for a PAIR LIST — the
    batched twin of window_match_counts_merge (bit-identical counts per
    pair), with the per-pair loop and threading in C. Layouts (all
    contiguous, caller-guaranteed in-bounds — GenomeProfile data):

      qh_cat/qw_cat: per-genome sorted_query() arrays concatenated,
        genome g at [q_off[g], q_off[g+1]);
      ref_cat: per-genome sorted distinct ref sets concatenated,
        genome g at [r_off[g], r_off[g+1]);
      pair_q/pair_r: genome indices per pair (int32);
      m_off: per-pair output offset (int64 prefix over each pair's
        query window count), with `total_windows` the grand total.

    Returns the zero-initialized (total_windows,) int32 matched array
    filled per pair at [m_off[p], m_off[p] + n_windows(pair_q[p]))."""
    qh_cat = np.ascontiguousarray(qh_cat, dtype=np.uint64)
    qw_cat = np.ascontiguousarray(qw_cat, dtype=np.int32)
    q_off = np.ascontiguousarray(q_off, dtype=np.int64)
    ref_cat = np.ascontiguousarray(ref_cat, dtype=np.uint64)
    r_off = np.ascontiguousarray(r_off, dtype=np.int64)
    pair_q = np.ascontiguousarray(pair_q, dtype=np.int32)
    pair_r = np.ascontiguousarray(pair_r, dtype=np.int32)
    m_off = np.ascontiguousarray(m_off, dtype=np.int64)
    if qh_cat.shape != qw_cat.shape:
        raise ValueError("qh_cat/qw_cat shape mismatch")
    if pair_q.shape != pair_r.shape or pair_q.shape != m_off.shape:
        raise ValueError("pair array shape mismatch")
    matched = np.zeros(int(total_windows), dtype=np.int32)
    if pair_q.shape[0] == 0:
        return matched
    _fn_wmb(
        qh_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        qw_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        q_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ref_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        r_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        pair_q.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        pair_r.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        m_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        pair_q.shape[0], int(threads),
        matched.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return matched
