"""Tiled all-pairs MinHash ANI on device — the framework's hot op.

Replaces the reference's dense O(N^2) host pair loop
(reference: src/finch.rs:53-73) with a tiled device computation:

  * a pair's Mash Jaccard is computed WITHOUT sorting the union: both
    sketches are already sorted, so two `searchsorted` passes + cumulative
    sums yield (a) which elements are common and (b) each element's rank in
    the distinct union — enough to count commons inside the merged
    bottom-k. O(K log K) per pair, O(K) memory, MXU/VPU friendly.
  * pairs are evaluated in (row_tile x col_tile) blocks via nested vmap.
  * across devices, rows are sharded over a 1-D mesh with `shard_map`;
    every device holds the (replicated) sketch matrix and computes its row
    block against all columns, `lax.map`-ing over column tiles to bound
    memory. ANI tiles stay on device; thresholding happens there too, so
    only the sparse survivors ever reach the host.

Semantics (merged bottom-k Jaccard, Mash distance, ANI = 1 - d) are
bit-compatible with ops/minhash_np.py and the reference's finch backend
(golden 0.9808188, reference: src/finch.rs:96).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from galah_tpu.utils.jax_compat import shard_map

from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.hashing import HASH_SENTINEL
from galah_tpu.utils import timing

jax.config.update("jax_enable_x64", True)

# GL10xx pipeline-discipline contract (analysis/pipeline_check.py): the
# streamed pair pass must never be eagerly materialized and must report
# how busy it kept the device between block arrivals.
PIPELINE_STAGE = {
    "streaming": ["iter_threshold_pairs_streamed"],
    "occupancy_gauge": "workload.pipeline_occupancy",
}


def _pair_stats(a: jax.Array, b: jax.Array,
                sketch_size: int) -> Tuple[jax.Array, jax.Array]:
    """(common, total) of the merged bottom-`sketch_size` distinct union.

    `a`, `b`: (K,) uint64 sorted ascending, SENTINEL-padded.
    """
    valid_a = a != HASH_SENTINEL
    valid_b = b != HASH_SENTINEL
    na = jnp.sum(valid_a.astype(jnp.int32))
    nb = jnp.sum(valid_b.astype(jnp.int32))

    pos_b = jnp.searchsorted(b, a)  # count of b-elements < a[i]
    match = (pos_b < b.shape[0]) & valid_a
    match = match & (jnp.take(b, jnp.minimum(pos_b, b.shape[0] - 1)) == a)

    n_common = jnp.sum(match.astype(jnp.int32))
    n_union = na + nb - n_common
    total = jnp.minimum(jnp.int32(sketch_size), n_union)

    # Rank of a[i] in the distinct union = (#a < a[i]) + (#b < a[i])
    # - (#common < a[i]); a is distinct so #a < a[i] is just i.
    cmatch_excl = jnp.cumsum(match.astype(jnp.int32)) - match.astype(jnp.int32)
    urank = jnp.arange(a.shape[0], dtype=jnp.int32) + pos_b.astype(jnp.int32) \
        - cmatch_excl
    common = jnp.sum((match & (urank < total)).astype(jnp.int32))
    return common, total


def _stats_to_ani(common: jax.Array, total: jax.Array, k: int) -> jax.Array:
    """Mash ANI (f32) from merged-bottom-k (common, total)."""
    j = common.astype(jnp.float32) / jnp.maximum(
        total.astype(jnp.float32), 1.0)
    d = -jnp.log(2.0 * j / (1.0 + j)) / jnp.float32(k)
    ani = 1.0 - d
    return jnp.where(common > 0, ani, jnp.float32(0.0))


@functools.partial(jax.jit, static_argnames=("sketch_size", "k"))
def tile_ani(rows: jax.Array, cols: jax.Array,
             sketch_size: int, k: int) -> jax.Array:
    """ANI for every (row, col) sketch pair: (Br,K),(Bc,K) -> (Br,Bc) f32."""
    def one_row(a):
        c, t = jax.vmap(lambda b: _pair_stats(a, b, sketch_size))(cols)
        return _stats_to_ani(c, t, k)

    return jax.vmap(one_row)(rows)


@functools.partial(jax.jit, static_argnames=("sketch_size", "k"))
def tile_stats(rows: jax.Array, cols: jax.Array,
               sketch_size: int, k: int) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 tiles — used for exact-parity tests."""
    def one_row(a):
        return jax.vmap(lambda b: _pair_stats(a, b, sketch_size))(cols)

    return jax.vmap(one_row)(rows)


@jax.jit
def tile_intersect_counts(rows: jax.Array, cols: jax.Array) -> jax.Array:
    """|row ∩ col| for sorted SENTINEL-padded hash rows -> (Br, Bc) int32.

    Used for marker-containment screening (the skani-equivalent
    preclusterer's candidate filter, reference: src/skani.rs:54-70).
    """
    def one_pair(a, b):
        valid = a != HASH_SENTINEL
        pos = jnp.searchsorted(b, a)
        hit = jnp.take(b, jnp.minimum(pos, b.shape[0] - 1)) == a
        return jnp.sum((hit & valid).astype(jnp.int32))

    return jax.vmap(lambda a: jax.vmap(lambda b: one_pair(a, b))(cols))(rows)


def _block_ani(block_rows: jax.Array, all_cols: jax.Array,
               sketch_size: int, k: int, col_tile: int) -> jax.Array:
    """(Br, N) ANI of a row block vs all columns, lax.map over col tiles."""
    n = all_cols.shape[0]
    n_tiles = n // col_tile  # caller pads N to a multiple of col_tile

    def one_tile(t):
        cols = jax.lax.dynamic_slice_in_dim(
            all_cols, t * col_tile, col_tile, axis=0)
        return tile_ani(block_rows, cols, sketch_size, k)

    tiles = jax.lax.map(one_tile, jnp.arange(n_tiles))  # (T, Br, col_tile)
    return jnp.transpose(tiles, (1, 0, 2)).reshape(block_rows.shape[0], n)


def all_pairs_ani(
    sketch_mat: np.ndarray,
    k: int,
    sketch_size: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    col_tile: int = 128,
) -> np.ndarray:
    """Full (N, N) ANI matrix, rows sharded over the mesh's devices.

    The reference walks i<j pairs on host threads; here the whole matrix is
    one sharded device computation (upper-triangle extraction happens in
    `threshold_pairs`). For very large N prefer `threshold_pairs`, which
    never materializes the full matrix on host — N is capped here so an
    API caller cannot accidentally allocate an O(N^2) host matrix.
    """
    n_genomes = sketch_mat.shape[0]
    if n_genomes > 16384:
        raise ValueError(
            f"all_pairs_ani materializes a dense ({n_genomes}, "
            f"{n_genomes}) matrix; use threshold_pairs for large N")
    if sketch_size is None:
        sketch_size = sketch_mat.shape[1]
    if mesh is None:
        devs = jax.devices()
        mesh = Mesh(np.array(devs), ("i",))
    n_dev = mesh.devices.size

    n = sketch_mat.shape[0]
    # Padded size must be divisible by the row sharding (n_dev) AND the
    # column tiling, so round up to a multiple of lcm(n_dev, col_tile).
    import math

    quantum = math.lcm(n_dev, col_tile)
    pad_n = -(-n // quantum) * quantum
    mat = np.full((pad_n, sketch_mat.shape[1]),
                  np.uint64(SENTINEL), dtype=np.uint64)
    mat[:n] = sketch_mat
    jmat = jnp.asarray(mat)

    fn = shard_map(
        functools.partial(_block_ani, sketch_size=sketch_size, k=k,
                          col_tile=col_tile),
        mesh=mesh,
        in_specs=(P("i", None), P(None, None)),
        out_specs=P("i", None),
    )
    ani = jax.jit(fn)(jmat, jmat)
    return np.asarray(ani[:n, :n])


def ani_to_jaccard(min_ani: float, k: int) -> float:
    """Invert Mash ANI to the equivalent Jaccard threshold (f64, exact)."""
    import math

    q = math.exp(-float(k) * (1.0 - float(min_ani)))
    return q / (2.0 - q)


def stats_to_ani_f64(common: np.ndarray, total: np.ndarray,
                     k: int) -> np.ndarray:
    """Host-side f64 Mash ANI from integer (common, total) — bit-compatible
    with ops/minhash_np.mash_ani and the reference's finch path."""
    j = common.astype(np.float64) / np.maximum(total.astype(np.float64), 1.0)
    with np.errstate(divide="ignore"):
        d = -np.log(2.0 * j / (1.0 + j)) / float(k)
    return np.where(common > 0, 1.0 - d, 0.0)


@functools.partial(
    jax.jit,
    static_argnames=("sketch_size", "k", "row_tile", "col_tile", "cap",
                     "n", "use_pallas"))
def _rowblock_candidates(
    jmat: jax.Array,     # (n_pad, K) uint64 padded sketch matrix
    r0: jax.Array,       # scalar i32: first global row of this block
    j_thr_lo: jax.Array, # f64: conservative (slightly lowered) threshold
    sketch_size: int,
    k: int,
    row_tile: int,
    col_tile: int,
    cap: int,
    n: int,
    use_pallas: bool,
):
    """One device dispatch: a (row_tile, n_pad) stats stripe, thresholded
    and compacted to at most `cap` candidate pairs on device.

    Returns (flat_idx (cap,), common (cap,), total (cap,), count) where
    flat_idx indexes the (row_tile, n_pad) stripe (-1 padding). count is
    the TRUE number of passing entries — count > cap signals overflow
    and the caller must re-run this block another way.
    """
    n_pad = jmat.shape[0]
    rows = jax.lax.dynamic_slice_in_dim(jmat, r0, row_tile, axis=0)
    n_ct = n_pad // col_tile

    if use_pallas:
        from galah_tpu.ops.pallas_pairwise import tile_stats_pallas

        def stats_fn(rows, cols):
            return tile_stats_pallas(rows, cols, sketch_size)
    else:
        def stats_fn(rows, cols):
            return tile_stats(rows, cols, sketch_size, k)

    # Tiles entirely below the diagonal contribute nothing; lax.map is a
    # sequential scan, so lax.cond really skips their compute at runtime
    # while keeping one compiled shape for every row block.
    t_first = r0 // col_tile

    def one_tile(t):
        def compute(_):
            cols = jax.lax.dynamic_slice_in_dim(
                jmat, t * col_tile, col_tile, axis=0)
            c, tt = stats_fn(rows, cols)
            return c.astype(jnp.int32), tt.astype(jnp.int32)

        def skip(_):
            z = jnp.zeros((row_tile, col_tile), jnp.int32)
            return z, z

        return jax.lax.cond(t >= t_first, compute, skip, None)

    common, total = jax.lax.map(one_tile, jnp.arange(n_ct))
    # (T, rt, ct) -> (rt, n_pad)
    common = jnp.transpose(common, (1, 0, 2)).reshape(row_tile, n_pad)
    total = jnp.transpose(total, (1, 0, 2)).reshape(row_tile, n_pad)

    gi = r0 + jnp.arange(row_tile)[:, None]
    gj = jnp.arange(n_pad)[None, :]
    mask = (common.astype(jnp.float64)
            >= j_thr_lo * total.astype(jnp.float64))
    mask &= (common > 0) & (gi < gj) & (gj < n)
    count = jnp.sum(mask.astype(jnp.int32))
    (flat_idx,) = jnp.nonzero(mask.ravel(), size=cap, fill_value=-1)
    safe = jnp.maximum(flat_idx, 0)
    return (flat_idx, jnp.take(common.ravel(), safe),
            jnp.take(total.ravel(), safe), count)


@functools.partial(
    jax.jit,
    static_argnames=("row_tile", "col_tile", "cap", "use_pallas"))
def _rowblock_screen(
    jmat: jax.Array,     # (n_pad, M) uint64 padded marker matrix
    counts: jax.Array,   # (n_pad,) int32 marker counts per genome
    r0: jax.Array,       # scalar i32: first global row of this block
    c_floor_lo: jax.Array,  # f64: conservative (lowered) containment floor
    n: jax.Array,        # scalar i32: true genome count
    row_tile: int,
    col_tile: int,
    cap: int,
    use_pallas: bool = False,
):
    """One device dispatch: a (row_tile, n_pad) marker-intersection
    stripe, containment-thresholded and compacted on device.

    Returns (flat_idx (cap,), inter (cap,), count) — flat_idx indexes the
    (row_tile, n_pad) stripe, inter is the raw intersection count so the
    host can apply the EXACT f64 containment check.
    """
    n_pad = jmat.shape[0]
    rows = jax.lax.dynamic_slice_in_dim(jmat, r0, row_tile, axis=0)
    n_ct = n_pad // col_tile
    t_first = r0 // col_tile

    def one_tile(t):
        def compute(_):
            cols = jax.lax.dynamic_slice_in_dim(
                jmat, t * col_tile, col_tile, axis=0)
            if use_pallas:
                from galah_tpu.ops.pallas_pairwise import (
                    tile_intersect_pallas,
                )

                return tile_intersect_pallas(rows, cols)
            return tile_intersect_counts(rows, cols).astype(jnp.int32)

        def skip(_):
            return jnp.zeros((row_tile, col_tile), jnp.int32)

        return jax.lax.cond(t >= t_first, compute, skip, None)

    inter = jax.lax.map(one_tile, jnp.arange(n_ct))
    inter = jnp.transpose(inter, (1, 0, 2)).reshape(row_tile, n_pad)

    rcnt = jax.lax.dynamic_slice_in_dim(counts, r0, row_tile, axis=0)
    denom = jnp.minimum(rcnt[:, None], counts[None, :])
    gi = r0 + jnp.arange(row_tile)[:, None]
    gj = jnp.arange(n_pad)[None, :]
    mask = (inter.astype(jnp.float64)
            >= c_floor_lo * denom.astype(jnp.float64))
    mask &= (inter > 0) & (gi < gj) & (gj < n)
    count = jnp.sum(mask.astype(jnp.int32))
    (flat_idx,) = jnp.nonzero(mask.ravel(), size=cap, fill_value=-1)
    return (flat_idx, jnp.take(inter.ravel(), jnp.maximum(flat_idx, 0)),
            count)


def screen_pairs(
    marker_mat: np.ndarray,   # (N, M) uint64 sorted SENTINEL-padded markers
    counts: np.ndarray,       # (N,) marker counts per genome
    c_floor: float,
    row_tile: Optional[int] = None,
    col_tile: Optional[int] = None,
    cap_per_row: int = 256,
    mesh: "Optional[Mesh]" = None,
    use_pallas: Optional[bool] = None,
) -> list[tuple[int, int]]:
    """i<j pairs whose marker containment >= c_floor, blocked on device.

    Containment = |markers_i ∩ markers_j| / min(|markers_i|, |markers_j|)
    — the skani-equivalent candidate screen (reference: src/skani.rs:54-70,
    screen_refs(0.80, ..)). ONE device dispatch per row block: the block's
    intersection stripe is computed tile-by-tile on device (lax.map),
    thresholded conservatively there, and only compacted candidates come
    back; the host applies the exact f64 containment check. On a
    multi-device runtime the column-sharded SPMD twin
    (parallel/mesh.sharded_screen_pairs) is selected automatically.
    """
    # No knobs pinned and above the sparse crossover: the inverted-index
    # collision counts ARE the containment numerators (marker sets are
    # distinct), so the host check below is exact with no second pass —
    # O(NM log NM + colliding pairs) instead of O(N^2) tiles, on ANY
    # backend (the screen is pure host work; the device never needs to
    # see the dense marker matrix at all). Tile/pallas knobs and an
    # explicit mesh pin the dense tiled implementations for parity
    # tests. The denom > 0 guard matches the tiled paths
    # (see _screen_pairs_single).
    from galah_tpu.ops.collision import sparse_screen_min_n

    if (mesh is None and use_pallas is None and row_tile is None
            and col_tile is None
            and marker_mat.shape[0] >= sparse_screen_min_n()
            and not os.environ.get("GALAH_TPU_DENSE_PAIRS")):
        from galah_tpu.ops.collision import collision_pair_counts

        counts64 = np.asarray(counts, dtype=np.int64)
        pi, pj, inter = collision_pair_counts(
            np.ascontiguousarray(marker_mat, dtype=np.uint64), counts64)
        denom = np.minimum(counts64[pi], counts64[pj]).astype(np.float64)
        keep = (denom > 0) & (inter.astype(np.float64)
                              >= c_floor * denom)
        n = marker_mat.shape[0]
        timing.counter("screen-candidates", int(pi.shape[0]))
        timing.counter("screen-possible-pairs", n * (n - 1) // 2)
        timing.counter("screen-kept-pairs", int(keep.sum()))
        from galah_tpu.obs import metrics as obs_metrics

        obs_metrics.gauge(
            "screen.survival_rate",
            help="Fraction of screened candidate pairs the threshold "
                 "kept (last screening pass)", unit="fraction").set(
            float(keep.sum()) / pi.shape[0] if pi.shape[0] else 0.0)
        return list(zip(pi[keep].tolist(), pj[keep].tolist()))

    if mesh is None and jax.device_count() > 1:
        from galah_tpu.parallel.mesh import auto_mesh

        mesh = auto_mesh()
    if mesh is not None and mesh.devices.size > 1:
        from galah_tpu.parallel.mesh import sharded_screen_pairs

        return sharded_screen_pairs(
            marker_mat, counts, c_floor, mesh=mesh,
            row_tile=row_tile if row_tile is not None else 64,
            col_tile=col_tile if col_tile is not None else 256,
            cap_per_row=cap_per_row, use_pallas=use_pallas)

    # Mosaic intersect kernel on TPU by default, with the same
    # explicit-pin / default-fallback policy as threshold_pairs.
    explicit = use_pallas is not None
    if use_pallas is None:
        from galah_tpu.ops.hll import use_pallas_default

        use_pallas = use_pallas_default()
    # per-path tile defaults, honoring explicit caller values
    from galah_tpu.ops._fallback import run_with_pallas_fallback

    result, _ = run_with_pallas_fallback(
        "intersect kernel", explicit, bool(use_pallas),
        lambda p: _screen_pairs_single(
            marker_mat, counts, c_floor,
            row_tile if row_tile is not None else (128 if p else 64),
            col_tile if col_tile is not None else 256,
            cap_per_row, p))
    return result


def _screen_pairs_single(
    marker_mat: np.ndarray,
    counts: np.ndarray,
    c_floor: float,
    row_tile: int,
    col_tile: int,
    cap_per_row: int,
    use_pallas: bool,
) -> list[tuple[int, int]]:
    import math

    n = marker_mat.shape[0]
    quantum = math.lcm(row_tile, col_tile)
    n_pad = -(-n // quantum) * quantum
    mat = np.full((n_pad, marker_mat.shape[1]),
                  np.uint64(SENTINEL), dtype=np.uint64)
    mat[:n] = marker_mat
    cnt = np.zeros(n_pad, dtype=np.int32)
    cnt[:n] = counts
    jmat = jnp.asarray(mat)
    jcnt = jnp.asarray(cnt)

    c_floor_lo = jnp.float64(c_floor * (1.0 - 1e-12) - 1e-300)
    counts64 = np.asarray(counts, dtype=np.int64)

    from galah_tpu.ops.compact import iter_blocks

    out: list[tuple[int, int]] = []
    for r0, (flat_idx, inter, count) in iter_blocks(
            n, row_tile, cap_per_row,
            lambda r0, cap: _rowblock_screen(
                jmat, jcnt, jnp.int32(r0), c_floor_lo, jnp.int32(n),
                row_tile=row_tile, col_tile=col_tile, cap=cap,
                use_pallas=use_pallas)):
        count = int(count)
        flat_idx = np.asarray(flat_idx)[:count]
        inter = np.asarray(inter)[:count].astype(np.int64)
        gi = r0 + flat_idx // n_pad
        gj = flat_idx % n_pad
        # exact host-side containment check. denom > 0 is belt and
        # braces: the device stripe mask already requires inter > 0,
        # and inter <= denom, so a denom == 0 pair cannot reach here —
        # the guard just keeps this check self-contained.
        denom = np.minimum(counts64[gi], counts64[gj]).astype(np.float64)
        keep = (denom > 0) & (inter.astype(np.float64) >= c_floor * denom)
        out.extend(zip(gi[keep].tolist(), gj[keep].tolist()))
    return out


def threshold_pairs(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    sketch_size: Optional[int] = None,
    row_tile: Optional[int] = None,
    col_tile: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    cap_per_row: int = 64,
    mesh: "Optional[Mesh]" = None,
) -> dict[tuple[int, int], float]:
    """Sparse {(i, j): ani} for i<j pairs with ani >= min_ani.

    row_tile/col_tile default per path (XLA: 64x128; Mosaic: 128x512);
    explicit values are honored on every path, including the fallback.

    One device dispatch per ROW BLOCK (not per tile): the block's stats
    stripe is computed tile-by-tile on device (`lax.map`), thresholded
    conservatively there, and only the compacted sparse candidates come
    back — the host then applies the exact f64 integer-Jaccard check
    (common/total >= j_thr), sidestepping f32 log rounding, and reports
    the f64 ANI. Direct replacement for the reference's thresholded
    pair-cache insert (reference: src/finch.rs:69-71). If a block's
    candidates overflow the on-device capacity (cap_per_row * row_tile),
    that block transparently re-runs with a larger one. With use_pallas,
    stats tiles run the Mosaic kernel (ops/pallas_pairwise.py) instead
    of the XLA searchsorted path — bit-identical integers either way.

    On a multi-device runtime the column-sharded SPMD implementation
    (parallel/mesh.sharded_threshold_pairs) is selected automatically;
    pass `mesh` to choose one explicitly.

    Above ops/collision.SPARSE_SCREEN_MIN_N genomes (no tile/pallas
    knobs pinned) EVERY backend takes the screened sparse path instead
    of dense tiles: host collision screen, then batched gathered pair
    evaluation on device (ops/sparse_device.py) — bit-identical
    results, O(NK log NK + survivors) instead of O(N^2).
    """
    # Single-device CPU backend with no knobs pinned: the compiled-C
    # merged-bottom-k walk (csrc/pairstats.c) measures ~13x the XLA-CPU
    # tiled pass on one core and computes the identical f64 mash ANI —
    # use it outright. Knob-pinning callers (tiles, pallas, mesh) and
    # TPU backends keep the device path.
    if (mesh is None and use_pallas is None and row_tile is None
            and col_tile is None):
        if jax.default_backend() == "cpu" and jax.device_count() == 1:
            try:
                from galah_tpu.ops._cpairstats import threshold_pairs_c

                eff = (sketch_size if sketch_size is not None
                       else sketch_mat.shape[1])
                return threshold_pairs_c(
                    np.asarray(sketch_mat), eff, k, float(min_ani))
            except ImportError:
                pass  # no C toolchain: fall through to the XLA path

    # Device backends above the sparse crossover: screened evaluation
    # (host collision screen + batched gathered pair stats on device)
    # replaces the dense O(N^2) tiles — same two-phase shape as the CPU
    # C path above, same bit-identical results. Tile/pallas knobs pin
    # the dense implementations (parity tests rely on that); an
    # explicit mesh is honored by sharding the candidate batches.
    from galah_tpu.ops.collision import sparse_screen_min_n

    if (use_pallas is None and row_tile is None and col_tile is None
            and sketch_mat.shape[0] >= sparse_screen_min_n()
            and not os.environ.get("GALAH_TPU_DENSE_PAIRS")):
        from galah_tpu.ops.sparse_device import threshold_pairs_sparse

        m = mesh
        if m is None and jax.device_count() > 1:
            from galah_tpu.parallel.mesh import make_mesh

            m = make_mesh()
        return threshold_pairs_sparse(
            sketch_mat, k=k, min_ani=min_ani, sketch_size=sketch_size,
            mesh=m if (m is not None and m.devices.size > 1) else None)

    # Auto-shard only when the caller left the knobs unset: explicit
    # use_pallas (True OR False) pins the single-device implementation,
    # as does an explicit mesh.
    if mesh is None and use_pallas is None and jax.device_count() > 1:
        from galah_tpu.parallel.mesh import auto_mesh

        mesh = auto_mesh()
    if mesh is not None and mesh.devices.size > 1:
        from galah_tpu.parallel.mesh import sharded_threshold_pairs

        return sharded_threshold_pairs(
            sketch_mat, k=k, min_ani=min_ani, mesh=mesh,
            sketch_size=sketch_size,
            row_tile=row_tile, col_tile=col_tile,
            cap_per_row=cap_per_row, use_pallas=use_pallas)

    # An explicit use_pallas=True pins the Mosaic kernel (failures
    # propagate, keeping parity tests honest); only the default choice
    # falls back to XLA on Mosaic failure.
    explicit = use_pallas is not None
    if use_pallas is None:
        from galah_tpu.ops.hll import use_pallas_default

        use_pallas = use_pallas_default()
    # Per-path tile defaults, honoring any explicit caller values: the
    # Mosaic kernel's program covers 8 query rows x all columns of its
    # call, so wider column tiles amortize dispatch overhead (VMEM
    # residency for the reference planes caps the width).
    rt = row_tile if row_tile is not None else (128 if use_pallas else 64)
    ct = col_tile if col_tile is not None else (512 if use_pallas else 128)

    if sketch_size is None:
        sketch_size = sketch_mat.shape[1]
    from galah_tpu.ops._fallback import run_with_pallas_fallback

    result, _ = run_with_pallas_fallback(
        "pair-stats kernel", explicit, bool(use_pallas),
        lambda p: _threshold_pairs_single(
            sketch_mat, k, min_ani, sketch_size,
            rt if p else (row_tile if row_tile is not None else 64),
            ct if p else (col_tile if col_tile is not None else 128),
            p, cap_per_row))
    return result


def _threshold_pairs_single(
    sketch_mat: np.ndarray,
    k: int,
    min_ani: float,
    sketch_size: int,
    row_tile: int,
    col_tile: int,
    use_pallas: bool,
    cap_per_row: int,
) -> dict[tuple[int, int], float]:
    n = sketch_mat.shape[0]
    import math

    quantum = math.lcm(row_tile, col_tile)
    n_pad = -(-n // quantum) * quantum
    mat = np.full((n_pad, sketch_mat.shape[1]),
                  np.uint64(SENTINEL), dtype=np.uint64)
    mat[:n] = sketch_mat
    jmat = jnp.asarray(mat)

    j_thr = ani_to_jaccard(min_ani, k)
    # Conservative device-side prefilter: exact f64 check happens on host
    # over the sparse survivors, so borderline pairs are never lost to
    # accumulated device rounding.
    j_thr_lo = jnp.float64(j_thr * (1.0 - 1e-12) - 1e-300)

    from galah_tpu.ops.compact import iter_blocks

    def run_block(r0, cap):
        timing.dispatch()
        return _rowblock_candidates(
            jmat, jnp.int32(r0), j_thr_lo,
            sketch_size=sketch_size, k=k, row_tile=row_tile,
            col_tile=col_tile, cap=cap, n=n,
            use_pallas=use_pallas)

    out: dict[tuple[int, int], float] = {}
    for r0, (flat_idx, common, total, count) in iter_blocks(
            n, row_tile, cap_per_row, run_block):
        timing.dispatch(sync=True)
        count = int(count)
        flat_idx = np.asarray(flat_idx)[:count]
        common = np.asarray(common)[:count].astype(np.int64)
        total = np.asarray(total)[:count].astype(np.int64)

        # exact host-side threshold + ANI
        keep = common.astype(np.float64) >= j_thr * total
        flat_idx, common, total = flat_idx[keep], common[keep], total[keep]
        ani = stats_to_ani_f64(common, total, k)
        gi = r0 + flat_idx // n_pad
        gj = flat_idx % n_pad
        for a, b, v in zip(gi.tolist(), gj.tolist(), ani.tolist()):
            out[(int(a), int(b))] = float(v)
    return out


@functools.partial(
    jax.jit, static_argnames=("sketch_size", "k", "row_tile"))
def _stripe_stats(rows_mat: jax.Array, cols_mat: jax.Array,
                  sketch_size: int, k: int,
                  row_tile: int) -> Tuple[jax.Array, jax.Array]:
    """(common, total) int32 of EVERY done row against one incoming
    column block — the per-block device dispatch of the streamed pair
    pass, lax.map over row tiles to bound the vmap intermediates."""
    n_rt = rows_mat.shape[0] // row_tile

    def one_tile(t):
        rows = jax.lax.dynamic_slice_in_dim(
            rows_mat, t * row_tile, row_tile, axis=0)
        c, tt = tile_stats(rows, cols_mat, sketch_size, k)
        return c.astype(jnp.int32), tt.astype(jnp.int32)

    c, t = jax.lax.map(one_tile, jnp.arange(n_rt))
    b = cols_mat.shape[0]
    return c.reshape(n_rt * row_tile, b), t.reshape(n_rt * row_tile, b)


def iter_threshold_pairs_streamed(
    blocks_iter,
    n: int,
    k: int,
    min_ani: float,
    sketch_size: int,
    mesh: "Optional[Mesh]" = None,
    block: int = 256,
    row_tile: int = 64,
):
    """Streamed pair pass as a GENERATOR: consume (r0, rows) sketch
    blocks (ops/sketch_stream.iter_sketch_row_blocks) and, per block,
    yield `(r1, increment)` where `increment` maps surviving (i, j)
    pairs with j < r1 that were first resolvable on this stripe. The
    union of all increments is IDENTICAL to
    `threshold_pairs(full_matrix, ...)` by construction: every i<j
    pair is covered exactly once (rows [0, r1) x cols [r0, r1),
    filtered to i < j), and the exact f64 integer-Jaccard check runs
    on host over the integer stats.

    Yielding per block is what lets a downstream consumer (the
    overlapped cluster engine) act on the prefix [0, r1) — whose pair
    neighborhood is COMPLETE at that point — while later genomes are
    still being ingested and sketched.

    Done-row counts are padded to powers of two (>= the tiling
    quantum) to bound the jit variants at O(log n); sentinel padding
    rows/cols are killed by the `common > 0` guard (a sentinel row
    intersects nothing). With a multi-device `mesh`, each stripe is
    computed with rows sharded over the mesh
    (parallel/mesh.sharded_stripe_stats) — bit-identical integers
    either way.

    Emits the stage="pairs" `workload.pipeline_occupancy` gauge on
    exhaustion: the fraction of this stage's wall spent working (vs
    blocked waiting on the upstream sketch stream).
    """
    j_thr = ani_to_jaccard(min_ani, k)
    n_dev = mesh.devices.size if mesh is not None else 1
    if n_dev > 1 and (n_dev & (n_dev - 1)):
        # non-pow2 mesh would break the pow2 row padding below; the
        # single-device stripe is always correct, just unsharded
        mesh, n_dev = None, 1
    quantum = row_tile * n_dev

    from galah_tpu.obs import flow as obs_flow
    from galah_tpu.obs import metrics as obs_metrics

    done = np.full((n, sketch_size), np.uint64(SENTINEL),
                   dtype=np.uint64)
    r1 = 0
    stripes = 0
    t_start = time.monotonic()
    wait_s = 0.0
    blocks = iter(blocks_iter)
    while True:
        # blocked on the upstream sketch stream (obs/flow records it
        # as the pairs stage's upstream-empty wait)
        with obs_flow.blocked("pairs", "upstream-empty") as bw:
            try:
                r0, rows = next(blocks)
            except StopIteration:
                break
        wait_s += bw.seconds
        obs_flow.absorb("sketch", "pairs")
        t_block = time.monotonic()
        bsz = rows.shape[0]
        assert r0 == r1, f"streamed blocks out of order: {r0} != {r1}"
        done[r0:r0 + bsz] = rows
        r1 = r0 + bsz

        # pow2 (>= quantum) done-row padding and fixed column width:
        # O(log n) distinct dispatch shapes across the whole stream.
        r_pad = quantum
        while r_pad < r1:
            r_pad <<= 1
        cols = np.full((block, sketch_size), np.uint64(SENTINEL),
                       dtype=np.uint64)
        cols[:bsz] = rows
        with obs_flow.blocked("pairs", "device-dispatch") as bdev:
            timing.dispatch()
            if mesh is not None:
                from galah_tpu.parallel.mesh import sharded_stripe_stats

                common, total = sharded_stripe_stats(
                    done[:r1], cols, sketch_size=sketch_size, k=k,
                    mesh=mesh, row_tile=row_tile, r_pad=r_pad)
            else:
                jrows = jnp.asarray(
                    np.vstack([done[:r1],
                               np.full((r_pad - r1, sketch_size),
                                       np.uint64(SENTINEL), np.uint64)]))
                common, total = _stripe_stats(
                    jrows, jnp.asarray(cols), sketch_size=sketch_size,
                    k=k, row_tile=row_tile)
            timing.dispatch(sync=True)
        stripes += 1

        common = np.asarray(common).astype(np.int64)
        total = np.asarray(total).astype(np.int64)
        gi = np.arange(common.shape[0])[:, None]
        gj = r0 + np.arange(block)[None, :]
        # exact host-side threshold + ANI; common > 0 kills sentinel
        # padding rows/cols (and the degenerate empty-sketch pairs,
        # matching the dense paths' device prefilter)
        keep = ((gi < gj) & (gj < r1) & (common > 0)
                & (common.astype(np.float64) >= j_thr * total))
        ki, kj = np.nonzero(keep)
        ani = stats_to_ani_f64(common[keep], total[keep], k)
        inc: dict[tuple[int, int], float] = {}
        for a, b, v in zip(ki.tolist(), (r0 + kj).tolist(),
                           ani.tolist()):
            inc[(int(a), int(b))] = float(v)
        # host post-processing time = stripe wall minus the device
        # bracket (the upstream wait is already excluded)
        obs_flow.record_service(
            "pairs", max(time.monotonic() - t_block - bdev.seconds,
                         0.0))
        efid = obs_flow.begin("edge_stripe")
        obs_flow.emit("pairs", efid)
        yield r1, inc
        # live gauge refresh (heartbeat samples the time-series)
        wall_now = time.monotonic() - t_start
        if wall_now > 0:
            obs_metrics.pipeline_occupancy(1.0 - wait_s / wall_now,
                                           stage="pairs")
    if r1 != n:
        raise ValueError(
            f"streamed pair pass saw {r1} rows, expected {n}")
    timing.counter("pairs-streamed-stripes", stripes)
    wall = time.monotonic() - t_start
    if wall > 0 and stripes:
        obs_metrics.pipeline_occupancy(1.0 - wait_s / wall,
                                       stage="pairs")


def threshold_pairs_streamed(
    blocks_iter,
    n: int,
    k: int,
    min_ani: float,
    sketch_size: int,
    mesh: "Optional[Mesh]" = None,
    block: int = 256,
    row_tile: int = 64,
) -> dict[tuple[int, int], float]:
    """`threshold_pairs` over an ARRIVING sketch stream — drains
    `iter_threshold_pairs_streamed` into one dict. The result is
    IDENTICAL to `threshold_pairs(full_matrix, ...)`; see the
    generator's docstring for the exactness argument."""
    out: dict[tuple[int, int], float] = {}
    for _r1, inc in iter_threshold_pairs_streamed(
            blocks_iter, n, k=k, min_ani=min_ani,
            sketch_size=sketch_size, mesh=mesh, block=block,
            row_tile=row_tile):
        out.update(inc)
    return out
