"""ctypes binding for the native collision counter (csrc/collision.c).

Exposes

    collision_pair_counts_c(mat, lens, big_run) -> (pi, pj, counts)

the compiled twin of ops/collision.collision_pair_counts — radix sort
of the (hash, row) multiset plus a run walk with hashmap pair
accumulation, replacing the numpy argsort/fancy-index/compaction
pipeline that dominates the screen at large N (249 s at N=100k,
measured 2026-07-31). Bit-identical triples in the same unique-sorted
order. Build/load failures raise ImportError (cached by utils/cbuild);
set GALAH_TPU_NO_CCOLLISION=1 to force the numpy path.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from galah_tpu.utils import cbuild

_lib = cbuild.build_and_load(
    "collision.c", "_libcollision",
    out_dir=os.path.dirname(os.path.abspath(__file__)),
    disable_env="GALAH_TPU_NO_CCOLLISION")
_fn = _lib.galah_collision_pair_counts
_fn.restype = ctypes.c_int64
_fn.argtypes = [
    ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
]


def collision_pair_counts_c(mat: np.ndarray, lens: np.ndarray,
                            big_run: int):
    """Exact |A ∩ B| for every colliding row pair; (pi, pj, counts)
    int64 with pi < pj, ordered by (pi, pj) — the numpy twin's
    np.unique key order."""
    mat = np.ascontiguousarray(mat, dtype=np.uint64)
    lens64 = np.ascontiguousarray(lens, dtype=np.int64)
    n, width = mat.shape
    empty = (np.zeros(0, np.int64),) * 3
    if n == 0 or int(lens64.sum()) == 0:
        return empty

    cap = max(1 << 20, 16 * n)
    for _ in range(2):
        pi = np.empty(cap, dtype=np.int64)
        pj = np.empty(cap, dtype=np.int64)
        counts = np.empty(cap, dtype=np.int64)
        found = _fn(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            n, width,
            lens64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            int(big_run),
            pi.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            pj.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            cap)
        if found < 0:
            raise MemoryError("galah_collision_pair_counts failed")
        if found <= cap:
            break
        cap = int(found)
    else:  # pragma: no cover - second pass always fits by construction
        raise RuntimeError("collision pair capacity still insufficient")
    if found == 0:
        return empty
    pi, pj, counts = pi[:found], pj[:found], counts[:found]
    order = np.argsort(pi * n + pj)  # match numpy np.unique key order
    return pi[order], pj[order], counts[order]
