"""Fixed-capacity on-device pair work queue (the megakernel substrate).

The host↔device boundary is the dispatch lever (PR 6: ~250→~16 groups
per round; PR 14: host orchestration, not device math, dominates the
e2e wall). The queue removes the per-round surviving-pair round-trip:
pairs that survive the screen are *enqueued on device* as compacted
``(i, j, ani)`` triples and consumed there by the fused slab fold
(ops/megakernel.py) — the surviving pair list of a round never
materializes on host.

Layout: a power-of-two-capacity ring of three parallel buffers
(``qi``/``qj`` int32, ``qv`` float64) plus two device scalars, the
compacted entry count and a cumulative overflow counter. Invariants
the megakernel relies on (tested in tests/test_megakernel.py):

  * **Compaction** — entries always occupy the dense prefix
    ``[0, count)``; :func:`enqueue` scatters each batch at
    ``count + cumsum(mask) - 1``, so a consumer needs only ``count``,
    never a validity scan.
  * **Bounded, exact overflow** — an enqueue that would pass capacity
    stores the prefix that fits and counts the rest in ``overflow``;
    the returned stored-mask tells the producer exactly which pairs
    must take the host spill path, so results stay exact at ANY
    capacity (the overflow-capacity parity sweep pins this).
  * **Pow2 bucketing** — enqueue batches pad to power-of-two buckets
    (same ``_bucket`` discipline as ops/greedy_select), so a run
    compiles O(log cap) enqueue variants, not one per batch size
    (GL3xx recompile-churn budget).

The drain walks the compacted index in a ``lax.while_loop`` (block
copies until ``count`` is passed) — used by the spill path and tests;
the megakernel's fold consumes the buffers in place without draining.

Capacity comes from ``GALAH_TPU_QUEUE_CAP`` (default 4096 pairs,
rounded up to a power of two; docs/dataflow.md has the flag table).

Bit-identity contract: ``qv`` is float64 end to end and the queue
never transforms values — it stores and returns the exact IEEE bits
the screen produced (same contract as ops/greedy_select).
"""

from __future__ import annotations

import logging
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from galah_tpu.obs.profile import profiled
from galah_tpu.utils import timing

jax.config.update("jax_enable_x64", True)

logger = logging.getLogger(__name__)

#: Default queue capacity in pairs (GALAH_TPU_QUEUE_CAP overrides;
#: rounded up to a power of two, floor _MIN_CAP).
DEFAULT_QUEUE_CAP = 4096
_MIN_CAP = 8

#: Entries copied per drain while_loop iteration.
_DRAIN_BLOCK = 64

# Numeric-determinism contract checked by `galah-tpu lint` (GL9xx):
# the queue stores decision values verbatim — no accumulation, no
# dtype change — so the fold downstream compares the same f64 bits
# the host path would.
DETERMINISM_CONTRACT = {
    "family": "device_queue",
    "dtype": "float64",
    "functions": ["_enqueue_jit", "_drain_jit"],
}

# Pipeline-discipline annotation (GL10xx): the jitted queue programs
# are device-round bodies — host-sync calls inside them would
# reintroduce the per-round round-trip the queue exists to remove
# (GL1006).
PIPELINE_STAGE = {  # galah-lint: ignore[GL704] the engine owns flow attribution
    "device_round": ["_enqueue_jit", "_drain_jit"],
}


def resolve_queue_cap() -> int:
    """Queue capacity from GALAH_TPU_QUEUE_CAP, power-of-two rounded.

    Malformed or non-positive values fall back to the default with a
    warning (never an error: capacity only moves the spill boundary,
    results are exact at any value)."""
    raw = (os.environ.get("GALAH_TPU_QUEUE_CAP") or "").strip()
    cap = DEFAULT_QUEUE_CAP
    if raw:
        try:
            cap = int(raw)
        except ValueError:
            logger.warning("ignoring malformed GALAH_TPU_QUEUE_CAP=%r "
                           "(want a positive integer)", raw)
            cap = DEFAULT_QUEUE_CAP
        if cap < 1:
            logger.warning("ignoring non-positive GALAH_TPU_QUEUE_CAP"
                           "=%d", cap)
            cap = DEFAULT_QUEUE_CAP
    return _pow2_at_least(cap)


def _pow2_at_least(n: int) -> int:
    b = _MIN_CAP
    while b < n:
        b *= 2
    return b


@profiled("queue.enqueue")
@jax.jit
def _enqueue_jit(qi: jax.Array, qj: jax.Array, qv: jax.Array,
                 count: jax.Array, overflow: jax.Array,
                 i: jax.Array, j: jax.Array, v: jax.Array,
                 valid: jax.Array):
    """Scatter one batch into the compacted prefix.

    ``valid`` masks batch padding. Each valid entry lands at
    ``count + (its rank among valid entries)``; entries whose slot
    would pass capacity are dropped (out-of-range scatter with
    ``mode='drop'``) and counted in ``overflow``. Returns the updated
    buffers/scalars plus the stored-mask."""
    cap = qi.shape[0]
    slots = count + jnp.cumsum(valid.astype(count.dtype)) - 1
    stored = valid & (slots < cap)
    idx = jnp.where(stored, slots, cap)  # cap == dropped
    qi = qi.at[idx].set(i, mode="drop")
    qj = qj.at[idx].set(j, mode="drop")
    qv = qv.at[idx].set(v, mode="drop")
    n_stored = jnp.sum(stored)
    n_valid = jnp.sum(valid)
    return (qi, qj, qv, count + n_stored,
            overflow + (n_valid - n_stored), stored)


@profiled("queue.drain")
@jax.jit
def _drain_jit(qi: jax.Array, qj: jax.Array, qv: jax.Array,
               count: jax.Array):
    """Compacted-index drain: a ``lax.while_loop`` walks the dense
    prefix in ``_DRAIN_BLOCK``-entry copies until ``count`` is passed.
    Slots past ``count`` come back as (0, 0, NaN) — never consumable
    (NaN compares False against any threshold)."""
    cap = qi.shape[0]
    oi = jnp.zeros(cap, dtype=qi.dtype)
    oj = jnp.zeros(cap, dtype=qj.dtype)
    ov = jnp.full(cap, jnp.nan, dtype=qv.dtype)

    def cond(carry):
        k = carry[0]
        return k < count

    def body(carry):
        k, oi, oj, ov = carry
        idx = k + jnp.arange(_DRAIN_BLOCK)
        take = idx < count
        src = jnp.minimum(idx, cap - 1)
        tgt = jnp.where(take, idx, cap)  # cap == dropped
        oi = oi.at[tgt].set(qi[src], mode="drop")
        oj = oj.at[tgt].set(qj[src], mode="drop")
        ov = ov.at[tgt].set(qv[src], mode="drop")
        return k + _DRAIN_BLOCK, oi, oj, ov

    _, oi, oj, ov = jax.lax.while_loop(
        cond, body, (jnp.zeros_like(count), oi, oj, ov))
    return oi, oj, ov


def _bucket(n: int) -> int:
    b = _MIN_CAP
    while b < n:
        b *= 2
    return b


class PairQueue:
    """Host handle over the device-resident queue buffers.

    The buffers live as jax arrays across enqueues — between the
    screen and the fold nothing transfers back to host. The host-side
    methods do the padding/bucketing and the (intentional, measured)
    scalar reads; the jitted bodies above stay sync-free (GL1006).
    """

    def __init__(self, cap: int = None) -> None:
        if cap is None:
            cap = resolve_queue_cap()
        self.cap = _pow2_at_least(int(cap))
        self._qi = jnp.zeros(self.cap, dtype=jnp.int32)
        self._qj = jnp.zeros(self.cap, dtype=jnp.int32)
        self._qv = jnp.full(self.cap, jnp.nan, dtype=jnp.float64)
        self._count = jnp.zeros((), dtype=jnp.int64)
        self._overflow = jnp.zeros((), dtype=jnp.int64)

    @property
    def count(self) -> int:
        return int(self._count)

    @property
    def overflow(self) -> int:
        return int(self._overflow)

    def enqueue(self, i: np.ndarray, j: np.ndarray,
                v: np.ndarray) -> int:
        """Append one batch of pairs; returns how many were stored.

        Pads the batch to a power-of-two bucket (masked) so repeated
        enqueues reuse a handful of compiled variants. A return below
        ``len(i)`` means the queue hit capacity mid-batch: the stored
        prefix is in the queue, the rest counted in ``overflow`` —
        the producer spills those to the host path."""
        m = len(i)
        if m == 0:
            return 0
        b = _bucket(m)
        ip = np.zeros(b, dtype=np.int32)
        jp = np.zeros(b, dtype=np.int32)
        vp = np.full(b, np.nan, dtype=np.float64)
        maskp = np.zeros(b, dtype=bool)
        ip[:m], jp[:m], vp[:m] = i, j, v
        maskp[:m] = True
        timing.dispatch(1)
        timing.counter("greedy-select-dispatches", 1)
        (self._qi, self._qj, self._qv, self._count, self._overflow,
         stored) = _enqueue_jit(
            self._qi, self._qj, self._qv, self._count, self._overflow,
            jnp.asarray(ip), jnp.asarray(jp), jnp.asarray(vp),
            jnp.asarray(maskp))
        return int(np.asarray(stored).sum())

    def drain(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The compacted entries as host triples; resets the count.

        The spill/test-facing consumer — the megakernel fold reads
        the device buffers in place instead."""
        timing.dispatch(1)
        oi, oj, ov = _drain_jit(self._qi, self._qj, self._qv,
                                self._count)
        m = self.count
        self.reset()
        return (np.asarray(oi)[:m], np.asarray(oj)[:m],
                np.asarray(ov)[:m])

    def reset(self, clear_overflow: bool = False) -> None:
        """Empty the queue (count to zero). The overflow counter is
        cumulative per run unless explicitly cleared."""
        self._count = jnp.zeros((), dtype=jnp.int64)
        if clear_overflow:
            self._overflow = jnp.zeros((), dtype=jnp.int64)
