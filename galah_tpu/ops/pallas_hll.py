"""Pallas TPU kernel: pairwise HyperLogLog union statistics.

The HLL pairwise pass (the dashing-equivalent precluster hot op,
reference: src/dashing.rs:76-100) needs, for every sketch pair (r, c),
the union register sum ``sum_m 2^-max(reg_r, reg_c)`` and the count of
zero union registers. Since ``2^-x`` is strictly decreasing, the
register-wise max is the elementwise **min** in pow2 space, so the host
precomputes ``pow2 = exp2(-regs)`` once per sketch matrix and the kernel
inner loop is pure VPU min+add — no transcendentals, no gathers.

The kernel tiles the register axis: grid step ``c`` loads a
(block_rows, chunk) slab of row sketches and a (block_cols, chunk) slab
of column sketches into VMEM and accumulates into the persistent
(block_rows, block_cols) output block (constant out index map, init at
c == 0). VMEM footprint is two input slabs + two output tiles,
independent of the full register width m.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# Static kernel contract checked by `galah-tpu lint` (GL1xx):
# representative bindings at the largest tile the row-block driver
# feeds this kernel (512x512 tile, m=4096 registers, chunk=1024).
PALLAS_CONTRACT = {
    "hll_union_stats_tile": {
        "bindings": {"br": 512, "bc": 512, "chunk": 1024},
        "in_dtypes": ["float32", "float32"],
        "kernel_fns": ["_kernel"],
    },
}


def _kernel(rows_ref, cols_ref, powsum_ref, zeros_ref):
    # Grid (m/chunk,): step c reduces the c-th register chunk of every
    # row sketch against every column sketch, accumulating into the
    # persistent (Br, Bc) output blocks (constant out index map, init at
    # c == 0). The fori loop walks row sketches one at a time so the
    # live intermediate is (Bc, chunk), never (Br, Bc, chunk).
    c = pl.program_id(0)

    @pl.when(c == 0)
    def _():
        powsum_ref[:] = jnp.zeros_like(powsum_ref)
        zeros_ref[:] = jnp.zeros_like(zeros_ref)

    cols = cols_ref[:]          # (Bc, chunk) f32

    def body(r, _):
        row = rows_ref[pl.ds(r, 1), :]                # (1, chunk)
        mn = jnp.minimum(row, cols)                   # (Bc, chunk)
        powsum_ref[pl.ds(r, 1), :] += jnp.sum(mn, axis=1)[None, :]
        zeros_ref[pl.ds(r, 1), :] += jnp.sum(
            (mn == 1.0).astype(jnp.float32), axis=1)[None, :]
        return jnp.int32(0)

    # int32 bounds: under jax_enable_x64 a python-int fori_loop index
    # becomes int64, which Mosaic cannot lower.
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(rows_ref.shape[0]), body,
                      jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("chunk", "interpret"))
def hll_union_stats_tile(
    rows_pow2: jax.Array,   # (Br, m) f32, 2^-register
    cols_pow2: jax.Array,   # (Bc, m) f32
    chunk: int = 1024,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """(powsum, zeros) f32 (Br, Bc) tiles of the pairwise HLL union.

    ``powsum[r, c] = sum_m 2^-max_reg`` and ``zeros[r, c]`` counts union
    registers equal to 0 — exactly the two reductions hll._estimate
    needs. m must be a multiple of ``chunk`` (register widths are powers
    of two >= 1024 in practice).
    """
    br, m = rows_pow2.shape
    bc = cols_pow2.shape[0]
    if m % chunk:
        raise ValueError(f"register width {m} not a multiple of {chunk}")
    grid = (m // chunk,)
    # index-map zeros are written as c*0 so they carry the grid index's
    # own dtype: a literal 0 canonicalizes to int64 under x64, which
    # Mosaic rejects at the MLIR boundary.
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, chunk), lambda c: (c * 0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bc, chunk), lambda c: (c * 0, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((br, bc), lambda c: (c * 0, c * 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, bc), lambda c: (c * 0, c * 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((br, bc), jnp.float32),
            jax.ShapeDtypeStruct((br, bc), jnp.float32),
        ],
        interpret=interpret,
    )(rows_pow2, cols_pow2)
