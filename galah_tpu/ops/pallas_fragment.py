"""Pallas TPU kernel: blocked multi-pair fragment-ANI window matching.

The exact-ANI refinement counts, per (query, reference) genome pair,
how many of each query window's surviving k-mer hashes are members of
the reference's sorted distinct-hash set. The XLA path is one
`searchsorted` dispatch per pair (ops/fragment_ani.py::
_window_match_counts_impl) — at campaign pair volumes the per-pair
round trip dominates, the same wall PR 2's pairlist kernel removed
from the screen. This module is the fragment-ANI twin: MULTIPLE pairs
per grid launch, dense block compares on u32 hi/lo planes, int32
per-element hit flags that the host folds into the identical
per-window (matched, total) integers.

Membership without dynamic indexing (hardware-driven, like the
pairlist kernel's design note): Mosaic rejects dynamic sublane loads
on real v5e, and an in-kernel binary search is all dynamic gathers.
Instead the HOST plans which reference blocks each query block can
possibly hit — both sides are sorted, so query block j's values lie
in [first_j, last_j] and only the reference blocks covering that value
range (a `searchsorted` on the host, O(jobs log H)) need to be
compared. Those block ids become a gather on the host; the kernel
itself sees only static shapes and BlockSpec index maps:

  * JOB = one query block: QB = 8*128 = 1024 consecutive sorted query
    elements in the dense kernels' transposed a-layout — element
    l*8 + s of the job at row s, lane l of an (8, 128) u32 plane pair;
  * each job scans SPAN consecutive gathered reference blocks of
    RB = 8*128 = 1024 sorted elements in b-layout (8, 128) planes;
    SPAN is the pow2-bucketed max over the launch's jobs, so the grid
    is rectangular: grid = (jobs, SPAN), out block revisited across
    the SPAN dim with an `@pl.when(s == 0)` init;
  * gathered windows are SUPERSETS of the needed range — safe because
    any extra block holds only values outside [first_j, last_j] (no
    false hits) — and the padding block is a dedicated ALL-SENTINEL
    block appended to the global block table, never a repeated real
    block (a repeat would double-count an element's membership: the
    reference set is distinct, so each element matches at most once
    across distinct blocks).

u64 hashes are split into hi/lo u32 planes ON THE HOST (numpy), so no
64-bit dtype ever reaches the kernel boundary (GL106). Sentinel-padded
query tail slots (u32 planes both 0xFFFFFFFF) are masked in-kernel;
sentinel reference slots can only equal sentinel queries, which that
same mask removes.

Pairs are packed by the caller into pow2-bucketed groups (padded
window count, padded ref-set size — ops/fragment_ani.py's
_bucket_pow2/pad_windows/pad_ref_set discipline) so launches compile
a small (job-bucket x span-bucket) variant lattice. Per-element hit
flags come back in element order; `fragment_ani` folds them with one
`np.bincount` per pair into the per-window matched counts that flow
unchanged through `_directed_from_counts_arrays` — DirectedANI floats
bit-identical to the XLA and C paths (integer counts are exact, and
the downstream f64 reduction is shared).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from galah_tpu.obs.profile import profiled
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pallas_pairwise import _zi
from galah_tpu.utils import timing

A_SUB = 8
B_LANE = 128

# Query elements per job: an (A_SUB, QLA) u32 plane pair in a-layout.
QLA = 128
# Reference sublane rows per block: an (RSB, B_LANE) plane pair.
RSB = 8

# Jobs per launch before the packer cuts a new launch: 2048 jobs is
# 2M query elements (16 MB of u32 planes) — big enough that the grid
# amortizes dispatch, small enough that the gathered reference planes
# (jobs * span * RB * 8 B) stay bounded by _GATHER_BYTES_CAP below.
LAUNCH_JOB_CAP = 2048

# Host-side cap on one launch's gathered reference planes. The gather
# duplicates blocks shared between jobs, so the bound is on the
# DUPLICATED volume: job_slots * span * RB elements * 8 B/elem.
_GATHER_BYTES_CAP = 256 << 20

# Job-slot bucket floor: launches are padded to pow2 job counts so the
# compile cache sees a small lattice, mirroring _bucket_pow2's role on
# the window/ref axes.
_JOB_FLOOR = 8

_U32_SENT = 0xFFFFFFFF

# Static kernel contract checked by `galah-tpu lint` (GL1xx):
# representative bindings at the production geometry (QLA=128, RSB=8)
# and a 2-block scan window.
PALLAS_CONTRACT = {
    "_window_hits_jit": {
        "bindings": {"qla": 128, "rsb": 8, "span": 2},
        "in_dtypes": ["uint32", "uint32", "uint32", "uint32"],
        "kernel_fns": ["_make_fragment_kernel"],
    },
}

# Numeric-determinism contract checked by `galah-tpu lint` (GL9xx):
# per-element membership hits are exact integer counts; the kernel and
# the XLA fallback must agree bit-for-bit for the same window packing.
DETERMINISM_CONTRACT = {
    "family": "fragment",
    "dtype": "int32",
    "functions": ["window_element_hits", "_window_hits_jit"],
}


def fragment_pairs_per_launch() -> Optional[int]:
    """Optional cap on pairs packed into one launch
    (GALAH_TPU_FRAGMENT_PAIRS) — the bench sweep knob; unset means the
    job/volume caps alone decide."""
    raw = os.environ.get("GALAH_TPU_FRAGMENT_PAIRS", "")
    if not raw:
        return None
    return max(1, int(raw))


def _make_fragment_kernel(qla: int, rsb: int):
    """Kernel body: one (job, span-step) program accumulating per-
    element membership hits of an (A_SUB, qla) query block against an
    (rsb, B_LANE) reference block."""

    def kernel(qh_ref, ql_ref, rh_ref, rl_ref, hits_ref):
        s = pl.program_id(1)

        @pl.when(s == 0)
        def _init():
            hits_ref[...] = jnp.zeros_like(hits_ref)

        qh = qh_ref[...]
        ql = ql_ref[...]
        sent = jnp.uint32(_U32_SENT)
        valid = jnp.logical_not((qh == sent) & (ql == sent))

        # Per query lane column: (A_SUB, 1) hi/lo against each of the
        # reference block's (1, B_LANE) row chunks — every broadcast
        # compare is one native (8, 128) vreg op. The reference set is
        # distinct and scanned blocks are distinct, so each element
        # hits at most once; summing lanes yields the 0/1 flag.
        cols = []
        for col in range(qla):
            ch = qh[:, col:col + 1]
            cl = ql[:, col:col + 1]
            hit = jnp.zeros((A_SUB, B_LANE), dtype=jnp.int32)
            for row in range(rsb):
                rh = rh_ref[row:row + 1, :]
                rl = rl_ref[row:row + 1, :]
                hit = hit + ((ch == rh) & (cl == rl)).astype(jnp.int32)
            cols.append(jnp.sum(hit, axis=1, keepdims=True,
                                dtype=jnp.int32))
        step = jnp.concatenate(cols, axis=1)
        hits_ref[...] = hits_ref[...] + step * valid.astype(jnp.int32)

    return kernel


def _window_hits_jit(
    q_hi: jax.Array,   # uint32 (jobs*A_SUB, qla) a-layout query plane
    q_lo: jax.Array,
    r_hi: jax.Array,   # uint32 (jobs*span*rsb, B_LANE) gathered blocks
    r_lo: jax.Array,
    span: int,
    interpret: bool,
) -> jax.Array:
    """One launch: per-element membership flags, int32 (jobs*A_SUB,
    qla) in the query planes' layout."""
    n_rows, qla = q_hi.shape
    n_jobs = n_rows // A_SUB
    rsb = r_hi.shape[0] // max(n_jobs * span, 1)
    kernel = _make_fragment_kernel(qla, rsb)
    return pl.pallas_call(
        kernel,
        grid=(n_jobs, span),
        in_specs=[
            pl.BlockSpec((A_SUB, qla), lambda j, s: (j, _zi(j)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((A_SUB, qla), lambda j, s: (j, _zi(j)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rsb, B_LANE),
                         lambda j, s, sp=span: (j * sp + s, _zi(j)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rsb, B_LANE),
                         lambda j, s, sp=span: (j * sp + s, _zi(j)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((A_SUB, qla), lambda j, s: (j, _zi(j)),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_rows, qla), jnp.int32),
        interpret=interpret,
    )(q_hi, q_lo, r_hi, r_lo)


_window_hits = profiled("fragment.window_hits")(
    jax.jit(_window_hits_jit, static_argnames=("span", "interpret")))


def _bucket_jobs(n: int) -> int:
    b = _JOB_FLOOR
    while b < n:
        b <<= 1
    return b


def _plan_pair(qh: np.ndarray, ref: np.ndarray,
               n_rblocks: int) -> Tuple[int, np.ndarray, np.ndarray]:
    """(n_jobs, lo_block, span) for one pair: which reference blocks
    each query block's sorted value range can possibly hit. Computed
    on the UNPADDED reference (padding is all-sentinel, above every
    valid hash, so padded blocks never need scanning — but scanning
    one as part of a pow2 window is harmless)."""
    qb = A_SUB * QLA
    rb = RSB * B_LANE
    n_q = qh.shape[0]
    n_jobs = -(-n_q // qb)
    if n_jobs == 0:
        z = np.zeros(0, dtype=np.int64)
        return 0, z, z
    firsts = qh[::qb]
    last_idx = np.minimum(np.arange(1, n_jobs + 1) * qb, n_q) - 1
    lasts = qh[last_idx]
    lo = np.searchsorted(ref, firsts, side="left") // rb
    hi = -(-np.searchsorted(ref, lasts, side="right") // rb)
    hi = np.minimum(np.maximum(hi, lo + 1), max(n_rblocks, 1))
    lo = np.minimum(lo, hi - 1)
    return n_jobs, lo.astype(np.int64), (hi - lo).astype(np.int64)


def _pow2_span(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def window_element_hits(
    items: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray]],
    interpret: bool = False,
) -> "List[np.ndarray]":
    """Per-element membership flags for many (query, reference) pairs.

    `items[i]` is `(qh, ref, ref_padded)`: the pair's sorted surviving
    query hashes (uint64, no sentinels — GenomeProfile.sorted_query()'s
    first array), the reference's sorted distinct set, and its
    sentinel-padded pow2 twin (GenomeProfile.padded_ref_set()).
    Returns one int32 0/1 array per item, aligned to `qh`'s order —
    `hits[e] == 1` iff `qh[e]` is a member of `ref`.

    Pairs are packed into as few launches as the job/volume caps allow
    (one Mosaic grid per launch covers every packed pair); reference
    planes are deduplicated by profile identity before the per-job
    block gather.
    """
    qb = A_SUB * QLA
    rb = RSB * B_LANE
    out: "List[Optional[np.ndarray]]" = [None] * len(items)

    # live pairs only; empty queries hit nothing by definition
    live: "List[int]" = []
    plans = {}
    for i, (qh, ref, ref_padded) in enumerate(items):
        if qh.shape[0] == 0:
            out[i] = np.zeros(0, dtype=np.int32)
            continue
        n_rblocks = ref_padded.shape[0] // rb
        plans[i] = _plan_pair(qh, ref, n_rblocks)
        live.append(i)

    pair_cap = fragment_pairs_per_launch()
    pos = 0
    while pos < len(live):
        # greedy launch packing under the job / gather-volume / pair
        # caps; a single oversized pair still launches alone
        end = pos
        jobs_total = 0
        span_max = 1
        while end < len(live):
            i = live[end]
            n_jobs, _lo, span = plans[i]
            nj = jobs_total + n_jobs
            sp = max(span_max, _pow2_span(int(span.max())))
            vol = _bucket_jobs(nj) * sp * rb * 8
            if end > pos and (nj > LAUNCH_JOB_CAP
                              or vol > _GATHER_BYTES_CAP
                              or (pair_cap is not None
                                  and end - pos >= pair_cap)):
                break
            jobs_total, span_max = nj, sp
            end += 1
        chunk = live[pos:end]
        pos = end
        _launch(items, plans, chunk, jobs_total, span_max, out,
                interpret)
    return out  # type: ignore[return-value]


def _launch(items, plans, chunk, jobs_total, span, out,
            interpret) -> None:
    """Pack one launch's query/reference planes, run the kernel, and
    scatter per-pair hit flags back into `out`."""
    qb = A_SUB * QLA
    rb = RSB * B_LANE
    n_jobs_pad = _bucket_jobs(jobs_total)

    # global reference block table, deduplicated by profile identity
    # (padded_ref_set() is cached per profile, so id() is stable);
    # block G is the dedicated all-sentinel padding block
    ref_base: "dict[int, int]" = {}
    ref_parts: "List[np.ndarray]" = []
    n_gblocks = 0
    for i in chunk:
        rp = items[i][2]
        if id(rp) not in ref_base:
            ref_base[id(rp)] = n_gblocks
            ref_parts.append(rp)
            n_gblocks += rp.shape[0] // rb
    ref_cat = (np.concatenate(ref_parts) if ref_parts
               else np.zeros(0, dtype=np.uint64))
    g_hi = (ref_cat >> np.uint64(32)).astype(np.uint32).reshape(
        n_gblocks, RSB, B_LANE)
    g_lo = ref_cat.astype(np.uint32).reshape(n_gblocks, RSB, B_LANE)
    sent_block = np.full((1, RSB, B_LANE), _U32_SENT, dtype=np.uint32)
    g_hi = np.concatenate([g_hi, sent_block])
    g_lo = np.concatenate([g_lo, sent_block])
    sent_idx = n_gblocks

    # per-job gathered block ids + the packed query planes
    job_blocks = np.full((n_jobs_pad, span), sent_idx, dtype=np.int64)
    q_cat = np.full(n_jobs_pad * qb, np.uint64(SENTINEL),
                    dtype=np.uint64)
    cursor = 0
    spans_needed = 0
    for i in chunk:
        qh, _ref, rp = items[i]
        n_jobs, lo, pair_span = plans[i]
        n_rblocks = rp.shape[0] // rb
        base = ref_base[id(rp)]
        # window start: shift left so the pow2 window stays in range
        # (superset scanning is safe; block REPETITION is not, so when
        # span exceeds the reference the tail maps to the sentinel
        # block instead of wrapping)
        r0 = np.maximum(np.minimum(lo, n_rblocks - span), 0)
        ids = r0[:, None] + np.arange(span, dtype=np.int64)[None, :]
        rows = np.where(ids < n_rblocks, base + ids, sent_idx)
        job_blocks[cursor:cursor + n_jobs] = rows
        q_cat[cursor * qb:cursor * qb + qh.shape[0]] = qh
        cursor += n_jobs
        spans_needed += int(pair_span.sum())

    r_hi = g_hi[job_blocks.reshape(-1)].reshape(
        n_jobs_pad * span * RSB, B_LANE)
    r_lo = g_lo[job_blocks.reshape(-1)].reshape(
        n_jobs_pad * span * RSB, B_LANE)
    q_hi = (q_cat >> np.uint64(32)).astype(np.uint32).reshape(
        n_jobs_pad, QLA, A_SUB).transpose(0, 2, 1).reshape(
        n_jobs_pad * A_SUB, QLA)
    q_lo = q_cat.astype(np.uint32).reshape(
        n_jobs_pad, QLA, A_SUB).transpose(0, 2, 1).reshape(
        n_jobs_pad * A_SUB, QLA)

    timing.counter("fragment-pallas-launches", 1)
    timing.counter("fragment-pallas-pairs", len(chunk))
    timing.counter("fragment-pallas-jobs", jobs_total)
    timing.counter("fragment-pallas-job-slots", n_jobs_pad)
    timing.counter("fragment-pallas-ref-blocks", n_jobs_pad * span)
    timing.counter("fragment-pallas-ref-blocks-needed", spans_needed)
    timing.dispatch()
    hits = _window_hits(jnp.asarray(q_hi), jnp.asarray(q_lo),
                        jnp.asarray(r_hi), jnp.asarray(r_lo),
                        span=span, interpret=interpret)
    timing.dispatch(sync=True)
    flat = np.asarray(hits).reshape(
        n_jobs_pad, A_SUB, QLA).transpose(0, 2, 1).reshape(-1)

    from galah_tpu.obs import metrics as obs_metrics

    obs_metrics.gauge(
        "fragment.pallas_job_occupancy",
        help="Real jobs / padded job slots in the last fragment-ANI "
             "Pallas launch (pow2 job bucketing waste)",
        unit="fraction").set(jobs_total / n_jobs_pad)
    obs_metrics.gauge(
        "fragment.pallas_span_occupancy",
        help="Needed reference blocks / scanned reference blocks in "
             "the last fragment-ANI Pallas launch (rectangular-span "
             "padding waste)",
        unit="fraction").set(
        spans_needed / max(n_jobs_pad * span, 1))

    cursor = 0
    for i in chunk:
        qh = items[i][0]
        n_jobs = plans[i][0]
        out[i] = flat[cursor * qb:cursor * qb + qh.shape[0]]
        cursor += n_jobs
