"""Vectorized MurmurHash3 x64_128 (h1) over fixed-length byte rows — numpy.

This is the semantic reference for the JAX kernel in ops/hashing.py, and the
host-side fallback. The reference's finch backend hashes canonical k-mer
ASCII bytes with murmurhash3_x64_128 seed 0 and keeps the low u64
(reference: src/finch.rs:33-47 parameterizes finch's sketcher; the hash
itself lives in the finch crate, reproduced here from the MurmurHash3 spec).

All arithmetic is uint64 wrap-around; numpy arrays wrap silently.
"""

from __future__ import annotations

import numpy as np

_C1 = np.uint64(0x87C37B91114253D5)
_C2 = np.uint64(0x4CF5AD432745937F)


def _rotl64(x: np.ndarray, r: int) -> np.ndarray:
    r = np.uint64(r)
    return (x << r) | (x >> (np.uint64(64) - r))


def _fmix64(x: np.ndarray) -> np.ndarray:
    x = x ^ (x >> np.uint64(33))
    x = x * np.uint64(0xFF51AFD7ED558CCD)
    x = x ^ (x >> np.uint64(33))
    x = x * np.uint64(0xC4CEB9FE1A85EC53)
    x = x ^ (x >> np.uint64(33))
    return x


def _le_u64(block: np.ndarray) -> np.ndarray:
    """Little-endian uint64 from uint8 rows of shape (..., 8)."""
    out = np.zeros(block.shape[:-1], dtype=np.uint64)
    for b in range(8):
        out |= block[..., b].astype(np.uint64) << np.uint64(8 * b)
    return out


def murmur3_x64_128_h1(keys: np.ndarray, seed: int = 0) -> np.ndarray:
    """h1 of MurmurHash3_x64_128 for each row of `keys` (uint8, shape (n, L)).

    Row length L is static (all keys same length), matching the fixed-k k-mer
    use case. Returns uint64 array of shape (n,).
    """
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    n, length = keys.shape
    h1 = np.full(n, np.uint64(seed), dtype=np.uint64)
    h2 = np.full(n, np.uint64(seed), dtype=np.uint64)

    nblocks = length // 16
    for blk in range(nblocks):
        k1 = _le_u64(keys[:, blk * 16: blk * 16 + 8])
        k2 = _le_u64(keys[:, blk * 16 + 8: blk * 16 + 16])
        k1 = k1 * _C1
        k1 = _rotl64(k1, 31)
        k1 = k1 * _C2
        h1 = h1 ^ k1
        h1 = _rotl64(h1, 27)
        h1 = h1 + h2
        h1 = h1 * np.uint64(5) + np.uint64(0x52DCE729)
        k2 = k2 * _C2
        k2 = _rotl64(k2, 33)
        k2 = k2 * _C1
        h2 = h2 ^ k2
        h2 = _rotl64(h2, 31)
        h2 = h2 + h1
        h2 = h2 * np.uint64(5) + np.uint64(0x38495AB5)

    tail = keys[:, nblocks * 16:]
    rem = length & 15
    k1 = np.zeros(n, dtype=np.uint64)
    k2 = np.zeros(n, dtype=np.uint64)
    if rem > 8:
        for b in range(rem - 1, 7, -1):
            k2 = k2 ^ (tail[:, b].astype(np.uint64) << np.uint64(8 * (b - 8)))
        k2 = k2 * _C2
        k2 = _rotl64(k2, 33)
        k2 = k2 * _C1
        h2 = h2 ^ k2
    if rem > 0:
        for b in range(min(rem, 8) - 1, -1, -1):
            k1 = k1 ^ (tail[:, b].astype(np.uint64) << np.uint64(8 * b))
        k1 = k1 * _C1
        k1 = _rotl64(k1, 31)
        k1 = k1 * _C2
        h1 = h1 ^ k1

    h1 = h1 ^ np.uint64(length)
    h2 = h2 ^ np.uint64(length)
    h1 = h1 + h2
    h2 = h2 + h1
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = h1 + h2
    # h2 = h2 + h1 would complete the 128-bit digest; only h1 is consumed.
    return h1
