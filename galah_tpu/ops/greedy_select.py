"""Device-resident greedy representative selection (round windows).

The engine's greedy scan (cluster/engine.py) decides, in quality order,
whether each genome becomes a representative: genome ``i`` is a rep iff
no earlier rep with a precluster hit has exact ANI >= threshold
(reference: src/clusterer.rs:155-225). The scan itself is sequential,
but its *decision state* is a tiny boolean lattice over a window of
genomes — this module keeps that lattice on device:

  * :func:`window_select` — one jitted segmented "peeling" fold over a
    window's intra-window ANI matrix plus the already-clustered flags
    from earlier rounds. Each fold iteration decides every genome whose
    earlier same-precluster neighbors are all decided (the union-find-
    style conflict resolution: a genome becomes a rep when no earlier
    *rep* neighbor reaches the threshold, and joins a cluster when one
    does). Segments never interact because cross-precluster entries of
    the matrix are NaN (no edge) by construction. The fold is exact
    greedy whenever it converges within the iteration budget; windows
    with decision chains deeper than the budget are *conflict windows*
    and the engine falls back to the host-order scan for them — rare,
    and measured (greedy-conflict-windows / greedy-host-fallback-
    windows counters).
  * :func:`membership_argmax` — the membership phase's argmax over the
    (non-rep x rep) candidate ANI matrix in the same jitted pass.
    ``jnp.argmax`` returns the FIRST maximum, which with columns in
    ascending rep order reproduces the host loop's strict-``>`` update
    exactly: ties go to the lowest rep index.

Bit-identity with the host scan relies on f64 end to end: inputs are
float64 (x64 enabled at import, same contract as ops/pairwise.py), the
threshold comparison is a single IEEE ``>=`` on the very same values
the host path would compare, and NaN (missing / gated-to-None ANI)
compares False exactly like the host's ``ani is not None`` guard.

Shapes are padded to power-of-two buckets so a run compiles a handful
of variants instead of one per window (GL3xx recompile-churn budget).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from galah_tpu.obs.profile import profiled
from galah_tpu.utils import timing

jax.config.update("jax_enable_x64", True)


# Greedy representative-selection strategies (GALAH_TPU_GREEDY_STRATEGY
# to pin; unset/"auto" resolves per backend):
#   device — round-based batched selection: K-genome windows, one
#            batched ANI dispatch per round, jitted segmented fold for
#            the intra-window decisions (this module)
#   host   — the per-genome windowed host scan (the pre-round engine)
GREEDY_STRATEGIES = ("device", "host")

#: Genomes speculatively taken per selection round (--rep-rounds).
DEFAULT_ROUND_WIDTH = 1024

#: Fold iterations before a window is declared a conflict window. Each
#: iteration decides at least one genome per undecided chain, so this
#: bounds the decision-dependency depth a window may carry; deeper
#: chains (every genome waiting on the previous one's rep/non-rep
#: status) fall back to the host-order scan, measured per window. Kept
#: at 2x the engine's materialization budget (engine.MAX_SUBROUNDS):
#: one rep emerges per sub-round per segment and each rep's members
#: decide one fold iteration later, so depth <= 2 * sub-rounds.
FOLD_ITERS = 32

_MIN_BUCKET = 8

# Numeric-determinism contract checked by `galah-tpu lint` (GL9xx):
# the device window fold and the host-order scan must pick the SAME
# representatives — selection compares scores, never re-accumulates,
# so any float handling here must preserve the stored values exactly.
DETERMINISM_CONTRACT = {
    "family": "greedy_select",
    "dtype": "float64",
    "functions": ["window_select", "membership_argmax",
                  "_window_select_jit", "_membership_argmax_jit"],
}


def resolve_greedy_strategy() -> Tuple[str, bool]:
    """(strategy, explicit) for the greedy representative scan.

    An explicit GALAH_TPU_GREEDY_STRATEGY pin always wins and its
    failures propagate (parity runs must never silently compare a
    fallback to itself — same contract as _resolve_fragment_strategy).
    AUTO resolves to the round-based device path everywhere: its
    decisions are bit-identical to the host scan by construction and
    it replaces O(preclusters + genomes/window) dispatches with
    O(genomes/round), which pays on every backend; a failure inside
    the device path demotes to the host scan for the run (the
    greedy-device-demoted counter records it).
    """
    env = (os.environ.get("GALAH_TPU_GREEDY_STRATEGY") or "").lower()
    if env in GREEDY_STRATEGIES:
        return env, True
    return "device", False


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


@profiled("greedy.window_select")
@jax.jit
def _window_select_jit(ani: jax.Array, ext: jax.Array, valid: jax.Array,
                       thr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Segmented greedy fold over one window.

    ``ani``: (W, W) float64, strictly upper-triangular by construction —
    ``ani[a, b]`` for a < b holds the exact ANI of the window's a-th and
    b-th genomes when they share a precluster AND a precluster hit, NaN
    otherwise (missing, gated-to-None, cross-segment, lower triangle).
    ``ext``: (W,) bool — genome already claimed by a rep from an earlier
    round. ``valid``: (W,) bool — padding mask. ``thr``: f64 scalar.

    Returns ``(rep, undecided)``: rep flags for decided genomes and the
    residual undecided mask (any True => the fold did not converge and
    the caller must treat the window as a conflict window).
    """
    edges = ani >= thr  # NaN compares False, like the host's None guard
    undecided = valid & ~ext
    rep = jnp.zeros_like(undecided)

    def body(_, carry):
        rep, undecided = carry
        # For column a: does any earlier (row) genome with an edge to a
        # remain undecided / sit decided-as-rep?
        earlier_und = jnp.any(edges & undecided[:, None], axis=0)
        earlier_rep = jnp.any(edges & rep[:, None], axis=0)
        new_rep = undecided & ~earlier_und & ~earlier_rep
        new_member = undecided & earlier_rep
        return rep | new_rep, undecided & ~new_rep & ~new_member

    rep, undecided = jax.lax.fori_loop(0, FOLD_ITERS, body,
                                       (rep, undecided))
    return rep, undecided


@profiled("greedy.membership_argmax")
@jax.jit
def _membership_argmax_jit(ani: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row argmax over the (non-rep x rep) candidate ANI matrix.

    ``ani``: (G, R) float64, NaN where a (genome, rep) pair is not a
    candidate (no precluster hit / ANI gated to None / column padding).
    Returns ``(best, has)``: the first-maximum column per row (ties to
    the lowest rep index, matching the host loop's strict-``>`` update)
    and whether the row had any candidate at all.
    """
    scored = jnp.where(jnp.isnan(ani), -jnp.inf, ani)
    best = jnp.argmax(scored, axis=1)
    has = jnp.any(jnp.isfinite(scored), axis=1)
    return best, has


def window_select(ani: np.ndarray, ext: np.ndarray,
                  thr: float) -> Tuple[np.ndarray, bool]:
    """Host wrapper around :func:`_window_select_jit` with bucketing.

    Pads to the next power-of-two window bucket (NaN matrix, False
    flags — padded slots never decide anything), runs the fold, and
    returns ``(rep_flags, converged)`` trimmed to the true width.
    """
    w = ani.shape[0]
    b = _bucket(w)
    mat = np.full((b, b), np.nan, dtype=np.float64)
    mat[:w, :w] = ani
    extp = np.zeros(b, dtype=bool)
    extp[:w] = ext
    validp = np.zeros(b, dtype=bool)
    validp[:w] = True
    timing.dispatch(1)
    timing.counter("greedy-select-dispatches", 1)
    rep, undecided = _window_select_jit(
        jnp.asarray(mat), jnp.asarray(extp), jnp.asarray(validp),
        jnp.float64(thr))
    rep = np.asarray(rep)[:w]
    converged = not bool(np.asarray(undecided)[:w].any())
    return rep, converged


def membership_argmax(ani: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host wrapper around :func:`_membership_argmax_jit` with bucketing.

    ``ani``: (G, R) float64 candidate matrix, NaN = not a candidate.
    Returns ``(best, has)`` trimmed to the true (G,) width; rows
    without any candidate carry ``has == False`` (the engine raises for
    them, exactly like the host loop's no-candidate RuntimeError).
    """
    g, r = ani.shape
    gb, rb = _bucket(g), _bucket(r)
    mat = np.full((gb, rb), np.nan, dtype=np.float64)
    if g and r:
        mat[:g, :r] = ani
    timing.dispatch(1)
    best, has = _membership_argmax_jit(jnp.asarray(mat))
    return np.asarray(best)[:g], np.asarray(has)[:g]
