"""Device MinHash sketching: chunked k-mer hashing + running bottom-k.

Produces bit-identical sketches to ops/minhash_np.py (the numpy semantic
reference), validated in tests/test_minhash.py, but runs the hash + top-k
work on the accelerator. Genomes are processed in fixed-size chunks (with
k-1 overlap) so XLA compiles one kernel per (chunk, k) and reuses it across
all genomes and contigs regardless of length.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from galah_tpu.config import Defaults
from galah_tpu.io.fasta import Genome
from galah_tpu.ops import hashing
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.minhash_np import MinHashSketch
from galah_tpu.utils import timing

# Chunk/budget policy lives with the chunk iterator (ops/hashing.py);
# re-exported here for existing importers.
DEFAULT_CHUNK = hashing.DEFAULT_CHUNK
BATCH_BUDGET = hashing.BATCH_BUDGET


def sketch_genome_device(
    genome: Genome,
    sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
    k: int = Defaults.MINHASH_KMER,
    seed: int = Defaults.MINHASH_SEED,
    chunk: int = DEFAULT_CHUNK,
    algo: str = Defaults.HASH_ALGO,
) -> MinHashSketch:
    """Bottom-k distinct canonical k-mer sketch, computed on device.

    On a single-device CPU backend the compiled-C sketcher
    (csrc/sketch.c) runs instead — bit-identical output, ~an order of
    magnitude faster than the XLA-CPU chunk pipeline on one core."""
    # An explicit non-default chunk pins the JAX chunk pipeline (the
    # C path has no chunking; parity tests drive the JAX path this way).
    if (jax.default_backend() == "cpu" and k <= 32
            and chunk == DEFAULT_CHUNK):
        try:
            from galah_tpu.ops import _csketch

            hashes = _csketch.sketch_bottomk(
                genome.codes, genome.contig_offsets, k=k,
                sketch_size=sketch_size, seed=seed, algo=algo)
            return MinHashSketch(hashes=hashes, sketch_size=sketch_size,
                                 kmer=k)
        except ImportError:
            pass  # no C toolchain: fall through to the JAX path

    running = jnp.full((sketch_size,), hashing.HASH_SENTINEL)
    for hashes, _pos, _n_new in hashing.iter_chunk_hashes(
            genome.codes, genome.contig_offsets, k=k, chunk=chunk,
            seed=seed, algo=algo):
        running = hashing.bottom_k_update(
            running, hashes, sketch_size=sketch_size)
        timing.dispatch()

    timing.dispatch(sync=True)
    out = np.asarray(running)
    out = out[out != np.uint64(SENTINEL)]
    return MinHashSketch(hashes=out, sketch_size=sketch_size, kmer=k)


@functools.partial(
    jax.jit, static_argnames=("k", "seed", "algo", "sketch_size"))
def _batch_sketch_kernel(packed, ambits, offsets, k, seed, algo,
                         sketch_size):
    """(G, C/4) packed genome rows -> (G, sketch_size) sorted distinct
    bottom-k hashes (SENTINEL-padded). One dispatch for the whole group."""
    h = hashing.canonical_kmer_hashes_batch(
        packed, ambits, offsets, k, seed, algo)
    h = jnp.sort(h, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros((h.shape[0], 1), bool), h[:, 1:] == h[:, :-1]], axis=1)
    h = jnp.where(dup, hashing.HASH_SENTINEL, h)
    h = jnp.sort(h, axis=-1)
    return h[:, : min(sketch_size, h.shape[1])]


def sketch_genomes_device_batch(
    genomes: Sequence[Genome],
    sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
    k: int = Defaults.MINHASH_KMER,
    seed: int = Defaults.MINHASH_SEED,
    algo: str = Defaults.HASH_ALGO,
    budget: int = BATCH_BUDGET,
) -> List[MinHashSketch]:
    """Sketch many genomes in a handful of dispatches, bit-identical to
    sketch_genome_device per genome.

    Genomes are bucketed by 64 Ki-padded length (bounding compile
    variants) and packed into (G, L) groups of at most `budget` total
    positions; each group is one device dispatch (hash + row-wise
    distinct bottom-k). Through a tunneled TPU the per-dispatch round
    trip otherwise dominates small-genome sketching (reference analog:
    finch sketch_files, src/finch.rs:47, a host-parallel per-file loop).
    Genomes longer than DEFAULT_CHUNK fall back to the chunked
    single-genome path.
    """
    out: List[MinHashSketch] = [None] * len(genomes)  # type: ignore
    skipped, group_iter = hashing.iter_genome_groups(
        genomes, budget=budget, max_len=DEFAULT_CHUNK)
    for i in skipped:
        out[i] = sketch_genome_device(
            genomes[i], sketch_size=sketch_size, k=k, seed=seed,
            algo=algo)
    for chunk_idxs, packed, ambits, offs in group_iter:
        timing.dispatch()
        timing.dispatch(sync=True)
        mat = np.asarray(_batch_sketch_kernel(
            jnp.asarray(packed), jnp.asarray(ambits),
            jnp.asarray(offs), k=k, seed=seed, algo=algo,
            sketch_size=sketch_size))
        for row, gi in enumerate(chunk_idxs):
            hs = mat[row]
            hs = hs[hs != np.uint64(SENTINEL)]
            out[gi] = MinHashSketch(
                hashes=hs, sketch_size=sketch_size, kmer=k)
    return out


def sketch_matrix(
    sketches: Sequence[MinHashSketch],
    sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
) -> np.ndarray:
    """Stack sketches into a SENTINEL-padded (N, sketch_size) uint64 matrix.

    This is the dense device-facing layout for the all-pairs kernel; rows
    sorted ascending with trailing sentinels for genomes that yielded fewer
    than sketch_size distinct k-mers.
    """
    n = len(sketches)
    mat = np.full((n, sketch_size), np.uint64(SENTINEL), dtype=np.uint64)
    for i, s in enumerate(sketches):
        m = min(s.size, sketch_size)
        mat[i, :m] = s.hashes[:m]
    return mat
