"""Device MinHash sketching: chunked k-mer hashing + running bottom-k.

Produces bit-identical sketches to ops/minhash_np.py (the numpy semantic
reference), validated in tests/test_minhash.py, but runs the hash + top-k
work on the accelerator. Genomes are processed in fixed-size chunks (with
k-1 overlap) so XLA compiles one kernel per (chunk, k) and reuses it across
all genomes and contigs regardless of length.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax.numpy as jnp

from galah_tpu.config import Defaults
from galah_tpu.io.fasta import Genome
from galah_tpu.ops import hashing
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.minhash_np import MinHashSketch

# 8 Mi positions per chunk (iter_chunk_hashes buckets it down to the
# genome size in 64 Ki steps): one dispatch covers most MAGs — through a
# remote-tunnel TPU the per-dispatch round trip dominates hashing
# launches. The hash pipeline is 1-D shifted slices (ops/hashing.py),
# so chunk memory is a few uint64 arrays of C elements.
DEFAULT_CHUNK = 1 << 23


def sketch_genome_device(
    genome: Genome,
    sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
    k: int = Defaults.MINHASH_KMER,
    seed: int = Defaults.MINHASH_SEED,
    chunk: int = DEFAULT_CHUNK,
    algo: str = Defaults.HASH_ALGO,
) -> MinHashSketch:
    """Bottom-k distinct canonical k-mer sketch, computed on device."""
    running = jnp.full((sketch_size,), hashing.HASH_SENTINEL)
    for hashes, _pos, _n_new in hashing.iter_chunk_hashes(
            genome.codes, genome.contig_offsets, k=k, chunk=chunk,
            seed=seed, algo=algo):
        running = hashing.bottom_k_update(
            running, hashes, sketch_size=sketch_size)

    out = np.asarray(running)
    out = out[out != np.uint64(SENTINEL)]
    return MinHashSketch(hashes=out, sketch_size=sketch_size, kmer=k)


def sketch_matrix(
    sketches: Sequence[MinHashSketch],
    sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
) -> np.ndarray:
    """Stack sketches into a SENTINEL-padded (N, sketch_size) uint64 matrix.

    This is the dense device-facing layout for the all-pairs kernel; rows
    sorted ascending with trailing sentinels for genomes that yielded fewer
    than sketch_size distinct k-mers.
    """
    n = len(sketches)
    mat = np.full((n, sketch_size), np.uint64(SENTINEL), dtype=np.uint64)
    for i, s in enumerate(sketches):
        m = min(s.size, sketch_size)
        mat[i, :m] = s.hashes[:m]
    return mat
