"""Bottom-k MinHash sketching + Mash distance — numpy reference path.

Semantics to match the reference's finch backend (reference:
src/finch.rs:26-73): canonical k-mers (lexicographic min of forward and
reverse complement), MurmurHash3 x64_128 h1 with seed 0, bottom-k sketch of
the 1000 smallest *distinct* hashes, Mash distance
d = -ln(2j/(1+j))/k from the merged-bottom-k Jaccard estimate, ANI = 1 - d.

Golden oracle: set1/1mbp.fna vs set1/500kb.fna -> ANI 0.9808188
(reference: src/finch.rs:96).

K-mers spanning a contig boundary or containing an ambiguous base are
skipped, matching needletail's valid-kmer iteration that finch consumes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np

from galah_tpu.config import Defaults
from galah_tpu.io.fasta import Genome
from galah_tpu.ops.murmur3_np import murmur3_x64_128_h1

# ASCII for code 0..3
_ASCII = np.frombuffer(b"ACGT", dtype=np.uint8)


@dataclasses.dataclass
class MinHashSketch:
    """Sorted ascending distinct bottom-k hash sketch of one genome."""

    hashes: np.ndarray  # uint64 [<= sketch_size], sorted ascending
    sketch_size: int
    kmer: int

    @property
    def size(self) -> int:
        return int(self.hashes.shape[0])


def canonical_kmer_hashes(
    genome: Genome,
    k: int = Defaults.MINHASH_KMER,
    seed: int = Defaults.MINHASH_SEED,
) -> np.ndarray:
    """All valid canonical k-mer hashes of a genome (with duplicates)."""
    codes = genome.codes
    n = codes.shape[0]
    if n < k:
        return np.zeros(0, dtype=np.uint64)

    # Sliding windows of codes: (n-k+1, k)
    win = np.lib.stride_tricks.sliding_window_view(codes, k)
    valid = (win != 255).all(axis=1)

    # Exclude windows that span a contig boundary.
    if genome.contig_offsets.shape[0] > 2:
        starts = np.arange(n - k + 1)
        # contig id of window start and of window end must agree
        cid_start = np.searchsorted(genome.contig_offsets, starts,
                                    side="right")
        cid_end = np.searchsorted(genome.contig_offsets, starts + k - 1,
                                  side="right")
        valid &= cid_start == cid_end

    win = win[valid]
    if win.shape[0] == 0:
        return np.zeros(0, dtype=np.uint64)

    # Pack forward and reverse-complement into integers for lexicographic
    # comparison (A<C<G<T holds in both code space and ASCII space, so the
    # packed-integer compare equals the string compare).
    shifts = (2 * np.arange(k - 1, -1, -1)).astype(np.uint64)
    w64 = win.astype(np.uint64)
    fwd = (w64 << shifts).sum(axis=1, dtype=np.uint64)
    rc_codes = 3 - win[:, ::-1]
    rev = (rc_codes.astype(np.uint64) << shifts).sum(axis=1, dtype=np.uint64)
    use_fwd = fwd <= rev

    canon = np.where(use_fwd[:, None], win, rc_codes)
    ascii_kmers = _ASCII[canon]
    return murmur3_x64_128_h1(ascii_kmers, seed=seed)


def sketch_genome(
    genome: Genome,
    sketch_size: int = Defaults.MINHASH_SKETCH_SIZE,
    k: int = Defaults.MINHASH_KMER,
    seed: int = Defaults.MINHASH_SEED,
) -> MinHashSketch:
    """Bottom-k distinct-hash sketch (finch Mash-mode equivalent)."""
    hashes = canonical_kmer_hashes(genome, k=k, seed=seed)
    distinct = np.unique(hashes)  # sorted ascending
    return MinHashSketch(
        hashes=distinct[:sketch_size], sketch_size=sketch_size, kmer=k)


def mash_jaccard(a: MinHashSketch, b: MinHashSketch) -> float:
    """Merged-bottom-k Jaccard estimate (Mash/finch semantics).

    Walk the two sorted sketches in merge order over the smallest
    `sketch_size` distinct union hashes; j = shared / seen.
    """
    size = min(a.sketch_size, b.sketch_size)
    ha, hb = a.hashes, b.hashes
    i = j = common = total = 0
    la, lb = len(ha), len(hb)
    while i < la and j < lb and total < size:
        if ha[i] < hb[j]:
            i += 1
        elif hb[j] < ha[i]:
            j += 1
        else:
            common += 1
            i += 1
            j += 1
        total += 1
    while i < la and total < size:
        i += 1
        total += 1
    while j < lb and total < size:
        j += 1
        total += 1
    if total == 0:
        return 0.0
    return common / total


def mash_ani(a: MinHashSketch, b: MinHashSketch) -> float:
    """ANI = 1 - Mash distance (reference: src/finch.rs:56-64)."""
    j = mash_jaccard(a, b)
    if j <= 0.0:
        return 0.0
    k = a.kmer
    d = -math.log(2.0 * j / (1.0 + j)) / k
    return 1.0 - d


def sketch_genomes(
    genomes: Sequence[Genome], **kw
) -> list[MinHashSketch]:
    return [sketch_genome(g, **kw) for g in genomes]
