"""Pallas TPU kernel: MurmurHash3 x64_128 (h1) over u32 plane pairs.

The reference's finch contract hashes every canonical k-mer with
murmur3 x64_128 (reference: src/finch.rs:33-47) — 11 u64
multiply-by-constant operations per k-mer. The TPU VPU has no 64-bit
integer unit; XLA emulates every u64 op over u32 pairs generically,
and the multiplies dominate device sketching. This kernel is the
promised explicit u32-pair implementation (ops/hashing.py's module
docstring): the murmur state machine runs on (hi, lo) uint32 planes
with each constant multiply decomposed into 16-bit limb products
(every 16x16 product fits u32 exactly; per-column limb accumulators
stay below 2^19, so one carry-propagation pass at the end suffices) —
the minimal-width schoolbook XLA's generic emulation cannot assume.

Scope: the k=21 MinHash production path. Input is the three assembled
key words (k1: bytes 0-7, k2: bytes 8-15, k1 tail: bytes 16-20) that
ops/hashing's XLA preamble already builds with cheap shift/or chains;
the kernel fuses the whole hash state machine — one block-elementwise
pass, no u64 intermediates in HBM. Bit-identical to
ops/hashing._murmur3_k21_1d (tests/test_pallas_sketch.py, interpret
mode on CPU; tests/test_tpu_hw.py on hardware).

QUARANTINED — hardware-retired, kept for the record. The 2026-08-01
amortized on-chip campaign measured this kernel at 0.06x the XLA u64
emulation on the murmur core (docs/artifacts/tpu_watch_20260801_0829/
amortized.txt): XLA's generic emulation fuses the constant multiplies
better than the 16-bit-limb schoolbook once the state machine is one
elementwise pass. No default path selects it — activation requires
BOTH hash_algo="murmur3" AND GALAH_TPU_PALLAS_HASH=1 (ops/hashing.py)
— and its parity tests run only in the slow/hardware tier
(tests/test_pallas_sketch.py). It stays in-tree as the reference
16-bit-limb u64-multiply decomposition should a future Mosaic release
change the economics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from galah_tpu.obs.profile import profiled

LANES = 128
BLOCK_SUB = 512  # sublanes per grid program (block = BLOCK_SUB x 128)

# Static kernel contract checked by `galah-tpu lint` (GL1xx): every
# block shape is (BLOCK_SUB, LANES) u32 planes, so no call-site
# bindings are needed.
PALLAS_CONTRACT = {
    "murmur3_k21_pallas": {
        "bindings": {},
        "in_dtypes": ["uint32", "uint32", "uint32",
                      "uint32", "uint32", "uint32"],
        "kernel_fns": ["_make_kernel", "_mulc64", "_add64", "_addc64",
                       "_xorc64", "_rotl64", "_shr64_xor", "_fmix64"],
    },
}

_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F
_F1 = 0xFF51AFD7ED558CCD
_F2 = 0xC4CEB9FE1A85EC53


def _limbs16(c: int):
    return [(c >> (16 * j)) & 0xFFFF for j in range(4)]


def _mulc64(hi: jax.Array, lo: jax.Array, c: int):
    """(hi, lo) u32 planes * 64-bit constant c, mod 2^64.

    Schoolbook over 16-bit limbs: products x_i * c_j with i + j <= 3,
    lo16 into column i+j, hi16 into column i+j+1; each column
    accumulates at most 8 terms < 2^16 (< 2^19 total), then one carry
    sweep rebuilds the planes. Zero limbs of c skip their products at
    trace time.
    """
    x = [lo & 0xFFFF, lo >> jnp.uint32(16), hi & 0xFFFF, hi >> jnp.uint32(16)]
    cl = _limbs16(c)
    acc = [None, None, None, None]

    def _addto(k, v):
        acc[k] = v if acc[k] is None else acc[k] + v

    for i in range(4):
        for j in range(4 - i):
            if cl[j] == 0:
                continue
            p = x[i] * jnp.uint32(cl[j])
            k = i + j
            _addto(k, p & 0xFFFF)
            if k + 1 < 4:
                _addto(k + 1, p >> jnp.uint32(16))
    zero = jnp.zeros_like(lo)
    acc = [a if a is not None else zero for a in acc]

    l0 = acc[0] & 0xFFFF
    carry = acc[0] >> jnp.uint32(16)
    a1 = acc[1] + carry
    l1 = a1 & 0xFFFF
    carry = a1 >> jnp.uint32(16)
    a2 = acc[2] + carry
    l2 = a2 & 0xFFFF
    carry = a2 >> jnp.uint32(16)
    l3 = (acc[3] + carry) & 0xFFFF
    return (l2 | (l3 << jnp.uint32(16))), (l0 | (l1 << jnp.uint32(16)))


def _add64(hi, lo, bhi, blo):
    lo2 = lo + blo
    carry = (lo2 < blo).astype(jnp.uint32)
    return hi + bhi + carry, lo2


def _addc64(hi, lo, c: int):
    return _add64(hi, lo, jnp.uint32((c >> 32) & 0xFFFFFFFF),
                  jnp.uint32(c & 0xFFFFFFFF))


def _xorc64(hi, lo, c: int):
    return (hi ^ jnp.uint32((c >> 32) & 0xFFFFFFFF),
            lo ^ jnp.uint32(c & 0xFFFFFFFF))


def _rotl64(hi, lo, r: int):
    if r == 32:
        return lo, hi
    if r < 32:
        return ((hi << jnp.uint32(r)) | (lo >> jnp.uint32(32 - r)),
                (lo << jnp.uint32(r)) | (hi >> jnp.uint32(32 - r)))
    s = r - 32
    return ((lo << jnp.uint32(s)) | (hi >> jnp.uint32(32 - s)),
            (hi << jnp.uint32(s)) | (lo >> jnp.uint32(32 - s)))


def _shr64_xor(hi, lo, r: int):
    """(hi, lo) ^= (hi, lo) >> r, for the fmix xorshifts (r = 33)."""
    if r < 32:
        nhi = hi >> jnp.uint32(r)
        nlo = (lo >> jnp.uint32(r)) | (hi << jnp.uint32(32 - r))
    else:
        nhi = jnp.zeros_like(hi)
        nlo = hi >> jnp.uint32(r - 32)
    return hi ^ nhi, lo ^ nlo


def _fmix64(hi, lo):
    hi, lo = _shr64_xor(hi, lo, 33)
    hi, lo = _mulc64(hi, lo, _F1)
    hi, lo = _shr64_xor(hi, lo, 33)
    hi, lo = _mulc64(hi, lo, _F2)
    return _shr64_xor(hi, lo, 33)


def _make_kernel(seed: int):
    seed_hi = (seed >> 32) & 0xFFFFFFFF
    seed_lo = seed & 0xFFFFFFFF

    def kernel(k1h, k1l, k2h, k2l, th, tl, outh, outl):
        h1h = jnp.full_like(k1h[:], jnp.uint32(seed_hi))
        h1l = jnp.full_like(k1l[:], jnp.uint32(seed_lo))
        h2h, h2l = h1h, h1l

        # body block: k1 = rotl(k1*C1, 31)*C2 folded into h1, then k2
        a, b = _mulc64(k1h[:], k1l[:], _C1)
        a, b = _rotl64(a, b, 31)
        a, b = _mulc64(a, b, _C2)
        h1h, h1l = h1h ^ a, h1l ^ b
        h1h, h1l = _rotl64(h1h, h1l, 27)
        h1h, h1l = _add64(h1h, h1l, h2h, h2l)
        h1h, h1l = _mulc64(h1h, h1l, 5)
        h1h, h1l = _addc64(h1h, h1l, 0x52DCE729)

        a, b = _mulc64(k2h[:], k2l[:], _C2)
        a, b = _rotl64(a, b, 33)
        a, b = _mulc64(a, b, _C1)
        h2h, h2l = h2h ^ a, h2l ^ b
        h2h, h2l = _rotl64(h2h, h2l, 31)
        h2h, h2l = _add64(h2h, h2l, h1h, h1l)
        h2h, h2l = _mulc64(h2h, h2l, 5)
        h2h, h2l = _addc64(h2h, h2l, 0x38495AB5)

        # 5-byte tail folds into h1 only; the contract uses only the
        # low 5 bytes of the tail word, so mask byte 4's plane here
        # rather than trusting every caller to pre-zero bytes 5-7
        a, b = _mulc64(th[:] & 0xFF, tl[:], _C1)
        a, b = _rotl64(a, b, 31)
        a, b = _mulc64(a, b, _C2)
        h1h, h1l = h1h ^ a, h1l ^ b

        # finalization, length = 21
        h1h, h1l = _xorc64(h1h, h1l, 21)
        h2h, h2l = _xorc64(h2h, h2l, 21)
        h1h, h1l = _add64(h1h, h1l, h2h, h2l)
        h2h, h2l = _add64(h2h, h2l, h1h, h1l)
        h1h, h1l = _fmix64(h1h, h1l)
        h2h, h2l = _fmix64(h2h, h2l)
        h1h, h1l = _add64(h1h, h1l, h2h, h2l)
        outh[:] = h1h
        outl[:] = h1l

    return kernel


def _zi(i):
    return i * 0


@profiled("sketch.murmur3_k21_pallas")
@functools.partial(jax.jit, static_argnames=("seed", "interpret"))
def murmur3_k21_pallas(
    k1: jax.Array,    # uint64 (n,): bytes 0-7 of the canonical k-mer
    k2: jax.Array,    # uint64 (n,): bytes 8-15
    k1t: jax.Array,   # uint64 (n,): bytes 16-20 (low 5 bytes used)
    seed: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """h1 of murmur3 x64_128 over 21-byte keys given as assembled
    little-endian words — bit-identical to ops/hashing._murmur3_k21_1d.
    """
    n = k1.shape[0]
    quantum = BLOCK_SUB * LANES
    n_pad = max(quantum, -(-n // quantum) * quantum)

    def planes(x):
        xp = jnp.zeros((n_pad,), jnp.uint64).at[:n].set(x)
        return ((xp >> jnp.uint64(32)).astype(jnp.uint32)
                .reshape(n_pad // LANES, LANES),
                xp.astype(jnp.uint32).reshape(n_pad // LANES, LANES))

    k1h, k1l = planes(k1)
    k2h, k2l = planes(k2)
    th, tl = planes(k1t)

    rows = n_pad // LANES
    grid = rows // BLOCK_SUB
    spec = pl.BlockSpec((BLOCK_SUB, LANES), lambda i: (i, _zi(i)),
                        memory_space=pltpu.VMEM)
    outh, outl = pl.pallas_call(
        _make_kernel(seed),
        grid=(grid,),
        in_specs=[spec] * 6,
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
                   jax.ShapeDtypeStruct((rows, LANES), jnp.uint32)],
        interpret=interpret,
    )(k1h, k1l, k2h, k2l, th, tl)
    out = (outh.reshape(-1).astype(jnp.uint64) << jnp.uint64(32)) \
        | outl.reshape(-1).astype(jnp.uint64)
    return out[:n]


def assemble_k21_words(cb) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Little-endian key words (k1, k2, tail) from 21 per-byte u64
    vectors — the same shift/or assembly _murmur3_k21_1d runs inline;
    shared so the kernel consumes identical inputs."""
    k1 = cb[0]
    for b in range(1, 8):
        k1 = k1 | (cb[b] << jnp.uint64(8 * b))
    k2 = cb[8]
    for b in range(1, 8):
        k2 = k2 | (cb[8 + b] << jnp.uint64(8 * b))
    t = cb[16]
    for b in range(1, 5):
        t = t | (cb[16 + b] << jnp.uint64(8 * b))
    return k1, k2, t
