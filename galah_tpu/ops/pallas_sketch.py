"""Pallas TPU kernel: MurmurHash3 x64_128 (h1) over u32 plane pairs.

The reference's finch contract hashes every canonical k-mer with
murmur3 x64_128 (reference: src/finch.rs:33-47) — 11 u64
multiply-by-constant operations per k-mer. The TPU VPU has no 64-bit
integer unit; XLA emulates every u64 op over u32 pairs generically,
and the multiplies dominate device sketching. This kernel is the
promised explicit u32-pair implementation (ops/hashing.py's module
docstring): the murmur state machine runs on (hi, lo) uint32 planes
with each constant multiply decomposed into 16-bit limb products
(every 16x16 product fits u32 exactly; per-column limb accumulators
stay below 2^19, so one carry-propagation pass at the end suffices) —
the minimal-width schoolbook XLA's generic emulation cannot assume.

Scope: the k=21 MinHash production path. Input is the three assembled
key words (k1: bytes 0-7, k2: bytes 8-15, k1 tail: bytes 16-20) that
ops/hashing's XLA preamble already builds with cheap shift/or chains;
the kernel fuses the whole hash state machine — one block-elementwise
pass, no u64 intermediates in HBM. Bit-identical to
ops/hashing._murmur3_k21_1d (tests/test_pallas_sketch.py, interpret
mode on CPU; tests/test_tpu_hw.py on hardware).

QUARANTINED — hardware-retired, kept for the record. The 2026-08-01
amortized on-chip campaign measured this kernel at 0.06x the XLA u64
emulation on the murmur core (docs/artifacts/tpu_watch_20260801_0829/
amortized.txt): XLA's generic emulation fuses the constant multiplies
better than the 16-bit-limb schoolbook once the state machine is one
elementwise pass. No default path selects it — activation requires
BOTH hash_algo="murmur3" AND GALAH_TPU_PALLAS_HASH=1 (ops/hashing.py)
— and its parity tests run only in the slow/hardware tier
(tests/test_pallas_sketch.py). It stays in-tree as the reference
16-bit-limb u64-multiply decomposition should a future Mosaic release
change the economics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from galah_tpu.obs.profile import profiled

LANES = 128
BLOCK_SUB = 512  # sublanes per grid program (block = BLOCK_SUB x 128)

# Static kernel contract checked by `galah-tpu lint` (GL1xx). The
# hash-only entry's blocks are all (BLOCK_SUB, LANES) u32 planes; the
# fused entry's bindings pin a representative launch (murmur arity,
# 8 jobs x span 2) so the evaluator can size its blocks and VMEM.
PALLAS_CONTRACT = {
    "murmur3_k21_pallas": {
        "bindings": {},
        "in_dtypes": ["uint32", "uint32", "uint32",
                      "uint32", "uint32", "uint32"],
        "kernel_fns": ["_make_kernel", "_murmur3_planes", "_mulc64",
                       "_add64", "_addc64", "_xorc64", "_rotl64",
                       "_shr64_xor", "_fmix64"],
    },
    "_fused_sketch_call": {
        "bindings": {"n_planes": 7, "jobs": 8, "span": 2},
        "in_dtypes": ["uint32", "uint32", "uint32", "uint32",
                      "uint32", "uint32", "uint32"],
        "kernel_fns": ["_make_fused_kernel", "_murmur3_planes",
                       "_tpufast_planes", "_mulc64", "_add64", "_addc64",
                       "_xorc64", "_rotl64", "_shl64", "_shr64_xor",
                       "_fmix64"],
    },
}

_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F
_F1 = 0xFF51AFD7ED558CCD
_F2 = 0xC4CEB9FE1A85EC53


def _limbs16(c: int):
    return [(c >> (16 * j)) & 0xFFFF for j in range(4)]


def _mulc64(hi: jax.Array, lo: jax.Array, c: int):
    """(hi, lo) u32 planes * 64-bit constant c, mod 2^64.

    Schoolbook over 16-bit limbs: products x_i * c_j with i + j <= 3,
    lo16 into column i+j, hi16 into column i+j+1; each column
    accumulates at most 8 terms < 2^16 (< 2^19 total), then one carry
    sweep rebuilds the planes. Zero limbs of c skip their products at
    trace time.
    """
    x = [lo & 0xFFFF, lo >> jnp.uint32(16), hi & 0xFFFF, hi >> jnp.uint32(16)]
    cl = _limbs16(c)
    acc = [None, None, None, None]

    def _addto(k, v):
        acc[k] = v if acc[k] is None else acc[k] + v

    for i in range(4):
        for j in range(4 - i):
            if cl[j] == 0:
                continue
            p = x[i] * jnp.uint32(cl[j])
            k = i + j
            _addto(k, p & 0xFFFF)
            if k + 1 < 4:
                _addto(k + 1, p >> jnp.uint32(16))
    zero = jnp.zeros_like(lo)
    acc = [a if a is not None else zero for a in acc]

    l0 = acc[0] & 0xFFFF
    carry = acc[0] >> jnp.uint32(16)
    a1 = acc[1] + carry
    l1 = a1 & 0xFFFF
    carry = a1 >> jnp.uint32(16)
    a2 = acc[2] + carry
    l2 = a2 & 0xFFFF
    carry = a2 >> jnp.uint32(16)
    l3 = (acc[3] + carry) & 0xFFFF
    return (l2 | (l3 << jnp.uint32(16))), (l0 | (l1 << jnp.uint32(16)))


def _add64(hi, lo, bhi, blo):
    lo2 = lo + blo
    carry = (lo2 < blo).astype(jnp.uint32)
    return hi + bhi + carry, lo2


def _addc64(hi, lo, c: int):
    return _add64(hi, lo, jnp.uint32((c >> 32) & 0xFFFFFFFF),
                  jnp.uint32(c & 0xFFFFFFFF))


def _xorc64(hi, lo, c: int):
    return (hi ^ jnp.uint32((c >> 32) & 0xFFFFFFFF),
            lo ^ jnp.uint32(c & 0xFFFFFFFF))


def _rotl64(hi, lo, r: int):
    if r == 32:
        return lo, hi
    if r < 32:
        return ((hi << jnp.uint32(r)) | (lo >> jnp.uint32(32 - r)),
                (lo << jnp.uint32(r)) | (hi >> jnp.uint32(32 - r)))
    s = r - 32
    return ((lo << jnp.uint32(s)) | (hi >> jnp.uint32(32 - s)),
            (hi << jnp.uint32(s)) | (lo >> jnp.uint32(32 - s)))


def _shr64_xor(hi, lo, r: int):
    """(hi, lo) ^= (hi, lo) >> r, for the fmix xorshifts (r = 33)."""
    if r < 32:
        nhi = hi >> jnp.uint32(r)
        nlo = (lo >> jnp.uint32(r)) | (hi << jnp.uint32(32 - r))
    else:
        nhi = jnp.zeros_like(hi)
        nlo = hi >> jnp.uint32(r - 32)
    return hi ^ nhi, lo ^ nlo


def _fmix64(hi, lo):
    hi, lo = _shr64_xor(hi, lo, 33)
    hi, lo = _mulc64(hi, lo, _F1)
    hi, lo = _shr64_xor(hi, lo, 33)
    hi, lo = _mulc64(hi, lo, _F2)
    return _shr64_xor(hi, lo, 33)


def _murmur3_planes(k1h, k1l, k2h, k2l, th, tl, seed: int):
    """The full murmur3 x64_128 h1 state machine over u32 plane VALUES
    (one 16-byte block + 5-byte k1 tail, length 21) — shared by the
    hash-only kernel and the fused sketch kernel."""
    seed_hi = (seed >> 32) & 0xFFFFFFFF
    seed_lo = seed & 0xFFFFFFFF
    h1h = jnp.full_like(k1h, jnp.uint32(seed_hi))
    h1l = jnp.full_like(k1l, jnp.uint32(seed_lo))
    h2h, h2l = h1h, h1l

    # body block: k1 = rotl(k1*C1, 31)*C2 folded into h1, then k2
    a, b = _mulc64(k1h, k1l, _C1)
    a, b = _rotl64(a, b, 31)
    a, b = _mulc64(a, b, _C2)
    h1h, h1l = h1h ^ a, h1l ^ b
    h1h, h1l = _rotl64(h1h, h1l, 27)
    h1h, h1l = _add64(h1h, h1l, h2h, h2l)
    h1h, h1l = _mulc64(h1h, h1l, 5)
    h1h, h1l = _addc64(h1h, h1l, 0x52DCE729)

    a, b = _mulc64(k2h, k2l, _C2)
    a, b = _rotl64(a, b, 33)
    a, b = _mulc64(a, b, _C1)
    h2h, h2l = h2h ^ a, h2l ^ b
    h2h, h2l = _rotl64(h2h, h2l, 31)
    h2h, h2l = _add64(h2h, h2l, h1h, h1l)
    h2h, h2l = _mulc64(h2h, h2l, 5)
    h2h, h2l = _addc64(h2h, h2l, 0x38495AB5)

    # 5-byte tail folds into h1 only; the contract uses only the
    # low 5 bytes of the tail word, so mask byte 4's plane here
    # rather than trusting every caller to pre-zero bytes 5-7
    a, b = _mulc64(th & 0xFF, tl, _C1)
    a, b = _rotl64(a, b, 31)
    a, b = _mulc64(a, b, _C2)
    h1h, h1l = h1h ^ a, h1l ^ b

    # finalization, length = 21
    h1h, h1l = _xorc64(h1h, h1l, 21)
    h2h, h2l = _xorc64(h2h, h2l, 21)
    h1h, h1l = _add64(h1h, h1l, h2h, h2l)
    h2h, h2l = _add64(h2h, h2l, h1h, h1l)
    h1h, h1l = _fmix64(h1h, h1l)
    h2h, h2l = _fmix64(h2h, h2l)
    h1h, h1l = _add64(h1h, h1l, h2h, h2l)
    return h1h, h1l


def _make_kernel(seed: int):
    def kernel(k1h, k1l, k2h, k2l, th, tl, outh, outl):
        h1h, h1l = _murmur3_planes(k1h[:], k1l[:], k2h[:], k2l[:],
                                   th[:], tl[:], seed)
        outh[:] = h1h
        outl[:] = h1l

    return kernel


def _zi(i):
    return i * 0


@profiled("sketch.murmur3_k21_pallas")
@functools.partial(jax.jit, static_argnames=("seed", "interpret"))
def murmur3_k21_pallas(
    k1: jax.Array,    # uint64 (n,): bytes 0-7 of the canonical k-mer
    k2: jax.Array,    # uint64 (n,): bytes 8-15
    k1t: jax.Array,   # uint64 (n,): bytes 16-20 (low 5 bytes used)
    seed: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """h1 of murmur3 x64_128 over 21-byte keys given as assembled
    little-endian words — bit-identical to ops/hashing._murmur3_k21_1d.
    """
    n = k1.shape[0]
    quantum = BLOCK_SUB * LANES
    n_pad = max(quantum, -(-n // quantum) * quantum)

    def planes(x):
        xp = jnp.zeros((n_pad,), jnp.uint64).at[:n].set(x)
        return ((xp >> jnp.uint64(32)).astype(jnp.uint32)
                .reshape(n_pad // LANES, LANES),
                xp.astype(jnp.uint32).reshape(n_pad // LANES, LANES))

    k1h, k1l = planes(k1)
    k2h, k2l = planes(k2)
    th, tl = planes(k1t)

    rows = n_pad // LANES
    grid = rows // BLOCK_SUB
    spec = pl.BlockSpec((BLOCK_SUB, LANES), lambda i: (i, _zi(i)),
                        memory_space=pltpu.VMEM)
    outh, outl = pl.pallas_call(
        _make_kernel(seed),
        grid=(grid,),
        in_specs=[spec] * 6,
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
                   jax.ShapeDtypeStruct((rows, LANES), jnp.uint32)],
        interpret=interpret,
    )(k1h, k1l, k2h, k2l, th, tl)
    out = (outh.reshape(-1).astype(jnp.uint64) << jnp.uint64(32)) \
        | outl.reshape(-1).astype(jnp.uint64)
    return out[:n]


# --------------------------------------------------------------------
# Fused hash + running bottom-k candidate reduction (NOT quarantined —
# this is the production fused sketch path behind
# GALAH_TPU_SKETCH_STRATEGY=fused; the quarantine note above covers
# only the hash-only murmur3_k21_pallas entry).
#
# Mosaic has no sort and no scatter, so an exact in-kernel bottom-k is
# off the table. Instead each job (genome) maintains a candidate file
# of per-POSITION-CLASS distinct minima: class = (sublane mod CAND_SUB,
# lane) of the incoming (BLOCK_SUB, LANES) hash block — C = CAND_SUB *
# LANES classes — and R_REG sorted registers per class, updated by a
# dedup check plus a compare-exchange bubble insert on u32 (hi, lo)
# planes. Registers only ever decrease, which yields a completeness
# CERTIFICATE the XLA post-pass checks: with T = the sketch_size-th
# smallest distinct candidate, any class whose final largest register
# m_R < T may have dropped a distinct value below T ("suspect"); if no
# class is suspect the candidate file provably contains the exact
# distinct bottom-k and the fused sketch is bit-identical to the
# chunked XLA / C paths. Suspect jobs (P ~ 1e-4 at the default
# sketch_size=1000: per-class Poisson load lambda ~ 0.5 vs R_REG = 8)
# are re-sketched on the exact chunked path, so the hard determinism
# gate holds unconditionally. Hashes never round-trip to XLA top-k:
# per launch only R_REG * CAND_SUB * LANES candidates per job leave
# the kernel, ~1/1000th of the hash stream.
# --------------------------------------------------------------------

CAND_SUB = 16   # candidate-class sublanes (classes = CAND_SUB x LANES)
R_REG = 8       # distinct-minima registers per class

_U32_SENT = 0xFFFFFFFF  # both planes -> ops/constants.SENTINEL (u64 max)


def _shl64(hi, lo, s: int):
    """(hi, lo) << s, mod 2^64 — the tpufast sparse-multiply shifts."""
    if s == 0:
        return hi, lo
    if s < 32:
        return ((hi << jnp.uint32(s)) | (lo >> jnp.uint32(32 - s)),
                lo << jnp.uint32(s))
    return lo << jnp.uint32(s - 32), jnp.zeros_like(lo)


def _tpufast_planes(kh, kl, seed: int):
    """ops/hashing._tpufast_mix on u32 (hi, lo) planes, bit-identical:
    seed xor, three shift-add sparse-constant rounds with xorshifts,
    and the final fold — adds/shifts/xors only, no multiplies."""
    c = (seed * 0x9E3779B97F4A7C15 + 0x1B873593) % (1 << 64)
    xh, xl = _xorc64(kh, kl, c)
    for sh_a, sh_b, sh_x in ((21, 37, 29), (13, 47, 31), (17, 41, 33)):
        ah, al = _shl64(xh, xl, sh_a)
        bh, bl = _shl64(xh, xl, sh_b)
        xh, xl = _add64(xh, xl, ah, al)
        xh, xl = _add64(xh, xl, bh, bl)
        xh, xl = _shr64_xor(xh, xl, sh_x)
    ah, al = _shl64(xh, xl, 26)
    xh, xl = _add64(xh, xl, ah, al)
    return _shr64_xor(xh, xl, 32)


def _make_fused_kernel(algo: str, seed: int):
    """Fused kernel: hash one (BLOCK_SUB, LANES) block of canonical key
    planes, then fold it into the job's per-class distinct-minima
    registers (the revisited output block, @pl.when-initialized on the
    job's first span step)."""
    n_words = 3 if algo == "murmur3" else 1

    def kernel(*refs):
        word_refs = refs[:2 * n_words]
        mask_ref = refs[2 * n_words]
        outh_ref = refs[2 * n_words + 1]
        outl_ref = refs[2 * n_words + 2]
        s = pl.program_id(1)

        @pl.when(s == 0)
        def _init():
            outh_ref[:] = jnp.full_like(outh_ref[:], jnp.uint32(_U32_SENT))
            outl_ref[:] = jnp.full_like(outl_ref[:], jnp.uint32(_U32_SENT))

        if algo == "murmur3":
            h_hi, h_lo = _murmur3_planes(
                word_refs[0][:], word_refs[1][:], word_refs[2][:],
                word_refs[3][:], word_refs[4][:], word_refs[5][:], seed)
        else:
            h_hi, h_lo = _tpufast_planes(word_refs[0][:], word_refs[1][:],
                                         seed)
        sent = jnp.uint32(_U32_SENT)
        invalid = mask_ref[:] == jnp.uint32(0)
        h_hi = jnp.where(invalid, sent, h_hi)
        h_lo = jnp.where(invalid, sent, h_lo)

        for f in range(BLOCK_SUB // CAND_SUB):
            vh = h_hi[f * CAND_SUB:(f + 1) * CAND_SUB, :]
            vl = h_lo[f * CAND_SUB:(f + 1) * CAND_SUB, :]
            regs = [(outh_ref[i * CAND_SUB:(i + 1) * CAND_SUB, :],
                     outl_ref[i * CAND_SUB:(i + 1) * CAND_SUB, :])
                    for i in range(R_REG)]
            # distinct-minima: a value already held by a register is a
            # duplicate — demote it to the sentinel (which also catches
            # invalid positions: SENT == SENT in the all-SENT init).
            dup = (vh == regs[0][0]) & (vl == regs[0][1])
            for mh, ml in regs[1:]:
                dup = dup | ((vh == mh) & (vl == ml))
            vh = jnp.where(dup, sent, vh)
            vl = jnp.where(dup, sent, vl)
            # sorted bubble insert (u64 lexicographic on the planes):
            # each step keeps the min in register i and carries the max
            # forward; the value displaced from the last register drops
            # out of the file — that loss is what the certificate
            # bounds. Each register is read before its single write, so
            # the pre-read `regs` values stay current through the fold.
            for i in range(R_REG):
                mh, ml = regs[i]
                lt = (vh < mh) | ((vh == mh) & (vl < ml))
                outh_ref[i * CAND_SUB:(i + 1) * CAND_SUB, :] = \
                    jnp.where(lt, vh, mh)
                outl_ref[i * CAND_SUB:(i + 1) * CAND_SUB, :] = \
                    jnp.where(lt, vl, ml)
                vh = jnp.where(lt, mh, vh)
                vl = jnp.where(lt, ml, vl)

    return kernel


def _fused_sketch_call(planes, span: int, algo: str, seed: int,
                       interpret: bool):
    """The fused pallas_call: grid (jobs, span), each job revisiting its
    (R_REG * CAND_SUB, LANES) candidate planes across its span of
    (BLOCK_SUB, LANES) key blocks. `planes` is 2 u32 planes per key
    word plus the validity plane, each (jobs * span * BLOCK_SUB, LANES).
    """
    n_planes = len(planes)
    jobs = planes[0].shape[0] // (span * BLOCK_SUB)
    in_spec = pl.BlockSpec((BLOCK_SUB, LANES),
                           lambda j, s, sp=span: (j * sp + s, _zi(j)),
                           memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((R_REG * CAND_SUB, LANES),
                            lambda j, s: (j, _zi(j)),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _make_fused_kernel(algo, seed),
        grid=(jobs, span),
        in_specs=[in_spec] * n_planes,
        out_specs=[out_spec, out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((jobs * R_REG * CAND_SUB, LANES),
                                 jnp.uint32),
            jax.ShapeDtypeStruct((jobs * R_REG * CAND_SUB, LANES),
                                 jnp.uint32),
        ],
        interpret=interpret,
    )(*planes)


def fused_sketch_candidates(
    words,            # tuple of uint64 (jobs, W) key-word rows
    valid,            # bool (jobs, W) window validity
    algo: str = "murmur3",
    seed: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """One fused launch: hash every key word and reduce to per-class
    distinct-minima candidates -> (jobs, R_REG, CAND_SUB * LANES)
    uint64, register-major (candidates[:, R_REG - 1] are the per-class
    largest registers the completeness certificate checks).

    W must be a span * BLOCK_SUB * LANES multiple; pad with valid=False
    (padding hashes to the sentinel and never enters the file).
    Unjitted building block — callers embed it in their own jit
    (ops/sketch_stream's group kernel) so the XLA preamble fuses into
    operand production.
    """
    jobs, w = valid.shape
    span = w // (BLOCK_SUB * LANES)
    if span * BLOCK_SUB * LANES != w:
        raise ValueError(
            f"fused sketch width {w} is not a multiple of the "
            f"{BLOCK_SUB * LANES}-position block")

    def planes(x):
        xr = x.reshape(jobs * span * BLOCK_SUB, LANES)
        return ((xr >> jnp.uint64(32)).astype(jnp.uint32),
                xr.astype(jnp.uint32))

    ins = []
    for word in words:
        hi, lo = planes(word)
        ins.extend((hi, lo))
    ins.append(valid.astype(jnp.uint32).reshape(
        jobs * span * BLOCK_SUB, LANES))
    outh, outl = _fused_sketch_call(tuple(ins), span, algo, seed,
                                    interpret)
    cand = (outh.astype(jnp.uint64) << jnp.uint64(32)) \
        | outl.astype(jnp.uint64)
    return cand.reshape(jobs, R_REG, CAND_SUB * LANES)


def assemble_k21_words(cb) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Little-endian key words (k1, k2, tail) from 21 per-byte u64
    vectors — the same shift/or assembly _murmur3_k21_1d runs inline;
    shared so the kernel consumes identical inputs."""
    k1 = cb[0]
    for b in range(1, 8):
        k1 = k1 | (cb[b] << jnp.uint64(8 * b))
    k2 = cb[8]
    for b in range(1, 8):
        k2 = k2 | (cb[8 + b] << jnp.uint64(8 * b))
    t = cb[16]
    for b in range(1, 5):
        t = t | (cb[16 + b] << jnp.uint64(8 * b))
    return k1, k2, t
