"""Fused device-resident greedy round (the megakernel).

PR 13 overlapped the pipeline's stages but each selection round still
launched one jitted window fold per round-window and synced through
host Python in between; PR 14's critical-path blame puts host
orchestration, not device math, at the top of the e2e wall. This
module collapses a *slab* of consecutive round windows into one fused
device program pair:

  1. the slab's surviving screen pairs enqueue into the on-device
     pair queue (ops/device_queue.py — one pow2-bucketed scatter
     dispatch, no host materialization of the surviving pair list),
  2. :func:`_slab_fold_jit` consumes the compacted queue in place: a
     ``lax.while_loop`` over scatter-max claim propagation applies the
     same peeling recurrence as ops/greedy_select._window_select_jit,
     but over the edge LIST instead of a dense per-window matrix — so
     S windows resolve in 2 dispatches instead of S.

Why a slab is exact: the round machinery is width-invariant (a window
of S·w genomes decides identically to S sequential w-windows —
tests/test_greedy_rounds.py::test_rep_rounds_width_invariance pins
this), and the edge-list recurrence is the matrix recurrence
restricted to the edges that exist: for column j,
``any(edges & undecided[:, None], axis=0)`` is exactly a scatter-max
of ``undecided[qi]`` over the edge endpoints ``qj``. Missing pairs
(NaN in the matrix) simply have no queue entry; entries whose value
fails ``v >= thr`` (NaN included — IEEE compares False, like the
host's ``None`` guard) never pass. The fold iterates until a fixpoint
(change-detected while_loop, slab-width bound), so whenever both paths
converge they reach the SAME fixpoint — bit-identical representatives.

Overflow exactness: a slab whose edge count exceeds the queue
capacity never half-runs — the engine spills the whole slab to the
existing dense per-window path (counted: megakernel-overflow-spills),
so clusterings are exact at ANY capacity and the capacity flag is a
pure performance knob.

Strategy: GALAH_TPU_MEGAKERNEL auto/0/1 (resolve here, enforced in
cluster/engine.py) — AUTO demotes to the per-window dense fold on any
runtime failure, an explicit ``1`` pin propagates failures so parity
runs never compare a fallback to itself (same contract as the overlap
and greedy-strategy pins).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from galah_tpu.obs.profile import profiled
from galah_tpu.ops.greedy_select import _bucket
from galah_tpu.utils import timing

jax.config.update("jax_enable_x64", True)

logger = logging.getLogger(__name__)

#: GALAH_TPU_MEGAKERNEL values: auto (engage inside device greedy
#: rounds, demote on failure), 0 (never), 1 (forced — failures and
#: ineligibility propagate).
MEGAKERNEL_MODES = ("auto", "0", "1")

#: Max consecutive round windows fused into one slab. The dispatch
#: reduction per slab is S windows -> 2 programs (enqueue + fold), so
#: 16 caps the win at 8x while keeping the conflict-fallback dense
#: matrix (slab_width^2 f64) small.
SLAB_WINDOWS = 16

# Numeric-determinism contract checked by `galah-tpu lint` (GL9xx):
# the fused fold must pick the SAME representatives as the dense
# window fold and the host scan — it compares stored f64 values with
# one IEEE >=, never re-accumulates.
DETERMINISM_CONTRACT = {
    "family": "megakernel",
    "dtype": "float64",
    "functions": ["slab_select", "_slab_fold_jit"],
}

# Pipeline-discipline annotation (GL10xx): the fused fold is a
# device-round body — a host-sync call inside it would reintroduce
# the per-round host round-trip the megakernel removes (GL1006).
PIPELINE_STAGE = {  # galah-lint: ignore[GL704] the engine owns flow attribution
    "device_round": ["_slab_fold_jit"],
}


def resolve_megakernel() -> Tuple[str, bool]:
    """(mode, explicit) for the fused-round strategy.

    Mirrors engine._overlap_mode: malformed values warn and read as
    AUTO; ``explicit`` is True only for a well-formed pin (the
    pinned-failure-propagation contract keys off mode == '1')."""
    env = (os.environ.get("GALAH_TPU_MEGAKERNEL") or "").strip().lower()
    if env in MEGAKERNEL_MODES:
        return env, True
    if env:
        logger.warning("ignoring malformed GALAH_TPU_MEGAKERNEL=%r "
                       "(want auto/0/1)", env)
    return "auto", False


@profiled("megakernel.slab_fold")
@jax.jit
def _slab_fold_jit(qi: jax.Array, qj: jax.Array, qv: jax.Array,
                   count: jax.Array, ext: jax.Array, valid: jax.Array,
                   thr: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Queue-fed segmented greedy fold over one slab.

    ``qi``/``qj``/``qv``: the pair queue's buffers — compacted
    slab-local edge triples with ``qi < qj`` in the dense prefix
    ``[0, count)``. ``ext``: per-position already-clustered flags from
    earlier rounds. ``valid``: padding mask. ``thr``: f64 scalar.

    Per iteration, exactly the _window_select_jit recurrence on the
    edge list: a position becomes a rep when no passing earlier
    neighbor is still undecided or already a rep, and a member when a
    passing earlier neighbor IS a rep. The while_loop drains the
    compacted queue index until no claim changes (fixpoint) or the
    slab-width depth bound — residual undecided positions signal the
    caller's conflict fallback, same contract as window_select.
    """
    cap = qi.shape[0]
    width = ext.shape[0]
    live = jnp.arange(cap) < count
    passing = live & (qv >= thr)  # NaN False, like the host None guard
    undecided = valid & ~ext
    rep = jnp.zeros_like(undecided)

    def cond(carry):
        it, _rep, _und, changed = carry
        return changed & (it < width)

    def body(carry):
        it, rep, und, _ = carry
        zeros = jnp.zeros(width, dtype=jnp.int32)
        und_at = zeros.at[qj].max(
            (passing & und[qi]).astype(jnp.int32)) > 0
        rep_at = zeros.at[qj].max(
            (passing & rep[qi]).astype(jnp.int32)) > 0
        new_rep = und & ~und_at & ~rep_at
        new_member = und & rep_at
        und2 = und & ~new_rep & ~new_member
        return it + 1, rep | new_rep, und2, jnp.any(und2 != und)

    _, rep, undecided, _ = jax.lax.while_loop(
        cond, body,
        (jnp.zeros((), dtype=jnp.int64), rep, undecided,
         jnp.ones((), dtype=bool)))
    return rep, undecided


def slab_select(queue, ei: np.ndarray, ej: np.ndarray, ev: np.ndarray,
                ext: np.ndarray,
                thr: float) -> Tuple[Optional[np.ndarray], bool]:
    """One fused slab round: enqueue the slab's edges, fold in place.

    ``queue``: a device_queue.PairQueue. ``ei``/``ej``: slab-local
    positions with ``ei < ej``; ``ev``: their exact f64 ANIs; ``ext``:
    already-clustered flags. Returns ``(rep_flags, converged)`` — or
    ``(None, False)`` when the edges did not fit the queue (capacity
    spill; the queue is reset and the caller takes the dense path).
    Two dispatches total regardless of how many round windows the
    slab fuses.
    """
    w = len(ext)
    n = len(ei)
    if n > queue.cap:
        queue.reset()
        return None, False
    stored = queue.enqueue(ei, ej, ev)  # 1 dispatch (pow2-bucketed)
    if stored < n:
        queue.reset()
        return None, False
    gb = _bucket(w)
    extp = np.zeros(gb, dtype=bool)
    extp[:w] = ext
    validp = np.zeros(gb, dtype=bool)
    validp[:w] = True
    timing.dispatch(1)
    timing.counter("greedy-select-dispatches", 1)
    rep, undecided = _slab_fold_jit(
        queue._qi, queue._qj, queue._qv, queue._count,
        jnp.asarray(extp), jnp.asarray(validp), jnp.float64(thr))
    queue.reset()
    rep_np = np.asarray(rep)[:w]
    converged = not bool(np.asarray(undecided)[:w].any())
    return rep_np, converged
