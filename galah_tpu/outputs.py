"""Output writing: cluster TSV, representative symlink/copy dirs, rep list.

Mirrors the reference's output layer (reference:
src/cluster_argument_parsing.rs:367-562): output files are opened and
directories created BEFORE clustering so failures surface early; the
cluster definition file holds "rep\tmember" lines (rep = first member of
each cluster); representative FASTAs are symlinked or copied into output
directories with `.1.fna`-style renaming on basename clashes; the rep
list file holds one representative path per line.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
from typing import List, Optional, Sequence, TextIO

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class OutputHandles:
    cluster_definition: Optional[TextIO] = None
    representative_fasta_directory: Optional[str] = None
    representative_fasta_directory_copy: Optional[str] = None
    representative_list: Optional[TextIO] = None


def _setup_directory(path: Optional[str], argument: str) -> Optional[str]:
    """Create (or accept empty pre-existing) output directory, fail fast
    otherwise (reference: src/cluster_argument_parsing.rs:488-522)."""
    if path is None:
        return None
    if os.path.exists(path):
        if not os.path.isdir(path):
            raise ValueError(
                f"The {argument} path specified ({path}) exists but is "
                "not a directory")
        if os.listdir(path):
            raise ValueError(
                f"The {argument} specified ({path}) exists and is not "
                "empty")
        logger.info("Using pre-existing but empty %s", argument)
    else:
        logger.info("Creating %s ..", argument)
        os.makedirs(path, exist_ok=True)
    return path


def _nearest_existing_dir(path: str) -> str:
    """Closest existing ancestor of `path` (os.makedirs would create
    everything below it)."""
    d = os.path.abspath(path)
    while not os.path.exists(d):
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return d


def validate_output_paths(
    cluster_definition: Optional[str] = None,
    representative_fasta_directory: Optional[str] = None,
    representative_fasta_directory_copy: Optional[str] = None,
    representative_list: Optional[str] = None,
) -> None:
    """Fail-fast checks mirroring setup_outputs WITHOUT touching the
    targets.

    Multi-host non-writer processes run this instead of setup_outputs:
    they must fail before the first collective exactly when the writer
    does (same shared filesystem, same answer), but must not open/
    truncate the files or create the directories process 0 will. The
    conditions below are setup_outputs' own, case for case: file
    outputs need an existing, writable direct parent and must not be
    directories; directory outputs must be empty if they exist
    (_setup_directory), else creatable (nearest existing ancestor
    writable, since makedirs creates intermediates).
    """
    for p in (cluster_definition, representative_list):
        if p:
            if os.path.isdir(p):
                raise ValueError(
                    f"output path {p} is a directory")
            d = os.path.dirname(os.path.abspath(p)) or "."
            if not os.path.isdir(d) or not os.access(d, os.W_OK):
                raise OSError(f"output path not writable: {p}")
            if os.path.exists(p) and not os.access(p, os.W_OK):
                raise OSError(f"output file not writable: {p}")
    for p, argument in (
            (representative_fasta_directory,
             "output-representative-fasta-directory"),
            (representative_fasta_directory_copy,
             "output-representative-fasta-directory-copy")):
        if not p:
            continue
        if os.path.exists(p):
            if not os.path.isdir(p):
                raise ValueError(
                    f"The {argument} path specified ({p}) exists but "
                    "is not a directory")
            if os.listdir(p):
                raise ValueError(
                    f"The {argument} specified ({p}) exists and is "
                    "not empty")
        else:
            anc = _nearest_existing_dir(p)
            if not os.path.isdir(anc) or not os.access(anc, os.W_OK):
                raise OSError(f"output directory not creatable: {p}")


def setup_outputs(
    cluster_definition: Optional[str] = None,
    representative_fasta_directory: Optional[str] = None,
    representative_fasta_directory_copy: Optional[str] = None,
    representative_list: Optional[str] = None,
) -> OutputHandles:
    """Open files / create directories before compute (fail-fast)."""
    return OutputHandles(
        cluster_definition=(open(cluster_definition, "w")
                            if cluster_definition else None),
        representative_fasta_directory=_setup_directory(
            representative_fasta_directory,
            "output-representative-fasta-directory"),
        representative_fasta_directory_copy=_setup_directory(
            representative_fasta_directory_copy,
            "output-representative-fasta-directory-copy"),
        representative_list=(open(representative_list, "w")
                             if representative_list else None),
    )


def _write_reps_to_directory(
    clusters: Sequence[Sequence[int]],
    genomes: Sequence[str],
    directory: Optional[str],
    copy: bool,
) -> None:
    if directory is None:
        return
    some_names_clashed = False
    for cluster in clusters:
        rep = genomes[cluster[0]]
        src = os.path.realpath(rep)
        basename = os.path.basename(rep)
        target = os.path.join(directory, basename)
        counter = 0
        while os.path.lexists(target):
            if not some_names_clashed:
                logger.warning(
                    "One or more sequence files have the same file name. "
                    "Renaming clashes by adding .1.fna, .2.fna etc.")
                some_names_clashed = True
            counter += 1
            target = os.path.join(directory, f"{basename}.{counter}.fna")
        if copy:
            shutil.copy(src, target)
        else:
            os.symlink(src, target)


def write_outputs(
    handles: OutputHandles,
    clusters: Sequence[Sequence[int]],
    genomes: Sequence[str],
) -> None:
    """Write all requested outputs (reference:
    src/cluster_argument_parsing.rs:432-485)."""
    if handles.cluster_definition is not None:
        for cluster in clusters:
            rep = genomes[cluster[0]]
            for genome_index in cluster:
                handles.cluster_definition.write(
                    f"{rep}\t{genomes[genome_index]}\n")
        handles.cluster_definition.close()

    _write_reps_to_directory(
        clusters, genomes, handles.representative_fasta_directory, copy=False)
    _write_reps_to_directory(
        clusters, genomes, handles.representative_fasta_directory_copy,
        copy=True)

    if handles.representative_list is not None:
        for cluster in clusters:
            handles.representative_list.write(f"{genomes[cluster[0]]}\n")
        handles.representative_list.close()


def read_cluster_file(path: str) -> List[List[str]]:
    """Parse a cluster-definition TSV back into clusters of paths.

    A line whose rep == member starts a new cluster (reference:
    src/cluster_validation.rs:80-113).
    """
    clusters: List[List[str]] = []
    with open(path) as fh:
        for line in fh:
            line = line.rstrip("\n")
            if not line:
                continue
            rep, member = line.split("\t")
            if rep == member:
                clusters.append([member])
            else:
                if not clusters:
                    raise ValueError(
                        f"malformed cluster file {path}: member line "
                        "before any representative line")
                clusters[-1].append(member)
    return clusters
