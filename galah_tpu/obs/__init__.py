"""galah-tpu observability: metrics, trace events, run reports.

The unified telemetry layer (docs/observability.md). Three pieces, one
lifecycle:

  * ``obs.metrics`` — the typed metrics registry (counters, gauges,
    histograms) with thread-safe emission; everything the StageTimer
    counts is mirrored here, plus registry-native series like
    per-batch ANI latency and pairlist waste ratios.
  * ``obs.trace`` — the Chrome-trace-format span/event recorder behind
    ``--trace-events PATH`` (Perfetto-loadable, including JAX compile
    events via jax.monitoring); ``obs.events`` adds structured
    resilience/warning events to the same timeline and to the report.
  * ``obs.report`` — assembles ``run_report.json`` at run end
    (``--run-report PATH`` / ``GALAH_OBS_REPORT``) and powers the
    ``galah-tpu report`` subcommand (render + ``--diff``).

``reset_run()`` gives a run a clean slate; ``finalize()`` assembles,
validates, and writes the report.

Import discipline: this package must stay importable without jax and
without circular imports from utils/timing.py — ``report`` is imported
lazily, only at assembly time.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from galah_tpu.obs import events, metrics, profile, trace  # noqa: F401

logger = logging.getLogger(__name__)


def reset_run() -> None:
    """Fresh metrics + events + profiler counters for a new run (trace
    recorder unchanged: its lifetime is the CLI invocation, managed by
    start/stop; the profiler's compiled caches survive too)."""
    metrics.reset()
    events.reset()
    profile.reset()
    # Index-operation snapshot (stdlib-only package, safe to import
    # here): one run = at most one index op's summary in the report.
    from galah_tpu import index as index_pkg

    index_pkg.reset()


def finalize(subcommand: str,
             report_path: Optional[str] = None,
             argv: Optional[List[str]] = None,
             started_at: Optional[float] = None,
             lint: Optional[dict] = None) -> Optional[dict]:
    """Assemble the run report, validate it against the committed
    schema, write it when a path is given, and close the trace.
    `lint` attaches the static-analysis summary (lint runs only).
    Telemetry failures log and return None — they never fail the run."""
    from galah_tpu.obs import report as report_mod

    out = None
    try:
        out = report_mod.assemble(subcommand, argv=argv,
                                  started_at=started_at, lint=lint)
        problems = report_mod.validate(out)
        if problems:  # a bug in assembly, not in the user's run
            logger.warning("run report failed schema validation: %s",
                           "; ".join(problems[:5]))
        if report_path:
            report_mod.write(report_path, out)
        # Feed the cross-run perf ledger (docs/observability.md):
        # one appended line per finalized run when GALAH_OBS_LEDGER
        # names a path, keyed by backend/topology/workload/strategy.
        from galah_tpu.config import env_value

        ledger_path = env_value("GALAH_OBS_LEDGER")
        if ledger_path:
            from galah_tpu.obs import ledger as ledger_mod

            ledger_mod.record_report(ledger_path, out, subcommand)
    except Exception:
        logger.warning("run report assembly failed", exc_info=True)
    finally:
        trace.stop()
    return out
