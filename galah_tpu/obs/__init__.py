"""galah-tpu observability: metrics, trace events, run reports.

The unified telemetry layer (docs/observability.md). Three pieces, one
lifecycle:

  * ``obs.metrics`` — the typed metrics registry (counters, gauges,
    histograms) with thread-safe emission; everything the StageTimer
    counts is mirrored here, plus registry-native series like
    per-batch ANI latency and pairlist waste ratios.
  * ``obs.trace`` — the Chrome-trace-format span/event recorder behind
    ``--trace-events PATH`` (Perfetto-loadable, including JAX compile
    events via jax.monitoring); ``obs.events`` adds structured
    resilience/warning events to the same timeline and to the report.
  * ``obs.report`` — assembles ``run_report.json`` at run end
    (``--run-report PATH`` / ``GALAH_OBS_REPORT``) and powers the
    ``galah-tpu report`` subcommand (render + ``--diff``).
  * ``obs.flow`` — flow ids + per-stage wait/service spans with
    blocked-on attribution for the overlapped pipeline, feeding the
    report's ``flow`` section and ``galah-tpu flow analyze``.
  * ``obs.heartbeat`` — the periodic ``heartbeat.jsonl`` liveness
    snapshot (``GALAH_OBS_HEARTBEAT_S``) behind ``galah-tpu top``.

``reset_run()`` gives a run a clean slate; ``finalize()`` assembles,
validates, and writes the report.

Import discipline: this package must stay importable without jax and
without circular imports from utils/timing.py — ``report`` is imported
lazily, only at assembly time.
"""

from __future__ import annotations

import atexit
import logging
import sys
from typing import List, Optional

from galah_tpu.obs import (events, flow, heartbeat, metrics,  # noqa: F401
                           profile, trace)

logger = logging.getLogger(__name__)


def reset_run() -> None:
    """Fresh metrics + events + profiler + flow counters for a new run
    (trace recorder unchanged: its lifetime is the CLI invocation,
    managed by start/stop; the profiler's compiled caches survive
    too)."""
    metrics.reset()
    events.reset()
    profile.reset()
    flow.reset()
    heartbeat.reset()
    # OpenMetrics exporter state (the fleet-rollup provider is bound
    # to one run's fleet dir).
    from galah_tpu.obs import openmetrics

    openmetrics.reset()
    # Index-operation snapshot (stdlib-only package, safe to import
    # here): one run = at most one index op's summary in the report.
    from galah_tpu import index as index_pkg

    index_pkg.reset()
    # Fleet-run snapshot (same stdlib-only snapshot-holder shape).
    from galah_tpu import fleet as fleet_pkg

    fleet_pkg.reset()


def _shard_context(report_path: Optional[str]) -> Optional[int]:
    """The fleet shard id this process is finalizing for, or None.

    A fleet worker subprocess carries the scheduler's
    GALAH_TPU_FLEET_WORKER env stamp and writes its report under
    ``shards/shard_NNN/``; both must agree before we brand the ledger
    entry — a bystander run that merely reports into a shard-shaped
    path keeps the plain key."""
    import os
    import re

    if not os.environ.get("GALAH_TPU_FLEET_WORKER"):
        return None
    m = re.search(r"shard_(\d+)", os.path.abspath(report_path or ""))
    return int(m.group(1)) if m else None


def finalize(subcommand: str,
             report_path: Optional[str] = None,
             argv: Optional[List[str]] = None,
             started_at: Optional[float] = None,
             lint: Optional[dict] = None) -> Optional[dict]:
    """Assemble the run report, validate it against the committed
    schema, write it when a path is given, and close the trace.
    `lint` attaches the static-analysis summary (lint runs only).
    Telemetry failures log and return None — they never fail the run."""
    from galah_tpu.obs import report as report_mod

    out = None
    try:
        # Stop the heartbeat FIRST (writes its final beat) so the
        # report's occupancy time-series includes the whole run; the
        # stop in the finally below is then an idempotent no-op.
        heartbeat.stop()
        out = report_mod.assemble(subcommand, argv=argv,
                                  started_at=started_at, lint=lint)
        problems = report_mod.validate(out)
        if problems:  # a bug in assembly, not in the user's run
            logger.warning("run report failed schema validation: %s",
                           "; ".join(problems[:5]))
        if report_path:
            report_mod.write(report_path, out)
        # Feed the cross-run perf ledger (docs/observability.md):
        # one appended line per finalized run when GALAH_OBS_LEDGER
        # names a path, keyed by backend/topology/workload/strategy.
        from galah_tpu.config import env_value

        ledger_path = env_value("GALAH_OBS_LEDGER")
        if ledger_path:
            from galah_tpu.obs import ledger as ledger_mod

            ledger_mod.record_report(ledger_path, out, subcommand,
                                     shard=_shard_context(report_path))
    except Exception:
        logger.warning("run report assembly failed", exc_info=True)
    finally:
        heartbeat.stop()
        trace.stop()
    return out


# -- crash/preemption artifact flushing ------------------------------
#
# Three exits can interrupt a run mid-stream: the cooperative
# preemption path (first signal -> PreemptionRequested -> finalize),
# an unhandled exception, and the second-signal hard exit. finalize()
# covers the first; the hooks below cover the other two so the trace
# gets its JSON terminator and the heartbeat its final beat — an
# interrupted run's artifacts must always be loadable.

_CRASH_HOOKS = {"installed": False}


def flush_artifacts() -> None:
    """Best-effort drain of the streaming telemetry sinks (idempotent:
    trace.stop/heartbeat.stop both tolerate repeat calls)."""
    try:
        heartbeat.stop()
    except Exception:
        logger.debug("heartbeat flush failed", exc_info=True)
    try:
        trace.stop()
    except Exception:
        logger.debug("trace flush failed", exc_info=True)


def install_crash_hooks() -> None:
    """Arm atexit + excepthook + the second-signal flush (idempotent,
    once per process; called from the CLI next to interrupt.install)."""
    if _CRASH_HOOKS["installed"]:
        return
    _CRASH_HOOKS["installed"] = True
    atexit.register(flush_artifacts)
    prev_hook = sys.excepthook

    def _excepthook(tp, val, tb):
        flush_artifacts()
        prev_hook(tp, val, tb)

    sys.excepthook = _excepthook
    # Second-signal hard exit: only the lock-light heartbeat flush (a
    # single O_APPEND write); the trace file is already durable per
    # event and closing it could deadlock inside a signal handler.
    from galah_tpu.resilience import interrupt

    interrupt.register_flush(heartbeat.flush)
