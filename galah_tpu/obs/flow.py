"""Flow-level pipeline observability: who waited on whom, and why.

The overlapped dataflow (cluster/engine.py `_cluster_overlapped`) runs
ingest → sketch → pair-screen → fragment-ANI → greedy as concurrent
streaming stages, but occupancy gauges alone cannot answer "which
stage limits e2e genomes/s". This module assigns a **flow id** to
every pipeline item (genome batch, sketch block, edge stripe,
fragment batch, greedy round), records each stage's **service** time
and **blocked** time with a reason (upstream-empty, downstream-full,
device-dispatch, host, lock), and streams the pairings into bounded
per-stage wait/service histograms plus Chrome-trace ``s``/``t``/``f``
flow events (obs/trace.py) linking producer to consumer across the
stage-token-adopting worker threads.

On top of the recorded graph, :func:`critical_path` decomposes the
end-to-end wall into per-stage **blame shares that sum to the wall**:
a stage's upstream-empty wait is blamed on its dominant producer
(recursively), everything else on the stage itself. That is the
machine answer behind ``galah-tpu flow analyze`` and the run report's
``flow`` section; the per-stage ``flow.<stage>.blame_s`` scalars feed
the perf ledger so a migrated bottleneck gates like a perf regression.

Design constraints:

  * **Bounded memory.** No per-item log: durations land in fixed
    log2-bucket histograms, boundary queues are capped deques
    (:data:`BOUNDARY_CAP`) whose evictions are counted, never grown.
  * **Cheap when off.** ``GALAH_OBS_FLOW=0`` turns every record call
    into a dict-lookup no-op; :func:`blocked` still measures (its
    ``.seconds`` feeds the occupancy gauges regardless).
  * **Sanitizer-clean.** All mutable state is guarded by one lock
    (GUARDED_BY below); trace/metrics — which take their own locks —
    are only ever called *outside* it.

Thread propagation mirrors utils/timing.py: the spawning thread takes
:func:`token`, pool workers run under :func:`adopt` (io/prefetch.py
``_adopting`` does both timers in one wrapper), so spans emitted from
a worker attribute to the stage context that submitted the work.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Deque, Dict, Iterator, List, Optional, Tuple

#: Flow-item kinds, one per pipeline boundary object.
FLOW_KINDS = ("genome_batch", "sketch_block", "edge_stripe",
              "fragment_batch", "greedy_round")

#: The blocked-on attribution vocabulary. `upstream-empty` is the only
#: reason that propagates blame to the producer in critical_path();
#: everything else is the stage's own problem.
BLOCKED_REASONS = ("upstream-empty", "downstream-full",
                   "device-dispatch", "host", "lock")

#: Per-boundary in-flight cap: beyond this the oldest pending flow id
#: is evicted (and counted as dropped) rather than growing the deque —
#: the bounded-memory gate for 1M-genome streams.
BOUNDARY_CAP = 4096

# Histogram buckets: log2 edges from 1 µs to ~1000 s. Fixed size, so
# a 10k-item stream and a 1M-item stream cost the same memory.
_BUCKET_EDGES = tuple(2.0 ** e for e in range(-20, 11))

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx)
# and enforced at runtime by GalahSan (THREADED_MODULES). The module
# global GLOBAL is deliberately NOT guarded: reset() runs in the
# single-threaded run lifecycle and every helper takes a local
# snapshot (`rec = GLOBAL`), the same idiom as trace.RECORDER.
GUARDED_BY = {
    "FlowRecorder._next_id": "FlowRecorder._lock",
    "FlowRecorder._kinds": "FlowRecorder._lock",
    "FlowRecorder._created": "FlowRecorder._lock",
    "FlowRecorder._completed": "FlowRecorder._lock",
    "FlowRecorder._dropped": "FlowRecorder._lock",
    "FlowRecorder._stages": "FlowRecorder._lock",
    "FlowRecorder._edges": "FlowRecorder._lock",
    "FlowRecorder._boundaries": "FlowRecorder._lock",
}
LOCK_ORDER = ["FlowRecorder._lock"]


class _Hist:
    """Fixed-bucket log2 duration histogram (seconds). Not
    thread-safe on its own: every instance lives inside a
    FlowRecorder and is only touched under its lock."""

    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.buckets = [0] * (len(_BUCKET_EDGES) + 1)

    def observe(self, seconds: float) -> None:
        s = max(0.0, float(seconds))
        self.count += 1
        self.sum += s
        self.min = min(self.min, s)
        self.max = max(self.max, s)
        lo, hi = 0, len(_BUCKET_EDGES)
        while lo < hi:  # first edge >= s (bisect; no imports needed)
            mid = (lo + hi) // 2
            if _BUCKET_EDGES[mid] < s:
                lo = mid + 1
            else:
                hi = mid
        self.buckets[lo] += 1

    def snapshot(self) -> dict:
        out = {"count": self.count, "sum_s": round(self.sum, 6)}
        if self.count:
            out["min_s"] = round(self.min, 6)
            out["max_s"] = round(self.max, 6)
            out["mean_s"] = round(self.sum / self.count, 6)
            # sparse: only non-empty buckets, keyed by upper edge
            nz = {}
            for i, n in enumerate(self.buckets):
                if n:
                    le = (_BUCKET_EDGES[i] if i < len(_BUCKET_EDGES)
                          else float("inf"))
                    nz[f"{le:.6g}"] = n
            out["le_s"] = nz
        return out


class _StageAgg:
    """Per-stage aggregates: item count, service histogram, one wait
    histogram per blocked reason. Lock discipline as _Hist."""

    __slots__ = ("items", "service", "waits")

    def __init__(self) -> None:
        self.items = 0
        self.service = _Hist()
        self.waits: Dict[str, _Hist] = {}

    def wait_hist(self, reason: str) -> _Hist:
        h = self.waits.get(reason)
        if h is None:
            h = self.waits[reason] = _Hist()
        return h


class _FlowContext(threading.local):
    """Thread-local (stage, flow_id) context stack, adoptable across
    pool workers like timing.StageTimer's stage tokens."""

    def __init__(self) -> None:
        self.stack: List[Tuple[Optional[str], Optional[int]]] = []


class _Blocked:
    """Result object of :func:`blocked`: carries the measured wall so
    call sites can keep their occupancy accounting (`wait_s +=
    b.seconds`) without a raw clock pair of their own (GL704)."""

    __slots__ = ("seconds",)

    def __init__(self) -> None:
        self.seconds = 0.0


class FlowRecorder:
    """Process-wide flow graph accumulator (one per run; see reset())."""

    def __init__(self, enabled: Optional[bool] = None) -> None:
        if enabled is None:
            enabled = _env_enabled()
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._tls = _FlowContext()
        self._next_id = 0
        self._kinds: Dict[str, int] = {}
        self._created = 0
        self._completed = 0
        self._dropped = 0
        self._stages: Dict[str, _StageAgg] = {}
        # (from_stage, to_stage) -> handoff count + queue-latency hist
        self._edges: Dict[Tuple[str, str], List] = {}
        # producing stage -> FIFO of (flow_id, enqueue_perf_t)
        self._boundaries: Dict[str, Deque[Tuple[int, float]]] = {}

    # -- flow ids ----------------------------------------------------

    def begin(self, kind: str) -> int:
        """Mint a flow id for a new pipeline item."""
        if not self.enabled:
            return 0
        with self._lock:
            self._next_id += 1
            fid = self._next_id
            self._created += 1
            self._kinds[kind] = self._kinds.get(kind, 0) + 1
        return fid

    def emit(self, stage: str, fid: int) -> None:
        """Producer side of a boundary: `stage` enqueues item `fid`
        for whatever consumes it next."""
        if not self.enabled or not fid:
            return
        now = time.perf_counter()
        dropped = False
        with self._lock:
            q = self._boundaries.get(stage)
            if q is None:
                q = self._boundaries[stage] = deque()
            if len(q) >= BOUNDARY_CAP:
                q.popleft()
                self._dropped += 1
                dropped = True
            q.append((fid, now))
        if not dropped:
            from galah_tpu.obs import trace
            trace.emit_flow("s", "flow", fid)

    def absorb(self, from_stage: str, to_stage: str) -> Optional[int]:
        """Consumer side: `to_stage` dequeues the oldest item
        `from_stage` emitted. Records the producer→consumer edge and
        the item's boundary-queue latency; returns the flow id (None
        when the boundary is empty, e.g. flow was disabled upstream)."""
        if not self.enabled:
            return None
        now = time.perf_counter()
        with self._lock:
            q = self._boundaries.get(from_stage)
            if not q:
                return None
            fid, t_enq = q.popleft()
            self._completed += 1
            key = (from_stage, to_stage)
            e = self._edges.get(key)
            if e is None:
                e = self._edges[key] = [0, _Hist()]
            e[0] += 1
            e[1].observe(now - t_enq)
            agg = self._stages.get(to_stage)
            if agg is None:
                agg = self._stages[to_stage] = _StageAgg()
            agg.items += 1
        from galah_tpu.obs import trace
        trace.emit_flow("f", "flow", fid)
        return fid

    def complete(self, fid: int) -> None:
        """Terminal flows (greedy rounds, fragment batches) that no
        downstream stage absorbs."""
        if not self.enabled or not fid:
            return
        with self._lock:
            self._completed += 1

    # -- spans -------------------------------------------------------

    def record_service(self, stage: Optional[str], seconds: float,
                       items: int = 0) -> None:
        """Add a service-time observation. ``items`` credits processed
        items for stages with no upstream boundary (ingest, sketch);
        stages that absorb() are item-counted there and pass 0."""
        if not self.enabled:
            return
        if stage is None:
            stage = self.current()[0]
            if stage is None:
                return
        with self._lock:
            agg = self._stages.get(stage)
            if agg is None:
                agg = self._stages[stage] = _StageAgg()
            agg.service.observe(seconds)
            agg.items += max(0, int(items))

    def record_wait(self, stage: Optional[str], reason: str,
                    seconds: float) -> None:
        if not self.enabled:
            return
        if stage is None:
            stage = self.current()[0]
            if stage is None:
                return
        if reason not in BLOCKED_REASONS:
            reason = "host"
        with self._lock:
            agg = self._stages.get(stage)
            if agg is None:
                agg = self._stages[stage] = _StageAgg()
            agg.wait_hist(reason).observe(seconds)

    @contextmanager
    def blocked(self, stage: str,
                reason: str) -> Iterator[_Blocked]:
        """Measure a blocked region. ALWAYS measures (the returned
        object's ``.seconds`` feeds occupancy math even with flow
        disabled); records + traces only when enabled."""
        b = _Blocked()
        t0 = time.perf_counter()
        try:
            yield b
        finally:
            b.seconds = time.perf_counter() - t0
            if self.enabled:
                self.record_wait(stage, reason, b.seconds)
                from galah_tpu.obs import trace
                trace.emit_complete(f"{stage}:blocked[{reason}]", t0,
                                    b.seconds, cat="flow")

    @contextmanager
    def span(self, stage: Optional[str] = None,
             fid: Optional[int] = None) -> Iterator[None]:
        """A service span, bound into the thread-local flow context so
        nested record_* calls (and adopted workers) attribute here."""
        t0 = time.perf_counter()
        self._tls.stack.append((stage, fid))
        try:
            yield
        finally:
            self._tls.stack.pop()
            dt = time.perf_counter() - t0
            if self.enabled and stage is not None:
                self.record_service(stage, dt)
                from galah_tpu.obs import trace
                trace.emit_complete(f"{stage}:service", t0, dt,
                                    cat="flow")
                if fid:
                    trace.emit_flow("t", "flow", fid)

    # -- thread propagation (mirrors timing.stage_token/adopt) -------

    def token(self) -> Tuple[Optional[str], Optional[int]]:
        """The current (stage, flow_id) context, for handing to a
        worker thread at submit time."""
        return self.current()

    @contextmanager
    def adopt(self, token: Tuple[Optional[str], Optional[int]]
              ) -> Iterator[None]:
        self._tls.stack.append(tuple(token))
        try:
            yield
        finally:
            self._tls.stack.pop()

    def current(self) -> Tuple[Optional[str], Optional[int]]:
        stack = self._tls.stack
        return stack[-1] if stack else (None, None)

    # -- introspection -----------------------------------------------

    def queue_depths(self) -> Dict[str, int]:
        """Current boundary-queue depth per producing stage (the
        heartbeat's live backlog signal)."""
        with self._lock:
            return {s: len(q) for s, q in sorted(self._boundaries.items())
                    if q}

    def snapshot(self) -> dict:
        """JSON-ready flow graph for the run report (bounded size)."""
        with self._lock:
            stages = {}
            for name in sorted(self._stages):
                agg = self._stages[name]
                waits = {r: agg.waits[r].snapshot()
                         for r in sorted(agg.waits)}
                stages[name] = {
                    "items": agg.items,
                    "service": agg.service.snapshot(),
                    "service_s": round(agg.service.sum, 6),
                    "wait": waits,
                    "wait_s": {r: round(agg.waits[r].sum, 6)
                               for r in sorted(agg.waits)},
                }
            edges = [{"from": a, "to": b, "items": e[0],
                      "queue": e[1].snapshot()}
                     for (a, b), e in sorted(self._edges.items())]
            return {
                "enabled": self.enabled,
                "flows": {"created": self._created,
                          "completed": self._completed,
                          "dropped": self._dropped,
                          "kinds": dict(sorted(self._kinds.items()))},
                "stages": stages,
                "edges": edges,
            }


def _env_enabled() -> bool:
    """GALAH_OBS_FLOW gate (default on; '0'/'false' disables)."""
    try:
        from galah_tpu.config import env_value
        raw = (env_value("GALAH_OBS_FLOW") or "1").strip().lower()
    except Exception:  # config unavailable mid-teardown: stay on
        return True
    return raw not in ("0", "false", "no", "off")


# Process-wide recorder backing the module-level helpers (same
# one-per-process idiom as metrics.GLOBAL / timing.GLOBAL).
GLOBAL = FlowRecorder()


def reset() -> None:
    """Fresh recorder (run start / tests); re-reads GALAH_OBS_FLOW."""
    global GLOBAL
    GLOBAL = FlowRecorder()


def enabled() -> bool:
    return GLOBAL.enabled


def begin(kind: str) -> int:
    return GLOBAL.begin(kind)


def emit(stage: str, fid: int) -> None:
    GLOBAL.emit(stage, fid)


def absorb(from_stage: str, to_stage: str) -> Optional[int]:
    return GLOBAL.absorb(from_stage, to_stage)


def complete(fid: int) -> None:
    GLOBAL.complete(fid)


def record_service(stage: Optional[str], seconds: float,
                   items: int = 0) -> None:
    GLOBAL.record_service(stage, seconds, items=items)


def record_wait(stage: Optional[str], reason: str,
                seconds: float) -> None:
    GLOBAL.record_wait(stage, reason, seconds)


def blocked(stage: str, reason: str):
    return GLOBAL.blocked(stage, reason)


def span(stage: Optional[str] = None, fid: Optional[int] = None):
    return GLOBAL.span(stage, fid)


def token() -> Tuple[Optional[str], Optional[int]]:
    return GLOBAL.token()


def adopt(tok: Tuple[Optional[str], Optional[int]]):
    return GLOBAL.adopt(tok)


def current() -> Tuple[Optional[str], Optional[int]]:
    return GLOBAL.current()


def queue_depths() -> Dict[str, int]:
    return GLOBAL.queue_depths()


def snapshot() -> dict:
    return GLOBAL.snapshot()


# -- critical path ---------------------------------------------------


def critical_path(snap: dict, e2e_wall_s: float) -> dict:
    """Decompose an e2e wall into per-stage blame shares (sum == wall).

    Pure function over a :func:`snapshot` (or a run report's ``flow``
    section). Walks backward from the terminal stage: each stage's
    observed wall splits into *self time* (service + downstream-full +
    device-dispatch + host + lock waits) blamed on the stage, and
    *upstream-empty* wait forwarded to its dominant producer (the
    incoming edge with the most handoffs), recursively. Conservation
    makes the shares sum to the full wall — the acceptance bar for
    ``galah-tpu flow analyze``.
    """
    wall = float(e2e_wall_s or 0.0)
    stages: Dict[str, dict] = dict(snap.get("stages") or {})
    out = {"e2e_wall_s": round(wall, 6), "bottleneck": None,
           "stages": {}}
    if not stages or wall <= 0:
        return out
    edges = list(snap.get("edges") or [])
    # dominant producer per consumer
    producer: Dict[str, Tuple[str, int]] = {}
    producing = set()
    for e in edges:
        a, b, n = e.get("from"), e.get("to"), int(e.get("items") or 0)
        if a is None or b is None:
            continue
        producing.add(a)
        if b not in producer or n > producer[b][1]:
            producer[b] = (a, n)
    # terminal stage: consumes but never produces; fall back to the
    # stage with the largest observed total when the graph is flat
    def total(s: str) -> float:
        st = stages.get(s) or {}
        return (float(st.get("service_s") or 0.0)
                + sum((st.get("wait_s") or {}).values()))
    terminals = [s for s in stages if s not in producing]
    terminal = (max(terminals, key=total) if terminals
                else max(stages, key=total))
    blame: Dict[str, float] = {s: 0.0 for s in stages}

    def attribute(stage: str, amount: float, visited: frozenset) -> None:
        if amount <= 0:
            return
        st = stages.get(stage)
        if st is None or stage in visited:
            blame[stage] = blame.get(stage, 0.0) + amount
            return
        waits = dict(st.get("wait_s") or {})
        up = float(waits.pop("upstream-empty", 0.0))
        self_time = float(st.get("service_s") or 0.0) + sum(waits.values())
        tot = self_time + up
        if tot <= 0:
            blame[stage] += amount
            return
        blame[stage] += amount * self_time / tot
        up_amount = amount * up / tot
        src = producer.get(stage, (None, 0))[0]
        if src is None or src == stage:
            blame[stage] += up_amount
        else:
            attribute(src, up_amount, visited | {stage})

    attribute(terminal, wall, frozenset())
    for s in sorted(blame):
        st = stages.get(s) or {}
        out["stages"][s] = {
            "blame_s": round(blame[s], 6),
            "share": round(blame[s] / wall, 6),
            "service_s": float(st.get("service_s") or 0.0),
            "wait_s": dict(st.get("wait_s") or {}),
        }
    out["bottleneck"] = max(blame, key=lambda s: blame[s])
    # host decomposition (the megakernel's headline gauge): each
    # stage's blame splits by its busy composition — the
    # device-dispatch wait bracket is device time, everything else
    # (service + host/lock waits) is host orchestration. flow.host.
    # share is the fraction of the e2e wall blamed on host work;
    # driving it down is what collapsing the per-round host
    # round-trips buys (ledger direction: lower-better).
    host_blame = 0.0
    for s, amount in blame.items():
        st = stages.get(s) or {}
        waits = dict(st.get("wait_s") or {})
        waits.pop("upstream-empty", None)
        dev = float(waits.get("device-dispatch", 0.0))
        busy = float(st.get("service_s") or 0.0) + sum(waits.values())
        host_frac = (busy - dev) / busy if busy > 0 else 1.0
        host_blame += amount * host_frac
    out["host"] = {"blame_s": round(host_blame, 6),
                   "share": round(host_blame / wall, 6)}
    return out


def render_critical_path(cp: dict, indent: str = "") -> List[str]:
    """Human lines for `galah-tpu flow analyze` and report render."""
    lines: List[str] = []
    st = cp.get("stages") or {}
    wall = cp.get("e2e_wall_s") or 0.0
    lines.append(f"{indent}flow critical path "
                 f"(e2e wall {wall:.2f}s):")
    if not st:
        lines.append(f"{indent}  (no flow data — run with "
                     "GALAH_OBS_FLOW=1)")
        return lines
    bn = cp.get("bottleneck")
    bn_share = (st.get(bn, {}).get("share") or 0.0) if bn else 0.0
    lines.append(f"{indent}  bottleneck: {bn} "
                 f"({100.0 * bn_share:.0f}% of wall)")
    host = cp.get("host") or {}
    if host:
        lines.append(
            f"{indent}  host blame: {host.get('blame_s') or 0.0:.2f}s "
            f"({100.0 * (host.get('share') or 0.0):.0f}% of wall; "
            "the rest sits in device-dispatch brackets)")
    lines.append(f"{indent}  {'stage':<10} {'blame':>8} {'share':>6} "
                 f"{'service':>8}  wait(top reason)")
    covered = 0.0
    for name in sorted(st, key=lambda s: -st[s].get("blame_s", 0.0)):
        ent = st[name]
        covered += ent.get("blame_s") or 0.0
        waits = ent.get("wait_s") or {}
        top = max(waits, key=lambda r: waits[r]) if waits else "-"
        wtxt = (f"{waits[top]:.2f}s {top}" if waits else "-")
        lines.append(
            f"{indent}  {name:<10} {ent.get('blame_s', 0.0):>7.2f}s "
            f"{100.0 * (ent.get('share') or 0.0):>5.0f}% "
            f"{ent.get('service_s', 0.0):>7.2f}s  {wtxt}")
    pct = 100.0 * covered / wall if wall else 0.0
    lines.append(f"{indent}  blame shares cover {pct:.0f}% of the "
                 "e2e wall")
    return lines
