"""Typed metrics registry: counters, gauges, histograms.

The registry is the machine-readable half of the telemetry layer
(docs/observability.md): the StageTimer keeps the human-facing stage
report, while every number a run produces — dispatch counts, pairlist
waste ratios, per-batch ANI latency — is ALSO registered here so the
end-of-run ``run_report.json`` (obs/report.py) can carry it without
scraping log lines.

Thread safety: emission is expected from worker threads (IO prefetch
pools, per-genome sketching workers), so every mutation happens under
one registry lock. The rates involved are per-dispatch, not per-element
— contention is negligible next to a device round trip.

Like timing.GLOBAL and the dispatch supervisor, one process-wide
registry (``GLOBAL``) backs the module-level helpers so call sites
never thread a registry object through constructors.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time as _time
from typing import Dict, Iterator, List, Optional, Union

Number = Union[int, float]

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx).
# Registry-created metrics share the registry's lock (one lock for
# all of it, module docstring); the per-class names below are how the
# checker sees that same object from inside each class.
GUARDED_BY = {
    "Counter.value": "Counter._lock",
    "Gauge.value": "Gauge._lock",
    "Histogram.count": "Histogram._lock",
    "Histogram.sum": "Histogram._lock",
    "Histogram.min": "Histogram._lock",
    "Histogram.max": "Histogram._lock",
    "MetricsRegistry._metrics": "MetricsRegistry._lock",
}
LOCK_ORDER = ["MetricsRegistry._lock"]


class Metric:
    """Base: a named, typed, documented series."""

    kind = "metric"

    def __init__(self, name: str, help: str = "", unit: str = "") -> None:
        self.name = name
        self.help = help
        self.unit = unit

    def snapshot(self) -> dict:
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing count (work done, cache hits, ...)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", unit: str = "",
                 _lock: Optional[threading.Lock] = None) -> None:
        super().__init__(name, help, unit)
        self._lock = _lock or threading.Lock()
        self.value = 0

    def inc(self, delta: Number = 1) -> None:
        if delta < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (delta={delta})")
        with self._lock:
            self.value += delta

    def snapshot(self) -> dict:
        return {"kind": self.kind, "unit": self.unit, "help": self.help,
                "value": self.value}


class Gauge(Metric):
    """Last-written value (a ratio, a config-derived size, ...)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", unit: str = "",
                 _lock: Optional[threading.Lock] = None) -> None:
        super().__init__(name, help, unit)
        self._lock = _lock or threading.Lock()
        self.value: Optional[Number] = None

    def set(self, value: Number) -> None:
        with self._lock:
            self.value = value

    def snapshot(self) -> dict:
        return {"kind": self.kind, "unit": self.unit, "help": self.help,
                "value": self.value}


class Histogram(Metric):
    """Streaming summary of observations: count / sum / min / max /
    mean (no bucket boundaries to tune; the run report wants honest
    aggregates, not quantile sketches)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", unit: str = "",
                 _lock: Optional[threading.Lock] = None) -> None:
        super().__init__(name, help, unit)
        self._lock = _lock or threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: Number) -> None:
        v = float(value)
        if math.isnan(v):
            return  # a NaN observation would poison sum/min/max
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        """Observe the wall-clock duration of a with-block, in seconds.

        The one sanctioned timing primitive for pipeline modules — the
        GL701 lint rule bans raw time.perf_counter() there precisely so
        durations land in the registry instead of ad-hoc log lines."""
        t0 = _time.perf_counter()
        try:
            yield
        finally:
            self.observe(_time.perf_counter() - t0)

    def snapshot(self) -> dict:
        return {"kind": self.kind, "unit": self.unit, "help": self.help,
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max, "mean": self.mean}


class MetricsRegistry:
    """Get-or-create registry of typed metrics, one lock for all of it."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, unit: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, unit=unit, _lock=self._lock)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "",
                unit: str = "") -> Counter:
        return self._get_or_create(Counter, name, help, unit)

    def gauge(self, name: str, help: str = "", unit: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help, unit)

    def histogram(self, name: str, help: str = "",
                  unit: str = "") -> Histogram:
        return self._get_or_create(Histogram, name, help, unit)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """Every metric's current state, JSON-ready, sorted by name."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: metrics[name].snapshot()
                for name in sorted(metrics)}


# Process-wide registry backing the module-level helpers (the same
# one-per-process idiom as timing.GLOBAL and dispatch.GLOBAL).
GLOBAL = MetricsRegistry()

#: The one pipeline-occupancy gauge name. Registered centrally so the
#: GL1004 auditor, the streamed stages that feed it, and the dataflow
#: work all agree on a single metric: fraction of a streaming stage's
#: wall spent with the consumer busy (1.0 = never starved, the
#: ROADMAP's "no stage starves" proof).
PIPELINE_OCCUPANCY_GAUGE = "workload.pipeline_occupancy"


def pipeline_occupancy(value: float, stage: str = "") -> Gauge:
    """Set the occupancy gauge (per-stage variant via ``[stage]``,
    like the timing counters' ``retries[site]`` convention)."""
    name = (f"{PIPELINE_OCCUPANCY_GAUGE}[{stage}]" if stage
            else PIPELINE_OCCUPANCY_GAUGE)
    g = GLOBAL.gauge(
        name,
        help="Streaming-stage occupancy: fraction of stage wall with "
             "the consumer busy (1.0 = never starved)")
    g.set(max(0.0, min(1.0, float(value))))
    return g


def counter(name: str, help: str = "", unit: str = "") -> Counter:
    return GLOBAL.counter(name, help=help, unit=unit)


def gauge(name: str, help: str = "", unit: str = "") -> Gauge:
    return GLOBAL.gauge(name, help=help, unit=unit)


def histogram(name: str, help: str = "", unit: str = "") -> Histogram:
    return GLOBAL.histogram(name, help=help, unit=unit)


def snapshot() -> Dict[str, dict]:
    return GLOBAL.snapshot()


def reset() -> None:
    """Fresh registry (run start / tests)."""
    global GLOBAL
    GLOBAL = MetricsRegistry()
