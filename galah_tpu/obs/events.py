"""Structured run events: retries, demotions, quarantines, warnings.

The resilience layer (PR 1) logs these things; this module makes them
*data*. Every ``record(kind, **fields)`` appends one timestamped row to
a process-wide log that the run report serializes under ``"events"``,
and mirrors it into the Chrome trace (obs/trace.py) as an instant event
so a Perfetto timeline shows retries/demotions at the moment they
happened, between the stage spans.

Timestamps are wall-clock epoch seconds (the report is a cross-run
artifact; perf_counter origins do not compare across processes).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from galah_tpu.obs import trace as _trace

_LOCK = threading.Lock()
_EVENTS: List[dict] = []

_WARN_ONCE_LOCK = threading.Lock()
_WARNED: Set[Tuple[str, str]] = set()

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx).
# The locks are never nested today (record() is called AFTER the
# warn-once lock is released); the declared order says which way the
# nesting must go if that ever changes.
GUARDED_BY = {
    "_EVENTS": "_LOCK",
    "_WARNED": "_WARN_ONCE_LOCK",
}
LOCK_ORDER = ["_WARN_ONCE_LOCK", "_LOCK"]


def record(kind: str, **fields) -> None:
    """Append one event row; values must be JSON-serializable."""
    row: Dict[str, object] = {"kind": kind, "time": time.time()}
    row.update(fields)
    with _LOCK:
        _EVENTS.append(row)
    _trace.emit_instant(kind, cat="event", args=fields or None)


def snapshot() -> List[dict]:
    with _LOCK:
        return [dict(r) for r in _EVENTS]


def reset() -> None:
    with _LOCK:
        _EVENTS.clear()


def warn_once(logger: logging.Logger, msg: str, *args,
              key: Optional[str] = None) -> None:
    """Emit `msg` at WARNING once per process, then suppress-and-count.

    For warnings whose repetition carries no information — e.g. the
    missing-CheckM-input notice fires once per clusterer construction,
    which in bench/ladder runs means once per in-process rung. The
    dedupe key is PROCESS-scoped: ``key`` when given (callers that
    re-phrase the same fact, or that must dedupe across modules, pass a
    stable identifier), else ``(logger.name, message)``. Suppressed
    repeats still :func:`record` a ``warn-once-suppressed`` event so
    the run report keeps the true multiplicity."""
    dedupe = (key or logger.name, key or msg)
    with _WARN_ONCE_LOCK:
        first = dedupe not in _WARNED
        if first:
            _WARNED.add(dedupe)
    if first:
        logger.warning(msg, *args)
    else:
        record("warn-once-suppressed", logger=logger.name,
               message=msg % args if args else msg)


def reset_warn_once() -> None:
    """Forget emitted warnings (tests)."""
    with _WARN_ONCE_LOCK:
        _WARNED.clear()
