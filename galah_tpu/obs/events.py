"""Structured run events: retries, demotions, quarantines, warnings.

The resilience layer (PR 1) logs these things; this module makes them
*data*. Every ``record(kind, **fields)`` appends one timestamped row to
a process-wide log that the run report serializes under ``"events"``,
and mirrors it into the Chrome trace (obs/trace.py) as an instant event
so a Perfetto timeline shows retries/demotions at the moment they
happened, between the stage spans.

Timestamps are wall-clock epoch seconds (the report is a cross-run
artifact; perf_counter origins do not compare across processes).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from galah_tpu.obs import trace as _trace

_LOCK = threading.Lock()
_EVENTS: List[dict] = []


def record(kind: str, **fields) -> None:
    """Append one event row; values must be JSON-serializable."""
    row: Dict[str, object] = {"kind": kind, "time": time.time()}
    row.update(fields)
    with _LOCK:
        _EVENTS.append(row)
    _trace.emit_instant(kind, cat="event", args=fields or None)


def snapshot() -> List[dict]:
    with _LOCK:
        return [dict(r) for r in _EVENTS]


def reset() -> None:
    with _LOCK:
        _EVENTS.clear()
