"""Device-cost attribution for jitted/Pallas entry points.

Wall-clock telemetry (utils/timing.py, obs/metrics.py) says how long a
stage took; it cannot say whether the stage was compute-, memory-, or
transfer-bound — the question every sketch-sizing and communication-
avoidance decision needs answered (ROADMAP autotuning item). This
module closes that gap with four measurements per registered entry
point, all landing in the ``device_costs`` section of run_report.json
(schema v3) and, through obs/ledger.py, in the cross-run perf ledger:

  * ``Compiled.cost_analysis()`` — XLA's static FLOP and bytes-accessed
    estimate per executable, captured once per (shape, dtype, static)
    signature at compile time;
  * compile walls — both our own lower+compile timing and the
    jax.monitoring compile-event durations attributed to whichever
    entry is compiling (the same hook stream obs/trace.py records);
  * HBM high-water — ``device.memory_stats()`` where the backend
    provides it (TPU), with a ``jax.live_arrays()`` fallback where it
    does not (CPU), sampled at compiles, periodically at calls, and at
    stage boundaries (``sample_memory``);
  * derived roofline utilization — achieved FLOP/s and bytes/s against
    the published per-chip peaks (``PEAKS``). The peaks are bf16-MXU /
    HBM datasheet numbers: integer-heavy sketch kernels will show low
    MXU utilization by construction, so the ratio ranks stages against
    each other, it is not an efficiency grade.

Registration is the ``profiled(name)`` decorator stacked ABOVE
``jax.jit`` (the jit decorator stays visible to the GL2xx/GL3xx AST
checkers). The wrapper is the dispatch path itself: it routes calls
through a per-signature AOT ``Compiled`` cache, so cost capture adds no
second trace (tracing tile_stats at K=1000 costs ~25 s — doing it twice
per signature would be a real regression). Anything the AOT path cannot
faithfully express falls back to the plain jitted call, permanently for
that signature:

  * tracer arguments (the entry is being traced inside an outer jit /
    shard_map / eval_shape) — passed straight through;
  * a lower()/compile() failure — plain call, fallback counted;
  * a ``Compiled`` call rejecting our dynamic/static argument split
    (static-declared Python scalars are stripped; a dynamic Python
    scalar would mismatch the pytree) — plain call for that signature,
    with the compile-time costs kept.

Everything here must stay importable without jax (obs/__init__.py's
import discipline): jax is only touched through ``sys.modules.get``.

Profiling is on by default (``GALAH_OBS_PROFILE=0`` disables it); the
fallbacks above mean the worst case of a surprising call pattern is the
exact pre-profiler dispatch behavior, minus the cost rows.
"""

from __future__ import annotations

import functools
import sys
import threading
import time as _time
from typing import Any, Dict, List, Optional, Tuple

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx).
# Per-entry counters live under the entry's own lock; the module-level
# HBM/compile accumulators under _LOCK; the entry registry under
# _REGISTRY_LOCK. None of them nest.
GUARDED_BY = {
    "ProfiledFunction.calls": "ProfiledFunction._lock",
    "ProfiledFunction.plain_calls": "ProfiledFunction._lock",
    "ProfiledFunction.aot_fallbacks": "ProfiledFunction._lock",
    "ProfiledFunction.dispatch_wall_s": "ProfiledFunction._lock",
    "ProfiledFunction.compile_wall_s": "ProfiledFunction._lock",
    "ProfiledFunction.monitored_compile_s": "ProfiledFunction._lock",
    "ProfiledFunction.flops_total": "ProfiledFunction._lock",
    "ProfiledFunction.bytes_total": "ProfiledFunction._lock",
    "ProfiledFunction.signatures": "ProfiledFunction._lock",
    "_REGISTRY": "_REGISTRY_LOCK",
    "_HBM": "_LOCK",
    "_TOTALS": "_LOCK",
}
LOCK_ORDER = ["_REGISTRY_LOCK", "ProfiledFunction._lock", "_LOCK"]

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: List["ProfiledFunction"] = []

_LOCK = threading.Lock()
#: Process-wide HBM high-water state: global peak plus one peak per
#: stage label handed to sample_memory().
_HBM: Dict[str, Any] = {"peak_bytes": None, "source": None,
                        "per_stage": {}}
#: Cross-entry accumulators (compile seconds seen by the jax.monitoring
#: hook that no entry was compiling for, e.g. outer-jit compiles).
_TOTALS: Dict[str, float] = {"monitored_compile_s": 0.0,
                             "unattributed_compile_s": 0.0}

# Entries currently inside lower()+compile(), per thread, innermost
# last — the attribution target for monitoring compile events.
_ACTIVE = threading.local()

_HOOK_INSTALLED = False
_HOOK_LOCK = threading.Lock()

#: Published per-chip peaks: device_kind prefix -> (FLOP/s, HBM B/s).
#: bf16 MXU + HBM datasheet figures (module docstring caveat); "cpu"
#: maps to None — no meaningful roofline for an unpinned host.
PEAKS: Dict[str, Optional[Tuple[float, float]]] = {
    "cpu": None,
    "TPU v2": (46e12, 700e9),
    "TPU v3": (123e12, 900e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v5": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6": (918e12, 1640e9),
}


def enabled() -> bool:
    """GALAH_OBS_PROFILE gate (default on; '0'/'false' disables)."""
    from galah_tpu.config import env_value

    raw = (env_value("GALAH_OBS_PROFILE") or "1").strip().lower()
    return raw not in ("0", "false", "no", "off")


def _is_arraylike(x: Any) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _is_tracer(x: Any) -> bool:
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    tracer = getattr(getattr(jax, "core", None), "Tracer", None)
    return tracer is not None and isinstance(x, tracer)


def _any_tracer(args, kwargs) -> bool:
    return any(_is_tracer(a) for a in args) or \
        any(_is_tracer(v) for v in kwargs.values())


def _descriptor(x: Any):
    """Hashable signature atom: shapes/dtypes for arrays, reprs for
    statics; None when the value defeats signature hashing."""
    if _is_arraylike(x):
        return ("a", tuple(x.shape), str(x.dtype))
    if isinstance(x, (bool, int, float, str, bytes, type(None))):
        return ("s", x)
    r = repr(x)
    return ("s", r) if len(r) <= 200 else None


def _sig_key(args, kwargs):
    parts = []
    for a in args:
        d = _descriptor(a)
        if d is None:
            return None
        parts.append(d)
    for k in sorted(kwargs):
        d = _descriptor(kwargs[k])
        if d is None:
            return None
        parts.append((k, d))
    return tuple(parts)


def _merge_cost_analysis(raw) -> Dict[str, float]:
    """cost_analysis() returns a list of per-computation dicts on this
    jax; sum the numeric keys we care about across entries."""
    if raw is None:
        return {}
    entries = raw if isinstance(raw, (list, tuple)) else [raw]
    out: Dict[str, float] = {}
    for ca in entries:
        if not isinstance(ca, dict):
            continue
        for key in ("flops", "bytes accessed"):
            v = ca.get(key)
            if isinstance(v, (int, float)):
                out[key] = out.get(key, 0.0) + float(v)
    return out


def _memory_analysis_dict(compiled) -> Dict[str, int]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for attr, key in (("argument_size_in_bytes", "argument_bytes"),
                      ("output_size_in_bytes", "output_bytes"),
                      ("temp_size_in_bytes", "temp_bytes"),
                      ("generated_code_size_in_bytes", "code_bytes")):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)):
            out[key] = int(v)
    return out


def _on_compile_event(event: str, duration: float, **_kw) -> None:
    """jax.monitoring duration listener: attribute compile seconds to
    whichever entry this thread is compiling, else to the module-wide
    unattributed bucket."""
    if "compil" not in event:
        return
    stack = getattr(_ACTIVE, "stack", None)
    entry = stack[-1] if stack else None
    if entry is not None:
        with entry._lock:
            entry.monitored_compile_s += float(duration)
    with _LOCK:
        _TOTALS["monitored_compile_s"] += float(duration)
        if entry is None:
            _TOTALS["unattributed_compile_s"] += float(duration)


def _install_hook() -> None:
    global _HOOK_INSTALLED
    with _HOOK_LOCK:
        if _HOOK_INSTALLED:
            return
        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _on_compile_event)
            _HOOK_INSTALLED = True
        except Exception:  # profiling must never break dispatch
            _HOOK_INSTALLED = True  # don't retry a broken hook API


class _Signature:
    """One compiled specialization of an entry (or its fallback)."""

    __slots__ = ("compiled", "flops", "bytes_accessed", "memory",
                 "plain_call", "compile_s")

    def __init__(self, compiled=None, flops=None, bytes_accessed=None,
                 memory=None, plain_call=False, compile_s=0.0):
        self.compiled = compiled
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.memory = memory or {}
        self.plain_call = plain_call
        self.compile_s = compile_s


class ProfiledFunction:
    """The registered wrapper around one jitted entry point."""

    def __init__(self, name: str, fn) -> None:
        self.name = name
        self.fn = fn
        self._lock = threading.Lock()
        self.signatures: Dict[Any, _Signature] = {}
        self.calls = 0
        self.plain_calls = 0
        self.aot_fallbacks = 0
        self.dispatch_wall_s = 0.0
        self.compile_wall_s = 0.0
        self.monitored_compile_s = 0.0
        self.flops_total = 0.0
        self.bytes_total = 0.0
        functools.update_wrapper(self, fn,
                                 updated=())  # keep fn's __dict__ off

    # -- bookkeeping -------------------------------------------------

    def reset(self) -> None:
        """Zero the counters for a new run; the compiled cache is kept
        (recompiling identical signatures would charge run N+1 for
        run N's compiles)."""
        with self._lock:
            self.calls = 0
            self.plain_calls = 0
            self.aot_fallbacks = 0
            self.dispatch_wall_s = 0.0
            self.compile_wall_s = 0.0
            self.monitored_compile_s = 0.0
            self.flops_total = 0.0
            self.bytes_total = 0.0

    def _account(self, sig: Optional[_Signature], wall: float,
                 plain: bool) -> int:
        with self._lock:
            self.calls += 1
            calls = self.calls
            self.dispatch_wall_s += wall
            if plain:
                self.plain_calls += 1
            if sig is not None:
                if sig.flops is not None:
                    self.flops_total += sig.flops
                if sig.bytes_accessed is not None:
                    self.bytes_total += sig.bytes_accessed
        return calls

    # -- compile path ------------------------------------------------

    def _compile_signature(self, key, args, kwargs) -> _Signature:
        stack = getattr(_ACTIVE, "stack", None)
        if stack is None:
            stack = _ACTIVE.stack = []
        _install_hook()
        stack.append(self)
        t0 = _time.perf_counter()
        try:
            compiled = self.fn.lower(*args, **kwargs).compile()
            dt = _time.perf_counter() - t0
            costs = _merge_cost_analysis(compiled.cost_analysis())
            sig = _Signature(
                compiled=compiled,
                flops=costs.get("flops"),
                bytes_accessed=costs.get("bytes accessed"),
                memory=_memory_analysis_dict(compiled),
                compile_s=dt)
        except Exception:
            dt = _time.perf_counter() - t0
            sig = _Signature(plain_call=True, compile_s=dt)
            with self._lock:
                self.aot_fallbacks += 1
        finally:
            stack.pop()
        with self._lock:
            self.compile_wall_s += dt
            cached = self.signatures.setdefault(key, sig)
        return cached

    def _mark_plain(self, key, sig: _Signature) -> None:
        with self._lock:
            sig.plain_call = True
            self.aot_fallbacks += 1
            self.signatures[key] = sig

    # -- dispatch ----------------------------------------------------

    def __call__(self, *args, **kwargs):
        if not enabled() or _any_tracer(args, kwargs):
            return self.fn(*args, **kwargs)
        key = _sig_key(args, kwargs)
        if key is None:
            return self.fn(*args, **kwargs)
        with self._lock:
            sig = self.signatures.get(key)
        if sig is None:
            sig = self._compile_signature(key, args, kwargs)
            sample_memory(self.name)
        t0 = _time.perf_counter()
        plain = sig.plain_call
        if plain:
            out = self.fn(*args, **kwargs)
        else:
            dyn_args = [a for a in args if _is_arraylike(a)]
            dyn_kwargs = {k: v for k, v in kwargs.items()
                          if _is_arraylike(v)}
            try:
                out = sig.compiled(*dyn_args, **dyn_kwargs)
            except TypeError:
                # our dynamic/static split mismatched the pytree —
                # permanent per-signature fallback, costs kept
                self._mark_plain(key, sig)
                plain = True
                out = self.fn(*args, **kwargs)
        calls = self._account(sig, _time.perf_counter() - t0, plain)
        if calls <= 4 or calls % 16 == 0:
            sample_memory(self.name)
        return out

    # -- reporting ---------------------------------------------------

    def snapshot(self, peak: Optional[Tuple[float, float]]) -> dict:
        with self._lock:
            memory: Dict[str, int] = {}
            for sig in self.signatures.values():
                for k, v in sig.memory.items():
                    memory[k] = max(memory.get(k, 0), v)
            wall = self.dispatch_wall_s
            achieved_f = (self.flops_total / wall
                          if wall > 0 and self.flops_total else None)
            achieved_b = (self.bytes_total / wall
                          if wall > 0 and self.bytes_total else None)
            return {
                "calls": self.calls,
                "plain_calls": self.plain_calls,
                "signatures": len(self.signatures),
                "aot_fallbacks": self.aot_fallbacks,
                "flops": self.flops_total or None,
                "bytes_accessed": self.bytes_total or None,
                "dispatch_wall_s": wall,
                "compile_wall_s": self.compile_wall_s,
                "monitored_compile_s": self.monitored_compile_s,
                "memory": memory,
                "achieved_flops_per_s": achieved_f,
                "achieved_bytes_per_s": achieved_b,
                "flops_utilization": (achieved_f / peak[0]
                                      if peak and achieved_f else None),
                "bandwidth_utilization": (achieved_b / peak[1]
                                          if peak and achieved_b
                                          else None),
            }


def profiled(name: str):
    """Register a jitted entry point for device-cost attribution:

        @profiled("pairwise.tile_stats")
        @functools.partial(jax.jit, static_argnames=(...))
        def tile_stats_pallas(...): ...

    Stacks above jax.jit (the jit decorator stays visible to the
    GL2xx/GL3xx checkers); also usable as a plain call on a jit object:
    ``_window_hits = profiled("fragment.window_hits")(jax.jit(f))``."""
    def wrap(fn):
        pf = ProfiledFunction(name, fn)
        with _REGISTRY_LOCK:
            _REGISTRY.append(pf)
        return pf
    return wrap


# ---------------------------------------------------------------------------
# HBM high-water sampling
# ---------------------------------------------------------------------------


def sample_memory(stage: Optional[str] = None) -> Optional[int]:
    """Record the current device-memory footprint (bytes, summed over
    local devices) into the global and per-stage high-water marks.

    TPU backends report allocator truth via ``device.memory_stats()``;
    backends without it (CPU) fall back to summing ``jax.live_arrays()``
    — an under-count of allocator slack, but a faithful live-buffer
    high-water. Returns the sampled byte count, or None when jax is not
    up. Never raises."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    current: Optional[int] = None
    source = None
    try:
        stats = []
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            stats.append(ms() if ms is not None else None)
        if any(s for s in stats):
            current = sum(int(s.get("peak_bytes_in_use",
                                    s.get("bytes_in_use", 0)))
                          for s in stats if s)
            source = "memory_stats"
        else:
            current = sum(int(getattr(a, "nbytes", 0))
                          for a in jax.live_arrays())
            source = "live_arrays"
    except Exception:
        return None
    with _LOCK:
        if _HBM["peak_bytes"] is None or current > _HBM["peak_bytes"]:
            _HBM["peak_bytes"] = current
            _HBM["source"] = source
        if stage is not None:
            prev = _HBM["per_stage"].get(stage)
            if prev is None or current > prev:
                _HBM["per_stage"][stage] = current
    return current


# ---------------------------------------------------------------------------
# Roofline peaks + snapshot
# ---------------------------------------------------------------------------


def device_peaks() -> dict:
    """The roofline peak entry for the current backend: device kind
    plus (peak FLOP/s, peak bytes/s), nulls when unknown/CPU."""
    out = {"device_kind": None, "peak_flops_per_s": None,
           "peak_bytes_per_s": None}
    jax = sys.modules.get("jax")
    if jax is None:
        return out
    try:
        kind = str(jax.devices()[0].device_kind)
    except Exception:
        return out
    out["device_kind"] = kind
    best = None
    for prefix, peak in PEAKS.items():
        if kind.lower().startswith(prefix.lower()) and peak is not None:
            if best is None or len(prefix) > best[0]:
                best = (len(prefix), peak)
    if best is not None:
        out["peak_flops_per_s"], out["peak_bytes_per_s"] = best[1]
    return out


def snapshot() -> dict:
    """The ``device_costs`` section of run_report.json (schema v3)."""
    peaks = device_peaks()
    peak = (None if peaks["peak_flops_per_s"] is None
            else (peaks["peak_flops_per_s"], peaks["peak_bytes_per_s"]))
    with _REGISTRY_LOCK:
        registry = list(_REGISTRY)
    entries = {pf.name: pf.snapshot(peak) for pf in registry
               if pf.calls or pf.signatures}
    with _LOCK:
        hbm = {"peak_bytes": _HBM["peak_bytes"],
               "source": _HBM["source"],
               "per_stage": dict(_HBM["per_stage"])}
        totals = dict(_TOTALS)
    return {
        "profiling_enabled": enabled(),
        "entries": entries,
        "hbm": hbm,
        "peaks": peaks,
        "compile": totals,
    }


def reset() -> None:
    """Per-run counter reset (obs.reset_run): compiled caches survive,
    counters and high-water marks do not."""
    with _REGISTRY_LOCK:
        registry = list(_REGISTRY)
    for pf in registry:
        pf.reset()
    with _LOCK:
        _HBM["peak_bytes"] = None
        _HBM["source"] = None
        _HBM["per_stage"] = {}
        _TOTALS["monitored_compile_s"] = 0.0
        _TOTALS["unattributed_compile_s"] = 0.0
