"""Chrome-trace-format span/event recorder (`--trace-events PATH`).

Writes one JSON trace event per line in the Chrome Trace Event "JSON
array" dialect — the file opens with ``[`` and every event line ends
with a comma. Chrome's trace viewer and Perfetto both accept the
unterminated form, and ``close()`` appends a terminator anyway so the
artifact is also plain valid JSON. The recorder is intentionally
append-only and line-buffered: a crashed run still leaves a loadable
trace up to the crash.

What lands in the trace:
  * every StageTimer span (utils/timing.py emits on stage exit) as a
    complete ("ph": "X") event, named by stage and categorized
    "stage";
  * structured events (retries, demotions, quarantines — obs/events.py)
    as instant ("ph": "i") events;
  * JAX compile/lowering activity via ``jax.monitoring`` listeners
    ("cat": "jax"), so compile storms are visible on the same timeline
    as the stages that triggered them;
  * flow events ("ph": "s"/"t"/"f", "cat": "flow" — obs/flow.py) tying
    a pipeline item's producer span to its consumer span across the
    stage-token-adopting worker threads, so a starved handoff shows up
    as a long arrow in Perfetto.

This is complementary to --profile-trace-dir (the XLA profiler): that
captures device timelines below the dispatch boundary; this captures
the host-side pipeline structure above it. Both load in Perfetto.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx).
# The module globals RECORDER/_JAX_HOOKS are deliberately NOT guarded:
# start()/stop() run in the single-threaded CLI lifecycle, and the
# emit_* helpers take a local snapshot (`rec = RECORDER`) so a
# concurrent stop() cannot null the reference mid-emit.
GUARDED_BY = {
    "TraceRecorder._fh": "TraceRecorder._lock",
    "TraceRecorder._closed": "TraceRecorder._lock",
}
LOCK_ORDER = ["TraceRecorder._lock"]


class TraceRecorder:
    """Streaming Chrome-trace writer; all emission is lock-serialized."""

    def __init__(self, path: str) -> None:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "w")
        self._fh.write("[\n")
        self._pid = os.getpid()
        # All timestamps are microseconds since recorder start, on the
        # same clock the StageTimer uses (perf_counter).
        self._t0 = time.perf_counter()
        self._closed = False
        self._emit({"ph": "M", "name": "process_name", "pid": self._pid,
                    "tid": 0,
                    "args": {"name": "galah-tpu host pipeline"}})

    def _emit(self, event: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._fh.write(json.dumps(event, sort_keys=True) + ",\n")
            self._fh.flush()

    def _ts(self, perf_t: float) -> float:
        return max(0.0, (perf_t - self._t0) * 1e6)

    def complete(self, name: str, start: float, duration: float,
                 cat: str = "stage", args: Optional[dict] = None) -> None:
        """A finished span: `start` is its time.perf_counter() value."""
        ev = {"ph": "X", "name": name, "cat": cat, "pid": self._pid,
              "tid": threading.get_ident() & 0xFFFFFFFF,
              "ts": round(self._ts(start), 3),
              "dur": round(duration * 1e6, 3)}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name: str, cat: str = "event",
                args: Optional[dict] = None) -> None:
        ev = {"ph": "i", "s": "p", "name": name, "cat": cat,
              "pid": self._pid,
              "tid": threading.get_ident() & 0xFFFFFFFF,
              "ts": round(self._ts(time.perf_counter()), 3)}
        if args:
            ev["args"] = args
        self._emit(ev)

    def flow(self, ph: str, name: str, flow_id: int,
             cat: str = "flow") -> None:
        """A Chrome flow event: ``ph`` is "s" (start), "t" (step) or
        "f" (finish). Events sharing (cat, id, name) are drawn as one
        arrow chain across threads — the producer emits "s" when an
        item enters a boundary queue, the consumer emits "f" when it
        dequeues it, and the viewer links the two slices even though
        they ran on different stage-token-adopting threads."""
        ev = {"ph": ph, "name": name, "cat": cat, "id": int(flow_id),
              "pid": self._pid,
              "tid": threading.get_ident() & 0xFFFFFFFF,
              "ts": round(self._ts(time.perf_counter()), 3)}
        if ph == "f":
            # bind to the enclosing slice's END, so the arrow lands on
            # the consuming span rather than the next unrelated one
            ev["bp"] = "e"
        self._emit(ev)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # terminate the array so the file is also plain valid JSON
            self._fh.write("{}\n]\n")
            self._fh.close()


# The active recorder, None when --trace-events was not given. The
# emit_* helpers below are the no-op-when-inactive forms every hot
# call site uses (utils/timing.py, obs/events.py).
RECORDER: Optional[TraceRecorder] = None

_JAX_HOOKS = {"installed": False}


def start(path: str) -> TraceRecorder:
    """Open the trace file and route all emission to it."""
    global RECORDER
    if RECORDER is not None:
        RECORDER.close()
    RECORDER = TraceRecorder(path)
    _install_jax_hooks()
    logger.info("Writing Chrome-trace events to %s (load in Perfetto)",
                path)
    return RECORDER


def stop() -> None:
    global RECORDER
    if RECORDER is not None:
        RECORDER.close()
        RECORDER = None


def active() -> bool:
    return RECORDER is not None


def emit_complete(name: str, start_t: float, duration: float,
                  cat: str = "stage",
                  args: Optional[dict] = None) -> None:
    rec = RECORDER
    if rec is not None:
        rec.complete(name, start_t, duration, cat=cat, args=args)


def emit_instant(name: str, cat: str = "event",
                 args: Optional[dict] = None) -> None:
    rec = RECORDER
    if rec is not None:
        rec.instant(name, cat=cat, args=args)


def emit_flow(ph: str, name: str, flow_id: int,
              cat: str = "flow") -> None:
    rec = RECORDER
    if rec is not None:
        rec.flow(ph, name, flow_id, cat=cat)


def _install_jax_hooks() -> None:
    """Forward jax.monitoring events into the trace, once per process.

    The listener registry has no public unregister, so the listeners
    stay installed and write to whatever recorder is active — a later
    `start()` keeps receiving compile events without re-registering.
    Durations arrive as (event, seconds): jax reports them at
    completion, so the span is drawn ending "now".
    """
    if _JAX_HOOKS["installed"]:
        return
    try:
        from jax import monitoring
    except Exception:  # jax absent/too old: trace still works
        return

    def _on_duration(event: str, duration: float, **kw) -> None:
        try:
            emit_complete(event, time.perf_counter() - float(duration),
                          float(duration), cat="jax")
        except Exception:  # telemetry must never take down a dispatch
            logger.debug("jax duration listener failed", exc_info=True)

    def _on_event(event: str, **kw) -> None:
        try:
            emit_instant(event, cat="jax")
        except Exception:
            logger.debug("jax event listener failed", exc_info=True)

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _JAX_HOOKS["installed"] = True
    except Exception:
        logger.debug("jax.monitoring hook install failed", exc_info=True)
