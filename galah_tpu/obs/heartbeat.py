"""Periodic liveness heartbeat: ``heartbeat.jsonl`` beside the report.

A daemon thread samples the telemetry registries every
``GALAH_OBS_HEARTBEAT_S`` seconds (default 0 = off) and durably
appends one crc-framed record (io/atomic.append_jsonl — the same
torn-tail-tolerant framing as checkpoints) per beat:

    {"beat": n, "ts": ..., "uptime_s": ..., "occupancy": {stage: v},
     "gauges": {...}, "counters": {...}, "queue_depths": {stage: n},
     "flow_items": {stage: n}}

This is the liveness primitive the preemptible-fleet supervisor and
the index service watch: a run whose heartbeat file stops advancing
is wedged, one whose occupancy collapses is starving, and a SIGKILL
mid-write costs exactly one torn record (skipped on read). The
in-process side keeps bounded per-stage occupancy accumulators
(min/sum/count/last) so the run report can render an occupancy
**time-series** summary instead of only the quiesce-time value.

``galah-tpu top <dir>`` renders the newest beat; the CLI starts the
thread next to the run-report sink and obs.finalize() (plus the
crash/preemption hooks — obs.install_crash_hooks) stops it with a
final beat so interrupted runs still carry a last snapshot.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

HEARTBEAT_FILENAME = "heartbeat.jsonl"

_SHARD_DIR_RE = re.compile(r"shard_(\d+)$")


def _infer_role_shard(directory: str) -> tuple:
    """(role, shard) stamps for beats written into ``directory``.

    A fleet worker subprocess carries the scheduler's
    GALAH_TPU_FLEET_WORKER env stamp and writes its heartbeat inside
    ``shards/shard_NNN/`` — both are recoverable here without any new
    plumbing. Single-process runs get (None, None): beats stay
    unstamped, and old logs read clean."""
    role = ("worker" if os.environ.get("GALAH_TPU_FLEET_WORKER")
            else None)
    shard = None
    m = _SHARD_DIR_RE.search(os.path.abspath(directory or "."))
    if m:
        shard = int(m.group(1))
    return role, shard


def _rss_mb() -> Optional[float]:
    """Resident set size in MB from /proc/self/status (stdlib-only;
    None on platforms without procfs)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except (OSError, ValueError, IndexError):
        pass
    return None

# Concurrency contract (GL8xx lint + GalahSan runtime). The module
# global GLOBAL is unguarded by the same lifecycle argument as
# trace.RECORDER: start()/stop() run in the single-threaded CLI
# lifecycle; the beat thread only ever touches its own instance.
GUARDED_BY = {
    "Heartbeat._beats": "Heartbeat._lock",
    "Heartbeat._occ": "Heartbeat._lock",
    "Heartbeat._rss": "Heartbeat._lock",
    "Heartbeat._final_done": "Heartbeat._lock",
}
LOCK_ORDER = ["Heartbeat._lock"]


class Heartbeat:
    """One run's heartbeat writer thread."""

    def __init__(self, directory: str, period_s: float,
                 role: Optional[str] = None) -> None:
        os.makedirs(directory or ".", exist_ok=True)
        self.path = os.path.join(directory or ".", HEARTBEAT_FILENAME)
        self.period_s = max(0.05, float(period_s))
        # role/shard stamps (set once here, read-only afterwards):
        # explicit role wins (the fleet scheduler passes "scheduler");
        # otherwise inferred from the worker env stamp + shard dir
        inferred_role, self.shard = _infer_role_shard(directory)
        self.role = role or inferred_role
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._t0 = time.monotonic()
        self._beats = 0
        # stage -> [min, sum, count, last] occupancy accumulator
        self._occ: Dict[str, list] = {}
        # [min, sum, count, peak] rss_mb accumulator — peak RSS is the
        # out-of-core tier's acceptance metric (docs/memory.md), so
        # the run report summarizes the whole beat series, not just
        # the final sample
        self._rss: Optional[list] = None
        self._final_done = False
        # sampler thread: only READS the registries (each behind its
        # own lock); it never emits stage telemetry, so there is no
        # stage context to adopt.
        # galah-lint: ignore[GL804] sampler thread emits no telemetry
        self._thread = threading.Thread(
            target=self._run, name="galah-heartbeat", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.period_s):
            try:
                self.beat()
            except Exception:  # telemetry never takes down the run
                logger.debug("heartbeat beat failed", exc_info=True)

    def _gather(self) -> dict:
        """Sample the registries — OUTSIDE self._lock (metrics/flow
        take their own locks; GalahSan lock-order discipline)."""
        from galah_tpu.obs import flow as obs_flow
        from galah_tpu.obs import metrics as obs_metrics
        from galah_tpu.obs.report import _OCC_RE

        gauges: Dict[str, float] = {}
        counters: Dict[str, float] = {}
        occupancy: Dict[str, float] = {}
        for name, m in obs_metrics.snapshot().items():
            kind = m.get("kind")
            if kind == "counter":
                counters[name] = m.get("value")
            elif kind == "gauge":
                v = m.get("value")
                if isinstance(v, (int, float)):
                    gauges[name] = v
                    match = _OCC_RE.match(name)
                    if match:
                        occupancy[match.group(1) or "pipeline"] = v
        fsnap = obs_flow.snapshot()
        flow_items = {s: st.get("items", 0)
                      for s, st in (fsnap.get("stages") or {}).items()}
        rec = {
            "ts": time.time(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "occupancy": occupancy,
            "gauges": gauges,
            "counters": counters,
            "queue_depths": obs_flow.queue_depths(),
            "flow_items": flow_items,
        }
        if self.role is not None:
            rec["role"] = self.role
        if self.shard is not None:
            rec["shard"] = self.shard
        rss = _rss_mb()
        if rss is not None:
            rec["rss_mb"] = rss
        return rec

    def beat(self) -> None:
        """Sample + durably append one record (also the final-flush
        entry point: crash hooks call this directly)."""
        from galah_tpu.io import atomic

        rec = self._gather()
        with self._lock:
            self._beats += 1
            rec["beat"] = self._beats
            for stage, v in rec["occupancy"].items():
                acc = self._occ.get(stage)
                if acc is None:
                    self._occ[stage] = [v, v, 1, v]
                else:
                    acc[0] = min(acc[0], v)
                    acc[1] += v
                    acc[2] += 1
                    acc[3] = v
            rss = rec.get("rss_mb")
            if isinstance(rss, (int, float)):
                if self._rss is None:
                    self._rss = [rss, rss, 1, rss]
                else:
                    self._rss[0] = min(self._rss[0], rss)
                    self._rss[1] += rss
                    self._rss[2] += 1
                    self._rss[3] = max(self._rss[3], rss)
        atomic.append_jsonl(self.path, rec,
                            site="io.atomic.append[heartbeat]")
        # OpenMetrics textfile tick rides the beat cadence: one
        # atomically-swapped .prom per beat when the flag is set
        try:
            from galah_tpu.obs import openmetrics as obs_openmetrics

            obs_openmetrics.maybe_export()
        except Exception:  # telemetry never takes down the run
            logger.debug("openmetrics export failed", exc_info=True)

    def stop(self, flush: bool = True, join_timeout: float = 5.0) -> None:
        """Stop the thread; with ``flush`` write one final beat (once,
        however many of finalize/atexit/excepthook call us)."""
        self._stop_evt.set()
        if (self._thread.is_alive()
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=join_timeout)
        if not flush:
            return
        with self._lock:
            if self._final_done:
                return
            self._final_done = True
        try:
            self.beat()
        except Exception:
            logger.debug("final heartbeat failed", exc_info=True)

    def snapshot(self) -> dict:
        """Bounded summary for the run report's flow section."""
        with self._lock:
            series = {
                stage: {"min": round(acc[0], 4),
                        "mean": round(acc[1] / acc[2], 4),
                        "last": round(acc[3], 4),
                        "samples": acc[2]}
                for stage, acc in sorted(self._occ.items())
            }
            beats = self._beats
            rss = None
            if self._rss is not None:
                rss = {"min_mb": round(self._rss[0], 1),
                       "mean_mb": round(self._rss[1] / self._rss[2], 1),
                       "peak_mb": round(self._rss[3], 1),
                       "samples": self._rss[2]}
        out = {"period_s": self.period_s, "beats": beats,
               "path": self.path, "occupancy_series": series}
        if rss is not None:
            out["rss_series"] = rss
        return out


# The active heartbeat, None when GALAH_OBS_HEARTBEAT_S is unset/0.
GLOBAL: Optional[Heartbeat] = None


def start(directory: str, period_s: float,
          role: Optional[str] = None) -> Heartbeat:
    global GLOBAL
    if GLOBAL is not None:
        GLOBAL.stop(flush=False)
    GLOBAL = Heartbeat(directory, period_s, role=role)
    GLOBAL.start()
    logger.info("Heartbeat every %.3gs -> %s (watch with "
                "`galah-tpu top %s`)", GLOBAL.period_s, GLOBAL.path,
                directory or ".")
    return GLOBAL


def maybe_start(report_path: Optional[str],
                role: Optional[str] = None) -> Optional[Heartbeat]:
    """CLI lifecycle hook: start next to the run-report sink when
    GALAH_OBS_HEARTBEAT_S > 0 (the flag's default keeps it off)."""
    try:
        from galah_tpu.config import env_value
        period = float(env_value("GALAH_OBS_HEARTBEAT_S") or 0.0)
    except (TypeError, ValueError):
        logger.warning("GALAH_OBS_HEARTBEAT_S is not a number; "
                       "heartbeat disabled")
        return None
    if period <= 0:
        return None
    directory = os.path.dirname(report_path) if report_path else "."
    return start(directory or ".", period, role=role)


def stop(flush: bool = True) -> None:
    hb = GLOBAL
    if hb is not None:
        hb.stop(flush=flush)


def flush() -> None:
    """One immediate beat (signal-path flush: no join, no teardown)."""
    hb = GLOBAL
    if hb is not None:
        try:
            hb.beat()
        except Exception:
            logger.debug("heartbeat flush failed", exc_info=True)


def active() -> bool:
    return GLOBAL is not None


def snapshot() -> Optional[dict]:
    hb = GLOBAL
    return None if hb is None else hb.snapshot()


def reset() -> None:
    """Drop the active heartbeat without a final beat (tests/run
    start); the thread is stopped first."""
    global GLOBAL
    if GLOBAL is not None:
        GLOBAL.stop(flush=False)
    GLOBAL = None


def load(directory: str):
    """(records, torn_count) of a run dir's heartbeat.jsonl — the
    torn-tail-tolerant read `galah-tpu top` renders from."""
    from galah_tpu.io import atomic
    path = directory
    if os.path.isdir(directory):
        path = os.path.join(directory, HEARTBEAT_FILENAME)
    return atomic.read_jsonl(path)


def read_latest_beat(path: str) -> Optional[dict]:
    """Newest beat record of a run dir's (or file's) heartbeat.jsonl,
    or None — tolerates missing files and torn tails, never raises.
    The fleet scheduler's liveness probe reads through this."""
    try:
        records, _torn = load(path)
    except Exception:
        logger.debug("heartbeat read failed: %s", path, exc_info=True)
        return None
    return records[-1] if records else None


def render_latest(directory: str) -> str:
    """Human rendering of the newest beat (the `galah-tpu top` body)."""
    path = (os.path.join(directory, HEARTBEAT_FILENAME)
            if os.path.isdir(directory) else directory)
    records, torn = load(directory)
    if not records:
        return (f"no heartbeat at {path} (run with "
                "GALAH_OBS_HEARTBEAT_S=<seconds>)\n")
    rec = records[-1]
    age = max(0.0, time.time() - float(rec.get("ts") or 0.0))
    who = ""
    if rec.get("role") is not None:
        who = f"  role {rec['role']}"
        if rec.get("shard") is not None:
            who += f" (shard {rec['shard']})"
    rss = (f"  rss {rec['rss_mb']:.0f}MB"
           if isinstance(rec.get("rss_mb"), (int, float)) else "")
    lines = [f"heartbeat {path}",
             f"  beat {rec.get('beat')}  age {age:.1f}s  uptime "
             f"{rec.get('uptime_s')}s{who}{rss}  ({len(records)} beat(s)"
             + (f", {torn} torn" if torn else "") + ")"]
    occ = rec.get("occupancy") or {}
    if occ:
        lines.append("  occupancy:")
        for stage in sorted(occ):
            v = occ[stage]
            bar = "#" * int(round(max(0.0, min(1.0, v)) * 20))
            lines.append(f"    {stage:<10} {v:5.2f} {bar}")
    depths = rec.get("queue_depths") or {}
    if depths:
        lines.append("  queue depths: " + "  ".join(
            f"{s}={n}" for s, n in sorted(depths.items())))
    items = rec.get("flow_items") or {}
    if items:
        lines.append("  flow items:   " + "  ".join(
            f"{s}={n}" for s, n in sorted(items.items())))
    return "\n".join(lines) + "\n"
