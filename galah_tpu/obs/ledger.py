"""Cross-run performance ledger: append-only JSONL with regression gating.

A run_report.json is a one-shot artifact; the only cross-run record
before this module was loose BENCH_r*.json files compared by hand. The
ledger makes perf drift a *gate*: every finalized run (and every bench
capture) appends one JSON line, and ``galah-tpu perf check`` compares
the newest entry against a median±MAD noise band over the last M
entries of the same key, exiting nonzero on regression.

Entry layout (one JSON object per line)::

    {"v": 1, "ts": 1754..., "sha": "9feb21d",
     "key": {"backend": "tpu", "device_kind": "TPU v4",
             "n_devices": 8,
             "workload": {"n": 4096, "k": 1000, "p": 8},
             "strategy": "auto", "source": "bench"},
     "metrics": {"run.duration_s": 512.3,
                 "bench.e2e_1000_genomes_per_sec": 71.2, ...}}

The KEY deliberately excludes the git sha: the whole point is comparing
the same (backend, topology, workload, strategy) configuration *across*
commits — the sha is recorded per entry so ``perf history`` can name
the commit that moved a metric. This is exactly the measurement
substrate the ROADMAP autotuning item needs: measured strategy walls
keyed by device topology and N/K/P.

Torn-tail tolerance: a run killed mid-append leaves a truncated last
line; ``read()`` skips unparseable lines (counting them) instead of
failing, and ``append()`` always writes complete single lines, so one
crash never poisons the history. Same discipline as the greedy-rounds
checkpoint (cluster/engine.py).

Import discipline: no jax, no heavy imports — the ``perf`` subcommand
runs on hosts with no usable accelerator (like ``report``).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

LEDGER_VERSION = 1

#: Defaults for the check window and noise band (flag-overridable:
#: GALAH_OBS_LEDGER_WINDOW / GALAH_OBS_LEDGER_MAD_K).
DEFAULT_WINDOW = 8
DEFAULT_MAD_K = 4.0
#: Entries needed before check() will issue a verdict at all.
MIN_HISTORY = 3

#: Absolute noise floor for seconds-scale metrics: a wall below this
#: spread is host-scheduler jitter, not a perf signal. A 0.5 ms
#: dispatch wall that triples is still meaningless; a 10 s stage that
#: doubles is not — the floor only widens bands that were narrower
#: than one scheduling quantum anyway.
SECONDS_NOISE_FLOOR = 0.05

#: Substrings that classify a metric's good direction. Checked against
#: the metric name; first family that matches wins.
_HIGHER_BETTER = ("per_sec", "per_s", "_rate", "speedup",
                  "utilization", "hit_rate")
_LOWER_BETTER = ("_s", "duration", "seconds", "wall", "_bytes",
                 "bytes_", "errors")
#: Exact-name directions checked before the substring families. The
#: host-blame share is the megakernel's headline gauge: host
#: orchestration migrating back above its ledger median is a
#: regression even though "share" matches no substring family.
_DIRECTION_OVERRIDES = {
    "flow.host.share": "lower",
    "flow.host.blame_s": "lower",
    "bench.megakernel_host_share": "lower",
}


def git_sha() -> Optional[str]:
    """Short HEAD sha of the checkout this process runs from, or None
    outside a git tree (the ledger records it, never requires it)."""
    try:
        here = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        proc = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=here,
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return None
    sha = proc.stdout.strip()
    return sha if proc.returncode == 0 and sha else None


def metric_direction(name: str) -> str:
    """'higher' / 'lower' / 'neutral' — which way is good for `name`.

    Inferred from naming conventions (rates up, walls and byte counts
    down); unknown metrics are 'neutral' and can drift but never gate.
    A few metrics carry an exact-name direction (see
    _DIRECTION_OVERRIDES) where the convention families would miss or
    misread them."""
    low = name.lower()
    override = _DIRECTION_OVERRIDES.get(low)
    if override is not None:
        return override
    if any(tok in low for tok in _HIGHER_BETTER):
        return "higher"
    if any(low.endswith(tok) or tok in low for tok in _LOWER_BETTER):
        return "lower"
    return "neutral"


def _is_seconds_metric(name: str) -> bool:
    low = name.lower()
    return (low.endswith("_s") or "duration" in low or "wall" in low
            or "seconds" in low)


def key_of(entry: Dict[str, Any]) -> str:
    """Canonical string identity of an entry's comparison key."""
    return json.dumps(entry.get("key", {}), sort_keys=True)


# ---------------------------------------------------------------------------
# File format
# ---------------------------------------------------------------------------


def append(path: str, entry: Dict[str, Any]) -> None:
    """Durably append one checksum-framed line (creating parent dirs).

    io/atomic.py owns the write discipline (O_APPEND single write +
    fsync + crc framing); this module keeps only the schema. atomic is
    as jax-free as this module, so the import discipline holds."""
    from galah_tpu.io import atomic

    atomic.append_jsonl(path, entry, site="io.atomic.append[ledger]")


def read(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """All parseable entries in file order, plus the count of skipped
    (torn/corrupt) lines. A missing file is an empty ledger. Framed
    (crc-checked) and legacy plain lines both parse."""
    from galah_tpu.io import atomic

    records, skipped = atomic.read_jsonl(path)
    entries: List[Dict[str, Any]] = []
    for obj in records:
        if isinstance(obj, dict) and isinstance(
                obj.get("metrics"), dict):
            entries.append(obj)
        else:
            skipped += 1
    return entries, skipped


# ---------------------------------------------------------------------------
# Building entries from run reports
# ---------------------------------------------------------------------------


def _flag_value(report: dict, name: str) -> Optional[str]:
    return (report.get("flags", {}).get(name) or {}).get("value")


def _gauge_value(report: dict, name: str) -> Optional[float]:
    m = report.get("metrics", {}).get(name) or {}
    v = m.get("value")
    return float(v) if isinstance(v, (int, float)) else None


def _int_or_none(v) -> Optional[int]:
    try:
        return int(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def workload_fingerprint(report: dict) -> Dict[str, Optional[int]]:
    """N/K/P from the report: the workload gauges the engine/bench set
    (workload.n_genomes, workload.sketch_k) and the pairlist block
    flag. Nulls where a run did not say — two runs only share a key
    when they agree on all three."""
    return {
        "n": _int_or_none(_gauge_value(report, "workload.n_genomes")),
        "k": _int_or_none(_gauge_value(report, "workload.sketch_k")),
        "p": _int_or_none(_flag_value(report,
                                      "GALAH_TPU_PAIRLIST_BLOCK")),
    }


def strategy_fingerprint(report: dict) -> str:
    """The pinned-strategy tuple (pairlist/fragment/greedy/sketch/
    overlap/mesh-shape), 'auto' where unpinned — a pinned run must not
    share a noise band with an AUTO run (and a 2x4-mesh run must not
    share one with a 1-D run)."""
    parts = []
    for flag in ("GALAH_TPU_PAIRLIST_STRATEGY",
                 "GALAH_TPU_FRAGMENT_STRATEGY",
                 "GALAH_TPU_GREEDY_STRATEGY",
                 "GALAH_TPU_SKETCH_STRATEGY",
                 "GALAH_TPU_OVERLAP",
                 "GALAH_TPU_MESH_SHAPE"):
        parts.append(_flag_value(report, flag) or "auto")
    return "/".join(parts)


def _stage_metrics(tree: List[dict], prefix: str,
                   out: Dict[str, float], depth: int = 0) -> None:
    # Top two stage levels only: deeper nodes are per-batch noise.
    for node in tree or []:
        name = f"{prefix}{node.get('name')}"
        v = node.get("total_s")
        if isinstance(v, (int, float)):
            out[f"stage.{name}_s"] = float(v)
        if depth == 0:
            _stage_metrics(node.get("children"), name + "/", out,
                           depth + 1)


def metrics_of_report(report: dict) -> Dict[str, float]:
    """The ledger-worthy scalars of one run report: run duration, the
    stage walls (two levels), dispatch totals, bench gauges, and the
    profiler's per-entry walls/compile seconds."""
    out: Dict[str, float] = {}
    dur = report.get("run", {}).get("duration_s")
    if isinstance(dur, (int, float)):
        out["run.duration_s"] = float(dur)
    _stage_metrics(report.get("stages", {}).get("tree", []), "", out)
    disp = report.get("dispatch", {})
    for key in ("total_dispatches", "total_syncs"):
        v = disp.get(key)
        if isinstance(v, (int, float)):
            out[f"dispatch.{key}"] = float(v)
    for name, m in (report.get("metrics", {}) or {}).items():
        if not name.startswith("bench."):
            continue
        v = m.get("value")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[name] = float(v)
    dc = report.get("device_costs") or {}
    for name, e in (dc.get("entries") or {}).items():
        for field in ("dispatch_wall_s", "compile_wall_s"):
            v = e.get(field)
            if isinstance(v, (int, float)) and v:
                out[f"profile.{name}.{field}"] = float(v)
    hbm = (dc.get("hbm") or {}).get("peak_bytes")
    if isinstance(hbm, (int, float)):
        out["profile.hbm_peak_bytes"] = float(hbm)
    cp = (report.get("flow") or {}).get("critical_path") or {}
    for name, blame in (cp.get("stages") or {}).items():
        for field in ("blame_s", "share"):
            v = blame.get(field)
            if isinstance(v, (int, float)):
                out[f"flow.{name}.{field}"] = float(v)
    for field in ("blame_s", "share"):
        v = (cp.get("host") or {}).get(field)
        if isinstance(v, (int, float)):
            out[f"flow.host.{field}"] = float(v)
    return out


def entry_from_report(report: dict, source: str,
                      ts: Optional[float] = None,
                      sha: Optional[str] = None,
                      shard: Optional[int] = None) -> Dict[str, Any]:
    """One ledger entry from an assembled run report dict.

    ``shard`` is the fleet-worker shard id: a worker subprocess runs
    the same (backend, workload, strategy) configuration as a whole
    single-process run but over a SUBSET of the genomes, so without a
    shard key member its wall would land in — and poison — the e2e
    noise band that ``perf check`` gates on. Shard entries get their
    own key (and so their own band); non-fleet entries keep the exact
    pre-shard key shape, so existing histories keep matching."""
    dev = report.get("device", {}) or {}
    kinds = {d.get("device_kind") for d in dev.get("devices") or []}
    key: Dict[str, Any] = {
        "backend": dev.get("backend"),
        "device_kind": (sorted(kinds)[0] if kinds else None),
        "n_devices": dev.get("device_count"),
        "workload": workload_fingerprint(report),
        "strategy": strategy_fingerprint(report),
        "source": source,
    }
    if shard is not None:
        key["shard"] = int(shard)
    return {
        "v": LEDGER_VERSION,
        "ts": float(ts if ts is not None else time.time()),
        "sha": sha if sha is not None else git_sha(),
        "key": key,
        "metrics": metrics_of_report(report),
    }


def record_report(path: str, report: dict, source: str,
                  shard: Optional[int] = None) -> bool:
    """Append `report` to the ledger at `path`; False (and a log line)
    on failure — feeding the ledger must never fail the run."""
    try:
        append(path, entry_from_report(report, source, shard=shard))
        return True
    except Exception:
        logger.warning("perf ledger append failed", exc_info=True)
        return False


# ---------------------------------------------------------------------------
# History + regression check
# ---------------------------------------------------------------------------


def history(entries: List[dict], metric: str,
            key: Optional[str] = None) -> List[dict]:
    """File-order rows {ts, sha, key, value} of `metric`, optionally
    restricted to entries whose canonical key equals `key`."""
    rows = []
    for e in entries:
        if key is not None and key_of(e) != key:
            continue
        v = e.get("metrics", {}).get(metric)
        if isinstance(v, (int, float)):
            rows.append({"ts": e.get("ts"), "sha": e.get("sha"),
                         "key": key_of(e), "value": float(v)})
    return rows


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def check(entries: List[dict], current: dict,
          window: int = DEFAULT_WINDOW,
          mad_k: float = DEFAULT_MAD_K,
          min_history: int = MIN_HISTORY) -> List[dict]:
    """Verdicts for every metric of `current` against the last `window`
    same-key entries of `entries` (which must NOT contain `current`).

    Per metric: {"metric", "value", "n_history", "median", "mad",
    "band": [lo, hi], "direction", "verdict"} with verdict one of
    ok / regression / improvement / drift / insufficient-history.
    The band is median ± mad_k * MAD, with the MAD floored at 1% of
    |median| (an all-identical history would otherwise declare any
    epsilon a regression) and, for seconds-scale metrics, at
    SECONDS_NOISE_FLOOR absolute (sub-millisecond walls triple on
    scheduler jitter alone) — only a move outside the band in the bad
    direction is a regression; 'drift' marks neutral-direction metrics
    outside the band and never gates."""
    key = key_of(current)
    same = [e for e in entries if key_of(e) == key]
    tail = same[-window:]
    verdicts = []
    for metric, value in sorted(current.get("metrics", {}).items()):
        if not isinstance(value, (int, float)):
            continue
        hist = [e["metrics"][metric] for e in tail
                if isinstance(e.get("metrics", {}).get(metric),
                              (int, float))]
        direction = metric_direction(metric)
        v: Dict[str, Any] = {"metric": metric, "value": float(value),
                             "n_history": len(hist),
                             "direction": direction}
        if len(hist) < min_history:
            v.update(verdict="insufficient-history", median=None,
                     mad=None, band=None)
            verdicts.append(v)
            continue
        med = _median(hist)
        mad = _median([abs(x - med) for x in hist])
        spread = max(mad_k * mad, 0.01 * abs(med), 1e-12)
        if _is_seconds_metric(metric):
            spread = max(spread, SECONDS_NOISE_FLOOR)
        lo, hi = med - spread, med + spread
        if lo <= value <= hi:
            verdict = "ok"
        elif direction == "neutral":
            verdict = "drift"
        elif (value < lo) == (direction == "higher"):
            verdict = "regression"
        else:
            verdict = "improvement"
        v.update(verdict=verdict, median=med, mad=mad, band=[lo, hi])
        verdicts.append(v)
    return verdicts


def regressions(verdicts: List[dict]) -> List[dict]:
    return [v for v in verdicts if v["verdict"] == "regression"]
