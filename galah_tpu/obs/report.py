"""run_report.json: assemble, validate, render, and diff run reports.

One schema-valid JSON artifact per run (``--run-report PATH`` /
``GALAH_OBS_REPORT``) carrying everything the hardware windows need to
diff and attribute: the config-flag snapshot (config.FLAGS registry),
device topology, the stage wall-clock tree, dispatch/sync round-trip
counts per stage, the precluster funnel (possible -> screened -> kept
-> ANI-computed pairs, cache hit rate), every resilience event
(retries, CPU demotions, quarantined genomes), and the full typed
metrics snapshot. The committed JSON Schema
(``run_report.schema.json``) is the contract; ``galah-tpu report``
renders and diffs these artifacts.
"""

from __future__ import annotations

import json
import logging
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

SCHEMA_PATH = os.path.join(os.path.dirname(__file__),
                           "run_report.schema.json")
# v8: lint.timings_s — per-checker-family wall seconds (additive)
REPORT_VERSION = 10  # v10: memory (rss min/mean/peak + pagestore)

# disp[<stage>] / sync[<stage>] — the StageTimer's dispatch counters
_DISP_RE = re.compile(r"^(disp|sync)\[(.*)\]$")
_RETRY_RE = re.compile(r"^retries\[(.*)\]$")
# workload.pipeline_occupancy[<stage>] gauges (obs/metrics.py); the
# unlabelled gauge is the whole-pipeline value
_OCC_RE = re.compile(r"^workload\.pipeline_occupancy(?:\[(.*)\])?$")


def flag_snapshot() -> Dict[str, dict]:
    """Every registered GALAH_* flag: effective value, default, and
    whether the environment set it (the PR-3 registry is the source)."""
    from galah_tpu.config import FLAGS, env_value

    snap = {}
    for name, flag in sorted(FLAGS.items()):
        raw = os.environ.get(name)
        snap[name] = {
            "value": env_value(name),
            "default": flag.default,
            "set": raw not in (None, ""),
            "section": flag.section,
        }
    return snap


def device_topology() -> dict:
    """Backend/device/process layout, null-filled when jax is not up.

    Deliberately import-only-if-loaded: assembling a report must never
    be the thing that first initializes a (possibly wedged) backend.
    """
    topo: dict = {"backend": None, "device_count": None,
                  "process_index": None, "process_count": None,
                  "jax_version": None, "devices": []}
    jax = sys.modules.get("jax")
    if jax is None:
        return topo
    try:
        topo["jax_version"] = getattr(jax, "__version__", None)
        topo["backend"] = jax.default_backend()
        topo["device_count"] = int(jax.device_count())
        topo["process_index"] = int(jax.process_index())
        topo["process_count"] = int(jax.process_count())
        topo["devices"] = [
            {"id": int(d.id), "platform": str(d.platform),
             "device_kind": str(d.device_kind)}
            for d in jax.devices()]
    except Exception as exc:  # report assembly must never kill the run
        logger.debug("device topology unavailable: %s", exc)
    return topo


def _split_dispatch_counters(
        counters: Dict[str, int]
) -> Tuple[Dict[str, int], Dict[str, int]]:
    disp: Dict[str, int] = {}
    sync: Dict[str, int] = {}
    for name, value in counters.items():
        m = _DISP_RE.match(name)
        if not m:
            continue
        (disp if m.group(1) == "disp" else sync)[m.group(2)] = value
    return disp, sync


def assemble(subcommand: str,
             argv: Optional[List[str]] = None,
             started_at: Optional[float] = None,
             lint: Optional[dict] = None) -> dict:
    """The full report dict from the process-wide telemetry state
    (timing.GLOBAL, obs.metrics, obs.events, the dispatch supervisor,
    the quarantine counter). `lint` is the static-analysis summary
    (core.lint_summary) attached by the lint subcommand only."""
    import galah_tpu
    from galah_tpu.obs import events as obs_events
    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.resilience import dispatch as rdispatch
    from galah_tpu.utils import timing

    timer = timing.GLOBAL
    counters = timer.counters()
    disp, sync = _split_dispatch_counters(counters)
    retries = {}
    for name, value in counters.items():
        m = _RETRY_RE.match(name)
        if m:
            retries[m.group(1)] = value

    metrics = obs_metrics.snapshot()

    def _metric_value(name: str, default=0):
        m = metrics.get(name)
        return m.get("value", default) if m else default

    hits = int(_metric_value("cache.hits") or 0)
    misses = int(_metric_value("cache.misses") or 0)
    finished = time.time()
    report = {
        "version": REPORT_VERSION,
        "kind": "galah-tpu-run-report",
        "run": {
            "subcommand": subcommand,
            "argv": list(argv) if argv is not None else list(sys.argv),
            "started_at": started_at,
            "finished_at": finished,
            "duration_s": (finished - started_at
                           if started_at is not None
                           else timer.elapsed()),
            "galah_tpu_version": galah_tpu.__version__,
        },
        "flags": flag_snapshot(),
        "device": device_topology(),
        "stages": {"total_s": timer.elapsed(), "tree": timer.tree()},
        "dispatch": {
            "dispatches": disp,
            "syncs": sync,
            "total_dispatches": sum(disp.values()),
            "total_syncs": sum(sync.values()),
        },
        "funnel": {
            "possible_pairs": counters.get("screen-possible-pairs", 0),
            "screened_candidates": counters.get("screen-candidates", 0),
            "kept_pairs": counters.get("screen-kept-pairs", 0),
            "exact_ani_computed": counters.get("exact-ani-computed", 0),
            "exact_ani_wasted": counters.get("exact-ani-wasted", 0),
            "cache": {
                "hits": hits,
                "misses": misses,
                "hit_rate": (hits / (hits + misses)
                             if hits + misses else None),
            },
        },
        "resilience": {
            "retries": retries,
            "demotions": [{"site": d.site, "reason": d.reason}
                          for d in rdispatch.demotions()],
            "quarantined_genomes": counters.get(
                "quarantined-genomes", 0),
        },
        "counters": counters,
        "metrics": metrics,
        "events": obs_events.snapshot(),
    }
    try:
        from galah_tpu.resilience import interrupt

        report["preemption"] = interrupt.snapshot()
    except Exception:  # additive section; never lose a report
        logger.debug("preemption snapshot failed", exc_info=True)
    try:
        from galah_tpu.obs import profile as obs_profile

        report["device_costs"] = obs_profile.snapshot()
    except Exception:  # device costs are additive; never lose a report
        logger.debug("device-cost snapshot failed", exc_info=True)
    try:
        from galah_tpu.analysis import sanitizer as galah_san

        san_summary = galah_san.summary_if_enabled()
        if san_summary is not None:
            report["sanitizer"] = san_summary
    except Exception:  # additive section (v4); never lose a report
        logger.debug("sanitizer summary failed", exc_info=True)
    try:
        from galah_tpu import index as index_pkg

        idx_snap = index_pkg.snapshot()
        if idx_snap is not None:
            report["index"] = idx_snap
    except Exception:  # additive section (v5); never lose a report
        logger.debug("index snapshot failed", exc_info=True)
    try:
        from galah_tpu import fleet as fleet_pkg

        fleet_snap = fleet_pkg.snapshot()
        if fleet_snap is not None:
            report["fleet"] = fleet_snap
            fleet_dir = fleet_snap.get("fleet_dir")
            if fleet_dir:
                from galah_tpu.obs import fleet_view

                ru = fleet_view.rollup(fleet_dir)
                if ru is not None:
                    report["fleet_rollup"] = ru
    except Exception:  # additive sections (v7/v9); never lose a report
        logger.debug("fleet snapshot failed", exc_info=True)
    try:
        from galah_tpu.obs import flow as obs_flow
        from galah_tpu.obs import heartbeat as obs_heartbeat

        flow_snap = obs_flow.snapshot()
        if flow_snap.get("stages"):
            flow_snap["critical_path"] = obs_flow.critical_path(
                flow_snap, report["run"]["duration_s"])
        hb_snap = obs_heartbeat.snapshot()
        if hb_snap is not None:
            flow_snap["heartbeat"] = hb_snap
        if flow_snap.get("stages") or hb_snap is not None:
            report["flow"] = flow_snap
    except Exception:  # additive section (v6); never lose a report
        logger.debug("flow snapshot failed", exc_info=True)
    try:
        mem = _memory_section(report)
        if mem:
            report["memory"] = mem
    except Exception:  # additive section (v10); never lose a report
        logger.debug("memory section failed", exc_info=True)
    if lint is not None:
        report["lint"] = lint
    return report


def _memory_section(report: dict) -> dict:
    """Host-memory summary (v10): the heartbeat's per-beat `rss_mb`
    series folded to min/mean/peak — peak RSS is the out-of-core
    tier's acceptance metric (docs/memory.md) — plus the pagestore's
    traffic counters when the paged sketch path ran."""
    mem: dict = {}
    rss = (((report.get("flow") or {}).get("heartbeat") or {})
           .get("rss_series"))
    if rss:
        mem["rss_mb"] = rss
    mets = report.get("metrics") or {}
    resident = (mets.get("pagestore.resident_bytes") or {}).get("value")
    if resident is not None:
        mem["pagestore"] = {
            "resident_bytes": resident,
            "page_ins": (mets.get("pagestore.page_ins") or {})
            .get("value", 0),
            "page_outs": (mets.get("pagestore.page_outs") or {})
            .get("value", 0),
        }
    skipped = (mets.get("prefilter.skipped") or {}).get("value")
    if skipped is not None:
        mem["prefilter_skipped"] = skipped
    return mem


def write(path: str, report: dict) -> None:
    from galah_tpu.io import atomic

    atomic.write_json(path, report, indent=1,
                      site="io.atomic.write[report]")
    logger.info("Wrote run report to %s", path)


def load(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def validate(report: dict) -> List[str]:
    """Schema-validation errors ([] == valid). Uses jsonschema against
    the committed schema when available; otherwise a structural check
    of the required top-level sections so report writing never gains a
    hard dependency."""
    with open(SCHEMA_PATH) as fh:
        schema = json.load(fh)
    try:
        import jsonschema
    except ImportError:
        required = schema.get("required", [])
        return [f"missing required section {k!r}" for k in required
                if k not in report]
    validator = jsonschema.Draft7Validator(schema)
    return [f"{'/'.join(str(p) for p in e.absolute_path) or '<root>'}: "
            f"{e.message}"
            for e in validator.iter_errors(report)]


# ---------------------------------------------------------------------------
# Human rendering + diffing (`galah-tpu report [--diff]`)
# ---------------------------------------------------------------------------


def _fmt_s(v: float) -> str:
    return f"{v:.2f}s"


def _render_stage_tree(tree: List[dict], indent: int = 2) -> List[str]:
    out = []
    for node in tree:
        count = f" x{node['count']}" if node.get("count", 1) > 1 else ""
        out.append(f"{' ' * indent}{node['name']}: "
                   f"{_fmt_s(node['total_s'])}{count}")
        out.extend(_render_stage_tree(node.get("children", []),
                                      indent + 2))
    return out


def _occupancy_rows(metrics: Dict[str, dict]) -> List[Tuple[str, float]]:
    """(stage, occupancy) rows from the metrics snapshot, per-stage
    gauges first, the unlabelled whole-pipeline value last."""
    rows: List[Tuple[str, float]] = []
    whole: Optional[float] = None
    for name, m in sorted(metrics.items()):
        mm = _OCC_RE.match(name)
        if not mm:
            continue
        v = m.get("value")
        if v is None:
            continue
        if mm.group(1):
            rows.append((mm.group(1), float(v)))
        else:
            whole = float(v)
    if whole is not None:
        rows.append(("pipeline", whole))
    return rows


def render(report: dict) -> str:
    """One human-readable page per report."""
    run = report.get("run", {})
    dev = report.get("device", {})
    funnel = report.get("funnel", {})
    cache = funnel.get("cache", {})
    res = report.get("resilience", {})
    disp = report.get("dispatch", {})
    lines = [
        f"galah-tpu run report (v{report.get('version')})",
        f"  subcommand: {run.get('subcommand')}   "
        f"version: {run.get('galah_tpu_version')}   "
        f"duration: {_fmt_s(run.get('duration_s', 0.0))}",
        f"  device: backend={dev.get('backend')} "
        f"devices={dev.get('device_count')} "
        f"process={dev.get('process_index')}/{dev.get('process_count')}",
        "",
        f"stages (total {_fmt_s(report.get('stages', {}).get('total_s', 0.0))}):",
    ]
    lines.extend(_render_stage_tree(
        report.get("stages", {}).get("tree", [])))
    lines += [
        "",
        f"dispatch round trips: {disp.get('total_dispatches', 0)} "
        f"dispatches, {disp.get('total_syncs', 0)} syncs",
    ]
    for stage_name in sorted(set(disp.get("dispatches", {}))
                             | set(disp.get("syncs", {}))):
        lines.append(
            f"  {stage_name}: "
            f"disp={disp.get('dispatches', {}).get(stage_name, 0)} "
            f"sync={disp.get('syncs', {}).get(stage_name, 0)}")
    hit_rate = cache.get("hit_rate")
    lines += [
        "",
        "precluster funnel:",
        f"  possible pairs:     {funnel.get('possible_pairs', 0)}",
        f"  screened candidates:{funnel.get('screened_candidates', 0):>8}",
        f"  kept pairs:         {funnel.get('kept_pairs', 0)}",
        f"  exact ANI computed: {funnel.get('exact_ani_computed', 0)} "
        f"({funnel.get('exact_ani_wasted', 0)} wasted)",
        f"  sketch cache:       {cache.get('hits', 0)} hits / "
        f"{cache.get('misses', 0)} misses"
        + (f" ({100.0 * hit_rate:.0f}% hit rate)"
           if hit_rate is not None else ""),
    ]
    mets = report.get("metrics", {})
    pruned = (mets.get("precluster.bucket_pruned_pairs") or {}) \
        .get("value")
    if pruned is not None:
        frac = (mets.get("precluster.bucket_pruned_fraction") or {}) \
            .get("value") or 0.0
        bands = (mets.get("precluster.bucket_count") or {}) \
            .get("value") or 0
        lines.append(
            f"  HLL-band prefilter: {int(pruned)} pairs pruned "
            f"({100.0 * frac:.0f}% of lattice, {int(bands)} band(s))")
    dcn = (mets.get("mesh.dcn_bytes_per_row") or {}).get("value")
    if dcn is not None:
        lines.append(
            f"  mesh DCN model:     {int(dcn)} bytes/row replicated")
    occ = _occupancy_rows(report.get("metrics", {}))
    if occ:
        lines += ["", "pipeline occupancy (busy fraction of stage "
                      "wall; 1.0 = never starved):"]
        for stage, v in occ:
            bar = "#" * int(round(max(0.0, min(1.0, v)) * 20))
            lines.append(f"  {stage:<10} {v:5.2f} {bar}")
    flow_sec = report.get("flow") or {}
    cp = flow_sec.get("critical_path") or {}
    if cp.get("stages"):
        from galah_tpu.obs import flow as obs_flow

        lines += [""] + obs_flow.render_critical_path(cp)
    hb = flow_sec.get("heartbeat") or {}
    series = hb.get("occupancy_series") or {}
    if series:
        lines += ["",
                  f"occupancy time-series ({hb.get('beats', 0)} "
                  f"heartbeat(s) every {hb.get('period_s')}s; "
                  "min/mean/last):"]
        for stage in sorted(series):
            s = series[stage]
            bar = "#" * int(round(
                max(0.0, min(1.0, s.get("mean", 0.0))) * 20))
            lines.append(
                f"  {stage:<10} {s.get('min', 0.0):.2f}/"
                f"{s.get('mean', 0.0):.2f}/{s.get('last', 0.0):.2f} "
                f"{bar}")
    mem = report.get("memory") or {}
    if mem:
        lines += ["", "memory:"]
        rss = mem.get("rss_mb") or {}
        if rss:
            lines.append(
                f"  rss: {rss.get('min_mb', 0.0):.0f}/"
                f"{rss.get('mean_mb', 0.0):.0f}/"
                f"{rss.get('peak_mb', 0.0):.0f} MB min/mean/peak "
                f"({rss.get('samples', 0)} beat(s))")
        pstore = mem.get("pagestore") or {}
        if pstore:
            lines.append(
                f"  pagestore: {int(pstore.get('resident_bytes', 0))} "
                f"bytes resident, {int(pstore.get('page_ins', 0))} "
                f"page-ins / {int(pstore.get('page_outs', 0))} "
                "page-outs")
        if mem.get("prefilter_skipped") is not None:
            lines.append(
                f"  prefilter skips: {int(mem['prefilter_skipped'])} "
                "genome(s) (bit-identical by construction)")
    lines += [
        "",
        "resilience:",
        f"  retries:    {res.get('retries', {}) or 'none'}",
        f"  demotions:  "
        f"{[d['site'] for d in res.get('demotions', [])] or 'none'}",
        f"  quarantined genomes: {res.get('quarantined_genomes', 0)}",
    ]
    events = report.get("events", [])
    if events:
        lines.append(f"  events ({len(events)}):")
        for ev in events[:20]:
            extra = {k: v for k, v in ev.items()
                     if k not in ("kind", "time")}
            lines.append(f"    {ev.get('kind')}: {extra}")
        if len(events) > 20:
            lines.append(f"    ... {len(events) - 20} more")
    dc = report.get("device_costs")
    if dc and dc.get("entries"):
        peaks = dc.get("peaks", {})
        hbm = dc.get("hbm", {})
        lines += ["", "device costs (profiled entry points):"]
        if peaks.get("device_kind"):
            pk = peaks.get("peak_flops_per_s")
            lines.append(
                f"  device kind: {peaks['device_kind']}"
                + (f" (peak {pk:.3g} FLOP/s)" if pk else ""))
        if hbm.get("peak_bytes") is not None:
            lines.append(
                f"  HBM high-water: {hbm['peak_bytes'] / 2**20:.1f} "
                f"MiB ({hbm.get('source')})")
        for name, e in sorted(dc["entries"].items()):
            flops = e.get("flops")
            byts = e.get("bytes_accessed")
            util = e.get("flops_utilization")
            parts = [f"calls={e.get('calls', 0)}",
                     f"compile={_fmt_s(e.get('compile_wall_s', 0.0))}",
                     f"dispatch={_fmt_s(e.get('dispatch_wall_s', 0.0))}"]
            if flops:
                parts.append(f"flops={flops:.3g}")
            if byts:
                parts.append(f"bytes={byts:.3g}")
            if util is not None:
                parts.append(f"mxu={100.0 * util:.2f}%")
            lines.append(f"  {name}: " + " ".join(parts))
    san = report.get("sanitizer")
    if san is not None:
        lines += [
            "",
            "concurrency sanitizer (GalahSan):",
            f"  {san.get('acquisitions', 0)} acquisitions across "
            f"{san.get('locks', 0)} locks in "
            f"{san.get('modules', 0)} modules",
            f"  edges: {san.get('edges_observed', 0)} observed / "
            f"{san.get('edges_declared', 0)} declared "
            f"({san.get('unexercised', 0)} declared-but-unexercised)",
            f"  violations: "
            f"{san.get('undeclared_acquisitions', 0)} undeclared, "
            f"{san.get('undeclared_edges', 0)} unordered, "
            f"{san.get('inversions', 0)} inversions, "
            f"{san.get('races', 0)} races",
        ]
    idx = report.get("index")
    if idx is not None:
        lines += [
            "",
            "sketch index:",
            f"  op: {idx.get('op')}   "
            f"generation: {idx.get('generation')}",
            f"  {idx.get('genomes', 0)} genome(s) in "
            f"{idx.get('clusters', 0)} cluster(s), "
            f"{idx.get('pairs', 0)} pair(s), "
            f"{idx.get('tombstones', 0)} tombstone(s)",
        ]
    fleet = report.get("fleet")
    if fleet is not None:
        lines += [
            "",
            "fleet:",
            f"  {fleet.get('n_shards', 0)} shard(s) over "
            f"{fleet.get('workers', 0)} worker(s): "
            f"{fleet.get('shards_done', 0)} done, "
            f"{fleet.get('shards_failed', 0)} failed",
            f"  {fleet.get('preemptions', 0)} preemption(s), "
            f"{fleet.get('reassignments', 0)} reassignment(s), "
            f"retry spend {fleet.get('retry_spend_s', 0)}s, "
            f"merge wall {fleet.get('merge_wall_s', 0)}s",
        ]
        for sh in fleet.get("shards") or []:
            chain = ",".join(sh.get("preemptions") or []) or "-"
            lines.append(
                f"    shard {sh.get('shard_id')} "
                f"[{sh.get('lo')}:{sh.get('hi')})  "
                f"{sh.get('status')}  attempts={sh.get('attempts')}  "
                f"chain={chain}")
    rollup = report.get("fleet_rollup")
    if rollup is not None:
        from galah_tpu.obs import fleet_view

        lines += [""] + fleet_view.render_rollup(rollup)
    lint = report.get("lint")
    if lint is not None:
        fams = ", ".join(f"{fam}={n}" for fam, n in
                         sorted(lint.get("by_family", {}).items()))
        lines += [
            "",
            "lint:",
            f"  {lint.get('errors', 0)} error(s), "
            f"{lint.get('warnings', 0)} warning(s), "
            f"{lint.get('notes', 0)} note(s), "
            f"{lint.get('suppressed', 0)} suppressed",
        ]
        if fams:
            lines.append(f"  by family: {fams}")
    metrics = report.get("metrics", {})
    if metrics:
        lines.append("")
        lines.append("metrics:")
        for name, m in sorted(metrics.items()):
            unit = f" {m['unit']}" if m.get("unit") else ""
            if m.get("kind") == "histogram":
                mean = m.get("mean")
                lines.append(
                    f"  {name}: n={m.get('count', 0)} "
                    f"mean={mean:.4g}{unit}" if mean is not None
                    else f"  {name}: n=0")
            else:
                lines.append(f"  {name}: {m.get('value')}{unit}")
    return "\n".join(lines) + "\n"


def _flatten_stages(tree: List[dict],
                    prefix: str = "") -> Dict[str, Tuple[float, int]]:
    flat: Dict[str, Tuple[float, int]] = {}
    for node in tree:
        path = f"{prefix}/{node['name']}" if prefix else node["name"]
        acc, count = flat.get(path, (0.0, 0))
        flat[path] = (acc + float(node.get("total_s", 0.0)),
                      count + int(node.get("count", 0)))
        flat.update(_flatten_stages(node.get("children", []), path))
    return flat


def _metric_scalar(m: dict) -> Optional[float]:
    if m.get("kind") == "histogram":
        return m.get("mean")
    v = m.get("value")
    return float(v) if isinstance(v, (int, float)) else None


def diff(a: dict, b: dict, label_a: str = "A",
         label_b: str = "B") -> str:
    """Per-stage and per-metric deltas between two reports (B - A)."""
    lines = [
        f"run report diff: {label_a} -> {label_b}",
        f"  duration: {_fmt_s(a['run']['duration_s'])} -> "
        f"{_fmt_s(b['run']['duration_s'])} "
        f"({b['run']['duration_s'] - a['run']['duration_s']:+.2f}s)",
        "",
        "per-stage wall clock:",
    ]
    sa = _flatten_stages(a.get("stages", {}).get("tree", []))
    sb = _flatten_stages(b.get("stages", {}).get("tree", []))
    for path in sorted(set(sa) | set(sb)):
        ta, _ = sa.get(path, (0.0, 0))
        tb, _ = sb.get(path, (0.0, 0))
        marker = ("" if path in sa and path in sb
                  else f"  [only in {label_a if path in sa else label_b}]")
        lines.append(f"  {path}: {_fmt_s(ta)} -> {_fmt_s(tb)} "
                     f"({tb - ta:+.2f}s){marker}")

    lines += ["", "dispatch round trips:"]
    for key in ("total_dispatches", "total_syncs"):
        va = a.get("dispatch", {}).get(key, 0)
        vb = b.get("dispatch", {}).get(key, 0)
        lines.append(f"  {key}: {va} -> {vb} ({vb - va:+d})")

    lines += ["", "funnel:"]
    fa, fb = a.get("funnel", {}), b.get("funnel", {})
    for key in ("possible_pairs", "screened_candidates", "kept_pairs",
                "exact_ani_computed", "exact_ani_wasted"):
        va, vb = fa.get(key, 0), fb.get(key, 0)
        lines.append(f"  {key}: {va} -> {vb} ({vb - va:+d})")

    oa = dict(_occupancy_rows(a.get("metrics", {})))
    ob = dict(_occupancy_rows(b.get("metrics", {})))
    if oa or ob:
        lines += ["", "pipeline occupancy:"]
        for stage in sorted(set(oa) | set(ob)):
            va, vb = oa.get(stage), ob.get(stage)
            delta = ("" if va is None or vb is None
                     else f" ({vb - va:+.2f})")
            lines.append(f"  {stage}: {va} -> {vb}{delta}")

    lines += ["", "per-metric deltas:"]
    ma = a.get("metrics", {})
    mb = b.get("metrics", {})
    for name in sorted(set(ma) | set(mb)):
        va = _metric_scalar(ma.get(name, {}))
        vb = _metric_scalar(mb.get(name, {}))
        if va is None and vb is None:
            continue
        delta = ("" if va is None or vb is None
                 else f" ({vb - va:+.6g})")
        lines.append(f"  {name}: {va} -> {vb}{delta}")

    ra = {d["site"] for d in a.get("resilience", {}).get("demotions", [])}
    rb = {d["site"] for d in b.get("resilience", {}).get("demotions", [])}
    if ra != rb:
        lines += ["", f"demotions: {sorted(ra)} -> {sorted(rb)}"]

    # device-cost drift — .get throughout so a v2/v3 pair still diffs
    da = (a.get("device_costs") or {}).get("entries") or {}
    db = (b.get("device_costs") or {}).get("entries") or {}
    if da or db:
        lines += ["", "device costs:"]
        ha = ((a.get("device_costs") or {}).get("hbm")
              or {}).get("peak_bytes")
        hb = ((b.get("device_costs") or {}).get("hbm")
              or {}).get("peak_bytes")
        if ha is not None or hb is not None:
            lines.append(f"  hbm_peak_bytes: {ha} -> {hb}")
        for name in sorted(set(da) | set(db)):
            ea, eb = da.get(name, {}), db.get(name, {})
            for field in ("dispatch_wall_s", "compile_wall_s",
                          "calls"):
                va, vb = ea.get(field), eb.get(field)
                if va is None and vb is None:
                    continue
                delta = ("" if va is None or vb is None
                         else f" ({vb - va:+.6g})")
                lines.append(
                    f"  {name}.{field}: {va} -> {vb}{delta}")

    # sanitizer drift — additive v4 section, .get throughout
    na, nb = a.get("sanitizer"), b.get("sanitizer")
    if na is not None or nb is not None:
        na, nb = na or {}, nb or {}
        lines += ["", "sanitizer drift:"]
        for key in ("acquisitions", "edges_observed",
                    "edges_declared", "undeclared_acquisitions",
                    "undeclared_edges", "inversions", "races",
                    "unexercised"):
            va, vb = int(na.get(key, 0)), int(nb.get(key, 0))
            lines.append(f"  {key}: {va} -> {vb} ({vb - va:+d})")

    # index drift — additive v5 section, .get throughout
    ia, ib = a.get("index"), b.get("index")
    if ia is not None or ib is not None:
        ia, ib = ia or {}, ib or {}
        lines += ["", "index drift:"]
        for key in ("generation", "genomes", "clusters", "pairs",
                    "tombstones"):
            va, vb = int(ia.get(key, 0)), int(ib.get(key, 0))
            lines.append(f"  {key}: {va} -> {vb} ({vb - va:+d})")

    # fleet drift — additive v7 section, .get throughout
    fla, flb = a.get("fleet"), b.get("fleet")
    if fla is not None or flb is not None:
        fla, flb = fla or {}, flb or {}
        lines += ["", "fleet drift:"]
        for key in ("n_shards", "shards_done", "shards_failed",
                    "preemptions", "reassignments"):
            va, vb = int(fla.get(key, 0)), int(flb.get(key, 0))
            lines.append(f"  {key}: {va} -> {vb} ({vb - va:+d})")

    # fleet rollup drift — additive v9 section, .get throughout;
    # tolerates one side being an older (v6-v8) report with no rollup
    ra, rb = a.get("fleet_rollup"), b.get("fleet_rollup")
    if ra is not None or rb is not None:
        ra, rb = ra or {}, rb or {}
        lines += ["", "fleet rollup drift:"]
        wa = float(ra.get("fleet_wall_s") or 0.0)
        wb = float(rb.get("fleet_wall_s") or 0.0)
        lines.append(f"  fleet_wall_s: {wa:.2f} -> {wb:.2f} "
                     f"({wb - wa:+.2f}s)")
        bna = ra.get("bottleneck")
        bnb = rb.get("bottleneck")
        lines.append(f"  bottleneck: {bna} -> {bnb}"
                     + ("  [MIGRATED]" if bna != bnb else ""))
        ca_ = ra.get("components") or {}
        cb_ = rb.get("components") or {}
        for comp in sorted(set(ca_) | set(cb_)):
            va = int(round(100 * ((ca_.get(comp) or {}).get("share")
                                  or 0.0)))
            vb = int(round(100 * ((cb_.get(comp) or {}).get("share")
                                  or 0.0)))
            lines.append(
                f"  share[{comp}]: {va}% -> {vb}% ({vb - va:+d}%)")

    # flow drift — additive v6 section, .get throughout. A migrated
    # bottleneck is THE regression signal the flow layer exists for.
    fa, fb = a.get("flow"), b.get("flow")
    if fa is not None or fb is not None:
        fa, fb = fa or {}, fb or {}
        ca = fa.get("critical_path") or {}
        cb = fb.get("critical_path") or {}
        lines += ["", "flow drift:"]
        bna, bnb = ca.get("bottleneck"), cb.get("bottleneck")
        lines.append(f"  bottleneck: {bna} -> {bnb}"
                     + ("  [MIGRATED]" if bna != bnb else ""))
        sa_, sb_ = ca.get("stages") or {}, cb.get("stages") or {}
        for stage in sorted(set(sa_) | set(sb_)):
            va = int(round(100 * (sa_.get(stage, {}).get("share")
                                  or 0.0)))
            vb = int(round(100 * (sb_.get(stage, {}).get("share")
                                  or 0.0)))
            lines.append(
                f"  share[{stage}]: {va}% -> {vb}% ({vb - va:+d}%)")
        da_ = (fa.get("flows") or {}).get("dropped", 0)
        db_ = (fb.get("flows") or {}).get("dropped", 0)
        if da_ or db_:
            lines.append(f"  dropped flows: {da_} -> {db_}")

    # memory drift — additive v10 section; peak RSS is the out-of-core
    # tier's acceptance metric, so its drift is the headline number.
    ma, mb = a.get("memory"), b.get("memory")
    if ma is not None or mb is not None:
        ma, mb = ma or {}, mb or {}
        lines += ["", "memory drift:"]
        pa = (ma.get("rss_mb") or {}).get("peak_mb")
        pb = (mb.get("rss_mb") or {}).get("peak_mb")
        if pa is not None or pb is not None:
            pa_f, pb_f = float(pa or 0.0), float(pb or 0.0)
            lines.append(
                f"  peak rss: {pa_f:.0f} -> {pb_f:.0f} MB "
                f"({pb_f - pa_f:+.0f} MB)")
        for key in ("page_ins", "page_outs"):
            va = int((ma.get("pagestore") or {}).get(key, 0))
            vb = int((mb.get("pagestore") or {}).get(key, 0))
            if va or vb:
                lines.append(f"  {key}: {va} -> {vb} ({vb - va:+d})")

    la, lb = a.get("lint"), b.get("lint")
    if la is not None or lb is not None:
        la, lb = la or {}, lb or {}
        lines += ["", "lint drift:"]
        for key in ("errors", "warnings", "notes", "suppressed"):
            va, vb = int(la.get(key, 0)), int(lb.get(key, 0))
            lines.append(f"  {key}: {va} -> {vb} ({vb - va:+d})")
        famc_a = la.get("by_family", {})
        famc_b = lb.get("by_family", {})
        for fam in sorted(set(famc_a) | set(famc_b)):
            va, vb = int(famc_a.get(fam, 0)), int(famc_b.get(fam, 0))
            if va != vb:
                lines.append(
                    f"  {fam}: {va} -> {vb} ({vb - va:+d})")
    return "\n".join(lines) + "\n"
